"""The serving scenario suite: SLO-gated, deterministic load + fault runs.

A *scenario* bundles one traffic shape (``serve/simulator.py``: arrival
pattern + multi-tenant class mix with per-class TTFT/TPOT SLOs), one engine
configuration (slots/blocks/chunking + scheduler policy) and optionally one
fault schedule (``resilience/faults.py``). :func:`run_scenario` drives it
through the continuous-batching engine and then *asserts SLO attainment
from the telemetry registry* — per-class ``serve_class_ttft_ms``/
``serve_class_tpot_ms`` histograms answer "what fraction of requests met
the target", and the run passes only when every gated class attains its
SLOs AND every request completed. CI gates on the resulting
``kind: "scenario"`` record in ``metrics.jsonl`` — "the system stayed
within SLO under this fault + this load", not just "it finished".

Determinism: scenarios run on a :class:`VirtualClock` — every clock read
advances simulated time by a fixed quantum and ``sleep`` advances it by the
requested amount, so latency numbers measure *scheduling structure* (ticks
spent queued, prefill chunks, preemptions, injected stalls) rather than
host speed. A scenario therefore produces the byte-identical report on any
machine, which is what lets CI gate on exact SLO attainment without flake.
SLO targets below are in virtual milliseconds against that cost model
(~``2 * per_call_s`` per engine tick plus injected fault time); wall-clock
runs (``virtual=False``) measure real latency instead and should gate on
generous targets only.

The catalog (also in docs/ARCHITECTURE.md):

=================== =====================================================
``steady``           single interactive class, homogeneous Poisson, FCFS —
                     the sanity baseline: an unstressed system meets SLOs
``burst-interactive`` bursty arrivals, interactive (priority 2) vs batch
                     (priority 0) tenants, priority scheduling with
                     prefill preemption protecting interactive TTFT
``multi-tenant``     three tenants (interactive/standard/batch) over a
                     diurnal rate cycle, priority scheduling
``burst-slow-tick``  ``burst-interactive``'s load composed with injected
                     slow-tick device stalls — SLOs must hold through a
                     degraded device
``crash-serve``      steady traffic with an injected ``engine-crash``
                     mid-serve: the serve supervisor
                     (``serve/supervisor.py``) recovers every in-flight
                     request from the journal — the gate requires ALL
                     requests complete, ≥ 1 restart actually happened,
                     and the SLOs held through the restart
``overload-shed``    a sustained burst at > 1.5x service capacity with
                     per-class hard deadlines: the supervisor sheds
                     expired and over-rate work so the interactive class
                     keeps attaining its SLOs — the gate requires
                     attainment ≥ 0.9 AND every request accounted for
                     (completed or structurally shed, none lost); the
                     no-deadline FCFS baseline fails the same gate
                     (tests pin both sides on exact numbers)
``fleet-replica-loss`` steady traffic over a 3-replica fleet
                     (``serve/fleet.py``) with a whole replica killed
                     mid-decode (``replica-kill@fleet.tick``): the dead
                     replica's in-flight requests migrate onto survivors
                     from its journal alone — the gate requires ALL
                     requests complete, ≥ 1 migration actually happened,
                     and the SLOs held through the loss (bit-exactness of
                     every migrated stream is pinned in tests/
                     test_fleet.py)
``hot-prefix-skew``  every request shares one system prefix: the
                     prefix-cache-aware router concentrates the prefix's
                     blocks on one replica (affinity) instead of paying
                     its prefill on every replica (round-robin) — tests
                     pin affinity's prefix-hit counters STRICTLY above
                     round-robin's on this exact workload
``fleet-autoscale-diurnal`` a compressed day/night arrival cycle over an
                     autoscaled fleet (min 1, max 3): sustained backlog
                     scales out, the idle trough drains-then-retires —
                     the exact virtual-clock replica-count trajectory
                     (``ServeFleet.replica_log``) is pinned in tests
``hot-adapter-churn`` two LoRA tenants over a 3-replica fleet with one
                     tenant's weights hot-swapped mid-run under load
                     (``serve/adapters.py``): adapter-affinity routing
                     concentrates each tenant on a resident replica, the
                     swap re-uploads without a retrace and old-version
                     prefix K/V is orphaned — the gate requires ALL
                     requests complete AND ≥ 3 bank uploads happened;
                     tests pin affinity's adapter-affinity hits STRICTLY
                     above round-robin's on this exact workload
=================== =====================================================

Supervised scenarios (``Scenario.supervised``) run through the
:class:`~..serve.supervisor.ServeSupervisor` — journaled submissions,
crash recovery, deadline enforcement and :class:`OverloadPolicy`
admission control — while unsupervised ones drive the engine directly
(deadlines carried by the workload are then stored but never enforced:
the baseline).
"""

from __future__ import annotations

import dataclasses
import os

from simple_distributed_machine_learning_tpu.resilience import faults
from simple_distributed_machine_learning_tpu.serve.fleet import (
    AutoscalePolicy,
    ServeFleet,
)
from simple_distributed_machine_learning_tpu.serve.metrics import ServeMetrics
from simple_distributed_machine_learning_tpu.serve.scheduler import (
    FCFSScheduler,
    PriorityScheduler,
)
from simple_distributed_machine_learning_tpu.serve.simulator import (
    SimConfig,
    TrafficClass,
    simulate,
)
from simple_distributed_machine_learning_tpu.serve.supervisor import (
    OverloadPolicy,
    ServeSupervisor,
    engine_factory,
)


class VirtualClock:
    """Deterministic simulated time: each read costs ``per_call_s``, each
    ``sleep(dt)`` advances ``dt``. Handed to the engine, its metrics AND
    the simulator (plus ``FaultPlan.sleep``) so all timestamps share one
    origin and one cost model."""

    def __init__(self, per_call_s: float = 0.001) -> None:
        if per_call_s <= 0:
            raise ValueError(f"per_call_s must be > 0, got {per_call_s}")
        self.per_call_s = per_call_s
        self._t = 0.0

    def __call__(self) -> float:
        self._t += self.per_call_s
        return self._t

    def sleep(self, dt: float) -> None:
        self._t += max(0.0, float(dt))


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One catalog entry; see the module docstring."""

    name: str
    description: str
    sim: SimConfig
    n_slots: int = 3
    block_size: int = 8
    # pool capacity in blocks and per-slot length cap (None = the engine
    # defaults): the cache-pressure dial — the offload-churn scenario
    # shrinks both so LRU eviction (and host-tier demotion) actually
    # happens regardless of the model's seq_len
    n_blocks: int | None = None
    max_len: int | None = None
    prefill_chunk: int | None = None
    scheduler: str = "priority"        # "fcfs" | "priority"
    chaos: str | None = None           # FaultPlan.parse spec, or None
    min_attainment: float = 0.9        # per-SLO pass bar
    # supervised scenarios run through the ServeSupervisor (journal, crash
    # recovery, deadline enforcement, overload admission control)
    supervised: bool = False
    max_restarts: int = 3
    degrade_after: int | None = None
    overload: OverloadPolicy | None = None
    # allow_shed: a request structurally shed (deadline/backpressure/class)
    # counts as ACCOUNTED FOR — the gate then requires completed + shed ==
    # n_requests instead of all-completed (overload scenarios shed by
    # design; losing a request silently still fails)
    allow_shed: bool = False
    # the chaos gate: the run must have restarted at least this many times
    # (a crash scenario whose fault never fired must FAIL, not pass
    # vacuously — the FaultSpec site check's dynamic twin)
    min_restarts: int = 0
    # fleet scenarios (serve/fleet.py): replicas > 0 runs the traffic
    # through a ServeFleet of that many supervised replicas behind the
    # route policy; min_migrations is the fleet chaos gate (a replica-loss
    # scenario whose kill never migrated anything must FAIL, not pass
    # vacuously), autoscale enables the queue-depth/KV autoscaler
    replicas: int = 0
    route: str = "affinity"
    autoscale: "object | None" = None       # AutoscalePolicy
    min_migrations: int = 0
    # disaggregated serving (ISSUE 17): prefill_replicas > 0 splits the
    # fleet into prefill/decode pools (serve/fleet.py) and min_handoffs is
    # its vacuous-pass gate (a disaggregated scenario that never handed
    # off must FAIL); host_cache_blocks/prefetch_ticks enable the paged
    # pool's host offload tier on every engine the run builds, and
    # min_host_demotes is ITS vacuous-pass gate (an offload-churn
    # scenario whose pressure never demoted a block must FAIL)
    prefill_replicas: int = 0
    min_handoffs: int = 0
    host_cache_blocks: int = 0
    prefetch_ticks: int = 1
    min_host_demotes: int = 0
    min_host_prefetch_hits: int = 0
    # multi-tenant LoRA serving (ISSUE 20): adapter_rank > 0 builds every
    # engine with an AdapterStore of that rank and registers `adapters`
    # (deterministic seeded weights per name) on the target before
    # traffic; adapter_swap_tick re-registers adapters[0] with NEW seeded
    # weights at that target tick — the hot-swap-under-load move — and
    # min_adapter_swaps is the vacuous-pass gate (a churn scenario whose
    # bank never uploaded must FAIL, not pass by doing nothing)
    adapter_rank: int = 0
    adapters: tuple = ()
    adapter_swap_tick: int = 0
    min_adapter_swaps: int = 0

    def __post_init__(self):
        if self.scheduler not in ("fcfs", "priority"):
            raise ValueError(
                f"scheduler must be fcfs|priority, got {self.scheduler!r}")
        if not 0 < self.min_attainment <= 1:
            raise ValueError(f"min_attainment must be in (0, 1], got "
                             f"{self.min_attainment}")
        if self.min_restarts and not self.supervised:
            raise ValueError(
                "min_restarts needs supervised=True (only the supervisor "
                "restarts an engine)")
        if (self.overload is not None or self.allow_shed) \
                and not (self.supervised or self.replicas):
            raise ValueError(
                "overload/allow_shed need supervised=True or a fleet "
                "(admission control and shedding live in the supervisor)")
        if self.replicas:
            if self.supervised:
                raise ValueError(
                    "replicas > 0 already runs every replica through its "
                    "own ServeSupervisor — drop supervised=True")
            from simple_distributed_machine_learning_tpu.serve.router import (  # noqa: E501
                POLICIES,
            )
            if self.route not in POLICIES:
                raise ValueError(f"route must be one of {POLICIES}, got "
                                 f"{self.route!r}")
        elif (self.min_migrations or self.autoscale is not None
              or self.route != "affinity" or self.prefill_replicas
              or self.min_handoffs):
            raise ValueError(
                "route/autoscale/min_migrations/prefill_replicas/"
                "min_handoffs are fleet knobs — set replicas > 0")
        if self.min_handoffs and not self.prefill_replicas:
            raise ValueError(
                "min_handoffs needs prefill_replicas > 0 (only a "
                "disaggregated fleet hands off)")
        if ((self.min_host_demotes or self.min_host_prefetch_hits)
                and not self.host_cache_blocks):
            raise ValueError(
                "min_host_demotes/min_host_prefetch_hits need "
                "host_cache_blocks > 0 (only the host offload tier "
                "demotes and prefetches)")
        if self.adapter_rank:
            if not (self.supervised or self.replicas):
                raise ValueError(
                    "adapter_rank needs supervised=True or a fleet (the "
                    "engine factory builds the AdapterStore)")
            if not self.adapters:
                raise ValueError(
                    "adapter_rank > 0 needs at least one tenant name in "
                    "`adapters`")
        elif (self.adapters or self.adapter_swap_tick
              or self.min_adapter_swaps):
            raise ValueError(
                "adapters/adapter_swap_tick/min_adapter_swaps need "
                "adapter_rank > 0")


# SLO targets are VIRTUAL milliseconds (see module docstring): an engine
# tick costs a few virtual ms, so "TTFT <= 60 vms" reads "first token
# within ~tens of ticks of arrival". Measured on the burst scenarios:
# priority+preemption holds interactive p95 TTFT at ~22-25 vms (attainment
# 1.0) while FCFS head-of-line blocking blows it to ~230-256 vms
# (attainment 0.75/0.375 — a hard SLO failure); tests/test_scenarios.py
# pins both sides of that gate.
_INTERACTIVE = TrafficClass("interactive", weight=0.35, priority=2,
                            ttft_slo_ms=60.0, tpot_slo_ms=40.0,
                            prompt_lens=(4, 6), max_new_tokens=8)
_STANDARD = TrafficClass("standard", weight=0.3, priority=1,
                         ttft_slo_ms=150.0, tpot_slo_ms=60.0,
                         prompt_lens=(8,), max_new_tokens=12)
_BATCH = TrafficClass("batch", weight=0.35, priority=0,
                      prompt_lens=(12,), max_new_tokens=24)

SCENARIOS: dict[str, Scenario] = {s.name: s for s in (
    Scenario(
        name="steady",
        description="single interactive class, homogeneous Poisson, FCFS "
                    "— the unstressed baseline must meet SLOs",
        sim=SimConfig(n_requests=16, rate=12.0, seed=0,
                      classes=(dataclasses.replace(_INTERACTIVE,
                                                   weight=1.0),)),
        n_slots=4, scheduler="fcfs"),
    Scenario(
        name="burst-interactive",
        description="bursty arrivals, interactive vs batch tenants; "
                    "priority scheduling + prefill preemption protect the "
                    "interactive class's TTFT through the spikes",
        sim=SimConfig(n_requests=28, rate=20.0, seed=0, arrival="bursty",
                      burst_factor=6.0, burst_duty=0.2, period_s=1.0,
                      classes=(_INTERACTIVE,
                               dataclasses.replace(_BATCH, weight=0.65))),
        n_slots=3, prefill_chunk=4),
    Scenario(
        name="multi-tenant",
        description="three tenants (interactive/standard/batch) over a "
                    "diurnal rate cycle, priority scheduling",
        sim=SimConfig(n_requests=30, rate=16.0, seed=0, arrival="diurnal",
                      diurnal_amplitude=0.8, period_s=2.0,
                      classes=(_INTERACTIVE, _STANDARD, _BATCH)),
        n_slots=4, prefill_chunk=4),
    Scenario(
        name="burst-slow-tick",
        description="burst-interactive's load with injected slow-tick "
                    "device stalls (deterministic chaos schedule) — SLOs "
                    "must hold through a degraded device",
        sim=SimConfig(n_requests=24, rate=18.0, seed=0, arrival="bursty",
                      burst_factor=6.0, burst_duty=0.2, period_s=1.0,
                      classes=(_INTERACTIVE,
                               dataclasses.replace(_BATCH, weight=0.65))),
        n_slots=3, prefill_chunk=4,
        chaos="slow-tick@serve.tick,dur=0.004,after=5,times=10"),
    Scenario(
        name="crash-serve",
        description="steady interactive traffic with an engine crash "
                    "injected mid-serve: the serve supervisor re-admits "
                    "every in-flight request from the journal bit-exact "
                    "and the SLOs hold through the restart (gate: all "
                    "complete AND >= 1 restart actually happened)",
        sim=SimConfig(n_requests=16, rate=12.0, seed=0,
                      classes=(dataclasses.replace(_INTERACTIVE,
                                                   weight=1.0),)),
        n_slots=4, prefill_chunk=4, scheduler="fcfs",
        supervised=True, chaos="engine-crash@serve.tick=6",
        min_restarts=1),
    Scenario(
        name="overload-shed",
        description="a sustained burst at > 1.5x service capacity with "
                    "per-class hard deadlines: the supervisor sheds "
                    "expired/over-budget work (deadline + queue-depth "
                    "backpressure) so the interactive class keeps "
                    "attaining its SLOs; the no-deadline FCFS baseline "
                    "fails the same gate",
        sim=SimConfig(n_requests=36, rate=40.0, seed=0, arrival="bursty",
                      burst_factor=5.0, burst_duty=0.3, period_s=1.0,
                      classes=(
                          # the hard deadline sits BELOW the SLO target:
                          # anything not started by 75 vms sheds, so every
                          # SERVED interactive request starts within the
                          # 100 vms target with a tick of slack to spare
                          dataclasses.replace(_INTERACTIVE,
                                              ttft_slo_ms=100.0,
                                              ttft_deadline_ms=75.0,
                                              deadline_ms=500.0),
                          dataclasses.replace(_BATCH, weight=0.65,
                                              deadline_ms=1500.0))),
        n_slots=2, prefill_chunk=4,
        supervised=True, allow_shed=True,
        # queue cap + the load-degraded hysteresis: past 6 queued the
        # supervisor locks best-effort (priority 0) traffic out entirely
        # until the backlog drains to 2 — graceful degradation before the
        # interactive class starves
        overload=OverloadPolicy(max_queue_depth=8,
                                degrade_queue_depth=6,
                                recover_queue_depth=2,
                                degraded_priority_floor=0)),
    Scenario(
        name="fleet-replica-loss",
        description="steady interactive traffic over a 3-replica fleet "
                    "with a whole replica killed mid-decode: its in-flight "
                    "requests migrate onto the survivors from its journal "
                    "alone (gate: all complete AND >= 1 migration actually "
                    "happened; per-stream bit-exactness is pinned in "
                    "tests/test_fleet.py)",
        sim=SimConfig(n_requests=16, rate=12.0, seed=0,
                      classes=(dataclasses.replace(_INTERACTIVE,
                                                   weight=1.0),)),
        n_slots=2, prefill_chunk=4, scheduler="fcfs",
        replicas=3, chaos="replica-kill@fleet.tick=5",
        min_migrations=1),
    Scenario(
        name="hot-prefix-skew",
        description="every request shares one 8-token system prefix: the "
                    "prefix-cache-aware router keeps the prefix's blocks "
                    "hot on one replica instead of re-prefilling them on "
                    "all three — tests pin affinity's prefix-hit counters "
                    "strictly above round-robin's on this exact workload",
        sim=SimConfig(n_requests=18, rate=16.0, seed=0,
                      shared_prefix_len=8,
                      classes=(dataclasses.replace(_INTERACTIVE,
                                                   weight=1.0),)),
        n_slots=2, block_size=8, prefill_chunk=4, scheduler="fcfs",
        replicas=3, route="affinity"),
    Scenario(
        name="fleet-autoscale-diurnal",
        description="a compressed day/night arrival cycle over an "
                    "autoscaled fleet (min 1, max 3): sustained backlog "
                    "scales out, the idle trough drains-then-retires; the "
                    "exact virtual-clock replica-count trajectory "
                    "(ServeFleet.replica_log) is pinned in tests",
        # calibrated so ONE virtual-clock run walks the whole autoscaler
        # state machine: the first peak scales 1 -> 3, the trough
        # drains-then-retires back to 1, the second peak scales out again
        # (tests/test_fleet.py pins the exact tick/replica trajectory)
        sim=SimConfig(n_requests=50, rate=60.0, seed=0, arrival="diurnal",
                      diurnal_amplitude=0.95, period_s=0.6,
                      classes=(dataclasses.replace(
                          _INTERACTIVE, weight=1.0, ttft_slo_ms=None,
                          tpot_slo_ms=None),)),
        n_slots=2, prefill_chunk=4, scheduler="fcfs",
        replicas=1,
        autoscale=AutoscalePolicy(min_replicas=1, max_replicas=3,
                                  scale_out_queue_depth=4,
                                  scale_out_ticks=2,
                                  retire_idle_s=0.08)),
    Scenario(
        name="disagg-prefill-heavy",
        description="bursty long-prompt arrivals whose decodes linger, "
                    "over a 4-replica fleet split 2 prefill + 2 decode: "
                    "new work boards the prefill pool only, every request "
                    "hands off at end-of-prefill by the journal snap/"
                    "adopt move, and lingering decodes clog the DECODE "
                    "pool's slots instead of blocking fresh prefills "
                    "(gate: all complete AND every request actually "
                    "handed off; tests pin disaggregated TTFT p95 "
                    "strictly below the symmetric 4-replica fleet's on "
                    "this exact workload)",
        sim=SimConfig(n_requests=16, rate=14.0, seed=0, arrival="bursty",
                      burst_factor=5.0, burst_duty=0.25, period_s=1.0,
                      classes=(dataclasses.replace(
                          _INTERACTIVE, weight=1.0, prompt_lens=(12, 16),
                          max_new_tokens=24, ttft_slo_ms=400.0,
                          tpot_slo_ms=None),)),
        n_slots=2, prefill_chunk=4, scheduler="fcfs",
        replicas=4, prefill_replicas=2, min_handoffs=16),
    Scenario(
        name="offload-churn",
        description="hot-prefix traffic interleaved with prefix-less "
                    "background scans under block-pool pressure, host "
                    "offload tier on: every scan burst evicts the idle "
                    "system prompt out of the 12-block pool, the LRU "
                    "eviction demotes it to host RAM instead of "
                    "discarding it, and the next hot arrival's "
                    "routing-time prefetch uploads it back ahead of "
                    "admission (gates: all complete AND demotions AND "
                    "prefetch hits actually happened; tests pin device "
                    "prefix-hit blocks strictly above the HBM-only "
                    "fleet's on this exact workload)",
        sim=SimConfig(n_requests=24, rate=4.0, seed=0,
                      shared_prefix_len=8, sampled_fraction=0.0,
                      classes=(
                          # hot tenant: every prompt opens with the shared
                          # system prompt (2 blocks at block_size=4)
                          dataclasses.replace(
                              _INTERACTIVE, weight=1.0, prompt_lens=(4,),
                              max_new_tokens=4, ttft_slo_ms=None,
                              tpot_slo_ms=None),
                          # background scans: NO shared prefix, long
                          # prompts — their allocations evict the idle
                          # prefix out of the 12-block pool between hot
                          # arrivals, demoting it to the host tier
                          TrafficClass(name="scan", weight=1.0,
                                       prompt_lens=(16,),
                                       max_new_tokens=8,
                                       shared_prefix=False))),
        n_slots=2, block_size=4, n_blocks=12, max_len=48, prefill_chunk=4,
        scheduler="fcfs",
        replicas=1, host_cache_blocks=12, prefetch_ticks=1,
        min_host_demotes=1, min_host_prefetch_hits=1),
    Scenario(
        name="hot-adapter-churn",
        description="two LoRA tenants' traffic over a 3-replica fleet "
                    "with one tenant's weights hot-swapped mid-run under "
                    "load: adapter-affinity routing keeps each tenant on "
                    "a replica already holding its bank row (round-robin "
                    "stays adapter-blind and re-uploads per landing), the "
                    "swap lands at a tick boundary without a retrace, and "
                    "the swapped tenant's later requests decode the NEW "
                    "weights (gates: all complete AND >= 3 bank uploads "
                    "actually happened; tests pin affinity's "
                    "adapter-affinity hit counter strictly above "
                    "round-robin's on this exact workload)",
        sim=SimConfig(n_requests=18, rate=16.0, seed=0,
                      classes=(
                          dataclasses.replace(
                              _INTERACTIVE, name="tenant-a", weight=0.5,
                              ttft_slo_ms=None, tpot_slo_ms=None,
                              adapter="tenant-a"),
                          dataclasses.replace(
                              _INTERACTIVE, name="tenant-b", weight=0.5,
                              ttft_slo_ms=None, tpot_slo_ms=None,
                              adapter="tenant-b"))),
        n_slots=2, prefill_chunk=4, scheduler="fcfs",
        replicas=3, route="affinity",
        adapter_rank=2, adapters=("tenant-a", "tenant-b"),
        adapter_swap_tick=6, min_adapter_swaps=3),
    Scenario(
        name="handoff-replica-loss",
        description="disaggregated fleet (1 prefill + 2 decode) with a "
                    "DECODE replica killed while handoffs are in flight: "
                    "handed-off requests re-adopt onto the surviving "
                    "decode replica from the dead one's journal alone, "
                    "and the handoff journal event keeps the prefill "
                    "source from double-serving them (gate: all complete "
                    "AND >= 1 handoff AND >= 1 migration; per-stream "
                    "bit-exactness through the race is pinned in "
                    "tests/test_disagg.py)",
        sim=SimConfig(n_requests=16, rate=12.0, seed=0,
                      classes=(dataclasses.replace(_INTERACTIVE,
                                                   weight=1.0),)),
        n_slots=2, prefill_chunk=4, scheduler="fcfs",
        replicas=3, prefill_replicas=1,
        chaos="replica-kill@fleet.tick=6,rank=1",
        min_handoffs=1, min_migrations=1),
)}


def run_scenario(scenario: Scenario | str, stages, cfg, *,
                 outdir: str | None = None, scheduler: str | None = None,
                 virtual: bool = True, per_call_s: float = 0.001,
                 supervised: bool | None = None, trace=None,
                 route: str | None = None,
                 prefill_replicas: int | None = None,
                 host_cache_blocks: int | None = None) -> dict:
    """Run one scenario end to end; returns the report with the SLO block.

    ``stages``/``cfg``: a ``make_gpt_stages`` build (the engine's usual
    contract). ``scheduler`` overrides the scenario's policy (the
    FCFS-vs-priority comparison tests use this); ``supervised`` overrides
    whether the run goes through the :class:`ServeSupervisor` — forcing
    ``False`` on a deadline-carrying scenario IS the no-deadline baseline
    the overload gate compares against. With ``outdir`` set, the serve
    record and a ``kind: "scenario"`` record (name, SLO attainment per
    class, ``slo_ok``, restart/shed counts, fault stats) land in
    ``metrics.jsonl`` + ``metrics.prom`` — the artifact CI's chaos job
    parses; supervised runs additionally write a post-mortem bundle per
    restart / drain-timeout / shed burst into ``outdir``.

    Fleet scenarios (``scenario.replicas > 0``) run through a
    :class:`~..serve.fleet.ServeFleet` of that many supervised replicas;
    ``route`` overrides the scenario's routing policy (the
    affinity-vs-round-robin comparison tests use this the way the
    FCFS-vs-priority tests use ``scheduler``), the per-replica journals
    land next to the metrics as ``journal-<name>-r<idx>.jsonl``, and the
    report gains a ``"fleet"`` block (replica losses, migrations,
    affinity hits, scale events, the replica-count trajectory).
    ``report["slo_ok"]`` then additionally requires at least
    ``min_migrations`` cross-replica migrations to have happened.

    ``prefill_replicas``/``host_cache_blocks`` override the scenario's
    disaggregation and host-offload-tier knobs the same way ``scheduler``
    and ``route`` do — forcing ``prefill_replicas=0`` IS the symmetric
    baseline the disaggregated TTFT gate compares against, and forcing
    ``host_cache_blocks=0`` IS the HBM-only baseline the host-tier
    prefix-hit gate compares against (tests pin both sides of each).
    ``slo_ok`` additionally requires ``min_handoffs`` handoffs (only when
    the run is actually disaggregated) and ``min_host_demotes`` demotions
    (only when the host tier is actually on) to have happened.

    ``trace`` enables request-scoped tracing (``serve/tracing.py``):
    ``True`` builds a :class:`~..serve.tracing.ServeTrace` (written to
    ``outdir`` as ``serve_trace-<name>.json`` + per-request timeline when
    an outdir is set), or pass a ready recorder. The recorder is fed only
    timestamps the engine already read, so the virtual clock advances
    identically with tracing on or off — every exact-pinned scenario
    number holds either way, and the trace itself is byte-identical
    across runs (tests pin both).

    ``report["slo_ok"]`` is True only when every gated class attains every
    target at ``min_attainment`` or better AND every request is accounted
    for — completed, or (``allow_shed`` scenarios) structurally shed — AND
    a supervised run restarted at least ``min_restarts`` times.
    """
    import tempfile
    import time

    if isinstance(scenario, str):
        if scenario not in SCENARIOS:
            raise ValueError(
                f"unknown scenario {scenario!r}; available: "
                f"{sorted(SCENARIOS)} (see resilience/scenarios.py)")
        scenario = SCENARIOS[scenario]
    clock = VirtualClock(per_call_s) if virtual else time.monotonic
    sleep = clock.sleep if virtual else time.sleep
    policy = scheduler or scenario.scheduler
    sched_cls = PriorityScheduler if policy == "priority" else FCFSScheduler
    sup_flag = scenario.supervised if supervised is None else supervised
    fleet_flag = scenario.replicas > 0
    route_policy = route or scenario.route
    n_prefill = (scenario.prefill_replicas if prefill_replicas is None
                 else prefill_replicas)
    n_host = (scenario.host_cache_blocks if host_cache_blocks is None
              else host_cache_blocks)

    plan = None
    if scenario.chaos:
        plan = faults.install(faults.FaultPlan.parse(scenario.chaos,
                                                     sleep=sleep))
    target = None
    tmpdir = None
    own_trace = trace is True      # we built it -> we close its file handle
    try:
        from simple_distributed_machine_learning_tpu.serve.engine import (
            InferenceEngine,
        )
        metrics = ServeMetrics(outdir=outdir, clock=clock)
        # streaming SLO engine (ISSUE 19): built from the scenario's own
        # per-class SLO targets whenever something owns a tick to drive
        # it (supervisor or fleet; a bare engine run has no evaluator).
        # Observation + evaluation never read a clock, so every
        # pre-existing exact-pinned scenario number is unchanged.
        slo_engine = None
        if sup_flag or fleet_flag:
            from simple_distributed_machine_learning_tpu.telemetry.slo import (  # noqa: E501
                SLOEngine,
            )
            slo_engine = SLOEngine.from_classes(
                scenario.sim.classes, registry=metrics.registry)
        if trace is True:
            from simple_distributed_machine_learning_tpu.serve.tracing import (  # noqa: E501
                ServeTrace,
            )
            trace = ServeTrace(outdir=outdir,
                               suffix=f"-{scenario.name}" if outdir else "")
        engine_kw = dict(n_slots=scenario.n_slots,
                         block_size=scenario.block_size,
                         n_blocks=scenario.n_blocks,
                         max_len=scenario.max_len,
                         prefill_chunk=scenario.prefill_chunk,
                         scheduler=sched_cls, metrics=metrics, clock=clock)
        if n_host:
            engine_kw["host_cache_blocks"] = n_host
            engine_kw["prefetch_ticks"] = scenario.prefetch_ticks
        if scenario.adapter_rank:
            engine_kw["adapter_rank"] = scenario.adapter_rank
        if trace and not (sup_flag or fleet_flag):
            engine_kw["trace"] = trace
        if fleet_flag:
            if outdir:
                jdir = outdir
            else:
                tmpdir = tempfile.TemporaryDirectory(prefix="sdml-fleet-")
                jdir = tmpdir.name
            target = ServeFleet(
                engine_factory(stages, cfg, **engine_kw), jdir,
                n_replicas=scenario.replicas,
                prefill_replicas=n_prefill, route=route_policy,
                metrics=metrics, clock=clock,
                autoscale=scenario.autoscale,
                max_restarts=scenario.max_restarts,
                degrade_after=scenario.degrade_after,
                overload=scenario.overload,
                trace=trace or None,
                # virtual-clock runs measure scheduling structure, not
                # durability (the supervised branch's sync rule)
                journal_sync=not virtual,
                journal_prefix=f"journal-{scenario.name}-r",
                postmortem_dir=outdir, slo=slo_engine)
        elif sup_flag:
            if outdir:
                jpath = os.path.join(outdir,
                                     f"journal-{scenario.name}.jsonl")
                if os.path.exists(jpath):
                    os.unlink(jpath)           # each run journals fresh
            else:
                tmpdir = tempfile.TemporaryDirectory(prefix="sdml-journal-")
                jpath = os.path.join(tmpdir.name, "journal.jsonl")
            from simple_distributed_machine_learning_tpu.serve.journal import (  # noqa: E501
                RequestJournal,
            )
            target = ServeSupervisor(
                engine_factory(stages, cfg, **engine_kw),
                # virtual-clock runs measure scheduling structure, not
                # durability: skip the per-record fsync (journal.py's own
                # sync=False designation for exactly this case)
                RequestJournal(jpath, sync=not virtual),
                metrics=metrics, clock=clock,
                max_restarts=scenario.max_restarts,
                degrade_after=scenario.degrade_after,
                overload=scenario.overload,
                trace=trace or None,
                # crash forensics ride along whenever artifacts do: one
                # post-mortem bundle per restart / drain-timeout / shed
                # burst next to the journal (no clock reads — the pinned
                # numbers cannot move)
                postmortem_dir=outdir, slo=slo_engine)
        else:
            target = InferenceEngine(stages, cfg, **engine_kw)
        if scenario.adapter_rank:
            # deterministic tenants: weights are a pure function of
            # (tenant index, cfg, rank), so the virtual-clock run's token
            # streams — and every pinned number — reproduce exactly
            import jax

            from simple_distributed_machine_learning_tpu.models import (
                lora,
            )
            for k, name in enumerate(scenario.adapters):
                target.register_adapter(name, lora.init_lora_adapter(
                    jax.random.key(1000 + k), cfg, scenario.adapter_rank))
            if scenario.adapter_swap_tick:
                # swap-under-load: at target tick N, re-register the
                # first tenant with NEW seeded weights. Tick counting
                # reads no clock, so the virtual timeline is identical
                # with the swap armed or not.
                swap_name = scenario.adapters[0]
                new_w = lora.init_lora_adapter(jax.random.key(424242),
                                               cfg, scenario.adapter_rank)
                inner_step = target.step
                state = {"n": 0}

                def step():
                    state["n"] += 1
                    if state["n"] == scenario.adapter_swap_tick:
                        target.register_adapter(swap_name, new_w)
                    return inner_step()

                target.step = step
        report = simulate(target, scenario.sim, sleep=sleep)
    finally:
        if plan is not None:
            faults.uninstall()
        if (sup_flag or fleet_flag) and target is not None:
            target.close()
        if trace and trace is not True:
            # `trace` stays the bool if setup raised before the recorder
            # was built — never shadow that original exception. A
            # caller-owned recorder only flushes (its lifecycle is the
            # caller's); one we built here closes its timeline handle too
            trace.close() if own_trace else trace.flush()
        if tmpdir is not None:
            tmpdir.cleanup()

    n = scenario.sim.n_requests
    accounted = report["completed"] + (report["shed"]
                                       if scenario.allow_shed else 0)
    slo: dict = {}
    ok = accounted == n
    if sup_flag:
        report["restarts"] = target.restarts
        report["supervisor_state"] = target.state
        report["postmortem_bundles"] = len(target.postmortems)
        ok &= target.restarts >= scenario.min_restarts
    if fleet_flag:
        report["fleet"] = {
            "replicas": scenario.replicas,
            "route": route_policy,
            "alive": target.n_alive,
            "in_rotation": target.n_in_rotation,
            "replica_losses": target.replica_losses,
            "migrations": target.migrations,
            "affinity_hits": int(metrics.route_affinity_hits.value),
            "scale_outs": int(metrics.fleet_scale_outs.value),
            "retired": int(metrics.fleet_retired.value),
            "replica_log": list(target.replica_log),
        }
        if n_prefill:
            report["fleet"]["prefill_replicas"] = n_prefill
            report["fleet"]["handoffs"] = target.handoffs
            ok &= target.handoffs >= scenario.min_handoffs
        report["restarts"] = sum(
            r.supervisor.restarts for r in target.replicas)
        ok &= target.migrations >= scenario.min_migrations
    if n_host:
        # host-offload-tier outcomes, summed over every pool the run
        # built (fleet replicas share one ServeMetrics, whose counters
        # aggregate the per-pool deltas)
        report["host_tier"] = {
            "host_cache_blocks": n_host,
            "demotes": int(metrics._host_counters[
                "host_demotes_total"].value),
            "promotes": int(metrics._host_counters[
                "host_promotes_total"].value),
            "prefetch_hits": int(metrics._host_counters[
                "host_prefetch_hits_total"].value),
            "prefetch_misses": int(metrics._host_counters[
                "host_prefetch_misses_total"].value),
            "host_evictions": int(metrics._host_counters[
                "host_evictions_total"].value),
            "transfer_bytes": int(metrics._host_counters[
                "host_transfer_bytes_total"].value),
        }
        ok &= (report["host_tier"]["demotes"]
               >= scenario.min_host_demotes)
        ok &= (report["host_tier"]["prefetch_hits"]
               >= scenario.min_host_prefetch_hits)
    if scenario.adapter_rank:
        report["adapters"] = {
            "rank": scenario.adapter_rank,
            "tenants": list(scenario.adapters),
            "resident_bytes": int(metrics.adapter_resident_bytes.value),
            "swaps": int(metrics.adapter_swaps.value),
            "adapter_affinity_hits": int(metrics.route_adapter_hits.value),
        }
        ok &= report["adapters"]["swaps"] >= scenario.min_adapter_swaps
    if trace:
        report["trace_events"] = trace.n_events
        # fold every traced request's timeline into the additive TTFT
        # decomposition (components must reconcile with the journaled
        # ttft_ms — attribute() raises on drift, a test failure)
        from simple_distributed_machine_learning_tpu.telemetry.attribution import (  # noqa: E501
            attribute,
        )
        report["attribution"] = attribute(trace.rows,
                                          registry=metrics.registry)
    for tc in scenario.sim.classes:
        if tc.ttft_slo_ms is None and tc.tpot_slo_ms is None:
            continue
        att = metrics.attainment(tc.name, ttft_slo_ms=tc.ttft_slo_ms,
                                 tpot_slo_ms=tc.tpot_slo_ms)
        cls_ok = True
        for key in ("ttft_attainment", "tpot_attainment"):
            if key in att:
                cls_ok &= (att[key] is not None
                           and att[key] >= scenario.min_attainment)
        att["ok"] = cls_ok
        slo[tc.name] = att
        ok &= cls_ok
    report["scenario"] = scenario.name
    report["scheduler"] = policy
    report["supervised"] = sup_flag
    report["slo"] = slo
    report["slo_ok"] = ok
    if slo_engine is not None:
        report["slo_alerts"] = slo_engine.summary()
    if plan is not None:
        report["faults"] = plan.stats()
    if outdir:
        from simple_distributed_machine_learning_tpu.telemetry.registry import (
            append_jsonl,
        )
        metrics.emit(extra={"scenario": scenario.name, "scheduler": policy,
                            "completed": report["completed"]})
        append_jsonl(os.path.join(outdir, "metrics.jsonl"), {
            "kind": "scenario", "scenario": scenario.name,
            "scheduler": policy, "supervised": sup_flag,
            "completed": report["completed"], "shed": report["shed"],
            "n_requests": report["n_requests"], "slo": slo, "slo_ok": ok,
            **({"restarts": report["restarts"]} if sup_flag else {}),
            **({"fleet": {k: v for k, v in report["fleet"].items()
                          if k != "replica_log"}} if fleet_flag else {}),
            **({"host_tier": report["host_tier"]} if n_host else {}),
            **({"adapters": report["adapters"]}
               if scenario.adapter_rank else {}),
            **({"slo_alerts": {
                "transitions": len(slo_engine.alerts.journal),
                "firing": slo_engine.active_alerts(),
                "states": slo_engine.alerts.states()}}
               if slo_engine is not None else {}),
            **({"attribution": report["attribution"]}
               if "attribution" in report else {}),
            **({"faults_fired": plan.stats()["total_fired"]}
               if plan is not None else {}),
        })
        if slo_engine is not None:
            # one joinable row per alert transition — what the CI chaos
            # drill greps a fired-and-resolved pair out of
            for tr in slo_engine.alerts.journal:
                append_jsonl(os.path.join(outdir, "metrics.jsonl"),
                             {"kind": "slo_alert",
                              "scenario": scenario.name, **tr})
    return report
