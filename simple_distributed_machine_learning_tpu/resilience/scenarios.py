"""The serving scenario suite: SLO-gated, deterministic load + fault runs.

A *scenario* bundles one traffic shape (``serve/simulator.py``: arrival
pattern + multi-tenant class mix with per-class TTFT/TPOT SLOs), one engine
configuration (slots/blocks/chunking + scheduler policy) and optionally one
fault schedule (``resilience/faults.py``). :func:`run_scenario` drives it
through the continuous-batching engine and then *asserts SLO attainment
from the telemetry registry* — per-class ``serve_class_ttft_ms``/
``serve_class_tpot_ms`` histograms answer "what fraction of requests met
the target", and the run passes only when every gated class attains its
SLOs AND every request completed. CI gates on the resulting
``kind: "scenario"`` record in ``metrics.jsonl`` — "the system stayed
within SLO under this fault + this load", not just "it finished".

Determinism: scenarios run on a :class:`VirtualClock` — every clock read
advances simulated time by a fixed quantum and ``sleep`` advances it by the
requested amount, so latency numbers measure *scheduling structure* (ticks
spent queued, prefill chunks, preemptions, injected stalls) rather than
host speed. A scenario therefore produces the byte-identical report on any
machine, which is what lets CI gate on exact SLO attainment without flake.
SLO targets below are in virtual milliseconds against that cost model
(~``2 * per_call_s`` per engine tick plus injected fault time); wall-clock
runs (``virtual=False``) measure real latency instead and should gate on
generous targets only.

The catalog (also in docs/ARCHITECTURE.md):

=================== =====================================================
``steady``           single interactive class, homogeneous Poisson, FCFS —
                     the sanity baseline: an unstressed system meets SLOs
``burst-interactive`` bursty arrivals, interactive (priority 2) vs batch
                     (priority 0) tenants, priority scheduling with
                     prefill preemption protecting interactive TTFT
``multi-tenant``     three tenants (interactive/standard/batch) over a
                     diurnal rate cycle, priority scheduling
``burst-slow-tick``  ``burst-interactive``'s load composed with injected
                     slow-tick device stalls — SLOs must hold through a
                     degraded device
=================== =====================================================
"""

from __future__ import annotations

import dataclasses
import os

from simple_distributed_machine_learning_tpu.resilience import faults
from simple_distributed_machine_learning_tpu.serve.metrics import ServeMetrics
from simple_distributed_machine_learning_tpu.serve.scheduler import (
    FCFSScheduler,
    PriorityScheduler,
)
from simple_distributed_machine_learning_tpu.serve.simulator import (
    SimConfig,
    TrafficClass,
    simulate,
)


class VirtualClock:
    """Deterministic simulated time: each read costs ``per_call_s``, each
    ``sleep(dt)`` advances ``dt``. Handed to the engine, its metrics AND
    the simulator (plus ``FaultPlan.sleep``) so all timestamps share one
    origin and one cost model."""

    def __init__(self, per_call_s: float = 0.001) -> None:
        if per_call_s <= 0:
            raise ValueError(f"per_call_s must be > 0, got {per_call_s}")
        self.per_call_s = per_call_s
        self._t = 0.0

    def __call__(self) -> float:
        self._t += self.per_call_s
        return self._t

    def sleep(self, dt: float) -> None:
        self._t += max(0.0, float(dt))


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One catalog entry; see the module docstring."""

    name: str
    description: str
    sim: SimConfig
    n_slots: int = 3
    block_size: int = 8
    prefill_chunk: int | None = None
    scheduler: str = "priority"        # "fcfs" | "priority"
    chaos: str | None = None           # FaultPlan.parse spec, or None
    min_attainment: float = 0.9        # per-SLO pass bar

    def __post_init__(self):
        if self.scheduler not in ("fcfs", "priority"):
            raise ValueError(
                f"scheduler must be fcfs|priority, got {self.scheduler!r}")
        if not 0 < self.min_attainment <= 1:
            raise ValueError(f"min_attainment must be in (0, 1], got "
                             f"{self.min_attainment}")


# SLO targets are VIRTUAL milliseconds (see module docstring): an engine
# tick costs a few virtual ms, so "TTFT <= 60 vms" reads "first token
# within ~tens of ticks of arrival". Measured on the burst scenarios:
# priority+preemption holds interactive p95 TTFT at ~22-25 vms (attainment
# 1.0) while FCFS head-of-line blocking blows it to ~230-256 vms
# (attainment 0.75/0.375 — a hard SLO failure); tests/test_scenarios.py
# pins both sides of that gate.
_INTERACTIVE = TrafficClass("interactive", weight=0.35, priority=2,
                            ttft_slo_ms=60.0, tpot_slo_ms=40.0,
                            prompt_lens=(4, 6), max_new_tokens=8)
_STANDARD = TrafficClass("standard", weight=0.3, priority=1,
                         ttft_slo_ms=150.0, tpot_slo_ms=60.0,
                         prompt_lens=(8,), max_new_tokens=12)
_BATCH = TrafficClass("batch", weight=0.35, priority=0,
                      prompt_lens=(12,), max_new_tokens=24)

SCENARIOS: dict[str, Scenario] = {s.name: s for s in (
    Scenario(
        name="steady",
        description="single interactive class, homogeneous Poisson, FCFS "
                    "— the unstressed baseline must meet SLOs",
        sim=SimConfig(n_requests=16, rate=12.0, seed=0,
                      classes=(dataclasses.replace(_INTERACTIVE,
                                                   weight=1.0),)),
        n_slots=4, scheduler="fcfs"),
    Scenario(
        name="burst-interactive",
        description="bursty arrivals, interactive vs batch tenants; "
                    "priority scheduling + prefill preemption protect the "
                    "interactive class's TTFT through the spikes",
        sim=SimConfig(n_requests=28, rate=20.0, seed=0, arrival="bursty",
                      burst_factor=6.0, burst_duty=0.2, period_s=1.0,
                      classes=(_INTERACTIVE,
                               dataclasses.replace(_BATCH, weight=0.65))),
        n_slots=3, prefill_chunk=4),
    Scenario(
        name="multi-tenant",
        description="three tenants (interactive/standard/batch) over a "
                    "diurnal rate cycle, priority scheduling",
        sim=SimConfig(n_requests=30, rate=16.0, seed=0, arrival="diurnal",
                      diurnal_amplitude=0.8, period_s=2.0,
                      classes=(_INTERACTIVE, _STANDARD, _BATCH)),
        n_slots=4, prefill_chunk=4),
    Scenario(
        name="burst-slow-tick",
        description="burst-interactive's load with injected slow-tick "
                    "device stalls (deterministic chaos schedule) — SLOs "
                    "must hold through a degraded device",
        sim=SimConfig(n_requests=24, rate=18.0, seed=0, arrival="bursty",
                      burst_factor=6.0, burst_duty=0.2, period_s=1.0,
                      classes=(_INTERACTIVE,
                               dataclasses.replace(_BATCH, weight=0.65))),
        n_slots=3, prefill_chunk=4,
        chaos="slow-tick@serve.tick,dur=0.004,after=5,times=10"),
)}


def run_scenario(scenario: Scenario | str, stages, cfg, *,
                 outdir: str | None = None, scheduler: str | None = None,
                 virtual: bool = True, per_call_s: float = 0.001) -> dict:
    """Run one scenario end to end; returns the report with the SLO block.

    ``stages``/``cfg``: a ``make_gpt_stages`` build (the engine's usual
    contract). ``scheduler`` overrides the scenario's policy (the
    FCFS-vs-priority comparison tests use this). With ``outdir`` set, the
    serve record and a ``kind: "scenario"`` record (name, SLO attainment
    per class, ``slo_ok``, fault stats) land in ``metrics.jsonl`` +
    ``metrics.prom`` — the artifact CI's chaos job parses.

    ``report["slo_ok"]`` is True only when every gated class attains every
    target at ``min_attainment`` or better AND all requests completed.
    """
    import time

    if isinstance(scenario, str):
        if scenario not in SCENARIOS:
            raise ValueError(
                f"unknown scenario {scenario!r}; available: "
                f"{sorted(SCENARIOS)} (see resilience/scenarios.py)")
        scenario = SCENARIOS[scenario]
    clock = VirtualClock(per_call_s) if virtual else time.monotonic
    sleep = clock.sleep if virtual else time.sleep
    policy = scheduler or scenario.scheduler
    sched_cls = PriorityScheduler if policy == "priority" else FCFSScheduler

    plan = None
    if scenario.chaos:
        plan = faults.install(faults.FaultPlan.parse(scenario.chaos,
                                                     sleep=sleep))
    try:
        from simple_distributed_machine_learning_tpu.serve.engine import (
            InferenceEngine,
        )
        metrics = ServeMetrics(outdir=outdir, clock=clock)
        engine = InferenceEngine(
            stages, cfg, n_slots=scenario.n_slots,
            block_size=scenario.block_size,
            prefill_chunk=scenario.prefill_chunk,
            scheduler=sched_cls, metrics=metrics, clock=clock)
        report = simulate(engine, scenario.sim, sleep=sleep)
    finally:
        if plan is not None:
            faults.uninstall()

    slo: dict = {}
    ok = bool(report["all_completed"])
    for tc in scenario.sim.classes:
        if tc.ttft_slo_ms is None and tc.tpot_slo_ms is None:
            continue
        att = metrics.attainment(tc.name, ttft_slo_ms=tc.ttft_slo_ms,
                                 tpot_slo_ms=tc.tpot_slo_ms)
        cls_ok = True
        for key in ("ttft_attainment", "tpot_attainment"):
            if key in att:
                cls_ok &= (att[key] is not None
                           and att[key] >= scenario.min_attainment)
        att["ok"] = cls_ok
        slo[tc.name] = att
        ok &= cls_ok
    report["scenario"] = scenario.name
    report["scheduler"] = policy
    report["slo"] = slo
    report["slo_ok"] = ok
    if plan is not None:
        report["faults"] = plan.stats()
    if outdir:
        from simple_distributed_machine_learning_tpu.telemetry.registry import (
            append_jsonl,
        )
        metrics.emit(extra={"scenario": scenario.name, "scheduler": policy,
                            "completed": report["completed"]})
        append_jsonl(os.path.join(outdir, "metrics.jsonl"), {
            "kind": "scenario", "scenario": scenario.name,
            "scheduler": policy, "completed": report["completed"],
            "n_requests": report["n_requests"], "slo": slo, "slo_ok": ok,
            **({"faults_fired": plan.stats()["total_fired"]}
               if plan is not None else {}),
        })
    return report
