"""Deterministic fault injection: seeded, reproducible failure schedules.

The reference program's failure story is "wait for the real thing": a dead
peer hangs it forever, a torn checkpoint write is discovered at the next
restore, a wedged accelerator eats a bench round (ROADMAP standing note).
This module turns every one of those into a *scheduled, seeded event* that
CI replays on every PR, instead of an incident someone debugs at 3am.

A :class:`FaultPlan` is a list of :class:`FaultSpec` entries — each one
"fire fault KIND at injection SITE when the context matches". Instrumented
code calls :func:`maybe_fire` at named sites; with no plan installed that is
one global ``is None`` check (the hot-path cost of the whole harness).

Fault kinds and their standard effects (applied by :func:`maybe_fire`):

=================== ==================================================
``host-kill``        raises :class:`HostLost` — the in-process stand-in
                     for a host dying mid-step; the supervisor
                     (``resilience/supervisor.py``) treats it exactly
                     like a real process loss: all in-memory state is
                     discarded, recovery is from disk only
``frozen-peer``      holds the site for ``dur`` seconds (``plan.sleep``);
                     at the ``watchdog.heartbeat`` site the watchdog
                     interprets it itself (stops heartbeating, socket
                     left open — the frozen-process signature)
``slow-tick``        sleeps ``dur`` seconds at the site (straggler /
                     degraded-device simulation)
``ckpt-write-crash`` truncates the in-flight temp file and raises
                     :class:`CheckpointWriteCrash` from inside the
                     checkpoint writer — the mid-write crash the atomic
                     write-then-rename discipline must survive
``wedged-device``    raises :class:`DeviceWedged`; ``bench.py`` maps it
                     onto the rc-17 wedged-accelerator signature
``engine-crash``     raises :class:`EngineCrash` — the inference engine's
                     in-process death (a tick or an admission dying with
                     its pool state): the serve supervisor
                     (``serve/supervisor.py``) discards the engine
                     wholesale and re-admits in-flight requests from the
                     request journal
``replica-kill``     a whole serving REPLICA (supervisor + engine +
                     in-memory journal handle) dies at a fleet tick: the
                     fleet (``serve/fleet.py``) interprets it itself via
                     :func:`check` — the replica drops from rotation and
                     its in-flight requests migrate onto surviving
                     replicas from its on-disk journal alone; a bare
                     :func:`maybe_fire` at the site raises
                     :class:`ReplicaLost`
``nan-grad``         a numerically poisoned step: the sentinel-enabled
                     trainer (``resilience/sentinel.py``) interprets it
                     via :func:`check` by feeding the step a NaN-scaled
                     batch, so the backward produces NaN gradients and
                     the donated update destroys the params — the
                     micro-rollback drill. A bare :func:`maybe_fire`
                     raises :class:`NumericFault`
``corrupt-batch``    a corrupted input batch (overflow-scaled values →
                     non-finite loss); trainer-interpreted like
                     ``nan-grad``, quarantined on detection
``loss-spike``       a finite numeric excursion (inputs scaled 100x → a
                     large but finite loss) for the EWMA spike detector;
                     trainer-interpreted like ``nan-grad``
``preempt``          a graceful preemption notice (the SIGTERM drill's
                     in-process twin): the trainer finishes the in-flight
                     step, forces a synchronous checkpoint + quarantine-
                     journal flush and returns cleanly. A bare
                     :func:`maybe_fire` raises :class:`Preempted`
=================== ==================================================

Injection sites threaded through the stack:

- ``train.step``          (``train/trainer.py``, ctx: ``step``; also the
                          ``loss-spike`` numeric site — the sentinel probes
                          that kind via ``check(..., only=)`` before the
                          generic ``maybe_fire`` excludes it)
- ``train.grad``          (``train/trainer.py``, ctx: ``step`` — the
                          ``nan-grad`` poisoned-gradient site)
- ``data.batch``          (``train/trainer.py``, ctx: ``step`` — the
                          ``corrupt-batch`` poisoned-input site)
- ``train.sigterm``       (``train/trainer.py``, ctx: ``step`` — the
                          ``preempt`` graceful-preemption site, probed once
                          per step before the next step starts)
- ``ckpt.write``          (``train/checkpoint.py``, ctx: ``path``, ``tmp``)
- ``serve.tick``          (``serve/engine.py``, ctx: ``step`` = tick index)
- ``serve.admit``         (``serve/engine.py::submit``, ctx: ``step`` = rid —
                          a crash while a request is being accepted, the
                          journaled-but-never-admitted corner)
- ``fleet.tick``          (``serve/fleet.py``, ctx: ``step`` = fleet tick,
                          ``rank`` = replica index — the fleet probes the
                          site once per alive replica per tick, so
                          ``rank=N`` targets replica N and a rank-less spec
                          kills the lowest-indexed alive replica)
- ``fleet.handoff``       (``serve/fleet.py::_handoff_step``, ctx: ``step``
                          = fleet tick, ``rank`` = SOURCE replica index —
                          probed once per completed handoff, exactly
                          between the destination's ``adopt`` and the
                          source's tombstone seal: a ``replica-kill`` here
                          is the kill-racing-adopt schedule the protocol
                          model checker (analysis/protocol.py) explores
                          and exports)
- ``watchdog.heartbeat``  (``utils/failure.py``, ctx: ``rank``)
- ``bench.probe``         (``bench.py``, ctx: ``step`` = probe attempt)

Plans come from :meth:`FaultPlan.parse` (the ``--chaos`` CLI grammar),
:meth:`FaultPlan.random` (seeded schedules — same seed, same faults), or
explicit specs. ``install()`` makes a plan process-active; sites are
matched by name so new subsystems opt in by calling ``maybe_fire``.

Grammar (``--chaos``): entries separated by ``;``, each
``kind@site[=step][,key=val...]`` with keys ``dur`` (seconds), ``after``
(skip the first N matching calls), ``times`` (fire at most N times;
0 = unlimited; default 1) and ``rank``. Examples::

    host-kill@train.step=6
    slow-tick@serve.tick,dur=0.004,after=2,times=6
    frozen-peer@watchdog.heartbeat,rank=1
    nan-grad@train.grad=12
    corrupt-batch@data.batch=3;preempt@train.sigterm=20
"""

from __future__ import annotations

import dataclasses
import os
import time

KINDS = ("host-kill", "frozen-peer", "slow-tick", "ckpt-write-crash",
         "wedged-device", "engine-crash", "replica-kill", "nan-grad",
         "corrupt-batch", "loss-spike", "preempt")

SITES = ("train.step", "train.grad", "data.batch", "train.sigterm",
         "ckpt.write", "serve.tick", "serve.admit", "fleet.tick",
         "fleet.handoff", "watchdog.heartbeat", "bench.probe")

#: kinds the numeric-anomaly sentinel (``resilience/sentinel.py``)
#: interprets itself — a plan containing one of these needs a
#: sentinel-enabled trainer, or the bare standard effect (a raised
#: :class:`NumericFault`) kills the run loudly instead of being absorbed.
SENTINEL_KINDS = ("nan-grad", "corrupt-batch", "loss-spike")

#: kinds that are only meaningful at ONE site (and, for the sites below,
#: sites that accept only one kind): any crossed pair would match-and-count
#: without ever taking effect — the vacuous-drill failure the strict site
#: check exists to stop.
_KIND_SITE = {"replica-kill": "fleet.tick", "nan-grad": "train.grad",
              "corrupt-batch": "data.batch", "preempt": "train.sigterm",
              "loss-spike": "train.step"}
#: secondary interpreting sites for kinds whose primary lives in
#: ``_KIND_SITE`` (which stays single-valued: it doubles as the
#: random-schedule and coverage default). ``replica-kill`` is also
#: interpreted at ``fleet.handoff`` — the adopt/seal race probe.
_KIND_EXTRA_SITES = {"replica-kill": ("fleet.handoff",)}
_SITE_KINDS = {"fleet.tick": ("replica-kill",),
               "fleet.handoff": ("replica-kill",),
               "train.grad": ("nan-grad",),
               "data.batch": ("corrupt-batch",),
               "train.sigterm": ("preempt",)}

ENV_VAR = "SDML_CHAOS"


class FaultInjected(RuntimeError):
    """Base of every exception an injected fault raises; carries the spec."""

    def __init__(self, spec: "FaultSpec", site: str):
        super().__init__(
            f"injected fault {spec.kind!r} fired at site {site!r} "
            f"(deterministic chaos schedule — resilience/faults.py)")
        self.spec = spec
        self.site = site


class HostLost(FaultInjected):
    """A host died mid-run (injected): in-memory state is gone, recovery
    must come from the checkpoint store."""


class DeviceWedged(FaultInjected):
    """The accelerator stopped responding (injected): the rc-17 signature
    bench.py's supervised smoke probe detects and retries."""


class CheckpointWriteCrash(FaultInjected):
    """The process crashed mid-checkpoint-write (injected): the temp file is
    truncated; the previously committed checkpoint must stay intact."""


class EngineCrash(FaultInjected):
    """The inference engine died mid-tick or mid-admission (injected): its
    pool buffers and host bookkeeping are gone; the serve supervisor must
    rebuild from scratch and recover in-flight requests from the journal."""


class ReplicaLost(FaultInjected):
    """A whole serving replica died (injected): supervisor, engine and
    every in-memory structure are gone; the fleet (``serve/fleet.py``)
    must migrate its in-flight requests onto surviving replicas from the
    dead replica's on-disk journal alone."""


class NumericFault(FaultInjected):
    """A numeric fault (nan-grad / corrupt-batch / loss-spike) fired at a
    site nothing interprets: the sentinel-enabled trainer absorbs these via
    :func:`check`; a bare :func:`maybe_fire` caller fails loudly instead of
    letting the drill pass vacuously (enable the sentinel)."""


class Preempted(FaultInjected):
    """A graceful-preemption notice fired at a site nothing interprets: the
    trainer absorbs ``preempt`` via :func:`check` (finish the step,
    synchronous checkpoint, clean exit); a bare caller fails loudly."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One scheduled fault; see the module docstring for field semantics."""

    kind: str
    site: str
    step: int | None = None     # fire only when ctx["step"] == step
    rank: int | None = None     # fire only when ctx["rank"] == rank
    after: int = 0              # skip the first N matching calls
    times: int = 1              # max firings (0 = unlimited)
    dur: float = 0.05           # hold/sleep seconds (slow-tick, frozen-peer)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; known: {KINDS}")
        if self.site not in SITES:
            # strict: a typo'd site would silently never fire and the chaos
            # drill would pass vacuously. A new subsystem's injection point
            # joins SITES alongside its maybe_fire() call.
            raise ValueError(
                f"unknown fault site {self.site!r}; instrumented sites: "
                f"{SITES}")
        pinned = _KIND_SITE.get(self.kind)
        if (pinned is not None and self.site != pinned
                and self.site not in _KIND_EXTRA_SITES.get(self.kind, ())):
            # a kind with a closed set of interpreting sites scheduled
            # anywhere else would match-and-count without ever taking
            # effect — the vacuous-drill failure the strict check stops
            allowed = (pinned,) + _KIND_EXTRA_SITES.get(self.kind, ())
            raise ValueError(
                f"kind {self.kind!r} at site {self.site!r}: this kind only "
                f"pairs with {allowed} (its interpreting sites)")
        allowed = _SITE_KINDS.get(self.site)
        if allowed is not None and self.kind not in allowed:
            raise ValueError(
                f"kind {self.kind!r} at site {self.site!r}: this site only "
                f"interprets {allowed} (any other kind would never take "
                f"effect there)")
        if self.after < 0 or self.times < 0 or self.dur < 0:
            raise ValueError(
                f"after/times/dur must be >= 0, got {self.after}/"
                f"{self.times}/{self.dur}")


class FaultPlan:
    """A deterministic schedule of faults plus its firing state.

    ``check(site, **ctx)`` matches and counts without side effects (the
    watchdog uses it to interpret ``frozen-peer`` itself); ``fire(site,
    **ctx)`` additionally applies each fired fault's standard effect —
    raise, or sleep through ``self.sleep`` (injectable, so a virtual-clock
    scenario advances simulated time instead of stalling the test).
    """

    def __init__(self, specs, sleep=time.sleep):
        self.specs = list(specs)
        self.sleep = sleep
        self._seen = [0] * len(self.specs)    # matching calls per spec
        self._fired = [0] * len(self.specs)   # firings per spec

    # -- construction ------------------------------------------------------

    @classmethod
    def parse(cls, text: str, sleep=time.sleep) -> "FaultPlan":
        """Parse the ``--chaos`` grammar (module docstring)."""
        specs = []
        for raw in text.split(";"):
            raw = raw.strip()
            if not raw:
                continue
            head, *fields = raw.split(",")
            if "@" not in head:
                raise ValueError(
                    f"bad fault entry {raw!r}: expected kind@site[=step]"
                    f"[,key=val...]")
            kind, site = head.split("@", 1)
            kw: dict = {"kind": kind.strip()}
            site = site.strip()
            if "=" in site:
                site, step = site.split("=", 1)
                kw["step"] = int(step)
            kw["site"] = site
            for field in fields:
                if "=" not in field:
                    raise ValueError(
                        f"bad fault field {field!r} in {raw!r}: expected "
                        f"key=val")
                k, v = (s.strip() for s in field.split("=", 1))
                if k == "dur":
                    kw[k] = float(v)
                elif k in ("after", "times", "rank", "step"):
                    kw[k] = int(v)
                else:
                    raise ValueError(
                        f"unknown fault field {k!r} in {raw!r}; known: "
                        f"dur, after, times, rank, step")
            specs.append(FaultSpec(**kw))
        if not specs:
            raise ValueError(f"fault plan {text!r} contains no entries")
        return cls(specs, sleep=sleep)

    @classmethod
    def random(cls, seed: int, n: int = 3, sites=("train.step",),
               kinds=("host-kill", "slow-tick"), max_step: int = 100,
               sleep=time.sleep) -> "FaultPlan":
        """A seeded random schedule: same seed, same faults, every run —
        the property that makes a chaos soak reproducible in CI."""
        import numpy as np

        rng = np.random.default_rng(seed)
        steps = sorted(int(s) for s in
                       rng.choice(max_step, size=n, replace=False))
        specs = []
        for step in steps:
            kind = str(rng.choice(list(kinds)))
            # site-pinned kinds (nan-grad, corrupt-batch, loss-spike,
            # preempt, replica-kill) land on their interpreting site; a
            # free draw would hit the pairing check and a random schedule
            # must always be a VALID schedule
            pinned = _KIND_SITE.get(kind)
            site = pinned if pinned else str(rng.choice(list(sites)))
            specs.append(FaultSpec(kind=kind, site=site, step=step))
        return cls(specs, sleep=sleep)

    # -- matching ----------------------------------------------------------

    def check(self, site: str, only=None, exclude=(),
              **ctx) -> list[FaultSpec]:
        """Specs firing for this call (matching + occurrence accounting,
        no effects applied).

        ``only``/``exclude`` filter by KIND before any occurrence
        accounting — a filtered-out spec is not "seen", so a caller that
        splits one site's kinds across two probes (the sentinel-enabled
        trainer checks ``loss-spike`` itself and excludes it from the
        generic ``maybe_fire``) still matches every spec exactly once.
        """
        fired = []
        for i, spec in enumerate(self.specs):
            if spec.site != site:
                continue
            if only is not None and spec.kind not in only:
                continue
            if spec.kind in exclude:
                continue
            if spec.rank is not None and ctx.get("rank") != spec.rank:
                continue
            if spec.step is not None and ctx.get("step") != spec.step:
                continue
            seen = self._seen[i]
            self._seen[i] = seen + 1
            if seen < spec.after:
                continue
            if spec.times and self._fired[i] >= spec.times:
                continue
            self._fired[i] += 1
            fired.append(spec)
        return fired

    def fire(self, site: str, only=None, exclude=(),
             **ctx) -> list[FaultSpec]:
        """``check`` + standard effects. Sleeping faults are applied first
        so a site scheduled with both a slow-tick and a host-kill stalls,
        then dies — the order a real degrading host fails in."""
        fired = self.check(site, only=only, exclude=exclude, **ctx)
        for spec in fired:
            if spec.kind in ("slow-tick", "frozen-peer"):
                self.sleep(spec.dur)
        for spec in fired:
            if spec.kind == "host-kill":
                raise HostLost(spec, site)
            if spec.kind == "wedged-device":
                raise DeviceWedged(spec, site)
            if spec.kind == "engine-crash":
                raise EngineCrash(spec, site)
            if spec.kind == "replica-kill":
                # the fleet interprets this kind via check() and never gets
                # here; a bare maybe_fire caller still fails loudly
                raise ReplicaLost(spec, site)
            if spec.kind in SENTINEL_KINDS:
                # the sentinel-enabled trainer interprets these via check()
                # and never gets here; without the sentinel the drill must
                # fail loudly, not pass vacuously
                raise NumericFault(spec, site)
            if spec.kind == "preempt":
                raise Preempted(spec, site)
            if spec.kind == "ckpt-write-crash":
                tmp = ctx.get("tmp")
                if tmp:
                    try:  # leave a half-written temp, like a real crash
                        with open(tmp, "r+b") as f:
                            f.truncate(max(0, os.path.getsize(tmp) // 2))
                    except OSError:
                        pass
                raise CheckpointWriteCrash(spec, site)
        return fired

    def stats(self) -> dict:
        """Per-spec firing counts (scenario reports embed this so a run
        proves its faults actually happened)."""
        return {
            "specs": [dataclasses.asdict(s) for s in self.specs],
            "fired": list(self._fired),
            "total_fired": sum(self._fired),
        }


# -- the process-active plan (the one global the hot paths check) -----------

_ACTIVE: FaultPlan | None = None


def install(plan: FaultPlan) -> FaultPlan:
    """Make ``plan`` the process-active fault schedule (replacing any)."""
    global _ACTIVE
    _ACTIVE = plan
    return plan


def uninstall() -> None:
    global _ACTIVE
    _ACTIVE = None


def active() -> FaultPlan | None:
    return _ACTIVE


def install_from_env(var: str = ENV_VAR) -> FaultPlan | None:
    """Install a plan from the ``SDML_CHAOS`` env var (how ``bench.py`` and
    subprocess harnesses receive their schedule); None when unset."""
    text = os.environ.get(var)
    if not text:
        return None
    return install(FaultPlan.parse(text))


def maybe_fire(site: str, only=None, exclude=(), **ctx) -> list[FaultSpec]:
    """The instrumented-code entry point: a no-op unless a plan is active."""
    if _ACTIVE is None:
        return []
    return _ACTIVE.fire(site, only=only, exclude=exclude, **ctx)


def check(site: str, only=None, exclude=(), **ctx) -> list[FaultSpec]:
    """Match without effects (callers that interpret the fault themselves,
    e.g. the watchdog's frozen-peer or the sentinel trainer's numeric
    kinds); no-op unless a plan is active."""
    if _ACTIVE is None:
        return []
    return _ACTIVE.check(site, only=only, exclude=exclude, **ctx)


# -- drill coverage lint ----------------------------------------------------

def drill_coverage(root: str | None = None, kinds=None, sites=None,
                   pairs=None) -> list[str]:
    """The chaos-coverage lint: every registered fault kind and every
    instrumented site must be FIRED by at least one test or CI drill, and
    every pinned kind<->site pair (``_KIND_SITE`` plus the
    ``_KIND_EXTRA_SITES`` secondaries) must be drilled as that exact
    pair — a new kind/site added without a drill currently passes
    vacuously, which is the one failure mode a deterministic chaos harness
    cannot tolerate. Scans ``tests/*.py``, ``.github/workflows/*.yml`` and
    the model checker's exported counterexample schedules
    (``tests/data/protocol_drills/*.chaos`` — analysis/protocol.py's
    ``render_drill`` artifacts, so a proved-and-exported interleaving
    counts as drill coverage) for the ``kind@site`` schedule grammar and
    keyword ``FaultSpec(...)`` constructions. Returns a list of
    human-readable gaps (empty = fully covered); the analysis CLI's
    ``--fixtures`` self-test runs it as an extra contract line."""
    import re

    kinds = tuple(kinds if kinds is not None else KINDS)
    sites = tuple(sites if sites is not None else SITES)
    if pairs is not None:
        required_pairs = set(dict(pairs).items())
    else:
        required_pairs = set(_KIND_SITE.items()) | {
            (k, s) for k, extra in _KIND_EXTRA_SITES.items()
            for s in extra}
    if root is None:
        root = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                            os.pardir, os.pardir))
    texts = []
    for sub in ("tests", os.path.join(".github", "workflows"),
                os.path.join("tests", "data", "protocol_drills")):
        d = os.path.join(root, sub)
        if not os.path.isdir(d):
            continue
        for fname in sorted(os.listdir(d)):
            if fname.endswith((".py", ".yml", ".yaml", ".chaos")):
                try:
                    with open(os.path.join(d, fname),
                              encoding="utf-8") as fh:
                        texts.append(fh.read())
                except OSError:
                    continue
    blob = "\n".join(texts)
    fired: set[tuple[str, str]] = set()
    # the FaultPlan.parse schedule grammar: kind@site[=step][...]
    for m in re.finditer(r"([a-z][a-z0-9-]*)@([a-z][a-z0-9.]*)", blob):
        k, s = m.group(1), m.group(2)
        if k in kinds and s in sites:
            fired.add((k, s))
    # keyword FaultSpec(...) constructions (tests that build plans in code)
    for m in re.finditer(r"FaultSpec\(([^)]*)\)", blob):
        body = m.group(1)
        km = re.search(r"kind\s*=\s*['\"]([a-z0-9-]+)['\"]", body)
        sm = re.search(r"site\s*=\s*['\"]([a-z0-9.]+)['\"]", body)
        if km and sm and km.group(1) in kinds and sm.group(1) in sites:
            fired.add((km.group(1), sm.group(1)))
    gaps: list[str] = []
    fired_kinds = {k for k, _ in fired}
    fired_sites = {s for _, s in fired}
    for k in kinds:
        if k not in fired_kinds:
            gaps.append(f"fault kind {k!r} is registered but no test/CI "
                        f"drill ever fires it")
    for s in sites:
        if s not in fired_sites:
            gaps.append(f"fault site {s!r} is instrumented but no test/CI "
                        f"drill ever fires it")
    for k, s in sorted(required_pairs):
        if k in kinds and s in sites and (k, s) not in fired:
            gaps.append(f"pinned pair {k}@{s} (one of the kind's "
                        f"interpreting sites) is never drilled as that "
                        f"pair")
    return gaps
