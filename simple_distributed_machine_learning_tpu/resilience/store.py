"""Validated checkpoint retention: manifest + checksums, latest-VALID pick.

``train/checkpoint.py`` writes one atomic ``.npz`` per save; the Trainer
keeps exactly one (``state.npz``, overwritten per epoch). That is enough for
"resume after a clean stop" but not for elastic restart, where the newest
file may be the one the crash corrupted (torn filesystem, bad disk, a
checkpoint from the very write that killed the host). The store keeps a
short history of *numbered* checkpoints plus a JSONL manifest recording each
file's sha256, size and topology, so restore picks the newest checkpoint
that still *verifies* — a corrupt checkpoint is never selected, it is
skipped with a warning and the previous generation restores instead.

Layout under ``dir``::

    ckpt-00000042.npz            one atomic save per training epoch
    ckpt-00000042.npz.meta.json  human-readable sidecar (checkpoint.py's)
    MANIFEST.jsonl               appended per save; atomically rewritten on
                                 GC and when a re-saved step supersedes its
                                 own stale entry (one entry per file)

Manifest entries record ``extra`` verbatim — the elastic supervisor stores
``n_stages`` there, which is how a restore onto a *different* topology knows
which source pipeline to repack from.

Multi-process: ``save`` must be called by every process (the device→host
gather inside ``save_checkpoint`` is a collective); only process 0 touches
the filesystem or the manifest, mirroring ``checkpoint.py``'s contract.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
from typing import Any

MANIFEST = "MANIFEST.jsonl"


def _sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


class CheckpointStore:
    """Retained, checksum-validated checkpoints in one directory."""

    def __init__(self, dir: str, keep: int = 3) -> None:
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.dir = dir
        self.keep = keep
        os.makedirs(dir, exist_ok=True)

    # -- write side --------------------------------------------------------

    def _manifest_path(self) -> str:
        return os.path.join(self.dir, MANIFEST)

    def save(self, buf, opt_state, step: int, extra: dict | None = None
             ) -> str | None:
        """One retained checkpoint generation: atomic ``.npz`` write (via
        ``save_checkpoint``), checksum, manifest append, history GC.
        Returns the path (process 0) or None (other processes)."""
        import jax

        from simple_distributed_machine_learning_tpu.train.checkpoint import (
            save_checkpoint,
        )
        path = os.path.join(self.dir, f"ckpt-{step:08d}.npz")
        # collective on every process; only process 0 writes the file
        save_checkpoint(path, buf, opt_state, step, extra=extra)
        if jax.process_index() != 0:
            return None
        entry = {
            "file": os.path.basename(path),
            "step": int(step),
            "sha256": _sha256(path),
            "bytes": os.path.getsize(path),
            "extra": dict(extra or {}),
        }
        # drop any stale entry for the same FILE first (a restarted attempt
        # re-saving the same step overwrote it on disk): two entries naming
        # one file would let _gc unlink it out from under the live one
        stale = [e for e in self.entries() if e["file"] == entry["file"]]
        if stale:
            self._rewrite([e for e in self.entries()
                           if e["file"] != entry["file"]] + [entry])
        else:
            with open(self._manifest_path(), "a") as f:
                f.write(json.dumps(entry) + "\n")
        self._gc()
        return path

    def _rewrite(self, entries: list[dict]) -> None:
        tmp = self._manifest_path() + ".tmp"
        with open(tmp, "w") as f:
            for e in entries:
                f.write(json.dumps(e) + "\n")
        os.replace(tmp, self._manifest_path())

    def _gc(self) -> None:
        """Drop generations beyond ``keep`` (oldest first): delete their
        files, then atomically rewrite the manifest without them. A crash
        between the two leaves dangling manifest entries — harmless, the
        validator skips entries whose file is gone."""
        entries = self.entries()
        if len(entries) <= self.keep:
            return
        dead, live = entries[:-self.keep], entries[-self.keep:]
        live_files = {e["file"] for e in live}
        for e in dead:
            if e["file"] in live_files:
                continue   # a live entry still references this file
            for suffix in ("", ".meta.json"):
                try:
                    os.unlink(os.path.join(self.dir, e["file"] + suffix))
                except OSError:
                    pass
        self._rewrite(live)

    # -- read side ---------------------------------------------------------

    def entries(self) -> list[dict]:
        """Manifest entries, oldest first. Unparseable lines (a crash mid-
        append tears at most the last one) are skipped, not fatal."""
        path = self._manifest_path()
        if not os.path.exists(path):
            return []
        out = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                    if "file" in e and "sha256" in e:
                        out.append(e)
                except (json.JSONDecodeError, TypeError):
                    continue
        return out

    def validate(self, entry: dict) -> bool:
        """Does this entry's file still verify? Existence, size and sha256
        — content-level truncation/corruption detection, not just mtime."""
        path = os.path.join(self.dir, entry["file"])
        try:
            if os.path.getsize(path) != entry["bytes"]:
                return False
            return _sha256(path) == entry["sha256"]
        except OSError:
            return False

    def latest_valid(self) -> dict | None:
        """The newest entry whose checkpoint verifies (None if none do).
        Invalid generations are skipped with a stderr warning — a corrupt
        checkpoint is NEVER selected for restore, the previous valid one
        is."""
        for entry in reversed(self.entries()):
            if self.validate(entry):
                return {**entry, "path": os.path.join(self.dir,
                                                      entry["file"])}
            sys.stderr.write(
                f"[resilience] skipping corrupt/missing checkpoint "
                f"{os.path.join(self.dir, entry['file'])} (checksum or "
                f"size mismatch) — falling back to an earlier one\n")
        return None

    def restore_latest(self, pipe=None, opt_treedef_like: Any = None,
                       src_pipe=None) -> dict | None:
        """``restore_checkpoint`` of :meth:`latest_valid` (None when the
        store is empty); the returned dict gains the manifest ``entry``."""
        from simple_distributed_machine_learning_tpu.train.checkpoint import (
            restore_checkpoint,
        )
        entry = self.latest_valid()
        if entry is None:
            return None
        st = restore_checkpoint(entry["path"], pipe=pipe,
                                opt_treedef_like=opt_treedef_like,
                                src_pipe=src_pipe)
        st["entry"] = entry
        return st
