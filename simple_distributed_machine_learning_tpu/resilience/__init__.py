"""Resilience: deterministic fault injection, elastic restart, SLO gates.

The north star serves millions of users, where host loss and load spikes
are the steady state; this package turns the repo's isolated failure
utilities (the dead-peer watchdog, async checkpointing with cross-topology
repack, the seeded traffic simulator) into one tested capability:

- :mod:`.faults` — seeded, reproducible fault schedules (host-kill,
  frozen-peer, slow-tick, checkpoint-write-crash, wedged-device) injected
  at named sites threaded through the Trainer, the checkpoint writer, the
  watchdog, the serving engine and the bench probe (``--chaos``);
- :mod:`.sentinel` — self-healing training: per-step NaN/Inf + EWMA-spike
  anomaly detection, a bounded in-memory snapshot ring for micro-rollback
  (no disk restore for a transient numeric fault), a deterministic
  corrupt-batch quarantine journal, and escalation to the elastic
  supervisor when anomalies repeat (``--sentinel``);
- :mod:`.store` — checksum-validated checkpoint history with a manifest:
  restore picks the latest checkpoint that VERIFIES, never a corrupt one;
- :mod:`.supervisor` — the elastic checkpoint-restart loop: on a
  recoverable failure, restore the latest valid checkpoint, repack it onto
  the surviving stage count (``repack_packed_buffer``) and resume, with
  bounded exponential backoff and a max-restart budget;
- :mod:`.scenarios` — the SLO-gated serving scenario suite: deterministic
  bursty/diurnal/multi-tenant traffic with per-class TTFT/TPOT targets,
  priority scheduling with prefill preemption, attainment computed from
  the telemetry registry (``--scenario``).

Attribute access is lazy (PEP 562): importing the package pulls in neither
jax nor the trainer until a symbol that needs them is touched — the faults
module stays importable from stdlib-only contexts like the watchdog's
monitor subprocess.
"""

from __future__ import annotations

_EXPORTS = {
    "FaultPlan": ".faults",
    "FaultSpec": ".faults",
    "FaultInjected": ".faults",
    "HostLost": ".faults",
    "DeviceWedged": ".faults",
    "CheckpointWriteCrash": ".faults",
    "EngineCrash": ".faults",
    "ReplicaLost": ".faults",
    "NumericFault": ".faults",
    "Preempted": ".faults",
    "CheckpointStore": ".store",
    "Sentinel": ".sentinel",
    "SentinelConfig": ".sentinel",
    "SentinelExhausted": ".sentinel",
    "QuarantineJournal": ".sentinel",
    "SnapshotRing": ".sentinel",
    "ElasticTrainer": ".supervisor",
    "PeerLost": ".supervisor",
    "RestartBudgetExceeded": ".supervisor",
    "RestartPolicy": ".supervisor",
    "make_elastic_trainer": ".supervisor",
    "supervise": ".supervisor",
    "Scenario": ".scenarios",
    "SCENARIOS": ".scenarios",
    "VirtualClock": ".scenarios",
    "run_scenario": ".scenarios",
}

__all__ = sorted(_EXPORTS) + ["faults", "scenarios", "sentinel", "store",
                              "supervisor"]


def __getattr__(name: str):
    import importlib

    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    return getattr(importlib.import_module(mod, __name__), name)
