"""The elastic checkpoint-restart supervisor: restore → repack → resume.

Composes the pieces that previously existed only in isolation (ROADMAP
item #5): the validated checkpoint history (``resilience/store.py``), the
cross-topology repack (``train/checkpoint.py::repack_packed_buffer``), and
the failure signals (``resilience/faults.py`` injected faults; the
watchdog's peer-loss surfaced as :class:`PeerLost`).

State machine (docs/ARCHITECTURE.md carries the same diagram)::

            +---------------------------------------------+
            v                                             |
    RUNNING --fault--> RESTORING --backoff--> RUNNING ... |
       |                   |                              |
       |                   +--budget exhausted--> FAILED  |
       +--fit() returns--> DONE <-------------------------+

- RUNNING: one *attempt* — a freshly built trainer (``build_trainer(n)``)
  driving ``fit()`` to completion. A recoverable failure (injected
  host-kill, peer loss from the watchdog, a checkpoint-write crash, a
  wedged device) aborts the attempt; every other exception propagates —
  a real bug must not be retried into oblivion.
- RESTORING: the next attempt's trainer restores the latest *valid*
  checkpoint from the store (corrupt generations are skipped by checksum)
  and — when the failure was a host/peer loss and a smaller topology is
  configured — repacks the packed param/optimizer buffers onto the
  surviving stage count before resuming. The restore happens inside
  ``build_trainer`` via :func:`make_elastic_trainer`; nothing in-memory
  survives an attempt, exactly as if the process had died.
- Backoff between attempts is exponential and bounded; the restart budget
  (``max_restarts``) caps the loop — a persistently failing run FAILS
  loudly with :class:`RestartBudgetExceeded` instead of flapping forever.
"""

from __future__ import annotations

import dataclasses
import sys
import time

from simple_distributed_machine_learning_tpu.resilience.faults import (
    CheckpointWriteCrash,
    DeviceWedged,
    HostLost,
)
from simple_distributed_machine_learning_tpu.resilience.sentinel import (
    SentinelExhausted,
)
from simple_distributed_machine_learning_tpu.resilience.store import (
    CheckpointStore,
)
from simple_distributed_machine_learning_tpu.train.trainer import Trainer


class PeerLost(RuntimeError):
    """A peer vanished or froze (the watchdog's verdict), surfaced as an
    exception for in-process supervision. OS-process runs exit with
    ``utils.failure.EXIT_PEER_LOST`` instead; a process-level supervisor
    maps that exit code onto this."""


#: failures the supervisor restarts through; anything else is a bug and
#: propagates. Host/peer loss additionally shrinks the topology (the dead
#: host's devices are gone); write crashes, device wedges and an exhausted
#: anomaly sentinel (micro-rollback could not absorb a systematic numeric
#: fault — escalate to a full disk restore) retry in place.
RECOVERABLE = (HostLost, PeerLost, CheckpointWriteCrash, DeviceWedged,
               SentinelExhausted)
_SHRINKING = (HostLost, PeerLost)


class RestartBudgetExceeded(RuntimeError):
    """More recoverable failures than ``max_restarts`` allows."""


@dataclasses.dataclass(frozen=True)
class RestartPolicy:
    max_restarts: int = 3
    base_backoff_s: float = 0.05
    backoff_factor: float = 2.0
    max_backoff_s: float = 2.0

    def __post_init__(self):
        if self.max_restarts < 0 or self.base_backoff_s < 0:
            raise ValueError("max_restarts/base_backoff_s must be >= 0")
        if self.backoff_factor < 1.0:
            raise ValueError("backoff_factor must be >= 1")


class ElasticTrainer(Trainer):
    """A :class:`Trainer` whose persistence is a :class:`CheckpointStore`.

    The base trainer's single-file ``state.npz`` path stays untouched
    (``config.checkpoint_dir`` must be None — the store owns persistence);
    every epoch saves one retained, checksummed generation whose manifest
    records the stage count it was written at. Per-epoch metric records
    are kept on ``self.history`` so the supervisor's report can prove loss
    continuity across a restart.
    """

    def __init__(self, pipe, train_ds, test_ds, config, store: CheckpointStore,
                 opt=None, telemetry=None) -> None:
        if config.checkpoint_dir:
            raise ValueError(
                "ElasticTrainer persists through its CheckpointStore; "
                "config.checkpoint_dir must be None (the two would race "
                "over who owns resume)")
        # before super().__init__: the base constructor resolves the
        # sentinel's quarantine-journal directory via _sentinel_dir()
        self.store = store
        super().__init__(pipe, train_ds, test_ds, config, opt=opt,
                         telemetry=telemetry)
        self.history: list[dict] = []

    def _sentinel_dir(self) -> str | None:
        # the quarantine journal lives next to the checkpoint generations,
        # so a restarted attempt skips the same batches
        return self.store.dir

    def _save(self, epoch: int, cursor: int | None = None,
              sync: bool = False) -> None:
        extra = self._save_extra(epoch, cursor)
        extra["n_stages"] = self.pipe.n_stages
        self.store.save(self.buf, self.opt_state, self._step_count,
                        extra=extra)

    def _log_metrics(self, record: dict) -> None:
        self.history.append(dict(record))
        super()._log_metrics(record)


def make_elastic_trainer(build_pipe, n_stages: int, store: CheckpointStore,
                         train_ds, test_ds, config, opt=None,
                         opt_factory=None, telemetry=None) -> ElasticTrainer:
    """Build one attempt's trainer at ``n_stages``, resumed from the store.

    ``build_pipe(n_stages) -> Pipeline`` is the topology factory — it must
    build the SAME model at any supported stage count (the contiguous-split
    families ``repack_stage_trees`` documents). When the latest valid
    checkpoint was written at a different stage count, a source pipeline is
    built just for its packing metadata and the packed param + optimizer
    buffers are repacked onto the new topology (``restore_checkpoint``'s
    ``src_pipe`` path); loss then continues from the restored step.

    ``opt_factory(pipe) -> Optimizer`` builds the optimizer against the
    attempt's OWN pipeline (pipe-dependent optimizers — e.g. replication-
    weighted gradient clipping — must see the topology they run on);
    ``opt`` passes a fixed instance instead.
    """
    pipe = build_pipe(n_stages)
    if opt is None and opt_factory is not None:
        opt = opt_factory(pipe)
    trainer = ElasticTrainer(pipe, train_ds, test_ds, config, store,
                             opt=opt, telemetry=telemetry)
    entry = store.latest_valid()
    if entry is None:
        return trainer
    from simple_distributed_machine_learning_tpu.train.checkpoint import (
        restore_checkpoint,
    )
    src_n = int(entry["extra"].get("n_stages", n_stages))
    src_pipe = pipe if src_n == pipe.n_stages else build_pipe(src_n)
    st = restore_checkpoint(entry["path"], pipe=pipe,
                            opt_treedef_like=trainer.opt_state,
                            src_pipe=src_pipe)
    trainer.buf = st["params"]
    trainer.opt_state = st["opt_state"]
    trainer._step_count = st["step"]
    trainer.start_epoch = int(st["extra"].get("epoch", 0)) + 1
    # a graceful-preemption checkpoint carries the mid-epoch data cursor:
    # resume re-enters the epoch at the exact next batch. The sentinel's
    # EWMA detector state rides along too (a spike right after resume
    # must not slip through a cold detector).
    trainer._resume_batch_idx = int(st["extra"].get("next_batch", 0))
    if trainer._sentinel is not None and "sentinel" in st["extra"]:
        trainer._sentinel.restore_detector(st["extra"]["sentinel"])
    trainer._print(
        f"| elastic: restored {entry['file']} (step {st['step']}, written "
        f"at {src_n} stage{'s' if src_n != 1 else ''}"
        + (f", repacked onto {n_stages}" if src_n != n_stages else "")
        + f"); resuming at epoch {trainer.start_epoch}"
        + (f" (batch {trainer._resume_batch_idx})"
           if trainer._resume_batch_idx else ""))
    return trainer


def supervise(build_trainer, topologies, *, policy: RestartPolicy | None = None,
              sleep=time.sleep) -> dict:
    """Run ``build_trainer(n_stages).fit()`` to completion through failures.

    ``topologies`` is the stage-count ladder, largest first — each host/peer
    loss steps down one rung (staying on the last once exhausted); other
    recoverable failures retry at the same rung. Returns the report dict:
    per-attempt outcomes with the resumed step and per-epoch loss history,
    the state-machine transition log, and the restart count. Raises
    :class:`RestartBudgetExceeded` (chained to the last failure) when the
    budget runs out, and re-raises non-recoverable exceptions untouched.
    """
    policy = policy or RestartPolicy()
    topologies = list(topologies)
    if not topologies:
        raise ValueError("topologies must name at least one stage count")
    report: dict = {"attempts": [], "transitions": [], "restarts": 0,
                    "completed": False}

    def note(state: str, n_stages: int) -> None:
        report["transitions"].append((state, n_stages))

    rung = 0
    restarts = 0
    backoff = policy.base_backoff_s
    while True:
        n_stages = topologies[rung]
        note("RUNNING", n_stages)
        trainer = build_trainer(n_stages)
        attempt = {"n_stages": n_stages,
                   "resumed_step": trainer._step_count,
                   "start_epoch": trainer.start_epoch}
        try:
            trainer.fit()
        except RECOVERABLE as e:
            attempt.update(outcome="fault", fault=type(e).__name__,
                           detail=str(e)[:200],
                           history=list(trainer.history))
            stats = getattr(trainer, "sentinel_stats", lambda: None)()
            if stats is not None:
                attempt["sentinel"] = stats
            report["attempts"].append(attempt)
            restarts += 1
            report["restarts"] = restarts
            if restarts > policy.max_restarts:
                note("FAILED", n_stages)
                raise RestartBudgetExceeded(
                    f"{restarts} recoverable failures exceed the "
                    f"max_restarts={policy.max_restarts} budget; last: "
                    f"{type(e).__name__}: {e}") from e
            if isinstance(e, _SHRINKING) and rung < len(topologies) - 1:
                rung += 1  # the lost host's devices are gone: shrink
            sys.stderr.write(
                f"[resilience] attempt at {n_stages} stage(s) lost to "
                f"{type(e).__name__}; restoring onto {topologies[rung]} "
                f"stage(s) after {backoff:.3g}s backoff "
                f"(restart {restarts}/{policy.max_restarts})\n")
            note("RESTORING", topologies[rung])
            sleep(min(backoff, policy.max_backoff_s))
            backoff = min(backoff * policy.backoff_factor,
                          policy.max_backoff_s)
            continue
        attempt.update(outcome="completed", history=list(trainer.history))
        stats = getattr(trainer, "sentinel_stats", lambda: None)()
        if stats is not None:
            attempt["sentinel"] = stats
        report["attempts"].append(attempt)
        report["completed"] = True
        note("DONE", n_stages)
        return report
