"""Self-healing training: anomaly sentinel, micro-rollback, quarantine.

PR-7 gave the trainer COARSE fault tolerance: any failure costs everything
back to the last disk checkpoint (a whole epoch of work at the default
cadence). But the most common production training faults are not host
losses — they are *numeric*: a NaN gradient from one bad batch, a corrupted
input record, a loss excursion that poisons the optimizer state. Production
TPU training treats those as routine events to absorb in-memory, not
crashes to replay from disk (PAPERS.md "Scalable Training of Language
Models using JAX pjit and TPUv4"). This module is that layer:

- **detection** (:meth:`Sentinel.observe`): every step's loss (and global
  gradient norm — one extra scalar the sentinel-enabled compiled step
  returns) is checked host-side for NaN/Inf, and against an EWMA spike
  threshold (``loss > spike_factor * (ewma + spike_margin)`` after a
  warmup of healthy steps). Anomalous observations never enter the EWMA,
  so one excursion cannot drag the threshold up after itself.
- **micro-rollback** (:class:`SnapshotRing`): a bounded in-memory ring of
  host-side ``(step, params, opt_state, data-cursor, EWMA state)``
  snapshots, refreshed every ``snapshot_every`` steps (plus one forced at
  each epoch entry, so a pre-anomaly point always exists). On detection
  the trainer restores the newest snapshot at-or-before the anomaly step
  and *replays* forward — orders of magnitude cheaper than a
  ``CheckpointStore`` disk generation, and exact: replayed steps re-run
  with the same per-step keys and batches, so the recovered trajectory is
  bit-identical to one that never took the fault.
- **quarantine** (:class:`QuarantineJournal`): the offending batch —
  identified as ``(epoch, batch_idx)`` — is recorded in an append-only
  JSONL journal and deterministically skipped on replay AND on any later
  run that loads the journal (a restarted attempt skips the same batches).
  The acceptance pin: with ``nan-grad@train.grad=K`` injected, the
  sentinel run's post-rollback per-step losses equal a clean run that
  pre-loaded the same quarantine journal and never saw the fault — exact,
  on single-stage and multi-stage pipelines (tests/test_sentinel.py).
- **escalation** (:class:`SentinelExhausted`): repeated anomalies within
  one ``window`` of steps (more than ``max_rollbacks`` of them) mean the
  fault is systematic, not transient — micro-rollback cannot converge, so
  the sentinel raises and the elastic supervisor
  (``resilience/supervisor.py``, which lists the exception as RECOVERABLE)
  takes over with a full disk restore.

Detection→rollback→quarantine→escalate is driven by the Trainer's step
loop (``train/trainer.py``); this module holds the state machine's memory
and verdicts. The cost when enabled: one device→host scalar sync per step
(the loss the log line already fetches periodically, plus the grad norm)
and one host gather per ``snapshot_every`` steps; when disabled the
trainer pays nothing.

Metric series (through the trainer's telemetry registry, when attached):

- ``train_anomalies_total{kind=}`` (counter) — anomalies detected, by
  verdict kind (``nan`` / ``inf`` / ``spike``)
- ``train_rollbacks_total`` (counter) — in-memory micro-rollbacks taken
- ``train_quarantined_batches_total`` (counter) — batches journaled as
  quarantined and deterministically skipped from then on
- ``train_snapshot_ring_bytes`` (gauge) — resident host bytes of the
  snapshot ring (bounded by ``ring_size`` x one state's bytes)
- ``train_preempt_graceful`` (gauge) — 1 when the run ended on a graceful
  preemption (SIGTERM / injected ``preempt``): in-flight step finished,
  synchronous checkpoint + quarantine-journal flush, clean exit
"""

from __future__ import annotations

import collections
import dataclasses
import json
import math
import os
from typing import NamedTuple


class SentinelExhausted(RuntimeError):
    """Micro-rollback cannot absorb the fault: more than ``max_rollbacks``
    anomalies within one ``window`` of steps. The elastic supervisor treats
    this as RECOVERABLE (restore the last valid disk checkpoint, same
    topology); anything above it treats it as the training run failing."""


@dataclasses.dataclass(frozen=True)
class SentinelConfig:
    """Knobs for detection and rollback; see the module docstring."""

    window: int = 16            # EWMA horizon AND the escalation window
    snapshot_every: int = 4     # steps between ring snapshots
    ring_size: int = 4          # retained snapshots (memory bound)
    spike_factor: float = 3.0   # loss > factor * (ewma + margin) = spike
    spike_margin: float = 0.25  # absolute slack so a near-zero EWMA does
    #                             not turn converged-loss jitter into spikes
    warmup_steps: int = 8       # healthy observations before spike checks
    max_rollbacks: int | None = None   # escalation budget (None: ring_size)

    def __post_init__(self):
        if self.window < 2:
            raise ValueError(f"sentinel window must be >= 2, got "
                             f"{self.window}")
        if self.snapshot_every < 1:
            raise ValueError(f"sentinel snapshot_every must be >= 1, got "
                             f"{self.snapshot_every}")
        if self.ring_size < 1:
            raise ValueError(f"sentinel ring_size must be >= 1, got "
                             f"{self.ring_size}")
        if self.spike_factor <= 1.0:
            raise ValueError(f"sentinel spike_factor must be > 1, got "
                             f"{self.spike_factor}")
        if self.spike_margin < 0 or self.warmup_steps < 0:
            raise ValueError("sentinel spike_margin/warmup_steps must be "
                             ">= 0")
        if self.max_rollbacks is not None and self.max_rollbacks < 1:
            raise ValueError(f"sentinel max_rollbacks must be >= 1, got "
                             f"{self.max_rollbacks}")

    @property
    def rollback_budget(self) -> int:
        return (self.ring_size if self.max_rollbacks is None
                else self.max_rollbacks)


class Snapshot(NamedTuple):
    """One host-side restore point (pre-step state at ``step``)."""

    step: int
    epoch: int
    batch_idx: int          # the data cursor: next batch to execute
    params: object          # np.ndarray copy of the packed param buffer
    opt_leaves: tuple       # np copies of the optimizer state leaves
    ewma: float | None      # EWMA state rides along so a rollback also
    healthy: int            # rewinds the detector, and replay re-updates
    #                         it with the identical losses
    nbytes: int


class Anomaly(NamedTuple):
    """One detection verdict (``observe``'s non-None return)."""

    step: int
    epoch: int
    batch_idx: int
    kind: str               # "nan" | "inf" | "spike"
    value: float            # the offending loss (or grad-norm) value


class SnapshotRing:
    """Bounded FIFO of :class:`Snapshot` entries (newest last)."""

    def __init__(self, ring_size: int) -> None:
        self._ring: collections.deque[Snapshot] = collections.deque(
            maxlen=ring_size)

    def __len__(self) -> int:
        return len(self._ring)

    def push(self, snap: Snapshot) -> None:
        # one snapshot per step: a replay re-gathering the same step
        # replaces the identical entry instead of aging a sibling out
        if self._ring and self._ring[-1].step == snap.step:
            self._ring[-1] = snap
            return
        self._ring.append(snap)

    def newest_at_or_before(self, step: int) -> Snapshot | None:
        """The rollback target: snapshots are PRE-step state, so the entry
        taken at the anomaly step itself is still clean."""
        for snap in reversed(self._ring):
            if snap.step <= step:
                return snap
        return None

    def bytes(self) -> int:
        return sum(s.nbytes for s in self._ring)

    def clear(self) -> None:
        self._ring.clear()


class QuarantineJournal:
    """Append-only JSONL journal of quarantined batches.

    Each record is ``{"epoch": E, "batch": B, "step": S, "kind": K,
    "value": V}``; the ``(epoch, batch)`` pair is the skip key — batch
    order is deterministic per epoch (fixed order, or the seeded shuffle),
    so the same journal skips the same data on every run that loads it.
    With ``path=None`` the journal is in-memory only (tests, dryruns);
    with a path it loads existing records on construction (a restarted or
    clean reference run skips identically) and flushes every append.

    ``write_ok=False`` (non-main processes of a multi-process run, which
    share the journal over the checkpoint filesystem): records and the
    skip set still update in memory — every rank must skip identically —
    but only the main process appends to the file, mirroring the
    checkpoint writers' rank-0 discipline (duplicated or interleaved
    appends from N hosts would corrupt the journal).
    """

    def __init__(self, path: str | None = None,
                 write_ok: bool = True) -> None:
        self.path = path
        self.write_ok = write_ok
        self.records: list[dict] = []
        self._skips: set[tuple[int, int]] = set()
        if path and os.path.exists(path):
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue           # torn tail: keep what parsed
                    self._note(rec)

    def _note(self, rec: dict) -> None:
        self.records.append(rec)
        self._skips.add((int(rec["epoch"]), int(rec["batch"])))

    def skip(self, epoch: int, batch_idx: int) -> bool:
        return (epoch, batch_idx) in self._skips

    def add(self, rec: dict) -> None:
        self._note(rec)
        if self.path and self.write_ok:
            os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                        exist_ok=True)
            with open(self.path, "a") as f:
                f.write(json.dumps(rec) + "\n")
                f.flush()

    def __len__(self) -> int:
        return len(self.records)


class Sentinel:
    """The per-run anomaly sentinel; the Trainer drives it per step.

    Protocol (``train/trainer.py``)::

        sentinel.begin_epoch(epoch)                  # clear ring, force snap
        sentinel.quarantined(epoch, batch_idx)       # skip check per batch
        sentinel.maybe_snapshot(step, ..., buf, opt) # pre-step, every K
        anomaly = sentinel.observe(step, ..., loss, gnorm)
        if anomaly:                                  # post-step
            snap = sentinel.rollback(anomaly)        # may raise Exhausted
            <restore snap, rewind the batch stream to snap.batch_idx>
    """

    def __init__(self, config: SentinelConfig | None = None, registry=None,
                 journal_path: str | None = None,
                 journal_write_ok: bool = True) -> None:
        self.config = config or SentinelConfig()
        self.registry = registry
        self.ring = SnapshotRing(self.config.ring_size)
        self.journal = QuarantineJournal(journal_path,
                                         write_ok=journal_write_ok)
        # step -> last HEALTHY loss: the bit-exactness record the recovery
        # pin compares (tests/test_sentinel.py). Bounded: only the recent
        # tail is ever revisited by a rollback, so old entries age out —
        # a million-step run must not grow an unbounded host-side dict.
        self.observed: dict[int, float] = {}
        self._observed_cap = max(4096, 4 * self.config.window)
        # a per-instance id stamped into every metric record: counters are
        # cumulative per sentinel LIFETIME, and the report CLI needs a
        # reliable generation boundary to sum across restarts (a pure
        # counter-drop heuristic misses a resumed run that re-accumulates
        # past the previous generation's count before its first record)
        self.run_id = "%08x" % int.from_bytes(os.urandom(4), "big")
        self.by_kind: dict[str, int] = {}
        self.n_anomalies = 0
        self.n_rollbacks = 0
        self._events: list[dict] = []          # drained per epoch record
        self._ewma: float | None = None
        self._healthy = 0
        self._alpha = 2.0 / (self.config.window + 1)
        self._last_anomaly_step: int | None = None
        self._streak = 0
        self._force_snapshot = False

    # -- counters ----------------------------------------------------------

    @property
    def n_quarantined(self) -> int:
        return len(self.journal)

    def _gauge_ring(self) -> None:
        if self.registry is not None:
            self.registry.gauge("train_snapshot_ring_bytes").set(
                self.ring.bytes())

    # -- epoch / snapshot lifecycle ---------------------------------------

    def begin_epoch(self, epoch: int) -> None:
        """Rollback is epoch-scoped (the epoch boundary ran eval/save, so
        rewinding across it would replay non-step work): clear the ring and
        force a snapshot at the first executed batch of the epoch, so a
        pre-anomaly restore point always exists."""
        self.ring.clear()
        self._force_snapshot = True
        self._gauge_ring()

    def maybe_snapshot(self, step: int, epoch: int, batch_idx: int,
                       buf, opt_state) -> bool:
        """Host-gather a restore point when one is due (every
        ``snapshot_every`` steps, or forced at epoch entry). Called BEFORE
        the step executes, so the captured state is pre-anomaly even when
        this very step is the poisoned one."""
        if not (self._force_snapshot
                or step % self.config.snapshot_every == 0):
            return False
        self._force_snapshot = False
        import jax
        import numpy as np

        from simple_distributed_machine_learning_tpu.train.checkpoint import (
            _to_host,
        )

        # copy=True: on the CPU backend device_get can alias the live XLA
        # buffer, which the next step's donation would reuse underneath a
        # long-lived ring entry
        params = np.array(_to_host(buf), copy=True)
        leaves = tuple(np.array(_to_host(leaf), copy=True)
                       for leaf in jax.tree.leaves(opt_state))
        nbytes = params.nbytes + sum(v.nbytes for v in leaves)
        self.ring.push(Snapshot(step=int(step), epoch=int(epoch),
                                batch_idx=int(batch_idx), params=params,
                                opt_leaves=leaves, ewma=self._ewma,
                                healthy=self._healthy, nbytes=nbytes))
        self._gauge_ring()
        return True

    # -- detection ---------------------------------------------------------

    def observe(self, step: int, epoch: int, batch_idx: int, loss: float,
                gnorm: float | None = None) -> Anomaly | None:
        """Judge one executed step. A healthy loss updates the EWMA and the
        per-step loss record; an anomalous one touches neither (so the
        detector's threshold and the bit-exactness record both match a run
        that never saw the fault)."""
        loss = float(loss)
        verdict = None
        for name, value in (("loss", loss),
                            ("grad-norm",
                             None if gnorm is None else float(gnorm))):
            if value is None:
                continue
            if math.isnan(value):
                verdict = ("nan", value)
                break
            if math.isinf(value):
                verdict = ("inf", value)
                break
        if (verdict is None and self._healthy >= self.config.warmup_steps
                and self._ewma is not None
                and loss > self.config.spike_factor
                * (self._ewma + self.config.spike_margin)):
            verdict = ("spike", loss)
        if verdict is None:
            self._ewma = (loss if self._ewma is None
                          else self._alpha * loss
                          + (1.0 - self._alpha) * self._ewma)
            self._healthy += 1
            self.observed[int(step)] = loss
            if len(self.observed) > self._observed_cap:
                # dicts iterate in insertion order: drop the oldest entry
                del self.observed[next(iter(self.observed))]
            return None
        kind, value = verdict
        self.n_anomalies += 1
        self.by_kind[kind] = self.by_kind.get(kind, 0) + 1
        event = {"step": int(step), "epoch": int(epoch),
                 "batch": int(batch_idx), "kind": kind,
                 "value": (None if math.isnan(value) or math.isinf(value)
                           else value)}
        self._events.append(event)
        if self.registry is not None:
            self.registry.counter("train_anomalies_total",
                                  labels={"kind": kind}).inc()
        return Anomaly(step=int(step), epoch=int(epoch),
                       batch_idx=int(batch_idx), kind=kind, value=value)

    # -- recovery ----------------------------------------------------------

    def quarantined(self, epoch: int, batch_idx: int) -> bool:
        return self.journal.skip(epoch, batch_idx)

    def rollback(self, anomaly: Anomaly) -> Snapshot:
        """Quarantine the offending batch, pick the restore point, rewind
        the detector state — or escalate with :class:`SentinelExhausted`
        when anomalies repeat faster than micro-rollback can absorb."""
        # quarantine FIRST: even an escalating anomaly's batch is recorded,
        # so the supervisor's next attempt (which loads the journal from
        # disk) skips it
        self.journal.add({"epoch": anomaly.epoch, "batch": anomaly.batch_idx,
                          "step": anomaly.step, "kind": anomaly.kind,
                          "value": (None if math.isnan(anomaly.value)
                                    or math.isinf(anomaly.value)
                                    else anomaly.value)})
        if self.registry is not None:
            self.registry.counter("train_quarantined_batches_total").inc()
        if (self._last_anomaly_step is not None
                and anomaly.step - self._last_anomaly_step
                <= self.config.window):
            self._streak += 1
        else:
            self._streak = 1
        self._last_anomaly_step = anomaly.step
        snap = self.ring.newest_at_or_before(anomaly.step)
        if snap is None or self._streak > self.config.rollback_budget:
            raise SentinelExhausted(
                f"sentinel exhausted at step {anomaly.step} "
                f"({anomaly.kind}): {self._streak} anomalies within a "
                f"{self.config.window}-step window exceed the "
                f"{self.config.rollback_budget}-rollback budget"
                if snap is not None else
                f"sentinel exhausted at step {anomaly.step} "
                f"({anomaly.kind}): no snapshot at or before the anomaly "
                f"remains in the ring")
        self.n_rollbacks += 1
        if self.registry is not None:
            self.registry.counter("train_rollbacks_total").inc()
        # rewind the detector with the state: replay re-updates it with
        # the identical losses, so post-recovery thresholds match a run
        # that never saw the fault
        self._ewma = snap.ewma
        self._healthy = snap.healthy
        return snap

    # -- persistence (rides the trainer checkpoint's ``extra``) -----------

    def detector_state(self) -> dict:
        """The EWMA detector's state, JSON-serializable. Checkpoints carry
        it so a resumed run's spike threshold matches the uninterrupted
        run's instead of re-warming from scratch (a spike right after
        resume must not slip through a cold detector)."""
        return {"ewma": self._ewma, "healthy": self._healthy}

    def restore_detector(self, state: dict) -> None:
        self._ewma = (None if state.get("ewma") is None
                      else float(state["ewma"]))
        self._healthy = int(state.get("healthy", 0))

    # -- reporting ---------------------------------------------------------

    def drain_events(self) -> list[dict]:
        events, self._events = self._events, []
        return events

    def stats(self) -> dict:
        return {"anomalies": self.n_anomalies,
                "by_kind": dict(self.by_kind),
                "rollbacks": self.n_rollbacks,
                "quarantined_batches": self.n_quarantined,
                # tells the report CLI whether this generation's quarantine
                # count carries the previous one's forward (reloaded from
                # disk — dedup on aggregation) or restarted from zero
                "quarantine_persistent": bool(self.journal.path),
                "snapshot_ring_bytes": self.ring.bytes(),
                "sentinel_run": self.run_id}
