"""Epoch driver: train/eval loops with the reference's console surface.

Replaces ``run_master`` and its inner ``train``/``test`` closures
(``/root/reference/simple_distributed.py:86-136``). Print formats are
byte-identical to the reference (``:114-117`` train, ``:130-132`` test) so
logs are directly comparable; an additional per-epoch throughput line covers
the north-star metric the reference never measured (SURVEY §6).

MPMD→SPMD note (SURVEY §7 hard part (c)): the reference's loops run only on
the master process while workers idle in an RPC serve loop. Here every process
runs the same loop; on multi-process runs each host feeds only its data-axis
rows of every batch (``_feed`` → ``data/sharding.py``), and only process 0
prints (``is_main``).
"""

from __future__ import annotations

import contextlib
import dataclasses
import sys

import jax
import numpy as np

from simple_distributed_machine_learning_tpu.data.mnist import (
    Dataset,
    batches,
    prefetch_batches,
)
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.resilience.faults import (
    maybe_fire,
)
from simple_distributed_machine_learning_tpu.train.optimizer import (
    Optimizer,
    sgd,
    shard_opt_state_zero1,
)
from simple_distributed_machine_learning_tpu.train.step import (
    make_eval_step,
    make_train_step,
)
from simple_distributed_machine_learning_tpu.utils.metrics import Throughput

# Reference hyperparameters (simple_distributed.py:18-22)
BATCH_SIZE = 60
EPOCHS = 10
LEARNING_RATE = 0.1
MOMENTUM = 0.5
LOG_INTERVAL = 10


@dataclasses.dataclass
class TrainConfig:
    epochs: int = EPOCHS
    batch_size: int = BATCH_SIZE
    learning_rate: float = LEARNING_RATE
    momentum: float = MOMENTUM
    log_interval: int = LOG_INTERVAL
    seed: int = 0
    print_throughput: bool = True
    # persistence (absent from the reference, SURVEY §5.4): a checkpoint is
    # written after every epoch and auto-resumed from on construction
    checkpoint_dir: str | None = None
    resume: bool = True
    # overlap the checkpoint FILE WRITE with the next epoch's compute (the
    # device->host gather stays synchronous — it is a collective)
    async_checkpoint: bool = False
    # ZeRO-1: shard optimizer state over the data axis (pure sharding
    # annotation; GSPMD inserts the collectives — optimizer.py)
    zero1: bool = False
    # seeded per-epoch shuffle of the train set (the reference trains in
    # fixed order, simple_distributed.py:94-95 — kept as the default for
    # loss-curve parity)
    shuffle: bool = False
    # per-host input sharding (multi-process runs): each process feeds only
    # its data-axis rows of every batch instead of materializing the global
    # batch on every host (data/sharding.py). On a single process this is a
    # no-op path and the plain numpy feed is used.
    shard_inputs: bool = True
    # machine-readable training log: one JSON line per epoch (epoch, step,
    # train_loss, samples_per_sec, eval_loss, accuracy — plus the raw
    # correct/n_eval counts the accuracy is computed from) appended to this
    # path by process 0. The console surface stays byte-identical to the
    # reference; this is the structured counterpart (SURVEY §5.5). Records
    # are written through the telemetry registry and carry "schema": 2.
    metrics_json: str | None = None
    # smoke/dryrun mode (cli.py --dryrun): train at most this many batches
    # per epoch. None = the full dataset, the reference's behavior.
    max_steps_per_epoch: int | None = None


class Trainer:
    """Drives a :class:`Pipeline` over a dataset, reference-style."""

    def __init__(self, pipe: Pipeline, train_ds: Dataset, test_ds: Dataset,
                 config: TrainConfig | None = None,
                 opt: Optimizer | None = None, telemetry=None) -> None:
        self.pipe = pipe
        self.train_ds = train_ds
        self.test_ds = test_ds
        self.config = config or TrainConfig()
        # the observability hook (telemetry/session.py): per-step latency
        # sampling, feed/step/eval host spans, per-epoch metric emission.
        # None = reference behavior (console + optional metrics_json only).
        self.telemetry = telemetry
        # LM datasets have [N, T] targets: telemetry reports tokens/sec
        # alongside examples/sec (0 = classifier, no token throughput)
        self._tokens_per_sample = (int(np.prod(train_ds.y.shape[1:]))
                                   if np.ndim(train_ds.y) > 1 else 0)
        self._registry = telemetry.registry if telemetry is not None else None
        self.opt = opt or sgd(self.config.learning_rate, self.config.momentum)
        self.buf = pipe.init_params()
        self.opt_state = self.opt.init(self.buf)
        if self.config.zero1:
            self.opt_state = shard_opt_state_zero1(
                self.opt_state, pipe.mesh, pipe.param_spec())
        self._train_step = make_train_step(pipe, self.opt)
        self._eval_step = make_eval_step(pipe)
        self._key = jax.random.key(self.config.seed)
        self._step_count = 0
        self._last_samples_per_sec = 0.0
        self._pending_save = None
        self.start_epoch = 1
        self.is_main = jax.process_index() == 0
        self._shard_inputs = (self.config.shard_inputs
                              and jax.process_count() > 1)
        self._shard_announced = False
        self._host_rows_cache: dict[int, tuple[int, int]] = {}
        if self.config.checkpoint_dir and self.config.resume:
            self._maybe_resume()

    # -- persistence (reference has none: SURVEY §5.4) --------------------

    def _ckpt_path(self) -> str:
        import os
        return os.path.join(self.config.checkpoint_dir, "state.npz")

    def _maybe_resume(self) -> None:
        import os
        path = self._ckpt_path()
        found = os.path.exists(path)
        if jax.process_count() > 1:
            # all processes must agree on whether/where to resume, or they
            # would issue different numbers of collective steps and hang
            # (e.g. checkpoint_dir on a non-shared filesystem)
            from jax.experimental import multihost_utils
            founds = multihost_utils.process_allgather(
                np.asarray([1 if found else 0], np.int32))
            if int(founds.min()) != int(founds.max()):
                raise RuntimeError(
                    f"checkpoint {path} visible on only some processes — "
                    "checkpoint_dir must be a shared filesystem for "
                    "multi-process resume")
        if not found:
            return
        from simple_distributed_machine_learning_tpu.train.checkpoint import (
            restore_checkpoint,
        )
        st = restore_checkpoint(path, pipe=self.pipe,
                                opt_treedef_like=self.opt_state)
        if tuple(st["params"].shape) != tuple(self.buf.shape):
            raise ValueError(
                f"checkpoint {path} does not match the model: packed param "
                f"buffer is {tuple(st['params'].shape)}, model expects "
                f"{tuple(self.buf.shape)} (different model/topology "
                f"config?). A checkpoint from a different contiguous stage "
                f"split of the SAME model can be rewritten with "
                f"train.checkpoint.repack_checkpoint (or restored with "
                f"restore_checkpoint(..., src_pipe=<source pipeline>)).")
        self.buf, self.opt_state = st["params"], st["opt_state"]
        self._step_count = st["step"]
        self.start_epoch = int(st["extra"].get("epoch", 0)) + 1
        self._print(f"| resumed from {path} at epoch {self.start_epoch} "
                    f"(step {self._step_count})")

    def _save(self, epoch: int) -> None:
        if not self.config.checkpoint_dir:
            return
        from simple_distributed_machine_learning_tpu.train.checkpoint import (
            save_checkpoint,
            save_checkpoint_async,
        )
        # every process participates: gathering non-addressable shards is a
        # collective inside save_checkpoint; only process 0 writes the file
        if self.config.async_checkpoint:
            if self._pending_save is not None:
                self._wait_pending()         # one write in flight at a time
            self._pending_save = save_checkpoint_async(
                self._ckpt_path(), self.buf, self.opt_state,
                self._step_count, extra={"epoch": epoch})
        else:
            save_checkpoint(self._ckpt_path(), self.buf, self.opt_state,
                            self._step_count, extra={"epoch": epoch})

    def _wait_pending(self) -> None:
        """Drain the in-flight async checkpoint write, SURFACING a failed
        write: ``AsyncSave.wait`` re-raises the writer thread's exception
        (original type and traceback — the supervisor's recoverability
        dispatch depends on the type) after a loud diagnostic, instead of
        letting a dead checkpoint pass silently as training success."""
        pending, self._pending_save = self._pending_save, None
        try:
            pending.wait()
        except BaseException as e:
            sys.stderr.write(
                f"[checkpoint] async write to {self._ckpt_path()} FAILED "
                f"({type(e).__name__}: {e}) — surfacing the writer "
                f"thread's error; the previously committed checkpoint is "
                f"intact\n")
            sys.stderr.flush()
            raise

    # -- reference console surface (simple_distributed.py:114-117,:130-132) --

    def _print(self, msg: str) -> None:
        if self.is_main:
            print(msg)

    def _feed(self, x, y, w):
        """Batch feed: per-host data-axis slices assembled into global
        arrays on multi-process runs, plain numpy otherwise.

        The slice is taken host-side BEFORE any device transfer, so each
        host's memory traffic is rows/dp, not the global batch — the correct
        multi-host mapping of the reference's master-only loading
        (simple_distributed.py:87-95, SURVEY §7 hard part (c))."""
        if not self._shard_inputs:
            return x, y, w
        import os
        import sys

        from simple_distributed_machine_learning_tpu.data.sharding import (
            host_rows,
            make_global_batch,
        )
        B = len(x)
        # (mesh, B) -> rows is run-invariant; don't pay the sharding-map
        # query on every hot-loop step (train and eval batches are padded to
        # a constant size, so this caches exactly one or two entries)
        lo_hi = self._host_rows_cache.get(B)
        if lo_hi is None:
            lo_hi = self._host_rows_cache[B] = host_rows(self.pipe.mesh, B)
        lo, hi = lo_hi
        if not self._shard_announced:
            self._shard_announced = True
            if os.environ.get("SDML_DEBUG_SHARDING"):
                # stderr + every rank: diagnostics must not touch the
                # reference-format (rank-0-only) stdout surface
                print(f"| host {jax.process_index()}: input rows "
                      f"[{lo},{hi}) of {B}", file=sys.stderr, flush=True)
        mesh = self.pipe.mesh
        xg = make_global_batch(mesh, x[lo:hi], B)
        yg = make_global_batch(mesh, y[lo:hi], B)
        wg = None if w is None else make_global_batch(mesh, w[lo:hi], B)
        return xg, yg, wg

    def train_epoch(self, epoch: int) -> float:
        cfg = self.config
        tele = self.telemetry
        meter = Throughput()
        n_total = len(self.train_ds.x)
        n_batches = max(1, (n_total + cfg.batch_size - 1) // cfg.batch_size)
        loss = 0.0
        # batch assembly on the native C++ prefetcher thread when available
        # (transparent python fallback), overlapped with the device step
        shuffle_seed = (cfg.seed * 100003 + epoch) if cfg.shuffle else None
        if tele is not None:
            tele.mark()                  # window start = loop entry, not init
        for batch_idx, b in enumerate(
                prefetch_batches(self.train_ds, cfg.batch_size,
                                 shuffle_seed=shuffle_seed)):
            if (cfg.max_steps_per_epoch is not None
                    and batch_idx >= cfg.max_steps_per_epoch):
                break
            # fault-injection site (resilience/faults.py): a scheduled
            # host-kill raises HostLost here (mid-epoch, between steps —
            # the supervisor restores from disk), slow-tick stalls the
            # step; one `is None` check when no plan is installed
            maybe_fire("train.step", step=self._step_count)
            key = jax.random.fold_in(self._key, self._step_count)
            # ragged final batch: zero-padded, masked out of the loss mean
            # (the reference just trains on the short batch, :108-113; the
            # weighted mean here gives the identical gradient)
            w = None
            if b.n_valid < len(b.x):
                w = (np.arange(len(b.x)) < b.n_valid).astype(np.float32)
            with (tele.span("feed") if tele is not None
                  else contextlib.nullcontext()):
                x, y, w = self._feed(b.x, b.y, w)
            if (tele is not None and batch_idx == 0
                    and epoch == self.start_epoch):
                # register the exact step + shapes for the static ICI-bytes
                # gauge (trace-only; shapes captured BEFORE donation).
                # Keyed on the run's first batch — not _step_count, which a
                # checkpoint resume starts nonzero
                from simple_distributed_machine_learning_tpu.analysis import (
                    abstractify,
                )
                tele.set_step_probe(
                    self._train_step, abstractify(self.buf),
                    abstractify(self.opt_state), abstractify(x),
                    abstractify(y), abstractify(key),
                    abstractify(w) if w is not None else None,
                    mesh=self.pipe.mesh)
            with (tele.span("step") if tele is not None
                  else contextlib.nullcontext()):
                self.buf, self.opt_state, loss = self._train_step(
                    self.buf, self.opt_state, x, y, key, w)
            self._step_count += 1
            meter.update(b.n_valid)
            if tele is not None:
                # the first batch of the run is forced: that window is the
                # compile window and the StepTimer keeps it split out
                tele.on_step(
                    loss, examples=b.n_valid,
                    tokens=b.n_valid * self._tokens_per_sample,
                    force_fence=(batch_idx == 0))
            if batch_idx == 0:
                # first step includes trace+compile; keep it out of the
                # throughput window (the metric is chip throughput)
                jax.block_until_ready(loss)
                meter.reset()
            if batch_idx % cfg.log_interval == 0:
                self._print(
                    'Train Epoch: {} [{}/{} ({:.0f}%)]\tLoss: {:.6f}'.format(
                        epoch, batch_idx * len(b.x), n_total,
                        100.0 * batch_idx / n_batches, float(loss)))
        jax.block_until_ready(self.buf)      # drain async-dispatched steps
        self._last_samples_per_sec = meter.samples_per_sec
        if cfg.print_throughput:
            self._print('| epoch {}: {:.1f} samples/sec'.format(
                epoch, meter.samples_per_sec))
        return float(loss)

    def evaluate(self) -> tuple[float, int]:
        cfg = self.config
        tele = self.telemetry
        total_loss = 0.0
        correct = 0
        # prediction units: samples for classifiers (y: [N]), tokens for
        # language models (y: [N, T]) — y.size covers both
        n = int(self.test_ds.y.size)
        for b in batches(self.test_ds, cfg.batch_size, pad_last=True):
            with (tele.span("eval") if tele is not None
                  else contextlib.nullcontext()):
                x, y, _ = self._feed(b.x, b.y, None)
                sl, c = self._eval_step(self.buf, x, y, self._key,
                                        np.int32(b.n_valid))
                total_loss += float(sl)      # host read closes the span at
                correct += int(c)            # the batch's true end
        avg = total_loss / n
        self._print(
            '\nTest set: Average loss: {:.4f}, Accuracy: {}/{} ({:.0f}%)\n'
            .format(avg, correct, n, 100.0 * correct / n))
        return avg, correct

    def _log_metrics(self, record: dict) -> None:
        """Per-epoch metrics through the telemetry registry.

        Every field is mirrored into registry instruments (monotonic
        counters for step/correct counts, gauges for the rest) so the same
        numbers ride the Prometheus exposition when telemetry is on; the
        JSONL line keeps every documented key (``accuracy`` is the headline)
        and is now schema-versioned (``"schema": 2`` — schema 1 was the bare
        unversioned record).
        """
        from simple_distributed_machine_learning_tpu.telemetry.registry import (
            append_jsonl,
        )
        reg = self._registry
        if reg is not None:
            # a Telemetry session is attached: its registry (and thus the
            # Prometheus exposition) carries the training series too
            steps = reg.counter("train_steps_total")
            steps.inc(record["step"] - steps.value)
            if record["correct"] is not None:
                reg.counter("eval_correct_total").inc(record["correct"])
            for key in ("train_loss", "eval_loss", "accuracy",
                        "samples_per_sec"):
                if record.get(key) is not None:
                    reg.gauge(key).set(record[key])
        if not (self.config.metrics_json and self.is_main):
            return
        append_jsonl(self.config.metrics_json, record, schema=2)

    def fit(self) -> None:
        """The reference's epoch driver (``simple_distributed.py:134-136``),
        plus per-epoch checkpointing when ``checkpoint_dir`` is set and a
        JSONL metrics record per epoch when ``metrics_json`` is set."""
        for epoch in range(self.start_epoch, self.config.epochs + 1):
            train_loss = self.train_epoch(epoch)
            eval_loss, correct = self.evaluate()
            n_eval = int(self.test_ds.y.size)
            record = {
                "epoch": epoch,
                "step": self._step_count,
                "train_loss": round(train_loss, 6),
                "samples_per_sec": round(self._last_samples_per_sec, 1),
                "eval_loss": round(eval_loss, 6),
                # accuracy is the documented key (--metrics-json help); the
                # raw counts stay so consumers can re-aggregate across epochs
                "accuracy": round(correct / n_eval, 6) if n_eval else None,
                "correct": correct,
                "n_eval": n_eval,
            }
            self._log_metrics(record)
            if self.telemetry is not None:
                # the full per-epoch telemetry record: step-latency
                # quantiles, throughput, memory, bubble estimate, ICI bytes
                # — with the training record's fields riding along
                self.telemetry.on_epoch(epoch, pipe=self.pipe, extra=record)
            self._save(epoch)
        if self._pending_save is not None:
            self._wait_pending()
        if self.telemetry is not None:
            self.telemetry.close()
