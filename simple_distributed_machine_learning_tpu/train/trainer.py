"""Epoch driver: train/eval loops with the reference's console surface.

Replaces ``run_master`` and its inner ``train``/``test`` closures
(``/root/reference/simple_distributed.py:86-136``). Print formats are
byte-identical to the reference (``:114-117`` train, ``:130-132`` test) so
logs are directly comparable; an additional per-epoch throughput line covers
the north-star metric the reference never measured (SURVEY §6).

MPMD→SPMD note (SURVEY §7 hard part (c)): the reference's loops run only on
the master process while workers idle in an RPC serve loop. Here every process
runs the same loop; on multi-process runs each host feeds only its data-axis
rows of every batch (``_feed`` → ``data/sharding.py``), and only process 0
prints (``is_main``).
"""

from __future__ import annotations

import contextlib
import dataclasses
import sys

import jax
import numpy as np

from simple_distributed_machine_learning_tpu.data.mnist import (
    Dataset,
    batches,
    prefetch_batches,
)
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.resilience.faults import (
    active as faults_active,
    check as faults_check,
    maybe_fire,
)
from simple_distributed_machine_learning_tpu.train.optimizer import (
    Optimizer,
    sgd,
    shard_opt_state_zero1,
)
from simple_distributed_machine_learning_tpu.train.step import (
    make_eval_step,
    make_train_step,
)
from simple_distributed_machine_learning_tpu.utils.metrics import Throughput

# Reference hyperparameters (simple_distributed.py:18-22)
BATCH_SIZE = 60
EPOCHS = 10
LEARNING_RATE = 0.1
MOMENTUM = 0.5
LOG_INTERVAL = 10


@dataclasses.dataclass
class TrainConfig:
    epochs: int = EPOCHS
    batch_size: int = BATCH_SIZE
    learning_rate: float = LEARNING_RATE
    momentum: float = MOMENTUM
    log_interval: int = LOG_INTERVAL
    seed: int = 0
    print_throughput: bool = True
    # persistence (absent from the reference, SURVEY §5.4): a checkpoint is
    # written after every epoch and auto-resumed from on construction
    checkpoint_dir: str | None = None
    resume: bool = True
    # overlap the checkpoint FILE WRITE with the next epoch's compute (the
    # device->host gather stays synchronous — it is a collective)
    async_checkpoint: bool = False
    # ZeRO-1: shard optimizer state over the data axis (pure sharding
    # annotation; GSPMD inserts the collectives — optimizer.py)
    zero1: bool = False
    # seeded per-epoch shuffle of the train set (the reference trains in
    # fixed order, simple_distributed.py:94-95 — kept as the default for
    # loss-curve parity)
    shuffle: bool = False
    # per-host input sharding (multi-process runs): each process feeds only
    # its data-axis rows of every batch instead of materializing the global
    # batch on every host (data/sharding.py). On a single process this is a
    # no-op path and the plain numpy feed is used.
    shard_inputs: bool = True
    # machine-readable training log: one JSON line per epoch (epoch, step,
    # train_loss, samples_per_sec, eval_loss, accuracy — plus the raw
    # correct/n_eval counts the accuracy is computed from) appended to this
    # path by process 0. The console surface stays byte-identical to the
    # reference; this is the structured counterpart (SURVEY §5.5). Records
    # are written through the telemetry registry and carry "schema": 2.
    metrics_json: str | None = None
    # smoke/dryrun mode (cli.py --dryrun): train at most this many batches
    # per epoch. None = the full dataset, the reference's behavior.
    max_steps_per_epoch: int | None = None
    # self-healing training (resilience/sentinel.py): check every step's
    # loss/grad-norm for NaN/Inf and EWMA loss spikes, keep a bounded
    # in-memory ring of host snapshots, and on an anomaly roll back to the
    # newest pre-anomaly snapshot, quarantine the offending batch (recorded
    # in <checkpoint_dir>/quarantine.jsonl and deterministically skipped
    # from then on) and replay forward — bit-exact vs a run that never saw
    # the fault. Cost when on: one device→host scalar sync per step and a
    # host gather every sentinel_snapshot_every steps.
    sentinel: bool = False
    sentinel_window: int = 16        # EWMA horizon + escalation window
    sentinel_snapshot_every: int = 4
    sentinel_ring: int = 4           # retained snapshots (memory bound)
    sentinel_spike_factor: float = 3.0


class Trainer:
    """Drives a :class:`Pipeline` over a dataset, reference-style."""

    def __init__(self, pipe: Pipeline, train_ds: Dataset, test_ds: Dataset,
                 config: TrainConfig | None = None,
                 opt: Optimizer | None = None, telemetry=None) -> None:
        self.pipe = pipe
        self.train_ds = train_ds
        self.test_ds = test_ds
        self.config = config or TrainConfig()
        # the observability hook (telemetry/session.py): per-step latency
        # sampling, feed/step/eval host spans, per-epoch metric emission.
        # None = reference behavior (console + optional metrics_json only).
        self.telemetry = telemetry
        # LM datasets have [N, T] targets: telemetry reports tokens/sec
        # alongside examples/sec (0 = classifier, no token throughput)
        self._tokens_per_sample = (int(np.prod(train_ds.y.shape[1:]))
                                   if np.ndim(train_ds.y) > 1 else 0)
        self._registry = telemetry.registry if telemetry is not None else None
        self.opt = opt or sgd(self.config.learning_rate, self.config.momentum)
        self.buf = pipe.init_params()
        self.opt_state = self.opt.init(self.buf)
        if self.config.zero1:
            self.opt_state = shard_opt_state_zero1(
                self.opt_state, pipe.mesh, pipe.param_spec())
        self._train_step = make_train_step(
            pipe, self.opt, with_grad_norm=self.config.sentinel)
        self._eval_step = make_eval_step(pipe)
        self._key = jax.random.key(self.config.seed)
        self._step_count = 0
        self._last_samples_per_sec = 0.0
        self._pending_save = None
        self.start_epoch = 1
        self.is_main = jax.process_index() == 0
        self._shard_inputs = (self.config.shard_inputs
                              and jax.process_count() > 1)
        self._shard_announced = False
        self._host_rows_cache: dict[int, tuple[int, int]] = {}
        # graceful preemption (SIGTERM / injected preempt@train.sigterm):
        # finish the in-flight step, synchronous checkpoint with the data
        # cursor, quarantine-journal flush, clean return
        self._stop_requested = False
        self._stop_signal: int | None = None
        self._preempt_cursor: int | None = None
        self._resume_batch_idx = 0
        self.preempted = False
        self.preempt_persisted = False
        self._sentinel = None
        if self.config.sentinel:
            import os

            from simple_distributed_machine_learning_tpu.resilience.sentinel import (  # noqa: E501
                Sentinel,
                SentinelConfig,
            )
            jdir = self._sentinel_dir()
            self._sentinel = Sentinel(
                SentinelConfig(
                    window=self.config.sentinel_window,
                    snapshot_every=self.config.sentinel_snapshot_every,
                    ring_size=self.config.sentinel_ring,
                    spike_factor=self.config.sentinel_spike_factor),
                registry=self._registry,
                journal_path=(os.path.join(jdir, "quarantine.jsonl")
                              if jdir else None),
                # rank-0 writes the shared journal; every rank still loads
                # it and skips identically (the checkpoint writers' rule)
                journal_write_ok=self.is_main)
        if self.config.checkpoint_dir and self.config.resume:
            self._maybe_resume()

    # -- persistence (reference has none: SURVEY §5.4) --------------------

    def _ckpt_path(self) -> str:
        import os
        return os.path.join(self.config.checkpoint_dir, "state.npz")

    def _maybe_resume(self) -> None:
        import os
        path = self._ckpt_path()
        found = os.path.exists(path)
        if jax.process_count() > 1:
            # all processes must agree on whether/where to resume, or they
            # would issue different numbers of collective steps and hang
            # (e.g. checkpoint_dir on a non-shared filesystem)
            from jax.experimental import multihost_utils
            founds = multihost_utils.process_allgather(
                np.asarray([1 if found else 0], np.int32))
            if int(founds.min()) != int(founds.max()):
                raise RuntimeError(
                    f"checkpoint {path} visible on only some processes — "
                    "checkpoint_dir must be a shared filesystem for "
                    "multi-process resume")
        if not found:
            return
        from simple_distributed_machine_learning_tpu.train.checkpoint import (
            restore_checkpoint,
        )
        st = restore_checkpoint(path, pipe=self.pipe,
                                opt_treedef_like=self.opt_state)
        if tuple(st["params"].shape) != tuple(self.buf.shape):
            raise ValueError(
                f"checkpoint {path} does not match the model: packed param "
                f"buffer is {tuple(st['params'].shape)}, model expects "
                f"{tuple(self.buf.shape)} (different model/topology "
                f"config?). A checkpoint from a different contiguous stage "
                f"split of the SAME model can be rewritten with "
                f"train.checkpoint.repack_checkpoint (or restored with "
                f"restore_checkpoint(..., src_pipe=<source pipeline>)).")
        self.buf, self.opt_state = st["params"], st["opt_state"]
        self._step_count = st["step"]
        self.start_epoch = int(st["extra"].get("epoch", 0)) + 1
        # a graceful-preemption checkpoint carries the mid-epoch data
        # cursor: the saved epoch is the last COMPLETED one, next_batch is
        # where the interrupted epoch re-enters
        self._resume_batch_idx = int(st["extra"].get("next_batch", 0))
        if self._sentinel is not None and "sentinel" in st["extra"]:
            self._sentinel.restore_detector(st["extra"]["sentinel"])
        self._print(f"| resumed from {path} at epoch {self.start_epoch} "
                    f"(step {self._step_count})"
                    + (f" (batch {self._resume_batch_idx})"
                       if self._resume_batch_idx else ""))

    def _save_extra(self, epoch: int, cursor: int | None) -> dict:
        """Checkpoint ``extra`` metadata. A completed epoch records itself;
        a graceful-preemption save mid-epoch records the last COMPLETED
        epoch plus the ``next_batch`` data cursor, so resume re-enters the
        interrupted epoch at the exact batch (same steps, same keys —
        bit-identical to the uninterrupted run). With the sentinel on, the
        EWMA detector state rides along so the resumed run's spike
        threshold matches the uninterrupted run's."""
        extra = ({"epoch": epoch} if cursor is None
                 else {"epoch": epoch - 1, "next_batch": int(cursor)})
        if self._sentinel is not None:
            extra["sentinel"] = self._sentinel.detector_state()
        return extra

    def _save(self, epoch: int, cursor: int | None = None,
              sync: bool = False) -> None:
        if not self.config.checkpoint_dir:
            return
        from simple_distributed_machine_learning_tpu.train.checkpoint import (
            save_checkpoint,
            save_checkpoint_async,
        )
        extra = self._save_extra(epoch, cursor)
        # every process participates: gathering non-addressable shards is a
        # collective inside save_checkpoint; only process 0 writes the file
        if self.config.async_checkpoint and not sync:
            if self._pending_save is not None:
                self._wait_pending()         # one write in flight at a time
            self._pending_save = save_checkpoint_async(
                self._ckpt_path(), self.buf, self.opt_state,
                self._step_count, extra=extra)
        else:
            save_checkpoint(self._ckpt_path(), self.buf, self.opt_state,
                            self._step_count, extra=extra)

    def _wait_pending(self) -> None:
        """Drain the in-flight async checkpoint write, SURFACING a failed
        write: ``AsyncSave.wait`` re-raises the writer thread's exception
        (original type and traceback — the supervisor's recoverability
        dispatch depends on the type) after a loud diagnostic, instead of
        letting a dead checkpoint pass silently as training success."""
        pending, self._pending_save = self._pending_save, None
        try:
            pending.wait()
        except BaseException as e:
            sys.stderr.write(
                f"[checkpoint] async write to {self._ckpt_path()} FAILED "
                f"({type(e).__name__}: {e}) — surfacing the writer "
                f"thread's error; the previously committed checkpoint is "
                f"intact\n")
            sys.stderr.flush()
            raise

    # -- self-healing training (resilience/sentinel.py) --------------------

    def _sentinel_dir(self) -> str | None:
        """Directory for the quarantine journal (``quarantine.jsonl``);
        None = in-memory journal. ``ElasticTrainer`` overrides this to its
        checkpoint store's directory."""
        return self.config.checkpoint_dir

    @property
    def sentinel(self):
        return self._sentinel

    def sentinel_stats(self) -> dict | None:
        """Cumulative sentinel counters (None when the sentinel is off) —
        the per-epoch metric record and the supervisor's attempt report
        both embed this."""
        return (None if self._sentinel is None
                else self._sentinel.stats())

    def request_stop(self, signum: int | None = None) -> None:
        """Graceful preemption (the CLI's SIGTERM/SIGINT handler calls
        this): the in-flight step finishes, then ``fit`` writes a
        synchronous checkpoint carrying the data cursor, flushes the
        quarantine journal and telemetry, and returns cleanly."""
        self._stop_requested = True
        self._stop_signal = signum

    def _restore_snapshot(self, snap) -> None:
        """Micro-rollback: re-place a ring snapshot's host state onto the
        live shardings (the mirror of ``restore_checkpoint``'s placement —
        mesh-sharded leaves via device_put, scalar optimizer leaves left as
        host values so jit replicates them)."""
        from jax.sharding import NamedSharding
        self.buf = jax.device_put(
            snap.params, NamedSharding(self.pipe.mesh,
                                       self.pipe.param_spec()))
        treedef = jax.tree.structure(self.opt_state)
        live = jax.tree.leaves(self.opt_state)
        leaves = []
        for ref, arr in zip(live, snap.opt_leaves):
            sh = getattr(ref, "sharding", None)
            leaves.append(jax.device_put(arr, sh)
                          if isinstance(sh, NamedSharding) else arr)
        self.opt_state = jax.tree.unflatten(treedef, leaves)
        self._step_count = snap.step

    def _epoch_stream(self, shuffle_seed: int | None, start_idx: int):
        """The epoch's ``(batch_idx, Batch)`` stream from ``start_idx``
        (0 = the whole epoch). Rollback and mid-epoch resume both re-enter
        here: batch order is deterministic per (epoch, seed), so skipping
        forward replays the exact same data the first pass saw."""
        stream = prefetch_batches(self.train_ds, self.config.batch_size,
                                  shuffle_seed=shuffle_seed)
        try:
            for i, b in enumerate(stream):
                if i < start_idx:
                    continue
                yield i, b
        finally:
            stream.close()

    def _apply_numeric_faults(self, x, step: int):
        """Interpret the sentinel's seeded numeric fault kinds
        (``resilience/faults.py``) on the RAW host batch, before any
        feed/sharding: nan-grad scales the inputs by NaN (the backward
        produces NaN gradients and the donated update destroys the
        params), corrupt-batch overflows them to non-finite, loss-spike
        scales them 100x (a large but finite excursion for the EWMA
        detector — f32-safe, unlike corrupt-batch's overflow). Without the sentinel the same sites fire the standard
        effect — a raised NumericFault — so a drill can never pass
        vacuously against an undefended trainer."""
        if faults_active() is None:
            return x
        if self._sentinel is None:
            maybe_fire("train.grad", step=step)
            maybe_fire("data.batch", step=step)
            return x
        fired = (faults_check("train.grad", step=step)
                 + faults_check("data.batch", step=step)
                 + faults_check("train.step", step=step,
                                only=("loss-spike",)))
        for spec in fired:
            if spec.kind == "nan-grad":
                x = np.asarray(x) * np.float32("nan")
            elif spec.kind == "corrupt-batch":
                x = np.asarray(x) * np.float32(1e30)
            elif spec.kind == "loss-spike":
                x = np.asarray(x) * np.float32(100.0)
        return x

    # -- reference console surface (simple_distributed.py:114-117,:130-132) --

    def _print(self, msg: str) -> None:
        if self.is_main:
            print(msg)

    def _feed(self, x, y, w):
        """Batch feed: per-host data-axis slices assembled into global
        arrays on multi-process runs, plain numpy otherwise.

        The slice is taken host-side BEFORE any device transfer, so each
        host's memory traffic is rows/dp, not the global batch — the correct
        multi-host mapping of the reference's master-only loading
        (simple_distributed.py:87-95, SURVEY §7 hard part (c))."""
        if not self._shard_inputs:
            return x, y, w
        import os
        import sys

        from simple_distributed_machine_learning_tpu.data.sharding import (
            host_rows,
            make_global_batch,
        )
        B = len(x)
        # (mesh, B) -> rows is run-invariant; don't pay the sharding-map
        # query on every hot-loop step (train and eval batches are padded to
        # a constant size, so this caches exactly one or two entries)
        lo_hi = self._host_rows_cache.get(B)
        if lo_hi is None:
            lo_hi = self._host_rows_cache[B] = host_rows(self.pipe.mesh, B)
        lo, hi = lo_hi
        if not self._shard_announced:
            self._shard_announced = True
            if os.environ.get("SDML_DEBUG_SHARDING"):
                # stderr + every rank: diagnostics must not touch the
                # reference-format (rank-0-only) stdout surface
                print(f"| host {jax.process_index()}: input rows "
                      f"[{lo},{hi}) of {B}", file=sys.stderr, flush=True)
        mesh = self.pipe.mesh
        xg = make_global_batch(mesh, x[lo:hi], B)
        yg = make_global_batch(mesh, y[lo:hi], B)
        wg = None if w is None else make_global_batch(mesh, w[lo:hi], B)
        return xg, yg, wg

    def train_epoch(self, epoch: int) -> float:
        cfg = self.config
        tele = self.telemetry
        sent = self._sentinel
        meter = Throughput()
        n_total = len(self.train_ds.x)
        n_batches = max(1, (n_total + cfg.batch_size - 1) // cfg.batch_size)
        loss = 0.0
        # batch assembly on the native C++ prefetcher thread when available
        # (transparent python fallback), overlapped with the device step
        shuffle_seed = (cfg.seed * 100003 + epoch) if cfg.shuffle else None
        # mid-epoch resume cursor (graceful-preemption checkpoints only):
        # consumed once, by the first epoch the run re-enters
        start_idx = (self._resume_batch_idx if epoch == self.start_epoch
                     else 0)
        self._resume_batch_idx = 0
        if tele is not None:
            tele.mark()                  # window start = loop entry, not init
        if sent is not None:
            sent.begin_epoch(epoch)      # fresh ring + forced entry snapshot
        stream = self._epoch_stream(shuffle_seed, start_idx)
        first = True                     # first EXECUTED batch of the epoch
        try:
            # explicit next() rather than `for ... in stream`: a rollback
            # REPLACES the stream mid-loop (rewound to the snapshot's data
            # cursor), which a for-loop's captured iterator would ignore
            while True:
                nxt = next(stream, None)
                if nxt is None:
                    break
                batch_idx, b = nxt
                if (cfg.max_steps_per_epoch is not None
                        and batch_idx >= cfg.max_steps_per_epoch):
                    break
                if sent is not None and sent.quarantined(epoch, batch_idx):
                    continue             # deterministic corrupt-batch skip
                step = self._step_count
                # graceful-preemption probe (injected preempt@train.sigterm
                # — the SIGTERM drill's deterministic in-process twin) plus
                # the async SIGTERM/SIGINT flag: checked BEFORE the next
                # step starts, so the in-flight one always finishes
                if faults_check("train.sigterm", step=step):
                    self._stop_requested = True
                if self._stop_requested:
                    self._preempt_cursor = batch_idx
                    break
                if sent is not None:
                    # pre-step snapshot: captured before the (possibly
                    # poisoned) update, so this very step's state is a
                    # valid rollback target
                    sent.maybe_snapshot(step, epoch, batch_idx, self.buf,
                                        self.opt_state)
                # fault-injection site (resilience/faults.py): a scheduled
                # host-kill raises HostLost here (mid-epoch, between steps —
                # the supervisor restores from disk), slow-tick stalls the
                # step; one `is None` check when no plan is installed.
                # loss-spike is the sentinel's kind: interpreted via
                # _apply_numeric_faults below, excluded here
                maybe_fire("train.step", step=step,
                           exclude=(("loss-spike",) if sent is not None
                                    else ()))
                key = jax.random.fold_in(self._key, step)
                # ragged final batch: zero-padded, masked out of the loss
                # mean (the reference just trains on the short batch,
                # :108-113; the weighted mean gives the identical gradient)
                w = None
                if b.n_valid < len(b.x):
                    w = (np.arange(len(b.x)) < b.n_valid).astype(np.float32)
                bx = self._apply_numeric_faults(b.x, step)
                with (tele.span("feed") if tele is not None
                      else contextlib.nullcontext()):
                    x, y, w = self._feed(bx, b.y, w)
                if (tele is not None and first
                        and epoch == self.start_epoch):
                    # register the exact step + shapes for the static
                    # ICI-bytes gauge (trace-only; shapes captured BEFORE
                    # donation). Keyed on the run's first batch — not
                    # _step_count, which a checkpoint resume starts nonzero
                    from simple_distributed_machine_learning_tpu.analysis import (  # noqa: E501
                        abstractify,
                    )
                    tele.set_step_probe(
                        self._train_step, abstractify(self.buf),
                        abstractify(self.opt_state), abstractify(x),
                        abstractify(y), abstractify(key),
                        abstractify(w) if w is not None else None,
                        mesh=self.pipe.mesh)
                gnorm = None
                with (tele.span("step") if tele is not None
                      else contextlib.nullcontext()):
                    if sent is not None:
                        self.buf, self.opt_state, loss, gnorm = \
                            self._train_step(self.buf, self.opt_state,
                                             x, y, key, w)
                    else:
                        self.buf, self.opt_state, loss = self._train_step(
                            self.buf, self.opt_state, x, y, key, w)
                self._step_count += 1
                if sent is not None:
                    # ONE host sync fetches both scalars — the sentinel's
                    # per-step cost (detection cannot be async)
                    loss_f, gnorm_f = (float(v) for v in
                                       jax.device_get((loss, gnorm)))
                    anomaly = sent.observe(step, epoch, batch_idx,
                                           loss_f, gnorm_f)
                    if anomaly is not None:
                        # micro-rollback: restore the newest pre-anomaly
                        # snapshot (params/opt/step/EWMA), rewind the batch
                        # stream to its data cursor and replay forward —
                        # the quarantined batch is skipped on the way
                        # through. Raises SentinelExhausted (supervisor-
                        # recoverable) when anomalies repeat faster than
                        # the ring can absorb.
                        snap = sent.rollback(anomaly)
                        self._restore_snapshot(snap)
                        self._print(
                            f"| sentinel: {anomaly.kind} at step "
                            f"{anomaly.step} (epoch {epoch} batch "
                            f"{anomaly.batch_idx}) — rolled back to step "
                            f"{snap.step}, batch quarantined, replaying")
                        stream.close()
                        stream = self._epoch_stream(shuffle_seed,
                                                    snap.batch_idx)
                        if tele is not None:
                            tele.mark()  # the poisoned window is not a step
                        continue
                meter.update(b.n_valid)
                if tele is not None:
                    # the first batch of the run is forced: that window is
                    # the compile window and the StepTimer keeps it split
                    tele.on_step(
                        loss, examples=b.n_valid,
                        tokens=b.n_valid * self._tokens_per_sample,
                        force_fence=first)
                if first:
                    # first step includes trace+compile; keep it out of the
                    # throughput window (the metric is chip throughput)
                    jax.block_until_ready(loss)
                    meter.reset()
                    first = False
                if batch_idx % cfg.log_interval == 0:
                    self._print(
                        'Train Epoch: {} [{}/{} ({:.0f}%)]\tLoss: '
                        '{:.6f}'.format(
                            epoch, batch_idx * len(b.x), n_total,
                            100.0 * batch_idx / n_batches, float(loss)))
        finally:
            stream.close()
        jax.block_until_ready(self.buf)      # drain async-dispatched steps
        self._last_samples_per_sec = meter.samples_per_sec
        if cfg.print_throughput:
            self._print('| epoch {}: {:.1f} samples/sec'.format(
                epoch, meter.samples_per_sec))
        return float(loss)

    def evaluate(self) -> tuple[float, int]:
        cfg = self.config
        tele = self.telemetry
        total_loss = 0.0
        correct = 0
        # prediction units: samples for classifiers (y: [N]), tokens for
        # language models (y: [N, T]) — y.size covers both
        n = int(self.test_ds.y.size)
        for b in batches(self.test_ds, cfg.batch_size, pad_last=True):
            with (tele.span("eval") if tele is not None
                  else contextlib.nullcontext()):
                x, y, _ = self._feed(b.x, b.y, None)
                sl, c = self._eval_step(self.buf, x, y, self._key,
                                        np.int32(b.n_valid))
                total_loss += float(sl)      # host read closes the span at
                correct += int(c)            # the batch's true end
        avg = total_loss / n
        self._print(
            '\nTest set: Average loss: {:.4f}, Accuracy: {}/{} ({:.0f}%)\n'
            .format(avg, correct, n, 100.0 * correct / n))
        return avg, correct

    def _log_metrics(self, record: dict) -> None:
        """Per-epoch metrics through the telemetry registry.

        Every field is mirrored into registry instruments (monotonic
        counters for step/correct counts, gauges for the rest) so the same
        numbers ride the Prometheus exposition when telemetry is on; the
        JSONL line keeps every documented key (``accuracy`` is the headline)
        and is now schema-versioned (``"schema": 2`` — schema 1 was the bare
        unversioned record).
        """
        from simple_distributed_machine_learning_tpu.telemetry.registry import (
            append_jsonl,
        )
        reg = self._registry
        if reg is not None:
            # a Telemetry session is attached: its registry (and thus the
            # Prometheus exposition) carries the training series too
            steps = reg.counter("train_steps_total")
            steps.inc(record["step"] - steps.value)
            if record["correct"] is not None:
                reg.counter("eval_correct_total").inc(record["correct"])
            for key in ("train_loss", "eval_loss", "accuracy",
                        "samples_per_sec"):
                if record.get(key) is not None:
                    reg.gauge(key).set(record[key])
        if not (self.config.metrics_json and self.is_main):
            return
        append_jsonl(self.config.metrics_json, record, schema=2)

    def fit(self) -> None:
        """The reference's epoch driver (``simple_distributed.py:134-136``),
        plus per-epoch checkpointing when ``checkpoint_dir`` is set and a
        JSONL metrics record per epoch when ``metrics_json`` is set.

        Graceful preemption (SIGTERM via :meth:`request_stop`, or the
        injected ``preempt@train.sigterm`` fault): the in-flight step
        finishes, a SYNCHRONOUS checkpoint carrying the mid-epoch data
        cursor is written, the quarantine journal and telemetry flush, and
        ``fit`` returns cleanly with ``self.preempted`` set — resume
        re-enters the interrupted epoch at the exact next batch and the
        trajectory is bit-identical to the uninterrupted run."""
        for epoch in range(self.start_epoch, self.config.epochs + 1):
            train_loss = self.train_epoch(epoch)
            if self._stop_requested:
                self._finish_preempt(epoch)
                return
            eval_loss, correct = self.evaluate()
            n_eval = int(self.test_ds.y.size)
            record = {
                "epoch": epoch,
                "step": self._step_count,
                "train_loss": round(train_loss, 6),
                "samples_per_sec": round(self._last_samples_per_sec, 1),
                "eval_loss": round(eval_loss, 6),
                # accuracy is the documented key (--metrics-json help); the
                # raw counts stay so consumers can re-aggregate across epochs
                "accuracy": round(correct / n_eval, 6) if n_eval else None,
                "correct": correct,
                "n_eval": n_eval,
            }
            if self._sentinel is not None:
                # the self-healing block rides every epoch record (and the
                # telemetry epoch record below), so a drill can re-assert
                # rollbacks from metrics.jsonl — not the exit code alone
                record.update(self.sentinel_stats())
                record["anomaly_events"] = self._sentinel.drain_events()
            self._log_metrics(record)
            if self.telemetry is not None:
                # the full per-epoch telemetry record: step-latency
                # quantiles, throughput, memory, bubble estimate, ICI bytes
                # — with the training record's fields riding along
                self.telemetry.on_epoch(epoch, pipe=self.pipe, extra=record)
            self._save(epoch)
        if self._pending_save is not None:
            self._wait_pending()
        if self.telemetry is not None:
            self.telemetry.close()

    def _finish_preempt(self, epoch: int) -> None:
        """The graceful-preemption epilogue: synchronous checkpoint (with
        the data cursor when the stop hit mid-epoch), quarantine-journal
        flush (each quarantine already flushed on append — this is the
        gauge + report), telemetry close, clean return."""
        if self._pending_save is not None:
            self._wait_pending()         # never orphan an in-flight write
        self._save(epoch, cursor=self._preempt_cursor, sync=True)
        # the interrupted epoch's metrics record still lands: a drill that
        # preempts after an anomaly must be able to re-assert rollbacks
        # from metrics.jsonl, and the drained anomaly_events would
        # otherwise be lost with the process
        record: dict = {"epoch": epoch, "step": self._step_count,
                        "preempted": True, "correct": None}
        if self._sentinel is not None:
            record.update(self.sentinel_stats())
            record["anomaly_events"] = self._sentinel.drain_events()
        self._log_metrics(record)
        if self.telemetry is not None:
            self.telemetry.on_epoch(epoch, pipe=self.pipe, extra=record)
        if self._registry is not None:
            self._registry.gauge("train_preempt_graceful").set(1)
        self.preempted = True
        sig = (f"signal {self._stop_signal}"
               if self._stop_signal is not None else "preempt notice")
        where = (f"batch {self._preempt_cursor} of epoch {epoch}"
                 if self._preempt_cursor is not None
                 else f"end of epoch {epoch}")
        # the single source of truth for "did the stop persist anything" —
        # the CLI's closing hint reads this instead of re-deriving it
        self.preempt_persisted = bool(
            self.config.checkpoint_dir
            or getattr(self, "store", None) is not None)
        self._print(
            f"| preempt: graceful stop on {sig} at step "
            f"{self._step_count} ({where}) — "
            + ("synchronous checkpoint + quarantine-journal flush"
               if self.preempt_persisted
               else "no checkpoint_dir configured, state NOT persisted "
               "(quarantine journal flushed)"))
        if self.telemetry is not None:
            self.telemetry.close()
