"""Optimizers with owner-local sharded state.

Replaces the reference's DistributedOptimizer
(``/root/reference/simple_distributed.py:100-104,:113``), which RPCs into each
param-owning process to run a local ``optim.SGD`` step. In SPMD, "owner-local"
is free: optimizer state is created with the same sharding as the parameter
buffer (``P('stage')``), so each device updates exactly its own stage's params
and momentum inside the compiled train step — no RPC, no separate engine.

``sgd`` reproduces torch's SGD-with-momentum update rule
(``buf = momentum * buf + grad; p -= lr * buf``) for loss-curve parity with
the reference's hyperparameters (lr=0.1, momentum=0.5,
``simple_distributed.py:20-21,:103``). Any optax transform can be used
instead via :func:`from_optax`.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax


class Optimizer(NamedTuple):
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any], tuple[Any, Any]]  # (grads, state, params) -> (new_params, new_state)


def sgd(learning_rate, momentum: float = 0.0) -> Optimizer:
    """torch-semantics SGD(momentum). State = momentum buffer (like-sharded).

    ``learning_rate`` may be a float (constant) or a :mod:`schedules`
    Schedule (``step -> lr``); with a schedule the state grows a step
    counter and the k-th update (0-indexed) runs at ``schedule(k)`` —
    torch's ``opt.step(); sched.step()`` convention.
    """
    import jax.numpy as jnp

    scheduled = callable(learning_rate)

    def init(params):
        buf = (() if momentum == 0.0
               else jax.tree.map(jnp.zeros_like, params))
        if scheduled:
            return (jnp.zeros((), jnp.int32), buf)
        return buf

    def update(grads, state, params):
        if scheduled:
            count, buf = state
            lr = learning_rate(count)
            count = count + 1
        else:
            buf, lr = state, learning_rate
        if momentum == 0.0:
            new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
            new_buf = ()
        else:
            new_buf = jax.tree.map(lambda b, g: momentum * b + g, buf, grads)
            new_params = jax.tree.map(lambda p, b: p - lr * b,
                                      params, new_buf)
        return new_params, ((count, new_buf) if scheduled else new_buf)

    return Optimizer(init, update)


def adamw(learning_rate, b1: float = 0.9, b2: float = 0.999,
          eps: float = 1e-8, weight_decay: float = 0.01) -> Optimizer:
    """torch-semantics AdamW (decoupled weight decay, bias-corrected
    moments — torch.optim.AdamW's update rule). State = (step, m, v),
    m/v like-sharded with the params.

    ``learning_rate``: float or Schedule; a schedule reuses the existing
    step counter (the k-th update runs at ``schedule(k)``) and scales both
    the decoupled decay and the moment step, like torch's LambdaLR over
    AdamW.
    """
    import jax.numpy as jnp

    scheduled = callable(learning_rate)

    def init(params):
        zeros = lambda: jax.tree.map(jnp.zeros_like, params)
        return (jnp.zeros((), jnp.int32), zeros(), zeros())

    def update(grads, state, params):
        step, m, v = state
        lr = learning_rate(step) if scheduled else learning_rate
        step = step + 1
        t = step.astype(jnp.float32)
        m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g, m, grads)
        v = jax.tree.map(lambda v_, g: b2 * v_ + (1 - b2) * g * g, v, grads)
        bc1 = 1 - b1 ** t
        bc2 = 1 - b2 ** t

        def upd(p, m_, v_):
            # decoupled decay first (torch applies p *= 1 - lr*wd before the
            # Adam step), then the bias-corrected moment update
            p = p * (1 - lr * weight_decay)
            return p - lr * (m_ / bc1) / (
                jnp.sqrt(v_ / bc2) + eps)

        return jax.tree.map(upd, params, m, v), (step, m, v)

    return Optimizer(init, update)


def clip_by_global_norm(opt: Optimizer, max_norm: float,
                        norm_weights: Any = None) -> Optimizer:
    """Wrap ``opt`` with torch ``clip_grad_norm_`` semantics: compute the
    global L2 norm over all gradient leaves and scale every gradient by
    ``min(1, max_norm / (norm + 1e-6))`` before the inner update.

    ``norm_weights``: optional per-leaf multiplier (broadcastable onto each
    leaf) for the SQUARED-norm accumulation. The packed ``[S, M, E, P]``
    pipeline buffer stores stages without tensor/expert shards redundantly
    on every model/expert slot, and after ``grad_sync`` each slot carries
    the FULL gradient — an unweighted norm would count those parameters
    ``n_model * n_expert`` times. ``Pipeline.replication_weights()``
    supplies the exact ``1/replication`` correction; on a tp=ep=1 mesh the
    unweighted norm is already exact.
    """
    import jax.numpy as jnp

    def update(grads, state, params):
        leaves = jax.tree.leaves(grads)
        wts = ([None] * len(leaves) if norm_weights is None
               else jax.tree.leaves(norm_weights))
        if len(wts) != len(leaves):
            # Structure mismatch: the weights were built against the packed
            # [S, M, E, P] buffer but the grads arrived as per-param pytrees
            # (make_scanned_train_step's single-device fast path unpacks the
            # buffer before the scan). That path only exists on a trivial
            # mesh, where the replication correction is exactly 1 — verify
            # and drop it rather than silently zip-truncating the norm to
            # the first gradient leaf.
            import numpy as np
            try:
                identity = all(np.all(np.asarray(w) == 1.0) for w in wts)
            except Exception:
                identity = False
            if not identity:
                raise ValueError(
                    f"clip_by_global_norm: norm_weights has {len(wts)} "
                    f"leaves but grads has {len(leaves)}; non-identity "
                    "replication weights cannot be applied to unpacked "
                    "per-param gradients")
            wts = [None] * len(leaves)
        sq = jnp.float32(0.0)
        for g, w in zip(leaves, wts):
            g2 = g.astype(jnp.float32) ** 2
            sq = sq + jnp.sum(g2 if w is None else g2 * w)
        norm = jnp.sqrt(sq)
        scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
        grads = jax.tree.map(lambda g: (g * scale).astype(g.dtype), grads)
        return opt.update(grads, state, params)

    return Optimizer(opt.init, update)


def shard_opt_state_zero1(state: Any, mesh, param_spec) -> Any:
    """ZeRO-1: shard the optimizer state's packed param axis over the DATA
    mesh axis (on top of the stage/model/expert sharding the buffer already
    has).

    Optimizer state is pure per-element memory — unlike params it is never
    read by the forward pass — so replicating it across data-parallel
    replicas (what like-sharded init does) wastes n_data x its bytes. With
    the state's last axis additionally sharded over ``data``, GSPMD
    partitions the elementwise update across data shards and inserts the
    all-gather for the params the next step needs — the ZeRO-1 recipe
    expressed purely as a sharding annotation, no hand-written collectives
    (the TPU-idiomatic equivalent of what DeepSpeed does with explicit
    reduce-scatter/all-gather).

    Buffer-shaped leaves get ``P(*param_spec[:-1], 'data')``; scalar leaves
    (step counters) stay replicated.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from simple_distributed_machine_learning_tpu.parallel.mesh import (
        DATA_AXIS,
    )

    n_data = mesh.shape.get(DATA_AXIS, 1)
    spec = P(*tuple(param_spec)[:-1], DATA_AXIS)

    def place(leaf):
        if getattr(leaf, "ndim", 0) == 0:
            return leaf
        if leaf.shape[-1] % n_data:
            import sys
            sys.stderr.write(
                f"zero1: packed param axis {leaf.shape[-1]} not divisible "
                f"by data axis {n_data} — this state leaf stays REPLICATED "
                f"(no memory saving)\n")
            return leaf
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree.map(place, state)


def from_optax(tx) -> Optimizer:
    """Adapt an optax GradientTransformation to this interface."""
    import optax

    def update(grads, state, params):
        updates, new_state = tx.update(grads, state, params)
        return optax.apply_updates(params, updates), new_state

    return Optimizer(tx.init, update)
