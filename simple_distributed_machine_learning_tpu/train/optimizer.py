"""Optimizers with owner-local sharded state.

Replaces the reference's DistributedOptimizer
(``/root/reference/simple_distributed.py:100-104,:113``), which RPCs into each
param-owning process to run a local ``optim.SGD`` step. In SPMD, "owner-local"
is free: optimizer state is created with the same sharding as the parameter
buffer (``P('stage')``), so each device updates exactly its own stage's params
and momentum inside the compiled train step — no RPC, no separate engine.

``sgd`` reproduces torch's SGD-with-momentum update rule
(``buf = momentum * buf + grad; p -= lr * buf``) for loss-curve parity with
the reference's hyperparameters (lr=0.1, momentum=0.5,
``simple_distributed.py:20-21,:103``). Any optax transform can be used
instead via :func:`from_optax`.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax


class Optimizer(NamedTuple):
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any], tuple[Any, Any]]  # (grads, state, params) -> (new_params, new_state)


def sgd(learning_rate: float, momentum: float = 0.0) -> Optimizer:
    """torch-semantics SGD(momentum). State = momentum buffer (like-sharded)."""

    def init(params):
        if momentum == 0.0:
            return ()
        return jax.tree.map(jax.numpy.zeros_like, params)

    def update(grads, state, params):
        if momentum == 0.0:
            new_params = jax.tree.map(lambda p, g: p - learning_rate * g,
                                      params, grads)
            return new_params, ()
        new_buf = jax.tree.map(lambda b, g: momentum * b + g, state, grads)
        new_params = jax.tree.map(lambda p, b: p - learning_rate * b,
                                  params, new_buf)
        return new_params, new_buf

    return Optimizer(init, update)


def adamw(learning_rate: float, b1: float = 0.9, b2: float = 0.999,
          eps: float = 1e-8, weight_decay: float = 0.01) -> Optimizer:
    """torch-semantics AdamW (decoupled weight decay, bias-corrected
    moments — torch.optim.AdamW's update rule). State = (step, m, v),
    m/v like-sharded with the params."""
    import jax.numpy as jnp

    def init(params):
        zeros = lambda: jax.tree.map(jnp.zeros_like, params)
        return (jnp.zeros((), jnp.int32), zeros(), zeros())

    def update(grads, state, params):
        step, m, v = state
        step = step + 1
        t = step.astype(jnp.float32)
        m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g, m, grads)
        v = jax.tree.map(lambda v_, g: b2 * v_ + (1 - b2) * g * g, v, grads)
        bc1 = 1 - b1 ** t
        bc2 = 1 - b2 ** t

        def upd(p, m_, v_):
            # decoupled decay first (torch applies p *= 1 - lr*wd before the
            # Adam step), then the bias-corrected moment update
            p = p * (1 - learning_rate * weight_decay)
            return p - learning_rate * (m_ / bc1) / (
                jnp.sqrt(v_ / bc2) + eps)

        return jax.tree.map(upd, params, m, v), (step, m, v)

    return Optimizer(init, update)


def shard_opt_state_zero1(state: Any, mesh, param_spec) -> Any:
    """ZeRO-1: shard the optimizer state's packed param axis over the DATA
    mesh axis (on top of the stage/model/expert sharding the buffer already
    has).

    Optimizer state is pure per-element memory — unlike params it is never
    read by the forward pass — so replicating it across data-parallel
    replicas (what like-sharded init does) wastes n_data x its bytes. With
    the state's last axis additionally sharded over ``data``, GSPMD
    partitions the elementwise update across data shards and inserts the
    all-gather for the params the next step needs — the ZeRO-1 recipe
    expressed purely as a sharding annotation, no hand-written collectives
    (the TPU-idiomatic equivalent of what DeepSpeed does with explicit
    reduce-scatter/all-gather).

    Buffer-shaped leaves get ``P(*param_spec[:-1], 'data')``; scalar leaves
    (step counters) stay replicated.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from simple_distributed_machine_learning_tpu.parallel.mesh import (
        DATA_AXIS,
    )

    n_data = mesh.shape.get(DATA_AXIS, 1)
    spec = P(*tuple(param_spec)[:-1], DATA_AXIS)

    def place(leaf):
        if getattr(leaf, "ndim", 0) == 0:
            return leaf
        if leaf.shape[-1] % n_data:
            import sys
            sys.stderr.write(
                f"zero1: packed param axis {leaf.shape[-1]} not divisible "
                f"by data axis {n_data} — this state leaf stays REPLICATED "
                f"(no memory saving)\n")
            return leaf
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree.map(place, state)


def from_optax(tx) -> Optimizer:
    """Adapt an optax GradientTransformation to this interface."""
    import optax

    def update(grads, state, params):
        updates, new_state = tx.update(grads, state, params)
        return optax.apply_updates(params, updates), new_state

    return Optimizer(tx.init, update)
