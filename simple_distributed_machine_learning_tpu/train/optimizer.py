"""Optimizers with owner-local sharded state.

Replaces the reference's DistributedOptimizer
(``/root/reference/simple_distributed.py:100-104,:113``), which RPCs into each
param-owning process to run a local ``optim.SGD`` step. In SPMD, "owner-local"
is free: optimizer state is created with the same sharding as the parameter
buffer (``P('stage')``), so each device updates exactly its own stage's params
and momentum inside the compiled train step — no RPC, no separate engine.

``sgd`` reproduces torch's SGD-with-momentum update rule
(``buf = momentum * buf + grad; p -= lr * buf``) for loss-curve parity with
the reference's hyperparameters (lr=0.1, momentum=0.5,
``simple_distributed.py:20-21,:103``). Any optax transform can be used
instead via :func:`from_optax`.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax


class Optimizer(NamedTuple):
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any], tuple[Any, Any]]  # (grads, state, params) -> (new_params, new_state)


def sgd(learning_rate: float, momentum: float = 0.0) -> Optimizer:
    """torch-semantics SGD(momentum). State = momentum buffer (like-sharded)."""

    def init(params):
        if momentum == 0.0:
            return ()
        return jax.tree.map(jax.numpy.zeros_like, params)

    def update(grads, state, params):
        if momentum == 0.0:
            new_params = jax.tree.map(lambda p, g: p - learning_rate * g,
                                      params, grads)
            return new_params, ()
        new_buf = jax.tree.map(lambda b, g: momentum * b + g, state, grads)
        new_params = jax.tree.map(lambda p, b: p - learning_rate * b,
                                  params, new_buf)
        return new_params, new_buf

    return Optimizer(init, update)


def from_optax(tx) -> Optimizer:
    """Adapt an optax GradientTransformation to this interface."""
    import optax

    def update(grads, state, params):
        updates, new_state = tx.update(grads, state, params)
        return optax.apply_updates(params, updates), new_state

    return Optimizer(tx.init, update)
