"""Checkpoint / resume for sharded training state.

The reference has no persistence at all — a crash loses every epoch (SURVEY
§5.4: no ``torch.save`` anywhere). Here the full training state — the
stage-sharded parameter buffer, optimizer state, step counter and RNG seed —
round-trips through a single ``.npz`` plus a JSON sidecar. Sharded arrays are
gathered on save and re-placed with the pipeline's sharding on restore;
same-topology resume is bit-exact.

Cross-topology resume: a checkpoint written at one pipeline stage count can
be re-packed for another via :func:`repack_checkpoint` (or
``restore_checkpoint(..., src_pipe=...)``) for models whose stages are a
CONTIGUOUS split of a unit sequence — per-stage trees that are plain lists
of layers (the MLP family) or ``{"blocks": [...]}`` dicts with ``embed`` on
the first stage and ``head`` on the last (the GPT family). Structurally
renamed splits (LeNet's fixed conv|fc vs fused trees) are not re-packable
and are rejected with an error. Buffer-shaped optimizer state (momentum,
AdamW moments) re-packs alongside the params; the data/model/expert axis
sizes must match between source and target.
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import numpy as np


def _to_host(x) -> np.ndarray:
    """Bring an array to host memory, multi-host-safely.

    A single-controller (or single-host) array is fully addressable and
    ``device_get`` suffices. In a multi-process run the stage-sharded buffer's
    shards live on OTHER processes' devices; ``process_allgather`` (a
    collective — every process must call it) reassembles the global value on
    every host.
    """
    if not isinstance(x, jax.Array) or x.is_fully_addressable:
        return np.asarray(jax.device_get(x))
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def _gather_state(buf: jax.Array, opt_state: Any, step: int,
                  extra: dict | None) -> tuple[dict, dict]:
    """Device→host gather of the full training state (COLLECTIVE in
    multi-process runs — every process must reach it, on its main thread)."""
    arrays = {"params": _to_host(buf)}
    opt_leaves, _ = jax.tree.flatten(opt_state)
    for i, leaf in enumerate(opt_leaves):
        arrays[f"opt_{i}"] = _to_host(leaf)
    meta = {"step": int(step), "n_opt_leaves": len(opt_leaves),
            "extra": extra or {}}
    return arrays, meta


class CheckpointCorruptError(ValueError):
    """A checkpoint file failed to load (truncated/corrupt): raised with
    the offending path instead of a raw zipfile/KeyError traceback, so the
    operator (or ``resilience.CheckpointStore.latest_valid``) knows which
    file to discard."""


def _write_npz(path: str, arrays: dict, meta: dict) -> None:
    import tempfile

    from simple_distributed_machine_learning_tpu.resilience.faults import (
        maybe_fire,
    )

    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    arrays = dict(arrays)
    arrays["_meta_json"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)
    # unique temp name (not path + '.tmp'): two in-flight async saves to the
    # same path must not interleave writes into one temp file — each writes
    # its own and the atomic replace keeps whichever finished last whole
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(os.path.abspath(path)),
                               prefix=os.path.basename(path) + ".tmp.",
                               suffix=".npz")
    os.close(fd)
    try:
        np.savez(tmp, **arrays)
        # fault-injection site (resilience/faults.py): ckpt-write-crash
        # truncates the temp and raises HERE — after the bytes, before the
        # rename — proving the committed checkpoint survives a mid-write
        # crash (the whole point of write-then-os.replace)
        maybe_fire("ckpt.write", path=path, tmp=tmp)
        os.replace(tmp, path)  # atomic: old checkpoint intact until whole
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    with open(path + ".meta.json", "w") as f:
        json.dump(meta, f)


def save_checkpoint(path: str, buf: jax.Array, opt_state: Any, step: int,
                    extra: dict | None = None) -> None:
    """Write training state to ``path`` (one .npz, atomically replaced).

    All metadata (step, leaf count, extras) travels INSIDE the .npz so a crash
    can never leave arrays and metadata out of sync; a human-readable
    ``path + '.meta.json'`` sidecar is written as a convenience copy and is
    not read on restore.

    Multi-process: EVERY process must call this (the gather of
    non-addressable shards is a collective); only process 0 touches the
    filesystem.
    """
    arrays, meta = _gather_state(buf, opt_state, step, extra)
    if jax.process_index() != 0:
        return
    _write_npz(path, arrays, meta)


class AsyncSave:
    """Handle for an in-flight async checkpoint write."""

    def __init__(self, thread=None):
        self._thread = thread
        self._error: BaseException | None = None

    def wait(self, timeout: float | None = None) -> None:
        """Block until the write completes; re-raise any write error."""
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError("checkpoint write still in flight")
        if self._error is not None:
            raise self._error

    @property
    def done(self) -> bool:
        return self._thread is None or not self._thread.is_alive()


def save_checkpoint_async(path: str, buf: jax.Array, opt_state: Any,
                          step: int, extra: dict | None = None) -> AsyncSave:
    """Like :func:`save_checkpoint` but the FILE WRITE happens on a
    background thread, so training resumes as soon as the device→host
    gather is done (the gather itself stays on the caller's thread — it is
    a collective in multi-process runs and must not race the train step's
    collectives). Call ``.wait()`` on the returned handle before process
    exit or before depending on the file."""
    import threading

    arrays, meta = _gather_state(buf, opt_state, step, extra)
    handle = AsyncSave()
    if jax.process_index() != 0:
        return handle

    def write():
        try:
            _write_npz(path, arrays, meta)
        except BaseException as e:  # noqa: BLE001 - surfaced via wait()
            handle._error = e

    t = threading.Thread(target=write, name="ckpt-write", daemon=True)
    handle._thread = t
    t.start()
    return handle


def _load_npz(path: str) -> tuple[dict, dict]:
    """Load ``(arrays, meta)`` from a checkpoint ``.npz``, turning every
    truncation/corruption failure mode into :class:`CheckpointCorruptError`
    naming the path — a half-written file must produce an actionable error,
    not a raw ``zipfile.BadZipFile``/``KeyError`` traceback."""
    import zipfile

    try:
        with np.load(path) as z:
            meta = json.loads(bytes(z["_meta_json"]).decode())
            arrays = {k: z[k] for k in z.files if k != "_meta_json"}
    except FileNotFoundError:
        raise
    except (zipfile.BadZipFile, KeyError, EOFError, OSError, ValueError,
            json.JSONDecodeError) as e:
        raise CheckpointCorruptError(
            f"checkpoint {path} is truncated or corrupt "
            f"({type(e).__name__}: {e}) — it cannot be restored; delete it "
            f"and restore an earlier checkpoint "
            f"(resilience.CheckpointStore.latest_valid skips invalid "
            f"generations automatically)") from e
    return arrays, meta


def _np_unpack(row: np.ndarray, meta) -> Any:
    """Host-side unpack_stage_params (no device round-trip on restore)."""
    leaves = []
    offset = 0
    for shape, size in zip(meta.shapes, meta.sizes):
        leaves.append(np.asarray(row[offset:offset + size]).reshape(shape))
        offset += size
    return jax.tree.unflatten(meta.treedef, leaves)


def _np_pack_row(tree: Any, width: int) -> np.ndarray:
    leaves = jax.tree.flatten(tree)[0]
    flat = (np.concatenate([np.ravel(np.asarray(l)).astype(np.float32)
                            for l in leaves])
            if leaves else np.zeros((0,), np.float32))
    return np.pad(flat, (0, width - flat.shape[0]))


def repack_stage_trees(trees: list, n_stages_new: int) -> list:
    """Re-split per-stage param trees to a new contiguous stage count.

    Two supported stage-tree conventions (the ones every splittable model
    builder in this framework produces):

    - every stage tree is a LIST of per-layer trees (MLP family): the lists
      concatenate into the global layer sequence and re-split contiguously;
    - every stage tree is a dict with a ``"blocks"`` list (GPT family):
      blocks concatenate and re-split; the first stage's non-block keys
      (``embed``) move to the new first stage, the last stage's (``head``)
      to the new last. From a 1-stage (fused) source both live on the same
      tree; the key named ``"head"`` is the one that moves to the new last
      stage — the convention the GPT builder defines.

    Anything else — structurally renamed splits like LeNet's conv|fc vs
    fused trees — raises.
    """
    from simple_distributed_machine_learning_tpu.parallel.staging import (
        contiguous_split,
    )
    if all(isinstance(t, list) for t in trees):
        units = [u for t in trees for u in t]
        return contiguous_split(units, n_stages_new)
    if all(isinstance(t, dict) and "blocks" in t for t in trees):
        for i, t in enumerate(trees[1:-1], start=1):
            if set(t) != {"blocks"}:
                raise ValueError(
                    f"stage {i} carries non-block keys {sorted(set(t))} — "
                    f"only the first (embed) and last (head) stages may")
        if len(trees) > 1:
            extras_first = {k: v for k, v in trees[0].items()
                            if k != "blocks"}
            extras_last = {k: v for k, v in trees[-1].items()
                           if k != "blocks"}
        else:
            # fused source: embed and head share the one tree — "head" is
            # the last-stage extra by convention, the rest go first
            extras_first = {k: v for k, v in trees[0].items()
                            if k not in ("blocks", "head")}
            extras_last = {k: v for k, v in trees[0].items() if k == "head"}
        blocks = [b for t in trees for b in t["blocks"]]
        split = contiguous_split(blocks, n_stages_new)
        out = []
        for s, bs in enumerate(split):
            t: dict = {"blocks": bs}
            if s == 0:
                t.update(extras_first)
            if s == n_stages_new - 1:
                t.update(extras_last)
            out.append(t)
        return out
    raise ValueError(
        "stages are not a contiguous split of a unit sequence (expected all "
        "lists, or all dicts with a 'blocks' list); this topology cannot be "
        "re-packed — rebuild and retrain, or restore at the original stage "
        "count")


def repack_packed_buffer(arr: np.ndarray, src_pipe, dst_pipe) -> np.ndarray:
    """Re-split a packed ``[S_src, M, E, P_src]`` buffer (params, momentum,
    AdamW moments — anything stage-packed) into ``dst_pipe``'s
    ``[S_dst, M, E, P_dst]`` layout. Same model, different contiguous stage
    split; the model/expert shard axes must match."""
    if (src_pipe.n_model, src_pipe.n_expert) != (dst_pipe.n_model,
                                                dst_pipe.n_expert):
        raise ValueError(
            f"model/expert axes must match to repack: source "
            f"{src_pipe.n_model}x{src_pipe.n_expert}, target "
            f"{dst_pipe.n_model}x{dst_pipe.n_expert}")
    arr = np.asarray(arr)
    want_src = tuple(src_pipe._buf0.shape)
    if tuple(arr.shape) != want_src:
        raise ValueError(
            f"buffer {tuple(arr.shape)} does not match the source pipeline's "
            f"packed layout {want_src}")
    out = np.zeros_like(dst_pipe._buf0)
    P_dst = out.shape[-1]
    for m in range(src_pipe.n_model):
        for e in range(src_pipe.n_expert):
            trees = [_np_unpack(arr[s, m, e], src_pipe.metas[s])
                     for s in range(src_pipe.n_stages)]
            new_trees = repack_stage_trees(trees, dst_pipe.n_stages)
            for s, t in enumerate(new_trees):
                meta = dst_pipe.metas[s]
                leaves = jax.tree.flatten(t)[0]
                shapes = tuple(tuple(np.shape(l)) for l in leaves)
                if shapes != meta.shapes:
                    raise ValueError(
                        f"re-split stage {s} leaf shapes {shapes} do not "
                        f"match the target pipeline's {meta.shapes} — "
                        f"source and target must build the same model")
                out[s, m, e] = _np_pack_row(t, P_dst)
    return out


def repack_checkpoint(path_in: str, path_out: str, src_pipe, dst_pipe
                      ) -> None:
    """Rewrite a checkpoint written at ``src_pipe``'s topology into
    ``dst_pipe``'s packed layout (params + every buffer-shaped optimizer
    leaf; scalar leaves pass through). Single-process, host-side only."""
    arrays, meta = _load_npz(path_in)
    src_shape = tuple(src_pipe._buf0.shape)
    arrays["params"] = repack_packed_buffer(arrays["params"], src_pipe,
                                            dst_pipe)
    for k in list(arrays):
        if k.startswith("opt_") and tuple(arrays[k].shape) == src_shape:
            arrays[k] = repack_packed_buffer(arrays[k], src_pipe, dst_pipe)
    _write_npz(path_out, arrays, meta)


def restore_checkpoint(path: str, pipe=None, opt_treedef_like: Any = None,
                       src_pipe=None) -> dict:
    """Load state. With ``pipe`` given, the param buffer is device_put with
    the pipeline's stage sharding; ``opt_treedef_like`` (e.g. ``opt.init(buf)``
    output) restores the optimizer pytree structure. ``src_pipe``: the
    pipeline the checkpoint was WRITTEN with — when its stage count differs
    from ``pipe``'s, params and buffer-shaped optimizer leaves are re-packed
    (see :func:`repack_stage_trees` for the supported model conventions)."""
    arrays, meta = _load_npz(path)
    try:
        params = arrays["params"]
        opt_leaves = [arrays[f"opt_{i}"] for i in range(meta["n_opt_leaves"])]
    except KeyError as e:
        raise CheckpointCorruptError(
            f"checkpoint {path} is missing array {e.args[0]!r} — truncated "
            f"or not a training checkpoint") from e

    buf = params
    if pipe is not None:
        from jax.sharding import NamedSharding

        want = tuple(pipe._buf0.shape)
        if tuple(params.shape) != want and src_pipe is not None:
            src_shape = tuple(src_pipe._buf0.shape)
            params = repack_packed_buffer(params, src_pipe, pipe)
            opt_leaves = [
                (repack_packed_buffer(l, src_pipe, pipe)
                 if tuple(l.shape) == src_shape else l)
                for l in opt_leaves]
        if tuple(params.shape) != want:
            # pre-device_put check: an old-layout checkpoint (e.g. written
            # before a topology/model change) would otherwise die inside
            # device_put with an opaque sharding/rank error
            raise ValueError(
                f"checkpoint {path} does not match the model: packed param "
                f"buffer is {tuple(params.shape)}, model expects {want} "
                f"(different model/topology config? pass src_pipe= to "
                f"re-pack a contiguous-split model across stage counts)")
        buf = jax.device_put(
            params, NamedSharding(pipe.mesh, pipe.param_spec()))

    opt_state: Any = opt_leaves
    if opt_treedef_like is not None:
        from jax.sharding import NamedSharding as _NS

        def _place(ref, arr):
            # re-place only leaves that carry a MESH sharding (momentum/
            # moment buffers shaped like the packed param buffer). Scalar
            # leaves — AdamW's step, a schedule's counter — come off
            # opt.init as uncommitted single-device arrays; device_put-ing
            # them to that device would COMMIT them and make the first
            # jitted step reject the mixed placement against the mesh-
            # sharded buffer. Left as host values, jit replicates them.
            sh = getattr(ref, "sharding", None)
            return jax.device_put(arr, sh) if isinstance(sh, _NS) else arr

        treedef = jax.tree.structure(opt_treedef_like)
        opt_state = jax.tree.unflatten(treedef, opt_leaves)
        if pipe is not None:
            opt_state = jax.tree.map(_place, opt_treedef_like, opt_state)

    return {"params": buf, "opt_state": opt_state, "step": meta["step"],
            "extra": meta["extra"]}
