"""Checkpoint / resume for sharded training state.

The reference has no persistence at all — a crash loses every epoch (SURVEY
§5.4: no ``torch.save`` anywhere). Here the full training state — the
stage-sharded parameter buffer, optimizer state, step counter and RNG seed —
round-trips through a single ``.npz`` plus a JSON sidecar. Sharded arrays are
gathered on save and re-placed with the pipeline's sharding on restore, so a
checkpoint written on one mesh layout can resume on another (e.g. 2-stage →
re-packed 4-stage requires matching stage structure; same-topology resume is
bit-exact).
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import numpy as np


def _to_host(x) -> np.ndarray:
    """Bring an array to host memory, multi-host-safely.

    A single-controller (or single-host) array is fully addressable and
    ``device_get`` suffices. In a multi-process run the stage-sharded buffer's
    shards live on OTHER processes' devices; ``process_allgather`` (a
    collective — every process must call it) reassembles the global value on
    every host.
    """
    if not isinstance(x, jax.Array) or x.is_fully_addressable:
        return np.asarray(jax.device_get(x))
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def _gather_state(buf: jax.Array, opt_state: Any, step: int,
                  extra: dict | None) -> tuple[dict, dict]:
    """Device→host gather of the full training state (COLLECTIVE in
    multi-process runs — every process must reach it, on its main thread)."""
    arrays = {"params": _to_host(buf)}
    opt_leaves, _ = jax.tree.flatten(opt_state)
    for i, leaf in enumerate(opt_leaves):
        arrays[f"opt_{i}"] = _to_host(leaf)
    meta = {"step": int(step), "n_opt_leaves": len(opt_leaves),
            "extra": extra or {}}
    return arrays, meta


def _write_npz(path: str, arrays: dict, meta: dict) -> None:
    import tempfile

    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    arrays = dict(arrays)
    arrays["_meta_json"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8)
    # unique temp name (not path + '.tmp'): two in-flight async saves to the
    # same path must not interleave writes into one temp file — each writes
    # its own and the atomic replace keeps whichever finished last whole
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(os.path.abspath(path)),
                               prefix=os.path.basename(path) + ".tmp.",
                               suffix=".npz")
    os.close(fd)
    try:
        np.savez(tmp, **arrays)
        os.replace(tmp, path)  # atomic: old checkpoint intact until whole
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    with open(path + ".meta.json", "w") as f:
        json.dump(meta, f)


def save_checkpoint(path: str, buf: jax.Array, opt_state: Any, step: int,
                    extra: dict | None = None) -> None:
    """Write training state to ``path`` (one .npz, atomically replaced).

    All metadata (step, leaf count, extras) travels INSIDE the .npz so a crash
    can never leave arrays and metadata out of sync; a human-readable
    ``path + '.meta.json'`` sidecar is written as a convenience copy and is
    not read on restore.

    Multi-process: EVERY process must call this (the gather of
    non-addressable shards is a collective); only process 0 touches the
    filesystem.
    """
    arrays, meta = _gather_state(buf, opt_state, step, extra)
    if jax.process_index() != 0:
        return
    _write_npz(path, arrays, meta)


class AsyncSave:
    """Handle for an in-flight async checkpoint write."""

    def __init__(self, thread=None):
        self._thread = thread
        self._error: BaseException | None = None

    def wait(self, timeout: float | None = None) -> None:
        """Block until the write completes; re-raise any write error."""
        if self._thread is not None:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError("checkpoint write still in flight")
        if self._error is not None:
            raise self._error

    @property
    def done(self) -> bool:
        return self._thread is None or not self._thread.is_alive()


def save_checkpoint_async(path: str, buf: jax.Array, opt_state: Any,
                          step: int, extra: dict | None = None) -> AsyncSave:
    """Like :func:`save_checkpoint` but the FILE WRITE happens on a
    background thread, so training resumes as soon as the device→host
    gather is done (the gather itself stays on the caller's thread — it is
    a collective in multi-process runs and must not race the train step's
    collectives). Call ``.wait()`` on the returned handle before process
    exit or before depending on the file."""
    import threading

    arrays, meta = _gather_state(buf, opt_state, step, extra)
    handle = AsyncSave()
    if jax.process_index() != 0:
        return handle

    def write():
        try:
            _write_npz(path, arrays, meta)
        except BaseException as e:  # noqa: BLE001 - surfaced via wait()
            handle._error = e

    t = threading.Thread(target=write, name="ckpt-write", daemon=True)
    handle._thread = t
    t.start()
    return handle


def restore_checkpoint(path: str, pipe=None, opt_treedef_like: Any = None
                       ) -> dict:
    """Load state. With ``pipe`` given, the param buffer is device_put with
    the pipeline's stage sharding; ``opt_treedef_like`` (e.g. ``opt.init(buf)``
    output) restores the optimizer pytree structure."""
    with np.load(path) as z:
        meta = json.loads(bytes(z["_meta_json"]).decode())
        params = z["params"]
        opt_leaves = [z[f"opt_{i}"] for i in range(meta["n_opt_leaves"])]

    buf = params
    if pipe is not None:
        from jax.sharding import NamedSharding

        want = tuple(pipe._buf0.shape)
        if tuple(params.shape) != want:
            # pre-device_put check: an old-layout checkpoint (e.g. written
            # before a topology/model change) would otherwise die inside
            # device_put with an opaque sharding/rank error
            raise ValueError(
                f"checkpoint {path} does not match the model: packed param "
                f"buffer is {tuple(params.shape)}, model expects {want} "
                f"(different model/topology config?)")
        buf = jax.device_put(
            params, NamedSharding(pipe.mesh, pipe.param_spec()))

    opt_state: Any = opt_leaves
    if opt_treedef_like is not None:
        from jax.sharding import NamedSharding as _NS

        def _place(ref, arr):
            # re-place only leaves that carry a MESH sharding (momentum/
            # moment buffers shaped like the packed param buffer). Scalar
            # leaves — AdamW's step, a schedule's counter — come off
            # opt.init as uncommitted single-device arrays; device_put-ing
            # them to that device would COMMIT them and make the first
            # jitted step reject the mixed placement against the mesh-
            # sharded buffer. Left as host values, jit replicates them.
            sh = getattr(ref, "sharding", None)
            return jax.device_put(arr, sh) if isinstance(sh, _NS) else arr

        treedef = jax.tree.structure(opt_treedef_like)
        opt_state = jax.tree.unflatten(treedef, opt_leaves)
        if pipe is not None:
            opt_state = jax.tree.map(_place, opt_treedef_like, opt_state)

    return {"params": buf, "opt_state": opt_state, "step": meta["step"],
            "extra": meta["extra"]}
