"""Training: optimizers, the compiled train step, driver loops, checkpointing."""

from simple_distributed_machine_learning_tpu.train.optimizer import (  # noqa: F401
    adamw,
    from_optax,
    sgd,
    shard_opt_state_zero1,
)
from simple_distributed_machine_learning_tpu.train.step import (  # noqa: F401
    make_eval_step,
    make_scanned_train_step,
    make_train_step,
)
from simple_distributed_machine_learning_tpu.train.checkpoint import (  # noqa: F401
    repack_checkpoint,
    restore_checkpoint,
    save_checkpoint,
    save_checkpoint_async,
)
from simple_distributed_machine_learning_tpu.train.trainer import (  # noqa: F401
    TrainConfig,
    Trainer,
)
