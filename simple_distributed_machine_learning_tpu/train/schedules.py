"""Learning-rate schedules: step-indexed lr for the compiled train step.

The reference trains at a single constant lr (0.1, hardcoded at
``/root/reference/simple_distributed.py:20,:103``); a framework needs decay
and warmup. A schedule here is a pure function ``step -> lr`` evaluated
INSIDE the jit'd optimizer update (``train/optimizer.py``): the step counter
rides the optimizer state, so a scanned multi-step window (``bench.py``,
``train/step.py::make_scanned_train_step``) decays correctly with no host
involvement.

Conventions match ``torch.optim.lr_scheduler`` stepped once per optimizer
step: the k-th update (0-indexed) uses ``schedule(k)``, i.e. the first update
runs at ``schedule(0)`` — exactly what torch's pattern
``opt.step(); sched.step()`` produces (pinned against torch by
``tests/test_schedules.py``).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

# step (int32 scalar, 0-indexed) -> lr (float32 scalar)
Schedule = Callable[[jax.Array], jax.Array]


def constant(lr: float) -> Schedule:
    def f(t):
        return jnp.float32(lr)
    return f


def cosine(base_lr: float, total_steps: int,
           final_frac: float = 0.0) -> Schedule:
    """Cosine decay from ``base_lr`` to ``final_frac * base_lr`` over
    ``total_steps`` (clamped there for any later steps)."""
    total = max(int(total_steps), 1)

    def f(t):
        frac = jnp.clip(t.astype(jnp.float32) / total, 0.0, 1.0)
        cos = 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
        return jnp.float32(base_lr) * (final_frac + (1.0 - final_frac) * cos)
    return f


def warmup_cosine(base_lr: float, warmup_steps: int, total_steps: int,
                  final_frac: float = 0.0) -> Schedule:
    """Linear warmup 0 -> base over ``warmup_steps`` (the k-th update at
    ``base * (k+1)/warmup``), then cosine decay over the remaining steps."""
    warm = max(int(warmup_steps), 0)
    decay = cosine(base_lr, max(int(total_steps) - warm, 1), final_frac)

    def f(t):
        tf = t.astype(jnp.float32)
        wu = jnp.float32(base_lr) * (tf + 1.0) / max(warm, 1)
        return jnp.where(t < warm, wu, decay(t - warm))
    return f


def step_decay(base_lr: float, step_size: int,
               gamma: float = 0.1) -> Schedule:
    """torch ``StepLR``: lr = base * gamma^floor(t / step_size)."""
    size = max(int(step_size), 1)

    def f(t):
        return jnp.float32(base_lr) * jnp.float32(gamma) ** (
            (t // size).astype(jnp.float32))
    return f
