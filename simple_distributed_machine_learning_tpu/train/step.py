"""The compiled train/eval steps.

One ``jit`` covers what the reference spreads over four distributed subsystems
per batch — forward RPC, loss, distributed-autograd backward, remote optimizer
step (``/root/reference/simple_distributed.py:109-113``). Buffers are donated,
so params and optimizer state update in place on-device.
"""

from __future__ import annotations

import functools
from typing import Any

import jax

from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.train.optimizer import Optimizer


def make_train_step(pipe: Pipeline, opt: Optimizer,
                    with_grad_norm: bool = False):
    """Returns ``step(buf, opt_state, x, targets, key) -> (buf, opt_state, loss)``.

    The whole pipeline fwd + bwd + update is one XLA program: the forward
    ppermute hops, their autodiff transposes (the backward hops), and each
    stage's owner-local optimizer update all schedule together, letting XLA
    overlap ICI transfer with compute — the overlap the reference's blocking
    RPC design structurally cannot have (SURVEY §3.3).

    ``with_grad_norm``: the step additionally returns the global L2 norm of
    the packed gradient buffer as a fourth output — the one extra scalar the
    numeric-anomaly sentinel (``resilience/sentinel.py``) watches for
    NaN/Inf alongside the loss. Computed from the gradients the update
    consumes anyway; the loss math is unchanged.
    """
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(buf, opt_state, x, targets, key, weights=None):
        # Pipeline.loss_and_grads: GPipe via value_and_grad of the loss-only
        # engine (no [batch, *out_shape] accumulator rides the scan), or the
        # hand-scheduled 1F1B interleave when the pipeline was built with
        # schedule='1f1b'
        loss, grads = pipe.loss_and_grads(buf, x, targets, key,
                                          deterministic=False,
                                          weights=weights)
        buf2, opt_state2 = opt.update(grads, opt_state, buf)
        if with_grad_norm:
            gnorm = jnp.sqrt(jnp.sum(jnp.square(
                grads.astype(jnp.float32))))
            return buf2, opt_state2, loss, gnorm
        return buf2, opt_state2, loss

    return step


def make_scanned_train_step(pipe: Pipeline, opt: Optimizer, unroll: int = 1,
                            pool_steps: int | None = None):
    """Returns ``step(buf, opt_state, xs, targets, key) -> (buf, opt_state, losses)``
    where ``xs``/``targets`` carry a leading ``n_steps`` axis: one compiled
    program runs ``n_steps`` optimizer steps via ``lax.scan``.

    Why this exists: the reference dispatches every batch from Python through
    a blocking RPC (``simple_distributed.py:108-113``), so host overhead is
    paid per batch. On TPU the same Python-side loop would pay ~ms-scale
    dispatch per step, dwarfing the sub-ms compute of reference-scale models.
    Scanning the whole window keeps the chip busy back-to-back — this is the
    TPU-idiomatic shape of a training loop, and what ``bench.py`` measures.

    ``pool_steps``: when set, ``xs``/``targets`` are a POOL of ``P`` batches
    rather than one per step; the scan runs ``pool_steps`` optimizer steps,
    reading batch ``t % P`` at step ``t``. This keeps the resident input
    footprint at ``P`` batches however long the window is (a 5000-step f32
    MNIST window would otherwise pin ~1 GB of HBM for inputs alone).
    """

    from simple_distributed_machine_learning_tpu.parallel.staging import (
        pack_stage_params,
        unpack_stage_params,
    )

    # shards-is-None matters: a tensor-/expert-parallel stage's apply uses
    # mesh collectives, which cannot be traced outside shard_map
    trivial_mesh = (pipe.n_stages == 1 and pipe.n_data == 1
                    and pipe.n_model == 1 and pipe.n_seq == 1
                    and pipe.n_expert == 1
                    and pipe.stages[0].shards is None
                    and pipe.stages[0].expert_shards is None)

    from simple_distributed_machine_learning_tpu.ops.losses import nll_loss

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(buf, opt_state, xs, targets, key):
        import jax.numpy as jnp

        def scan_batches(body, init):
            if pool_steps is None:
                return jax.lax.scan(body, init, (xs, targets), unroll=unroll)
            n_pool = xs.shape[0]

            def body_pool(carry, t):
                x = jax.lax.dynamic_index_in_dim(xs, t % n_pool, 0,
                                                 keepdims=False)
                tt = jax.lax.dynamic_index_in_dim(targets, t % n_pool, 0,
                                                  keepdims=False)
                return body(carry, (x, tt))

            return jax.lax.scan(body_pool, init, jnp.arange(pool_steps),
                                unroll=unroll)

        # On the degenerate single-device mesh, differentiating through the
        # packed [1, 1, P] buffer costs ~10x the model itself per scan
        # iteration (the slice/concat machinery's autodiff). Unpack params and
        # any buffer-shaped optimizer state to pytrees ONCE per window, scan
        # on pytrees, repack at the end. Buffer-shaped state leaves (SGD
        # momentum, AdamW m/v) are unpacked alongside the params; scalar
        # leaves (step counters, carried bias-correction powers) pass through
        # unchanged — excluding them from this path sent every
        # counter-carrying optimizer down the packed-buffer engine, which
        # XLA:CPU compiles to ~1.4x the bytes and ~7x the live temp of the
        # pytree path for AdamW (benchmarks/opt_cost_analysis.py, the
        # round-5 "AdamW halves gpt_bf16" regression).
        os_leaves, os_def = jax.tree.flatten(opt_state)

        def _buf_shaped(l):
            return getattr(l, "shape", None) == buf.shape

        unpackable = trivial_mesh and all(
            _buf_shaped(l) or getattr(l, "ndim", None) == 0
            for l in os_leaves)

        if unpackable:
            meta = pipe.metas[0]
            stage = pipe.stages[0]
            buf_slot = [_buf_shaped(l) for l in os_leaves]

            def repack(tree):
                return pack_stage_params([tree])[0].reshape(buf.shape)

            params0 = unpack_stage_params(buf[0, 0, 0], meta)
            state0 = jax.tree.unflatten(os_def, [
                unpack_stage_params(l[0, 0, 0], meta) if is_buf else l
                for l, is_buf in zip(os_leaves, buf_slot)])

            def loss_tree(pp, x, t, k):
                # same math and RNG stream as Pipeline._fused_loss
                kk = jax.random.fold_in(
                    jax.random.fold_in(jax.random.fold_in(k, 0), 0), 0)
                xs = x.reshape((x.shape[0],) + tuple(stage.in_shape))
                if pipe.compute_dtype is not None:
                    pp = jax.tree.map(
                        lambda a: a.astype(pipe.compute_dtype), pp)
                    xs = xs.astype(pipe.compute_dtype)
                out = stage.apply(pp, xs, kk, False)
                import jax.numpy as jnp
                aux = jnp.float32(0.0)
                if isinstance(out, tuple):
                    out, aux = out
                    aux = aux.astype(jnp.float32)
                return nll_loss(out.astype(jnp.float32), t, "mean") + aux

            def body(carry, batch):
                p, s, i = carry
                x, t = batch
                k = jax.random.fold_in(key, i)
                loss, grads = jax.value_and_grad(loss_tree)(p, x, t, k)
                p2, s2 = opt.update(grads, s, p)
                return (p2, s2, i + 1), loss

            (p2, s2, _), losses = scan_batches(body, (params0, state0, 0))
            # s2's buffer-slot "leaves" are params-shaped trees
            # (flatten_up_to recovers them for repacking); scalar slots come
            # back as the scalars they are
            opt2 = jax.tree.unflatten(
                os_def, [repack(t_) if is_buf else t_
                         for t_, is_buf in zip(os_def.flatten_up_to(s2),
                                               buf_slot)])
            return repack(p2), opt2, losses

        def body(carry, batch):
            b, s, i = carry
            x, t = batch
            k = jax.random.fold_in(key, i)
            loss, grads = pipe.loss_and_grads(b, x, t, k,
                                              deterministic=False)
            b2, s2 = opt.update(grads, s, b)
            return (b2, s2, i + 1), loss

        (buf2, opt2, _), losses = scan_batches(body, (buf, opt_state, 0))
        return buf2, opt2, losses

    return step


def make_eval_step(pipe: Pipeline):
    """Returns ``eval_step(buf, x, targets, key, n_valid) -> (sum_nll, n_correct)``.

    Deterministic: dropout is OFF — deliberately diverging from the
    reference's quirk of leaving worker-side dropout active during eval
    (``simple_distributed.py:75`` with ``model.eval()`` not crossing RPC at
    ``:120``; SURVEY §3.5 flags this as a bug not to carry over).

    ``n_valid`` masks zero-padded trailing rows of a ragged final batch (the
    compiled pipeline needs static shapes; the reference's DataLoader just
    emits a short batch, ``simple_distributed.py:95``).

    Memory: built on ``Pipeline.eval_metrics`` — the sums are computed
    inside the shard_map scan, so no ``[batch, *out_shape]`` logits tensor
    is ever materialized or replicated across stages (eval fits wherever
    training fits, even for vocab-wide LM outputs).
    """
    import jax.numpy as jnp

    @jax.jit
    def step(buf, x, targets, key, n_valid):
        # per-sample 0/1 validity mask; eval_metrics broadcasts it over any
        # token axes (LM targets [B, T])
        mask = (jnp.arange(x.shape[0]) < n_valid).astype(jnp.float32)
        sum_loss, _, correct = pipe.eval_metrics(buf, x, targets, key,
                                                 weights=mask)
        return sum_loss, correct          # correct is exact int32

    return step
