"""The compiled train/eval steps.

One ``jit`` covers what the reference spreads over four distributed subsystems
per batch — forward RPC, loss, distributed-autograd backward, remote optimizer
step (``/root/reference/simple_distributed.py:109-113``). Buffers are donated,
so params and optimizer state update in place on-device.
"""

from __future__ import annotations

import functools
from typing import Any

import jax

from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.train.optimizer import Optimizer


def make_train_step(pipe: Pipeline, opt: Optimizer):
    """Returns ``step(buf, opt_state, x, targets, key) -> (buf, opt_state, loss)``.

    The whole pipeline fwd + bwd + update is one XLA program: the forward
    ppermute hops, their autodiff transposes (the backward hops), and each
    stage's owner-local optimizer update all schedule together, letting XLA
    overlap ICI transfer with compute — the overlap the reference's blocking
    RPC design structurally cannot have (SURVEY §3.3).
    """

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(buf, opt_state, x, targets, key):
        def loss_fn(b):
            loss, _ = pipe.loss_and_logits(b, x, targets, key, deterministic=False)
            return loss
        loss, grads = jax.value_and_grad(loss_fn)(buf)
        buf2, opt_state2 = opt.update(grads, opt_state, buf)
        return buf2, opt_state2, loss

    return step


def make_eval_step(pipe: Pipeline):
    """Returns ``eval_step(buf, x, targets, key) -> (sum_nll, n_correct)``.

    Deterministic: dropout is OFF — deliberately diverging from the
    reference's quirk of leaving worker-side dropout active during eval
    (``simple_distributed.py:75`` with ``model.eval()`` not crossing RPC at
    ``:120``; SURVEY §3.5 flags this as a bug not to carry over).
    """

    @jax.jit
    def step(buf, x, targets, key):
        _, logp = pipe.loss_and_logits(buf, x, targets, key, deterministic=True)
        from simple_distributed_machine_learning_tpu.ops.losses import nll_loss
        sum_loss = nll_loss(logp, targets, reduction="sum")
        correct = (logp.argmax(-1) == targets).sum()
        return sum_loss, correct

    return step
