"""ctypes bindings for the native C++ data loader (``native/data_loader.cpp``).

Builds ``libsdml_data.so`` on demand with ``make`` (g++ is in the image;
pybind11 is not, hence the plain C ABI + ctypes). Everything here degrades
gracefully: if the toolchain or .so is unavailable, callers fall back to the
pure-NumPy paths in ``mnist.py``.
"""

from __future__ import annotations

import ctypes
import fcntl
import os
import subprocess
from typing import Iterator

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libsdml_data.so")

_lib = None  # None = not attempted; False = attempted and unavailable


def _load() -> ctypes.CDLL | None:
    global _lib
    if _lib is not None:
        return _lib or None  # False (cached failure) -> None
    # always invoke make: it is a no-op when the .so is newer than the
    # sources, and rebuilds when data_loader.cpp changed (a pre-existing .so
    # must never mask an edited source file). flock serializes concurrent
    # processes (every rank of a multi-process launch lands here at startup)
    # so none can dlopen a half-written .so.
    try:
        with open(os.path.join(_NATIVE_DIR, ".build.lock"), "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                           capture_output=True, timeout=120)
    except Exception:
        if not os.path.exists(_SO_PATH):
            _lib = False
            return None
    try:
        lib = ctypes.CDLL(_SO_PATH)
    except OSError:
        _lib = False
        return None
    lib.idx_read.argtypes = [ctypes.c_char_p,
                             ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
                             ctypes.POINTER(ctypes.c_int64),
                             ctypes.POINTER(ctypes.c_int)]
    lib.idx_read.restype = ctypes.c_int
    lib.idx_free.argtypes = [ctypes.POINTER(ctypes.c_float)]
    lib.prefetcher_create.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
    lib.prefetcher_create.restype = ctypes.c_void_p
    lib.prefetcher_next.argtypes = [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_float),
                                    ctypes.POINTER(ctypes.c_int32)]
    lib.prefetcher_next.restype = ctypes.c_int64
    lib.prefetcher_num_batches.argtypes = [ctypes.c_void_p]
    lib.prefetcher_num_batches.restype = ctypes.c_int64
    lib.prefetcher_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


def idx_read_native(path: str) -> np.ndarray | None:
    """Parse an IDX file via the C++ codec. None if native lib unavailable."""
    lib = _load()
    if lib is None:
        return None
    data = ctypes.POINTER(ctypes.c_float)()
    dims = (ctypes.c_int64 * 4)()
    ndim = ctypes.c_int()
    rc = lib.idx_read(path.encode(), ctypes.byref(data), dims,
                      ctypes.byref(ndim))
    if rc != 0:
        raise IOError(f"idx_read({path!r}) failed with code {rc}")
    shape = tuple(dims[i] for i in range(ndim.value))
    n = int(np.prod(shape))
    out = np.ctypeslib.as_array(data, shape=(n,)).reshape(shape).copy()
    lib.idx_free(data)
    return out


class NativePrefetcher:
    """Background-thread batch assembly over (x, y) arrays.

    Iterates ``(x_batch, y_batch, n_valid)`` in ``order``; the final ragged
    batch arrives zero-padded, mirroring ``mnist.batches(pad_last=True)``.
    """

    def __init__(self, x: np.ndarray, y: np.ndarray, batch: int,
                 order: np.ndarray | None = None, depth: int = 2):
        lib = _load()
        if lib is None:
            raise RuntimeError("native loader unavailable")
        self._lib = lib
        self.x = np.ascontiguousarray(x, np.float32).reshape(len(x), -1)
        y2 = np.ascontiguousarray(y, np.int32)
        self.y = y2.reshape(len(y2), -1)
        self.batch = batch
        self.row_x = self.x.shape[1]
        self.row_y = self.y.shape[1]
        self._x_shape = x.shape[1:]
        self._y_shape = y.shape[1:]
        order = (np.arange(len(x), dtype=np.int64) if order is None
                 else np.ascontiguousarray(order, np.int64))
        self._h = lib.prefetcher_create(
            self.x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            self.y.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            len(self.x), self.row_x, self.row_y, batch,
            order.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), depth)
        self.n_batches = lib.prefetcher_num_batches(self._h)

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray, int]]:
        bx = np.empty((self.batch, self.row_x), np.float32)
        by = np.empty((self.batch, self.row_y), np.int32)
        while True:
            n_valid = self._lib.prefetcher_next(
                self._h,
                bx.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                by.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            if n_valid < 0:
                return
            yield (bx.reshape((self.batch,) + self._x_shape).copy(),
                   by.reshape((self.batch,) + self._y_shape).copy(),
                   int(n_valid))

    def close(self) -> None:
        if self._h:
            self._lib.prefetcher_destroy(self._h)
            self._h = None

    def __del__(self):  # noqa: D105
        try:
            self.close()
        except Exception:
            pass
