"""Per-host input sharding: each process feeds only its data-axis slice.

The reference loads the full dataset on the master and ships every batch over
RPC (``/root/reference/simple_distributed.py:87-95``). The straight SPMD
mapping of that — every host materializing the full global batch and letting
the in_spec shard it — is correct but wrongly shaped for real multi-host data
parallelism: host memory and host→device transfer then scale with the GLOBAL
batch. This module gives each process the right contract instead: host ``h``
materializes only the contiguous rows of the global batch its own devices
need, and :func:`jax.make_array_from_process_local_data` assembles the global
``jax.Array`` without any host ever holding the whole thing.

On a single process (tests, the one-chip bench) the addressable slice is the
whole batch and everything degenerates to the status quo.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from simple_distributed_machine_learning_tpu.parallel.mesh import DATA_AXIS


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Global-batch sharding: axis 0 over the mesh's data axis, all other
    axes replicated (stage/model/seq/expert devices all need every feature
    of their data shard's rows)."""
    return NamedSharding(mesh, P(DATA_AXIS))


def host_rows(mesh: Mesh, batch: int) -> tuple[int, int]:
    """This process's contiguous ``[lo, hi)`` row range of a ``[batch, ...]``
    global array under :func:`batch_sharding`.

    Raises if the addressable rows are not one contiguous range (cannot
    happen with ``make_mesh``'s data-major device order, but a custom device
    permutation could interleave shards — better loud than silently wrong).
    """
    sh = batch_sharding(mesh)
    slices = sorted(
        (idx[0].indices(batch)[:2]
         for idx in sh.addressable_devices_indices_map((batch,)).values()),
    )
    lo, hi = slices[0]
    for s_lo, s_hi in slices[1:]:      # interval merge: O(n_devices log n)
        if s_lo > hi:
            raise ValueError(
                f"process-addressable rows of a {batch}-row batch are not "
                f"contiguous ({slices}); per-host input sharding needs a "
                f"data-major device order (make_mesh's default)")
        hi = max(hi, s_hi)
    return lo, hi


def make_global_batch(mesh: Mesh, local: np.ndarray | jax.Array,
                      global_batch: int) -> jax.Array:
    """Assemble the global ``[global_batch, ...]`` array from this process's
    local rows (``host_rows(mesh, global_batch)`` of it).

    Every process must call this (it establishes a multi-host global array);
    the result feeds any compiled step exactly like the replicated numpy
    batch used to, but only local rows ever touch this host's memory/ICI.
    """
    sh = batch_sharding(mesh)
    return jax.make_array_from_process_local_data(
        sh, np.asarray(local), (global_batch,) + tuple(local.shape[1:]))
