"""Synthetic token streams for language-model training (zero-egress).

A deterministic order-1 Markov chain over the vocabulary: structure a 2-layer
GPT can learn (next-token entropy well below uniform), generated hermetically
— the LM analogue of ``mnist.synthetic_mnist``.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


class LMData(NamedTuple):
    x: np.ndarray        # [N, T] int32 input tokens
    y: np.ndarray        # [N, T] int32 next-token targets


def synthetic_tokens(n_seqs: int, seq_len: int, vocab: int,
                     seed: int = 0) -> LMData:
    rng = np.random.default_rng(seed)
    # peaked transition matrix: each token has ~4 likely successors
    logits = rng.normal(size=(vocab, vocab)).astype(np.float32)
    top = np.argsort(logits, axis=1)[:, -4:]
    boost = np.zeros_like(logits)
    np.put_along_axis(boost, top, 4.0, axis=1)
    p = np.exp(logits * 0.1 + boost)
    p /= p.sum(1, keepdims=True)
    logp = np.log(p)

    toks = np.empty((n_seqs, seq_len + 1), np.int64)
    toks[:, 0] = rng.integers(0, vocab, n_seqs)
    for t in range(seq_len):
        g = rng.gumbel(size=(n_seqs, vocab))
        toks[:, t + 1] = np.argmax(logp[toks[:, t]] + g, axis=1)
    toks = toks.astype(np.int32)
    return LMData(toks[:, :-1], toks[:, 1:])
