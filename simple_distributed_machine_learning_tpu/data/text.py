"""Synthetic token streams for language-model training (zero-egress).

A deterministic order-1 Markov chain over the vocabulary: structure a 2-layer
GPT can learn (next-token entropy well below uniform), generated hermetically
— the LM analogue of ``mnist.synthetic_mnist``.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


class LMData(NamedTuple):
    x: np.ndarray        # [N, T] int32 input tokens
    y: np.ndarray        # [N, T] int32 next-token targets


def synthetic_tokens(n_seqs: int, seq_len: int, vocab: int,
                     seed: int = 0) -> LMData:
    rng = np.random.default_rng(seed)
    # peaked transition matrix: each token has ~4 likely successors
    logits = rng.normal(size=(vocab, vocab)).astype(np.float32)
    top = np.argsort(logits, axis=1)[:, -4:]
    boost = np.zeros_like(logits)
    np.put_along_axis(boost, top, 4.0, axis=1)
    p = np.exp(logits * 0.1 + boost)
    p /= p.sum(1, keepdims=True)
    logp = np.log(p)

    toks = np.empty((n_seqs, seq_len + 1), np.int64)
    toks[:, 0] = rng.integers(0, vocab, n_seqs)
    for t in range(seq_len):
        g = rng.gumbel(size=(n_seqs, vocab))
        toks[:, t + 1] = np.argmax(logp[toks[:, t]] + g, axis=1)
    toks = toks.astype(np.int32)
    return LMData(toks[:, :-1], toks[:, 1:])


def byte_corpus(path: str, seq_len: int, test_frac: float = 0.1,
                max_seqs: int | None = None) -> tuple[LMData, LMData]:
    """Byte-level LM dataset from a LOCAL file: ``(train, test)``.

    The real-data path for ``--model gpt`` — the LM analogue of the MNIST
    IDX loader (the reference sources real data first and falls back to
    synthetic, ``/root/reference/simple_distributed.py:87-95``; zero-egress
    here means the corpus is any file already on disk). vocab is the full
    byte range (256). The file is chopped into non-overlapping ``seq_len``
    windows with next-byte targets (``y[t] = x[t+1]``'s byte); the split is
    contiguous AND skips the boundary byte — the last train window's final
    TARGET would otherwise be the first test byte, so test text starts one
    byte later and is strictly never seen in training (input or target).
    """
    with open(path, "rb") as f:
        raw = np.frombuffer(f.read(), np.uint8)
    n = (len(raw) - 1) // seq_len
    if max_seqs is not None:
        if max_seqs < 2:
            raise ValueError(
                f"max_seqs={max_seqs} leaves nothing to split (need >= 2 "
                f"windows, one each for train and test)")
        n = min(n, max_seqs)
    n_test = max(1, int(n * test_frac))
    n_train = n - n_test
    off = n_train * seq_len + 1        # +1: skip the leaked boundary byte
    # the skip can cost the last window a byte; recompute what still fits,
    # but never grow past the test_frac/max_seqs-derived count
    n_test = (min((len(raw) - off - 1) // seq_len, n_test)
              if n_train >= 1 else 0)
    if n_train < 1 or n_test < 1:
        raise ValueError(
            f"corpus {path!r} has {len(raw)} bytes — needs at least "
            f"2*seq_len+2 = {2 * seq_len + 2} for a held-out test split")
    tr_x = raw[:n_train * seq_len].reshape(n_train, seq_len)
    tr_y = raw[1:n_train * seq_len + 1].reshape(n_train, seq_len)
    te_x = raw[off:off + n_test * seq_len].reshape(n_test, seq_len)
    te_y = raw[off + 1:off + n_test * seq_len + 1].reshape(n_test, seq_len)
    return (LMData(tr_x.astype(np.int32), tr_y.astype(np.int32)),
            LMData(te_x.astype(np.int32), te_y.astype(np.int32)))
