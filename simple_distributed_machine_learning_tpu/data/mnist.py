"""MNIST data pipeline (reference parity) with a hermetic synthetic fallback.

Reference behavior being matched (``/root/reference/simple_distributed.py:87-95``):
MNIST train+test, both cut to 1/10 via ``Subset(range(len//10))`` → 6000 train
/ 1000 test samples; batch 60; **no shuffle** (deterministic batch order);
``ToTensor`` scaling only (x/255, no normalization).

Sourcing differs by necessity: the reference downloads via torchvision; this
build runs in a zero-egress environment, so the loader reads standard IDX
files from disk when present (``train-images-idx3-ubyte`` etc., optionally
.gz) and otherwise generates a deterministic synthetic 10-class digit-like
dataset with the same shapes/sizes, so training, tests, and benchmarks are
hermetic.

Layout is NHWC ``[N, 28, 28, 1]`` float32 in [0, 1].
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Iterator, NamedTuple

import numpy as np

IMG_SHAPE = (28, 28, 1)


class Dataset(NamedTuple):
    x: np.ndarray  # [N, 28, 28, 1] float32
    y: np.ndarray  # [N] int32


def _read_idx(path: str) -> np.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


def _find(root: str, stem: str) -> str | None:
    for name in (stem, stem + ".gz"):
        for sub in ("", "MNIST/raw"):
            p = os.path.join(root, sub, name)
            if os.path.exists(p):
                return p
    return None


def load_idx_mnist(root: str) -> tuple[Dataset, Dataset] | None:
    """Load real MNIST from IDX files under ``root``; None if absent.

    Non-gzip files go through the native C++ IDX codec
    (``native/data_loader.cpp``) when the toolchain is available — it returns
    images already normalized to [0, 1] float32 — with the pure-NumPy parser
    as fallback (and for .gz files, which the native codec does not decode).
    """
    from simple_distributed_machine_learning_tpu.data import native_loader

    paths = {k: _find(root, s) for k, s in {
        "train_x": "train-images-idx3-ubyte",
        "train_y": "train-labels-idx1-ubyte",
        "test_x": "t10k-images-idx3-ubyte",
        "test_y": "t10k-labels-idx1-ubyte",
    }.items()}
    if any(v is None for v in paths.values()):
        return None

    native_ok = native_loader.available()

    def imgs(p):
        if native_ok and not p.endswith(".gz"):
            return native_loader.idx_read_native(p)[..., None]
        return (_read_idx(p).astype(np.float32) / 255.0)[..., None]

    def labels(p):
        if native_ok and not p.endswith(".gz"):
            return native_loader.idx_read_native(p).astype(np.int32)
        return _read_idx(p).astype(np.int32)

    train = Dataset(imgs(paths["train_x"]), labels(paths["train_y"]))
    test = Dataset(imgs(paths["test_x"]), labels(paths["test_y"]))
    return train, test


def synthetic_mnist(n_train: int = 60000, n_test: int = 10000,
                    seed: int = 0) -> tuple[Dataset, Dataset]:
    """Deterministic MNIST-shaped 10-class task.

    Each class is a smooth random 28×28 prototype; samples are the prototype
    under small random shifts plus pixel noise, clipped to [0, 1]. Learnable
    by a conv net but not trivially linearly separable — adequate for loss
    curves, tests, and throughput benchmarks without network access.
    """
    rng = np.random.default_rng(seed)
    base = rng.normal(size=(10, 32, 32)).astype(np.float32)
    # smooth prototypes: blur by box filter passes
    for _ in range(3):
        base = (base + np.roll(base, 1, 1) + np.roll(base, -1, 1)
                + np.roll(base, 1, 2) + np.roll(base, -1, 2)) / 5.0
    base = (base - base.min((1, 2), keepdims=True))
    base = base / base.max((1, 2), keepdims=True)

    def gen(n, rng):
        labels = (np.arange(n) % 10).astype(np.int32)  # balanced, fixed order
        dx = rng.integers(0, 5, size=n)
        dy = rng.integers(0, 5, size=n)
        imgs = np.empty((n, 28, 28), np.float32)
        for i in range(n):
            p = base[labels[i]]
            imgs[i] = p[dx[i]:dx[i] + 28, dy[i]:dy[i] + 28]
        imgs += rng.normal(scale=0.15, size=imgs.shape).astype(np.float32)
        np.clip(imgs, 0.0, 1.0, out=imgs)
        return Dataset(imgs[..., None], labels)

    return gen(n_train, rng), gen(n_test, rng)


def load_mnist(root: str = "data", subset_divisor: int = 10,
               synthetic_ok: bool = True) -> tuple[Dataset, Dataset]:
    """Reference-equivalent dataset: real MNIST if on disk, else synthetic;
    both splits cut to their first ``1/subset_divisor`` (reference ``:91-92``)."""
    loaded = load_idx_mnist(root)
    if loaded is None:
        if not synthetic_ok:
            raise FileNotFoundError(
                f"MNIST IDX files not found under {root!r} and synthetic "
                f"fallback disabled")
        # generate only the post-subset sizes (synthetic data has no
        # "real prefix" to preserve; generating 70k then slicing 10% away
        # would waste a 70k-iteration python loop and ~220 MB transients)
        loaded = synthetic_mnist(n_train=60000 // max(subset_divisor, 1),
                                 n_test=10000 // max(subset_divisor, 1))
        return loaded
    train, test = loaded
    if subset_divisor > 1:
        train = Dataset(train.x[: len(train.x) // subset_divisor],
                        train.y[: len(train.y) // subset_divisor])
        test = Dataset(test.x[: len(test.x) // subset_divisor],
                       test.y[: len(test.y) // subset_divisor])
    return train, test


class Batch(NamedTuple):
    x: np.ndarray
    y: np.ndarray
    n_valid: int  # <= len(x): trailing rows are padding


def batches(ds: Dataset, batch_size: int, pad_last: bool = True,
            shuffle_seed: int | None = None) -> Iterator[Batch]:
    """Batches in fixed order (the reference's default — no shuffle,
    ``:94-95``) or a seeded permutation (``shuffle_seed``: deterministic and
    reproducible per epoch, unlike the reference's implicit global RNG).

    The pipeline is a compiled static-shape program, so a ragged final batch
    (the reference's test set: 1000 = 16·60 + 40) is zero-padded to full size
    and carries ``n_valid`` for masked loss/accuracy accumulation.
    """
    n = len(ds.x)
    # mask into RandomState's 32-bit range: callers derive epoch seeds by
    # multiplication (trainer: seed * 100003 + epoch) which overflows it
    order = (np.random.RandomState(shuffle_seed % 2**32).permutation(n)
             if shuffle_seed is not None else None)
    for start in range(0, n, batch_size):
        idx = (order[start:start + batch_size] if order is not None
               else slice(start, start + batch_size))
        x = ds.x[idx]
        y = ds.y[idx]
        n_valid = len(x)
        if n_valid < batch_size:
            if not pad_last:
                return
            pad = batch_size - n_valid
            x = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
            y = np.concatenate([y, np.zeros((pad,) + y.shape[1:], y.dtype)])
        yield Batch(x, y, n_valid)


def prefetch_batches(ds: Dataset, batch_size: int,
                     shuffle_seed: int | None = None) -> Iterator[Batch]:
    """Like :func:`batches` (pad_last semantics) but batch assembly runs on
    the native C++ prefetcher thread (``native/data_loader.cpp``) when the
    toolchain is available, overlapping gather/pad with the device step —
    the TPU-side analogue of the torch DataLoader worker the reference leans
    on (SURVEY §2.3). Falls back to the pure-Python iterator transparently.

    ``shuffle_seed``: seeded epoch shuffle. The permutation is handed to the
    native prefetcher as its gather order (it assembles batches by index on
    its own thread), so no shuffled copy of the dataset is ever
    materialized; the Python fallback (:func:`batches`) gathers per batch
    with the identical permutation RNG.
    """
    from simple_distributed_machine_learning_tpu.data import native_loader

    if not native_loader.available():
        yield from batches(ds, batch_size, pad_last=True,
                           shuffle_seed=shuffle_seed)
        return
    order = (np.random.RandomState(
                 shuffle_seed % 2**32).permutation(len(ds.x))
             if shuffle_seed is not None else None)
    pf = native_loader.NativePrefetcher(ds.x, ds.y, batch_size, order=order)
    try:
        for bx, by, n_valid in pf:
            yield Batch(bx, by.astype(ds.y.dtype, copy=False), n_valid)
    finally:
        pf.close()
