"""Data pipeline: MNIST (IDX files or deterministic synthetic fallback)."""
