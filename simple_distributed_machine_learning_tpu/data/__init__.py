"""Data pipeline: MNIST (IDX files or synthetic fallback), byte-LM corpora,
per-host batch sharding."""

from simple_distributed_machine_learning_tpu.data.sharding import (  # noqa: F401
    host_rows,
    make_global_batch,
)
from simple_distributed_machine_learning_tpu.data.text import (  # noqa: F401
    byte_corpus,
    synthetic_tokens,
)
