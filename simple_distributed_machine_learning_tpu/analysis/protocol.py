"""Bounded model checking of the serve fleet protocol.

The journal snap/adopt/handoff protocol (``serve/journal.py``,
``serve/supervisor.py``, ``serve/fleet.py``) argues its safety story in
prose: the terminal ``handoff`` tombstone prevents double-serve, the
adopting replica's ``snap`` makes its journal self-sufficient, refcounts
conserve, boarding blocks until a host-tier upload lands. The chaos drills
sample that story — one fault at one tick; this module PROVES it (to a
depth bound) by exhaustive interleaving exploration over a small-step
abstraction of the fleet:

- abstract replicas with a pool role, a free-block counter and an
  append-only journal of event tuples mirroring the real grammar
  (``submit``/``tok``/``done``/``shed``/``snap``/``handoff``);
- per-request lifecycle ``q``/``a``/``d``/``s`` (queued/active/done/shed)
  plus ghost fields — tokens delivered to the caller, completions seen —
  that make double-serve an observable state property;
- transitions for every interleaving point the real fleet has: the
  journaled-but-not-admitted submit corner, boarding, token emission,
  shedding, the three-step handoff (release / adopt / seal), single-replica
  crash with journal-only migration (including the replica-kill-racing-
  adopt point between adopt and seal — the ``fleet.handoff`` fault site),
  whole-host crash with cold recovery from every journal, host-upload
  landing, and drain-then-retire.

Fidelity note: single-replica crashes are generated only at the points the
real fleet can observe one (the ``fleet.tick`` probe, and ``fleet.handoff``
between adopt and seal); the whole-host crash (``crash_host``) can land
between ANY two journal appends — that is the transition that found the
tombstone-before-copy ordering bug the copy-then-tombstone fix in
``ServeFleet._handoff_step`` closes.

Every violation renders as a finite counterexample trace and exports as a
``resilience/faults.py`` FaultPlan schedule (:func:`export_fault_plan`),
so a failing model run becomes a replayable chaos drill — closing the loop
with ``drill_coverage``. Pure stdlib: no jax, no numpy — the CI lint job
runs ``--serve-protocol`` in milliseconds-to-seconds on CPU.
"""

from __future__ import annotations

import dataclasses

from simple_distributed_machine_learning_tpu.analysis.report import (
    Finding,
    Report,
    Severity,
)
from simple_distributed_machine_learning_tpu.analysis.statespace import (
    Exploration,
    Violation,
    explore,
)

#: abstract request lifecycle (the model's compressed spelling of
#: serve/request.py's QUEUED/ACTIVE/DONE/SHED)
Q, A, D, S = "q", "a", "d", "s"

#: the safety invariants the checker proves, in report order
INVARIANTS = ("double-serve", "lost-request", "refcount", "boarding-gate",
              "journal-grammar")


@dataclasses.dataclass(frozen=True)
class ProtocolConfig:
    """One model-checking run's fleet shape, fault budgets and protocol
    variant. The defect knobs exist for the seeded fixtures: each flips
    the abstraction to a protocol the real code must never implement, and
    the checker must produce the counterexample proving why."""

    n_prefill: int = 1
    n_decode: int = 1
    n_requests: int = 2
    need_tokens: int = 2          # tokens to finish a request
    blocks_per_replica: int = 2
    crash_budget: int = 1
    handoff_budget: int = 1
    shed_budget: int = 1
    upload_rids: tuple = (1,)     # rids with an in-flight host->HBM upload
    depth: int = 8
    allow_retire: bool = True
    # -- protocol variant / defect knobs ----------------------------------
    #: "copy-then-tombstone" is the fixed ordering (adopt journals the snap
    #: on the destination BEFORE the source journals the terminal handoff);
    #: "tombstone-then-copy" is the pre-fix ordering, kept as the seeded
    #: defect that loses a request to a host crash between the two appends
    handoff_order: str = "copy-then-tombstone"
    drop_tombstone: bool = False  # defect: terminal handoff never journaled
    refund_on_shed: bool = True   # defect False: shed skips block refund
    recovery_dedup: bool = True   # the _lose_replica live-elsewhere guard
    gate_uploads: bool = True     # boarding blocked until upload lands

    def __post_init__(self):
        if self.n_prefill < 1 or self.n_decode < 1:
            raise ValueError("a disaggregated model needs >= 1 replica "
                             "per pool")
        if self.need_tokens < 1 or self.n_requests < 0:
            raise ValueError("need_tokens >= 1 and n_requests >= 0")
        if self.handoff_order not in ("copy-then-tombstone",
                                      "tombstone-then-copy"):
            raise ValueError(f"unknown handoff_order "
                             f"{self.handoff_order!r}")

    @property
    def n_replicas(self) -> int:
        return self.n_prefill + self.n_decode

    def summary(self) -> str:
        knobs = [k for k, bad in (
            ("tombstone-first", self.handoff_order == "tombstone-then-copy"),
            ("drop-tombstone", self.drop_tombstone),
            ("skip-refund", not self.refund_on_shed),
            ("no-recovery-dedup", not self.recovery_dedup),
            ("ungated-uploads", not self.gate_uploads)) if bad]
        return (f"{self.n_prefill}p+{self.n_decode}d replicas, "
                f"{self.n_requests} reqs x {self.need_tokens} toks, "
                f"budgets crash={self.crash_budget} "
                f"handoff={self.handoff_budget} shed={self.shed_budget}, "
                f"depth {self.depth}"
                + (f", defects: {'+'.join(knobs)}" if knobs else ""))


# -- state ------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class _Rep:
    """One abstract replica. ``live`` entries are ``(rid, state, ntok,
    blocks)`` sorted by rid (finished requests stay, like the real
    ``supervisor.requests`` dict); ``pending`` rids are journaled but not
    yet admitted (the mid-submit crash corner)."""

    idx: int
    role: str                  # "prefill" | "decode"
    alive: bool
    journal: tuple             # event tuples, see _fold
    live: tuple
    pending: tuple
    free: int


@dataclasses.dataclass(frozen=True)
class _State:
    """The whole fleet plus ghost observables. ``limbo`` holds the one
    in-memory mid-handoff handle: ``(rid, ntok, src, dst, stage)`` with
    stage ``released`` (detached from the source) or ``adopted`` (snap
    journaled on the destination, tombstone not yet sealed)."""

    reps: tuple
    limbo: tuple
    uploads: tuple             # rids whose host->HBM upload is in flight
    submitted: int             # rids 0..submitted-1 have journaled submits
    delivered: tuple           # ghost: tokens handed to the caller, per rid
    done_ct: tuple             # ghost: completions observed, per rid
    shed_ct: tuple             # ghost: sheds observed, per rid
    crash_budget: int
    handoff_budget: int
    shed_budget: int


def _initial(cfg: ProtocolConfig) -> _State:
    reps = tuple(
        _Rep(idx=i, role=("prefill" if i < cfg.n_prefill else "decode"),
             alive=True, journal=(), live=(), pending=(),
             free=cfg.blocks_per_replica)
        for i in range(cfg.n_replicas))
    zeros = (0,) * cfg.n_requests
    return _State(reps=reps, limbo=(), uploads=tuple(sorted(
                      r for r in cfg.upload_rids if r < cfg.n_requests)),
                  submitted=0, delivered=zeros, done_ct=zeros,
                  shed_ct=zeros, crash_budget=cfg.crash_budget,
                  handoff_budget=cfg.handoff_budget,
                  shed_budget=cfg.shed_budget)


# -- journal fold (the model's recover_state) -------------------------------

def _fold(journal, need: int):
    """Fold one abstract journal into ``{rid: (state, ntok)}`` plus a list
    of grammar-discipline errors — the model twin of
    ``serve/journal.py::recover_state``, including the terminal-tombstone
    drop, snap replacement (which resurrects a rid adopted BACK after its
    handoff) and the journaled-but-not-acked DONE promotion."""
    reqs: dict = {}
    dropped: set = set()
    errs: list[str] = []
    for ev in journal:
        kind, rid = ev[0], ev[1]
        if kind == "snap":
            dropped.discard(rid)
            reqs[rid] = [ev[3] if len(ev) > 4 else Q, ev[2]]
            continue
        if rid in dropped:
            errs.append(f"'{kind}' for rid {rid} after its handoff "
                        f"tombstone — the journal grammar marks the rid "
                        f"as moved out")
            continue
        if kind == "submit":
            reqs[rid] = [Q, 0]
        elif kind == "tok":
            if rid not in reqs:
                errs.append(f"'tok' for rid {rid} with no submit/snap")
            elif reqs[rid][0] in (D, S):
                errs.append(f"'tok' for rid {rid} after it finished")
            else:
                reqs[rid][1] += 1
        elif kind == "done":
            if rid not in reqs:
                errs.append(f"'done' for rid {rid} with no submit/snap")
            elif reqs[rid][0] == D:
                errs.append(f"double 'done' for rid {rid}")
            else:
                reqs[rid][0] = D
        elif kind == "shed":
            if rid not in reqs:
                errs.append(f"'shed' for rid {rid} with no submit/snap")
            else:
                reqs[rid][0] = S
        elif kind == "handoff":
            reqs.pop(rid, None)
            dropped.add(rid)
        else:
            errs.append(f"unknown journal event kind {kind!r}")
    for st in reqs.values():
        if st[0] == Q and st[1] >= need:
            st[0] = D               # the not-acked promotion
    return {rid: tuple(st) for rid, st in reqs.items()}, errs


def abstract_recover(events: list) -> dict:
    """The model's fold over REAL journal records (dicts straight from
    ``read_journal``): ``{rid: (state, n_tokens)}`` with the same
    discipline ``recover_state`` implements — what the old-grammar
    regression test pins the two against. Tick-less and ``why``-less
    records (pre-field journals) fold identically: neither key is read."""
    model_evs = []
    budgets: dict = {}               # per-rid max_new rides submit/snap
    for ev in events:
        kind = ev.get("ev")
        if kind == "submit":
            budgets[int(ev["rid"])] = int(ev["max_new"])
            model_evs.append(("submit", int(ev["rid"])))
        elif kind in ("tok", "done", "shed", "handoff"):
            model_evs.append((kind, int(ev["rid"])))
        elif kind == "snap":
            st = {"queued": Q, "active": Q, "done": D, "shed": S}.get(
                ev.get("state"), Q)
            budgets[int(ev["rid"])] = int(ev["max_new"])
            model_evs.append(("snap", int(ev["rid"]),
                              len(ev.get("toks", ())), st, ev.get("why")))
        # "restart" records are observability-only, exactly like the real
        # fold — the journal-grammar hostlint rule pins that every other
        # kind a writer emits lands in one of these branches
    reqs, _errs = _fold(tuple(model_evs), need=1 << 30)
    out = {}
    for rid, (st, ntok) in reqs.items():
        if st == Q and rid in budgets and ntok >= budgets[rid]:
            st = D
        out[rid] = (st, ntok)
    return out


# -- transitions ------------------------------------------------------------

def _rep_replace(s: _State, rep: _Rep, **kw) -> _State:
    reps = tuple(dataclasses.replace(r, **kw) if r.idx == rep.idx else r
                 for r in s.reps)
    return dataclasses.replace(s, reps=reps)


def _live_get(rep: _Rep, rid: int):
    for e in rep.live:
        if e[0] == rid:
            return e
    return None


def _live_set(live: tuple, entry) -> tuple:
    return tuple(sorted([e for e in live if e[0] != entry[0]] + [entry]))


def _live_del(live: tuple, rid: int) -> tuple:
    return tuple(e for e in live if e[0] != rid)


def _bump(t: tuple, i: int, by: int = 1) -> tuple:
    return t[:i] + (t[i] + by,) + t[i + 1:]


def _alive(s: _State):
    return [r for r in s.reps if r.alive]


def _adopt_target(s: _State, ntok: int, exclude=()):
    """Deterministic loss-migration routing: the degradation chain
    ``_role_candidates`` implements, collapsed to lowest-idx (the model
    has no affinity state to break ties with)."""
    role = "decode" if ntok > 0 else "prefill"
    cands = ([r for r in _alive(s) if r.role == role
              and r.idx not in exclude]
             or [r for r in _alive(s) if r.idx not in exclude])
    return cands[0] if cands else None


def _adopt_onto(s: _State, rep: _Rep, rid: int, ntok: int,
                why: str) -> _State:
    """Journal the snap FIRST, then restore — the ``adopt`` discipline."""
    s = _rep_replace(
        s, rep,
        journal=rep.journal + (("snap", rid, ntok, Q, why),),
        live=_live_set(rep.live, (rid, Q, ntok, 0)))
    return s


def _crash_rep(cfg: ProtocolConfig, s: _State, rep: _Rep) -> _State:
    """One replica dies; the fleet migrates off its journal alone —
    ``ServeFleet._lose_replica`` with (when ``recovery_dedup``) the
    live-elsewhere guard. The dead journal is cleared afterwards: it is
    never read again, and normalizing it collapses equivalent states."""
    folded, _errs = _fold(rep.journal, cfg.need_tokens)
    s = _rep_replace(s, rep, alive=False, journal=(), live=(),
                     pending=(), free=0)
    for rid in sorted(folded):
        st, ntok = folded[rid]
        if st in (D, S):
            continue                       # handle-only adoption
        if cfg.recovery_dedup and any(
                _live_get(r, rid) is not None or rid in r.pending
                for r in _alive(s)):
            continue                       # live elsewhere: never re-adopt
        target = _adopt_target(s, ntok)
        if target is None:                 # no survivor (model boundary)
            continue
        s = _adopt_onto(s, target, rid, ntok, "failure")
    return s


def _crash_host(cfg: ProtocolConfig, s: _State) -> _State:
    """The whole fleet process dies between any two journal appends: every
    in-memory structure (limbo included) is gone; each alive replica cold-
    restarts from its own journal; rids live in several journals (a
    mid-handoff crash without the tombstone) dedup to the copy with the
    most progress, lowest idx first — the deterministic recovery rule."""
    terminal: set = set()                   # a done/shed record anywhere
    for r in _alive(s):                     # proves completion: never
        folded, _errs = _fold(r.journal, cfg.need_tokens)
        terminal.update(rid for rid, (st, _n) in folded.items()
                        if st in (D, S))    # re-serve such a rid
    winners: dict = {}                      # rid -> (ntok, idx)
    for r in _alive(s):
        folded, _errs = _fold(r.journal, cfg.need_tokens)
        for rid, (st, ntok) in folded.items():
            if st in (D, S) or rid in terminal:
                continue
            best = winners.get(rid)
            if best is None or ntok > best[0]:
                winners[rid] = (ntok, r.idx)
    reps = []
    for r in s.reps:
        if not r.alive:
            reps.append(r)
            continue
        folded, _errs = _fold(r.journal, cfg.need_tokens)
        # recover_state keeps finished handles too — the post-restart
        # requests dict is what the replica-loss dedup guard consults
        live = tuple(sorted(
            (rid, st, ntok, 0) for rid, (st, ntok) in folded.items()
            if (st in (D, S)) or (st == Q and winners.get(
                rid, (None, None))[1] == r.idx)))
        reps.append(dataclasses.replace(
            r, live=live, pending=(), free=cfg.blocks_per_replica))
    return dataclasses.replace(s, reps=tuple(reps), limbo=())


def _transitions(cfg: ProtocolConfig):
    def gen(s: _State):
        out = []
        alive = _alive(s)
        limbo_released = any(e[4] == "released" for e in s.limbo)
        # -- submit (journal, then admit: the mid-submit crash corner) ----
        if s.submitted < cfg.n_requests:
            rid = s.submitted
            cands = ([r for r in alive if r.role == "prefill"] or alive)
            if cands:
                t = cands[0]
                out.append((("submit_journal", rid), dataclasses.replace(
                    _rep_replace(s, t,
                                 journal=t.journal + (("submit", rid),),
                                 pending=t.pending + (rid,)),
                    submitted=rid + 1)))
        for r in alive:
            for rid in r.pending:
                out.append((("submit_admit", r.idx, rid), _rep_replace(
                    s, r, pending=tuple(p for p in r.pending if p != rid),
                    live=_live_set(r.live, (rid, Q, 0, 0)))))
        # -- board / tok / shed ------------------------------------------
        for r in alive:
            for (rid, st, ntok, blocks) in r.live:
                if st == Q and r.free > 0:
                    if (rid in s.uploads and r.role == "decode"
                            and cfg.gate_uploads):
                        continue    # boarding blocked until upload lands
                    out.append((("board", r.idx, rid), _rep_replace(
                        s, r, free=r.free - 1,
                        live=_live_set(r.live, (rid, A, ntok, blocks + 1)))))
        for r in alive:
            for (rid, st, ntok, blocks) in r.live:
                if st != A:
                    continue
                n2 = ntok + 1
                if n2 >= cfg.need_tokens:       # finishing token + done ack
                    s2 = _rep_replace(
                        s, r, free=r.free + blocks,
                        journal=r.journal + (("tok", rid), ("done", rid)),
                        live=_live_set(r.live, (rid, D, n2, 0)))
                    s2 = dataclasses.replace(
                        s2, delivered=_bump(s2.delivered, rid),
                        done_ct=_bump(s2.done_ct, rid))
                else:
                    s2 = _rep_replace(
                        s, r, journal=r.journal + (("tok", rid),),
                        live=_live_set(r.live, (rid, A, n2, blocks)))
                    s2 = dataclasses.replace(
                        s2, delivered=_bump(s2.delivered, rid))
                out.append((("tok", r.idx, rid), s2))
                if s.shed_budget > 0:
                    refund = blocks if cfg.refund_on_shed else 0
                    s3 = _rep_replace(
                        s, r, free=r.free + refund,
                        journal=r.journal + (("shed", rid),),
                        live=_live_set(r.live, (rid, S, ntok, 0)))
                    s3 = dataclasses.replace(
                        s3, shed_ct=_bump(s3.shed_ct, rid),
                        shed_budget=s.shed_budget - 1)
                    out.append((("shed", r.idx, rid), s3))
        # -- the three-step handoff --------------------------------------
        if s.handoff_budget > 0 and not s.limbo:
            for src in alive:
                if src.role != "prefill":
                    continue
                for (rid, st, ntok, blocks) in src.live:
                    if st != A or not 0 < ntok < cfg.need_tokens:
                        continue
                    dsts = [r for r in alive if r.role == "decode"
                            and r.idx != src.idx]
                    if not dsts:
                        continue
                    dst = dsts[0]
                    jr = src.journal
                    if (cfg.handoff_order == "tombstone-then-copy"
                            and not cfg.drop_tombstone):
                        jr = jr + (("handoff", rid, dst.idx),)
                    s2 = _rep_replace(s, src, free=src.free + blocks,
                                      live=_live_del(src.live, rid),
                                      journal=jr)
                    s2 = dataclasses.replace(
                        s2, handoff_budget=s.handoff_budget - 1,
                        limbo=s2.limbo + ((rid, ntok, src.idx, dst.idx,
                                           "released"),))
                    out.append((("handoff_begin", src.idx, rid), s2))
        for e in s.limbo:
            rid, ntok, src_i, dst_i, stage = e
            if stage == "released":
                dst = next((r for r in s.reps
                            if r.idx == dst_i and r.alive), None)
                if dst is None:             # original target died: re-route
                    dst = _adopt_target(s, ntok, exclude=(src_i,))
                if dst is None:
                    continue
                s2 = _adopt_onto(s, dst, rid, ntok, "handoff")
                if cfg.handoff_order == "tombstone-then-copy":
                    new_limbo = tuple(x for x in s.limbo if x != e)
                else:
                    new_limbo = tuple(
                        (rid, ntok, src_i, dst.idx, "adopted")
                        if x == e else x for x in s.limbo)
                s2 = dataclasses.replace(s2, limbo=new_limbo)
                out.append((("handoff_adopt", dst.idx, rid), s2))
            else:                           # "adopted": seal the tombstone
                src = next((r for r in s.reps
                            if r.idx == src_i and r.alive), None)
                s2 = dataclasses.replace(
                    s, limbo=tuple(x for x in s.limbo if x != e))
                if src is not None and not cfg.drop_tombstone:
                    s2 = _rep_replace(
                        s2, src,
                        journal=src.journal + (("handoff", rid, dst_i),))
                out.append((("handoff_seal", src_i, rid), s2))
        # -- crashes ------------------------------------------------------
        if s.crash_budget > 0:
            for r in alive:
                if len(alive) < 2:
                    break       # the fleet replaces its last replica; the
                    #             model keeps a fixed set (boundary)
                mid = next((e for e in s.limbo
                            if e[2] == r.idx and e[4] == "adopted"), None)
                if limbo_released or (s.limbo and mid is None):
                    # the real fleet's replica-kill interleaving points are
                    # fleet.tick (limbo empty) and fleet.handoff (between
                    # adopt and seal, source only)
                    continue
                label = (("crash", r.idx, "mid-handoff") if mid
                         else ("crash", r.idx))
                s2 = dataclasses.replace(_crash_rep(cfg, s, r),
                                         crash_budget=s.crash_budget - 1)
                out.append((label, s2))
            out.append((("crash_host",), dataclasses.replace(
                _crash_host(cfg, s), crash_budget=s.crash_budget - 1)))
        # -- upload landing / retire --------------------------------------
        for rid in s.uploads:
            out.append((("upload_lands", rid), dataclasses.replace(
                s, uploads=tuple(u for u in s.uploads if u != rid))))
        if cfg.allow_retire and not s.limbo:
            for r in alive:
                if len(alive) < 2:
                    break
                if r.pending or any(st in (Q, A) for _, st, _, _ in r.live):
                    continue    # drain-then-retire: only observed-idle
                out.append((("retire", r.idx), _rep_replace(
                    s, r, alive=False, journal=(), live=(), free=0)))
        return out
    return gen


# -- invariants -------------------------------------------------------------

def _invariants(cfg: ProtocolConfig):
    def double_serve(s: _State):
        homes: dict = {}
        for r in _alive(s):
            for (rid, st, ntok, _b) in r.live:
                if st in (Q, A):
                    if rid in homes:
                        return (f"rid {rid} is live on replicas "
                                f"{homes[rid]} and {r.idx} at once")
                    homes[rid] = r.idx
            for rid in r.pending:
                homes.setdefault(rid, r.idx)
        for rid in range(cfg.n_requests):
            if s.done_ct[rid] > 1:
                return f"rid {rid} completed {s.done_ct[rid]} times"
            if s.done_ct[rid] and (rid in homes or any(
                    e[0] == rid and e[4] == "released" for e in s.limbo)):
                return (f"rid {rid} already completed once yet is live "
                        f"again (re-adopted after done) — it will be "
                        f"served twice")
            if s.delivered[rid] > cfg.need_tokens:
                return (f"rid {rid} delivered {s.delivered[rid]} tokens, "
                        f"budget {cfg.need_tokens}")
        return None

    def lost_request(s: _State):
        for rid in range(s.submitted):
            if s.done_ct[rid] or s.shed_ct[rid]:
                continue
            if any(e[0] == rid for e in s.limbo):
                continue
            present = False
            for r in _alive(s):
                if rid in r.pending or _live_get(r, rid) is not None:
                    present = True
                    break
                folded, _errs = _fold(r.journal, cfg.need_tokens)
                if rid in folded:
                    present = True
                    break
            if not present and not any(e[0] == rid for e in s.limbo):
                return (f"rid {rid} was submitted, never finished, and is "
                        f"recoverable from no alive replica's journal — "
                        f"the request is lost")
        return None

    def refcount(s: _State):
        for r in _alive(s):
            held = sum(b for (_rid, _st, _n, b) in r.live)
            if r.free + held != cfg.blocks_per_replica or r.free < 0:
                return (f"replica {r.idx}: free={r.free} + held={held} != "
                        f"capacity={cfg.blocks_per_replica} — block "
                        f"refcounts do not conserve")
        return None

    def boarding_gate(s: _State):
        for r in _alive(s):
            if r.role != "decode":
                continue
            for (rid, st, _n, _b) in r.live:
                if st == A and rid in s.uploads:
                    return (f"rid {rid} is ACTIVE on decode replica "
                            f"{r.idx} while its host->HBM upload is still "
                            f"in flight — boarding read half-uploaded "
                            f"rows")
        return None

    def journal_grammar(s: _State):
        for r in _alive(s):
            _folded, errs = _fold(r.journal, cfg.need_tokens)
            if errs:
                return f"replica {r.idx} journal: {errs[0]}"
        return None

    return {"double-serve": double_serve, "lost-request": lost_request,
            "refcount": refcount, "boarding-gate": boarding_gate,
            "journal-grammar": journal_grammar}


# -- counterexample -> chaos drill ------------------------------------------

def export_fault_plan(violation: Violation) -> tuple:
    """``(plan_text, note)`` for a counterexample trace. ``plan_text`` is
    a ``FaultPlan.parse``-able schedule (the ``--chaos``/``SDML_CHAOS``
    grammar) covering every crash in the trace: plain crashes map to
    ``replica-kill@fleet.tick`` (the k-th crash carries ``after=k``, so a
    replay fires them in trace order, one fleet tick apart) and
    mid-handoff crashes to ``replica-kill@fleet.handoff``. ``None`` when
    the trace needs a whole-host crash — no real injection site can lose
    the fleet process's memory, which is exactly why that failure mode
    must be model-checked rather than drilled."""
    specs = []
    tick_crashes = 0
    for lab in violation.trace:
        if lab[0] == "crash_host":
            return None, ("counterexample requires a whole-host crash "
                          "between two journal appends; model-only (no "
                          "schedulable injection site)")
        if lab[0] != "crash":
            continue
        if len(lab) > 2:                    # mid-handoff: adopt/seal race
            specs.append(f"replica-kill@fleet.handoff,rank={lab[1]}")
        else:
            spec = f"replica-kill@fleet.tick,rank={lab[1]}"
            if tick_crashes:
                spec += f",after={tick_crashes}"
            specs.append(spec)
            tick_crashes += 1
    if not specs:
        return None, "counterexample contains no crash transitions"
    return ";".join(specs), f"{len(specs)} scheduled fault(s)"


def render_drill(violation: Violation, cfg: ProtocolConfig) -> str:
    """The exportable ``.chaos`` artifact: the abstract counterexample as
    comments, the replayable FaultPlan schedule as the payload line.
    ``load_drill`` reads it back; ``drill_coverage`` scans these files as
    a coverage source."""
    lines = ["# chaos drill exported by analysis/protocol.py "
             "(bounded model checker)",
             f"# invariant violated: {violation.invariant}",
             f"# model config: {cfg.summary()}",
             "# abstract counterexample (shortest trace):"]
    for i, lab in enumerate(violation.trace):
        head, *rest = lab
        lines.append(f"#   {i + 1}. {head}"
                     + (f"({', '.join(str(x) for x in rest)})"
                        if rest else ""))
    plan, note = export_fault_plan(violation)
    lines.append(f"# {note}")
    lines.append(plan if plan is not None else "# (no schedule)")
    return "\n".join(lines) + "\n"


def load_drill(path: str) -> str | None:
    """The FaultPlan schedule text inside an exported ``.chaos`` file
    (comment and blank lines stripped), or None for a model-only drill."""
    plans = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                plans.append(line)
    return ";".join(plans) if plans else None


# -- the checker entry point ------------------------------------------------

def check_protocol(cfg: ProtocolConfig | None = None,
                   max_states: int = 500_000) -> Report:
    """Explore every interleaving of the abstract fleet to ``cfg.depth``
    and render violations as ERROR findings (rule family ``protocol``),
    each carrying its counterexample trace and exported chaos schedule.
    The returned :class:`Report` additionally exposes ``exploration``
    (the :class:`~.statespace.Exploration`) and ``verdict`` (the
    depth-honest summary line) as attributes."""
    cfg = cfg or ProtocolConfig()
    result = explore(_initial(cfg), _transitions(cfg), _invariants(cfg),
                     depth=cfg.depth, max_states=max_states)
    findings = []
    for v in sorted(result.violations, key=lambda v: v.invariant):
        plan, note = export_fault_plan(v)
        hint = (f"replay the exported chaos schedule: SDML_CHAOS='{plan}'"
                if plan is not None else f"model-only: {note}")
        findings.append(Finding(
            rule=f"protocol.{v.invariant}", severity=Severity.ERROR,
            message=v.render(), where=f"model[{cfg.summary()}]",
            hint=hint))
    if result.truncated:
        findings.append(Finding(
            rule="protocol.state-cap", severity=Severity.ERROR,
            message=f"state cap {max_states} hit after {result.states} "
                    f"states — the run proves nothing at this bound",
            where=f"model[{cfg.summary()}]",
            hint="raise max_states or shrink the model"))
    report = Report(name="serve-protocol", findings=findings)
    report.exploration = result
    report.verdict = result.verdict(INVARIANTS)
    return report


# -- seeded-defect / clean-twin configs (analysis/fixtures.py wires these) --

#: the fleet as shipped: copy-then-tombstone handoff, recovery dedup,
#: gated uploads — must prove every invariant to depth 8 (the acceptance
#: bar: 2-pool fleet, 1 crash + 1 handoff budget)
CLEAN = ProtocolConfig()

#: the terminal handoff event dropped: a later source loss re-adopts a
#: request the decode pool already completed — double-serve
DROPPED_TOMBSTONE = ProtocolConfig(
    n_decode=2, n_requests=1, upload_rids=(), crash_budget=2,
    shed_budget=0, allow_retire=False, depth=11, drop_tombstone=True)

#: the pre-fix ordering: tombstone journaled on the source BEFORE the
#: destination's snap — a host crash between the appends loses the request
LEGACY_ORDER = ProtocolConfig(
    n_requests=1, upload_rids=(), shed_budget=0, allow_retire=False,
    depth=6, handoff_order="tombstone-then-copy")

#: shed skips the block refund — refcount conservation breaks
SKIPPED_REFUND = ProtocolConfig(
    n_requests=1, upload_rids=(), crash_budget=0, handoff_budget=0,
    allow_retire=False, depth=4, refund_on_shed=False)

#: boarding not gated on the in-flight host upload — a decode replica
#: reads half-uploaded K/V rows
UNGATED_BOARDING = ProtocolConfig(
    n_requests=1, upload_rids=(0,), crash_budget=0, shed_budget=0,
    allow_retire=False, depth=8, gate_uploads=False)
