"""Static verification of Pallas kernels — the ``kernel-*`` rule family.

The preflight gate (rules.py, bounds.py) historically treated every
``pallas_call`` as an opaque primitive: the repo's strongest correctness
tool was blind exactly where its riskiest code lives (the fused
paged-attention / flash-decode kernels the serve registry runs every
tick). This module opens the box. For each ``pallas_call`` equation the
:class:`~.bounds.BoundsWalker` encounters, four machine checks run over
the kernel's OWN metadata — grid, BlockSpec index maps, block shapes,
scratch avals — so the disciplines ``ops/paged_attention.py`` argues in
comments become proofs:

- **index-map bounds** (``kernel-oob.index-map`` ERROR /
  ``kernel-unproven.index-map`` WARNING): every BlockSpec index map is
  evaluated over the interval lattice with each grid axis seeded
  ``[0, grid[i]-1]`` and scalar-prefetch operands seeded from the caller's
  declared ``spec(...)`` contracts (block-table entries <= n_blocks,
  positions < max_len). A block index that can escape
  ``[0, ceil(dim/block)-1]`` is an out-of-bounds HBM window — the
  trash-block-0 and fetch-elision-clamp disciplines, machine-checked.
- **grid write races** (``kernel-race.parallel-overwrite`` ERROR /
  ``kernel-race.unproven-map`` WARNING): each output element must be
  written by at most one cell of every ``parallel`` grid axis. The output
  index map is evaluated affinely in the grid axes: a component with a
  nonzero integer coefficient in axis ``g`` is injective along ``g``; an
  axis no component reaches means every iteration rewrites the same
  window — exactly the property an autotuner mutation silently breaks.
  ``arbitrary`` axes are sequential and may legally revisit a window
  (the online-softmax accumulate discipline).
- **tiling lint** (``kernel-tile.pad-waste`` WARNING): Mosaic pads each
  block's trailing two dims up to the dtype's minimum tile
  (f32 ``(8,128)``, bf16 ``(16,128)``, int8/fp8 ``(32,128)``); a block
  whose natural layout pads >= 4x while the transposed layout would pad
  less than half that is the known small-head-dim hazard (dh in the lane
  slot) — fix the layout, don't eat the copy.
- **dtype lint** (``kernel-dtype-drift.low-precision-scratch`` WARNING):
  sub-f32 floating scratch in a kernel that carries state across grid
  iterations loses the online-softmax accumulation precision the dense
  path's f32 einsum promotion guarantees.

:func:`kernel_hbm_costs` additionally derives HBM traffic rows from the
kernels themselves (block bytes x the grid trips each index map actually
depends on), tagged ``kernel.kv_stream`` for table-indexed streams and
``kernel.io`` for the rest. ``programs.lint_serve`` reconciles the
kv_stream bytes against the hand-built ``HBMCost`` tick model
(``decode.kv_gather`` et al.) EXACTLY — the analyzer's claim that the
fused kernel deletes the 2x ``kv_attn_reread`` pass is computed from the
kernel's own BlockSpecs, not hand-asserted.

Everything here is metadata-only: no kernel body is executed, no TPU is
required, and the checks run identically on the CPU interpret-mode traces
the test suite uses.
"""

from __future__ import annotations

import math

import numpy as np

from simple_distributed_machine_learning_tpu.analysis.report import (
    Finding,
    HBMCost,
    Severity,
)
from simple_distributed_machine_learning_tpu.analysis.trace import (
    is_low_precision,
    source_line,
    subjaxprs,
)

_INF = math.inf
_LANE = 128

#: the rule families this module emits — CLI gates and CI drills key off it
KERNEL_FAMILIES = ("kernel-oob", "kernel-unproven", "kernel-race",
                   "kernel-tile", "kernel-dtype-drift", "kernel-hbm")

#: tile-lint thresholds: flag when natural-layout padding wastes >= 4x the
#: block's bytes AND the transposed layout would waste less than half that
_WASTE_FLAG = 4.0
_WASTE_RATIO = 2.0


# -- pallas_call metadata accessors ---------------------------------------

def _grid_mapping(eqn):
    return eqn.params.get("grid_mapping")


def _grid(gm) -> tuple[int, ...]:
    out = []
    for g in getattr(gm, "grid", ()) or ():
        try:
            out.append(int(g))
        except (TypeError, ValueError):
            out.append(1)       # dynamic grid dim: treat as unit (rare)
    return tuple(out)


def _dimension_semantics(eqn, n_axes: int) -> tuple[str, ...]:
    cp = eqn.params.get("compiler_params") or {}
    if not isinstance(cp, dict):
        cp = getattr(cp, "__dict__", {}) or {}
    mosaic = cp.get("mosaic") or {}
    if not isinstance(mosaic, dict):
        mosaic = getattr(mosaic, "__dict__", {}) or {}
    sem = mosaic.get("dimension_semantics")
    if not sem:
        return ("arbitrary",) * n_axes
    sem = tuple(str(s) for s in sem)
    return sem + ("arbitrary",) * (n_axes - len(sem))


def _counts(eqn, gm) -> tuple[int, int, int, int]:
    """(num_scalar_prefetch, num_inputs, num_outputs, num_scratch)."""
    n_sp = int(getattr(gm, "num_index_operands", 0) or 0)
    n_out = len(eqn.outvars)
    n_out = int(getattr(gm, "num_outputs", n_out) or n_out)
    bms = list(getattr(gm, "block_mappings", ()) or ())
    n_in = int(getattr(gm, "num_inputs", len(bms) - n_out)
               or (len(bms) - n_out))
    n_scr = int(getattr(gm, "num_scratch_operands", 0) or 0)
    return n_sp, n_in, n_out, n_scr


def _bm_parts(bm):
    """(block_shape, array_shape, dtype) of one BlockMapping, or None."""
    if bm is None:
        return None
    raw = getattr(bm, "block_shape", None)
    asd = getattr(bm, "array_shape_dtype", None)
    if raw is None or asd is None:
        return None
    shape = tuple(int(s) for s in asd.shape)
    block = []
    for d, b in enumerate(raw):
        try:
            block.append(int(b))
        except (TypeError, ValueError):
            # Mapped/None entry: the dim is carried whole (squeezed)
            block.append(1)
    return tuple(block), shape, np.dtype(asd.dtype)


def _index_map_jaxpr(bm):
    return getattr(bm, "index_map_jaxpr", None)


# -- index-map evaluation over the interval lattice ------------------------

def _eval_index_map(walker, closed, grid, sp_ivs):
    """Interval of each index-map output component, grid axes seeded
    ``[0, grid[i]-1]`` and scalar-prefetch refs seeded from the enclosing
    contract intervals."""
    from simple_distributed_machine_learning_tpu.analysis.bounds import (
        TOP,
        Interval,
    )
    jaxpr = getattr(closed, "jaxpr", closed)
    ivs = [Interval(0, max(0, g - 1)) for g in grid]
    ivs += list(sp_ivs)
    ivs = ivs[:len(jaxpr.invars)]
    ivs += [TOP] * (len(jaxpr.invars) - len(ivs))
    env = walker._sub_env(closed, ivs)
    walker._mute += 1           # inner gathers report as kernel-oob, not
    try:                        # scatter-bounds
        walker._walk(jaxpr, env)
    finally:
        walker._mute -= 1
    return [env.read(v) for v in jaxpr.outvars]


def _dep_axes(closed, n_grid: int):
    """Per-component set of grid axes each index-map output depends on
    (transitively; SMEM ``get``s propagate their index deps)."""
    jaxpr = getattr(closed, "jaxpr", closed)
    deps: dict[int, frozenset] = {}
    for i, v in enumerate(jaxpr.invars):
        deps[id(v)] = frozenset([i]) if i < n_grid else frozenset()

    def rd(atom):
        if hasattr(atom, "val"):
            return frozenset()
        return deps.get(id(atom), frozenset())

    for eqn in jaxpr.eqns:
        u = frozenset()
        for v in eqn.invars:
            u |= rd(v)
        for ov in eqn.outvars:
            deps[id(ov)] = u
    return [rd(v) for v in jaxpr.outvars]


def _affine_components(closed, n_grid: int):
    """Affine form ``(const, {axis: coef})`` of each output component, or
    ``None`` where the map is not affine in the grid axes (``get``, ``min``
    clamps, ...). A nonzero integer coefficient proves injectivity along
    that axis — the write-race certificate."""
    jaxpr = getattr(closed, "jaxpr", closed)
    aff: dict[int, tuple | None] = {}
    for i, v in enumerate(jaxpr.invars):
        aff[id(v)] = (0.0, {i: 1.0}) if i < n_grid else None

    def rd(atom):
        if hasattr(atom, "val"):
            try:
                arr = np.asarray(atom.val)
                if arr.size == 1:
                    return (float(arr.reshape(())), {})
            except (TypeError, ValueError):
                pass
            return None
        return aff.get(id(atom))

    def comb(x, y, sy):
        if x is None or y is None:
            return None
        c = x[0] + sy * y[0]
        coefs = dict(x[1])
        for k, v in y[1].items():
            coefs[k] = coefs.get(k, 0.0) + sy * v
        return (c, {k: v for k, v in coefs.items() if v})

    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        ins = [rd(v) for v in eqn.invars]
        out = None
        if prim in ("add", "add_any") and len(ins) == 2:
            out = comb(ins[0], ins[1], 1.0)
        elif prim == "sub" and len(ins) == 2:
            out = comb(ins[0], ins[1], -1.0)
        elif prim == "mul" and len(ins) == 2:
            for a, b in ((ins[0], ins[1]), (ins[1], ins[0])):
                if a is not None and b is not None and not b[1]:
                    out = (a[0] * b[0], {k: v * b[0]
                                         for k, v in a[1].items() if v})
                    break
        elif prim == "neg" and ins:
            out = comb((0.0, {}), ins[0], -1.0)
        elif prim in ("convert_element_type", "copy", "squeeze", "reshape",
                      "broadcast_in_dim", "stop_gradient") and ins:
            out = ins[0]
        for ov in eqn.outvars:
            aff[id(ov)] = out
    return [rd(v) for v in jaxpr.outvars]


# -- the checks ------------------------------------------------------------

def check_pallas_call(walker, eqn, ins, env):
    """BoundsWalker transfer function for ``pallas_call``: run the four
    kernel checks, emitting through the walker, and return TOP for the
    kernel's outputs (attention math itself is not interval-tracked)."""
    from simple_distributed_machine_learning_tpu.analysis.bounds import (
        TOP,
        _index_verdict,
    )
    n = len(eqn.outvars)
    gm = _grid_mapping(eqn)
    if gm is None:
        return [TOP] * n
    grid = _grid(gm)
    sem = _dimension_semantics(eqn, len(grid))
    n_sp, n_in, n_out, n_scr = _counts(eqn, gm)
    bms = list(getattr(gm, "block_mappings", ()) or ())
    sp_ivs = list(ins[:n_sp])
    src = source_line(eqn)
    mute = walker._mute > 0

    def emit(f):
        if not mute:
            walker._emit(f)

    for i, bm in enumerate(bms):
        parts = _bm_parts(bm)
        closed = _index_map_jaxpr(bm)
        if parts is None or closed is None:
            continue
        block, shape, dtype = parts
        is_out = i >= n_in
        what = (f"output {i - n_in}" if is_out else f"input {i}")

        # (1) index-map bounds proof
        comps = _eval_index_map(walker, closed, grid, sp_ivs)
        for k, iv in enumerate(comps):
            if k >= len(block) or k >= len(shape):
                continue
            n_blocks_k = max(1, -(-shape[k] // max(1, block[k])))
            allowed_hi = n_blocks_k - 1
            verdict = _index_verdict(iv, allowed_hi)
            if verdict == "ok":
                continue
            lo = "-inf" if iv.lo == -_INF else int(iv.lo)
            hi = "inf" if iv.hi == _INF else int(iv.hi)
            if verdict == "oob":
                emit(Finding(
                    rule="kernel-oob.index-map", severity=Severity.ERROR,
                    message=(f"pallas_call {what} index map component {k} "
                             f"has range [{lo}, {hi}] but the backing "
                             f"buffer (shape {shape}, block {block}) only "
                             f"addresses block indices [0, {allowed_hi}] "
                             f"— the kernel would stream a window outside "
                             f"the buffer"),
                    where=src,
                    hint="clamp the index map (the fetch-elision "
                         "jnp.minimum discipline) or tighten the declared "
                         "spec(...) contract on the scalar-prefetch "
                         "operand feeding it"))
            else:
                emit(Finding(
                    rule="kernel-unproven.index-map",
                    severity=Severity.WARNING,
                    message=(f"pallas_call {what} index map component {k} "
                             f"could not be bounded (range [{lo}, {hi}] vs "
                             f"addressable [0, {allowed_hi}]) — the block "
                             f"stream is only as safe as the undeclared "
                             f"operand feeding it"),
                    where=src,
                    hint="declare the scalar-prefetch operand's range via "
                         "analysis.bounds.spec (block tables <= n_blocks, "
                         "positions < max_len) so the proof closes"))

        # (2) grid write-race detection (outputs only)
        if is_out:
            aff = _affine_components(closed, len(grid))
            deps = _dep_axes(closed, len(grid))
            for g, gsize in enumerate(grid):
                if gsize <= 1 or sem[g] != "parallel":
                    continue    # arbitrary axes are sequential: revisiting
                    # a window is the accumulate discipline, not a race
                covered = any(a is not None and a[1].get(g)
                              for a in aff)
                reaches = any(g in d for d in deps)
                if covered:
                    continue
                if reaches:
                    emit(Finding(
                        rule="kernel-race.unproven-map",
                        severity=Severity.WARNING,
                        message=(f"pallas_call {what} index map depends on "
                                 f"parallel grid axis {g} non-affinely — "
                                 f"injectivity (each output window written "
                                 f"by one cell) could not be proven"),
                        where=src,
                        hint="make the output map affine in the parallel "
                             "axis, or mark the axis 'arbitrary' if it "
                             "deliberately accumulates"))
                else:
                    emit(Finding(
                        rule="kernel-race.parallel-overwrite",
                        severity=Severity.ERROR,
                        message=(f"pallas_call {what} index map ignores "
                                 f"parallel grid axis {g} (size {gsize}): "
                                 f"every cell of that axis writes the SAME "
                                 f"output window concurrently — last "
                                 f"writer wins, nondeterministically"),
                        where=src,
                        hint="index the output block by the parallel axis, "
                             "or declare the axis 'arbitrary' in "
                             "dimension_semantics so Mosaic serializes it "
                             "for an accumulate discipline"))

        # (3) tiling lint: Mosaic pads the trailing two dims to the
        # dtype's minimum tile; compare against the transposed layout
        if len(block) >= 2:
            sub, lane = block[-2], block[-1]
            if sub > 0 and lane > 0:
                st, lt = _min_tile(dtype)
                waste = (_roundup(sub, st) * _roundup(lane, lt)) / (sub * lane)
                waste_t = (_roundup(lane, st) * _roundup(sub, lt)) / (sub * lane)
                if waste >= _WASTE_FLAG and waste >= _WASTE_RATIO * waste_t:
                    emit(Finding(
                        rule="kernel-tile.pad-waste",
                        severity=Severity.WARNING,
                        message=(f"pallas_call {what} block {block} "
                                 f"({dtype.name}) pads to the "
                                 f"({st},{lt}) minimum tile at {waste:.0f}x "
                                 f"its size — transposing the trailing "
                                 f"dims would pad only {waste_t:.0f}x (the "
                                 f"small-head-dim-in-the-lane-slot "
                                 f"hazard)"),
                        where=src,
                        hint="swap the trailing block dims (pack the "
                             "small dim into sublanes, the long one into "
                             "lanes) — ops/paged_attention.py's 'packed' "
                             "layout is the reference fix"))

    # (4) dtype lint: sub-f32 floating scratch accumulators
    body = eqn.params.get("jaxpr")
    body_jaxpr = getattr(body, "jaxpr", body)
    if body_jaxpr is not None and n_scr:
        for v in list(body_jaxpr.invars)[-n_scr:]:
            aval = getattr(v, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is None:
                continue
            if np.dtype(dt).kind == "f" and is_low_precision(dt):
                emit(Finding(
                    rule="kernel-dtype-drift.low-precision-scratch",
                    severity=Severity.WARNING,
                    message=(f"pallas_call carries "
                             f"{np.dtype(dt).name} scratch "
                             f"{tuple(getattr(aval, 'shape', ()))} across "
                             f"grid iterations — online-softmax state "
                             f"accumulated below f32 drifts from the "
                             f"dense path's einsum promotion (the "
                             f"bit-exactness contract)"),
                    where=src,
                    hint="allocate the accumulator/l/m scratch as "
                         "pltpu.VMEM(..., jnp.float32) and cast only on "
                         "the final store"))
    return [TOP] * n


def _roundup(x: int, q: int) -> int:
    return -(-x // q) * q


def _min_tile(dtype: np.dtype) -> tuple[int, int]:
    """Mosaic minimum (sublane, lane) tile for a dtype (pallas guide:
    f32 (8,128), bf16/f16 (16,128), int8/fp8 (32,128))."""
    size = np.dtype(dtype).itemsize
    if size >= 4:
        return 8, _LANE
    if size == 2:
        return 16, _LANE
    return 32, _LANE


# -- kernel-derived HBM cost rows -----------------------------------------

def _uses_scalar_prefetch(closed, n_grid: int) -> bool:
    """True when the index map dereferences a scalar-prefetch ref (a
    ``get`` on an invar past the grid axes) — the table-indexed K/V
    stream signature."""
    jaxpr = getattr(closed, "jaxpr", closed)
    refs = {id(v) for v in list(jaxpr.invars)[n_grid:]}
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "get" and eqn.invars \
                and id(eqn.invars[0]) in refs:
            return True
    return False


def kernel_hbm_costs(closed_jaxpr, program: str = "") -> list[HBMCost]:
    """Derive HBM bytes-per-run rows from every ``pallas_call`` in a traced
    program: each BlockMapping moves ``prod(block) * itemsize`` bytes once
    per distinct index-map value, i.e. per cell of the grid axes the map
    actually depends on (axes it ignores revisit the same window — Mosaic
    elides the copy, and so does this model). Streams whose index map
    dereferences a scalar-prefetch operand (the block-table signature) are
    tagged ``kernel.kv_stream``; everything else ``kernel.io``. Enclosing
    ``scan`` trip counts multiply through."""
    kv = 0
    io = 0
    calls = 0

    def walk(jaxpr, trips):
        nonlocal kv, io, calls
        for eqn in jaxpr.eqns:
            if eqn.primitive.name == "pallas_call":
                gm = _grid_mapping(eqn)
                if gm is None:
                    continue
                calls += 1
                grid = _grid(gm)
                n_sp, n_in, n_out, _ = _counts(eqn, gm)
                for i, bm in enumerate(getattr(gm, "block_mappings", ())
                                       or ()):
                    parts = _bm_parts(bm)
                    closed = _index_map_jaxpr(bm)
                    if parts is None or closed is None:
                        continue
                    block, _shape, dtype = parts
                    deps = frozenset().union(
                        *_dep_axes(closed, len(grid))) \
                        if grid else frozenset()
                    t = trips
                    for g in deps:
                        if g < len(grid):
                            t *= grid[g]
                    nbytes = int(np.prod(block)) * dtype.itemsize * t
                    if i < n_in and _uses_scalar_prefetch(closed,
                                                          len(grid)):
                        kv += nbytes
                    else:
                        io += nbytes
                continue
            mult = 1
            if eqn.primitive.name == "scan":
                mult = int(eqn.params.get("length", 1) or 1)
            for _key, _i, sub in subjaxprs(eqn):
                walk(getattr(sub, "jaxpr", sub), trips * mult)

    walk(getattr(closed_jaxpr, "jaxpr", closed_jaxpr), 1)
    if not calls:
        return []
    rows = [HBMCost(
        op="kernel.kv_stream", program=program, bytes_per_tick=kv,
        note=f"{calls} pallas_call(s): table-indexed K/V blocks x the "
             f"grid trips their index maps depend on — derived from the "
             f"kernels' own BlockSpecs")]
    if io:
        rows.append(HBMCost(
            op="kernel.io", program=program, bytes_per_tick=io,
            note="non-table kernel operand/output blocks x grid trips"))
    return rows
