"""Structured findings for the static analyzer.

A lint pass over a traced step produces :class:`Finding`s — (severity, rule
id, provenance, message, fix hint) — plus a per-collective ICI cost table
(:class:`CollectiveCost`). :class:`Report` aggregates both and owns the
exit-code policy: ``ok(fail_on)`` is what the CLI / ``--lint`` preflights
key off.

Rule ids are ``family.check`` — the family is the coarse bucket the ISSUE /
docs tables use (``ppermute-deadlock``, ``unreduced-gradient``, ``mesh-axis``,
``dtype-drift``, ``donation``), the check names the specific defect.
"""

from __future__ import annotations

import dataclasses
import enum


class Severity(enum.IntEnum):
    """Ordered so ``max()`` over findings is the report's worst finding."""
    INFO = 1
    WARNING = 2
    ERROR = 3

    def __str__(self) -> str:  # "ERROR" not "Severity.ERROR" in reports
        return self.name


@dataclasses.dataclass(frozen=True)
class Finding:
    """One defect (or hazard) the analyzer can point at an equation.

    ``where`` is the provenance path — the chain of enclosing call /
    control-flow equations down to the offending one (plus the user source
    line when jax recorded one) — so a finding inside
    ``shard_map/scan/cond[branch 2]`` reads as exactly that.
    """
    rule: str                 # "family.check", e.g. "ppermute-deadlock.partial-perm"
    severity: Severity
    message: str              # what is wrong, with the concrete axis/shape/dtype
    where: str = ""           # eqn provenance path + source line
    hint: str = ""            # how to fix it

    @property
    def family(self) -> str:
        return self.rule.split(".", 1)[0]

    def format(self) -> str:
        loc = f"\n    at {self.where}" if self.where else ""
        fix = f"\n    fix: {self.hint}" if self.hint else ""
        return f"[{self.severity}] {self.rule}: {self.message}{loc}{fix}"


@dataclasses.dataclass(frozen=True)
class CollectiveCost:
    """Bytes-over-ICI estimate for one collective equation.

    ``bytes_per_call`` is the operand payload; ``ici_bytes`` applies the
    standard ring-algorithm traffic factor for the collective kind over an
    axis group of ``group_size`` devices (psum ``2(n-1)/n``, all_gather
    ``n-1`` x shard, reduce_scatter / all_to_all ``(n-1)/n``, ppermute
    ``1``); ``trips`` is the static trip count of enclosing scans, so the
    table ranks collectives by what they actually move per step.
    """
    prim: str
    axes: tuple[str, ...]
    group_size: int
    bytes_per_call: int
    ici_bytes: int            # bytes_per_call x traffic factor, per trip
    trips: int                # product of enclosing scan lengths
    where: str = ""

    @property
    def total_bytes(self) -> int:
        return self.ici_bytes * self.trips


@dataclasses.dataclass(frozen=True)
class HBMCost:
    """HBM traffic estimate for one serving-program memory stream.

    The serving twin of :class:`CollectiveCost`: where a train step's
    dominant off-chip traffic is collective bytes over ICI, a decode tick's
    is K/V cache bytes over HBM — the paged gather reads every table block
    of every slot each tick, and the scatter lands one position per slot.
    ``bytes_per_tick`` is the static program cost (shapes are static, so it
    does not vary with occupancy); ``bytes_resident`` models what occupancy
    actually PINS (cross-checked against the pool's
    ``serve_kv_bytes_resident`` gauge in tests)."""
    op: str                   # e.g. "decode.kv_gather"
    program: str              # registry program the stream belongs to
    bytes_per_tick: int
    note: str = ""


class Report:
    """The result of one ``analyze()`` run: findings + ICI cost table
    (+ the serving HBM-bytes-per-tick table when the registry adds one)."""

    def __init__(self, name: str = "", findings=None, costs=None, hbm=None):
        self.name = name
        self.findings: list[Finding] = list(findings or [])
        self.costs: list[CollectiveCost] = list(costs or [])
        self.hbm: list[HBMCost] = list(hbm or [])

    # -- aggregation ------------------------------------------------------

    def extend(self, other: "Report") -> "Report":
        self.findings.extend(other.findings)
        self.costs.extend(other.costs)
        self.hbm.extend(other.hbm)
        return self

    @property
    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity >= Severity.ERROR]

    @property
    def warnings(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == Severity.WARNING]

    def by_family(self, family: str) -> list[Finding]:
        return [f for f in self.findings if f.family == family]

    def ok(self, fail_on: str = "error") -> bool:
        """``fail_on='error'``: only ERROR findings gate (the preflight
        default — dtype-drift warnings on a deliberate bf16 run must not
        block the launch). ``fail_on='warning'``: any WARNING+ gates (the
        fixture/CI-demonstration mode)."""
        threshold = (Severity.WARNING if fail_on == "warning"
                     else Severity.ERROR)
        return all(f.severity < threshold for f in self.findings)

    # -- formatting -------------------------------------------------------

    def format(self, costs: bool = True, top: int = 8) -> str:
        head = f"analysis: {self.name}" if self.name else "analysis"
        lines = [head]
        if not self.findings:
            lines.append("  no findings: clean")
        for f in sorted(self.findings, key=lambda f: -f.severity):
            lines.extend("  " + ln for ln in f.format().splitlines())
        if costs and self.costs:
            lines.append("  bytes over ICI per step (top collectives):")
            ranked = sorted(self.costs, key=lambda c: -c.total_bytes)
            for c in ranked[:top]:
                axes = ",".join(c.axes) or "-"
                lines.append(
                    f"    {c.prim:<16} axis={axes:<8} group={c.group_size} "
                    f"x{c.trips:<5} {_human_bytes(c.total_bytes):>10}  "
                    f"{c.where}")
            if len(ranked) > top:
                rest = sum(c.total_bytes for c in ranked[top:])
                lines.append(f"    ... {len(ranked) - top} more collectives, "
                             f"{_human_bytes(rest)}")
            total = sum(c.total_bytes for c in self.costs)
            lines.append(f"    total: {_human_bytes(total)}")
        if costs and self.hbm:
            lines.append("  HBM bytes per serve tick (KV-cache streams):")
            for h in sorted(self.hbm, key=lambda h: -h.bytes_per_tick):
                note = f"  ({h.note})" if h.note else ""
                lines.append(
                    f"    {h.op:<24} {_human_bytes(h.bytes_per_tick):>10}  "
                    f"{h.program}{note}")
            lines.append(
                f"    total: "
                f"{_human_bytes(sum(h.bytes_per_tick for h in self.hbm))}")
        return "\n".join(lines)


def _human_bytes(n: int) -> str:
    size = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if size < 1024 or unit == "GiB":
            return f"{size:.1f}{unit}" if unit != "B" else f"{int(size)}B"
        size /= 1024
    return f"{size:.1f}GiB"
