"""Seeded-defect fixtures: programs the analyzer MUST flag (and clean twins
it must not).

Each fixture is a tiny, deliberately broken distributed step built the same
way the engine builds real ones (``compat.shard_map`` over a named mesh) —
one per rule family, mirroring the ways a hand-written stage fn actually
goes wrong: a ring permutation that skips the wraparound hop, a
data-parallel update that forgets the gradient all-reduce, a collective
over a misspelled axis, a bf16 running sum, a buffer read after donation.

``tests/test_analysis.py`` asserts every defect fixture produces a finding
of its family and every ``defect=False`` twin analyzes clean; the CLI's
``--fixtures`` self-test mode re-runs the same contract from the command
line (non-zero exit when any fixture misbehaves), which is what the CI lint
job invokes.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from simple_distributed_machine_learning_tpu.analysis import Report, analyze


@dataclasses.dataclass(frozen=True)
class Fixture:
    name: str
    family: str              # rule family expected in the findings
    defect: bool             # True: must flag; False: must be clean
    description: str
    build: Callable[[], Report]


def _devs(n: int):
    import jax
    devices = jax.devices()
    if len(devices) < n:
        raise SystemExit(
            f"fixture needs {n} devices, have {len(devices)} (run under "
            f"xla_force_host_platform_device_count)")
    import numpy as np
    return np.array(devices[:n])


def _mesh(n: int, axis: str = "data"):
    from jax.sharding import Mesh
    return Mesh(_devs(n), (axis,))


# -- ppermute-deadlock: a ring missing its wraparound hop ------------------

def partial_ppermute() -> Report:
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from simple_distributed_machine_learning_tpu.parallel.compat import (
        shard_map,
    )

    mesh = _mesh(4)

    def shift(x):
        # BUG: [(j, j+1)] without the (3, 0) wraparound — not a bijection;
        # device 0 receives from nobody, device 3's send has no pair
        return lax.ppermute(x, "data", [(0, 1), (1, 2), (2, 3)])

    fn = jax.jit(shard_map(shift, mesh=mesh, in_specs=P("data"),
                           out_specs=P("data"), check_vma=False))
    x = jax.ShapeDtypeStruct((8, 16), jnp.float32)
    return analyze(fn, x, mesh=mesh, name="fixture:partial_ppermute")


# -- unreduced-gradient: data-parallel SGD missing the grad psum -----------

def _dp_sgd_report(sync: bool, name: str) -> Report:
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from simple_distributed_machine_learning_tpu.parallel.compat import (
        shard_map,
    )

    mesh = _mesh(4)

    def step(w, x):
        def loss(w):
            return jnp.mean((x @ w) ** 2)
        g = jax.grad(loss)(w)
        if sync:
            g = lax.pmean(g, "data")
        # else BUG: each data shard applies only ITS batch shard's gradient
        # while the out_spec claims the replicas stay identical
        return w - 0.1 * g

    # check_vma=False: the engines this analyzer preflights run check-free
    # (old-jax compat), so the missing reduction must be caught HERE, not by
    # modern jax's own trace-time checker
    fn = jax.jit(shard_map(step, mesh=mesh, in_specs=(P(), P("data")),
                           out_specs=P(), check_vma=False))
    w = jax.ShapeDtypeStruct((16, 4), jnp.float32)
    x = jax.ShapeDtypeStruct((8, 16), jnp.float32)
    return analyze(fn, w, x, mesh=mesh, name=name)


def dropped_grad_sync() -> Report:
    return _dp_sgd_report(False, "fixture:dropped_grad_sync")


def clean_grad_sync() -> Report:
    return _dp_sgd_report(True, "fixture:clean_grad_sync")


# -- mesh-axis: collective over an axis the mesh does not bind -------------

def wrong_axis_name() -> Report:
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from simple_distributed_machine_learning_tpu.parallel.compat import (
        shard_map,
    )

    mesh = _mesh(4)          # axes: ('data',)

    def reduce(x):
        # BUG: the mesh has no 'model' axis — a TP stage fn pasted into a
        # data-parallel launch
        return lax.psum(x, "model")

    fn = jax.jit(shard_map(reduce, mesh=mesh, in_specs=P("data"),
                           out_specs=P("data"), check_vma=False))
    x = jax.ShapeDtypeStruct((8, 16), jnp.float32)
    return analyze(fn, x, mesh=mesh, name="fixture:wrong_axis_name")


# -- dtype-drift: bf16 psum into a bf16 scan accumulator -------------------

def bf16_psum_accumulator() -> Report:
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from simple_distributed_machine_learning_tpu.parallel.compat import (
        shard_map,
    )

    mesh = _mesh(4)

    def accumulate(xs):
        def body(acc, x_t):
            # BUG x2: the cross-device reduction runs in bf16, and the
            # running sum is carried in bf16 — increments vanish once the
            # sum outgrows 256x the step size
            return acc + jnp.sum(lax.psum(x_t, "data"), axis=0), ()

        acc0 = jnp.zeros((16,), jnp.bfloat16)
        acc, _ = lax.scan(body, acc0, xs)
        return acc

    fn = jax.jit(shard_map(accumulate, mesh=mesh, in_specs=P(None, "data"),
                           out_specs=P(), check_vma=False))
    xs = jax.ShapeDtypeStruct((32, 8, 16), jnp.bfloat16)
    return analyze(fn, xs, mesh=mesh, name="fixture:bf16_psum_accumulator")


# -- donation: buffer read after being donated -----------------------------

def read_after_donate() -> Report:
    import functools

    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=(0,))
    def update(buf, grads):
        return buf - 0.1 * grads

    def two_phase(buf, grads):
        new_buf = update(buf, grads)
        # BUG: the old buffer was donated to update() — its pages may
        # already back new_buf; this read is use-after-free on device
        drift = jnp.sum(new_buf - buf)
        return new_buf, drift

    b = jax.ShapeDtypeStruct((1024,), jnp.float32)
    g = jax.ShapeDtypeStruct((1024,), jnp.float32)
    return analyze(two_phase, b, g, name="fixture:read_after_donate")


# -- scatter-bounds: a block-table index past the pool ---------------------

def _tiny_serve():
    """One tiny GPT build + paged geometry shared by the serve fixtures."""
    import jax

    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )
    cfg = GPTConfig(vocab=16, seq_len=16, d_model=8, n_heads=2, n_layers=1)
    stages, _, _ = make_gpt_stages(jax.random.key(0), cfg, 1)
    return cfg, stages


def oob_block_table() -> Report:
    """The paged decode step handed a block-table contract that can reach
    one past the pool (what an engine WITHOUT slots.py's invariant-guarded
    tables could feed it): the K/V scatter provably lands outside
    ``n_blocks + 1`` — another request's blocks, silently."""
    import jax
    import numpy as np

    from simple_distributed_machine_learning_tpu.analysis import spec
    from simple_distributed_machine_learning_tpu.models.gpt import (
        make_paged_decode_step,
    )
    cfg, stages = _tiny_serve()
    S, ml, bs = 2, 12, 4
    NB, n_blocks = 3, 6
    step = make_paged_decode_step(stages, cfg, ml, bs)
    params = [jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), s.params)
        for s in stages]
    kc = jax.ShapeDtypeStruct((1, n_blocks + 1, 2, bs, 4), np.float32)
    return analyze(
        step, params, kc, kc,
        spec((S,), np.int32, 0, cfg.vocab - 1),
        spec((S,), np.int32, 0, ml - 1),
        # BUG: entries may reach n_blocks + 1 — one past the last block
        spec((S, NB), np.int32, 0, n_blocks + 1),
        jax.ShapeDtypeStruct((S, 2), np.uint32),
        jax.ShapeDtypeStruct((S,), np.float32),
        spec((S,), np.int32, 0, cfg.vocab),
        jax.ShapeDtypeStruct((S,), np.float32),
        name="fixture:oob_block_table")


# -- donation v2: a CoW copy reading buffers the prefill donated -----------

def _cow_tick_report(threaded: bool, name: str) -> Report:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from simple_distributed_machine_learning_tpu.analysis import spec
    from simple_distributed_machine_learning_tpu.models.gpt import (
        make_paged_block_copy,
        make_paged_prefill_chunk,
    )
    cfg, stages = _tiny_serve()
    ml, bs, n_blocks = 12, 4, 6
    chunk = make_paged_prefill_chunk(stages, cfg, ml, bs)
    copy = make_paged_block_copy()

    def tick(params, kc, vc, tokens, p0, table, kd, t, k_, p_):
        kc2, vc2, tok, _kd2 = chunk(params, kc, vc, tokens, p0, table, kd,
                                    t, k_, p_)
        if threaded:
            kc3, vc3 = copy(kc2, vc2, jnp.int32(2), jnp.int32(1))
        else:
            # BUG: the copy reads the PRE-PREFILL pool buffers — the chunk
            # call already donated them, so their pages may back kc2/vc2
            # by now; this is the cross-program read-after-donate
            kc3, vc3 = copy(kc, vc, jnp.int32(2), jnp.int32(1))
        return kc3, vc3, tok

    params = [jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), s.params)
        for s in stages]
    kc = jax.ShapeDtypeStruct((1, n_blocks + 1, 2, bs, 4), np.float32)
    return analyze(
        tick, params, kc, kc,
        spec((1, 3), np.int32, 0, cfg.vocab - 1),
        spec((), np.int32, 0, ml - 4),
        spec((3,), np.int32, 0, n_blocks),
        jax.ShapeDtypeStruct((2,), np.uint32),
        jax.ShapeDtypeStruct((), np.float32),
        spec((), np.int32, 0, cfg.vocab),
        jax.ShapeDtypeStruct((), np.float32),
        name=name)


def cow_read_after_donate() -> Report:
    return _cow_tick_report(False, "fixture:cow_read_after_donate")


def clean_cow_tick() -> Report:
    return _cow_tick_report(True, "fixture:clean_cow_tick")


# -- retrace-explosion: a builder that forgets the build cache -------------

def unmemoized_retrace() -> Report:
    """A decode builder that reconstructs its jitted program on every call
    instead of routing through ``_DECODE_BUILD_CACHE`` — each engine/test
    would re-trace and re-compile an identical program."""
    import jax

    from simple_distributed_machine_learning_tpu.analysis.programs import (
        check_builder_memo,
    )

    def bad_make_decode():
        @jax.jit
        def decode(tok):
            return tok + 1
        return decode

    return Report(name="fixture:unmemoized_retrace",
                  findings=check_builder_memo("bad_make_decode",
                                              bad_make_decode))


# -- sharded-state: a ZeRO shard consumed without its gather ---------------

def _zero1_report(reduced: bool, name: str) -> Report:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from simple_distributed_machine_learning_tpu.analysis import spec
    from simple_distributed_machine_learning_tpu.parallel.compat import (
        shard_map,
    )

    mesh = _mesh(4)

    def step(w, m, g):
        # ZeRO-style: m is each device's OWN opt-state shard carried in a
        # replicated-shape buffer (the check_rep=False idiom — no in_spec
        # can express it, which is what analysis.spec(vary=...) declares)
        m2 = 0.9 * m + g
        if reduced:
            m2 = lax.pmean(m2, "data")   # gather/reduce before the update
        # else BUG: each device updates the replicated params with ITS
        # shard's momentum — params silently diverge across the axis
        return w - 0.1 * m2, m2

    fn = jax.jit(shard_map(step, mesh=mesh, in_specs=(P(), P(), P()),
                           out_specs=(P(), P()), check_vma=False))
    w = jax.ShapeDtypeStruct((16, 4), jnp.float32)
    g = jax.ShapeDtypeStruct((16, 4), jnp.float32)
    m = spec((16, 4), np.float32, vary=("data",))
    return analyze(fn, w, m, g, mesh=mesh, name=name)


def dropped_gather_before_use() -> Report:
    return _zero1_report(False, "fixture:dropped_gather_before_use")


def clean_gather_before_use() -> Report:
    return _zero1_report(True, "fixture:clean_gather_before_use")


# -- kernel-*: seeded Pallas kernel defects (analysis/kernels.py) ----------

def _paged_kernel_report(table_hi_slack: int, layout: str,
                         dh: int, bs: int, name: str) -> Report:
    """Trace the REAL fused paged-attention kernel on synthetic shapes with
    a block-table contract reaching ``n_blocks + table_hi_slack`` — slack 0
    is the slots.py invariant (clean), slack 1 is a table that can point
    one block past the pool (kernel-oob)."""
    import jax
    import numpy as np

    from simple_distributed_machine_learning_tpu.analysis import (
        analyze,
        spec,
    )
    from simple_distributed_machine_learning_tpu.ops.paged_attention import (
        paged_attention,
    )
    S, H, K, NB, n_blocks = 2, 2, 1, 3, 5

    def attend(q, kc, vc, tables, qpos):
        return paged_attention(q, kc, vc, tables, qpos, block_size=bs,
                               _layout=layout)

    q = jax.ShapeDtypeStruct((S, H, K, dh), np.float32)
    kv = jax.ShapeDtypeStruct((n_blocks + 1, H, bs, dh), np.float32)
    return analyze(
        attend, q, kv, kv,
        spec((S, NB), np.int32, 0, n_blocks + table_hi_slack),
        spec((S, K), np.int32, 0, NB * bs - 1),
        name=name)


def kernel_oob_index_map() -> Report:
    """The fused kernel's K/V index map fed a block-table contract that can
    reach one past the pool: the BlockSpec would stream a window outside
    the backing buffer."""
    return _paged_kernel_report(1, "natural", dh=8, bs=4,
                                name="fixture:kernel_oob_index_map")


def kernel_clean_paged() -> Report:
    """The same kernel under the slots.py table invariant — every index
    map proves in bounds (must be fully clean)."""
    return _paged_kernel_report(0, "natural", dh=8, bs=4,
                                name="fixture:kernel_clean_paged")


def kernel_bad_tile() -> Report:
    """The pre-fix small-head-dim layout at a TPU-realistic block size:
    dh=4 in the 128-lane slot pads every K/V block 32x (the ROADMAP #2
    hazard the 'packed' layout fixes)."""
    return _paged_kernel_report(0, "natural", dh=4, bs=128,
                                name="fixture:kernel_bad_tile")


def kernel_packed_tile() -> Report:
    """The fixed layout for the same shapes: block positions in the lane
    slot, the small head dim padded <= 2x into sublanes (must be clean)."""
    return _paged_kernel_report(0, "packed", dh=4, bs=128,
                                name="fixture:kernel_packed_tile")


def _grid_kernel_report(racing: bool, scratch_dtype, name: str) -> Report:
    """A hand-built pallas_call over a parallel grid axis — ``racing``
    collapses every cell's output window onto block 0 (what an autotuner
    mutation that drops the output index silently does)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from simple_distributed_machine_learning_tpu.analysis import analyze
    from simple_distributed_machine_learning_tpu.ops.flash_attention import (
        _compiler_params,
        pltpu,
    )

    def kern(x_ref, o_ref, acc_ref):
        acc_ref[...] = x_ref[...].astype(acc_ref.dtype) * 2
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)

    out_idx = (lambda i: (0, 0)) if racing else (lambda i: (i, 0))

    def fn(x):
        return pl.pallas_call(
            kern,
            grid=(4,),
            in_specs=[pl.BlockSpec((1, 128), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((1, 128), out_idx),
            out_shape=jax.ShapeDtypeStruct((4, 128), jnp.float32),
            scratch_shapes=[pltpu.VMEM((1, 128), scratch_dtype)],
            compiler_params=_compiler_params("parallel"),
            interpret=True,
        )(x)

    x = jax.ShapeDtypeStruct((4, 128), jnp.float32)
    return analyze(fn, x, name=name)


def kernel_grid_race() -> Report:
    import jax.numpy as jnp
    return _grid_kernel_report(True, jnp.float32,
                               "fixture:kernel_grid_race")


def kernel_clean_grid() -> Report:
    import jax.numpy as jnp
    return _grid_kernel_report(False, jnp.float32,
                               "fixture:kernel_clean_grid")


def kernel_f16_accumulator() -> Report:
    """An online-softmax-style scratch accumulator allocated in f16: state
    carried across grid iterations below f32 drifts from the dense path's
    einsum promotion (the bit-exactness contract)."""
    import jax.numpy as jnp
    return _grid_kernel_report(False, jnp.float16,
                               "fixture:kernel_f16_accumulator")


def kernel_f32_accumulator() -> Report:
    import jax.numpy as jnp
    return _grid_kernel_report(False, jnp.float32,
                               "fixture:kernel_f32_accumulator")


# -- clean twin: a full pipeline train step must produce zero findings -----

def clean_pipeline_step() -> Report:
    import jax

    from simple_distributed_machine_learning_tpu.analysis.preflight import (
        _abstract_batch,
        abstractify,
    )
    from simple_distributed_machine_learning_tpu.models.mlp import (
        make_mlp_stages,
    )
    from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        Pipeline,
    )
    from simple_distributed_machine_learning_tpu.train.optimizer import sgd
    from simple_distributed_machine_learning_tpu.train.step import (
        make_train_step,
    )

    stages, wire, out = make_mlp_stages(jax.random.key(0), [16, 16, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=2, devices=jax.devices()[:4])
    pipe = Pipeline(stages, mesh, wire, out, n_microbatches=2)
    opt = sgd(0.1, momentum=0.5)
    buf = abstractify(pipe.init_params())
    state = jax.eval_shape(opt.init, buf)
    x, t, k = _abstract_batch(pipe, 8, 16)
    return analyze(make_train_step(pipe, opt), buf, state, x, t, k,
                   mesh=mesh, name="fixture:clean_pipeline_step")


# -- protocol: seeded defects in the abstract fleet model ------------------
#
# Each builder runs the bounded model checker over a fleet model carrying
# ONE protocol defect (a knob on ProtocolConfig that mirrors a real bug
# class in serve/fleet.py + serve/supervisor.py).  The defect fixtures must
# produce a `protocol.*` ERROR with a concrete counterexample trace; the
# clean twin explores the same transition system with the defect knobs off
# and must prove every invariant to its depth.  Pure stdlib — no jax.

def protocol_dropped_handoff() -> Report:
    from simple_distributed_machine_learning_tpu.analysis.protocol import (
        DROPPED_TOMBSTONE,
        check_protocol,
    )
    return check_protocol(DROPPED_TOMBSTONE)


def protocol_legacy_handoff_order() -> Report:
    from simple_distributed_machine_learning_tpu.analysis.protocol import (
        LEGACY_ORDER,
        check_protocol,
    )
    return check_protocol(LEGACY_ORDER)


def protocol_skipped_refund() -> Report:
    from simple_distributed_machine_learning_tpu.analysis.protocol import (
        SKIPPED_REFUND,
        check_protocol,
    )
    return check_protocol(SKIPPED_REFUND)


def protocol_ungated_boarding() -> Report:
    from simple_distributed_machine_learning_tpu.analysis.protocol import (
        UNGATED_BOARDING,
        check_protocol,
    )
    return check_protocol(UNGATED_BOARDING)


def protocol_clean_fleet() -> Report:
    from simple_distributed_machine_learning_tpu.analysis.protocol import (
        CLEAN,
        check_protocol,
    )
    return check_protocol(CLEAN)


FIXTURES: dict[str, Fixture] = {f.name: f for f in [
    Fixture("partial_ppermute", "ppermute-deadlock", True,
            "ring permutation missing its wraparound hop", partial_ppermute),
    Fixture("dropped_grad_sync", "unreduced-gradient", True,
            "data-parallel update without the gradient all-reduce",
            dropped_grad_sync),
    Fixture("wrong_axis_name", "mesh-axis", True,
            "psum over an axis the mesh does not bind", wrong_axis_name),
    Fixture("bf16_psum_accumulator", "dtype-drift", True,
            "bf16 cross-device reduction into a bf16 scan carry",
            bf16_psum_accumulator),
    Fixture("read_after_donate", "donation", True,
            "buffer read after being donated to a jitted update",
            read_after_donate),
    Fixture("oob_block_table", "scatter-bounds", True,
            "paged decode with a block-table contract one past the pool",
            oob_block_table),
    Fixture("cow_read_after_donate", "donation", True,
            "CoW block copy reading buffers the prefill chunk donated",
            cow_read_after_donate),
    Fixture("unmemoized_retrace", "retrace-explosion", True,
            "decode builder rebuilding its program outside the memo",
            unmemoized_retrace),
    Fixture("dropped_gather_before_use", "sharded-state", True,
            "ZeRO opt-state shard consumed without gather/reduce",
            dropped_gather_before_use),
    Fixture("kernel_oob_index_map", "kernel-oob", True,
            "fused paged kernel with a block-table contract past the pool",
            kernel_oob_index_map),
    Fixture("kernel_grid_race", "kernel-race", True,
            "pallas output index map collapsing a parallel grid axis",
            kernel_grid_race),
    Fixture("kernel_bad_tile", "kernel-tile", True,
            "small head dim in the 128-lane slot (32x Mosaic tile padding)",
            kernel_bad_tile),
    Fixture("kernel_f16_accumulator", "kernel-dtype-drift", True,
            "f16 scratch accumulator carried across grid iterations",
            kernel_f16_accumulator),
    Fixture("protocol_dropped_handoff", "protocol", True,
            "handoff sealed without journaling the source tombstone",
            protocol_dropped_handoff),
    Fixture("protocol_legacy_handoff_order", "protocol", True,
            "tombstone-then-copy handoff (pre-fix ordering, loses the rid)",
            protocol_legacy_handoff_order),
    Fixture("protocol_skipped_refund", "protocol", True,
            "shed/preempt path that never refunds the KV block refcounts",
            protocol_skipped_refund),
    Fixture("protocol_ungated_boarding", "protocol", True,
            "decode boarding not gated on the prefetch upload landing",
            protocol_ungated_boarding),
    Fixture("clean_grad_sync", "", False,
            "the dropped_grad_sync fixture with the pmean restored",
            clean_grad_sync),
    Fixture("clean_cow_tick", "", False,
            "the CoW tick with donated buffers threaded correctly",
            clean_cow_tick),
    Fixture("clean_gather_before_use", "", False,
            "the ZeRO update with the reduce restored (must be clean)",
            clean_gather_before_use),
    Fixture("clean_pipeline_step", "", False,
            "a 2-stage dp=2 GPipe train step (must be clean)",
            clean_pipeline_step),
    Fixture("kernel_clean_paged", "", False,
            "the fused paged kernel under the slots.py table invariant",
            kernel_clean_paged),
    Fixture("kernel_clean_grid", "", False,
            "the grid kernel with its output indexed by the parallel axis",
            kernel_clean_grid),
    Fixture("kernel_packed_tile", "", False,
            "the small-head-dim kernel in the fixed 'packed' layout",
            kernel_packed_tile),
    Fixture("kernel_f32_accumulator", "", False,
            "the grid kernel with its scratch accumulator in f32",
            kernel_f32_accumulator),
    Fixture("protocol_clean_fleet", "", False,
            "the 2-pool fleet model with every defect knob off (proves)",
            protocol_clean_fleet),
]}


def _replay_exported_drill() -> tuple[bool, list[str]]:
    """Anti-vacuous gate for the model checker's counterexample export: the
    FaultPlan exported from the dropped-tombstone model's double-serve
    counterexample must replay as a REAL failure (more tokens streamed than
    the request asked for) on a live 3-replica disaggregated fleet carrying
    the same seeded defect (``log_handoff`` suppressed), and the intact
    twin must stay exactly-once under the identical kill schedule.  Without
    this, a model bug that exports unparseable or toothless schedules would
    pass every purely-abstract check."""
    import os
    import tempfile

    import jax
    import numpy as np

    from simple_distributed_machine_learning_tpu.analysis.protocol import (
        DROPPED_TOMBSTONE,
        check_protocol,
        export_fault_plan,
    )
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )
    from simple_distributed_machine_learning_tpu.resilience import faults
    from simple_distributed_machine_learning_tpu.serve import (
        RequestJournal,
        ServeFleet,
        engine_factory,
    )
    from simple_distributed_machine_learning_tpu.serve.request import DONE

    lines = []
    report = check_protocol(DROPPED_TOMBSTONE)
    viol = next((v for v in report.exploration.violations
                 if v.invariant == "double-serve"), None)
    if viol is None:
        return False, ["== exported-drill replay: model found no "
                       "double-serve counterexample -> FAILED"]
    plan_text, note = export_fault_plan(viol)
    if plan_text is None:
        return False, [f"== exported-drill replay: counterexample not "
                       f"expressible as a FaultPlan ({note}) -> FAILED"]
    lines.append(f"  exported plan: {plan_text}")

    cfg = GPTConfig(vocab=32, seq_len=48, d_model=32, n_heads=2, n_layers=2)
    stages = make_gpt_stages(jax.random.key(0), cfg, 2)[0]
    prompt = np.asarray(
        jax.random.randint(jax.random.key(7), (4,), 0, cfg.vocab), np.int32)
    max_new = 3

    def run(drop_tombstone: bool) -> int:
        """Drive the model's scenario (submit -> prefill -> handoff ->
        DONE), then install the exported plan and keep ticking; returns
        total tokens streamed to the caller over the whole run."""
        faults.uninstall()
        orig = RequestJournal.log_handoff
        if drop_tombstone:
            RequestJournal.log_handoff = lambda self, **kw: None
        try:
            with tempfile.TemporaryDirectory() as td:
                fleet = ServeFleet(
                    engine_factory(stages, cfg, n_slots=2, block_size=4,
                                   prefill_chunk=3),
                    os.path.join(td, "j"), n_replicas=3,
                    prefill_replicas=1, journal_sync=False)
                got = []
                h = fleet.submit(prompt, max_new_tokens=max_new, seed=11,
                                 on_token=lambda req, tok: got.append(tok))
                for _ in range(60):
                    fleet.step()
                    if h.state == DONE and fleet.handoffs >= 1:
                        break
                faults.install(faults.FaultPlan.parse(plan_text))
                for _ in range(len(plan_text.split(";")) + 1):
                    fleet.step()
                faults.uninstall()
                for _ in range(60):
                    if h.state == DONE:
                        break
                    fleet.step()
                fleet.close()
                return len(got)
        finally:
            RequestJournal.log_handoff = orig
            faults.uninstall()

    defect_tokens = run(drop_tombstone=True)
    clean_tokens = run(drop_tombstone=False)
    defect_good = defect_tokens > max_new
    clean_good = clean_tokens == max_new
    lines.append(f"  defect twin (log_handoff dropped): streamed "
                 f"{defect_tokens}/{max_new} tokens -> "
                 f"{'double-served as predicted' if defect_good else 'NO REAL FAILURE (vacuous export)'}")  # noqa: E501
    lines.append(f"  clean twin (tombstone intact):     streamed "
                 f"{clean_tokens}/{max_new} tokens -> "
                 f"{'exactly-once' if clean_good else 'UNEXPECTED FAILURE'}")
    ok = defect_good and clean_good
    lines.insert(0, f"== exported-drill replay: counterexample must fail a "
                    f"real fleet -> {'OK' if ok else 'FAILED'}")
    return ok, lines


def self_test() -> tuple[bool, str]:
    """Run every fixture against its contract, plus the chaos drill
    coverage lint (``resilience.faults.drill_coverage``: every registered
    fault kind x site fired by at least one test/CI drill). Returns
    (ok, report_text) — the CLI ``--fixtures`` mode prints the text and
    exits 0 iff ok."""
    lines = []
    ok = True
    for fx in FIXTURES.values():
        report = fx.build()
        flagged = not report.ok(fail_on="warning")
        family_hit = (not fx.defect or
                      any(f.family == fx.family for f in report.findings))
        good = (flagged and family_hit) if fx.defect else not flagged
        ok = ok and good
        verdict = "OK" if good else "FIXTURE CONTRACT VIOLATED"
        want = (f"must flag [{fx.family}]" if fx.defect else "must be clean")
        lines.append(f"== {fx.name}: {want} -> {verdict}")
        lines.append(report.format(costs=False))
    from simple_distributed_machine_learning_tpu.resilience.faults import (
        drill_coverage,
    )
    gaps = drill_coverage()
    verdict = "OK" if not gaps else "COVERAGE GAPS"
    lines.append(f"== fault drill coverage: every kind x site fired "
                 f"-> {verdict}")
    for g in gaps:
        lines.append(f"  MISSING: {g}")
        ok = False
    replay_ok, replay_lines = _replay_exported_drill()
    ok = ok and replay_ok
    lines.extend(replay_lines)
    return ok, "\n".join(lines)
