"""The lint passes: one walk of the traced step, five rule families.

The walker visits every equation of the traced step (recursing through
``scan``/``cond``/``pjit``/``shard_map``/``remat``/``custom_vjp`` sub-jaxprs)
carrying three pieces of state:

- a **replication environment** inside each ``shard_map``: for every value,
  the set of mesh axes it may VARY over (differ across devices). Inputs seed
  from ``in_names``; ``axis_index`` introduces variance; ``psum``/
  ``all_gather`` over an axis remove it (every device then holds the same
  value); ``ppermute`` preserves it; control flow joins it (a ``switch`` on a
  stage index makes every branch output stage-varying). This is a static
  reimplementation of the vma/replication typing that ``check_rep=False``
  era shard_maps never got — and it is what catches a dropped gradient
  reduction (family ``unreduced-gradient``): a ``shard_map`` output whose
  ``out_specs`` CLAIM replication over an axis the dataflow says it still
  varies over means a ``psum``/``ring_psum``/reduce-scatter is missing
  before the optimizer update.

- a **provenance path** (which pjit/scan/cond frames enclose the eqn) plus
  jax's recorded source line, so findings point at code.

- a **trip multiplier** (product of enclosing scan lengths) for the
  bytes-over-ICI cost table.

The other families ride the same walk: ``ppermute-deadlock`` (non-bijective
permutations; collectives inside ``cond``/``switch`` branches that diverge —
the PR-2 XLA:CPU rendezvous caveat, now machine-checked — or inside ``while``
loops with device-varying trip counts), ``mesh-axis`` (axis names not in the
active mesh, permutation endpoints outside the axis), ``dtype-drift``
(sub-fp32 cross-device reductions and scan carries that accumulate in
sub-fp32), and ``donation`` (a buffer donated to a jitted call and read
again afterwards — the classic read-after-donate crash, caught before any
device allocates).
"""

from __future__ import annotations

from typing import Any

from simple_distributed_machine_learning_tpu.analysis.report import (
    CollectiveCost,
    Finding,
    Severity,
)
from simple_distributed_machine_learning_tpu.analysis.trace import (
    RENDEZVOUS_PRIMS,
    aval_bytes,
    eqn_axes,
    is_low_precision,
    norm_axes,
    open_jaxpr,
    source_line,
    subjaxprs,
)

EMPTY: frozenset = frozenset()

# traffic factor over an axis group of n devices: bytes actually moved per
# operand byte by the standard ring algorithm for each collective kind
def _ici_factor(prim: str, n: int) -> float:
    if n <= 1:
        return 0.0
    return {
        "psum": 2.0 * (n - 1) / n,           # reduce-scatter + all-gather
        "pmin": 2.0 * (n - 1) / n,
        "pmax": 2.0 * (n - 1) / n,
        "all_gather": float(n - 1),           # (n-1) shards arrive
        "reduce_scatter": (n - 1) / n,
        "all_to_all": (n - 1) / n,            # keeps 1/n locally
        "ppermute": 1.0,                      # one hop, whole payload
        "pbroadcast": 1.0,
    }.get(prim, 1.0)


class _MeshCtx:
    """The active shard_map context: manual axis name -> size."""

    def __init__(self, axes: dict[str, int]):
        self.axes = dict(axes)

    def size(self, name: str) -> int | None:
        return self.axes.get(name)


def _mesh_axes_of(eqn, active_mesh) -> dict[str, int]:
    """Manual (non-auto) axes of a shard_map eqn, cross-checked against the
    launch mesh when one was passed to ``analyze``."""
    mesh = eqn.params.get("mesh", None)
    auto = eqn.params.get("auto", None) or frozenset()
    axes: dict[str, int] = {}
    shape = getattr(mesh, "shape", None)
    if shape:
        for name, size in dict(shape).items():
            if name not in auto:
                axes[name] = int(size)
    if not axes and active_mesh is not None:
        axes = {n: int(s) for n, s in dict(active_mesh.shape).items()}
    return axes


def _names_to_axes(names: Any) -> frozenset:
    """A shard_map in_names/out_names entry ({dim: (axis, ...)}) as the flat
    set of mesh axes it maps."""
    out = set()
    for v in dict(names or {}).values():
        out.update(norm_axes(v))
    return frozenset(out)


class Walker:
    """One pass over the traced step, accumulating findings and costs."""

    def __init__(self, active_mesh=None):
        self.active_mesh = active_mesh
        self.findings: list[Finding] = []
        self.costs: list[CollectiveCost] = []
        self._path: list[str] = []
        self._trips = 1
        self._mute = 0         # >0 during scan fixpoint pre-passes

    # -- plumbing ---------------------------------------------------------

    def _where(self, eqn=None) -> str:
        path = "/".join(self._path) or "<top>"
        src = source_line(eqn) if eqn is not None else ""
        return f"{path} ({src})" if src else path

    def _emit(self, rule: str, severity: Severity, message: str, eqn=None,
              hint: str = "") -> None:
        if self._mute:
            return
        self.findings.append(Finding(rule=rule, severity=severity,
                                     message=message, where=self._where(eqn),
                                     hint=hint))

    def _read(self, env: dict, atom) -> frozenset:
        # Literals (and unseen constvars) are device-uniform
        return env.get(id(atom), EMPTY) if hasattr(atom, "aval") else EMPTY

    # -- entry points -----------------------------------------------------

    def visit_outer(self, jaxpr, in_vary=None) -> list:
        """Walk a jaxpr OUTSIDE any shard_map: track donation, enter
        shard_maps, recurse through call-like eqns.

        ``in_vary`` optionally seeds DECLARED device-variance per invar (a
        caller's ``analysis.spec(..., vary=('data',))`` contract): a buffer
        whose shape is replicated but whose CONTENT each device holds a
        different shard of — exactly a ZeRO opt-state shard in the
        check_rep=False era, which no ``in_names`` can express. The
        variance threads through call-like eqns into every shard_map's
        replication inference, where a consume-without-gather surfaces as a
        missing reduction (re-tagged ``sharded-state`` by run_rules).
        Returns the out-vars' variance (for the recursion)."""
        jaxpr = open_jaxpr(jaxpr)
        donated: dict[int, str] = {}       # id(var) -> donation site
        vary: dict[int, frozenset] = {}
        if in_vary:
            for var, v in zip(jaxpr.invars, in_vary):
                if v:
                    vary[id(var)] = frozenset(v)

        def _vary_of(atoms):
            return [vary.get(id(v), EMPTY) for v in atoms]

        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            for invar in eqn.invars:
                key = id(invar)
                if key in donated:
                    self._emit(
                        "donation.read-after-donate", Severity.ERROR,
                        f"value donated at {donated[key]} is read again by "
                        f"'{prim}' — after donation the buffer may already "
                        f"be overwritten on device",
                        eqn,
                        hint="use the returned (updated) value, or drop the "
                             "argument from donate_argnums")
                    break
            if prim == "shard_map":
                self._path.append("shard_map")
                try:
                    self._visit_shard_map(eqn, incoming=_vary_of(eqn.invars))
                finally:
                    self._path.pop()
            elif prim in RENDEZVOUS_PRIMS or prim == "axis_index":
                # a mesh collective with no enclosing shard_map: axis names
                # can only bind through a mesh this analyzer cannot see
                self._emit(
                    "mesh-axis.unknown-axis", Severity.ERROR,
                    f"collective '{prim}' over {eqn_axes(eqn)} outside any "
                    f"shard_map — no mesh binds these axis names", eqn,
                    hint="collectives must run inside shard_map over a mesh "
                         "that names the axis")
            else:
                trips = (int(eqn.params.get("length", 1) or 1)
                         if prim == "scan" else 1)
                for key, _, sub in subjaxprs(eqn):
                    self._path.append(
                        f"pjit:{eqn.params.get('name', key)}"
                        if prim == "pjit"
                        else f"scan[x{trips}]" if prim == "scan" else prim)
                    self._trips *= trips
                    try:
                        # map eqn invars onto this sub-jaxpr's params:
                        # cond branches drop the predicate, while's two
                        # jaxprs each see their own consts + the carry —
                        # declared vary= contracts must thread through
                        # these boundaries, not silently reset
                        if prim == "cond":
                            ev = list(eqn.invars)[1:]
                        elif prim == "while":
                            cnc = eqn.params.get("cond_nconsts", 0)
                            bnc = eqn.params.get("body_nconsts", 0)
                            iv = list(eqn.invars)
                            ev = (iv[:cnc] + iv[cnc + bnc:]
                                  if key == "cond_jaxpr" else iv[cnc:])
                        else:
                            ev = list(eqn.invars)
                        sub_vary = (_vary_of(ev)
                                    if len(sub.invars) == len(ev)
                                    else None)
                        outs = self.visit_outer(sub, in_vary=sub_vary)
                    finally:
                        self._trips //= trips
                        self._path.pop()
                    if (vary and key != "cond_jaxpr"
                            and len(outs) >= len(eqn.outvars)):
                        # union across sub-jaxprs (cond/switch branches):
                        # ANY branch's variance survives the join — the
                        # last branch overwriting would certify a defect
                        # reachable only through an earlier branch
                        for var, v in zip(eqn.outvars, outs):
                            if v:
                                vary[id(var)] = (
                                    vary.get(id(var), frozenset()) | v)
                if vary and not any(True for _ in subjaxprs(eqn)):
                    # plain eqn: declared variance flows through
                    union = frozenset().union(*_vary_of(eqn.invars)) \
                        if eqn.invars else EMPTY
                    if union:
                        for var in eqn.outvars:
                            vary[id(var)] = union
            if prim == "pjit":
                don = eqn.params.get("donated_invars") or ()
                site = self._where(eqn)
                seen_at: dict[int, bool] = {}   # id(var) -> any donated
                flagged: set[int] = set()   # one finding per (eqn, buffer)
                for invar, d in zip(eqn.invars, don):
                    if not hasattr(invar, "aval"):
                        continue
                    key = id(invar)
                    if (key in seen_at and (d or seen_at[key])
                            and key not in flagged):
                        flagged.add(key)
                        self._emit(
                            "donation.double-donation", Severity.ERROR,
                            f"the same buffer is passed twice to "
                            f"'{eqn.params.get('name', 'pjit')}' with at "
                            f"least one position donated — the donated "
                            f"pages may be reused while the aliased "
                            f"parameter still reads them", eqn,
                            hint="pass distinct buffers, or drop the "
                                 "aliased position from donate_argnums")
                    seen_at[key] = seen_at.get(key, False) or bool(d)
                    if d:
                        donated[key] = site
        for outvar in jaxpr.outvars:
            if id(outvar) in donated:
                self._emit(
                    "donation.read-after-donate", Severity.ERROR,
                    f"value donated at {donated[id(outvar)]} is returned "
                    f"from the traced function — the caller would read a "
                    f"donated buffer", None,
                    hint="return the updated value instead of the donated "
                         "input")
        return _vary_of(jaxpr.outvars)

    def _visit_shard_map(self, eqn, incoming=None) -> None:
        axes = _mesh_axes_of(eqn, self.active_mesh)
        ctx = _MeshCtx(axes)
        inner = open_jaxpr(eqn.params["jaxpr"])
        in_names = eqn.params.get("in_names")
        out_names = eqn.params.get("out_names")
        if in_names is None:            # new-jax spelling: in_specs PartitionSpec
            in_vmas = [EMPTY for _ in inner.invars]
        else:
            in_vmas = [_names_to_axes(n) for n in in_names]
        if incoming:
            # declared content-variance (ZeRO shards in replicated-shape
            # buffers) joins whatever in_names already map
            in_vmas = [v | inc for v, inc in
                       zip(in_vmas, incoming + [EMPTY] * len(in_vmas))]
        # cross-check the traced mesh against the launch mesh
        if self.active_mesh is not None:
            active = {n: int(s) for n, s in dict(self.active_mesh.shape).items()}
            for name, size in axes.items():
                if size > 1 and active.get(name, 1) != size:
                    self._emit(
                        "mesh-axis.mesh-mismatch", Severity.ERROR,
                        f"shard_map traced over mesh axis '{name}' of size "
                        f"{size}, but the active mesh has "
                        f"{name}={active.get(name, '<absent>')}", eqn,
                        hint="rebuild the step for the launch mesh (axis "
                             "sizes are baked in at trace time)")
        out_vmas = self._visit_vma(inner, in_vmas, ctx)
        if out_names is None:
            return
        for i, (names, vma) in enumerate(zip(out_names, out_vmas)):
            claimed = _names_to_axes(names)
            missing = sorted(
                ax for ax in vma - claimed
                if ctx.size(ax) is not None and ctx.size(ax) > 1)
            if missing:
                aval = getattr(inner.outvars[i], "aval", None)
                shape = getattr(aval, "shape", "?")
                self._emit(
                    "unreduced-gradient.missing-reduce", Severity.ERROR,
                    f"shard_map output {i} (shape {shape}) still varies over "
                    f"mesh axis(es) {missing} but its out_spec claims "
                    f"replication — a cross-device reduction is missing on "
                    f"this path (each device would keep only its own "
                    f"partial value, e.g. an unsynced gradient)", eqn,
                    hint=f"psum/ring_psum/reduce-scatter over {missing} "
                         f"before returning, or map the axis in out_specs")

    # -- replication inference inside shard_map ---------------------------

    def _visit_vma(self, jaxpr, in_vmas, ctx) -> list:
        jaxpr = open_jaxpr(jaxpr)
        env: dict[int, frozenset] = {}
        for var in jaxpr.constvars:
            env[id(var)] = EMPTY
        for var, vma in zip(jaxpr.invars, in_vmas):
            env[id(var)] = frozenset(vma)
        for eqn in jaxpr.eqns:
            outs = self._eqn_vma(eqn, env, ctx)
            for var, vma in zip(eqn.outvars, outs):
                env[id(var)] = vma
        return [self._read(env, v) for v in jaxpr.outvars]

    def _eqn_vma(self, eqn, env, ctx) -> list:
        prim = eqn.primitive.name
        in_vmas = [self._read(env, v) for v in eqn.invars]
        union = frozenset().union(*in_vmas) if in_vmas else EMPTY
        n_out = len(eqn.outvars)

        if prim in RENDEZVOUS_PRIMS:
            return self._collective_vma(eqn, in_vmas, union, ctx)
        if prim == "axis_index":
            axes = eqn_axes(eqn)
            self._check_axes(eqn, axes, ctx)
            return [frozenset(axes)]
        if prim == "cond":
            return self._cond_vma(eqn, in_vmas, ctx)
        if prim == "scan":
            return self._scan_vma(eqn, in_vmas, ctx)
        if prim == "while":
            return self._while_vma(eqn, in_vmas, ctx)

        # generic call-like primitives (pjit, closed_call, remat2,
        # custom_jvp/vjp calls, ...): recurse when a sub-jaxpr's arity
        # matches, else fall back to the union rule
        for key, _, sub in subjaxprs(eqn):
            if len(sub.invars) == len(eqn.invars):
                self._path.append(prim if prim != "pjit"
                                  else f"pjit:{eqn.params.get('name', '')}")
                try:
                    outs = self._visit_vma(sub, in_vmas, ctx)
                finally:
                    self._path.pop()
                if len(outs) >= n_out:
                    return outs[:n_out]
        return [union] * n_out

    def _collective_vma(self, eqn, in_vmas, union, ctx) -> list:
        prim = eqn.primitive.name
        axes = eqn_axes(eqn)
        self._check_axes(eqn, axes, ctx)
        self._check_dtype(eqn, prim)
        self._record_cost(eqn, prim, axes, ctx)
        groups = eqn.params.get("axis_index_groups")
        if prim == "ppermute":
            self._check_perm(eqn, axes, ctx)
            return [union] * len(eqn.outvars)
        if prim in ("psum", "pmin", "pmax", "all_gather"):
            if groups:
                # replicated only within each group: conservatively varying
                return [union] * len(eqn.outvars)
            return [vma - frozenset(axes) for vma in
                    (in_vmas if len(in_vmas) == len(eqn.outvars)
                     else [union] * len(eqn.outvars))]
        if prim in ("all_to_all", "reduce_scatter", "pbroadcast"):
            # device-dependent slices (or an explicit varying cast)
            return [union | frozenset(axes)] * len(eqn.outvars)
        return [union] * len(eqn.outvars)

    def _cond_vma(self, eqn, in_vmas, ctx) -> list:
        branches = eqn.params.get("branches") or ()
        pred_vma, op_vmas = in_vmas[0], in_vmas[1:]
        outs = None
        for b, branch in enumerate(branches):
            self._path.append(f"cond[branch {b}]")
            try:
                b_outs = self._visit_vma(branch, op_vmas, ctx)
            finally:
                self._path.pop()
            outs = (b_outs if outs is None else
                    [a | b_ for a, b_ in zip(outs, b_outs)])
        if outs is None:
            outs = [frozenset()] * len(eqn.outvars)
        self._check_branch_divergence(eqn, branches, pred_vma, ctx)
        return [o | pred_vma for o in outs]

    def _scan_vma(self, eqn, in_vmas, ctx) -> list:
        p = eqn.params
        body = p["jaxpr"]
        nc, ncar = p.get("num_consts", 0), p.get("num_carry", 0)
        length = int(p.get("length", 1) or 1)
        consts, carry = in_vmas[:nc], list(in_vmas[nc:nc + ncar])
        xs = in_vmas[nc + ncar:]
        self._check_carry_dtype(eqn, body, nc, ncar)
        # fixpoint on the carry (muted: no duplicate findings/costs)
        self._mute += 1
        try:
            for _ in range(len(ctx.axes) + 2):
                outs = self._visit_vma(body, consts + carry + xs, ctx)
                new_carry = [c | o for c, o in zip(carry, outs[:ncar])]
                if new_carry == carry:
                    break
                carry = new_carry
        finally:
            self._mute -= 1
        # final, reporting pass with the stabilized carry
        self._path.append(f"scan[x{length}]")
        self._trips *= length
        try:
            outs = self._visit_vma(body, consts + carry + xs, ctx)
        finally:
            self._trips //= length
            self._path.pop()
        return outs

    def _while_vma(self, eqn, in_vmas, ctx) -> list:
        p = eqn.params
        cnc, bnc = p.get("cond_nconsts", 0), p.get("body_nconsts", 0)
        cond_consts = in_vmas[:cnc]
        body_consts = in_vmas[cnc:cnc + bnc]
        carry = list(in_vmas[cnc + bnc:])
        pred_vma = EMPTY
        self._mute += 1
        try:
            for _ in range(len(ctx.axes) + 2):
                pred = self._visit_vma(p["cond_jaxpr"], cond_consts + carry,
                                       ctx)
                pred_vma = pred[0] if pred else EMPTY
                outs = self._visit_vma(p["body_jaxpr"], body_consts + carry,
                                       ctx)
                new_carry = [c | o | pred_vma for c, o in zip(carry, outs)]
                if new_carry == carry:
                    break
                carry = new_carry
        finally:
            self._mute -= 1
        if pred_vma and self._has_rendezvous(p["body_jaxpr"]):
            axes_used = self._rendezvous_axes(p["body_jaxpr"])
            sev = (Severity.ERROR if pred_vma & axes_used
                   else Severity.WARNING)
            self._emit(
                "ppermute-deadlock.varying-trip-count", sev,
                f"while loop whose trip count varies over {sorted(pred_vma)} "
                f"contains collectives over {sorted(axes_used)} — devices "
                f"would disagree on how many rendezvous to join", eqn,
                hint="make the trip count device-uniform (psum/pmax the "
                     "predicate) or hoist the collectives out of the loop")
        self._path.append("while")
        try:
            outs = self._visit_vma(p["body_jaxpr"], body_consts + carry, ctx)
        finally:
            self._path.pop()
        return [o | pred_vma for o in outs]

    # -- the individual checks -------------------------------------------

    def _check_axes(self, eqn, axes, ctx) -> None:
        known = set(ctx.axes)
        for ax in axes:
            if ax not in known:
                self._emit(
                    "mesh-axis.unknown-axis", Severity.ERROR,
                    f"collective '{eqn.primitive.name}' names axis '{ax}' "
                    f"which is not in the active mesh (axes: "
                    f"{sorted(known)})", eqn,
                    hint="fix the axis_name, or launch on a mesh that has "
                         "this axis")

    def _check_perm(self, eqn, axes, ctx) -> None:
        perm = eqn.params.get("perm")
        if perm is None or not axes:
            return
        size = 1
        for ax in axes:
            size *= ctx.size(ax) or 1
        pairs = [tuple(p) for p in perm]
        srcs = [s for s, _ in pairs]
        dsts = [d for _, d in pairs]
        oob = [i for i in srcs + dsts if not (0 <= i < size)]
        if oob:
            self._emit(
                "mesh-axis.perm-out-of-range", Severity.ERROR,
                f"ppermute over {axes} (size {size}) names device index(es) "
                f"{sorted(set(oob))} outside [0, {size})", eqn,
                hint="ring permutations must index devices of the named "
                     "axis; check the chunk/ring size against the mesh")
            return
        full = (len(pairs) == size and len(set(srcs)) == size
                and len(set(dsts)) == size)
        if not full:
            self._emit(
                "ppermute-deadlock.partial-perm", Severity.ERROR,
                f"ppermute over {axes} (size {size}) is not a full bijection "
                f"({len(set(srcs))} distinct sources, {len(set(dsts))} "
                f"distinct destinations, {size} needed) — devices outside "
                f"the permutation stall the collective-permute rendezvous "
                f"and receivers without a source read zeros", eqn,
                hint="send a (possibly dummy) chunk from every device: "
                     "perm=[(j, (j+1) % size) for j in range(size)]")

    def _check_dtype(self, eqn, prim) -> None:
        # min/max select an existing element — bf16 pmin/pmax are bit-exact;
        # only summing reductions lose increments below the ulp
        if prim not in ("psum", "reduce_scatter"):
            return
        for invar in eqn.invars:
            aval = getattr(invar, "aval", None)
            if aval is not None and is_low_precision(aval.dtype):
                self._emit(
                    "dtype-drift.low-precision-reduction", Severity.WARNING,
                    f"'{prim}' reduces {aval.dtype} operands across devices "
                    f"— cross-device accumulation in sub-fp32 loses "
                    f"increments as the axis (or value magnitude) grows",
                    eqn,
                    hint="accumulate in float32: cast before the reduction "
                         "and back after (the loss/grad paths already do)")
                return

    def _check_carry_dtype(self, eqn, body, nc, ncar) -> None:
        """Scan carries that ACCUMULATE (carry-out reachable from carry-in
        through an add) in sub-fp32: the classic silent drift — a bf16
        running sum stops growing once increments fall below its ulp."""
        body_j = open_jaxpr(body)
        carry_in = body_j.invars[nc:nc + ncar]
        carry_out = body_j.outvars[:ncar]
        for i, (vin, vout) in enumerate(zip(carry_in, carry_out)):
            aval = getattr(vin, "aval", None)
            if aval is None or not is_low_precision(aval.dtype):
                continue
            if self._accumulates(body_j, vin, vout):
                self._emit(
                    "dtype-drift.low-precision-carry", Severity.WARNING,
                    f"scan carry {i} accumulates in {aval.dtype}: a running "
                    f"sum in sub-fp32 silently drops increments (bf16 has 8 "
                    f"mantissa bits — sums stall near 256x the step size)",
                    eqn,
                    hint="carry the accumulator as float32 and cast at the "
                         "edges")

    @staticmethod
    def _accumulates(jaxpr, vin, vout) -> bool:
        """Is ``vout`` reachable from ``vin`` through an add-like eqn?"""
        add_like = {"add", "add_any", "scatter-add"}
        # taint[var] = (reachable, passed_through_add)
        taint: dict[int, bool] = {id(vin): False}
        for eqn in jaxpr.eqns:
            hit = [taint[id(v)] for v in eqn.invars if id(v) in taint]
            if not hit:
                continue
            via_add = any(hit) or eqn.primitive.name in add_like
            for ov in eqn.outvars:
                taint[id(ov)] = taint.get(id(ov), False) or via_add
            # recurse one level into call-like bodies cheaply: treat any
            # sub-jaxpr containing an add as an add on this path
            if not via_add:
                for _, _, sub in subjaxprs(eqn):
                    if any(e.primitive.name in add_like for e in sub.eqns):
                        for ov in eqn.outvars:
                            taint[id(ov)] = True
                        break
        return taint.get(id(vout), False)

    def _check_branch_divergence(self, eqn, branches, pred_vma, ctx) -> None:
        """Collectives inside cond/switch branches that do not line up
        across branches. If the predicate varies over the axis a collective
        runs over, devices in one rendezvous group take different branches —
        a hard deadlock everywhere. If it varies only over OTHER axes the
        groups are internally consistent (each group sees one branch), but
        backends with a global rendezvous (old XLA:CPU collective-permute —
        the PR-2 caveat) still deadlock: flag as a portability warning."""
        if not pred_vma or len(branches) < 2:
            return
        sigs = [self._collective_sig(b) for b in branches]
        axes_used: set = set()
        has_ppermute = False

        def scan_sig(sig):
            nonlocal has_ppermute
            for prim, axes, extra in sig:
                if prim == "scan":
                    scan_sig(extra)
                else:
                    axes_used.update(axes)
                    has_ppermute = has_ppermute or prim == "ppermute"
        for s in sigs:
            scan_sig(s)
        diverge = any(s != sigs[0] for s in sigs[1:])
        if diverge and pred_vma & axes_used:
            # devices of one rendezvous group take different branches and
            # issue different collective sequences: deadlock everywhere
            self._emit(
                "ppermute-deadlock.branch-divergent", Severity.ERROR,
                f"cond/switch on a predicate varying over "
                f"{sorted(pred_vma)} has branches with DIFFERENT collective "
                f"sequences over the SAME axes {sorted(pred_vma & axes_used)}"
                f" — devices of one collective group take different "
                f"branches: deadlock on every backend", eqn,
                hint="make every branch issue the same collective sequence "
                     "(dummy hops on non-participating branches)")
        elif has_ppermute:
            # the PR-2 caveat, machine-checked: ppermute rings inside
            # device-divergent branches are group-consistent (each stage's
            # seq/expert group agrees on its branch — safe on TPU, where the
            # permutes are independent ICI DMAs), but old XLA:CPU pairs
            # collective-permutes through one GLOBAL rendezvous, and the
            # stage-skewed branch execution deadlocks it. Branch-resident
            # psums/all-reduces rendezvous per group and are fine (TP
            # pipelines run green on CPU), so only rings are flagged.
            self._emit(
                "ppermute-deadlock.ring-in-branch", Severity.WARNING,
                f"ppermute ring(s) over {sorted(axes_used)} inside "
                f"cond/switch branches dispatched on a predicate varying "
                f"over {sorted(pred_vma)} — safe on TPU ICI, but old "
                f"XLA:CPU's global collective-permute rendezvous deadlocks "
                f"under branch-skewed execution (the PR-2 caveat)", eqn,
                hint="on CPU backends run this model on a 1-stage mesh (the "
                     "cli/tests fallback), or keep rings out of "
                     "stage-dispatched branches")
        elif diverge and not pred_vma & axes_used:
            # divergent psum/all-gather sequences with group-consistent
            # branch choice: correct and deadlock-free (per-group
            # rendezvous); surface as INFO so audits still see it
            self._emit(
                "ppermute-deadlock.branch-divergent", Severity.INFO,
                f"cond/switch branches issue different (non-ppermute) "
                f"collective sequences over {sorted(axes_used)}; the "
                f"predicate varies only over {sorted(pred_vma)}, so each "
                f"collective group agrees on its branch — correct, noted "
                f"for audit", eqn)

    def _collective_sig(self, jaxpr) -> tuple:
        """Ordered sequence of rendezvous collectives a branch issues
        (recursively; scans contribute their body times the trip count —
        encoded structurally so differing lengths differ)."""
        sig = []
        for eqn in open_jaxpr(jaxpr).eqns:
            prim = eqn.primitive.name
            if prim in RENDEZVOUS_PRIMS:
                perm = eqn.params.get("perm")
                sig.append((prim, eqn_axes(eqn),
                            tuple(map(tuple, perm)) if perm else None))
            elif prim == "scan":
                inner = self._collective_sig(eqn.params["jaxpr"])
                if inner:
                    sig.append(("scan", (int(eqn.params.get("length", 1) or 1),),
                                inner))
            else:
                for _, _, sub in subjaxprs(eqn):
                    sig.extend(self._collective_sig(sub))
        return tuple(sig)

    def _has_rendezvous(self, jaxpr) -> bool:
        return bool(self._collective_sig(jaxpr))

    def _rendezvous_axes(self, jaxpr) -> frozenset:
        axes = set()

        def collect(sig):
            for prim, a, extra in sig:
                if prim == "scan":
                    collect(extra)
                else:
                    axes.update(a)
        collect(self._collective_sig(jaxpr))
        return frozenset(axes)

    def _record_cost(self, eqn, prim, axes, ctx) -> None:
        if self._mute or prim not in RENDEZVOUS_PRIMS:
            return
        group = 1
        for ax in axes:
            group *= ctx.size(ax) or 1
        payload = sum(aval_bytes(getattr(v, "aval", None)) or 0
                      for v in eqn.invars
                      if getattr(v, "aval", None) is not None)
        self.costs.append(CollectiveCost(
            prim=prim, axes=tuple(axes), group_size=group,
            bytes_per_call=payload,
            ici_bytes=int(payload * _ici_factor(prim, group)),
            trips=self._trips, where=self._where(eqn)))


def run_rules(closed_jaxpr, active_mesh=None, arg_ranges=None, arg_vary=None):
    """Run every lint pass over a traced step; returns (findings, costs).

    ``arg_ranges``/``arg_vary`` are flat per-invar contract annotations
    (from ``analysis.spec`` args, see ``analyze``): value intervals engage
    the scatter-bounds interval pass; declared device-variance engages the
    sharded-state pass — the replication inference runs twice, and a
    missing-reduction finding present ONLY under the declared shards is
    re-tagged ``sharded-state.missing-gather`` (the defect is consuming a
    sharded buffer without gathering it, not a dropped gradient psum).
    """
    import dataclasses

    w = Walker(active_mesh=active_mesh)
    w.visit_outer(closed_jaxpr, in_vary=arg_vary)
    findings, costs = w.findings, w.costs

    if arg_vary and any(arg_vary):
        base = Walker(active_mesh=active_mesh)
        base.visit_outer(closed_jaxpr)
        base_keys = {(f.rule, f.where) for f in base.findings}
        retagged = []
        for f in findings:
            if (f.rule == "unreduced-gradient.missing-reduce"
                    and (f.rule, f.where) not in base_keys):
                f = dataclasses.replace(
                    f, rule="sharded-state.missing-gather",
                    message=("a buffer DECLARED device-sharded (a ZeRO "
                             "param/opt-state shard in a replicated-shape "
                             "buffer) flows into this output without a "
                             "gather/reduce: " + f.message),
                    hint="all_gather the shard (or psum the partial) over "
                         "the declared axis before it meets replicated "
                         "state — gather-before-use / reduce-before-update")
            retagged.append(f)
        findings = retagged

    # The bounds pass always runs — even with no declared contracts, a
    # PROMISE_IN_BOUNDS gather/scatter must surface as unproven-promise
    # rather than analyze vacuously clean (an empty report is a proof).
    from simple_distributed_machine_learning_tpu.analysis.bounds import (
        check_bounds,
    )
    findings = findings + check_bounds(closed_jaxpr, list(arg_ranges or ()))
    return findings, costs
