"""Preflight glue: lint the exact step a launch is about to execute.

``cli.py --lint`` and ``bench.py --lint`` call into here. Everything is
abstract (ShapeDtypeStructs) — a preflight never allocates device buffers or
runs a FLOP, so gating a 1000-chip launch on it costs trace time only.

Also home of the pure-Python spec validators bench routes its ``--tp`` /
``--overlap`` flags through, so an invalid combination (a chunk count that
does not divide the model axis) exits with one clear message instead of a
trace-time stack.
"""

from __future__ import annotations

from simple_distributed_machine_learning_tpu.analysis import (
    Report,
    abstractify,
    analyze,
)


def validate_tp_overlap(tp: int, overlap: str, n_devices: int | None = None,
                        cfg=None, batch: int | None = None, n_micro: int = 1,
                        ) -> tuple[list[str], list[str]]:
    """Validate a tensor-parallel/overlap spec BEFORE building the model.

    Returns ``(errors, warnings)``: errors make the combo untraceable or
    wrong (exit with the message); warnings mean the ring schedule silently
    degrades to the monolithic collective (``ring_psum``'s divisibility
    fallback) — the run is correct but measures nothing new.

    ``cfg`` is a ``GPTConfig``-shaped object (``d_model``/``n_heads``/
    ``mlp_ratio``/``seq_len``/``attn_impl``/``n_experts`` attributes);
    ``batch``/``n_micro`` let the token-axis chunking of the scattered MLP
    (``matmul_reducescatter`` rows) be checked too.
    """
    errors: list[str] = []
    warnings: list[str] = []
    if tp < 1:
        errors.append(f"--tp must be >= 1, got {tp}")
        return errors, warnings
    if overlap not in ("none", "ring", None):
        errors.append(f"--overlap must be none|ring, got {overlap!r}")
    if overlap == "ring" and tp < 2:
        errors.append("--overlap ring needs --tp >= 2 (there is no "
                      "collective to schedule on an unsharded row)")
    if n_devices is not None and tp > n_devices:
        errors.append(f"--tp {tp} needs {tp} devices, have {n_devices}")
    if cfg is not None and tp > 1:
        heads = getattr(cfg, "n_heads", None)
        d_model = getattr(cfg, "d_model", None)
        ratio = getattr(cfg, "mlp_ratio", 4)
        if heads is not None and heads % tp:
            errors.append(
                f"--tp {tp} does not divide n_heads={heads}: attention "
                f"shards by head, so heads per shard must be integral")
        if d_model is not None and (ratio * d_model) % tp:
            errors.append(
                f"--tp {tp} does not divide the MLP hidden width "
                f"{ratio}*{d_model}={ratio * d_model}: the column-parallel "
                f"chunk count must divide the model axis")
        if getattr(cfg, "attn_impl", "dense") not in ("dense", None) and tp > 1:
            errors.append(
                f"--tp shards attention by head with dense local math; "
                f"attn={getattr(cfg, 'attn_impl', None)!r} is not "
                f"composable with it")
        if getattr(cfg, "n_experts", 0) and tp > 1:
            errors.append("--tp cannot combine with MoE experts (a stage is "
                          "tensor- OR expert-sharded, not both)")
        if overlap == "ring":
            if d_model is not None and d_model % tp:
                warnings.append(
                    f"ring overlap: d_model={d_model} not divisible by "
                    f"tp={tp} — the attention projection's ring_psum falls "
                    f"back to the monolithic psum (correct, no overlap)")
            seq_len = getattr(cfg, "seq_len", None)
            if batch is not None and seq_len is not None:
                tokens = (batch // max(1, n_micro)) * seq_len
                if tokens % tp:
                    warnings.append(
                        f"ring overlap: {tokens} tokens per microbatch not "
                        f"divisible by tp={tp} — the scattered MLP falls "
                        f"back to allgather + monolithic psum")
    return errors, warnings


def lint_step(fn, *args, mesh=None, name: str = "step") -> Report:
    """Analyze ``fn`` on (abstractified) example args against ``mesh``."""
    return analyze(fn, *[abstractify(a) for a in args], mesh=mesh, name=name)


def lint_trainer(trainer, batch_size: int | None = None) -> Report:
    """Lint the EXACT compiled train + eval steps a ``Trainer`` is about to
    run: same pipeline, same optimizer, same donation, same batch shapes.
    """
    import jax
    import numpy as np

    pipe = trainer.pipe
    B = int(batch_size or trainer.config.batch_size)
    key = jax.ShapeDtypeStruct((), jax.random.key(0).dtype)
    buf = abstractify(trainer.buf)
    opt_state = abstractify(trainer.opt_state)
    x = jax.ShapeDtypeStruct((B,) + tuple(trainer.train_ds.x.shape[1:]),
                             np.float32)
    tgt = jax.ShapeDtypeStruct((B,) + tuple(trainer.train_ds.y.shape[1:]),
                               np.int32)
    report = analyze(trainer._train_step, buf, opt_state, x, tgt, key,
                     mesh=pipe.mesh, name="train_step")
    n_valid = jax.ShapeDtypeStruct((), np.int32)
    report.extend(analyze(trainer._eval_step, buf, x, tgt, key, n_valid,
                          mesh=pipe.mesh, name="eval_step"))
    report.name = "train_step + eval_step"
    return report


def _abstract_batch(pipe, batch: int, in_dim: int):
    import jax
    import numpy as np
    x = jax.ShapeDtypeStruct((batch, in_dim), np.float32)
    t = jax.ShapeDtypeStruct((batch,) + pipe.out_shape[:-1], np.int32)
    key = jax.ShapeDtypeStruct((), jax.random.key(0).dtype)
    return x, t, key


def dryrun_reports(n_devices: int) -> list[Report]:
    """Analyze the steps ``__graft_entry__.dryrun_multichip(n)`` executes:
    the GPipe train step on the same dp x pp x tp mesh split, the
    memory-flat eval, the ZeRO-1 + AdamW step when the mesh has a data
    axis, and the 1F1B step where >= 2 stages fit. One Report per step —
    the CI lint gate requires every one of them clean.
    """
    import jax

    from simple_distributed_machine_learning_tpu.models.mlp import (
        make_mlp_stages,
    )
    from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        Pipeline,
    )
    from simple_distributed_machine_learning_tpu.parallel.tensor import (
        make_mlp_tp_stages,
    )
    from simple_distributed_machine_learning_tpu.train import schedules
    from simple_distributed_machine_learning_tpu.train.optimizer import (
        adamw,
        clip_by_global_norm,
        sgd,
        shard_opt_state_zero1,
    )
    from simple_distributed_machine_learning_tpu.train.step import (
        make_train_step,
    )

    devices = jax.devices()
    if len(devices) < n_devices:
        raise SystemExit(
            f"analysis --dryrun {n_devices}: need {n_devices} devices, have "
            f"{len(devices)} (run under xla_force_host_platform_device_count)")
    key = jax.random.key(0)
    # identical topology selection to __graft_entry__.dryrun_multichip
    if n_devices % 8 == 0:
        n_stages, n_model = 2, 2
        n_data = n_devices // (n_stages * n_model)
        stages, wire_dim, out_dim = make_mlp_tp_stages(
            key, [16, 16, 16, 16, 10], n_stages, n_model)
        dims0 = 16
    else:
        n_stages = 4 if n_devices % 4 == 0 else (2 if n_devices % 2 == 0 else 1)
        n_model = 1
        n_data = n_devices // n_stages
        dims = [16] * n_stages + [10]
        stages, wire_dim, out_dim = make_mlp_stages(key, dims, n_stages)
        dims0 = dims[0]
    mesh = make_mesh(n_stages=n_stages, n_data=n_data, n_model=n_model,
                     devices=devices[:n_devices])
    n_micro = 2
    pipe = Pipeline(stages, mesh, wire_dim, out_dim, n_microbatches=n_micro)
    buf = abstractify(pipe.init_params())
    opt = sgd(0.1, momentum=0.5)
    opt_state = jax.eval_shape(opt.init, buf)
    step = make_train_step(pipe, opt)
    batch = 2 * n_micro * n_data
    x, t, k = _abstract_batch(pipe, batch, dims0)
    tag = f"{n_devices}dev dp={n_data} pp={n_stages} tp={n_model}"
    reports = [
        analyze(step, buf, opt_state, x, t, k, mesh=mesh,
                name=f"train_step[{tag}]"),
        analyze(jax.jit(pipe.eval_metrics), buf, x, t, k, mesh=mesh,
                name=f"eval_metrics[{tag}]"),
    ]

    if n_data > 1:
        opt_a = adamw(1e-3)
        st_a = jax.eval_shape(
            lambda b: shard_opt_state_zero1(opt_a.init(b), mesh,
                                            pipe.param_spec()), buf)
        step_a = make_train_step(pipe, opt_a)
        reports.append(analyze(step_a, buf, st_a, x, t, k, mesh=mesh,
                               name=f"zero1_adamw_step[{tag}]"))

    fb_stages = 2 if n_devices % 2 == 0 else 1
    if fb_stages >= 2:
        if n_devices % 8 == 0:
            fb_model = 2
            fstages, fwire, fout = make_mlp_tp_stages(
                key, [16, 16, 16, 16, 10], fb_stages, fb_model)
        else:
            fb_model = 1
            fstages, fwire, fout = make_mlp_stages(key, [16, 16, 10],
                                                   fb_stages)
        fb_data = n_devices // (fb_stages * fb_model)
        fmesh = make_mesh(n_stages=fb_stages, n_data=fb_data,
                          n_model=fb_model, devices=devices[:n_devices])
        fpipe = Pipeline(fstages, fmesh, fwire, fout, n_microbatches=2,
                         schedule="1f1b")
        fopt = clip_by_global_norm(
            sgd(schedules.warmup_cosine(0.1, 2, 20), 0.5), 1.0,
            fpipe.replication_weights())
        fbuf = abstractify(fpipe.init_params())
        fstate = jax.eval_shape(fopt.init, fbuf)
        fstep = make_train_step(fpipe, fopt)
        fx, ft, fk = _abstract_batch(fpipe, 4 * fb_data, 16)
        reports.append(analyze(
            fstep, fbuf, fstate, fx, ft, fk, mesh=fmesh,
            name=f"1f1b_step[{n_devices}dev dp={fb_data} pp={fb_stages} "
                 f"tp={fb_model}]"))
    return reports


def format_reports(reports: list[Report], costs: bool = False) -> str:
    return "\n".join(r.format(costs=costs) for r in reports)


def all_ok(reports: list[Report], fail_on: str = "error") -> bool:
    return all(r.ok(fail_on) for r in reports)
