"""CLI for the static analyzer — the standalone preflight gate.

Examples::

    # lint the exact steps dryrun_multichip(8) executes (CI runs 1..10)
    python -m simple_distributed_machine_learning_tpu.analysis --dryrun 8

    # run one seeded-defect fixture (exits non-zero when it flags, which a
    # defect fixture always must)
    python -m simple_distributed_machine_learning_tpu.analysis \
        --fixture dropped_grad_sync

    # self-test every fixture against its contract (defects flag, cleans
    # pass) — the CI lint job's other half
    python -m simple_distributed_machine_learning_tpu.analysis --fixtures

Exit code: 0 when every analyzed step satisfies ``--fail-on`` (default:
``warning`` for fixtures — a demonstration must demonstrate — and ``error``
for ``--dryrun``/preflights, where e.g. a deliberate-bf16 dtype warning must
not block a launch).
"""

from __future__ import annotations

import argparse
import sys


def _bootstrap_devices(n: int) -> None:
    """Virtual-CPU backend, same dance as __graft_entry__/tests: must run
    before the first jax operation; keep whatever exists if backends are
    already up (in-process callers)."""
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
        from simple_distributed_machine_learning_tpu.parallel.compat import (
            set_host_device_count,
        )
        set_host_device_count(n)
    except RuntimeError:
        pass


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m simple_distributed_machine_learning_tpu.analysis",
        description="static sharding & collective analyzer (preflight gate)")
    p.add_argument("--dryrun", type=int, default=None, metavar="N",
                   help="analyze the steps dryrun_multichip(N) executes on "
                        "an N-virtual-device mesh")
    p.add_argument("--serve", action="store_true",
                   help="lint the serving-program registry (cached decoder "
                        "+ slot/paged prefill, decode, CoW copy and the "
                        "composite tick) over the paged layout at two "
                        "block/chunk shapes, the dense layout, the "
                        "speculative pair and the serve supervisor's "
                        "degraded-fallback layout")
    p.add_argument("--serve-kernel", action="store_true",
                   help="kernel-only preflight over the same registry "
                        "sweep: every layout's Pallas kernel paths must "
                        "lint clean — zero kernel-family findings at ANY "
                        "severity (no unproven index maps), zero trace "
                        "failures (the gate ROADMAP #2's autotuner runs "
                        "every candidate through)")
    p.add_argument("--hostlint", action="store_true",
                   help="host-side AST lint: decode builders memoized "
                        "through _DECODE_BUILD_CACHE, no bypass call "
                        "sites in serve/ or tests/, no raw jax.jit in "
                        "serve/, journal writer/reader grammar "
                        "cross-check (pure ast, no tracing)")
    p.add_argument("--serve-protocol", action="store_true",
                   help="bounded model checking of the serve fleet "
                        "protocol: exhaustively explore every "
                        "tick/crash/handoff/adopt/shed/prefetch/retire "
                        "interleaving of an abstract 2-pool fleet to "
                        "--depth and prove the no-double-serve / "
                        "no-lost-request / refcount-conservation / "
                        "boarding-gate invariants (pure stdlib, no jax; "
                        "exit 2 on a violated invariant, each violation "
                        "prints its counterexample + exported chaos "
                        "schedule)")
    p.add_argument("--depth", type=int, default=None, metavar="N",
                   help="--serve-protocol exploration depth bound "
                        "(default: the clean model's pinned depth 8)")
    p.add_argument("--fixture", default=None, metavar="NAME",
                   help="run one seeded fixture (see --list)")
    p.add_argument("--fixtures", action="store_true",
                   help="self-test every fixture against its contract")
    p.add_argument("--list", action="store_true",
                   help="list fixtures and rule families")
    p.add_argument("--fail-on", choices=("error", "warning"), default=None,
                   help="finding severity that makes the exit code non-zero "
                        "(default: warning for fixtures, error for --dryrun)")
    p.add_argument("--costs", action="store_true",
                   help="print the bytes-over-ICI cost table per step")
    args = p.parse_args(argv)

    if args.list:
        from simple_distributed_machine_learning_tpu.analysis.fixtures import (
            FIXTURES,
        )
        print("rule families: ppermute-deadlock unreduced-gradient "
              "mesh-axis dtype-drift donation scatter-bounds "
              "retrace-explosion sharded-state hostlint journal-grammar "
              "protocol kernel-oob kernel-unproven kernel-race "
              "kernel-tile kernel-dtype-drift kernel-hbm")
        print("fixtures:")
        for fx in FIXTURES.values():
            kind = "defect" if fx.defect else "clean"
            print(f"  {fx.name:<24} [{kind:>6}] {fx.description}")
        return 0

    if not (args.hostlint or args.serve or args.serve_kernel or args.fixtures
            or args.serve_protocol or args.fixture is not None
            or args.dryrun is not None):
        p.error("nothing to do: pass --dryrun N, --serve, --serve-kernel, "
                "--hostlint, --serve-protocol, --fixture NAME, --fixtures "
                "or --list")
    if args.dryrun is not None and args.dryrun < 1:
        p.error(f"--dryrun needs a positive device count, got "
                f"{args.dryrun}")

    # Modes compose: every requested mode runs and the exit code ANDs the
    # results (a combined `--serve --hostlint` must not silently drop one
    # gate).  Bootstrap once, sized for the most demanding requested mode —
    # --hostlint and --serve-protocol alone stay jax-free (pure ast /
    # pure stdlib; pinned by a purge-and-block subprocess test).
    need = max(1 if (args.serve or args.serve_kernel) else 0,
               8 if (args.fixtures or args.fixture is not None) else 0,
               args.dryrun or 0)
    if need:
        _bootstrap_devices(need)
    ok = True
    protocol_violated = False

    if args.hostlint:
        import os as _os

        from simple_distributed_machine_learning_tpu.analysis.hostlint import (
            lint_repo,
        )
        report = lint_repo()
        # the SDML_LINT_INJECT gate drill, mirrored inline (importing
        # programs.py's helper would pull jax into this jax-free mode)
        tag = _os.environ.get("SDML_LINT_INJECT")
        if tag:
            from simple_distributed_machine_learning_tpu.analysis.report import (  # noqa: E501
                Finding,
                Severity,
            )
            report.findings.append(Finding(
                rule=f"injected.{tag}", severity=Severity.ERROR,
                message="seeded ERROR finding injected via "
                        "SDML_LINT_INJECT — the gate drill proving "
                        "--lint preflights actually fail",
                where="SDML_LINT_INJECT", hint="unset SDML_LINT_INJECT"))
        print(report.format(costs=False))
        host_ok = report.ok(args.fail_on or "error")
        print(f"analysis --hostlint: {'clean' if host_ok else 'FLAGGED'}")
        ok &= host_ok

    if args.serve:
        from simple_distributed_machine_learning_tpu.analysis.programs import (
            default_registry_reports,
        )
        reports = default_registry_reports()
        for r in reports:
            print(r.format(costs=args.costs))
        fail_on = args.fail_on or "error"
        serve_ok = all(r.ok(fail_on) for r in reports)
        print(f"analysis --serve: {len(reports)} layouts "
              f"{'clean' if serve_ok else 'FLAGGED'}")
        ok &= serve_ok

    if args.serve_kernel:
        from simple_distributed_machine_learning_tpu.analysis.kernels import (
            KERNEL_FAMILIES,
        )
        from simple_distributed_machine_learning_tpu.analysis.programs import (
            default_registry_reports,
        )
        reports = default_registry_reports()
        gating = [f for r in reports for f in r.findings
                  if f.family in KERNEL_FAMILIES or f.rule == "trace.failed"]
        for f in gating:
            print("\n".join("  " + ln for ln in f.format().splitlines()))
        for r in reports:
            rows = [h for h in r.hbm if h.op.startswith("kernel.")]
            if rows:
                print(f"{r.name}: "
                      + ", ".join(f"{h.program} {h.op}="
                                  f"{h.bytes_per_tick}B" for h in rows))
        # kernel paths gate at ANY severity (zero unproven is the
        # contract), and the whole report must still be ERROR-free so the
        # SDML_LINT_INJECT drill trips this preflight too
        kern_ok = (not gating
                   and all(r.ok(args.fail_on or "error") for r in reports))
        print(f"analysis --serve-kernel: {len(reports)} layouts "
              f"{'kernel-clean' if kern_ok else 'FLAGGED'}")
        ok &= kern_ok

    if args.serve_protocol:
        import dataclasses as _dc
        import os as _os

        from simple_distributed_machine_learning_tpu.analysis.protocol import (
            INVARIANTS,
            CLEAN,
            check_protocol,
        )
        cfg = CLEAN if args.depth is None else _dc.replace(
            CLEAN, depth=args.depth)
        report = check_protocol(cfg)
        # the SDML_LINT_INJECT gate drill, mirrored inline (importing
        # programs.py's helper would pull jax into this jax-free mode)
        tag = _os.environ.get("SDML_LINT_INJECT")
        if tag:
            from simple_distributed_machine_learning_tpu.analysis.report import (  # noqa: E501
                Finding,
                Severity,
            )
            report.findings.append(Finding(
                rule=f"injected.{tag}", severity=Severity.ERROR,
                message="seeded ERROR finding injected via "
                        "SDML_LINT_INJECT — the gate drill proving "
                        "--lint preflights actually fail",
                where="SDML_LINT_INJECT", hint="unset SDML_LINT_INJECT"))
        print(report.format(costs=False))
        print(f"model: {cfg.summary()}")
        print(f"invariants: {', '.join(INVARIANTS)}")
        print(f"verdict: {report.verdict}")
        proto_ok = report.ok(args.fail_on or "error")
        print(f"analysis --serve-protocol: "
              f"{'clean' if proto_ok else 'FLAGGED'}")
        ok &= proto_ok
        protocol_violated |= not proto_ok

    if args.fixtures:
        from simple_distributed_machine_learning_tpu.analysis.fixtures import (
            self_test,
        )
        fx_ok, text = self_test()
        print(text)
        print(f"fixture self-test: {'OK' if fx_ok else 'FAILED'}")
        ok &= fx_ok

    if args.fixture is not None:
        from simple_distributed_machine_learning_tpu.analysis.fixtures import (
            FIXTURES,
        )
        if args.fixture not in FIXTURES:
            p.error(f"unknown fixture {args.fixture!r} (see --list)")
        report = FIXTURES[args.fixture].build()
        print(report.format(costs=args.costs))
        ok &= report.ok(args.fail_on or "warning")

    if args.dryrun is not None:
        from simple_distributed_machine_learning_tpu.analysis.preflight import (
            all_ok,
            dryrun_reports,
        )
        reports = dryrun_reports(args.dryrun)
        for r in reports:
            print(r.format(costs=args.costs))
        fail_on = args.fail_on or "error"
        dry_ok = all_ok(reports, fail_on)
        print(f"analysis --dryrun {args.dryrun}: "
              f"{len(reports)} steps {'clean' if dry_ok else 'FLAGGED'}")
        ok &= dry_ok

    # a violated protocol invariant is the loudest possible failure: its
    # own exit code (2), distinct from ordinary lint findings (1), so CI
    # and scripts can branch on "the protocol itself is broken"
    return 0 if ok else (2 if protocol_violated else 1)


if __name__ == "__main__":
    sys.exit(main())
