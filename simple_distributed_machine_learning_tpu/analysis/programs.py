"""The compiled-program registry: one call lints everything a launch runs.

PR 3's preflight covered the train/eval step; since the serving subsystem
landed, the riskiest compiled code is the DECODE path — five programs
(``models/gpt.py``: ``make_slot_prefill``/``make_slot_decode_step`` for the
dense layout, ``make_paged_prefill_chunk``/``make_paged_decode_step``/
``make_paged_block_copy`` for the paged one, plus ``make_cached_decoder``,
the solo-parity anchor) whose failure modes are silent: an out-of-range
block-table index scatters K/V into another request's blocks, a CoW copy
reads a buffer the prefill already donated, a per-prompt-length retrace
explodes the trace cache under real traffic. This module enumerates those
entry points with ABSTRACT-ARG BUILDERS — each argument carries the value
contract the host side (``serve/slots.py``) maintains, declared via
``analysis.spec`` — so ``lint_serve`` traces and lints the exact programs a
serve tick will execute, plus a composite tick that threads donated buffers
across program boundaries the way ``serve/engine.py`` does.

What runs per program:

- the full PR-3 rule walk (donation incl. double-donation, mesh-axis,
  dtype-drift — serving is single-device, so collective families are
  vacuous here but the walk still guards regressions);
- the ``scatter-bounds`` interval pass (``analysis/bounds.py``) against the
  declared contracts — block-table gathers proven within ``n_blocks + 1``,
  position counters within ``block_size``/``max_len``: the trash-page and
  trailing-zero disciplines ``serve/slots.py`` argues in prose,
  machine-checked against the compiled artifact;
- the ``retrace-explosion`` policy checks (builders memoized through
  ``_DECODE_BUILD_CACHE``; trace keys with unbounded runtime shapes
  flagged unless the deployment bounds them — prompt-length buckets or a
  ``prefill_chunk``);
- the HBM-bytes-per-tick cost model (:class:`~.report.HBMCost`): the
  serving twin of the ICI table — K/V bytes gathered/scattered per decode
  tick as a function of block size and slot count, plus
  :func:`predict_kv_bytes_resident`, cross-checked against the pool's
  ``serve_kv_bytes_resident`` gauge in tests.

Since ISSUE 9 the registry also covers sharded + speculative serving: with
``cfg.n_tensor_parallel > 1`` (pass the live ``mesh``) every serving
program is rebuilt as its exact ``shard_map`` twin — head-sharded pool,
packed Megatron weights — and the mesh-axis + scatter-bounds rules walk
the sharded block gathers; with ``spec_k >= 2`` (pass the draft build) the
draft propose scan, the batched verify step and a composite speculative
tick join the registry, and the HBM model reports PER-SHARD bytes plus the
verify/propose streams.

Entry points::

    spec = ServeSpec(cfg, n_slots=4, kv_layout="paged", block_size=16,
                     prefill_chunk=8, prompt_lens=(4, 8, 12))
    report = lint_serve(stages, spec)         # one Report, all programs
    report = lint_engine(engine)              # a live engine's exact knobs

``SDML_LINT_INJECT=<tag>`` (environment) appends one seeded ERROR finding
to every ``lint_serve`` report — the resilience-style drill that proves the
``--lint`` gates actually exit nonzero (CI and tests use it; never set it
in a real launch).
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Any, Callable

from simple_distributed_machine_learning_tpu.analysis import (
    Report,
    abstractify,
    analyze,
    spec,
)
from simple_distributed_machine_learning_tpu.analysis.report import (
    Finding,
    HBMCost,
    Severity,
)


@dataclasses.dataclass(frozen=True)
class ServeSpec:
    """Static description of one serving deployment — what the registry
    needs to rebuild the exact compiled programs and their contracts.

    ``prompt_lens`` declares the deployment's prompt-length buckets (the
    simulator's ``GPT_SERVE_PROMPTS``, a real frontend's bucketing): the
    retrace-explosion rule treats a prompt-shaped trace key as bounded iff
    buckets are declared or chunked prefill bounds the shapes.

    Tensor parallelism rides in ``cfg.n_tensor_parallel`` (the engine's
    own knob — :attr:`tp` reads it); ``lint_serve`` then needs the live
    ``mesh`` to rebuild the sharded programs. ``spec_k``/``draft_cfg``
    declare speculative decoding (``lint_serve`` additionally needs the
    ``draft_stages`` build to trace the propose/verify pair)."""
    cfg: Any
    n_slots: int = 4
    max_len: int | None = None          # None -> cfg.seq_len
    kv_layout: str = "paged"
    block_size: int = 16
    n_blocks: int | None = None         # None -> dense-equivalent capacity
    prefill_chunk: int | None = None
    cache_dtype: Any = None
    prompt_lens: tuple | None = None
    spec_k: int = 0                     # 0 -> plain decode (no draft)
    draft_cfg: Any = None
    # the decode/verify attention path: "dense" gather-then-dense (two
    # passes over resident K/V per tick) or "fused" (the Pallas
    # paged-attention kernel's single pass) — the HBM model's per-tick
    # rows and the registry's built programs both key off it
    attn_kernel: str = "dense"
    # the host-RAM offload tier (paged only — serve/slots.py): evicted
    # prefix blocks demote to a host-side LRU of this many blocks instead
    # of dying; 0 disables the tier (and the host rows of the HBM model).
    # prefetch_ticks is the async host->HBM upload latency in engine ticks
    host_cache_blocks: int = 0
    prefetch_ticks: int = 1
    # multi-tenant LoRA serving (ISSUE 20): ``n_adapters`` is the device
    # adapter bank's TOTAL row count (the engine's rule is n_slots + 1;
    # row 0 is the pinned all-zero base row) and ``adapter_rank`` the
    # low-rank width of every row; 0 disables adapters. When on, every
    # decode-path program is rebuilt as its ``adapters=True`` twin —
    # trailing traced ``(bank, aid[s])`` args — and the bank-row upload
    # program joins the registry.
    n_adapters: int = 0
    adapter_rank: int = 0

    @property
    def adapters_on(self) -> bool:
        return self.n_adapters > 0 and self.adapter_rank > 0

    @property
    def tp(self) -> int:
        """Tensor-parallel width — the cfg's own knob, surfaced so the
        HBM model and per-shard byte accounting read one source."""
        return int(getattr(self.cfg, "n_tensor_parallel", 1))

    @property
    def ml(self) -> int:
        return int(self.max_len if self.max_len is not None
                   else self.cfg.seq_len)

    @property
    def blocks_per_seq(self) -> int:
        return math.ceil(self.ml / self.block_size)

    @property
    def nb(self) -> int:
        """Resolved pool capacity in blocks (the engine's default rule)."""
        if self.n_blocks is not None:
            return int(self.n_blocks)
        return self.n_slots * self.blocks_per_seq

    @property
    def resolved_chunk(self) -> int:
        """The prefill-chunk length the compiled program actually traces
        for this deployment: the declared chunk, else the largest prompt
        bucket (whole-remaining-prompt chunks compile per prompt shape),
        else 8; clamped to [1, ml-1]. The HBM model MUST use this same
        rule — a table row for a chunk the registry never built would
        mis-state the linted program's bytes."""
        c = self.prefill_chunk
        if c is None:
            c = int(max(self.prompt_lens)) if self.prompt_lens else 8
        return max(1, min(int(c), self.ml - 1))


@dataclasses.dataclass(frozen=True)
class Program:
    """One registry entry: a built (memoized) callable plus the abstract
    args — with declared contracts — that one serve tick would feed it."""
    name: str
    fn: Callable
    args: tuple


def check_builder_memo(name: str, build: Callable[[], Any]) -> list[Finding]:
    """The ``_DECODE_BUILD_CACHE`` contract, machine-checked: calling a
    decode-path builder twice with identical static config must return the
    SAME callable (and therefore the same compiled executables). A builder
    that returns fresh objects recompiles per engine/test instance — the
    retrace-explosion failure mode at the build level."""
    first, second = build(), build()
    if first is second:
        return []
    return [Finding(
        rule="retrace-explosion.unmemoized-builder", severity=Severity.ERROR,
        message=(f"builder '{name}' returned a DIFFERENT callable for an "
                 f"identical static config — every engine (and every test) "
                 f"constructing it pays a fresh trace + XLA compile"),
        where=name,
        hint="route the build through models.gpt._DECODE_BUILD_CACHE "
             "(_memo_build) keyed on the static config")]


def _retrace_finding(name: str, axis: str, sspec: ServeSpec) -> list[Finding]:
    """Flag a builder whose trace key includes an unbounded runtime value
    (a per-prompt-length retrace) unless the deployment bounds it."""
    if sspec.prompt_lens is not None:
        return []
    return [Finding(
        rule="retrace-explosion.unbounded-trace-key",
        severity=Severity.WARNING,
        message=(f"'{name}' retraces per distinct {axis}, and this "
                 f"deployment declares no bound on it — under real traffic "
                 f"every new length is a fresh trace + XLA compile (the "
                 f"trace cache grows without limit)"),
        where=name,
        hint="bucket prompt lengths (ServeSpec.prompt_lens / the "
             "simulator's buckets) or serve the paged layout with a "
             "prefill_chunk, which bounds prefill shapes to the chunk "
             "size")]


# -- abstract-arg builders -------------------------------------------------

def _key_sds():
    import jax
    return jax.ShapeDtypeStruct((), jax.random.key(0).dtype)


def _sds(shape, dtype):
    import jax
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _cache_sds(shape, cache_dtype):
    """Abstract pool buffer for ``shape`` under ``cache_dtype``: a plain
    struct, or the QuantKV (data + per-row scale plane) pytree a
    quantized pool actually threads through every tick program."""
    import numpy as np

    from simple_distributed_machine_learning_tpu.models.gpt import (
        QuantKV,
        _cache_dtype,
        _is_quantized_dtype,
    )
    if _is_quantized_dtype(cache_dtype):
        return QuantKV(_sds(shape, _cache_dtype(cache_dtype)),
                       _sds(shape[:-1], np.float32))
    return _sds(shape, _cache_dtype(cache_dtype))


def build_registry(stages, sspec: ServeSpec, mesh=None, draft_stages=None
                   ) -> tuple[list[Program], list[Finding]]:
    """Build every compiled program of ``sspec``'s serve path with its
    abstract args + contracts; returns (programs, policy findings) where
    the findings are the retrace/memo checks that are not jaxpr rules.

    With ``sspec.tp > 1`` pass the live ``mesh`` — the registry then
    builds the EXACT shard_map programs a TP engine runs (head-sharded
    pool, packed Megatron weights). With ``sspec.spec_k >= 2`` pass the
    ``draft_stages`` build — the draft propose scan, the batched verify
    and a composite speculative tick join the registry."""
    import numpy as np

    from simple_distributed_machine_learning_tpu.models.gpt import (
        _cache_dtype,
        make_cached_decoder,
        make_paged_block_copy,
        make_paged_decode_step,
        make_paged_prefill_chunk,
        make_slot_decode_step,
        make_slot_prefill,
        pack_tp_serve_params,
    )

    cfg = sspec.cfg
    S, ml, bs = sspec.n_slots, sspec.ml, sspec.block_size
    V = cfg.vocab
    H = cfg.n_heads
    dh = cfg.d_model // H
    L = cfg.n_layers
    NB = sspec.blocks_per_seq
    n_blocks = sspec.nb
    cd = _cache_dtype(sspec.cache_dtype)
    dense_params = [s.params for s in stages]
    if sspec.tp > 1:
        # the TP serving layout: stacked Megatron block slices + replicated
        # embed/head (what the engine actually feeds the shard_map programs)
        params = abstractify(pack_tp_serve_params(dense_params, sspec.tp))
    else:
        params = abstractify(dense_params)

    f32 = _sds((), np.float32)
    f32S = _sds((S,), np.float32)
    kd1 = _sds((2,), np.uint32)
    kdS = _sds((S, 2), np.uint32)
    toks = spec((S,), np.int32, 0, V - 1)
    pos = spec((S,), np.int32, 0, ml - 1)
    top_ks = spec((S,), np.int32, 0, V)
    top_k1 = spec((), np.int32, 0, V)

    programs: list[Program] = []
    findings: list[Finding] = []

    # the cached decoder: the solo-parity anchor every served request is
    # bit-exact against — linted at one representative bucket (always the
    # dense single-device build, whatever the serving layout/tp)
    t0 = int(min(sspec.prompt_lens)) if sspec.prompt_lens else min(4, ml - 1)
    t0 = max(1, min(t0, ml - 1))
    n_new = ml - t0
    # the solo anchor decodes dense rows: a quantized serving dtype
    # widens to f32 there (quantized pools are judged against it at
    # pinned tolerance, not bit-exactness)
    from simple_distributed_machine_learning_tpu.models.gpt import (
        _is_quantized_dtype as _is_q,
    )
    anchor_cd = None if _is_q(sspec.cache_dtype) else sspec.cache_dtype
    findings += check_builder_memo(
        "make_cached_decoder",
        lambda: make_cached_decoder(stages, cfg_dense(cfg), t0, n_new,
                                    cache_dtype=anchor_cd))
    findings += _retrace_finding("make_cached_decoder",
                                 "(prompt_len, n_new) pair", sspec)
    programs.append(Program(
        "cached_decoder",
        make_cached_decoder(stages, cfg_dense(cfg), t0, n_new,
                            cache_dtype=anchor_cd),
        (abstractify(dense_params), spec((1, t0), np.int32, 0, V - 1),
         _key_sds())))

    K = int(sspec.spec_k)
    speculative = K >= 2 and draft_stages is not None
    valid_n = spec((S,), np.int32, 0, K) if speculative else None
    drafts_a = spec((S, K), np.int32, 0, V - 1) if speculative else None
    qrows_a = _sds((S, K, V), np.float32) if speculative else None

    # the multi-tenant adapter bank and its index contracts: the bank is
    # TRACED data (hot-swap never retraces), the per-slot adapter ids are
    # gathers into [0, n_adapters) — the scatter-bounds pass proves the
    # bank-row gathers and the upload's row scatter stay inside the bank
    bank = aid1 = aids = None
    if sspec.adapters_on:
        from simple_distributed_machine_learning_tpu.models.gpt import (
            make_adapter_bank_update,
        )
        N, r, d = sspec.n_adapters, sspec.adapter_rank, cfg.d_model
        bank = {"aq": _sds((N, L, d, r), np.float32),
                "bq": _sds((N, L, r, d), np.float32),
                "av": _sds((N, L, d, r), np.float32),
                "bv": _sds((N, L, r, d), np.float32)}
        row_a = {"aq": _sds((L, d, r), np.float32),
                 "bq": _sds((L, r, d), np.float32),
                 "av": _sds((L, d, r), np.float32),
                 "bv": _sds((L, r, d), np.float32)}
        aid1 = spec((), np.int32, 0, N - 1)
        aids = spec((S,), np.int32, 0, N - 1)
        findings += check_builder_memo("make_adapter_bank_update",
                                       make_adapter_bank_update)
        programs.append(Program(
            "adapter_bank_update", make_adapter_bank_update(),
            (bank, spec((), np.int32, 0, N - 1), row_a)))

    def _spec_draft_programs():
        """The draft propose scan + its abstract pool (dense slot layout
        whatever the target layout — the engine's draft discipline)."""
        from simple_distributed_machine_learning_tpu.models.gpt import (
            _cache_dtype,
            _is_quantized_dtype,
            make_slot_propose,
        )
        dcfg = sspec.draft_cfg
        dL = sum(len(p["blocks"]) for p in (s.params for s in draft_stages))
        # dense draft rows: a quantized TARGET dtype falls back to f32 for
        # the draft (the engine's rule — trace the program it actually runs)
        draft_cd = (None if _is_quantized_dtype(sspec.cache_dtype)
                    else sspec.cache_dtype)
        dkc = _sds((dL, S, dcfg.n_heads, ml,
                    dcfg.d_model // dcfg.n_heads), _cache_dtype(draft_cd))
        propose = make_slot_propose(draft_stages, dcfg, ml, K, draft_cd)
        memo = check_builder_memo(
            "make_slot_propose",
            lambda: make_slot_propose(draft_stages, dcfg, ml, K, draft_cd))
        dparams = abstractify([s.params for s in draft_stages])
        propose_args = (dparams, dkc, dkc, toks, pos, kdS, f32S, top_ks,
                        f32S)
        return propose, propose_args, memo

    if sspec.kv_layout == "dense":
        kc = _sds((L, S, H, ml, dh), cd)
        prefill = make_slot_prefill(stages, cfg, ml, sspec.cache_dtype,
                                    mesh=mesh)
        decode = make_slot_decode_step(stages, cfg, ml, sspec.cache_dtype,
                                       mesh=mesh)
        findings += check_builder_memo(
            "make_slot_prefill",
            lambda: make_slot_prefill(stages, cfg, ml, sspec.cache_dtype,
                                      mesh=mesh))
        findings += check_builder_memo(
            "make_slot_decode_step",
            lambda: make_slot_decode_step(stages, cfg, ml,
                                          sspec.cache_dtype, mesh=mesh))
        findings += _retrace_finding("make_slot_prefill", "prompt length",
                                     sspec)
        t0p = t0
        prefill_args = (params, kc, kc, spec((1, t0p), np.int32, 0, V - 1),
                        spec((), np.int32, 0, S - 1), kd1, f32, top_k1, f32)
        decode_args = (params, kc, kc, toks, pos, kdS, f32S, top_ks, f32S)
        programs.append(Program("slot_prefill", prefill, prefill_args))
        programs.append(Program("slot_decode", decode, decode_args))

        if sspec.adapters_on:
            findings += check_builder_memo(
                "make_slot_prefill[adapters]",
                lambda: make_slot_prefill(stages, cfg, ml,
                                          sspec.cache_dtype, mesh=mesh,
                                          adapters=True))
            findings += check_builder_memo(
                "make_slot_decode_step[adapters]",
                lambda: make_slot_decode_step(stages, cfg, ml,
                                              sspec.cache_dtype,
                                              mesh=mesh, adapters=True))
            programs.append(Program(
                "slot_prefill_adapter",
                make_slot_prefill(stages, cfg, ml, sspec.cache_dtype,
                                  mesh=mesh, adapters=True),
                prefill_args + (bank, aid1)))
            programs.append(Program(
                "slot_decode_adapter",
                make_slot_decode_step(stages, cfg, ml, sspec.cache_dtype,
                                      mesh=mesh, adapters=True),
                decode_args + (bank, aids)))

        # the composite tick: prefill -> decode with the pool buffers
        # THREADED the way engine.step does — donated-buffer flow across
        # the program boundary is what the donation rules walk here
        def dense_tick(params, kc, vc, prompt, slot, kd_1, t1, k1, p1,
                       toks, pos, kds, temps, tks, tps):
            kc, vc, tok, kd_1 = prefill(params, kc, vc, prompt, slot, kd_1,
                                        t1, k1, p1)
            kc, vc, toks2, kds2 = decode(params, kc, vc, toks, pos, kds,
                                         temps, tks, tps)
            return kc, vc, tok, toks2, kds2

        programs.append(Program(
            "dense_tick", dense_tick,
            prefill_args[:1] + (kc, kc) + prefill_args[3:]
            + decode_args[3:]))

        if speculative:
            from simple_distributed_machine_learning_tpu.models.gpt import (
                make_slot_verify_step,
            )
            propose, propose_args, memo = _spec_draft_programs()
            findings += memo
            verify = make_slot_verify_step(stages, cfg, ml, K,
                                           sspec.cache_dtype, mesh=mesh)
            findings += check_builder_memo(
                "make_slot_verify_step",
                lambda: make_slot_verify_step(stages, cfg, ml, K,
                                              sspec.cache_dtype,
                                              mesh=mesh))
            verify_args = (params, kc, kc, toks, pos, drafts_a, qrows_a,
                           valid_n, kdS, f32S, top_ks, f32S)
            programs.append(Program("slot_propose", propose, propose_args))
            programs.append(Program("slot_verify", verify, verify_args))

            # the composite speculative tick: propose (draft pool) ->
            # verify (target pool), proposals flowing between on device.
            # Single-device targets execute this as the engine's FUSED
            # make_slot_spec_tick program — lint exactly that build; a TP
            # engine dispatches the two halves separately, so the closure
            # composition below IS its tick
            if sspec.tp == 1:
                from simple_distributed_machine_learning_tpu.models.gpt import (  # noqa: E501
                    make_slot_spec_tick,
                )
                dcfg = sspec.draft_cfg
                dense_spec_tick = make_slot_spec_tick(
                    stages, cfg, draft_stages, dcfg, ml, K,
                    sspec.cache_dtype)
                findings += check_builder_memo(
                    "make_slot_spec_tick",
                    lambda: make_slot_spec_tick(stages, cfg, draft_stages,
                                                dcfg, ml, K,
                                                sspec.cache_dtype))
            else:
                def dense_spec_tick(dparams, dkc, dvc, params, kc, vc,
                                    toks, pos, valid, dkds, kds, temps,
                                    tks, tps):
                    dkc, dvc, drafts, qrows, dkds2 = propose(
                        dparams, dkc, dvc, toks, pos, dkds, temps, tks,
                        tps)
                    kc, vc, toks2, n_acc, kds2 = verify(
                        params, kc, vc, toks, pos, drafts, qrows, valid,
                        kds, temps, tks, tps)
                    return dkc, dvc, kc, vc, toks2, n_acc, kds2, dkds2

            programs.append(Program(
                "dense_spec_tick", dense_spec_tick,
                propose_args[:3] + (params, kc, kc, toks, pos, valid_n,
                                    kdS, kdS, f32S, top_ks, f32S)))
        return programs, findings

    # paged layout
    kc = _cache_sds((L, n_blocks + 1, H, bs, dh), sspec.cache_dtype)
    kernel = sspec.attn_kernel
    tables = spec((S, NB), np.int32, 0, n_blocks)
    table1 = spec((NB,), np.int32, 0, n_blocks)
    c = sspec.resolved_chunk
    chunk = make_paged_prefill_chunk(stages, cfg, ml, bs,
                                     sspec.cache_dtype, mesh=mesh)
    decode = make_paged_decode_step(stages, cfg, ml, bs,
                                    sspec.cache_dtype, mesh=mesh,
                                    kernel=kernel)
    copy = make_paged_block_copy()
    findings += check_builder_memo(
        "make_paged_prefill_chunk",
        lambda: make_paged_prefill_chunk(stages, cfg, ml, bs,
                                         sspec.cache_dtype, mesh=mesh))
    findings += check_builder_memo(
        "make_paged_decode_step",
        lambda: make_paged_decode_step(stages, cfg, ml, bs,
                                       sspec.cache_dtype, mesh=mesh,
                                       kernel=kernel))
    findings += check_builder_memo("make_paged_block_copy",
                                   make_paged_block_copy)
    if sspec.prefill_chunk is None:
        findings += _retrace_finding("make_paged_prefill_chunk",
                                     "chunk (= whole-prompt) length", sspec)

    chunk_args = (params, kc, kc, spec((1, c), np.int32, 0, V - 1),
                  spec((), np.int32, 0, ml - 1 - c), table1, kd1, f32,
                  top_k1, f32)
    decode_args = (params, kc, kc, toks, pos, tables, kdS, f32S, top_ks,
                   f32S)
    copy_args = (kc, kc, spec((), np.int32, 1, n_blocks),
                 spec((), np.int32, 0, n_blocks))
    programs.append(Program("paged_prefill_chunk", chunk, chunk_args))
    programs.append(Program("paged_decode", decode, decode_args))
    programs.append(Program("paged_block_copy", copy, copy_args))

    if sspec.adapters_on:
        findings += check_builder_memo(
            "make_paged_prefill_chunk[adapters]",
            lambda: make_paged_prefill_chunk(stages, cfg, ml, bs,
                                             sspec.cache_dtype, mesh=mesh,
                                             adapters=True))
        findings += check_builder_memo(
            "make_paged_decode_step[adapters]",
            lambda: make_paged_decode_step(stages, cfg, ml, bs,
                                           sspec.cache_dtype, mesh=mesh,
                                           kernel=kernel, adapters=True))
        programs.append(Program(
            "paged_prefill_chunk_adapter",
            make_paged_prefill_chunk(stages, cfg, ml, bs,
                                     sspec.cache_dtype, mesh=mesh,
                                     adapters=True),
            chunk_args + (bank, aid1)))
        programs.append(Program(
            "paged_decode_adapter",
            make_paged_decode_step(stages, cfg, ml, bs, sspec.cache_dtype,
                                   mesh=mesh, kernel=kernel,
                                   adapters=True),
            decode_args + (bank, aids)))

    # the composite tick: chunk -> CoW copy -> decode, pool buffers
    # threaded exactly as engine.step/_ensure_writable_range thread them.
    # A read of the pre-call buffer after any stage donated it is the
    # cross-program read-after-donate the donation rules exist for.
    def paged_tick(params, kc, vc, tokens, p0, table, kd_1, t1, k1, p1,
                   dst, src, toks, pos, tables, kds, temps, tks, tps):
        kc, vc, tok, kd_1 = chunk(params, kc, vc, tokens, p0, table, kd_1,
                                  t1, k1, p1)
        kc, vc = copy(kc, vc, dst, src)
        kc, vc, toks2, kds2 = decode(params, kc, vc, toks, pos, tables,
                                     kds, temps, tks, tps)
        return kc, vc, tok, toks2, kds2

    programs.append(Program(
        "paged_tick", paged_tick,
        chunk_args[:1] + (kc, kc) + chunk_args[3:] + copy_args[2:]
        + decode_args[3:]))

    if speculative:
        from simple_distributed_machine_learning_tpu.models.gpt import (
            make_paged_verify_step,
        )
        propose, propose_args, memo = _spec_draft_programs()
        findings += memo
        verify = make_paged_verify_step(stages, cfg, ml, bs, K,
                                        sspec.cache_dtype, mesh=mesh,
                                        kernel=kernel)
        findings += check_builder_memo(
            "make_paged_verify_step",
            lambda: make_paged_verify_step(stages, cfg, ml, bs, K,
                                           sspec.cache_dtype, mesh=mesh,
                                           kernel=kernel))
        verify_args = (params, kc, kc, toks, pos, drafts_a, qrows_a,
                       valid_n, tables, kdS, f32S, top_ks, f32S)
        programs.append(Program("paged_propose", propose, propose_args))
        programs.append(Program("paged_verify", verify, verify_args))

        # single-device targets run the engine's FUSED make_paged_spec_tick
        # build; a TP engine dispatches the two halves separately (see the
        # dense branch's note)
        if sspec.tp == 1:
            from simple_distributed_machine_learning_tpu.models.gpt import (
                make_paged_spec_tick,
            )
            dcfg = sspec.draft_cfg
            paged_spec_tick = make_paged_spec_tick(
                stages, cfg, draft_stages, dcfg, ml, bs, K,
                sspec.cache_dtype, kernel=kernel)
            findings += check_builder_memo(
                "make_paged_spec_tick",
                lambda: make_paged_spec_tick(stages, cfg, draft_stages,
                                             dcfg, ml, bs, K,
                                             sspec.cache_dtype,
                                             kernel=kernel))
        else:
            def paged_spec_tick(dparams, dkc, dvc, params, kc, vc, toks,
                                pos, valid, tables, dkds, kds, temps, tks,
                                tps):
                dkc, dvc, drafts, qrows, dkds2 = propose(
                    dparams, dkc, dvc, toks, pos, dkds, temps, tks, tps)
                kc, vc, toks2, n_acc, kds2 = verify(
                    params, kc, vc, toks, pos, drafts, qrows, valid,
                    tables, kds, temps, tks, tps)
                return dkc, dvc, kc, vc, toks2, n_acc, kds2, dkds2

        programs.append(Program(
            "paged_spec_tick", paged_spec_tick,
            propose_args[:3] + (params, kc, kc, toks, pos, valid_n,
                                tables, kdS, kdS, f32S, top_ks, f32S)))
    return programs, findings


def cfg_dense(cfg):
    """The single-device twin of a (possibly TP) serving config — what the
    solo-parity anchor decodes with."""
    if getattr(cfg, "n_tensor_parallel", 1) == 1:
        return cfg
    return dataclasses.replace(cfg, n_tensor_parallel=1)


def degraded_spec(sspec: ServeSpec) -> ServeSpec:
    """The serve supervisor's degraded-fallback deployment for ``sspec`` —
    the SAME transform ``serve/supervisor.py::engine_factory`` applies when
    rebuilding past ``degrade_after`` restarts: speculation off, tensor
    parallelism off, dense slot rows.  Kept here as one function so the
    registry sweep (:func:`default_registry_reports`) lints the exact
    layout a chaos-stressed supervisor will rebuild into — a fallback that
    only exists on the worst day must be proven clean on every PR."""
    from simple_distributed_machine_learning_tpu.models.gpt import (
        _is_quantized_dtype,
    )
    return ServeSpec(cfg_dense(sspec.cfg), n_slots=sspec.n_slots,
                     max_len=sspec.max_len, kv_layout="dense",
                     # quantized blocks and the fused kernel are paged
                     # features: the dense fallback widens to f32 and
                     # dense-math attention (engine_factory's rule)
                     cache_dtype=(None
                                  if _is_quantized_dtype(sspec.cache_dtype)
                                  else sspec.cache_dtype),
                     prompt_lens=sspec.prompt_lens,
                     # the adapter bank SURVIVES degraded rebuilds —
                     # engine_factory's _adapter_kw applies to both
                     # branches (tenants keep serving on the worst day)
                     n_adapters=sspec.n_adapters,
                     adapter_rank=sspec.adapter_rank)


# -- the HBM-bytes-per-tick model ------------------------------------------

def hbm_tick_costs(sspec: ServeSpec, n_layers: int | None = None
                   ) -> list[HBMCost]:
    """Static K/V traffic per serve tick, the serving mirror of the ICI
    cost table. Shapes are static — the batched decode gathers EVERY
    slot's full table span every tick regardless of occupancy (that is the
    design: one compiled program serves every tick), so the per-tick
    stream sizes depend on block geometry and slot count only; what
    occupancy changes is the RESIDENT bytes
    (:func:`predict_kv_bytes_resident`)."""
    from simple_distributed_machine_learning_tpu.serve.slots import (
        kv_block_bytes,
    )
    cfg = sspec.cfg
    L = n_layers if n_layers is not None else cfg.n_layers
    H, dh = cfg.n_heads, cfg.d_model // cfg.n_heads
    S, ml = sspec.n_slots, sspec.ml
    tp = sspec.tp
    # K + V, one position, 1 layer — PER SHARD (the TP serving programs
    # split the head axis tp ways, so each chip streams H/tp heads).
    # Derived from kv_block_bytes so it IS the pool's bytes_per_block per
    # row — which makes quantized caches automatic: int8/fp8 data plus the
    # per-row f32 scale planes the kernel (and the dense-path dequant
    # gather) actually stream
    row = kv_block_bytes(1, H // tp, 1, dh, sspec.cache_dtype)
    shard = f" (per {tp}-way shard)" if tp > 1 else ""
    fused = sspec.attn_kernel == "fused"
    out: list[HBMCost] = []
    K = int(sspec.spec_k)
    if sspec.kv_layout == "paged":
        span = sspec.blocks_per_seq * sspec.block_size
        out.append(HBMCost(
            "decode.kv_gather", "paged_decode", S * L * span * row,
            note=f"{S} slots x {L} layers x {span}-row table span{shard}"
                 + (" — the fused kernel's single pass" if fused else "")))
        if not fused:
            # gather-then-dense materializes the gathered span and the
            # attention einsums read it back: a SECOND full pass of
            # resident K/V per tick — exactly what kernel='fused'
            # (ops/paged_attention.py) eliminates
            out.append(HBMCost(
                "decode.kv_attn_reread", "paged_decode",
                S * L * span * row,
                note=f"dense-math path rereads the materialized "
                     f"span{shard}; eliminated by kernel='fused'"))
        out.append(HBMCost(
            "decode.kv_scatter", "paged_decode", S * L * row,
            note=f"one position per slot per layer{shard}"))
        c = sspec.resolved_chunk
        out.append(HBMCost(
            "prefill.kv_scatter", "paged_prefill_chunk", c * L * row,
            note=f"{c}-token chunk{shard}"))
        out.append(HBMCost(
            "prefill.kv_gather", "paged_prefill_chunk", L * span * row,
            note=f"the chunk attends the gathered table span{shard}"))
        out.append(HBMCost(
            "cow.block_copy", "paged_block_copy",
            L * sspec.block_size * row,
            note=f"per copy-on-write divergence, all layers{shard}"))
        if sspec.host_cache_blocks:
            # the host offload tier's transfer-bandwidth bill: one whole
            # block (all layers, K+V, plus quantized scale planes — it IS
            # the pool's bytes_per_block) crosses the HBM<->host boundary
            # per demotion and per prefetch promotion. The pool's
            # host_transfer_bytes_total counter advances by exactly this
            # per move — predict_transfer_bytes reconciles it to zero
            # drift (tests/test_disagg.py)
            blk = kv_block_bytes(L, H // tp, sspec.block_size, dh,
                                 sspec.cache_dtype)
            out.append(HBMCost(
                "offload.demote_copy", "host_offload", blk,
                note=f"per HBM->host demotion: the evicted block, all "
                     f"layers{shard} — an eviction that would otherwise "
                     f"discard the prefix"))
            out.append(HBMCost(
                "offload.prefetch_upload", "host_offload", blk,
                note=f"per host->HBM promotion: one async-prefetched "
                     f"block, all layers{shard}, spread over "
                     f"{sspec.prefetch_ticks} tick(s)"))
        if K >= 2:
            out.append(HBMCost(
                "verify.kv_scatter", "paged_verify", S * L * K * row,
                note=f"{K} speculated positions per slot per layer{shard}"))
            out.append(HBMCost(
                "verify.kv_gather", "paged_verify", S * L * span * row,
                note=f"the verify queries attend the table span{shard}"
                     + (" — the fused kernel's single pass" if fused
                        else "")))
            if not fused:
                out.append(HBMCost(
                    "verify.kv_attn_reread", "paged_verify",
                    S * L * span * row,
                    note=f"dense-math path rereads the materialized "
                         f"span{shard}; eliminated by kernel='fused'"))
    else:
        out.append(HBMCost(
            "decode.kv_read", "slot_decode", S * L * ml * row,
            note=f"{S} rows x {L} layers x max_len={ml}{shard}"))
        out.append(HBMCost(
            "decode.kv_scatter", "slot_decode", S * L * row,
            note=f"one position per slot per layer{shard}"))
        if K >= 2:
            out.append(HBMCost(
                "verify.kv_scatter", "slot_verify", S * L * K * row,
                note=f"{K} speculated positions per slot per layer{shard}"))
            out.append(HBMCost(
                "verify.kv_read", "slot_verify", S * L * ml * row,
                note=f"the verify queries read the full rows{shard}"))
    if sspec.adapters_on:
        # the adapter bank's per-tick traffic: each slot gathers its
        # tenant's whole A/B row (4 planes x L layers, f32) per decode
        # dispatch, prefill gathers one row, and every hot-swap/first
        # admission scatters one row back. Billed with the SAME formula
        # as the resident gauge (models/lora.py::bank_bytes) so the rows
        # and predict_adapter_bytes can never disagree on a row's size.
        # Under TP the bq/bv planes are column-sliced per shard but the
        # aq/av gathers replicate — billed at the replicated full row.
        from simple_distributed_machine_learning_tpu.models import lora
        row_b = lora.bank_bytes(1, L, cfg.d_model, sspec.adapter_rank)
        paged = sspec.kv_layout == "paged"
        out.append(HBMCost(
            "decode.adapter_gather",
            "paged_decode" if paged else "slot_decode", S * row_b,
            note=f"{S} slots x one bank row ({L} layers, 4 low-rank "
                 f"planes, rank {sspec.adapter_rank}) — row 0 (base) "
                 f"gathers the same bytes of zeros"))
        out.append(HBMCost(
            "prefill.adapter_gather",
            "paged_prefill_chunk" if paged else "slot_prefill", row_b,
            note="the boarding request's one bank row"))
        out.append(HBMCost(
            "adapter.bank_upload", "adapter_bank_update", row_b,
            note="per hot-swap / first admission: one donated bank-row "
                 "rewrite (serve_adapter_swaps_total advances by 1)"))
    if K >= 2 and sspec.draft_cfg is not None:
        from simple_distributed_machine_learning_tpu.models.gpt import (
            _is_quantized_dtype,
        )
        dcfg = sspec.draft_cfg
        # the draft pool is dense slot rows; a quantized TARGET dtype
        # falls back to f32 for the draft (the engine's rule)
        draft_cd = (None if _is_quantized_dtype(sspec.cache_dtype)
                    else sspec.cache_dtype)
        drow = kv_block_bytes(1, dcfg.n_heads, 1,
                              dcfg.d_model // dcfg.n_heads, draft_cd)
        dL = dcfg.n_layers
        out.append(HBMCost(
            "propose.kv_read", "slot_propose", K * S * dL * ml * drow,
            note=f"{K} draft steps x {S} rows x {dL} draft layers x "
                 f"max_len={ml} (replicated draft)"))
        out.append(HBMCost(
            "propose.kv_scatter", "slot_propose", K * S * dL * drow,
            note="one position per draft step per slot per draft layer"))
    return out


def predict_kv_bytes_resident(sspec: ServeSpec, rows_per_seq,
                              n_layers: int | None = None) -> int:
    """Model of the pool's ``serve_kv_bytes_resident`` gauge: bytes the
    given live sequences pin, where each entry of ``rows_per_seq`` is one
    sequence's written-row count (``prompt_len + tokens_emitted - 1`` once
    decoding). Assumes no prefix sharing between the sequences — shared
    blocks make the true gauge strictly smaller, never larger, which is
    what makes the runtime KV-drift gauge (``serve_kv_drift_bytes`` =
    live − predicted) a leak detector: 0 without sharing, ≤ 0 with it,
    and > 0 only if the pool pins blocks the model says it cannot need.
    Dense layout: ``rows_per_seq`` is ignored — the dense pool pins every
    row up front, so the prediction is the full allocation. PER SHARD
    under TP — the pool's gauge reports per-chip bytes (heads split ``tp``
    ways), and this model must agree with it EXACTLY
    (tests/test_analysis_serve.py)."""
    from simple_distributed_machine_learning_tpu.serve.slots import (
        kv_block_bytes,
    )
    cfg = sspec.cfg
    L = n_layers if n_layers is not None else cfg.n_layers
    if sspec.kv_layout == "dense":
        per_row = kv_block_bytes(L, cfg.n_heads // sspec.tp, sspec.ml,
                                 cfg.d_model // cfg.n_heads,
                                 sspec.cache_dtype)
        return per_row * sspec.n_slots
    per_block = kv_block_bytes(L, cfg.n_heads // sspec.tp, sspec.block_size,
                               cfg.d_model // cfg.n_heads,
                               sspec.cache_dtype)
    blocks = sum(math.ceil(r / sspec.block_size) for r in rows_per_seq)
    return blocks * per_block


def predict_adapter_bytes(sspec: ServeSpec,
                          n_layers: int | None = None) -> int:
    """Model of the AdapterStore's ``serve_adapter_resident_bytes`` gauge:
    HBM the device adapter bank pins — the whole static allocation (every
    row, resident or not; the bank never reallocates). Computed with the
    store's OWN formula (:func:`~..models.lora.bank_bytes`), so the parity
    pin is exact by construction: any drift means the deployment spec and
    the live store describe different banks
    (tests/test_adapters.py pins predicted == live)."""
    if not sspec.adapters_on:
        return 0
    from simple_distributed_machine_learning_tpu.models import lora
    cfg = sspec.cfg
    L = n_layers if n_layers is not None else cfg.n_layers
    return lora.bank_bytes(sspec.n_adapters, L, cfg.d_model,
                           sspec.adapter_rank)


def _host_block_bytes(sspec: ServeSpec, n_layers: int | None = None) -> int:
    """One paged block's bytes for ``sspec`` — the pool's own
    ``bytes_per_block`` (per shard; quantized scale planes included), the
    unit both host-tier predictors below bill in."""
    from simple_distributed_machine_learning_tpu.serve.slots import (
        kv_block_bytes,
    )
    cfg = sspec.cfg
    L = n_layers if n_layers is not None else cfg.n_layers
    return kv_block_bytes(L, cfg.n_heads // sspec.tp, sspec.block_size,
                          cfg.d_model // cfg.n_heads, sspec.cache_dtype)


def predict_host_kv_bytes(sspec: ServeSpec, n_host_blocks: int,
                          n_layers: int | None = None) -> int:
    """Model of the pool's ``serve_host_bytes_resident`` gauge: bytes the
    host-RAM offload tier pins for ``n_host_blocks`` demoted blocks. The
    host tier stores whole blocks (the exact device layout, numpy-side),
    so the model is blocks x ``bytes_per_block`` — and like
    ``predict_kv_bytes_resident`` it must agree with the live gauge
    EXACTLY: any drift is an offload-tier accounting leak
    (tests/test_disagg.py pins drift == 0 mid-handoff, post-demote and
    with a prefetch in flight)."""
    return n_host_blocks * _host_block_bytes(sspec, n_layers)


def predict_transfer_bytes(sspec: ServeSpec, n_blocks: int,
                           n_layers: int | None = None) -> int:
    """Model of the pool's ``serve_host_transfer_bytes_total`` counter:
    every block crossing the HBM↔host boundary — demotions down,
    prefetch promotions up — moves exactly ``bytes_per_block``
    (quantized caches move the narrow data planes plus their f32 scales,
    so int8 blocks cross at roughly half the f32 bill). ``n_blocks`` is
    the move count (``host_demotes_total + host_promotes_total``); the
    prediction must equal the live counter exactly, same discipline as
    ``serve_kv_drift_bytes``."""
    return n_blocks * _host_block_bytes(sspec, n_layers)


# -- the one-call preflights -----------------------------------------------

def jnp_dtype_name(cache_dtype) -> str:
    import jax.numpy as jnp
    return jnp.dtype(cache_dtype).name


def _injected_findings() -> list[Finding]:
    tag = os.environ.get("SDML_LINT_INJECT")
    if not tag:
        return []
    return [Finding(
        rule=f"injected.{tag}", severity=Severity.ERROR,
        message="seeded ERROR finding injected via SDML_LINT_INJECT — the "
                "gate drill proving --lint preflights actually fail",
        where="SDML_LINT_INJECT", hint="unset SDML_LINT_INJECT")]


def lint_serve(stages, sspec: ServeSpec, name: str | None = None,
               mesh=None, draft_stages=None) -> Report:
    """Trace and lint every compiled program of one serving deployment;
    returns a single merged :class:`Report` carrying the findings of all
    rule families, the retrace/memo policy checks and the
    HBM-bytes-per-tick table. Pass the live ``mesh`` for a TP deployment
    (``sspec.tp > 1``) and the ``draft_stages`` build for a speculative
    one (``sspec.spec_k >= 2``)."""
    if sspec.tp > 1 and mesh is None:
        raise ValueError(
            f"lint_serve: sspec.cfg.n_tensor_parallel={sspec.tp} needs the "
            f"deployment's mesh to rebuild the sharded programs")
    if sspec.spec_k >= 2 and draft_stages is None:
        raise ValueError(
            f"lint_serve: sspec.spec_k={sspec.spec_k} needs the "
            f"draft_stages build to trace the propose/verify pair")
    programs, policy = build_registry(stages, sspec, mesh=mesh,
                                      draft_stages=draft_stages)
    n_layers = sum(len(p["blocks"]) for p in (s.params for s in stages))
    label = name or (f"serve[{sspec.kv_layout} slots={sspec.n_slots} "
                     f"max_len={sspec.ml}"
                     + (f" block={sspec.block_size}"
                        f" chunk={sspec.prefill_chunk}"
                        if sspec.kv_layout == "paged" else "")
                     + (" kernel=fused" if sspec.attn_kernel == "fused"
                        else "")
                     + (f" cache={jnp_dtype_name(sspec.cache_dtype)}"
                        if sspec.cache_dtype is not None else "")
                     + (f" tp={sspec.tp}" if sspec.tp > 1 else "")
                     + (f" spec_k={sspec.spec_k}" if sspec.spec_k
                        else "")
                     + (f" adapters={sspec.n_adapters}"
                        f"r{sspec.adapter_rank}"
                        if sspec.adapters_on else "") + "]")
    report = Report(name=label, findings=list(policy))
    kernel_rows: list[HBMCost] = []
    for prog in programs:
        sub = analyze(prog.fn, *prog.args, mesh=mesh,
                      name=f"{label}:{prog.name}")
        for f in sub.findings:
            report.findings.append(dataclasses.replace(
                f, where=f"{prog.name}: {f.where}" if f.where
                else prog.name))
        report.costs.extend(sub.costs)
        # kernel-derived HBM rows (analysis/kernels.py): what the traced
        # pallas_calls' own BlockSpecs say the program streams
        kernel_rows.extend(dataclasses.replace(h, program=prog.name)
                           for h in sub.hbm)
    report.hbm.extend(kernel_rows)
    model_rows = hbm_tick_costs(sspec, n_layers=n_layers)
    report.hbm.extend(model_rows)
    report.findings.extend(
        _reconcile_kernel_hbm(kernel_rows, model_rows, sspec))
    report.findings.extend(_injected_findings())
    return report


def _reconcile_kernel_hbm(kernel_rows: list[HBMCost],
                          model_rows: list[HBMCost],
                          sspec: ServeSpec) -> list[Finding]:
    """Cross-check the kernel-DERIVED K/V stream bytes (block shapes x the
    grid trips each index map depends on, from the traced pallas_calls)
    against the hand-built tick model's gather rows. The fused kernel's
    whole value claim — it deletes the 2x ``kv_attn_reread`` pass, reading
    resident K/V exactly once per tick — must be computed from the
    kernel's own BlockSpecs, not asserted: the two totals agree EXACTLY or
    the registry gate fails."""
    if sspec.attn_kernel != "fused":
        return []
    derived: dict[str, int] = {}
    for h in kernel_rows:
        if h.op == "kernel.kv_stream":
            derived[h.program] = derived.get(h.program, 0) + h.bytes_per_tick
    model = {(m.program, m.op): m.bytes_per_tick for m in model_rows}
    out: list[Finding] = []
    for prog, op in (("paged_decode", "decode.kv_gather"),
                     ("paged_verify", "verify.kv_gather")):
        want = model.get((prog, op))
        if want is None:
            continue
        got = derived.get(prog)
        if got is None:
            out.append(Finding(
                rule="kernel-hbm.mismatch", severity=Severity.ERROR,
                message=(f"attn_kernel='fused' but no pallas_call K/V "
                         f"stream was traced in {prog} — the registry "
                         f"linted a program that is not running the "
                         f"kernel it claims"),
                where=prog,
                hint="the engine/registry builder dropped the fused "
                     "kernel path; rebuild with kernel='fused' plumbed "
                     "through"))
        elif got != want:
            out.append(Finding(
                rule="kernel-hbm.mismatch", severity=Severity.ERROR,
                message=(f"{prog}: the traced kernels' BlockSpecs stream "
                         f"{got} K/V bytes/tick but the HBM tick model's "
                         f"{op} row says {want} — the fused single-pass "
                         f"claim (the deleted kv_attn_reread) no longer "
                         f"matches the kernel itself"),
                where=prog,
                hint="hbm_tick_costs and the kernel BlockSpecs are one "
                     "contract: fix whichever drifted"))
    return out


def default_registry_reports() -> list[Report]:
    """The CI lint gate's serve-program sweep: one tiny GPT build linted
    over the paged layout at two block/chunk shapes plus the dense layout,
    all with the simulator's prompt buckets declared — every report must
    be ERROR-free for the gate to pass (``--serve`` in the analysis
    CLI)."""
    import jax

    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )
    cfg = GPTConfig(vocab=32, seq_len=24, d_model=16, n_heads=2, n_layers=2)
    stages, _, _ = make_gpt_stages(jax.random.key(0), cfg, 1)
    import dataclasses as _dc
    draft_cfg = _dc.replace(cfg, n_layers=1)
    draft_stages, _, _ = make_gpt_stages(jax.random.key(1), draft_cfg, 1)
    buckets = (4, 8, 12)
    # the speculative paged layout runs the FUSED verify kernel (the
    # K-token variant of paged attention) so the registry sweep lints —
    # and HBM-reconciles — both fused tick shapes, not just K=1 decode
    spec_paged = ServeSpec(cfg, n_slots=4, kv_layout="paged", block_size=4,
                           prefill_chunk=3, prompt_lens=buckets, spec_k=4,
                           draft_cfg=draft_cfg, attn_kernel="fused")
    specs = [
        ServeSpec(cfg, n_slots=4, kv_layout="paged", block_size=4,
                  prefill_chunk=3, prompt_lens=buckets),
        ServeSpec(cfg, n_slots=4, kv_layout="paged", block_size=8,
                  prefill_chunk=None, prompt_lens=buckets),
        # the fused Pallas paged-attention kernel over an int8-quantized
        # pool (interpret mode off-TPU): the serving hot path's kernel
        # variant is linted exactly like the dense-math programs
        ServeSpec(cfg, n_slots=4, kv_layout="paged", block_size=4,
                  prefill_chunk=3, prompt_lens=buckets,
                  cache_dtype="int8", attn_kernel="fused"),
        ServeSpec(cfg, n_slots=4, kv_layout="dense", prompt_lens=buckets),
        # the multi-tenant adapter layouts (ISSUE 20): every decode-path
        # program's adapters=True twin plus the bank-row upload program,
        # bank sized by the engine's n_slots + 1 rule
        ServeSpec(cfg, n_slots=4, kv_layout="paged", block_size=4,
                  prefill_chunk=3, prompt_lens=buckets, n_adapters=5,
                  adapter_rank=2),
        ServeSpec(cfg, n_slots=4, kv_layout="dense", prompt_lens=buckets,
                  n_adapters=5, adapter_rank=2),
        # the speculative pair (draft propose + batched verify + composite
        # tick) on both layouts — TP deployments need a live multi-device
        # mesh, so the CLI/tests cover those where devices exist
        spec_paged,
        ServeSpec(cfg, n_slots=4, kv_layout="dense", prompt_lens=buckets,
                  spec_k=4, draft_cfg=draft_cfg),
    ]
    reports = [lint_serve(stages, s, draft_stages=(draft_stages
                                                   if s.spec_k else None))
               for s in specs]
    # the serve supervisor's degraded-fallback layout, derived from the
    # full speculative deployment by the SAME rule engine_factory applies
    # on a chaos-driven rebuild — explicitly named so the gate output
    # shows the fallback was proven, not assumed
    reports.append(lint_serve(
        stages, degraded_spec(spec_paged),
        name=f"serve[degraded fallback of paged spec_k={spec_paged.spec_k}"
             f": dense slots={spec_paged.n_slots} tp=1 spec_k=0]"))
    return reports


def engine_spec(engine, prompt_lens: tuple | None = None) -> ServeSpec:
    """The :class:`ServeSpec` of a LIVE engine — the one engine->spec
    mapping (layout, block geometry, chunk size, cache dtype, spec/draft
    shape) shared by the lint preflight and the runtime KV-drift gauge,
    so the two can never describe different deployments."""
    pool = engine.pool
    paged = engine.kv_layout == "paged"
    return ServeSpec(
        cfg=engine.cfg, n_slots=pool.n_slots, max_len=engine.max_len,
        kv_layout=engine.kv_layout,
        block_size=pool.block_size if paged else 16,
        n_blocks=pool.n_blocks if paged else None,
        prefill_chunk=engine.prefill_chunk,
        # pool.kc.dtype covers QuantKV too (its dtype property is the
        # narrow storage dtype, which round-trips through _cache_dtype)
        cache_dtype=pool.kc.dtype, prompt_lens=prompt_lens,
        spec_k=engine.spec_k if engine.speculative else 0,
        draft_cfg=engine.draft_cfg,
        attn_kernel=engine.attn_kernel,
        host_cache_blocks=getattr(pool, "host_cache_blocks", 0),
        prefetch_ticks=getattr(pool, "prefetch_ticks", 1),
        n_adapters=(0 if getattr(engine, "_adapters", None) is None
                    else engine._adapters.n_rows),
        adapter_rank=(0 if getattr(engine, "_adapters", None) is None
                      else engine._adapters.rank))


def lint_engine(engine, prompt_lens: tuple | None = None) -> Report:
    """Preflight a live :class:`~..serve.engine.InferenceEngine`'s EXACT
    programs — same layout, block geometry, chunk size and cache dtype the
    engine constructed (``InferenceEngine(lint=True)`` calls this at
    construction)."""
    return lint_serve(engine.stages, engine_spec(engine, prompt_lens),
                      mesh=engine.mesh, draft_stages=engine.draft_stages)
