"""Jaxpr tracing and traversal helpers for the static analyzer.

The analyzer never runs the step — it traces it to a ``ClosedJaxpr``
(:func:`trace_to_jaxpr`) and walks equations, recursing through every
sub-jaxpr a primitive carries (``scan``/``cond``/``switch`` bodies, ``pjit``
and ``custom_vjp`` call jaxprs, ``shard_map`` inner jaxprs, ``remat``
thunks). Everything here is version-tolerant over the jaxpr surface the
repo supports (jax 0.4.x through the 0.9 vma era): param keys are probed,
never assumed.
"""

from __future__ import annotations

from typing import Any, Iterator

import jax
from jax import core as jax_core

try:                                     # moved in newer jax
    from jax.extend import core as jex_core
    _JAXPR_TYPES = (jax_core.Jaxpr, jex_core.Jaxpr)
    _CLOSED_TYPES = (jax_core.ClosedJaxpr, jex_core.ClosedJaxpr)
except Exception:                         # pragma: no cover - old jax only
    _JAXPR_TYPES = (jax_core.Jaxpr,)
    _CLOSED_TYPES = (jax_core.ClosedJaxpr,)

# collectives the lint passes care about, by primitive name
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmin", "pmax", "ppermute", "pbroadcast",
    "all_gather", "all_to_all", "reduce_scatter", "axis_index",
})
# collectives that are a cross-device rendezvous (axis_index is free)
RENDEZVOUS_PRIMS = COLLECTIVE_PRIMS - {"axis_index"}


def is_jaxpr(x: Any) -> bool:
    return isinstance(x, _JAXPR_TYPES)


def is_closed(x: Any) -> bool:
    return isinstance(x, _CLOSED_TYPES)


def open_jaxpr(x: Any):
    """The underlying ``Jaxpr`` of a possibly-closed jaxpr."""
    return x.jaxpr if is_closed(x) else x


def subjaxprs(eqn) -> Iterator[tuple[str, int, Any]]:
    """Yield ``(param_key, index, open_jaxpr)`` for every jaxpr in the
    equation's params — the generic recursion the analyzer uses so new
    call-like primitives are walked without a per-primitive case."""
    for key, val in eqn.params.items():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for i, v in enumerate(vals):
            if is_jaxpr(v) or is_closed(v):
                yield key, i, open_jaxpr(v)


def all_primitives(jaxpr) -> frozenset:
    """Every primitive name reachable in a (closed) jaxpr, recursing
    through all sub-jaxprs via :func:`subjaxprs` — the coverage audit the
    serve-registry regression test pins: if a program emits a primitive the
    generic recursion cannot reach (a new call-like primitive whose jaxpr
    hides in an unprobed param), it will be missing here and the test
    snaps."""
    out: set = set()

    def walk(j):
        for eqn in open_jaxpr(j).eqns:
            out.add(eqn.primitive.name)
            for _key, _i, sub in subjaxprs(eqn):
                walk(sub)

    walk(open_jaxpr(jaxpr))
    return frozenset(out)


def norm_axes(axes: Any) -> tuple[str, ...]:
    """Collective axis params normalized to a tuple of NAMED axes (positional
    int axes from vmap land are not mesh axes and are dropped)."""
    if axes is None:
        return ()
    if isinstance(axes, (tuple, list, frozenset, set)):
        return tuple(a for a in axes if isinstance(a, str))
    return (axes,) if isinstance(axes, str) else ()


def eqn_axes(eqn) -> tuple[str, ...]:
    """The named mesh axes a collective equation operates over."""
    p = eqn.params
    return norm_axes(p.get("axes", p.get("axis_name")))


def source_line(eqn) -> str:
    """User-source summary of an equation, '' when jax kept none."""
    try:
        from jax._src import source_info_util
        return source_info_util.summarize(eqn.source_info)
    except Exception:
        return ""


def aval_bytes(aval) -> int:
    try:
        import numpy as np
        return int(aval.size) * int(np.dtype(aval.dtype).itemsize)
    except Exception:
        return 0


def is_low_precision(dtype) -> bool:
    """True for dtypes that silently drop accumulation increments well
    before fp32 does (the dtype-drift rule's definition of '<fp32')."""
    import numpy as np
    try:
        d = np.dtype(dtype)
    except TypeError:
        d = np.dtype(getattr(dtype, "dtype", "float32"))
    if d.kind not in "fV":                 # ints/bools accumulate exactly
        return False
    name = getattr(dtype, "name", d.name)
    return name in ("bfloat16", "float16", "float8_e4m3fn", "float8_e5m2")


def trace_to_jaxpr(fn, *abstract_args, **abstract_kwargs):
    """``jax.make_jaxpr`` over abstract (ShapeDtypeStruct) or concrete args.

    This is the analyzer's only interaction with the function under test —
    zero FLOPs, no device buffers. Raises whatever tracing raises; callers
    that want trace errors AS findings use ``analyze()``'s wrapping.
    """
    return jax.make_jaxpr(fn)(*abstract_args, **abstract_kwargs)


def shape_dtype(x) -> jax.ShapeDtypeStruct:
    """Abstract stand-in for an array (device buffers stay untouched)."""
    if isinstance(x, jax.ShapeDtypeStruct):
        return x
    return jax.ShapeDtypeStruct(jax.numpy.shape(x), x.dtype)


def abstractify(tree):
    """Pytree of abstract stand-ins for a pytree of arrays."""
    return jax.tree.map(shape_dtype, tree)
