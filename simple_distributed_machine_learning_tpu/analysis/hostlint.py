"""Host-side AST lint: the ``_DECODE_BUILD_CACHE`` discipline.

The jaxpr rules see compiled programs; this pass sees the PYTHON that
builds them. The discipline (models/gpt.py, PR 6): every decode-path
builder is memoized on its static config in ``_DECODE_BUILD_CACHE``, so a
fleet of engines (and a test suite full of them) shares one traced +
compiled program per config. Three ways the discipline rots, all cheap to
catch with ``ast`` and expensive to catch in production:

- ``hostlint.unmemoized-builder`` — a decode builder in ``models/gpt.py``
  whose body no longer routes through ``_memo_build`` (a refactor dropped
  the memo; every engine recompiles);
- ``hostlint.builder-bypass`` — a call site anywhere outside
  ``models/gpt.py`` invoking a private ``_build_*`` helper directly,
  skipping the memo the public ``make_*`` wraps around it;
- ``hostlint.cache-poke`` — code outside ``models/gpt.py`` touching
  ``_DECODE_BUILD_CACHE`` itself (clearing or seeding it from a distance);
- ``hostlint.raw-jit-in-serve`` — a ``jax.jit`` created inside ``serve/``:
  the serving layer's contract is that every compiled program comes from
  the memoized gpt builders, so a stray jit there is an unmemoized program
  by construction;
- ``hostlint.wall-clock-in-serve`` — a wall-clock or RNG CALL inside
  ``serve/`` (``time.time``/``monotonic``/``perf_counter``,
  ``datetime.now``, ``random.*``): the exact-pinned scenario suite and the
  journal-replay determinism contract (PRs 10-11) hold ONLY because every
  clock read goes through the injectable plumbing (``clock=`` default
  args, the simulator's VirtualClock) — referencing ``time.monotonic`` as
  a default is sanctioned, calling it inline is not;
- ``metric-catalog.undocumented`` — a metric name registered in
  ``serve/metrics.py`` or the telemetry SLO/attribution modules (any
  full-string constant matching the ``serve_*``/``train_*`` metric
  grammar) that ``telemetry/catalog.py`` cannot resolve to a HELP bullet:
  an instrument with no documentation renders ``HELP <name> (undocumented)``
  in the Prometheus exposition and tells an operator nothing. The catalog
  module is loaded by file path (it imports only ast/os/re), so this rule
  — like every other hostlint rule — runs without jax;
- ``journal-grammar.unread-event`` — a journal event kind some writer in
  ``serve/`` emits (a dict display with a constant ``"ev"`` key) that NO
  reader dispatches on: neither ``serve/journal.py::recover_state`` (the
  crash-recovery fold) nor the telemetry report reader compares the
  ``"ev"`` field against it. A record type nobody reads silently vanishes
  on recovery — the exact failure mode the protocol model checker
  (analysis/protocol.py) assumes away, so the grammar cross-check is what
  keeps the abstraction honest against the real writers.

Pure ``ast`` — no jax import, so the CI lint job runs it in milliseconds:
``python -m simple_distributed_machine_learning_tpu.analysis --hostlint``.
"""

from __future__ import annotations

import ast
import importlib.util
import os
import re

from simple_distributed_machine_learning_tpu.analysis.report import (
    Finding,
    Report,
    Severity,
)

# The memoized decode-path builders (mirrors models.gpt.DECODE_BUILDERS —
# tests/test_analysis_serve.py pins the two lists equal so this cannot
# silently drift from the real module).
DECODE_BUILDER_NAMES = (
    "make_cached_decoder",
    "make_slot_prefill",
    "make_slot_decode_step",
    "make_paged_prefill_chunk",
    "make_paged_decode_step",
    "make_paged_block_copy",
    "make_adapter_bank_update",
    "make_slot_propose",
    "make_slot_verify_step",
    "make_paged_verify_step",
    "make_slot_spec_tick",
    "make_paged_spec_tick",
)

_PKG = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO = os.path.dirname(_PKG)
GPT_PATH = os.path.join(_PKG, "models", "gpt.py")


def _calls_in(node) -> list:
    return [n for n in ast.walk(node) if isinstance(n, ast.Call)]


def _call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _jit_bindings(tree) -> tuple[set, set]:
    """Names a module binds to jax itself and to jit-like callables, so
    every spelling is caught: ``jax.jit``, ``import jax as j; j.jit``,
    ``from jax import jit [as q]``, ``from jax.experimental.pjit import
    pjit``."""
    jax_aliases, jit_names = {"jax"}, set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax":
                    jax_aliases.add(a.asname or "jax")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name in ("jit", "pjit"):
                        jit_names.add(a.asname or a.name)
            elif node.module and node.module.startswith("jax."):
                for a in node.names:
                    if a.name == "pjit":
                        jit_names.add(a.asname or "pjit")
    return jax_aliases, jit_names


#: wall-clock readers in the ``time`` module (sleep excluded: it consumes
#: time rather than reads it, and the simulator injects it explicitly)
_WALLCLOCK_TIME_FNS = ("time", "monotonic", "perf_counter", "time_ns",
                       "monotonic_ns", "perf_counter_ns")
_WALLCLOCK_DT_FNS = ("now", "utcnow", "today")


def _clock_bindings(tree) -> tuple[set, set, set, set]:
    """Names a module binds to the time/datetime/random modules and to
    wall-clock functions imported from them, mirroring ``_jit_bindings``'s
    alias resolution so every spelling is caught."""
    time_a, dt_a, rand_a, direct = set(), set(), set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    time_a.add(a.asname or "time")
                elif a.name == "datetime":
                    dt_a.add(a.asname or "datetime")
                elif a.name == "random":
                    rand_a.add(a.asname or "random")
        elif isinstance(node, ast.ImportFrom):
            if node.module == "time":
                for a in node.names:
                    if a.name in _WALLCLOCK_TIME_FNS:
                        direct.add(a.asname or a.name)
            elif node.module == "datetime":
                for a in node.names:
                    if a.name in ("datetime", "date"):
                        dt_a.add(a.asname or a.name)
            elif node.module == "random":
                for a in node.names:
                    direct.add(a.asname or a.name)
    return time_a, dt_a, rand_a, direct


def _wallclock_call(call: ast.Call, bindings) -> str | None:
    """The dotted name of a wall-clock/RNG read this Call performs, or
    None. Only CALLS count — ``clock=time.monotonic`` default-arg
    REFERENCES are the sanctioned injection points."""
    time_a, dt_a, rand_a, direct = bindings
    f = call.func
    if isinstance(f, ast.Name) and f.id in direct:
        return f.id
    if isinstance(f, ast.Attribute):
        root = f.value
        if isinstance(root, ast.Name):
            if root.id in time_a and f.attr in _WALLCLOCK_TIME_FNS:
                return f"{root.id}.{f.attr}"
            if root.id in rand_a:
                return f"{root.id}.{f.attr}"
            if root.id in dt_a and f.attr in _WALLCLOCK_DT_FNS:
                return f"{root.id}.{f.attr}"
        if (isinstance(root, ast.Attribute)
                and isinstance(root.value, ast.Name)
                and root.value.id in dt_a
                and f.attr in _WALLCLOCK_DT_FNS):
            return f"{root.value.id}.{root.attr}.{f.attr}"
    return None


def _is_jax_jit(node, jax_aliases: set, jit_names: set) -> bool:
    """A jit reference in any spelling (covers ``jax.jit(...)``,
    ``@jax.jit``, ``functools.partial(jax.jit, ...)`` operands, and the
    aliased forms ``_jit_bindings`` resolves)."""
    if (isinstance(node, ast.Attribute) and node.attr in ("jit", "pjit")
            and isinstance(node.value, ast.Name)
            and node.value.id in jax_aliases):
        return True
    return isinstance(node, ast.Name) and node.id in jit_names


def _where(path: str, node, repo: str = _REPO) -> str:
    rel = os.path.relpath(path, repo)
    return f"{rel}:{getattr(node, 'lineno', '?')}"


def lint_builder_definitions(gpt_path: str = GPT_PATH) -> list[Finding]:
    """Every decode builder's definition must route through the memo."""
    with open(gpt_path) as f:
        tree = ast.parse(f.read(), filename=gpt_path)
    findings: list[Finding] = []
    defs = {n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)}
    for name in DECODE_BUILDER_NAMES:
        fn = defs.get(name)
        if fn is None:
            findings.append(Finding(
                rule="hostlint.unmemoized-builder", severity=Severity.ERROR,
                message=f"decode builder '{name}' not found in "
                        f"{os.path.basename(gpt_path)} — the hostlint "
                        f"builder list is stale or the builder was removed",
                where=_where(gpt_path, tree),
                hint="update DECODE_BUILDER_NAMES alongside the builder"))
            continue
        if not any(_call_name(c) == "_memo_build" for c in _calls_in(fn)):
            findings.append(Finding(
                rule="hostlint.unmemoized-builder", severity=Severity.ERROR,
                message=(f"decode builder '{name}' no longer routes its "
                         f"build through _memo_build — every engine and "
                         f"test constructing it re-traces and re-compiles "
                         f"an identical program"),
                where=_where(gpt_path, fn),
                hint="wrap the build in _memo_build(key, build) keyed on "
                     "the static config (see the sibling builders)"))
    return findings


def _lint_call_sites(path: str, allow_jit: bool,
                     repo: str = _REPO,
                     check_clock: bool | None = None) -> list[Finding]:
    # historically the wall-clock rule rode on the serve/ (allow_jit)
    # gate; check_clock decouples them so determinism-pinned modules
    # OUTSIDE serve/ (the telemetry SLO pipeline) get clock-checked
    # without inheriting the raw-jit rule
    if check_clock is None:
        check_clock = not allow_jit
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    findings: list[Finding] = []
    jax_aliases, jit_names = _jit_bindings(tree)
    clock_bindings = _clock_bindings(tree)
    for node in ast.walk(tree):
        if (isinstance(node, (ast.Name, ast.Attribute))
                and (node.id if isinstance(node, ast.Name) else node.attr)
                == "_DECODE_BUILD_CACHE"):
            findings.append(Finding(
                rule="hostlint.cache-poke", severity=Severity.ERROR,
                message="_DECODE_BUILD_CACHE touched outside models/gpt.py "
                        "— the memo's invariants (keying, shared "
                        "executables) belong to its owner",
                where=_where(path, node, repo),
                hint="go through the public make_* builders"))
        if isinstance(node, ast.Call):
            name = _call_name(node)
            if name.startswith("_build_") and any(
                    name == "_build" + pub[len("make"):]
                    for pub in DECODE_BUILDER_NAMES):
                findings.append(Finding(
                    rule="hostlint.builder-bypass", severity=Severity.ERROR,
                    message=(f"direct call to private builder '{name}' "
                             f"skips the _DECODE_BUILD_CACHE memo — this "
                             f"call site compiles its own copy of the "
                             f"program"),
                    where=_where(path, node, repo),
                    hint=f"call the public "
                         f"make{name[len('_build'):]} instead"))
            if check_clock:
                clock = _wallclock_call(node, clock_bindings)
                if clock is not None:
                    findings.append(Finding(
                        rule="hostlint.wall-clock-in-serve",
                        severity=Severity.ERROR,
                        message=(f"'{clock}()' called inside a "
                                 f"determinism-pinned module (serve/ and "
                                 f"the telemetry SLO pipeline) — the "
                                 f"exact-pinned scenarios and journal "
                                 f"replay are deterministic ONLY because "
                                 f"every clock/RNG read goes through the "
                                 f"injectable plumbing"),
                        where=_where(path, node, repo),
                        hint="take the clock as an injectable default arg "
                             "(clock=time.monotonic) or use the "
                             "simulator's VirtualClock; seed randomness "
                             "explicitly"))
        if not allow_jit and _is_jax_jit(node, jax_aliases, jit_names):
            findings.append(Finding(
                rule="hostlint.raw-jit-in-serve", severity=Severity.ERROR,
                message="jax.jit created inside serve/ — serving programs "
                        "must come from the memoized models/gpt.py "
                        "builders, or every engine compiles its own",
                where=_where(path, node, repo),
                hint="add (or extend) a memoized make_* builder in "
                     "models/gpt.py and call that"))
    return findings


JOURNAL_PATH = os.path.join(_PKG, "serve", "journal.py")
TELEMETRY_REPORT_PATH = os.path.join(_PKG, "telemetry", "report.py")


def _is_ev_load(expr) -> bool:
    """``<x>["ev"]`` or ``<x>.get("ev", ...)`` — the two spellings the
    journal readers use to pull a record's event kind. Keyed on the
    literal ``"ev"`` so ``r.get("kind")`` dispatches (metrics records)
    never count as journal reads."""
    if (isinstance(expr, ast.Subscript)
            and isinstance(expr.slice, ast.Constant)
            and expr.slice.value == "ev"):
        return True
    return (isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr == "get" and expr.args
            and isinstance(expr.args[0], ast.Constant)
            and expr.args[0].value == "ev")


def _event_writes(path: str, repo: str = _REPO) -> list:
    """``(kind, where)`` for every journal record literal in a module: a
    dict display carrying a constant ``"ev"`` key with a constant string
    value — the shape every ``RequestJournal.log_*`` writer uses."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        for k, v in zip(node.keys, node.values):
            if (isinstance(k, ast.Constant) and k.value == "ev"
                    and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)):
                out.append((v.value, _where(path, node, repo)))
    return out


def _event_reads(path: str) -> set:
    """Every event kind a reader module dispatches on: string constants
    compared (``==`` or ``in (...)``) against a value that came from the
    ``"ev"`` key — directly (``ev.get("ev") == "restart"``) or through a
    variable (``kind = ev["ev"]; ... kind == "submit"``)."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    kind_vars: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and _is_ev_load(node.value):
            kind_vars.update(t.id for t in node.targets
                             if isinstance(t, ast.Name))
    kinds: set = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare) or len(node.ops) != 1:
            continue
        if not (_is_ev_load(node.left)
                or (isinstance(node.left, ast.Name)
                    and node.left.id in kind_vars)):
            continue
        comp = node.comparators[0]
        if (isinstance(node.ops[0], ast.Eq)
                and isinstance(comp, ast.Constant)
                and isinstance(comp.value, str)):
            kinds.add(comp.value)
        elif (isinstance(node.ops[0], ast.In)
                and isinstance(comp, (ast.Tuple, ast.List, ast.Set))):
            kinds.update(e.value for e in comp.elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str))
    return kinds


def lint_journal_grammar(writer_paths=None, reader_paths=None,
                         repo: str = _REPO) -> list[Finding]:
    """The writer/reader cross-check: every event kind any ``serve/``
    writer emits must have a dispatching reader in ``recover_state`` or
    the telemetry report — AST-checked, so a new record type can never
    silently vanish on recovery. Paths are parameterizable so the tests
    can lint seeded-defect modules."""
    if writer_paths is None:
        serve_dir = os.path.join(_PKG, "serve")
        writer_paths = [os.path.join(serve_dir, f)
                        for f in sorted(os.listdir(serve_dir))
                        if f.endswith(".py")]
    if reader_paths is None:
        reader_paths = [JOURNAL_PATH, TELEMETRY_REPORT_PATH]
    read: set = set()
    for p in reader_paths:
        read |= _event_reads(p)
    findings: list[Finding] = []
    for p in writer_paths:
        for kind, where in _event_writes(p, repo):
            if kind not in read:
                findings.append(Finding(
                    rule="journal-grammar.unread-event",
                    severity=Severity.ERROR,
                    message=(f"journal event kind '{kind}' is written "
                             f"here but NO reader dispatches on it — "
                             f"neither recover_state nor the telemetry "
                             f"report compares the 'ev' field against "
                             f"'{kind}', so the record silently vanishes "
                             f"on recovery/replay"),
                    where=where,
                    hint="add a recover_state branch (or a report reader) "
                         "for the new kind, and a transition for it in "
                         "the protocol model (analysis/protocol.py)"))
    return findings


#: modules whose full-string ``serve_*``/``train_*`` constants ARE metric
#: names (verified by inspection — no span names or jsonl kinds match the
#: grammar here); the catalog rule scans exactly these.
_METRIC_FILES = (("serve", "metrics.py"), ("telemetry", "slo.py"),
                 ("telemetry", "attribution.py"))
_METRIC_NAME_RE = re.compile(r"^(serve|train)_[a-z0-9_]+$")


def _metric_constants(path: str) -> list[tuple]:
    """``(name, node)`` for every full-string constant in ``path`` that
    matches the metric-name grammar."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    return [(node.value, node) for node in ast.walk(tree)
            if isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and _METRIC_NAME_RE.match(node.value)]


def lint_metric_catalog(metric_files=None,
                        repo: str = _REPO) -> list[Finding]:
    """``metric-catalog.undocumented``: every metric name that appears in
    the registering modules must resolve through
    ``telemetry/catalog.py::metric_help`` (a HELP bullet in a catalog
    docstring or an ``EXTRA_HELP`` entry). The catalog module is loaded by
    FILE PATH — importing the ``telemetry`` package would pull in jax,
    and the CI lint job (and ``test_hostlint_runs_without_jax``) run this
    suite on a jax-free interpreter. ``metric_files`` parameterizes the
    scanned modules for seeded-defect tests, mirroring
    ``lint_journal_grammar``'s writer/reader path injection."""
    pkg = os.path.join(repo, "simple_distributed_machine_learning_tpu")
    catalog_path = os.path.join(pkg, "telemetry", "catalog.py")
    spec = importlib.util.spec_from_file_location(
        "_sdml_hostlint_catalog", catalog_path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    help_map = mod.metric_help()
    if metric_files is None:
        metric_files = [os.path.join(pkg, *rel) for rel in _METRIC_FILES]
    findings: list[Finding] = []
    for path in metric_files:
        for name, node in _metric_constants(path):
            if name not in help_map:
                findings.append(Finding(
                    rule="metric-catalog.undocumented",
                    severity=Severity.ERROR,
                    message=(f"metric '{name}' is registered but "
                             f"telemetry/catalog.py has no HELP text for "
                             f"it — the Prometheus exposition renders "
                             f"'(undocumented)' and operators fly blind"),
                    where=_where(path, node, repo),
                    hint="add a ``{name}`` — help bullet to the owning "
                         "module's docstring (catalog.py parses the "
                         "bullet grammar) or an EXTRA_HELP entry"))
    return findings


def lint_repo(repo: str = _REPO) -> Report:
    """The whole hostlint suite: builder definitions in models/gpt.py;
    cache-poke and builder-bypass EVERYWHERE outside the cache's owner —
    the whole package, repo-root scripts (bench.py) and tests/ — because
    "code outside models/gpt.py touching _DECODE_BUILD_CACHE" is the
    documented rule, and a poke from cli.py or bench.py rots the memo
    just as surely as one from serve/; raw-jit additionally in serve/
    (every other layer creates jits legitimately)."""
    pkg = os.path.join(repo,
                       "simple_distributed_machine_learning_tpu")
    gpt = os.path.abspath(os.path.join(pkg, "models", "gpt.py"))
    findings = lint_builder_definitions(gpt)
    findings.extend(lint_journal_grammar(repo=repo))
    findings.extend(lint_metric_catalog(repo=repo))
    serve_dir = os.path.abspath(os.path.join(pkg, "serve")) + os.sep
    # determinism-pinned modules outside serve/: the SLO/alert/attribution
    # pipeline feeds exact-pinned scenario numbers, so it gets the same
    # no-wall-clock rule (without serve/'s raw-jit rule)
    clock_paths = {os.path.abspath(os.path.join(pkg, "telemetry", f))
                   for f in ("slo.py", "alerts.py", "attribution.py")}
    paths: list[str] = []
    for d in (pkg, os.path.join(repo, "tests")):
        if not os.path.isdir(d):
            continue
        for root, _dirs, files in sorted(os.walk(d)):
            for fname in sorted(files):
                if fname.endswith(".py"):
                    paths.append(os.path.join(root, fname))
    paths.extend(os.path.join(repo, f) for f in sorted(os.listdir(repo))
                 if f.endswith(".py"))
    for path in paths:
        ap = os.path.abspath(path)
        if ap == gpt:
            continue
        findings.extend(_lint_call_sites(
            path, allow_jit=not ap.startswith(serve_dir), repo=repo,
            check_clock=(ap.startswith(serve_dir) or ap in clock_paths)))
    return Report(name="hostlint", findings=findings)
