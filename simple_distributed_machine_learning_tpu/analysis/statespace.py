"""Bounded explicit-state model checking: the exhaustive-interleaving core.

The jaxpr passes (analysis/rules.py) and the Pallas prover (PR 16) verify
COMPILED programs; this module verifies PROTOCOLS — host-side state
machines whose failure modes are schedule-dependent interleavings no
sampled chaos drill reliably hits. It is a deliberately small
explicit-state checker: breadth-first exploration of every transition
interleaving from an initial state, deduplicated on state hash, bounded by
depth, with shortest-counterexample traces reconstructed from parent
pointers.

Design rules the tests pin:

- **Dedup soundness** — two paths reaching one state explore its successors
  once. States must therefore be VALUES (frozen dataclasses / nested
  tuples): equality is state identity, and any ghost bookkeeping a model
  carries (delivered-token counts, crash budgets) is part of the state on
  purpose — two histories that differ in observable effects are different
  states.
- **Depth-bound honesty** — the verdict always says "proved to depth N",
  never a bare "proved": a bounded search that hit its bound is evidence,
  not proof. When the frontier exhausts below the bound the verdict says
  so (the state space was finite and fully explored), still phrased with
  the depth it ran to.
- **Determinism** — transitions are explored in sorted label order and BFS
  order is queue order, so two runs over the same model produce
  byte-identical reports. No wall clock, no RNG, no set-iteration order
  leaks into results.

:func:`explore` is generic: ``transitions(state)`` yields ``(label,
next_state)`` pairs and ``invariants`` maps names to predicates returning
``None`` (holds) or a violation message. ``analysis/protocol.py`` builds
the serve-fleet model on top.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Callable, Hashable, Iterable

#: a transition label: a tuple of strings/ints (sortable, hashable) whose
#: first element names the action — e.g. ``("crash", 1, "mid-handoff")``
Label = tuple


@dataclasses.dataclass(frozen=True)
class Violation:
    """One invariant failure with its shortest witnessing schedule."""

    invariant: str            # invariant name ("double-serve", ...)
    message: str              # what is false in the bad state
    trace: tuple[Label, ...]  # transition labels, initial -> bad state
    depth: int                # len(trace)

    def render(self, label_str=None) -> str:
        fmt = label_str or _default_label_str
        lines = [f"invariant '{self.invariant}' violated at depth "
                 f"{self.depth}: {self.message}"]
        for i, lab in enumerate(self.trace):
            lines.append(f"  {i + 1}. {fmt(lab)}")
        return "\n".join(lines)


def _default_label_str(label: Label) -> str:
    head, *rest = label
    return f"{head}({', '.join(str(r) for r in rest)})" if rest else str(head)


@dataclasses.dataclass
class Exploration:
    """What one bounded run established (and how hard it looked)."""

    states: int               # distinct states explored
    transitions: int          # transitions taken (incl. into dedup hits)
    dedup_hits: int           # transitions that landed on a known state
    depth_bound: int
    depth_reached: int        # deepest distinct state seen
    complete: bool            # frontier exhausted BELOW the bound
    truncated: bool           # state cap hit (max_states) — never a proof
    violations: list[Violation] = dataclasses.field(default_factory=list)

    def verdict(self, invariants: Iterable[str]) -> str:
        """The honesty-pinned summary line. Never a bare "proved": a
        depth-bounded search proves properties only up to its bound, and
        the phrasing carries the bound even when the state space was
        exhausted below it."""
        names = ", ".join(invariants)
        if self.violations:
            broken = sorted({v.invariant for v in self.violations})
            return (f"VIOLATED: {', '.join(broken)} — "
                    f"{len(self.violations)} counterexample(s) within "
                    f"depth {self.depth_bound} "
                    f"({self.states} states explored)")
        if self.truncated:
            return (f"inconclusive: state cap hit after {self.states} "
                    f"states — nothing proved")
        scope = ("state space exhausted — every reachable interleaving"
                 if self.complete else
                 "depth bound reached — deeper schedules unexplored")
        return (f"proved to depth {self.depth_bound}: {names} "
                f"({self.states} states, {self.transitions} transitions, "
                f"{scope})")


def explore(initial: Hashable,
            transitions: Callable[[Hashable], Iterable[tuple[Label,
                                                             Hashable]]],
            invariants: dict[str, Callable[[Hashable], str | None]],
            depth: int,
            max_states: int = 500_000) -> Exploration:
    """Breadth-first bounded exploration with state-hash dedup.

    Checks every invariant on every DISTINCT reachable state (including
    the initial one). The first violation of each invariant is recorded
    with its shortest trace (BFS guarantees minimality); exploration
    continues so one run reports every broken invariant. Successors of a
    violating state are still explored — a model may violate one
    invariant on the way to violating another.
    """
    if depth < 0:
        raise ValueError(f"depth must be >= 0, got {depth}")
    seen: dict = {initial: (None, None)}        # state -> (parent, label)
    depth_of = {initial: 0}
    queue = deque([initial])
    result = Exploration(states=0, transitions=0, dedup_hits=0,
                         depth_bound=depth, depth_reached=0,
                         complete=True, truncated=False)
    broken: set[str] = set()

    def _check(state) -> None:
        for name, pred in invariants.items():
            if name in broken:
                continue
            msg = pred(state)
            if msg is not None:
                broken.add(name)
                result.violations.append(Violation(
                    invariant=name, message=msg,
                    trace=_trace_to(state, seen),
                    depth=depth_of[state]))

    _check(initial)
    result.states = 1
    while queue:
        state = queue.popleft()
        d = depth_of[state]
        if d >= depth:
            # a cut frontier: there were unexplored schedules past the
            # bound iff this state has any successor at all
            if next(iter(transitions(state)), None) is not None:
                result.complete = False
            continue
        for label, nxt in sorted(transitions(state), key=lambda t: t[0]):
            result.transitions += 1
            if nxt in seen:
                result.dedup_hits += 1
                continue
            if len(seen) >= max_states:
                result.truncated = True
                result.complete = False
                return result
            seen[nxt] = (state, label)
            depth_of[nxt] = d + 1
            result.states += 1
            result.depth_reached = max(result.depth_reached, d + 1)
            _check(nxt)
            queue.append(nxt)
    return result


def _trace_to(state, seen) -> tuple[Label, ...]:
    labels: list[Label] = []
    while True:
        parent, label = seen[state]
        if parent is None:
            break
        labels.append(label)
        state = parent
    return tuple(reversed(labels))
