"""Static sharding & collective analysis — the multi-chip preflight gate.

The engine composes five mesh axes (``data``/``stage``/``model``/``seq``/
``expert``) with ring collectives whose failure modes are silent or
catastrophic at scale: a branch-divergent ``ppermute`` ring deadlocks the
collective-permute rendezvous, a missing ``psum`` on a gradient path trains
a subtly wrong model, a wrong axis name or a sub-fp32 accumulator corrupts
numerics without crashing. This package traces the EXACT compiled step a
launch is about to execute to a ``ClosedJaxpr`` — zero FLOPs, no device
buffers — and runs a pluggable suite of lint passes over it (``rules.py``),
returning structured findings plus a bytes-over-ICI cost report per
collective.

Rule families (the catalog table lives in docs/ARCHITECTURE.md):

- ``ppermute-deadlock`` — non-bijective permutations; collectives inside
  divergent ``cond``/``switch`` branches or varying-trip-count ``while``
  loops (the PR-2 XLA:CPU caveat, machine-checked);
- ``unreduced-gradient`` — a shard_map output claiming replication over an
  axis the dataflow says it still varies over (a dropped grad psum);
- ``mesh-axis`` — collective axis names absent from the active mesh,
  permutation endpoints outside the axis, trace-time axis binding errors;
- ``dtype-drift`` — sub-fp32 cross-device reductions and scan carries that
  accumulate in sub-fp32;
- ``donation`` — buffers read after being donated to a jitted call
  (incl. across program boundaries in a composite serve tick, and one
  buffer aliased into a call that donates it);
- ``scatter-bounds`` — dataflow interval analysis (``bounds.py``) proving
  every gather/scatter/dynamic-slice index stays inside its operand, given
  declared input contracts (``analysis.spec``) — the serve path's silent
  K/V-corruption class;
- ``retrace-explosion`` — decode builders whose trace keys include
  unbounded runtime values (per-prompt-length retraces) and builders that
  dropped the ``_DECODE_BUILD_CACHE`` memo (``programs.py``);
- ``sharded-state`` — gather-before-use / reduce-before-update over
  declared ZeRO-style shards (``spec(..., vary=('data',))``), the
  fully-sharded-training groundwork;
- ``kernel-oob`` / ``kernel-unproven`` / ``kernel-race`` /
  ``kernel-tile`` / ``kernel-dtype-drift`` / ``kernel-hbm`` — static
  verification INSIDE every ``pallas_call`` (``kernels.py``): BlockSpec
  index-map bounds proofs over the grid + scalar-prefetch contracts,
  grid write-race detection on parallel axes, Mosaic tiling / scratch
  dtype lint, and kernel-derived HBM cost rows reconciled exactly
  against the serve registry's tick model.

``programs.py`` is the whole-program registry (every compiled entry point
with abstract-arg builders + the HBM-bytes-per-tick cost model);
``hostlint.py`` is the AST-level twin for the host-side build discipline.

Library API::

    from simple_distributed_machine_learning_tpu import analysis
    report = analysis.analyze(step_fn, buf_sds, state_sds, x_sds, t_sds,
                              key_sds, mesh=pipe.mesh)
    print(report.format())
    if not report.ok():          # any ERROR finding
        raise SystemExit(1)

CLI (the preflight gates ``cli.py --lint`` / ``bench.py --lint`` wrap)::

    python -m simple_distributed_machine_learning_tpu.analysis --dryrun 8
    python -m simple_distributed_machine_learning_tpu.analysis --serve
    python -m simple_distributed_machine_learning_tpu.analysis --hostlint
    python -m simple_distributed_machine_learning_tpu.analysis --fixtures
"""

from __future__ import annotations

from simple_distributed_machine_learning_tpu.analysis.report import (
    CollectiveCost,
    Finding,
    HBMCost,
    Report,
    Severity,
)

__all__ = [
    "ArgSpec", "CollectiveCost", "Finding", "HBMCost", "Report", "Severity",
    "abstractify", "analyze", "analyze_jaxpr", "shape_dtype", "spec",
]

# report.py is pure stdlib; everything else transitively imports jax, so
# those symbols resolve lazily (PEP 562) — analysis.hostlint stays
# importable and runnable when jax is absent or wedged (its whole point).
_LAZY = {
    "ArgSpec": "bounds", "spec": "bounds",
    "run_rules": "rules",
    "abstractify": "trace", "shape_dtype": "trace",
    "trace_to_jaxpr": "trace",
}


def __getattr__(name: str):
    import importlib

    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    return getattr(importlib.import_module(f"{__name__}.{mod}"), name)


def analyze_jaxpr(closed_jaxpr, mesh=None, name: str = "",
                  arg_ranges=None, arg_vary=None) -> Report:
    """Run the lint suite over an already-traced ``ClosedJaxpr``."""
    from simple_distributed_machine_learning_tpu.analysis.kernels import (
        kernel_hbm_costs,
    )
    from simple_distributed_machine_learning_tpu.analysis.rules import (
        run_rules,
    )
    findings, costs = run_rules(closed_jaxpr, active_mesh=mesh,
                                arg_ranges=arg_ranges, arg_vary=arg_vary)
    return Report(name=name, findings=findings, costs=costs,
                  hbm=kernel_hbm_costs(closed_jaxpr, program=name))


def _unwrap_specs(abstract_args, abstract_kwargs):
    """Split ``ArgSpec`` annotations out of the args pytree: plain abstract
    args for tracing, plus flat (range, vary) lists aligned with the traced
    jaxpr's invars (``jax.make_jaxpr`` flattens ``(args, kwargs)`` the same
    way)."""
    import jax

    from simple_distributed_machine_learning_tpu.analysis.bounds import (
        ArgSpec,
    )
    leaves, tree = jax.tree.flatten((abstract_args, abstract_kwargs))
    ranges = [a.interval if isinstance(a, ArgSpec) else None for a in leaves]
    vary = [frozenset(a.vary) if isinstance(a, ArgSpec) else frozenset()
            for a in leaves]
    plain = [a.sds if isinstance(a, ArgSpec) else a for a in leaves]
    args, kwargs = jax.tree.unflatten(tree, plain)
    if not any(r is not None for r in ranges):
        ranges = None
    if not any(vary):
        vary = None
    return args, kwargs, ranges, vary


def analyze(fn, *abstract_args, mesh=None, name: str = "", **abstract_kwargs
            ) -> Report:
    """Trace ``fn`` on abstract args and lint the result.

    ``abstract_args`` are ``jax.ShapeDtypeStruct``s (or concrete arrays —
    only shapes/dtypes are read; use :func:`abstractify` on real buffers).
    Any arg may instead be an :func:`analysis.spec <bounds.spec>` — a
    ShapeDtypeStruct carrying a declared value range (the scatter-bounds
    rule's input contract) and/or declared device-varying mesh axes (the
    sharded-state rule's seed).
    ``mesh`` is the ACTIVE launch mesh: axis existence and sizes of every
    collective are checked against it, catching a step traced for one
    topology and launched on another.

    Trace failures become findings rather than exceptions, so a preflight
    can always print one report: an unbound axis name (``psum`` over an
    axis the mesh does not carry) is exactly the ``mesh-axis`` defect this
    suite exists to catch, and jax surfaces it at bind time.
    """
    from simple_distributed_machine_learning_tpu.analysis.trace import (
        trace_to_jaxpr,
    )
    name = name or getattr(fn, "__name__", "") or "step"
    abstract_args, abstract_kwargs, arg_ranges, arg_vary = _unwrap_specs(
        abstract_args, abstract_kwargs)
    try:
        jaxpr = trace_to_jaxpr(fn, *abstract_args, **abstract_kwargs)
    except Exception as e:  # noqa: BLE001 - any trace error becomes a finding
        msg = str(e)
        rule, hint = "trace.failed", (
            "the step could not even be traced on these shapes; the error "
            "above is jax's own diagnosis")
        low = msg.lower()
        if "axis name" in msg or "unbound" in low:
            rule = "mesh-axis.unknown-axis"
            hint = ("a collective names an axis the enclosing mesh does not "
                    "bind — fix the axis_name or the mesh")
        elif "vma" in low or "varying" in low or "replicat" in low:
            # modern jax's own vma checker rejected the program — same
            # defect class as the analyzer's static replication inference
            rule = "unreduced-gradient.trace-error"
            hint = ("jax's vma checker refused the program: a value claimed "
                    "replicated still varies — add the missing reduction")
        first = msg.splitlines()[0] if msg.strip() else "<no message>"
        return Report(name=name, findings=[Finding(
            rule=rule, severity=Severity.ERROR,
            message=f"tracing failed: {type(e).__name__}: {first}",
            where=name, hint=hint)])
    return analyze_jaxpr(jaxpr, mesh=mesh, name=name,
                         arg_ranges=arg_ranges, arg_vary=arg_vary)
