"""Dataflow interval analysis over jaxprs — the ``scatter-bounds`` rule.

The serving programs' one irreducible hazard is an index: a block-table
entry feeding a K/V scatter, a position counter feeding a
``dynamic_update_slice``, a sampled token feeding an embedding gather. XLA
never raises on an out-of-range index — depending on the op's mode it
CLAMPS (the write silently lands on the last row: another request's K/V),
DROPS (the write vanishes: attention reads stale garbage), or is outright
undefined (``PROMISE_IN_BOUNDS``, which the paged block gathers use). The
pool's Python guards (``serve/slots.py``) keep the HOST-side tables inside
the contract; this pass machine-checks that the COMPILED programs respect
it: given declared value ranges for the index-bearing inputs (``spec``),
interval arithmetic is propagated through every equation and every
gather/scatter/dynamic-slice start index is proven inside its operand's
bounds.

Contract declaration — wrap any abstract arg the caller can bound::

    from simple_distributed_machine_learning_tpu.analysis import bounds
    tables = bounds.spec((S, NB), np.int32, 0, n_blocks)   # table entries
    pos    = bounds.spec((S,),    np.int32, 0, max_len - 1)
    report = analysis.analyze(step_fn, params_sds, kc, vc, toks, pos,
                              tables, ...)

Findings:

- ``scatter-bounds.out-of-range`` (ERROR) — an index interval provably
  reaches outside ``[0, dim - window]``: the write/read lands in (or
  silently clamps onto) memory belonging to someone else;
- ``scatter-bounds.unproven-promise`` (WARNING) — a ``PROMISE_IN_BOUNDS``
  gather/scatter whose index interval the analysis cannot bound: the
  program promises XLA something nobody proved.

The propagation is deliberately conservative: unknown values are
``[-inf, inf]``, unhandled primitives produce unknowns, scan/while carries
run a widening fixpoint — the pass can miss a proof (a WARNING at worst)
but never claims safety it did not derive.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable

import numpy as np

from simple_distributed_machine_learning_tpu.analysis.report import (
    Finding,
    Severity,
)
from simple_distributed_machine_learning_tpu.analysis.trace import (
    source_line,
    subjaxprs,
)

_INF = math.inf


@dataclasses.dataclass(frozen=True)
class Interval:
    """Inclusive value bounds; ``[-inf, inf]`` is the unknown (TOP)."""
    lo: float
    hi: float

    @property
    def known(self) -> bool:
        return self.lo > -_INF or self.hi < _INF

    def __or__(self, other: "Interval") -> "Interval":
        return Interval(min(self.lo, other.lo), max(self.hi, other.hi))


TOP = Interval(-_INF, _INF)
BOOL = Interval(0, 1)


@dataclasses.dataclass(frozen=True)
class ArgSpec:
    """An abstract argument plus its declared value contract.

    ``lo``/``hi`` are the inclusive bounds the CALLER guarantees for every
    element (the host-side discipline being machine-checked); ``vary`` are
    mesh axes the buffer's CONTENT differs over even though its shape is
    replicated (the sharded-state rule's seed — a ZeRO shard passed as a
    full-shape buffer)."""
    sds: Any
    lo: float | None = None
    hi: float | None = None
    vary: tuple = ()

    @property
    def interval(self) -> Interval | None:
        if self.lo is None and self.hi is None:
            return None
        return Interval(-_INF if self.lo is None else self.lo,
                        _INF if self.hi is None else self.hi)


def spec(shape, dtype, lo=None, hi=None, vary=()) -> ArgSpec:
    """A ``ShapeDtypeStruct`` carrying a value contract (see ArgSpec)."""
    import jax
    return ArgSpec(jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype)),
                   lo=lo, hi=hi, vary=tuple(vary))


def _const_interval(val) -> Interval:
    try:
        a = np.asarray(val)
        if a.size == 0 or a.dtype.kind not in "iub":
            return TOP
        return Interval(float(a.min()), float(a.max()))
    except Exception:
        return TOP


class _Env:
    """Interval state for one jaxpr body: per-var intervals, concrete
    values for small integer constants (per-component index recovery), and
    the concatenate decomposition of index vectors."""

    def __init__(self):
        self.iv: dict[int, Interval] = {}
        self.concrete: dict[int, np.ndarray] = {}
        self.parts: dict[int, list[tuple[int, Interval]]] = {}

    def read(self, atom) -> Interval:
        if hasattr(atom, "val"):            # Literal (has .aval too)
            return _const_interval(atom.val)
        return self.iv.get(id(atom), TOP)

    def read_concrete(self, atom) -> np.ndarray | None:
        if hasattr(atom, "val"):
            v = np.asarray(atom.val)
            return v if v.dtype.kind in "iub" else None
        return self.concrete.get(id(atom))

    def seed_consts(self, jaxpr, consts) -> None:
        """Constvars get their actual values: intervals always, the whole
        array when it is a small integer one (per-component index-vector
        recovery, e.g. a literal ``[layer, 0]`` scatter index)."""
        for var, val in zip(jaxpr.constvars, consts):
            self.iv[id(var)] = _const_interval(val)
            try:
                arr = np.asarray(val)
            except Exception:
                continue
            if arr.ndim <= 2 and arr.size <= 4096 and arr.dtype.kind in "iub":
                self.concrete[id(var)] = arr


def _mul_iv(a: Interval, b: Interval) -> Interval:
    prods = []
    for x in (a.lo, a.hi):
        for y in (b.lo, b.hi):
            if (x in (0, -0.0) or y in (0, -0.0)):
                prods.append(0.0)
            elif abs(x) == _INF or abs(y) == _INF:
                prods.append(_INF if (x > 0) == (y > 0) else -_INF)
            else:
                prods.append(x * y)
    return Interval(min(prods), max(prods))


def _div_iv(a: Interval, b: Interval) -> Interval:
    # only the shape the index programs use: a known nonneg dividend over a
    # positive constant divisor (pos // block_size)
    if b.lo == b.hi and b.lo > 0 and a.lo >= 0 and a.known:
        c = b.lo
        hi = a.hi if a.hi == _INF else float(int(a.hi // c))
        return Interval(float(int(a.lo // c)), hi)
    return TOP


def _floordiv_iv(a: Interval, b: Interval) -> Interval:
    # any-sign dividend over a positive constant divisor
    if b.lo == b.hi and b.lo > 0 and a.known:
        c = b.lo
        lo = a.lo if a.lo == -_INF else float(math.floor(a.lo / c))
        hi = a.hi if a.hi == _INF else float(math.floor(a.hi / c))
        return Interval(lo, hi)
    return TOP


def _mod_iv(a: Interval, b: Interval) -> Interval:
    # Python-semantics mod (sign follows the divisor)
    if b.lo == b.hi and b.lo > 0:
        return Interval(0, b.lo - 1)
    return TOP


def _cmp_iv(prim: str, a: Interval, b: Interval) -> Interval:
    """Comparison result interval: [0,0]/[1,1] when the operand intervals
    decide it, else the unknown bool [0,1]."""
    if prim == "lt":
        if a.hi < b.lo:
            return Interval(1, 1)
        if a.lo >= b.hi:
            return Interval(0, 0)
    elif prim == "le":
        if a.hi <= b.lo:
            return Interval(1, 1)
        if a.lo > b.hi:
            return Interval(0, 0)
    elif prim == "gt":
        if a.lo > b.hi:
            return Interval(1, 1)
        if a.hi <= b.lo:
            return Interval(0, 0)
    elif prim == "ge":
        if a.lo >= b.hi:
            return Interval(1, 1)
        if a.hi < b.lo:
            return Interval(0, 0)
    elif prim == "eq":
        if a.lo == a.hi == b.lo == b.hi:
            return Interval(1, 1)
        if a.hi < b.lo or b.hi < a.lo:
            return Interval(0, 0)
    elif prim == "ne":
        if a.lo == a.hi == b.lo == b.hi:
            return Interval(0, 0)
        if a.hi < b.lo or b.hi < a.lo:
            return Interval(1, 1)
    return BOOL


def _rem_iv(a: Interval, b: Interval) -> Interval:
    # lax.rem's sign follows the dividend
    if b.lo == b.hi and b.lo > 0:
        c = b.lo
        hi = min(a.hi, c - 1) if a.hi < _INF else c - 1
        if a.lo >= 0:
            return Interval(0.0, max(0.0, hi))
        return Interval(-(c - 1), c - 1)
    return TOP


def _index_verdict(iv: Interval, allowed_hi: int) -> str:
    """Classify an index interval against ``[0, allowed_hi]``.

    ``"ok"`` — provably in bounds. ``"oob"`` — the violation is carried by
    a FINITE bound (a declared/derived range that genuinely reaches outside
    the operand). ``"unproven"`` — the only violating side is infinite:
    nothing was proven either way, so a half-declared contract (only ``lo``
    or only ``hi``) degrades to the same not-proven treatment as no
    contract at all instead of escalating to a gating ERROR."""
    if iv.lo >= 0 and iv.hi <= allowed_hi:
        return "ok"
    if iv.lo > allowed_hi or iv.hi < 0:
        return "oob"                    # EVERY possible value is outside
    if (iv.lo < 0 and iv.lo > -_INF) or (allowed_hi < iv.hi < _INF):
        return "oob"                    # a finite declared bound reaches out
    return "unproven"


_MODE_EFFECT = {
    "GatherScatterMode.CLIP": "the index CLAMPS to the edge — the access "
                              "silently lands on the last row in bounds",
    "GatherScatterMode.FILL_OR_DROP": "the write is silently DROPPED (or "
                                      "the read filled) — downstream math "
                                      "consumes stale garbage",
    "GatherScatterMode.PROMISE_IN_BOUNDS": "the program PROMISED XLA the "
                                           "index is in bounds — out of "
                                           "range is undefined behavior",
}


class BoundsWalker:
    """One interval-propagation pass; findings accumulate on ``emit``."""

    def __init__(self, emit: Callable[..., None]):
        self._emit = emit
        self._mute = 0

    # -- body walk --------------------------------------------------------

    def run(self, closed_jaxpr, in_ranges: list[Interval | None]):
        jaxpr = closed_jaxpr.jaxpr
        env = _Env()
        env.seed_consts(jaxpr, closed_jaxpr.consts)
        ivs = list(in_ranges) + [None] * (len(jaxpr.invars) - len(in_ranges))
        for var, iv in zip(jaxpr.invars, ivs):
            env.iv[id(var)] = iv if iv is not None else TOP
        outs = self._walk(jaxpr, env)
        return outs

    def _walk(self, jaxpr, env: _Env) -> list[Interval]:
        for eqn in jaxpr.eqns:
            outs = self._eqn(eqn, env)
            for var, iv in zip(eqn.outvars, outs):
                env.iv[id(var)] = iv
        return [env.read(v) for v in jaxpr.outvars]

    def _sub_env(self, sub_closed_or_open, in_ivs: list[Interval]) -> _Env:
        env = _Env()
        jaxpr = getattr(sub_closed_or_open, "jaxpr", sub_closed_or_open)
        env.seed_consts(jaxpr, getattr(sub_closed_or_open, "consts", ()))
        for var, iv in zip(jaxpr.invars, in_ivs):
            env.iv[id(var)] = iv
        return env

    def _call_sub(self, sub, in_ivs) -> list[Interval]:
        jaxpr = getattr(sub, "jaxpr", sub)
        env = self._sub_env(sub, in_ivs)
        self._walk(jaxpr, env)
        return [env.read(v) for v in jaxpr.outvars]

    # -- per-equation transfer function -----------------------------------

    def _eqn(self, eqn, env: _Env) -> list[Interval]:
        prim = eqn.primitive.name
        ins = [env.read(v) for v in eqn.invars]
        union = Interval(min((i.lo for i in ins), default=-_INF),
                         max((i.hi for i in ins), default=_INF)) \
            if ins else TOP
        n = len(eqn.outvars)
        a = ins[0] if ins else TOP

        if prim in ("add", "add_any"):
            return [Interval(ins[0].lo + ins[1].lo, ins[0].hi + ins[1].hi)] * n
        if prim == "sub":
            return [Interval(ins[0].lo - ins[1].hi, ins[0].hi - ins[1].lo)] * n
        if prim == "mul":
            return [_mul_iv(ins[0], ins[1])] * n
        if prim == "div":
            return [_div_iv(ins[0], ins[1])] * n
        if prim == "rem":
            return [_rem_iv(ins[0], ins[1])] * n
        if prim == "neg":
            return [Interval(-a.hi, -a.lo)] * n
        if prim == "sign":
            lo = -1 if a.lo < 0 else (0 if a.lo == 0 else 1)
            hi = 1 if a.hi > 0 else (0 if a.hi == 0 else -1)
            return [Interval(lo, hi)] * n
        if prim == "max":
            return [Interval(max(ins[0].lo, ins[1].lo),
                             max(ins[0].hi, ins[1].hi))] * n
        if prim == "min":
            return [Interval(min(ins[0].lo, ins[1].lo),
                             min(ins[0].hi, ins[1].hi))] * n
        if prim == "clamp":
            lo_b, x, hi_b = ins
            m = Interval(max(x.lo, lo_b.lo), max(x.hi, lo_b.hi))
            return [Interval(min(m.lo, hi_b.lo), min(m.hi, hi_b.hi))] * n
        if prim in ("eq", "ne", "lt", "le", "gt", "ge"):
            # decidable comparisons matter: jnp's negative-index
            # normalization is `where(idx < 0, idx + N, idx)`, and proving
            # the predicate constant-false is what keeps a declared
            # in-bounds index from widening to [lo, hi + N]
            return [_cmp_iv(prim, ins[0], ins[1])] * n
        if prim in ("is_finite", "not", "reduce_and", "reduce_or"):
            return [BOOL] * n
        if prim in ("and", "or", "xor"):
            aval = getattr(eqn.outvars[0], "aval", None)
            if aval is not None and np.dtype(aval.dtype).kind == "b":
                if prim == "and":
                    return [Interval(min(ins[0].lo, ins[1].lo),
                                     min(ins[0].hi, ins[1].hi))] * n
                if prim == "or":
                    return [Interval(max(ins[0].lo, ins[1].lo),
                                     max(ins[0].hi, ins[1].hi))] * n
                return [BOOL] * n
            return [TOP] * n
        if prim == "select_n":
            pred, cases = ins[0], ins[1:]
            if pred.lo == pred.hi and 0 <= pred.lo < len(cases):
                return [cases[int(pred.lo)]] * n    # decided predicate
            out = cases[0]
            for c in cases[1:]:
                out = out | c
            return [out] * n
        if prim in ("broadcast_in_dim", "reshape", "transpose", "squeeze",
                    "rev", "slice", "copy", "stop_gradient",
                    "reduce_max", "reduce_min", "sort", "expand_dims",
                    "reduce_precision", "real", "optimization_barrier"):
            if prim == "sort":
                return [env.read(v) for v in eqn.invars][:n] or [a] * n
            return [a] * n
        if prim == "convert_element_type":
            src = getattr(eqn.invars[0], "aval", None)
            dst = getattr(eqn.outvars[0], "aval", None)
            if (src is not None and dst is not None
                    and np.dtype(src.dtype).kind in "iub"):
                dk = np.dtype(dst.dtype)
                if dk.kind == "b":
                    return [BOOL] * n
                if dk.kind in "iu":
                    # a narrowing cast WRAPS at runtime: the interval
                    # survives only when provably representable in the
                    # destination dtype, else nothing is known
                    info = np.iinfo(dk)
                    if a.lo >= info.min and a.hi <= info.max:
                        return [a] * n
                    return [TOP] * n
                return [a] * n
            return [TOP] * n
        if prim == "iota":
            dim = eqn.params.get("dimension", 0)
            shape = eqn.params.get("shape") or eqn.outvars[0].aval.shape
            size = shape[dim] if shape else 1
            return [Interval(0, max(0, size - 1))] * n
        if prim in ("argmax", "argmin"):
            axes = eqn.params.get("axes", (0,))
            size = eqn.invars[0].aval.shape[int(axes[0])]
            return [Interval(0, max(0, size - 1))] * n
        if prim == "top_k":
            # (values, indices)
            size = eqn.invars[0].aval.shape[-1]
            out = [a, Interval(0, max(0, size - 1))]
            return out[:n] + [TOP] * (n - len(out))
        if prim == "concatenate":
            dim = eqn.params.get("dimension", 0)
            out_aval = getattr(eqn.outvars[0], "aval", None)
            if out_aval is not None and dim == len(out_aval.shape) - 1:
                env.parts[id(eqn.outvars[0])] = [
                    (int(v.aval.shape[-1]), env.read(v))
                    for v in eqn.invars]
            return [union] * n
        if prim == "pad":
            return [ins[0] | ins[1]] * n
        if prim == "gather":
            self._check_gather(eqn, env)
            return [a] * n
        if prim == "scatter":
            self._check_scatter(eqn, env)
            return [ins[0] | ins[2]] * n
        if prim in ("scatter-add", "scatter_add", "scatter-mul",
                    "scatter_mul", "scatter-min", "scatter_min",
                    "scatter-max", "scatter_max"):
            self._check_scatter(eqn, env)
            return [TOP] * n
        if prim == "dynamic_slice":
            self._check_dynamic(eqn, env, has_update=False)
            return [a] * n
        if prim == "dynamic_update_slice":
            self._check_dynamic(eqn, env, has_update=True)
            return [ins[0] | ins[1]] * n
        if prim == "scan":
            return self._scan(eqn, env)
        if prim == "while":
            return self._while(eqn, env)
        if prim == "cond":
            return self._cond(eqn, env)
        if prim == "get":
            # Pallas ref read (SMEM scalar-prefetch deref in index maps):
            # values drawn from the ref carry the ref's content interval
            return [a] * n
        if prim == "pallas_call":
            # open the kernel box: index-map bounds proofs, write-race
            # detection, tiling/dtype lint (analysis/kernels.py)
            from simple_distributed_machine_learning_tpu.analysis import (
                kernels,
            )
            return kernels.check_pallas_call(self, eqn, ins, env)

        if prim == "pjit" and len(ins) == 2:
            # jnp's floor_divide/remainder lower to div/rem plus a
            # sign-correction select whose predicate is only RELATIONALLY
            # decidable (sign(d) != sign(c) AND rem != 0 share d) — plain
            # interval propagation widens it; compute the closed form
            name = eqn.params.get("name")
            if name == "floor_divide":
                return [_floordiv_iv(ins[0], ins[1])] * n
            if name == "remainder":
                return [_mod_iv(ins[0], ins[1])] * n

        # generic call-like primitives: recurse when the arity matches
        for _key, _i, sub in subjaxprs(eqn):
            closed = eqn.params.get(_key)
            closed = (closed if not isinstance(closed, (tuple, list))
                      else closed[_i])
            target = getattr(closed, "jaxpr", closed)
            if len(target.invars) == len(eqn.invars):
                outs = self._call_sub(closed, ins)
                if len(outs) >= n:
                    return outs[:n]
        return [TOP] * n

    # -- control flow -----------------------------------------------------

    def _scan(self, eqn, env: _Env) -> list[Interval]:
        p = eqn.params
        body = p["jaxpr"]
        nc, ncar = p.get("num_consts", 0), p.get("num_carry", 0)
        ins = [env.read(v) for v in eqn.invars]
        consts, carry, xs = ins[:nc], list(ins[nc:nc + ncar]), ins[nc + ncar:]
        # an xs row's values are bounded by the whole stacked array's
        outs = None
        self._mute += 1
        try:
            for it in range(8):
                outs = self._call_sub(body, consts + carry + xs)
                new_carry = [c | o for c, o in zip(carry, outs[:ncar])]
                if new_carry == carry:
                    break
                carry = new_carry
            else:
                carry = [TOP] * ncar          # widen: no fixpoint reached
        finally:
            self._mute -= 1
        outs = self._call_sub(body, consts + carry + xs)
        return carry + outs[ncar:]

    def _while(self, eqn, env: _Env) -> list[Interval]:
        p = eqn.params
        cnc, bnc = p.get("cond_nconsts", 0), p.get("body_nconsts", 0)
        ins = [env.read(v) for v in eqn.invars]
        body_consts = ins[cnc:cnc + bnc]
        carry = list(ins[cnc + bnc:])
        self._mute += 1
        try:
            for it in range(8):
                outs = self._call_sub(p["body_jaxpr"], body_consts + carry)
                new_carry = [c | o for c, o in zip(carry, outs)]
                if new_carry == carry:
                    break
                carry = new_carry
            else:
                carry = [TOP] * len(carry)
        finally:
            self._mute -= 1
        # findings passes over the post-fixpoint carry — the cond jaxpr is
        # a program too (an index-bearing read in the loop predicate must
        # not analyze vacuously clean)
        self._call_sub(p["body_jaxpr"], body_consts + carry)
        self._call_sub(p["cond_jaxpr"], ins[:cnc] + carry)
        return carry

    def _cond(self, eqn, env: _Env) -> list[Interval]:
        ins = [env.read(v) for v in eqn.invars]
        op_ivs = ins[1:]
        outs = None
        for branch in eqn.params.get("branches") or ():
            b_outs = self._call_sub(branch, op_ivs)
            outs = (b_outs if outs is None
                    else [x | y for x, y in zip(outs, b_outs)])
        return outs if outs is not None else [TOP] * len(eqn.outvars)

    # -- the index checks -------------------------------------------------

    def _components(self, eqn, idx_atom, n_comp, env: _Env
                    ) -> list[Interval] | None:
        """Per-component intervals of an index vector: exact for concrete
        constants, whole-array for single components, recovered from the
        ``concatenate`` that built the vector otherwise."""
        conc = env.read_concrete(idx_atom)
        if conc is not None and conc.shape and conc.shape[-1] == n_comp:
            return [Interval(float(conc[..., k].min()),
                             float(conc[..., k].max()))
                    for k in range(n_comp)]
        if conc is not None and conc.ndim == 1 and conc.shape[0] == n_comp:
            return [Interval(float(v), float(v)) for v in conc]
        if n_comp == 1:
            return [env.read(idx_atom)]
        parts = env.parts.get(id(idx_atom))
        if parts is not None and sum(w for w, _ in parts) == n_comp:
            out = []
            for width, iv in parts:
                out.extend([iv] * width)
            return out
        return None

    def _flag(self, eqn, what: str, comp: int, dim: int, iv: Interval,
              allowed_hi: int, mode) -> None:
        if self._mute:
            return
        op_shape = eqn.invars[0].aval.shape
        effect = _MODE_EFFECT.get(str(mode), "out-of-bounds behavior is "
                                             "backend-defined")
        src = source_line(eqn)
        lo = "-inf" if iv.lo == -_INF else int(iv.lo)
        hi = "inf" if iv.hi == _INF else int(iv.hi)
        self._emit(Finding(
            rule="scatter-bounds.out-of-range", severity=Severity.ERROR,
            message=(f"{what} index component {comp} into operand dim {dim} "
                     f"(shape {tuple(op_shape)}) has range [{lo}, {hi}] but "
                     f"only [0, {allowed_hi}] is addressable — {effect}"),
            where=src,
            hint="tighten the producing arithmetic or the declared input "
                 "contract (analysis.bounds.spec) so the index interval "
                 "fits; for K/V writes this is the slots.py block/position "
                 "discipline the compiled program must not outrun"))

    def _flag_unproven(self, eqn, what: str) -> None:
        if self._mute:
            return
        self._emit(Finding(
            rule="scatter-bounds.unproven-promise", severity=Severity.WARNING,
            message=(f"{what} runs in PROMISE_IN_BOUNDS mode but the index "
                     f"interval could not be bounded — an out-of-range "
                     f"index here is undefined behavior"),
            where=source_line(eqn),
            hint="declare the index-bearing input's range via "
                 "analysis.bounds.spec (or clamp in-program) so the "
                 "promise is provable"))

    def _check_gather(self, eqn, env: _Env) -> None:
        dn = eqn.params.get("dimension_numbers")
        slice_sizes = eqn.params.get("slice_sizes") or ()
        mode = eqn.params.get("mode")
        if dn is None:
            return
        start_map = tuple(dn.start_index_map)
        comps = self._components(eqn, eqn.invars[1], len(start_map), env)
        op_shape = eqn.invars[0].aval.shape
        for k, d in enumerate(start_map):
            win = slice_sizes[d] if d < len(slice_sizes) else 1
            allowed_hi = int(op_shape[d]) - int(win)
            iv = comps[k] if comps is not None else TOP
            verdict = _index_verdict(iv, allowed_hi)
            if verdict == "oob":
                self._flag(eqn, "gather", k, d, iv, allowed_hi, mode)
            elif verdict == "unproven" and "PROMISE" in str(mode):
                self._flag_unproven(eqn, "gather")

    def _check_scatter(self, eqn, env: _Env) -> None:
        dn = eqn.params.get("dimension_numbers")
        mode = eqn.params.get("mode")
        if dn is None:
            return
        sdod = tuple(dn.scatter_dims_to_operand_dims)
        inserted = set(dn.inserted_window_dims)
        batching = set(getattr(dn, "operand_batching_dims", ()) or ())
        op_shape = eqn.invars[0].aval.shape
        upd_shape = eqn.invars[2].aval.shape
        uwd = tuple(dn.update_window_dims)
        # map each non-inserted, non-batching operand dim to its window size
        window = {}
        j = 0
        for d in range(len(op_shape)):
            if d in inserted or d in batching:
                window[d] = 1
                continue
            window[d] = upd_shape[uwd[j]] if j < len(uwd) else 1
            j += 1
        comps = self._components(eqn, eqn.invars[1], len(sdod), env)
        for k, d in enumerate(sdod):
            allowed_hi = int(op_shape[d]) - int(window.get(d, 1))
            iv = comps[k] if comps is not None else TOP
            verdict = _index_verdict(iv, allowed_hi)
            if verdict == "oob":
                self._flag(eqn, "scatter", k, d, iv, allowed_hi, mode)
            elif verdict == "unproven" and "PROMISE" in str(mode):
                self._flag_unproven(eqn, "scatter")

    def _check_dynamic(self, eqn, env: _Env, has_update: bool) -> None:
        op = eqn.invars[0].aval.shape
        if has_update:
            windows = eqn.invars[1].aval.shape
            starts = eqn.invars[2:]
        else:
            windows = eqn.params.get("slice_sizes") or ()
            starts = eqn.invars[1:]
        what = "dynamic_update_slice" if has_update else "dynamic_slice"
        for d, start in enumerate(starts):
            win = windows[d] if d < len(windows) else 1
            allowed_hi = int(op[d]) - int(win)
            iv = env.read(start)
            if _index_verdict(iv, allowed_hi) == "oob":
                self._flag(eqn, what, d, d, iv, allowed_hi,
                           "GatherScatterMode.CLIP")
            # unproven: XLA clamps dynamic-slice starts; nothing to promise


def check_bounds(closed_jaxpr, in_ranges: list[Interval | None]
                 ) -> list[Finding]:
    """Run the interval pass over a traced program given declared input
    ranges (aligned with the jaxpr's flat invars; ``None`` = unknown).
    Returns scatter-bounds findings; an empty list is a PROOF relative to
    the declared contract, not an absence of checking."""
    findings: list[Finding] = []
    BoundsWalker(findings.append).run(closed_jaxpr, in_ranges)
    return findings
