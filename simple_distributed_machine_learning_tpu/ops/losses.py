"""Loss and metric ops (``F.log_softmax`` / ``F.nll_loss`` equivalents).

The reference computes ``log_softmax`` on the last pipeline stage
(``/root/reference/simple_distributed.py:79``) and ``nll_loss`` on the master
(``:111``, mean reduction; ``:126`` sum reduction via the deprecated
``size_average=False``). Here both reductions are explicit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def log_softmax(x: jax.Array, axis: int = -1) -> jax.Array:
    return jax.nn.log_softmax(x, axis=axis)


def nll_loss(log_probs: jax.Array, targets: jax.Array,
             reduction: str = "mean") -> jax.Array:
    """Negative log likelihood of integer ``targets`` under ``log_probs``.

    log_probs: [..., C] (already log-probabilities), targets: [...] int.
    """
    picked = jnp.take_along_axis(
        log_probs, targets[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    losses = -picked
    if reduction == "mean":
        return jnp.mean(losses)
    if reduction == "sum":
        return jnp.sum(losses)
    if reduction == "none":
        return losses
    raise ValueError(f"unknown reduction: {reduction!r}")


def softmax_cross_entropy(logits: jax.Array, targets: jax.Array,
                          reduction: str = "mean") -> jax.Array:
    """Cross entropy from raw logits (= nll_loss ∘ log_softmax, fused)."""
    return nll_loss(log_softmax(logits), targets, reduction=reduction)


def accuracy(log_probs_or_logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Fraction of rows whose argmax matches ``targets`` (reference ``:127-128``)."""
    pred = jnp.argmax(log_probs_or_logits, axis=-1)
    return jnp.mean((pred == targets).astype(jnp.float32))
