"""Attention: causal multi-head self-attention + ring attention over a mesh.

The reference has no attention at all (conv+FC only, SURVEY §5.7); the
tiny-GPT pipeline config (BASELINE.json config 5) introduces a sequence axis,
and long-context support is first-class in this framework: ``ring_attention``
shards the sequence over a mesh axis and rotates K/V blocks with
``lax.ppermute`` over ICI — the same collective the pipeline engine uses for
stage hops — with blockwise-stable (flash-style) softmax accumulation, so
attention over sequences far larger than one chip's HBM is a mesh-width knob,
not a rewrite.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from simple_distributed_machine_learning_tpu.parallel.compat import (
    axis_size as _axis_size,
)
from jax.sharding import PartitionSpec as P

SEQ_AXIS = "seq"


def mha_init(key: jax.Array, d_model: int, n_heads: int,
             dtype=jnp.float32) -> dict:
    """QKVO projection params for multi-head attention."""
    if d_model % n_heads:
        raise ValueError(f"d_model {d_model} not divisible by {n_heads} heads")
    ks = jax.random.split(key, 4)
    bound = 1.0 / math.sqrt(d_model)

    def w(k):
        return jax.random.uniform(k, (d_model, d_model), dtype,
                                  minval=-bound, maxval=bound)

    return {"wq": w(ks[0]), "wk": w(ks[1]), "wv": w(ks[2]), "wo": w(ks[3])}


def _split_heads(x: jax.Array, n_heads: int) -> jax.Array:
    b, t, d = x.shape
    return x.reshape(b, t, n_heads, d // n_heads).transpose(0, 2, 1, 3)


def _merge_heads(x: jax.Array) -> jax.Array:
    b, h, t, dh = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b, t, h * dh)


def causal_attention_core(q: jax.Array, k: jax.Array,
                          v: jax.Array) -> jax.Array:
    """Dense causal softmax attention on split heads: [B, H, T, Dh] each.

    The single source of the masked-softmax math — reused by
    :func:`causal_attention` and the Ulysses sequence-parallel path
    (``parallel/sequence.py``); the Pallas kernel and ring attention are
    tested against it.
    """
    dh = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(dh)
    t = q.shape[2]
    mask = jnp.tril(jnp.ones((t, t), bool))
    scores = jnp.where(mask, scores, -jnp.inf)
    return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, axis=-1), v)


def causal_attention(params: dict, x: jax.Array, n_heads: int) -> jax.Array:
    """Standard causal MHA on one device. x: [B, T, D] -> [B, T, D]."""
    h = n_heads
    q = _split_heads(x @ params["wq"], h)
    k = _split_heads(x @ params["wk"], h)
    v = _split_heads(x @ params["wv"], h)
    return _merge_heads(causal_attention_core(q, k, v)) @ params["wo"]


def _block_accumulate(q, k, v, acc, q_off, k_off, scale):
    """One flash-style block: fold (k, v) into the running (o, l, m) for q.

    q: [B,H,Tq,Dh]; k/v: [B,H,Tk,Dh]; positions are global offsets for the
    causal mask. Numerically stable: running rowmax m, normalizer l.
    """
    o, l, m = acc
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    tq, tk = q.shape[2], k.shape[2]
    qpos = q_off + jnp.arange(tq)[:, None]
    kpos = k_off + jnp.arange(tk)[None, :]
    scores = jnp.where(qpos >= kpos, scores, -jnp.inf)
    m_new = jnp.maximum(m, scores.max(-1))
    # guard: rows with everything masked so far keep m=-inf; exp(-inf+inf)=nan
    corr = jnp.where(jnp.isneginf(m_new), 0.0, jnp.exp(m - m_new))
    p = jnp.exp(scores - m_new[..., None])
    p = jnp.where(jnp.isneginf(scores), 0.0, p)
    o = o * corr[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    l = l * corr + p.sum(-1)
    return o, l, m_new


def ring_attention(params: dict, x: jax.Array, n_heads: int,
                   axis: str = SEQ_AXIS) -> jax.Array:
    """Causal MHA with the sequence sharded over mesh axis ``axis``.

    Must be called inside ``shard_map``: ``x`` is this device's local sequence
    chunk ``[B, T_local, D]`` (chunk i = global positions
    ``[i*T_local, (i+1)*T_local)``). K/V blocks rotate around the ring via
    ``ppermute``; each hop rides ICI and XLA overlaps it with the current
    block's attention compute. Output matches :func:`causal_attention` on the
    gathered sequence to float tolerance (see tests/test_attention.py).
    """
    h = n_heads
    s = _axis_size(axis)
    idx = lax.axis_index(axis)
    q = _split_heads(x @ params["wq"], h)
    k = _split_heads(x @ params["wk"], h)
    v = _split_heads(x @ params["wv"], h)
    b, _, t_loc, dh = q.shape
    scale = 1.0 / math.sqrt(dh)
    q_off = idx * t_loc

    def body(carry, r):
        k_r, v_r, acc = carry
        src = (idx - r) % s           # whose block we currently hold
        acc = _block_accumulate(q, k_r, v_r, acc, q_off, src * t_loc, scale)
        # pass K/V to the next device in the ring (device i -> i+1), so at
        # step r+1 we hold block (idx - r - 1): walking left = causal history
        perm = [(i, (i + 1) % s) for i in range(s)]
        k_r = lax.ppermute(k_r, axis, perm)
        v_r = lax.ppermute(v_r, axis, perm)
        return (k_r, v_r, acc), None

    # derive (l, m) from q so they inherit q's full varying-axes type — the
    # scan carry must type-match the loop body under check_vma no matter
    # which enclosing mesh axes (seq alone, or the pipeline's data/stage/
    # model too) the inputs vary over
    acc0 = (jnp.zeros_like(q),
            jnp.zeros_like(q[..., 0]),
            jnp.full_like(q[..., 0], -jnp.inf))
    (_, _, (o, l, _)), _ = lax.scan(body, (k, v, acc0), jnp.arange(s))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return _merge_heads(out) @ params["wo"]
