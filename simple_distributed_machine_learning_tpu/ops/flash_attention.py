"""Pallas flash attention: the fused causal-attention kernel for TPU.

The reference has no attention at all (conv+FC only, SURVEY §5.7) and no
custom kernels — its hot ops bottom out in ATen's C++/CUDA kernels
(``/root/reference/simple_distributed.py:42-46,:75-79``; SURVEY §2.3). The
TPU-native analogue of "a hand-tuned native kernel for the hot op" is a
Pallas kernel lowered through Mosaic to the MXU. This module provides one for
the framework's hottest op — causal multi-head attention:

- **blockwise online softmax** (flash style): the [T, T] score matrix is never
  materialized; K/V stream through VMEM one ``block_k`` tile at a time via a
  third grid axis, so VMEM holds O(block_q·d + block_k·d) regardless of T;
- **MXU-shaped tiles**: q/k/v blocks are zero-padded to a 128-lane head dim
  and (block_q, block_k) multiples of the sublane tile, so both matmuls in the
  inner loop land on the 128x128 systolic array;
- **causal block skipping**: k-blocks wholly past the diagonal are predicated
  off with ``pl.when`` (forward) / a diagonal-bounded loop (backward),
  halving FLOPs vs masking a full sweep — and their HBM fetches are elided
  too: the block index maps clamp at the diagonal, so skipped iterations
  revisit the previous block and Mosaic's pipeline issues no copy (without
  the clamp, K/V traffic is rectangular while the work is triangular, and
  the waste grows with T);
- **f32 accumulation** in VMEM scratch regardless of input dtype;
- backward via ``jax.custom_vjp`` recompute: cotangents re-derive the
  attention weights blockwise from the saved (l, m) softmax statistics —
  standard flash-attention-2 practice, no [T, T] residuals.

On non-TPU backends the same kernel runs in Pallas interpret mode, so the
test suite exercises the real kernel code path hermetically on CPU
(tests/test_flash_attention.py).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pltpu only imports on TPU-capable installs; interpret mode needs pl only
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

NEG_INF = -1e30  # finite -inf stand-in: keeps exp/max NaN-free in the kernel
_LANES = 128     # TPU lane width: head dim is padded to this; l/m scratch width

# jax 0.4.x ships the TPU compiler-params dataclass as TPUCompilerParams
# (renamed to CompilerParams in the 0.5+ line). Resolve once at import so the
# kernels build on both series — this name mismatch was exactly what made
# every flash test ERROR (not fail) on the 0.4.x container even though the
# interpret-mode fallback below would have run the kernel fine.
_COMPILER_PARAMS_CLS = getattr(pltpu, "CompilerParams", None) or (
    getattr(pltpu, "TPUCompilerParams", None) if _HAS_PLTPU else None)


def _compiler_params(*dimension_semantics: str):
    return _COMPILER_PARAMS_CLS(
        dimension_semantics=tuple(dimension_semantics))


def _interpret() -> bool:
    """Pallas interpret mode unless the DEFAULT backend is a real TPU.

    ``jax.default_backend()`` (not ``jax.devices()`` probing): on containers
    that bake in a TPU plugin but pin ``JAX_PLATFORMS=cpu`` (this test env),
    the default backend is authoritative for where the computation will
    actually run — probing for TPU devices would pick interpret=False and
    then fail to lower through Mosaic on the CPU path."""
    return jax.default_backend() != "tpu"


def _vma_of(*xs) -> frozenset:
    """Union of the operands' varying-manual-axes (vma) sets.

    Under ``shard_map(..., check_vma=True)`` — how every pipeline engine
    here runs — ``pallas_call`` out_shape structs must declare how outputs
    vary over the manual mesh axes, or tracing fails; the kernel's outputs
    vary exactly as its operands do. Outside shard_map this is the empty
    set and changes nothing (and on 0.4.x, where no vma type system exists,
    ``compat.vma_of`` is constant-empty)."""
    from simple_distributed_machine_learning_tpu.parallel.compat import (
        vma_of,
    )
    vma = frozenset()
    for x in xs:
        vma |= vma_of(x)
    return vma


def _struct(shape, dtype, vma: frozenset = frozenset()):
    """``jax.ShapeDtypeStruct`` with the vma declaration where the jax
    version has one (the check_vma era); plain struct on 0.4.x, whose
    ``shard_map(check_rep=False)`` route never consults vma at all."""
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:  # 0.4.x: no vma type system
        return jax.ShapeDtypeStruct(shape, dtype)


def _diag_kv_index(block_q: int, block_k: int):
    """Index map for K/V blocks on a (bh, q-block, k-block) grid, clamped at
    the causal diagonal: k-blocks wholly past the diagonal revisit the last
    needed block, so Mosaic's pipeline elides their HBM fetch (no copy when
    the block index is unchanged between iterations). One copy of the clamp
    arithmetic for the forward and dq passes."""
    def idx(i, j, kb):
        return (i, jnp.minimum(kb, ((j + 1) * block_q - 1) // block_k), 0)
    return idx


def _pad_to(x: jax.Array, axis: int, mult: int) -> jax.Array:
    size = x.shape[axis]
    rem = (-size) % mult
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, l_ref, m_ref,
                  acc_scr, l_scr, m_scr, *,
                  block_q: int, block_k: int, t_real: int, scale: float):
    """One (batch*head, q-block, k-block) grid cell.

    The k-block axis is innermost: for a fixed (bh, q-block), scratch
    (acc, l, m) carries the online-softmax state across k iterations; the
    output block is written on the last one (standard revisiting pattern).

    q_ref: [1, block_q, d]; k_ref/v_ref: [1, block_k, d];
    o_ref: [1, block_q, d]; l_ref/m_ref: [1, 1, block_q] (saved for
    backward — the length-1 middle axis keeps the last-two block dims
    (1, block_q) legal under Mosaic's (8, 128) tiling rule: a 2-D
    [bh, tq] layout with (1, block_q) blocks fails to lower on real TPU);
    l_scr/m_scr: [block_q, 128] f32 (value broadcast across lanes).
    """
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    n_kb = pl.num_programs(2)
    q_start = qi * block_q
    k_start = kb * block_k

    @pl.when(kb == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)
        l_scr[...] = jnp.zeros_like(l_scr)
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)

    # causal: k-blocks wholly past the diagonal contribute nothing — skip
    @pl.when(k_start < q_start + block_q)
    def _compute():
        # dots run in the INPUT dtype (bf16 stays bf16 on the MXU — 3x the
        # f32 throughput) with f32 accumulation via preferred_element_type;
        # only the softmax statistics are f32
        q = q_ref[0]                                      # [bq, d]
        k = k_ref[0]                                      # [bk, d]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        qpos = q_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        kpos = k_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = (qpos >= kpos) & (kpos < t_real)
        s = jnp.where(mask, s, NEG_INF)
        m_prev = m_scr[:, 0]
        l_prev = l_scr[:, 0]
        m_new = jnp.maximum(m_prev, s.max(axis=1))
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        acc_scr[...] = (acc_scr[...] * corr[:, None]
                        + jax.lax.dot_general(
                            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32))
        l_new = l_prev * corr + p.sum(axis=1)
        l_scr[...] = jnp.broadcast_to(l_new[:, None], l_scr.shape)
        m_scr[...] = jnp.broadcast_to(m_new[:, None], m_scr.shape)

    @pl.when(kb == n_kb - 1)
    def _finalize():
        l = l_scr[:, 0]
        o_ref[0] = (acc_scr[...]
                    / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
        l_ref[0, 0] = l
        m_ref[0, 0] = m_scr[:, 0]


def _flash_fwd_call(q, k, v, block_q: int, block_k: int):
    """Run the kernel. q/k/v: [B, H, T, Dh] -> (o [B,H,T,Dh], l, m [B,H,T])."""
    if not _HAS_PLTPU:  # pragma: no cover
        raise RuntimeError("flash_attention needs jax.experimental.pallas.tpu")
    b, h, t, dh = q.shape
    scale = 1.0 / math.sqrt(dh)
    # MXU tiling: lane dim -> 128, q/k blocks -> sublane multiples
    qp = _pad_to(_pad_to(q, 3, _LANES), 2, block_q)
    kp = _pad_to(_pad_to(k, 3, _LANES), 2, block_k)
    vp = _pad_to(_pad_to(v, 3, _LANES), 2, block_k)
    tq, dp = qp.shape[2], qp.shape[3]
    tk = kp.shape[2]
    bh = b * h
    qp = qp.reshape(bh, tq, dp)
    kp = kp.reshape(bh, tk, dp)
    vp = vp.reshape(bh, tk, dp)

    grid = (bh, tq // block_q, tk // block_k)
    kernel = functools.partial(_flash_kernel, block_q=block_q,
                               block_k=block_k, t_real=t, scale=scale)
    # bh and q-blocks are independent; the k axis carries scratch state
    compiler_params = _compiler_params("parallel", "parallel", "arbitrary")

    # Causal fetch elision (_diag_kv_index): the kernel predicates off
    # compute for k-blocks past the diagonal, but an unclamped index map
    # would still FETCH those blocks from HBM every iteration — rectangular
    # K/V traffic for triangular work, growing with T (the r4 "flash trails
    # dense more the longer the sequence" signature). The clamp cuts K/V
    # HBM reads ~2x for causal.
    _kv_idx = _diag_kv_index(block_q, block_k)
    vma = _vma_of(qp, kp, vp)

    o, l, m = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, dp), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, block_k, dp), _kv_idx),
            pl.BlockSpec((1, block_k, dp), _kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, dp), lambda i, j, kb: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j, kb: (i, 0, j)),
            pl.BlockSpec((1, 1, block_q), lambda i, j, kb: (i, 0, j)),
        ],
        out_shape=[
            _struct((bh, tq, dp), q.dtype, vma),
            _struct((bh, 1, tq), jnp.float32, vma),
            _struct((bh, 1, tq), jnp.float32, vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, dp), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        compiler_params=compiler_params,
        interpret=_interpret(),
    )(qp, kp, vp)
    o = o.reshape(b, h, tq, dp)[:, :, :t, :dh]
    l = l.reshape(b, h, tq)[:, :, :t]
    m = m.reshape(b, h, tq)[:, :, :t]
    return o, l, m


def _rows_3d(x: jax.Array, bh: int, tq: int) -> jax.Array:
    """[B, H, Tpad] -> [bh, 1, tq]: the Mosaic-legal per-row layout (see
    ``_flash_kernel`` docstring on the length-1 middle axis)."""
    return x.reshape(bh, 1, tq)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    block_q: int = 128, block_k: int = 128) -> jax.Array:
    """Causal flash attention. q/k/v: [B, H, T, Dh] -> [B, H, T, Dh].

    Matches the dense reference :func:`~.attention.causal_attention` core to
    float tolerance while never materializing the [T, T] score matrix.
    """
    o, _, _ = _flash_fwd_call(q, k, v, block_q, block_k)
    return o


def _flash_fwd(q, k, v, block_q, block_k):
    o, l, m = _flash_fwd_call(q, k, v, block_q, block_k)
    return o, (q, k, v, o, l, m)


def _recompute_p(q_ref, k_ref, m_ref, li_ref, q_start, k_start,
                 block_q, block_k, t_real, scale):
    """Shared backward-block math: re-derive the probability block
    ``p = exp(s - m) / l`` from the saved softmax statistics (exactly the
    forward's value — no [T, T] residuals; flash-attention-2 practice).
    Returns q/k in their INPUT dtype (the callers' dots stay on the native-
    dtype MXU path) and p in f32."""
    qs = q_ref[0]                                         # [bq, d]
    kk = k_ref[0]                                         # [bk, d]
    s = jax.lax.dot_general(qs, kk, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    qpos = q_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    kpos = k_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    mask = (qpos >= kpos) & (kpos < t_real) & (qpos < t_real)
    m_row = m_ref[0, 0]                                   # [bq]
    li_row = li_ref[0, 0]
    p = jnp.where(mask, jnp.exp(s - m_row[:, None]) * li_row[:, None], 0.0)
    return qs, kk, p


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, m_ref, li_ref, dl_ref,
               dq_ref, dq_scr, *,
               block_q: int, block_k: int, t_real: int, scale: float):
    """dq pass: grid (bh, q-block, k-block), k innermost.

    For a fixed q block the scratch accumulates ``dq += ds·k·scale`` across
    its (diagonal-bounded) k blocks; ``ds = p*(dp - delta)`` with
    ``dp = do·vᵀ`` and ``delta = rowsum(do*o)`` precomputed outside.
    """
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    n_kb = pl.num_programs(2)
    q_start = qi * block_q
    k_start = kb * block_k

    @pl.when(kb == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    # causal: k-blocks wholly past the diagonal contribute nothing — skip
    @pl.when(k_start < q_start + block_q)
    def _compute():
        _, kk, p = _recompute_p(q_ref, k_ref, m_ref, li_ref, q_start,
                                k_start, block_q, block_k, t_real, scale)
        do = do_ref[0]                                    # [bq, d]
        v = v_ref[0]                                      # [bk, d]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dl_ref[0, 0][:, None])
        dq_scr[...] += jax.lax.dot_general(
            (ds * scale).astype(kk.dtype), kk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == n_kb - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(k_ref, v_ref, q_ref, do_ref, m_ref, li_ref, dl_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *,
                block_q: int, block_k: int, t_real: int, scale: float):
    """dk/dv pass: grid (bh, k-block, q-block), q innermost.

    For a fixed k block the scratch accumulates ``dv += pᵀ·do`` and
    ``dk += dsᵀ·(q·scale)`` across its q blocks, starting at the causal
    diagonal (earlier q blocks are fully masked).
    """
    kbi = pl.program_id(1)
    qb = pl.program_id(2)
    n_qb = pl.num_programs(2)
    k_start = kbi * block_k
    q_start = qb * block_q

    @pl.when(qb == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    # causal: q-blocks wholly before this k block see none of it — skip
    @pl.when(q_start + block_q > k_start)
    def _compute():
        qs, _, p = _recompute_p(q_ref, k_ref, m_ref, li_ref, q_start,
                                k_start, block_q, block_k, t_real, scale)
        do = do_ref[0]                                    # [bq, d]
        v = v_ref[0]                                      # [bk, d]
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),  # pᵀ·do
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dl_ref[0, 0][:, None])
        dk_scr[...] += jax.lax.dot_general(
            (ds * scale).astype(qs.dtype), qs,
            (((0,), (0,)), ((), ())),                     # dsᵀ·qs -> [bk, d]
            preferred_element_type=jnp.float32)

    @pl.when(qb == n_qb - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd(block_q, block_k, res, do):
    """Pallas recompute-based backward (flash-attention-2 style).

    Two kernels with the forward's blocking: a dq pass (k innermost,
    diagonal-bounded like the forward) and a dk/dv pass (q innermost,
    starting at the diagonal). Both re-derive each probability block from
    the saved (l, m) — ``p = exp(s - m)/l`` — so no [T, T] matrix and no
    attention-weight residuals ever exist; VMEM stays O(block·d) per cell.
    """
    q, k, v, o, l, m = res
    b, h, t, dh = q.shape
    scale = 1.0 / math.sqrt(dh)
    # delta_i = sum_j do_ij * o_ij (rowwise), the softmax-jacobian constant
    delta = (do.astype(jnp.float32) * o.astype(jnp.float32)).sum(-1)
    # padded q rows: mask has qpos >= t_real, so their p-blocks are all-zero;
    # linv pads to 0 as belt-and-braces
    mp = _pad_to(m, 2, block_q)
    linvp = _pad_to(1.0 / jnp.maximum(l, 1e-30), 2, block_q)
    dlp = _pad_to(delta, 2, block_q)
    qp = _pad_to(_pad_to(q, 3, _LANES), 2, block_q)
    dop = _pad_to(_pad_to(do, 3, _LANES), 2, block_q)
    kp = _pad_to(_pad_to(k, 3, _LANES), 2, block_k)
    vp = _pad_to(_pad_to(v, 3, _LANES), 2, block_k)
    tq, dp_ = qp.shape[2], qp.shape[3]
    tk = kp.shape[2]
    bh = b * h
    qp = qp.reshape(bh, tq, dp_)
    dop = dop.reshape(bh, tq, dp_)
    kp = kp.reshape(bh, tk, dp_)
    vp = vp.reshape(bh, tk, dp_)
    mp = _rows_3d(mp, bh, tq)
    linvp = _rows_3d(linvp, bh, tq)
    dlp = _rows_3d(dlp, bh, tq)
    n_qb, n_kb = tq // block_q, tk // block_k
    vma = _vma_of(qp, kp, vp, dop, mp, linvp, dlp)

    q_spec = pl.BlockSpec((1, block_q, dp_), lambda i, j, kb: (i, j, 0))
    # clamp past-diagonal k fetches to the last needed block (same causal
    # fetch elision as the forward — skipped cells must not cost HBM reads)
    k_spec = pl.BlockSpec((1, block_k, dp_), _diag_kv_index(block_q, block_k))
    row_spec = pl.BlockSpec((1, 1, block_q), lambda i, j, kb: (i, 0, j))
    compiler_params = _compiler_params("parallel", "parallel", "arbitrary")

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_q=block_q, block_k=block_k,
                          t_real=t, scale=scale),
        grid=(bh, n_qb, n_kb),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec,
                  row_spec],
        out_specs=pl.BlockSpec((1, block_q, dp_), lambda i, j, kb: (i, j, 0)),
        out_shape=_struct((bh, tq, dp_), q.dtype, vma),
        scratch_shapes=[pltpu.VMEM((block_q, dp_), jnp.float32)],
        compiler_params=compiler_params,
        interpret=_interpret(),
    )(qp, kp, vp, dop, mp, linvp, dlp)

    # dkv grid: (bh, k-block, q-block) — index maps select by the axis kind.
    # Pre-diagonal q-blocks see none of this k block: clamp their fetches up
    # to the first needed q block (fetch elision, mirror of the forward)
    def _q_idx(i, j, qb):
        return (i, jnp.maximum(qb, (j * block_k) // block_q), 0)

    def _row_idx(i, j, qb):
        return (i, 0, jnp.maximum(qb, (j * block_k) // block_q))

    kv_spec = pl.BlockSpec((1, block_k, dp_), lambda i, j, qb: (i, j, 0))
    qi_spec = pl.BlockSpec((1, block_q, dp_), _q_idx)
    rowi_spec = pl.BlockSpec((1, 1, block_q), _row_idx)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=block_q, block_k=block_k,
                          t_real=t, scale=scale),
        grid=(bh, n_kb, n_qb),
        in_specs=[kv_spec, kv_spec, qi_spec, qi_spec, rowi_spec, rowi_spec,
                  rowi_spec],
        out_specs=[
            pl.BlockSpec((1, block_k, dp_), lambda i, j, qb: (i, j, 0)),
            pl.BlockSpec((1, block_k, dp_), lambda i, j, qb: (i, j, 0)),
        ],
        out_shape=[
            _struct((bh, tk, dp_), k.dtype, vma),
            _struct((bh, tk, dp_), v.dtype, vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, dp_), jnp.float32),
            pltpu.VMEM((block_k, dp_), jnp.float32),
        ],
        compiler_params=compiler_params,
        interpret=_interpret(),
    )(kp, vp, qp, dop, mp, linvp, dlp)

    dq = dq.reshape(b, h, tq, dp_)[:, :, :t, :dh]
    dk = dk.reshape(b, h, tk, dp_)[:, :, :t, :dh]
    dv = dv.reshape(b, h, tk, dp_)[:, :, :t, :dh]
    return dq, dk, dv


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def flash_mha(params: dict, x: jax.Array, n_heads: int,
              block_q: int = 128, block_k: int = 128) -> jax.Array:
    """Drop-in for :func:`~.attention.causal_attention` using the Pallas core.

    x: [B, T, D] -> [B, T, D], with the same QKVO params
    (:func:`~.attention.mha_init`).
    """
    from simple_distributed_machine_learning_tpu.ops.attention import (
        _merge_heads,
        _split_heads,
    )
    q = _split_heads(x @ params["wq"], n_heads)
    k = _split_heads(x @ params["wk"], n_heads)
    v = _split_heads(x @ params["wv"], n_heads)
    o = flash_attention(q, k, v, block_q, block_k)
    return _merge_heads(o) @ params["wo"]
