"""Pallas paged attention: fused block-table gather + flash-style decode.

The serving hot path (``models/gpt.py::_paged_decode_fwd`` and the
speculative ``_paged_verify_fwd``) historically did the standard two-pass
dance every tick: gather each slot's physical K/V blocks into a dense
``[S, H, span, dh]`` row buffer (one full HBM read of resident K/V plus a
full write of the gathered copy), then dense masked attention over that
buffer (a second full read). This module fuses the two into ONE Pallas
kernel pass, following the grid/online-softmax structure of
``ops/flash_attention.py``:

- **block-table-indexed gather**: the per-slot block table and query
  positions ride in as scalar-prefetch operands
  (``pltpu.PrefetchScalarGridSpec``), so the K/V BlockSpec index maps
  dereference ``tables[s, kb]`` directly — each physical block streams from
  HBM into VMEM exactly once per tick, already in sequence order, and no
  gathered dense copy ever exists;
- **online softmax** (flash style): the k-block grid axis is innermost and
  carries ``(acc, l, m)`` scratch across iterations, so the ``[K, span]``
  score matrix is never materialized and VMEM holds O(H·K·dh + H·bs·dh);
- **past-the-end fetch elision**: k-blocks wholly past the newest query
  position are predicated off with ``pl.when``, and the index map clamps
  their block id at the last needed one — an unchanged index between
  iterations means Mosaic's pipeline issues no HBM copy (the
  ``_diag_kv_index`` trick from the causal kernel, applied to the
  position mask instead of the diagonal);
- **fused dequantization**: int8/fp8 K/V blocks carry per-row (position x
  head) f32 scales; the kernel multiplies them back in VMEM right after the
  block load, so a quantized pool pays the narrow dtype's HBM bytes without
  a separate dequantize pass (the whole point of quantizing: the decode
  tick is memory-bound on exactly this stream);
- **f32 score/accumulator math**: K/V tiles are upcast (or dequantized) to
  f32 before the dots, matching the dense path's einsum promotion — which
  is what keeps greedy decode through this kernel TOKEN-bit-exact against
  the gather-then-dense path (logits agree to accumulation-order ulps;
  tests/test_paged_attention.py pins both).

On non-TPU backends the same kernel runs in Pallas interpret mode
(``flash_attention._interpret``), so the serving engine's ``kernel="fused"``
path is exercised hermetically on CPU. One kernel serves both tick shapes:
the single-query flash-decode tick is the ``K = 1`` case of the K-token
speculative verify.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from simple_distributed_machine_learning_tpu.ops.flash_attention import (
    _HAS_PLTPU,
    _LANES,
    NEG_INF,
    _compiler_params,
    _interpret,
    _struct,
    _vma_of,
    pltpu,
)


def _paged_attn_kernel(tables_ref, qpos_ref, q_ref, k_ref, v_ref, *rest,
                       bs: int, n_q: int, scale: float, quant: bool,
                       packed: bool):
    """One (slot, k-block) grid cell; k-block innermost carries the
    online-softmax state.

    ``q_ref``: [1, H, K, dh] (this slot's queries, all heads);
    ``k_ref``/``v_ref``: [1, H, bs, dh] — the PHYSICAL block the index map
    dereferenced through the slot's table (``packed``: [1, H, dh, bs], the
    block positions living in the 128-lane slot so a small head dim pads
    to sublanes, not lanes); with ``quant``, ``ks_ref``/``vs_ref``:
    [1, H, bs] per-row dequant scales of the same block; ``o_ref``:
    [1, H, K, dh] f32. Scratch: ``acc`` [H, K, dh] f32 and the
    lane-broadcast ``l``/``m`` [H, K, _LANES] f32 (flash_attention's
    scratch idiom)."""
    if quant:
        ks_ref, vs_ref, o_ref, acc_scr, l_scr, m_scr = rest
    else:
        o_ref, acc_scr, l_scr, m_scr = rest
    s_idx = pl.program_id(0)
    kb = pl.program_id(1)
    n_kb = pl.num_programs(1)

    @pl.when(kb == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)
        l_scr[...] = jnp.zeros_like(l_scr)
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)

    # k-blocks wholly past the newest query position contribute nothing —
    # skip (their fetch is elided by the index-map clamp below)
    @pl.when(kb * bs <= qpos_ref[s_idx, n_q - 1])
    def _compute():
        # per-query positions of this slot (K is static and small)
        qp = jnp.stack([qpos_ref[s_idx, j] for j in range(n_q)])
        q = q_ref[0].astype(jnp.float32)                  # [H, K, dh]
        k = k_ref[0].astype(jnp.float32)      # [H, bs, dh] / packed [H, dh, bs]
        v = v_ref[0].astype(jnp.float32)
        if quant:
            scl = (ks_ref[0][:, None, :], vs_ref[0][:, None, :]) \
                if packed else (ks_ref[0][..., None], vs_ref[0][..., None])
            k = k * scl[0]
            v = v * scl[1]
        # scores in f32 — the dense path's einsum promotion, so the fused
        # logits track the gather-then-dense ones to ulps
        kdim = 1 if packed else 2
        s = lax.dot_general(q, k, (((2,), (kdim,)), ((0,), (0,)))) * scale
        kpos = kb * bs + lax.broadcasted_iota(jnp.int32, (1, n_q, bs), 2)
        mask = kpos <= qp[None, :, None]                  # [1, K, bs]
        s = jnp.where(mask, s, NEG_INF)                   # [H, K, bs]
        m_prev = m_scr[..., 0]                            # [H, K]
        l_prev = l_scr[..., 0]
        m_new = jnp.maximum(m_prev, s.max(axis=2))
        p = jnp.exp(s - m_new[..., None])
        p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        vdim = 2 if packed else 1
        acc_scr[...] = (acc_scr[...] * corr[..., None]
                        + lax.dot_general(p, v,
                                          (((2,), (vdim,)), ((0,), (0,)))))
        l_scr[...] = jnp.broadcast_to(
            (l_prev * corr + p.sum(axis=2))[..., None], l_scr.shape)
        m_scr[...] = jnp.broadcast_to(m_new[..., None], m_scr.shape)

    @pl.when(kb == n_kb - 1)
    def _finalize():
        l = l_scr[..., 0]
        o_ref[0] = (acc_scr[...]
                    / jnp.maximum(l, 1e-30)[..., None]).astype(o_ref.dtype)


#: f32 sublane quantum — the ``packed`` layout pads the head dim to this
_SUBLANES = 8


def paged_attention(q: jax.Array, kc: jax.Array, vc: jax.Array,
                    tables: jax.Array, qpos: jax.Array, *,
                    block_size: int, kscale: jax.Array | None = None,
                    vscale: jax.Array | None = None,
                    _layout: str = "auto") -> jax.Array:
    """Fused paged attention over one layer's physical block pool.

    ``q``: [S, H, K, dh] queries (K = 1 for the flash-decode tick, the
    speculative width for verify); ``kc``/``vc``: [n_blocks+1, H, bs, dh]
    physical blocks (trash block 0 included); ``tables``: [S, NB] int32
    logical->physical ids; ``qpos``: [S, K] int32 query positions,
    NON-DECREASING along K (the engine's ``pos + j`` plan). With a
    quantized pool pass ``kscale``/``vscale`` [n_blocks+1, H, bs] — the
    per-row f32 dequant scales — and int8/fp8 ``kc``/``vc``.

    Returns f32 [S, H, K, dh]: exactly what the dense-math path's masked
    softmax-attention einsum pair produces over the gathered span, with
    rows past each query's position masked out (trash-table entries
    included, same as the dense mask).

    ``_layout`` picks how K/V blocks meet Mosaic's (sublane, lane) tiles:

    - ``"natural"`` — blocks stream as stored, ``[1, H, bs, dh]`` with the
      head dim in the 128-lane slot. Fine when ``dh`` is a lane multiple;
      a small head dim pads every block up to 128 lanes (the ROADMAP #2
      hazard the ``kernel-tile.pad-waste`` lint flags).
    - ``"packed"`` — K/V blocks are transposed once on the host to
      ``[1, H, dh', bs]`` (``dh'`` = ``dh`` rounded up to the f32 sublane
      quantum, 8): block positions take the lane slot, the small head dim
      pads at most 2x into sublanes instead of up to 32x into lanes. The
      zero-padded rows contribute nothing to either dot, so the math is
      identical to ``"natural"``.
    - ``"auto"`` (default) — ``natural`` when ``dh`` is a lane multiple or
      in interpret mode (no tiling there), else ``packed``.
    """
    if not _HAS_PLTPU:  # pragma: no cover
        raise RuntimeError("paged_attention needs jax.experimental.pallas."
                           "tpu (interpret mode covers non-TPU backends)")
    S, H, K, dh = q.shape
    NB = tables.shape[1]
    bs = int(block_size)
    if kc.shape[-2] != bs:
        raise ValueError(f"kc block axis {kc.shape[-2]} != block_size {bs}")
    quant = kscale is not None
    if quant != (vscale is not None):
        raise ValueError("pass both kscale and vscale, or neither")
    if _layout not in ("auto", "natural", "packed"):
        raise ValueError(f"_layout must be auto/natural/packed, "
                         f"got {_layout!r}")
    scale = 1.0 / math.sqrt(dh)
    interpret = _interpret()
    layout = _layout
    if layout == "auto":
        layout = ("natural" if interpret or dh % _LANES == 0
                  else "packed")
    packed = layout == "packed"
    dp = dh
    if packed:
        dp = dh + (-dh) % _SUBLANES
        if dp != dh:
            pad = [(0, 0)] * 3 + [(0, dp - dh)]
            q = jnp.pad(q, pad)
            kc = jnp.pad(kc, pad)
            vc = jnp.pad(vc, pad)
        # one host-side transpose per tick ([..., bs, dh'] -> [..., dh', bs])
        # beats the old pad-to-128-lanes copy (<= 2x bytes vs up to 32x)
        kc = jnp.swapaxes(kc, -1, -2)
        vc = jnp.swapaxes(vc, -1, -2)

    def _kv_idx(s, kb, tables_ref, qpos_ref):
        # past-the-end fetch elision: clamp at the newest query's block so
        # skipped iterations revisit it (no HBM copy when unchanged)
        last = qpos_ref[s, K - 1] // bs
        return (tables_ref[s, jnp.minimum(kb, last)], 0, 0, 0)

    def _q_idx(s, kb, tables_ref, qpos_ref):
        return (s, 0, 0, 0)

    def _scale_idx(s, kb, tables_ref, qpos_ref):
        last = qpos_ref[s, K - 1] // bs
        return (tables_ref[s, jnp.minimum(kb, last)], 0, 0)

    kv_block = (1, H, dp, bs) if packed else (1, H, bs, dp)
    in_specs = [
        pl.BlockSpec((1, H, K, dp), _q_idx),
        pl.BlockSpec(kv_block, _kv_idx),
        pl.BlockSpec(kv_block, _kv_idx),
    ]
    operands = [q, kc, vc]
    if quant:
        in_specs += [pl.BlockSpec((1, H, bs), _scale_idx),
                     pl.BlockSpec((1, H, bs), _scale_idx)]
        operands += [kscale, vscale]

    vma = _vma_of(q, kc, vc)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, NB),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, H, K, dp), _q_idx),
        scratch_shapes=[
            pltpu.VMEM((H, K, dp), jnp.float32),
            pltpu.VMEM((H, K, _LANES), jnp.float32),
            pltpu.VMEM((H, K, _LANES), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_attn_kernel, bs=bs, n_q=K, scale=scale,
                          quant=quant, packed=packed),
        grid_spec=grid_spec,
        out_shape=_struct((S, H, K, dp), jnp.float32, vma),
        # slots are independent; the k-block axis carries scratch state
        compiler_params=_compiler_params("parallel", "arbitrary"),
        interpret=interpret,
    )(tables.astype(jnp.int32), qpos.astype(jnp.int32), *operands)
    return out[..., :dh]


def paged_flash_decode(q: jax.Array, kc: jax.Array, vc: jax.Array,
                       tables: jax.Array, pos: jax.Array, *,
                       block_size: int, kscale: jax.Array | None = None,
                       vscale: jax.Array | None = None) -> jax.Array:
    """The one-query-per-slot flash-decode tick: ``q`` [S, H, 1, dh],
    ``pos`` [S] — the ``K = 1`` specialization of :func:`paged_attention`
    (the decode tick attends every position ``<= pos[s]``)."""
    return paged_attention(q, kc, vc, tables, pos[:, None],
                           block_size=block_size, kscale=kscale,
                           vscale=vscale)
