"""Core NN layer ops as pure functions (explicit params, explicit RNG).

Capability parity with the reference's layer set — ``Conv2d``, ``Linear``,
``Dropout2d``, ``F.relu``, ``F.max_pool2d``, ``F.dropout``
(``/root/reference/simple_distributed.py:29-31,:42-46,:63-64,:75``) — rebuilt
TPU-first:

- convs run in NHWC / HWIO layout (the TPU-preferred layout; XLA tiles the
  contraction onto the MXU without transposes);
- linear weights are stored ``[in, out]`` so ``x @ w`` is a row-major matmul;
- dropout takes an explicit PRNG key and a ``deterministic`` flag instead of
  torch's global RNG + implicit ``module.training`` state (the reference's eval
  path famously leaves worker-side dropout on — ``simple_distributed.py:75``
  with ``model.eval()`` never crossing RPC at ``:120``; here eval is simply
  ``deterministic=True``);
- initializers reproduce torch's defaults (kaiming-uniform with a=sqrt(5),
  i.e. U(-1/sqrt(fan_in), 1/sqrt(fan_in)) for both weight and bias) so loss
  curves are distributionally comparable with the reference.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax


def _torch_uniform_bound(fan_in: int) -> float:
    # torch nn.Linear / nn.Conv2d default init: kaiming_uniform_(a=sqrt(5))
    # reduces to U(-1/sqrt(fan_in), +1/sqrt(fan_in)); bias uses the same bound.
    return 1.0 / math.sqrt(fan_in)


def linear_init(key: jax.Array, in_features: int, out_features: int,
                dtype=jnp.float32) -> dict:
    """Params for a dense layer: ``{'w': [in, out], 'b': [out]}``."""
    kw, kb = jax.random.split(key)
    bound = _torch_uniform_bound(in_features)
    return {
        "w": jax.random.uniform(kw, (in_features, out_features), dtype,
                                minval=-bound, maxval=bound),
        "b": jax.random.uniform(kb, (out_features,), dtype,
                                minval=-bound, maxval=bound),
    }


def linear(params: dict, x: jax.Array) -> jax.Array:
    """``x @ w + b``. x: [..., in] -> [..., out]."""
    return jnp.matmul(x, params["w"]) + params["b"]


def conv2d_init(key: jax.Array, in_channels: int, out_channels: int,
                kernel_size: int | Sequence[int], dtype=jnp.float32) -> dict:
    """Params for a 2-D conv in HWIO layout: ``{'w': [kh, kw, in, out], 'b': [out]}``."""
    if isinstance(kernel_size, int):
        kh = kw = kernel_size
    else:
        kh, kw = kernel_size
    kkey, bkey = jax.random.split(key)
    fan_in = in_channels * kh * kw
    bound = _torch_uniform_bound(fan_in)
    return {
        "w": jax.random.uniform(kkey, (kh, kw, in_channels, out_channels), dtype,
                                minval=-bound, maxval=bound),
        "b": jax.random.uniform(bkey, (out_channels,), dtype,
                                minval=-bound, maxval=bound),
    }


def conv2d(params: dict, x: jax.Array, stride: int = 1,
           padding: str = "VALID") -> jax.Array:
    """2-D convolution, NHWC activations / HWIO weights (TPU-native layout).

    x: [N, H, W, C_in] -> [N, H', W', C_out]. The reference's convs are NCHW
    torch modules (``simple_distributed.py:29-30``); NHWC is the layout the TPU
    MXU wants, so the framework standardizes on it end to end.
    """
    y = lax.conv_general_dilated(
        x, params["w"],
        window_strides=(stride, stride),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return y + params["b"]


def max_pool2d(x: jax.Array, window: int = 2, stride: int | None = None) -> jax.Array:
    """Max pooling over H, W of an NHWC tensor (``F.max_pool2d`` equivalent)."""
    stride = window if stride is None else stride
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, window, window, 1),
        window_strides=(1, stride, stride, 1),
        padding="VALID",
    )


def relu(x: jax.Array) -> jax.Array:
    return jax.nn.relu(x)


def layer_norm_init(d: int, dtype=jnp.float32) -> dict:
    return {"scale": jnp.ones((d,), dtype), "bias": jnp.zeros((d,), dtype)}


def layer_norm(params: dict, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    """LayerNorm over the trailing feature axis."""
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * params["scale"] + params["bias"]


def embedding_init(key: jax.Array, vocab: int, d: int,
                   dtype=jnp.float32) -> jax.Array:
    """Token-embedding table [vocab, d] (normal 0.02, GPT convention)."""
    return 0.02 * jax.random.normal(key, (vocab, d), dtype)


def embedding_lookup(table: jax.Array, ids: jax.Array) -> jax.Array:
    return jnp.take(table, ids, axis=0)


def dropout(key: jax.Array, x: jax.Array, rate: float = 0.5,
            deterministic: bool = False) -> jax.Array:
    """Inverted dropout (``F.dropout`` equivalent, explicit key & mode)."""
    if deterministic or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


def dropout2d(key: jax.Array, x: jax.Array, rate: float = 0.5,
              deterministic: bool = False) -> jax.Array:
    """Channel dropout (``nn.Dropout2d`` equivalent): zeroes whole channels.

    x is NHWC, so the mask is drawn per (sample, channel) and broadcast over
    H and W — same semantics as torch's NCHW Dropout2d.
    """
    if deterministic or rate == 0.0:
        return x
    keep = 1.0 - rate
    n, _, _, c = x.shape
    mask = jax.random.bernoulli(key, keep, (n, 1, 1, c))
    return jnp.where(mask, x / keep, 0.0)
