"""Functional NN ops: pure functions over explicit params and RNG keys.

TPU-native replacement for the ATen kernels the reference invokes through
``torch.nn`` / ``torch.nn.functional`` (``/root/reference/simple_distributed.py:42-46,
:75-79``). Everything here lowers to XLA:TPU HLO; layouts are chosen for the MXU
(NHWC convs, ``[in, out]`` matmul weights).
"""

from simple_distributed_machine_learning_tpu.ops.layers import (  # noqa: F401
    conv2d,
    conv2d_init,
    dropout,
    dropout2d,
    linear,
    linear_init,
    max_pool2d,
    relu,
)
from simple_distributed_machine_learning_tpu.ops.losses import (  # noqa: F401
    accuracy,
    log_softmax,
    nll_loss,
    softmax_cross_entropy,
)
