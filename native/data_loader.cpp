// Native data loader: IDX parsing + threaded batch prefetch.
//
// TPU-native analogue of the reference's native data path (the reference
// leans on torch's C++ DataLoader machinery and torchvision's MNIST codec;
// SURVEY §2.3). Exposed to Python via ctypes (no pybind11 in the image —
// plain C ABI).
//
// Two facilities:
//   1. idx_read / idx_free — parse big-endian IDX files (images or labels)
//      into a caller-owned float32/int32 buffer, normalizing u8 images to
//      [0, 1] NHWC.
//   2. prefetcher_* — a background thread that assembles fixed-size batches
//      (gather rows by index) into a small ring of pinned host buffers while
//      the accelerator step runs, hiding host-side batch-assembly latency.
//
// Build: make -C native   (produces libsdml_data.so)

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- IDX codec

// Reads an IDX file. Returns 0 on success. Caller frees with idx_free.
//   out_data: float32 buffer (u8 data normalized /255; other dtypes cast)
//   out_dims: up to 4 dims, unused set to 1; out_ndim: actual rank.
int idx_read(const char* path, float** out_data, int64_t* out_dims,
             int* out_ndim) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  unsigned char magic[4];
  if (std::fread(magic, 1, 4, f) != 4) { std::fclose(f); return -2; }
  if (magic[0] != 0 || magic[1] != 0) { std::fclose(f); return -3; }
  const int dtype = magic[2];  // 0x08 u8, 0x0D f32
  const int ndim = magic[3];
  if (ndim < 1 || ndim > 4) { std::fclose(f); return -4; }

  int64_t total = 1;
  for (int i = 0; i < 4; ++i) out_dims[i] = 1;
  for (int i = 0; i < ndim; ++i) {
    unsigned char b[4];
    if (std::fread(b, 1, 4, f) != 4) { std::fclose(f); return -5; }
    int64_t d = (int64_t(b[0]) << 24) | (int64_t(b[1]) << 16) |
                (int64_t(b[2]) << 8) | int64_t(b[3]);
    out_dims[i] = d;
    total *= d;
  }
  *out_ndim = ndim;

  float* dst = static_cast<float*>(std::malloc(total * sizeof(float)));
  if (!dst) { std::fclose(f); return -6; }

  if (dtype == 0x08) {  // unsigned byte
    std::vector<unsigned char> raw(total);
    if (std::fread(raw.data(), 1, total, f) != size_t(total)) {
      std::free(dst); std::fclose(f); return -7;
    }
    const float inv = 1.0f / 255.0f;
    // labels (ndim==1) stay as raw values; images normalize to [0,1]
    const float scale = (ndim == 1) ? 1.0f : inv;
    for (int64_t i = 0; i < total; ++i) dst[i] = raw[i] * scale;
  } else if (dtype == 0x0D) {  // big-endian float32
    std::vector<unsigned char> raw(total * 4);
    if (std::fread(raw.data(), 1, total * 4, f) != size_t(total) * 4) {
      std::free(dst); std::fclose(f); return -7;
    }
    for (int64_t i = 0; i < total; ++i) {
      unsigned char* p = &raw[i * 4];
      uint32_t v = (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
                   (uint32_t(p[2]) << 8) | uint32_t(p[3]);
      std::memcpy(&dst[i], &v, 4);
    }
  } else {
    std::free(dst); std::fclose(f); return -8;
  }
  std::fclose(f);
  *out_data = dst;
  return 0;
}

void idx_free(float* p) { std::free(p); }

// ------------------------------------------------------------- prefetcher

// Ring-buffered background batch assembly: gathers rows of a source array
// into batch buffers on a worker thread.
struct Prefetcher {
  const float* src_x;      // [n, row_x] row-major
  const int32_t* src_y;    // [n, row_y]
  int64_t row_x, row_y, n;
  int64_t batch;
  const int64_t* order;    // [n] gather order (epoch permutation), owned copy
  std::vector<int64_t> order_store;

  int depth;               // ring slots
  std::vector<std::vector<float>> slot_x;
  std::vector<std::vector<int32_t>> slot_y;
  std::vector<int> slot_state;  // 0 empty, 1 full
  int64_t next_produce = 0, next_consume = 0, n_batches = 0;

  std::mutex mu;
  std::condition_variable cv_full, cv_empty;
  std::thread worker;
  std::atomic<bool> stop{false};

  void run() {
    while (!stop.load()) {
      int64_t b;
      int slot;
      {
        std::unique_lock<std::mutex> lk(mu);
        if (next_produce >= n_batches) return;
        b = next_produce;
        slot = int(b % depth);
        cv_empty.wait(lk, [&] {
          return stop.load() || slot_state[slot] == 0;
        });
        if (stop.load()) return;
        next_produce++;
      }
      float* bx = slot_x[slot].data();
      int32_t* by = slot_y[slot].data();
      const int64_t start = b * batch;
      for (int64_t i = 0; i < batch; ++i) {
        const int64_t src_row =
            (start + i < n) ? order[start + i] : -1;  // pad with zeros
        if (src_row >= 0) {
          std::memcpy(bx + i * row_x, src_x + src_row * row_x,
                      row_x * sizeof(float));
          std::memcpy(by + i * row_y, src_y + src_row * row_y,
                      row_y * sizeof(int32_t));
        } else {
          std::memset(bx + i * row_x, 0, row_x * sizeof(float));
          std::memset(by + i * row_y, 0, row_y * sizeof(int32_t));
        }
      }
      {
        std::lock_guard<std::mutex> lk(mu);
        slot_state[slot] = 1;
      }
      cv_full.notify_one();
    }
  }
};

void* prefetcher_create(const float* x, const int32_t* y, int64_t n,
                        int64_t row_x, int64_t row_y, int64_t batch,
                        const int64_t* order, int depth) {
  auto* p = new Prefetcher();
  p->src_x = x; p->src_y = y; p->n = n;
  p->row_x = row_x; p->row_y = row_y; p->batch = batch;
  p->order_store.assign(order, order + n);
  p->order = p->order_store.data();
  p->depth = depth > 0 ? depth : 2;
  p->n_batches = (n + batch - 1) / batch;
  p->slot_x.resize(p->depth);
  p->slot_y.resize(p->depth);
  p->slot_state.assign(p->depth, 0);
  for (int i = 0; i < p->depth; ++i) {
    p->slot_x[i].resize(batch * row_x);
    p->slot_y[i].resize(batch * row_y);
  }
  p->worker = std::thread([p] { p->run(); });
  return p;
}

int64_t prefetcher_num_batches(void* h) {
  return static_cast<Prefetcher*>(h)->n_batches;
}

// Blocks until the next batch is assembled; copies it into out_x/out_y.
// Returns the number of valid rows in the batch, or -1 when exhausted.
int64_t prefetcher_next(void* h, float* out_x, int32_t* out_y) {
  auto* p = static_cast<Prefetcher*>(h);
  int64_t b;
  int slot;
  {
    std::unique_lock<std::mutex> lk(p->mu);
    if (p->next_consume >= p->n_batches) return -1;
    b = p->next_consume;
    slot = int(b % p->depth);
    p->cv_full.wait(lk, [&] { return p->slot_state[slot] == 1; });
    p->next_consume++;
  }
  std::memcpy(out_x, p->slot_x[slot].data(),
              p->batch * p->row_x * sizeof(float));
  std::memcpy(out_y, p->slot_y[slot].data(),
              p->batch * p->row_y * sizeof(int32_t));
  {
    std::lock_guard<std::mutex> lk(p->mu);
    p->slot_state[slot] = 0;
  }
  p->cv_empty.notify_one();
  const int64_t start = b * p->batch;
  const int64_t valid = (start + p->batch <= p->n) ? p->batch : (p->n - start);
  return valid;
}

void prefetcher_destroy(void* h) {
  auto* p = static_cast<Prefetcher*>(h);
  p->stop.store(true);
  p->cv_empty.notify_all();
  p->cv_full.notify_all();
  if (p->worker.joinable()) p->worker.join();
  delete p;
}

}  // extern "C"
