"""Mixed precision (bfloat16 compute, f32 master) and rematerialization."""

import jax
import jax.numpy as jnp
import numpy as np

from simple_distributed_machine_learning_tpu.models.mlp import make_mlp_stages
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.train.optimizer import sgd
from simple_distributed_machine_learning_tpu.train.step import (
    make_scanned_train_step,
    make_train_step,
)


def _problem(batch=8):
    key = jax.random.key(0)
    stages, wd, od = make_mlp_stages(key, [16, 32, 10], 2)
    x = jax.random.normal(jax.random.key(1), (batch, 16))
    y = jax.random.randint(jax.random.key(2), (batch,), 0, 10)
    return stages, wd, od, x, y


def _pipe(stages, wd, od, **kw):
    return Pipeline(stages, make_mesh(n_stages=2, n_data=1), wd, od,
                    n_microbatches=2, **kw)


def test_bf16_close_to_f32_and_master_stays_f32():
    stages, wd, od, x, y = _problem()
    p32 = _pipe(stages, wd, od)
    p16 = _pipe(stages, wd, od, compute_dtype=jnp.bfloat16)
    l32, lp32 = p32.loss_and_logits(p32.init_params(), x, y, jax.random.key(0),
                                    deterministic=True)
    l16, lp16 = p16.loss_and_logits(p16.init_params(), x, y, jax.random.key(0),
                                    deterministic=True)
    assert lp16.dtype == jnp.float32          # loss path re-enters f32
    np.testing.assert_allclose(float(l16), float(l32), rtol=3e-2)
    np.testing.assert_allclose(np.asarray(lp16), np.asarray(lp32), atol=0.15)


def test_bf16_trains():
    stages, wd, od, x, y = _problem(batch=16)
    pipe = _pipe(stages, wd, od, compute_dtype=jnp.bfloat16)
    buf = pipe.init_params()
    assert buf.dtype == jnp.float32           # master params stay f32
    opt = sgd(0.3, momentum=0.5)
    state = opt.init(buf)
    step = make_train_step(pipe, opt)
    l0 = None
    for i in range(20):
        buf, state, l = step(buf, state, x, y, jax.random.key(i))
        l0 = float(l) if l0 is None else l0
    assert float(l) < 0.7 * l0
    assert buf.dtype == jnp.float32


def test_remat_is_numerically_identical():
    stages, wd, od, x, y = _problem()
    base = _pipe(stages, wd, od)
    rem = _pipe(stages, wd, od, remat=True)

    def grad_of(pipe):
        buf = pipe.init_params()
        return jax.grad(lambda b: pipe.loss_and_logits(
            b, x, y, jax.random.key(0), deterministic=True)[0])(buf)

    g1, g2 = grad_of(base), grad_of(rem)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-6, atol=1e-7)


def test_bf16_scanned_fast_path():
    """Single-device scanned window honors compute_dtype (the bench path)."""
    key = jax.random.key(0)
    stages, wd, od = make_mlp_stages(key, [16, 32, 10], 1)
    pipe = Pipeline(stages, make_mesh(1, 1), wd, od,
                    compute_dtype=jnp.bfloat16)
    opt = sgd(0.1, momentum=0.5)
    buf = pipe.init_params()
    state = opt.init(buf)
    step = make_scanned_train_step(pipe, opt)
    xs = jax.random.normal(key, (5, 8, 16))
    ts = jax.random.randint(key, (5, 8), 0, 10)
    buf, state, losses = step(buf, state, xs, ts, key)
    assert buf.dtype == jnp.float32
    assert np.isfinite(np.asarray(losses)).all()
    assert float(losses[-1]) < float(losses[0]) + 0.5
