"""End-to-end loss-trajectory parity against torch itself.

The north star is matching the reference family's loss curve
(``/root/reference/simple_distributed.py:106-117`` is the loop being matched).
Unit tests prove per-op parity (tests/test_ops.py); this test closes the loop:
the SAME torch-initialized LeNet weights run N SGD(momentum) steps in torch
and in this framework's 2-stage pipeline (packed stage-sharded buffer, real
ppermute hops), on the same fixed batch order, dropout-free on both sides
(train-time dropout is stochastic and framework RNGs differ by construction;
SURVEY §6's parity caveat says compare with dropout disabled). Per-step losses
must agree to float32 tolerance — if numerics drift from the reference family
(init layout, conv/pool semantics, log_softmax/nll math, SGD update rule),
this fails.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from simple_distributed_machine_learning_tpu.models.lenet import (
    FEATURES,
    IN_SHAPE,
    N_CLASSES,
    _conv_apply,
    _fc_apply,
)
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import (
    Pipeline,
    Stage,
)
from simple_distributed_machine_learning_tpu.train.optimizer import sgd

N_STEPS = 8
BATCH = 20
LR, MOMENTUM = 0.1, 0.5  # the reference's hyperparameters (:20-21)


def _torch_lenet(seed: int = 0):
    """The reference's Network1+Network2 module spec, torch default init."""
    torch.manual_seed(seed)
    return {
        "conv1": torch.nn.Conv2d(1, 10, 5),
        "conv2": torch.nn.Conv2d(10, 20, 5),
        "fc1": torch.nn.Linear(FEATURES, 50),
        "fc2": torch.nn.Linear(50, N_CLASSES),
    }


def _torch_forward(m: dict, x: torch.Tensor) -> torch.Tensor:
    """Reference forward (``simple_distributed.py:42-46,:75-79``), dropout-free."""
    z = F.relu(F.max_pool2d(m["conv1"](x), 2))
    z = m["conv2"](z)                       # dropout2d off for the parity run
    z = F.relu(F.max_pool2d(z, 2))
    z = z.view(-1, FEATURES)
    z = F.relu(m["fc1"](z))                 # F.dropout off
    return F.log_softmax(m["fc2"](z), dim=1)


def _nhwc_flat_perm() -> np.ndarray:
    """Map our NHWC flatten order (h, w, c) to torch's NCHW order (c, h, w).

    After the two conv/pool blocks the map is [4, 4, 20] (ours) vs [20, 4, 4]
    (torch); entry ``p`` of the result is the torch flat index of our ``p``-th
    flattened feature, so fc1 weights can be re-rowed to consume our layout.
    """
    h_, w_, c_ = 4, 4, 20
    return np.array([c * (h_ * w_) + h * w_ + w
                     for h in range(h_) for w in range(w_) for c in range(c_)])


def _export_torch_params(m: dict) -> tuple[dict, dict]:
    """Torch state -> our stage param pytrees (conv stage, fc stage)."""
    def t2n(t):
        return t.detach().numpy()

    conv = {
        # torch conv weight is OIHW; ours is HWIO
        "conv1": {"w": t2n(m["conv1"].weight).transpose(2, 3, 1, 0),
                  "b": t2n(m["conv1"].bias)},
        "conv2": {"w": t2n(m["conv2"].weight).transpose(2, 3, 1, 0),
                  "b": t2n(m["conv2"].bias)},
    }
    perm = _nhwc_flat_perm()
    fc = {
        # torch linear weight is [out, in]; ours is [in, out]. fc1's input
        # rows are additionally permuted: our flatten is (h, w, c), torch's
        # is (c, h, w) — same features, fixed permutation.
        "fc1": {"w": t2n(m["fc1"].weight).T[perm].copy(),
                "b": t2n(m["fc1"].bias)},
        "fc2": {"w": t2n(m["fc2"].weight).T.copy(),
                "b": t2n(m["fc2"].bias)},
    }
    as_jnp = lambda tree: jax.tree.map(jax.numpy.asarray, tree)
    return as_jnp(conv), as_jnp(fc)


def test_lenet_sgd_loss_trajectory_matches_torch():
    rng = np.random.default_rng(42)
    xs = rng.normal(size=(N_STEPS, BATCH, 28, 28, 1)).astype(np.float32)
    ys = rng.integers(0, N_CLASSES, size=(N_STEPS, BATCH)).astype(np.int32)

    # -- torch trajectory (the reference's loop, :106-117, dropout-free) ----
    m = _torch_lenet()
    params_t = [p for mod in m.values() for p in mod.parameters()]
    opt_t = torch.optim.SGD(params_t, lr=LR, momentum=MOMENTUM)
    torch_losses = []
    for i in range(N_STEPS):
        x = torch.from_numpy(xs[i].transpose(0, 3, 1, 2).copy())  # NHWC->NCHW
        y = torch.from_numpy(ys[i]).long()
        opt_t.zero_grad()
        loss = F.nll_loss(_torch_forward(m, x), y)
        loss.backward()
        opt_t.step()
        torch_losses.append(float(loss))

    # -- this framework: same weights in the packed 2-stage pipeline -------
    conv_params, fc_params = _export_torch_params(_torch_lenet())
    stages = [
        Stage(apply=_conv_apply, params=conv_params, in_shape=IN_SHAPE),
        Stage(apply=_fc_apply, params=fc_params, in_shape=(FEATURES,)),
    ]
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, 28 * 28, N_CLASSES)
    opt = sgd(LR, MOMENTUM)
    buf = pipe.init_params()
    state = opt.init(buf)

    @jax.jit
    def step(buf, state, x, t):
        def loss_fn(b):
            # deterministic=True: dropout off, matching the torch side
            return pipe.loss_and_logits(b, x, t, jax.random.key(0),
                                        deterministic=True)[0]
        loss, grads = jax.value_and_grad(loss_fn)(buf)
        buf, state = opt.update(grads, state, buf)
        return buf, state, loss

    jax_losses = []
    for i in range(N_STEPS):
        buf, state, loss = step(buf, state, xs[i], ys[i])
        jax_losses.append(float(loss))

    # step 0 is identical math on identical weights; later steps compound
    # float32 conv/matmul reduction-order differences through the SGD
    # trajectory, so the tolerance grows per step
    for i, (lt, lj) in enumerate(zip(torch_losses, jax_losses)):
        assert lj == pytest.approx(lt, rel=1e-4 * (i + 1) + 1e-5), (
            f"step {i}: torch={lt:.6f} ours={lj:.6f} "
            f"(full: torch={torch_losses} ours={jax_losses})")


def test_lenet_torch_init_distribution_matches():
    """Our initializers draw from torch's default distributions (bounds)."""
    from simple_distributed_machine_learning_tpu.ops.layers import (
        conv2d_init,
        linear_init,
    )
    key = jax.random.key(0)
    c = conv2d_init(key, 1, 10, 5)
    ref = _torch_lenet()
    bound = 1.0 / np.sqrt(1 * 5 * 5)
    assert float(np.abs(np.asarray(c["w"])).max()) <= bound
    assert float(ref["conv1"].weight.abs().max()) <= bound
    l = linear_init(key, FEATURES, 50)
    bound = 1.0 / np.sqrt(FEATURES)
    assert float(np.abs(np.asarray(l["w"])).max()) <= bound
    assert float(ref["fc1"].weight.abs().max()) <= bound
