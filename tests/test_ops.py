"""Numerical parity of the functional ops against torch (CPU).

The reference's layer math is torch's (``/root/reference/simple_distributed.py:42-46,
:75-79``); these tests pin our NHWC/JAX implementations to the same numerics
so loss-curve parity is meaningful.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as TF

from simple_distributed_machine_learning_tpu import ops

RTOL = 1e-5
ATOL = 1e-5


def test_linear_matches_torch():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 7)).astype(np.float32)
    w = rng.normal(size=(7, 3)).astype(np.float32)
    b = rng.normal(size=(3,)).astype(np.float32)
    got = ops.linear({"w": jnp.asarray(w), "b": jnp.asarray(b)}, jnp.asarray(x))
    want = TF.linear(torch.from_numpy(x), torch.from_numpy(w.T),
                     torch.from_numpy(b)).numpy()
    np.testing.assert_allclose(np.asarray(got), want, rtol=RTOL, atol=ATOL)


def test_conv2d_matches_torch():
    rng = np.random.default_rng(1)
    x_nchw = rng.normal(size=(2, 3, 10, 10)).astype(np.float32)
    w_oihw = rng.normal(size=(5, 3, 4, 4)).astype(np.float32)
    b = rng.normal(size=(5,)).astype(np.float32)
    # ours: NHWC activations, HWIO weights
    x_nhwc = jnp.asarray(x_nchw.transpose(0, 2, 3, 1))
    w_hwio = jnp.asarray(w_oihw.transpose(2, 3, 1, 0))
    got = ops.conv2d({"w": w_hwio, "b": jnp.asarray(b)}, x_nhwc)
    want = TF.conv2d(torch.from_numpy(x_nchw), torch.from_numpy(w_oihw),
                     torch.from_numpy(b)).numpy()
    np.testing.assert_allclose(np.asarray(got).transpose(0, 3, 1, 2), want,
                               rtol=1e-4, atol=1e-4)


def test_max_pool2d_matches_torch():
    rng = np.random.default_rng(2)
    x_nchw = rng.normal(size=(2, 3, 8, 8)).astype(np.float32)
    got = ops.max_pool2d(jnp.asarray(x_nchw.transpose(0, 2, 3, 1)), 2)
    want = TF.max_pool2d(torch.from_numpy(x_nchw), 2).numpy()
    np.testing.assert_allclose(np.asarray(got).transpose(0, 3, 1, 2), want,
                               rtol=RTOL, atol=ATOL)


def test_log_softmax_nll_match_torch():
    rng = np.random.default_rng(3)
    logits = rng.normal(size=(6, 10)).astype(np.float32) * 3
    targets = rng.integers(0, 10, size=(6,))
    lp = ops.log_softmax(jnp.asarray(logits))
    np.testing.assert_allclose(
        np.asarray(lp), TF.log_softmax(torch.from_numpy(logits), dim=1).numpy(),
        rtol=RTOL, atol=ATOL)
    for reduction in ("mean", "sum"):
        got = ops.nll_loss(lp, jnp.asarray(targets), reduction)
        want = TF.nll_loss(TF.log_softmax(torch.from_numpy(logits), dim=1),
                           torch.from_numpy(targets).long(),
                           reduction=reduction).numpy()
        np.testing.assert_allclose(np.asarray(got), want, rtol=RTOL, atol=ATOL)


def test_linear_init_matches_torch_bounds():
    params = ops.linear_init(jax.random.key(0), 320, 50)
    bound = 1.0 / np.sqrt(320)
    w = np.asarray(params["w"])
    assert w.shape == (320, 50)
    assert w.min() >= -bound and w.max() <= bound
    # torch draws from the same bound
    tl = torch.nn.Linear(320, 50)
    assert abs(tl.weight.detach().numpy().max()) <= bound + 1e-6


def test_dropout_semantics():
    key = jax.random.key(0)
    x = jnp.ones((100, 100))
    y = ops.dropout(key, x, rate=0.5)
    kept = np.asarray(y != 0)
    assert 0.4 < kept.mean() < 0.6
    np.testing.assert_allclose(np.asarray(y)[kept], 2.0)  # inverted scaling
    np.testing.assert_allclose(
        np.asarray(ops.dropout(key, x, 0.5, deterministic=True)), np.asarray(x))


def test_dropout2d_drops_whole_channels():
    key = jax.random.key(1)
    x = jnp.ones((8, 4, 4, 16))
    y = np.asarray(ops.dropout2d(key, x, rate=0.5))
    # each (sample, channel) plane is uniformly zero or uniformly scaled
    per_plane = y.transpose(0, 3, 1, 2).reshape(8 * 16, -1)
    assert np.all((per_plane == 0).all(-1) | (per_plane == 2.0).all(-1))
