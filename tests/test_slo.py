"""Streaming SLO engine (ISSUE 19): windowed quantiles, multi-window
burn-rate alerts, and the alert -> fleet feedback loop.

The acceptance pins: the alert state machine transitions exactly as the
SRE diagram says (one transition per evaluation, tick-stamped, never a
clock read); the overload-shed scenario fires and resolves
``slo_burn{class=interactive}`` at EXACT virtual-clock ticks; per-token
TPOT samples stay out of the burn series (the request-level SLI — a shed
storm must not be diluted by hundreds of good token observations); and a
replica whose burn alert fires demonstrably loses the router's affinity
preference while firing and regains it after resolve, with hysteresis.
"""

import json
import os

import jax
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    make_gpt_stages,
)
from simple_distributed_machine_learning_tpu.resilience import faults
from simple_distributed_machine_learning_tpu.resilience.scenarios import (
    VirtualClock,
    run_scenario,
)
from simple_distributed_machine_learning_tpu.serve import (
    ServeMetrics,
    engine_factory,
)
from simple_distributed_machine_learning_tpu.serve.fleet import (
    AutoscalePolicy,
    ServeFleet,
)
from simple_distributed_machine_learning_tpu.telemetry.alerts import (
    Alert,
    AlertBook,
)
from simple_distributed_machine_learning_tpu.telemetry.slo import (
    SLOEngine,
    SLOObjective,
    WindowHistogram,
)

CFG = GPTConfig(vocab=32, seq_len=48, d_model=32, n_heads=2, n_layers=2)
_STAGES = None


def _model():
    global _STAGES
    if _STAGES is None:
        _STAGES = make_gpt_stages(jax.random.key(0), CFG, 2)[0]
    return _STAGES


def _prompt(n, seed):
    return np.asarray(
        jax.random.randint(jax.random.key(seed), (n,), 0, CFG.vocab),
        np.int32)


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.uninstall()
    yield
    faults.uninstall()


# ---------------------------------------------------------------------------
# the alert state machine (telemetry/alerts.py) — pure, no jax, no clock


def test_alert_full_cycle_one_transition_per_evaluation():
    a = Alert("k", pending_ticks=2, resolve_ticks=3)
    assert a.evaluate(1, True) == ("inactive", "pending")
    assert a.evaluate(2, True) == ("pending", "firing")
    assert a.fired_at == 2 and a.firing
    assert a.evaluate(3, True) is None            # firing stays firing
    # the un-flap hysteresis: resolve needs resolve_ticks CONSECUTIVE
    # clear evaluations — a mid-streak breach resets it
    assert a.evaluate(4, False) is None
    assert a.evaluate(5, True) is None
    assert a.evaluate(6, False) is None
    assert a.evaluate(7, False) is None
    assert a.evaluate(8, False) == ("firing", "resolved")
    assert a.resolved_at == 8 and not a.firing
    # resolved is a ONE-evaluation state: the explicit "just cleared" row
    assert a.evaluate(9, False) == ("resolved", "inactive")


def test_alert_blip_never_pages_and_resolved_can_retrip():
    a = Alert("k", pending_ticks=2, resolve_ticks=2)
    # a single-tick blip: pending decays straight back, never firing
    assert a.evaluate(1, True) == ("inactive", "pending")
    assert a.evaluate(2, False) == ("pending", "inactive")
    # drive to resolved, then re-trip: resolved -> pending (not firing —
    # the page needs a fresh pending_ticks streak)
    for t, b in ((3, True), (4, True), (5, False), (6, False)):
        a.evaluate(t, b)
    assert a.state == "resolved"
    assert a.evaluate(7, True) == ("resolved", "pending")


def test_alert_validation():
    with pytest.raises(ValueError):
        Alert("k", pending_ticks=0)
    with pytest.raises(ValueError):
        Alert("k", resolve_ticks=0)


def test_alert_book_journals_context_and_replays_active_at():
    book = AlertBook(pending_ticks=1, resolve_ticks=1)
    assert book.evaluate("a", 1, True, burn_fast=2.0) == {
        "tick": 1, "alert": "a", "from": "inactive", "to": "pending",
        "burn_fast": 2.0}
    book.evaluate("a", 2, True, burn_fast=3.0)
    book.evaluate("b", 2, True)
    assert book.firing() == ["a"]
    assert book.states() == {"a": "firing", "b": "pending"}
    # journal replay reconstructs the firing set as of any tick — the
    # flight-row/bundle tick-join contract
    assert book.active_at(1) == []
    assert book.active_at(2) == ["a"] == book.active_at(2.5)
    book.evaluate("a", 3, False)                  # firing -> resolved
    assert book.active_at(2) == ["a"]
    assert book.active_at(3) == [] == book.firing()


# ---------------------------------------------------------------------------
# windowed quantiles — static buckets, deterministic by construction


def test_window_histogram_quantiles_are_bucket_upper_bounds():
    h = WindowHistogram(bounds=(1.0, 2.0, 5.0, 10.0), window=2)
    for v in (0.5, 1.5, 7.0):
        h.observe(v)
    h.roll()
    assert h.n == 3
    assert h.quantile(0.5) == 2.0                 # nearest rank, never
    assert h.quantile(1.0) == 10.0                # an interpolation
    h.observe(100.0)                              # overflow clamps to the
    h.roll()                                      # last bound
    assert h.quantile(1.0) == 10.0
    # the window slides: two fresh empty ticks evict everything
    h.roll()
    h.roll()
    assert h.n == 0 and h.quantile(0.5) is None


def test_window_histogram_validation():
    with pytest.raises(ValueError):
        WindowHistogram(window=0)
    with pytest.raises(ValueError):
        WindowHistogram(bounds=(5.0, 1.0))
    with pytest.raises(ValueError):
        WindowHistogram(bounds=(1.0, 1.0))


# ---------------------------------------------------------------------------
# the engine: objectives, burn math, the request-level SLI


def test_objective_and_engine_validation():
    with pytest.raises(ValueError):
        SLOObjective("x", ttft_slo_ms=10.0, target=1.0)
    with pytest.raises(ValueError):
        SLOObjective("x")                         # tracks nothing
    obj = SLOObjective("x", ttft_slo_ms=10.0)
    assert obj.budget == pytest.approx(0.1)
    with pytest.raises(ValueError):
        SLOEngine([obj], fast_window=4, slow_window=2)
    with pytest.raises(ValueError):
        SLOEngine([obj, SLOObjective("x", tpot_slo_ms=5.0)])
    with pytest.raises(ValueError):
        SLOEngine([obj], min_count=0)


def test_from_classes_none_when_nothing_to_track():
    class TC:
        def __init__(self, name, ttft=None, tpot=None):
            self.name, self.ttft_slo_ms, self.tpot_slo_ms = name, ttft, tpot

    assert SLOEngine.from_classes([TC("a"), TC("b")]) is None
    eng = SLOEngine.from_classes([TC("a"), TC("b", ttft=50.0)])
    assert set(eng.objectives) == {"b"}


def test_tpot_samples_stay_out_of_the_burn_series():
    """The request-level SLI: per-token TPOT observations feed the
    quantile window only — a flood of them (every one violating its
    target!) must not move the burn rate, else a shed storm would be
    diluted into invisibility by the surviving requests' token streams."""
    eng = SLOEngine([SLOObjective("x", ttft_slo_ms=10.0, tpot_slo_ms=1.0)],
                    fast_window=2, slow_window=4)
    for _ in range(100):
        eng.observe_tpot("x", 99.0)               # all violate the target
    assert eng.evaluate(1) == []
    assert eng.burn_rates() == {"x": 0.0}
    assert eng.window_quantiles()["x_tpot_p95_ms"] == 100.0
    # one violating TTFT is one bad request: burn = (1/1) / 0.1
    eng.observe_ttft("x", 99.0)
    eng.evaluate(2)
    assert eng.burn_rates() == {"x": pytest.approx(10.0)}
    # a shed is a violated observation by definition
    eng.observe_shed("x")
    eng.observe_ttft("x", 1.0)
    eng.evaluate(3)
    assert eng.burn_rates() == {"x": pytest.approx((2 / 3) / 0.1)}
    # unknown classes are ignored, never KeyError
    eng.observe_ttft("ghost", 1.0)
    eng.observe_shed("ghost")


def test_multi_window_condition_needs_both_windows():
    """Fast window alone is flappy: one hot fast window over a clean slow
    window must NOT breach (the SRE multi-window point)."""
    eng = SLOEngine([SLOObjective("x", ttft_slo_ms=10.0)],
                    fast_window=1, slow_window=32, pending_ticks=1)
    for t in range(1, 20):                        # long clean history
        eng.observe_ttft("x", 1.0)
        eng.evaluate(t)
    eng.observe_ttft("x", 99.0)                   # one hot tick
    assert eng.evaluate(20) == []                 # fast=10, slow=.5: holds


# ---------------------------------------------------------------------------
# the scenario pins: exact fire/resolve ticks under the virtual clock


def test_overload_shed_burn_alert_trajectory_pinned():
    """THE alert determinism pin: the shed storm fires
    ``slo_burn{class=interactive}`` and drains it at exact ticks — every
    transition, both burn rates, byte-for-byte."""
    rep = run_scenario("overload-shed", _model(), CFG)
    alerts = rep["slo_alerts"]
    assert alerts["tick"] == 82
    assert alerts["windows"] == {"fast": 8, "slow": 32,
                                 "burn_threshold": 1.0}
    key = "slo_burn{class=interactive}"
    assert alerts["transitions"] == [
        {"tick": 37, "alert": key, "from": "inactive", "to": "pending",
         "burn_fast": 3.3333, "burn_slow": 1.4286},
        {"tick": 38, "alert": key, "from": "pending", "to": "firing",
         "burn_fast": 5.0, "burn_slow": 2.2222},
        {"tick": 49, "alert": key, "from": "firing", "to": "resolved",
         "burn_fast": 0.0, "burn_slow": 2.5},
        {"tick": 50, "alert": key, "from": "resolved", "to": "inactive",
         "burn_fast": 0.0, "burn_slow": 2.5},
    ]
    # fired AND resolved within the run: nothing left active at the end
    assert alerts["firing"] == []
    assert alerts["states"] == {key: "inactive"}
    # the pre-existing overload pins must survive the SLO engine riding
    # along (it observes, never steers the supervised run)
    assert rep["completed"] == 11 and rep["shed"] == 25
    assert rep["slo"]["interactive"]["ttft_ms_p95"] == 75.651


def test_crash_serve_burns_no_budget():
    """A crash the supervisor absorbs within SLO (attainment 1.0) must
    fire NOTHING — alerts are for burn, not for restarts."""
    rep = run_scenario("crash-serve", _model(), CFG)
    assert rep["slo_alerts"]["transitions"] == []
    assert rep["slo_alerts"]["states"] == {
        "slo_burn{class=interactive}": "inactive"}
    # windowed quantiles are pinned bucket bounds, not interpolations
    assert rep["slo_alerts"]["window_quantiles"] == {
        "interactive_tpot_p95_ms": 5.0, "interactive_ttft_p95_ms": 20.0}
    assert rep["restarts"] == 1


def test_slo_blocks_deterministic_across_runs():
    r1 = run_scenario("overload-shed", _model(), CFG)
    r2 = run_scenario("overload-shed", _model(), CFG)
    assert (json.dumps(r1["slo_alerts"], sort_keys=True)
            == json.dumps(r2["slo_alerts"], sort_keys=True))


def test_slo_alert_records_land_in_metrics_jsonl(tmp_path):
    """The CI chaos drill's grep target: one ``kind: "slo_alert"`` record
    per journaled transition, joinable on tick."""
    d = str(tmp_path / "run")
    run_scenario("overload-shed", _model(), CFG, outdir=d)
    with open(os.path.join(d, "metrics.jsonl")) as f:
        recs = [json.loads(ln) for ln in f if ln.strip()]
    alerts = [r for r in recs if r.get("kind") == "slo_alert"]
    assert [(r["tick"], r["to"]) for r in alerts] == [
        (37, "pending"), (38, "firing"), (49, "resolved"), (50, "inactive")]
    assert all(r["scenario"] == "overload-shed" for r in alerts)
    scen = next(r for r in recs if r.get("kind") == "scenario")
    assert scen["slo_alerts"]["transitions"] == 4


# ---------------------------------------------------------------------------
# the flight-recorder / bundle tick-join contract, extended to alerts


def test_flight_rows_join_alert_journal(tmp_path):
    """Every flight row's ``active_alerts`` snapshot must agree with the
    alert journal replayed to the same tick — the bundle/journal
    tick-join contract, extended to alerts (both are stamped with the
    supervisor's monotonic tick, evaluation strictly before the snap)."""
    from simple_distributed_machine_learning_tpu.serve import ServeSupervisor
    from simple_distributed_machine_learning_tpu.serve.flight import (
        FlightRecorder,
    )

    metrics = ServeMetrics()
    slo = SLOEngine([SLOObjective("interactive", ttft_slo_ms=1e-6)],
                    fast_window=2, slow_window=4, pending_ticks=2,
                    resolve_ticks=2)
    flight = FlightRecorder()
    sup = ServeSupervisor(
        engine_factory(_model(), CFG, n_slots=2, block_size=4,
                       prefill_chunk=3, metrics=metrics),
        os.path.join(str(tmp_path), "journal.jsonl"), metrics=metrics,
        flight=flight, slo=slo)
    for i in range(4):                 # every TTFT violates the 1ns target
        sup.submit(_prompt(5, i), max_new_tokens=3, cls="interactive")
    sup.drain()
    for _ in range(10):                # idle ticks: the alert drains too
        sup.step()
    sup.close()
    tos = [t["to"] for t in slo.alerts.journal]
    assert "firing" in tos and "resolved" in tos
    rows = flight.rows()
    assert any(r["active_alerts"] for r in rows)
    for r in rows:
        assert r["active_alerts"] == slo.alerts.active_at(r["tick"]), \
            r["tick"]


def test_postmortem_bundle_carries_active_alert_set(tmp_path):
    """The shed-burst bundle overload-shed dumps records the firing set
    at its trigger tick AND per flight row — all joinable against the
    journaled transitions."""
    import glob

    d = str(tmp_path / "run")
    run_scenario("overload-shed", _model(), CFG, outdir=d)
    with open(os.path.join(d, "metrics.jsonl")) as f:
        journal = [json.loads(ln) for ln in f if ln.strip()
                   and json.loads(ln).get("kind") == "slo_alert"]

    def active_at(tick):
        state = {}
        for row in journal:
            if row["tick"] > tick:
                break
            state[row["alert"]] = row["to"]
        return sorted(k for k, s in state.items() if s == "firing")

    paths = glob.glob(os.path.join(d, "postmortem-*.json"))
    assert paths
    for p in paths:
        with open(p) as f:
            b = json.load(f)
        assert b["active_alerts"] == active_at(b["tick"])
        for row in b["flight"]:
            assert row["active_alerts"] == active_at(row["tick"])


# ---------------------------------------------------------------------------
# the closed loop: firing replica loses affinity, hysteresis re-entry


def _fleet(tmp_path, slo, **fleet_kw):
    clock = VirtualClock(per_call_s=0.001)
    metrics = ServeMetrics()
    fleet = ServeFleet(
        engine_factory(_model(), CFG, n_slots=2, block_size=4,
                       prefill_chunk=3, clock=clock, metrics=metrics),
        os.path.join(str(tmp_path), "fleet"), n_replicas=2,
        journal_sync=False, clock=clock, metrics=metrics, slo=slo,
        **fleet_kw)
    return fleet, metrics


def test_firing_replica_loses_affinity_then_reenters(tmp_path):
    slo = SLOEngine([SLOObjective("synthetic", ttft_slo_ms=10.0)],
                    fast_window=2, slow_window=4, pending_ticks=2,
                    resolve_ticks=2)
    fleet, metrics = _fleet(tmp_path, slo, alert_recover_ticks=2)
    try:
        # warm the hot prefix onto one replica (8 tokens = 2 full blocks)
        hot = _prompt(8, 7)
        h = fleet.submit(hot.copy(), max_new_tokens=4, seed=1)
        home = fleet._home[h.rid]
        fleet.drain()
        rep2, hit = fleet.router.route(hot, fleet._alive())
        assert rep2.idx == home and hit            # affinity established
        # burn the home replica's budget: one violating request-level
        # observation per fleet tick, attributed to ITS index
        for _ in range(2):
            slo.observe_ttft("synthetic", 999.0, replica=home)
            fleet.step()
        assert slo.firing_replicas() == {home}
        assert fleet._alert_demoted == {home}
        assert [e["replica"] for e in fleet.replica_log
                if e["event"] == "alert-demote"] == [home]
        # the demoted replica keeps its longer prefix but the router must
        # not PREFER it: the hot prompt lands on the other replica and the
        # suppression is counted
        h2 = fleet.submit(hot.copy(), max_new_tokens=4, seed=2)
        assert fleet._home[h2.rid] != home
        assert fleet.router.last_suppressed
        assert metrics.route_alert_demotions.value == 1
        fleet.drain()
        # recovery: clean ticks resolve the alert (resolve_ticks), then
        # the fleet's OWN hysteresis (alert_recover_ticks) re-enters it —
        # two separate debounces, both must elapse
        for _ in range(8):
            fleet.step()
        assert slo.firing_replicas() == set()
        assert fleet._alert_demoted == set()
        assert [e["replica"] for e in fleet.replica_log
                if e["event"] == "alert-re-enter"] == [home]
        h3 = fleet.submit(hot.copy(), max_new_tokens=4, seed=3)
        assert fleet._home[h3.rid] == home         # preference restored
        assert metrics.route_alert_demotions.value == 1
        assert metrics.summary()["route_alert_demotions"] == 1
    finally:
        fleet.close()


def test_fleet_validation_and_demotion_never_empties_candidates(tmp_path):
    with pytest.raises(ValueError):
        AutoscalePolicy(scale_out_burn_rate=0.0)
    slo = SLOEngine([SLOObjective("synthetic", ttft_slo_ms=10.0)],
                    fast_window=2, slow_window=4, pending_ticks=1)
    with pytest.raises(ValueError):
        _fleet(tmp_path, slo, alert_recover_ticks=0)
    # every replica firing: demotion deprioritizes but the fleet still
    # routes (a demoted replica serves — it just stops attracting)
    fleet, metrics = _fleet(tmp_path, slo)
    try:
        for _ in range(2):
            for idx in range(2):
                slo.observe_ttft("synthetic", 999.0, replica=idx)
            fleet.step()
        assert slo.firing_replicas() == {0, 1}
        assert fleet._alert_demoted == {0, 1}
        h = fleet.submit(_prompt(5, 3), max_new_tokens=3, seed=4)
        fleet.drain()
        assert h.state == "done"
    finally:
        fleet.close()


def test_burn_rate_feeds_autoscaler_scale_out(tmp_path):
    """The optional scale-out trigger: sustained burn counts toward the
    same backlog streak as queue depth — capacity arrives on latency
    pressure before the queue-depth watermark trips."""
    clock = VirtualClock(per_call_s=0.001)
    metrics = ServeMetrics()
    slo = SLOEngine([SLOObjective("synthetic", ttft_slo_ms=10.0)],
                    fast_window=2, slow_window=4)
    fleet = ServeFleet(
        engine_factory(_model(), CFG, n_slots=2, block_size=4,
                       prefill_chunk=3, clock=clock, metrics=metrics),
        os.path.join(str(tmp_path), "fleet"), n_replicas=1,
        journal_sync=False, clock=clock, metrics=metrics, slo=slo,
        autoscale=AutoscalePolicy(min_replicas=1, max_replicas=2,
                                  scale_out_queue_depth=999,
                                  scale_out_ticks=2, retire_idle_s=60.0,
                                  scale_out_burn_rate=1.0))
    try:
        assert fleet.n_alive == 1
        for _ in range(2):
            slo.observe_ttft("synthetic", 999.0)
            fleet.step()
        assert fleet.n_alive == 2
        assert any(e["event"] == "scale-out" for e in fleet.replica_log)
    finally:
        fleet.close()
