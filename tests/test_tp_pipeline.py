"""3D parallelism: tensor-parallel stages inside the GPipe pipeline.

A (data x stage x model) mesh runs the full train step with every axis active;
values and whole SGD trajectories must match the dense single-device model
(tensor-parallel init splits the same dense init, so parity is exact up to
float tolerance — any gradient convention error would compound step by step).
"""

import jax
import jax.numpy as jnp
import numpy as np

from simple_distributed_machine_learning_tpu.ops.losses import nll_loss
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.parallel.tensor import (
    make_mlp_tp_stages,
)
from simple_distributed_machine_learning_tpu.train.optimizer import sgd
from simple_distributed_machine_learning_tpu.train.step import make_train_step

DIMS = [8, 16, 12, 16, 10]          # 2 stages x (column -> row) pair


def _dense_from_shards(stages):
    """Reconstruct each stage's dense (w1, b1, w2, b2) from its TP shards."""
    dense = []
    for st in stages:
        sh = st.shards
        w1 = jnp.concatenate([s["w1"]["w"] for s in sh], axis=1)
        b1 = jnp.concatenate([s["w1"]["b"] for s in sh], axis=0)
        w2 = jnp.concatenate([s["w2"]["w"] for s in sh], axis=0)
        b2 = sh[0]["w2"]["b"]        # replicated
        dense.append((w1, b1, w2, b2))
    return dense


def _dense_apply(dense, x):
    h = x
    for i, (w1, b1, w2, b2) in enumerate(dense):
        h = jax.nn.relu(h @ w1 + b1) @ w2 + b2
        if i < len(dense) - 1:
            h = jax.nn.relu(h)
    return jax.nn.log_softmax(h, axis=-1)


def _problem(n_model, n_data=1, batch=8):
    key = jax.random.key(0)
    stages, wire_dim, out_dim = make_mlp_tp_stages(key, DIMS, 2, n_model)
    mesh = make_mesh(n_stages=2, n_data=n_data, n_model=n_model)
    pipe = Pipeline(stages, mesh, wire_dim, out_dim, n_microbatches=2)
    x = jax.random.normal(jax.random.key(1), (batch, DIMS[0]))
    y = jax.random.randint(jax.random.key(2), (batch,), 0, DIMS[-1])
    return stages, pipe, x, y


def test_tp_pipeline_matches_dense():
    stages, pipe, x, y = _problem(n_model=2)
    buf = pipe.init_params()
    loss, logp = pipe.loss_and_logits(buf, x, y, jax.random.key(0),
                                      deterministic=True)
    want_logp = _dense_apply(_dense_from_shards(stages), x)
    np.testing.assert_allclose(np.asarray(logp), np.asarray(want_logp),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(float(loss),
                               float(nll_loss(want_logp, y, "mean")),
                               rtol=2e-5, atol=2e-5)


def test_tp_pipeline_sgd_trajectory_matches_dense():
    """10 SGD(momentum) steps on the 3D-parallel pipeline track a dense
    single-device implementation of the same model step for step."""
    stages, pipe, x, y = _problem(n_model=2, n_data=2, batch=8)
    buf = pipe.init_params()
    opt = sgd(0.2, momentum=0.5)
    opt_state = opt.init(buf)
    step = make_train_step(pipe, opt)

    dense = _dense_from_shards(stages)
    flat, treedef = jax.tree.flatten(dense)
    vel = [jnp.zeros_like(l) for l in flat]

    def dense_loss(flat_params):
        d = jax.tree.unflatten(treedef, flat_params)
        return nll_loss(_dense_apply(d, x), y, "mean")

    losses_pipe, losses_dense = [], []
    for i in range(10):
        buf, opt_state, l = step(buf, opt_state, x, y, jax.random.key(i))
        losses_pipe.append(float(l))
        ld, g = jax.value_and_grad(dense_loss)(flat)
        vel = [0.5 * v + gg for v, gg in zip(vel, g)]       # torch-style
        flat = [p - 0.2 * v for p, v in zip(flat, vel)]
        losses_dense.append(float(ld))

    np.testing.assert_allclose(losses_pipe, losses_dense, rtol=1e-4,
                               atol=1e-5)
    assert losses_pipe[-1] < losses_pipe[0]


def test_full_3d_mesh_all_axes_active():
    """(data=2, stage=2, model=2) = 8 devices: one train step runs and the
    replicated-over-data, sharded-over-(stage,model) buffer stays finite."""
    _, pipe, x, y = _problem(n_model=2, n_data=2, batch=8)
    assert dict(pipe.mesh.shape) == {"data": 2, "stage": 2, "model": 2, "seq": 1, "expert": 1}
    buf = pipe.init_params()
    opt = sgd(0.1, momentum=0.5)
    step = make_train_step(pipe, opt)
    buf, _, loss = step(buf, opt.init(buf), x, y, jax.random.key(0))
    assert np.isfinite(float(loss))
    assert np.isfinite(np.asarray(jax.device_get(buf))).all()


def test_replicated_stages_on_model_mesh_match():
    """Stages WITHOUT model shards on an n_model=2 mesh (redundant compute on
    every model slot) must produce the exact same SGD trajectory as the same
    model on an n_model=1 mesh — the engine's grad_sync keeps replica grads
    at full magnitude and in sync."""
    from simple_distributed_machine_learning_tpu.models.mlp import (
        make_mlp_stages,
    )

    key = jax.random.key(0)
    x = jax.random.normal(jax.random.key(1), (8, 16))
    y = jax.random.randint(jax.random.key(2), (8,), 0, 10)

    def run(n_model):
        stages, wd, od = make_mlp_stages(key, [16, 32, 10], 2)
        mesh = make_mesh(n_stages=2, n_data=1, n_model=n_model)
        pipe = Pipeline(stages, mesh, wd, od, n_microbatches=2)
        buf = pipe.init_params()
        opt = sgd(0.2, momentum=0.5)
        state = opt.init(buf)
        step = make_train_step(pipe, opt)
        losses = []
        for i in range(6):
            buf, state, l = step(buf, state, x, y, jax.random.key(i))
            losses.append(float(l))
        return losses

    np.testing.assert_allclose(run(1), run(2), rtol=1e-5, atol=1e-6)
