"""Expert parallelism composed with the pipeline (VERDICT r1 item 4).

MoE-GPT with its expert weights genuinely sharded over the mesh's "expert"
axis — per-device expert storage rows in the packed buffer, sequence-split
routing, 2x all-to-all dispatch inside the engine's shard_map — must match
the dense (n_expert_parallel=1) pipeline exactly: same routing groups (one
sequence each), same capacity, so values, aux loss, and SGD trajectories are
identical.
"""

import dataclasses

import jax
import numpy as np

from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    make_gpt_stages,
)
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.train.optimizer import sgd
from simple_distributed_machine_learning_tpu.train.step import make_train_step

CFG = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=2,
                n_experts=4, moe_top_k=2)


def _data(key, batch):
    kx, ky = jax.random.split(key)
    x = jax.random.randint(kx, (batch, CFG.seq_len), 0, CFG.vocab)
    y = jax.random.randint(ky, (batch, CFG.seq_len), 0, CFG.vocab)
    return x.astype(jax.numpy.float32), y


def _pipe(n_ep, n_micro=2):
    cfg = dataclasses.replace(CFG, n_expert_parallel=n_ep)
    stages, wd, od = make_gpt_stages(jax.random.key(0), cfg, 2)
    mesh = make_mesh(n_stages=2, n_data=1, n_expert=n_ep)
    return Pipeline(stages, mesh, wd, od, n_microbatches=n_micro)


def test_ep_buffer_is_expert_sharded():
    pipe = _pipe(2)
    assert pipe.n_expert == 2
    buf = pipe.init_params()
    # [n_stages, n_model, n_expert, P]: expert rows differ (sharded storage)
    assert buf.shape[:3] == (2, 1, 2)
    rows = np.asarray(jax.device_get(buf))
    assert not np.array_equal(rows[0, 0, 0], rows[0, 0, 1])
    assert "expert" in str(buf.sharding.spec)


def test_ep_pipeline_matches_dense_pipeline():
    x, y = _data(jax.random.key(1), 8)
    key = jax.random.key(2)
    dense = _pipe(1)
    ld, logits_d = dense.loss_and_logits(dense.init_params(), x, y, key,
                                         deterministic=True)
    ep = _pipe(2)
    le, logits_e = ep.loss_and_logits(ep.init_params(), x, y, key,
                                      deterministic=True)
    np.testing.assert_allclose(float(le), float(ld), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(logits_e), np.asarray(logits_d),
                               rtol=2e-4, atol=2e-4)


def test_ep_sgd_trajectory_matches_dense():
    """Gradients through the all-to-all dispatch, the expert-sharded storage
    rows, and the grad-synced replicated leaves reproduce dense training."""
    x, y = _data(jax.random.key(3), 8)
    opt = sgd(0.1, momentum=0.5)
    losses = {}
    for name, pipe in (("dense", _pipe(1)), ("ep", _pipe(2))):
        buf = pipe.init_params()
        state = opt.init(buf)
        step = make_train_step(pipe, opt)
        ls = []
        for i in range(3):
            buf, state, loss = step(buf, state, x, y,
                                    jax.random.fold_in(jax.random.key(4), i))
            ls.append(float(loss))
        losses[name] = ls
    np.testing.assert_allclose(losses["ep"], losses["dense"],
                               rtol=5e-5, atol=5e-5)


def test_weighted_loss_applies_to_nll_only():
    """Documented contract (pipeline.loss_and_logits): per-sample ``weights``
    scale the NLL term only; MoE aux load-balancing terms stay unweighted,
    matching the dense path which computes aux over the full batch."""
    import jax.numpy as jnp

    from simple_distributed_machine_learning_tpu.ops.losses import nll_loss

    x, y = _data(jax.random.key(8), 8)
    w = jnp.asarray([1.0, 1.0, 1.0, 0.5, 2.0, 0.0, 1.5, 1.0])
    pipe = _pipe(1, n_micro=1)
    buf = pipe.init_params()
    loss, _ = pipe.loss_and_logits(buf, x, y, jax.random.key(9),
                                   deterministic=True, weights=w)

    # dense ground truth: weighted-mean NLL + UNWEIGHTED sum of stage aux
    h, aux = x, jnp.float32(0.0)
    for s, stage in enumerate(pipe.stages):
        h = h.reshape((h.shape[0],) + tuple(stage.in_shape))
        out = stage.apply(stage.params, h,
                          jax.random.fold_in(jax.random.key(9), s), True)
        if isinstance(out, tuple):
            out, a = out
            aux = aux + a
        h = out
    nll = nll_loss(h, y, "none")
    wb = jnp.broadcast_to(w[:, None], nll.shape)
    want = jnp.sum(nll * wb) / jnp.sum(wb) + aux
    np.testing.assert_allclose(float(loss), float(want), rtol=2e-5, atol=2e-5)

    # scaling every weight leaves the loss identical: the weighted mean is
    # scale-invariant and aux never sees the weights
    loss2, _ = pipe.loss_and_logits(buf, x, y, jax.random.key(9),
                                    deterministic=True, weights=w * 7.0)
    np.testing.assert_allclose(float(loss2), float(loss), rtol=1e-6, atol=1e-6)


def test_ep_composes_with_data_parallel():
    """dp=2 x pp=2 x ep=2 = 8 devices, one train step, finite loss."""
    cfg = dataclasses.replace(CFG, n_expert_parallel=2)
    stages, wd, od = make_gpt_stages(jax.random.key(5), cfg, 2)
    mesh = make_mesh(n_stages=2, n_data=2, n_expert=2)
    pipe = Pipeline(stages, mesh, wd, od, n_microbatches=2)
    x, y = _data(jax.random.key(6), 8)
    opt = sgd(0.1, momentum=0.5)
    buf = pipe.init_params()
    state = opt.init(buf)
    step = make_train_step(pipe, opt)
    buf, state, loss = step(buf, state, x, y, jax.random.key(7))
    assert np.isfinite(float(loss))
