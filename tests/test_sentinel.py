"""Self-healing training (resilience/sentinel.py): detection, rollback,
quarantine, escalation, preemption.

THE acceptance pin lives here: with ``nan-grad@train.grad=K`` injected, the
sentinel run detects the NaN at step K, rolls back to the newest in-memory
snapshot, quarantines the offending batch and replays — and its per-step
losses equal a clean run that pre-loaded the same quarantine journal and
never saw the fault, EXACTLY, on the single-stage and the 2-stage pipeline
layouts. Plus: corrupt-batch determinism across runs, the EWMA spike
threshold (with its no-false-positive guarantee on a normal warmup run),
the snapshot ring's memory bound, escalation to the elastic supervisor on
ring exhaustion, graceful preemption (injected + real SIGTERM), the new
fault grammar, and the CLI surface.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.data.mnist import Dataset
from simple_distributed_machine_learning_tpu.models.mlp import make_mlp_stages
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.resilience import (
    CheckpointStore,
    RestartPolicy,
    faults,
    make_elastic_trainer,
    supervise,
)
from simple_distributed_machine_learning_tpu.resilience.sentinel import (
    QuarantineJournal,
    Sentinel,
    SentinelConfig,
    SentinelExhausted,
    Snapshot,
    SnapshotRing,
)
from simple_distributed_machine_learning_tpu.train.trainer import (
    TrainConfig,
    Trainer,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.uninstall()
    yield
    faults.uninstall()


def _tiny_ds():
    rng = np.random.RandomState(0)
    return Dataset(rng.randn(120, 12).astype(np.float32),
                   rng.randint(0, 10, 120))


_DIMS = [12, 16, 14, 16, 10]


def _build_pipe(n):
    stages, wd, od = make_mlp_stages(jax.random.key(0), _DIMS, n)
    return Pipeline(stages, make_mesh(n_stages=n, n_data=1,
                                      devices=jax.devices()[:n]), wd, od)


def _cfg(checkpoint_dir=None, **kw):
    base = dict(epochs=3, batch_size=30, print_throughput=False,
                sentinel=True, sentinel_snapshot_every=2,
                checkpoint_dir=checkpoint_dir)
    base.update(kw)
    return TrainConfig(**base)


# ---------------------------------------------------------------------------
# fault grammar: new kinds/sites


def test_new_fault_kinds_parse_and_pair_strictly():
    p = faults.FaultPlan.parse(
        "nan-grad@train.grad=12;corrupt-batch@data.batch=3;"
        "loss-spike@train.step=7;preempt@train.sigterm=20")
    assert [(s.kind, s.site, s.step) for s in p.specs] == [
        ("nan-grad", "train.grad", 12), ("corrupt-batch", "data.batch", 3),
        ("loss-spike", "train.step", 7), ("preempt", "train.sigterm", 20)]
    # a typo'd site must still fail loudly (the vacuous-drill guard)
    with pytest.raises(ValueError, match="unknown fault site"):
        faults.FaultPlan.parse("nan-grad@train.grads=12")
    # crossed kind<->site pairs are refused at parse time
    with pytest.raises(ValueError, match="only pairs with"):
        faults.FaultPlan.parse("nan-grad@train.step=12")
    with pytest.raises(ValueError, match="only pairs with"):
        faults.FaultPlan.parse("loss-spike@data.batch=3")
    with pytest.raises(ValueError, match="only interprets"):
        faults.FaultPlan.parse("host-kill@train.grad=3")
    with pytest.raises(ValueError, match="only interprets"):
        faults.FaultPlan.parse("slow-tick@train.sigterm=3")


def test_fault_random_covers_new_kinds_with_valid_sites():
    kinds = ("nan-grad", "corrupt-batch", "loss-spike", "preempt")
    a = faults.FaultPlan.random(11, n=8, kinds=kinds,
                                sites=("train.step",), max_step=50)
    b = faults.FaultPlan.random(11, n=8, kinds=kinds,
                                sites=("train.step",), max_step=50)
    # every drawn spec is VALID (site-pinned kinds landed on their
    # interpreting sites) and the schedule is seed-deterministic
    assert ([(s.kind, s.site, s.step) for s in a.specs]
            == [(s.kind, s.site, s.step) for s in b.specs])
    assert {s.kind for s in a.specs} <= set(kinds)
    for s in a.specs:
        assert s.site == faults._KIND_SITE[s.kind]


def test_numeric_fault_without_sentinel_fails_loudly():
    """Against an undefended trainer the numeric kinds must raise, not be
    silently counted — a drill can never pass vacuously."""
    faults.install(faults.FaultPlan.parse("nan-grad@train.grad=0"))
    ds = _tiny_ds()
    tr = Trainer(_build_pipe(1), ds, ds,
                 _cfg(sentinel=False, epochs=1))
    with pytest.raises(faults.NumericFault):
        tr.fit()


def test_check_only_exclude_filters_without_consuming():
    plan = faults.install(faults.FaultPlan.parse("loss-spike@train.step=3"))
    # excluded probes do not consume the occurrence...
    assert faults.maybe_fire("train.step", step=3,
                             exclude=("loss-spike",)) == []
    # ...so the interpreting probe still matches it exactly once
    fired = faults.check("train.step", step=3, only=("loss-spike",))
    assert [s.kind for s in fired] == ["loss-spike"]
    assert plan.stats()["total_fired"] == 1


# ---------------------------------------------------------------------------
# snapshot ring + quarantine journal units


def _snap(step, nbytes=100):
    return Snapshot(step=step, epoch=1, batch_idx=step, params=None,
                    opt_leaves=(), ewma=None, healthy=0, nbytes=nbytes)


def test_snapshot_ring_bound_and_lookup():
    ring = SnapshotRing(3)
    for s in (0, 2, 4, 6):
        ring.push(_snap(s))
    assert len(ring) == 3                       # oldest aged out
    assert ring.bytes() == 300
    assert ring.newest_at_or_before(5).step == 4
    assert ring.newest_at_or_before(6).step == 6   # pre-step snapshots:
    assert ring.newest_at_or_before(1) is None     # the anomaly step's own
    ring.push(_snap(6, nbytes=50))              # re-snapshot same step
    assert len(ring) == 3 and ring.bytes() == 250


def test_quarantine_journal_roundtrip_and_torn_tail(tmp_path):
    path = str(tmp_path / "quarantine.jsonl")
    j = QuarantineJournal(path)
    j.add({"epoch": 2, "batch": 3, "step": 11, "kind": "nan", "value": None})
    j.add({"epoch": 1, "batch": 0, "step": 0, "kind": "spike", "value": 9.0})
    with open(path, "a") as f:
        f.write('{"epoch": 5, "ba')        # torn tail from a crash
    j2 = QuarantineJournal(path)
    assert len(j2) == 2
    assert j2.skip(2, 3) and j2.skip(1, 0) and not j2.skip(2, 4)


def test_sentinel_config_validation():
    with pytest.raises(ValueError, match="window"):
        SentinelConfig(window=1)
    with pytest.raises(ValueError, match="snapshot_every"):
        SentinelConfig(snapshot_every=0)
    with pytest.raises(ValueError, match="ring_size"):
        SentinelConfig(ring_size=0)
    with pytest.raises(ValueError, match="spike_factor"):
        SentinelConfig(spike_factor=1.0)


def test_observe_ewma_excludes_anomalies():
    s = Sentinel(SentinelConfig(warmup_steps=2, spike_factor=2.0,
                                spike_margin=0.0))
    for i, loss in enumerate((1.0, 1.0, 1.0)):
        assert s.observe(i, 1, i, loss) is None
    a = s.observe(3, 1, 3, 5.0)                 # 5 > 2 * ewma(1.0)
    assert a is not None and a.kind == "spike"
    # the spike did NOT enter the EWMA: the same value trips again
    assert s.observe(4, 1, 4, 5.0).kind == "spike"
    assert s.observe(5, 1, 5, float("nan")).kind == "nan"
    assert s.observe(6, 1, 6, 1.0, gnorm=float("inf")).kind == "inf"
    assert s.n_anomalies == 4
    assert sorted(s.observed) == [0, 1, 2]      # healthy steps only


# ---------------------------------------------------------------------------
# THE acceptance pin: nan-grad rollback bit-exact vs a clean run


@pytest.mark.parametrize("n_stages", [1, 2])
def test_nan_grad_rollback_bit_exact_vs_clean_run(tmp_path, n_stages):
    """Injected NaN gradients at step 6 -> detect, roll back to the
    (pre-step) snapshot, quarantine the batch, replay. The recovered run's
    per-step losses equal a clean run that pre-loaded the same quarantine
    journal and never saw the fault — EXACT float equality, both pipeline
    layouts."""
    ds = _tiny_ds()
    dirty, clean_dir = str(tmp_path / "dirty"), str(tmp_path / "clean")

    faults.install(faults.FaultPlan.parse("nan-grad@train.grad=6"))
    tr = Trainer(_build_pipe(n_stages), ds, ds, _cfg(dirty))
    tr.fit()
    faults.uninstall()
    assert tr.sentinel.n_anomalies == 1 and tr.sentinel.n_rollbacks == 1
    [q] = tr.sentinel.journal.records
    assert (q["step"], q["kind"]) == (6, "nan")

    # the clean reference: same config, SAME quarantine journal (loaded
    # from disk — the deterministic-skip contract), no fault installed
    os.makedirs(clean_dir)
    with open(os.path.join(dirty, "quarantine.jsonl")) as f:
        journal = f.read()
    with open(os.path.join(clean_dir, "quarantine.jsonl"), "w") as f:
        f.write(journal)
    ref = Trainer(_build_pipe(n_stages), ds, ds, _cfg(clean_dir))
    ref.fit()
    assert ref.sentinel.n_anomalies == 0 and ref.sentinel.n_rollbacks == 0

    # bit-exact: every executed step's loss, including the replayed ones
    assert tr.sentinel.observed == ref.sentinel.observed
    assert len(tr.sentinel.observed) == 11     # 3 epochs x 4 - 1 skipped


def test_corrupt_batch_quarantine_deterministic_across_runs():
    """Two identical runs under the same corrupt-batch schedule produce
    byte-identical quarantine records and per-step losses (the seeded
    chaos contract extended to the sentinel's recovery)."""
    ds = _tiny_ds()
    results = []
    for _ in range(2):
        faults.install(faults.FaultPlan.parse("corrupt-batch@data.batch=5"))
        tr = Trainer(_build_pipe(1), ds, ds, _cfg())
        tr.fit()
        faults.uninstall()
        results.append((dict(tr.sentinel.observed),
                        list(tr.sentinel.journal.records)))
    assert results[0] == results[1]
    [q] = results[0][1]
    assert q["step"] == 5 and q["kind"] in ("nan", "inf")
    assert (q["epoch"], q["batch"]) == (2, 1)   # 4 steps/epoch


# ---------------------------------------------------------------------------
# loss-spike EWMA threshold


def test_loss_spike_no_false_positive_on_warmup_run():
    """A normal lr-warmup run (the regime with the most natural loss
    movement) must trip NOTHING: zero anomalies, zero rollbacks."""
    from simple_distributed_machine_learning_tpu.train import schedules
    from simple_distributed_machine_learning_tpu.train.optimizer import sgd
    ds = _tiny_ds()
    tr = Trainer(_build_pipe(1), ds, ds, _cfg(),
                 opt=sgd(schedules.warmup_cosine(0.1, 6, 12), 0.5))
    tr.fit()
    assert tr.sentinel.n_anomalies == 0
    assert tr.sentinel.n_rollbacks == 0
    assert len(tr.sentinel.observed) == 12      # every step healthy


def test_loss_spike_detected_and_rolled_back():
    ds = _tiny_ds()
    faults.install(faults.FaultPlan.parse("loss-spike@train.step=10"))
    tr = Trainer(_build_pipe(1), ds, ds, _cfg())
    tr.fit()
    faults.uninstall()
    assert tr.sentinel.by_kind == {"spike": 1}
    assert tr.sentinel.n_rollbacks == 1
    [q] = tr.sentinel.journal.records
    assert q["step"] == 10 and q["kind"] == "spike"
    assert q["value"] is not None              # finite excursion, recorded


# ---------------------------------------------------------------------------
# snapshot-ring memory bound (the gauge's contract)


def test_snapshot_ring_memory_bound_and_gauge():
    from simple_distributed_machine_learning_tpu.telemetry.registry import (
        MetricsRegistry,
    )
    ds = _tiny_ds()
    reg = MetricsRegistry()
    cfg = _cfg(sentinel_snapshot_every=1, sentinel_ring=3)
    tr = Trainer(_build_pipe(1), ds, ds, cfg)
    tr._sentinel.registry = reg                # gauge without a Telemetry
    tr.fit()
    sent = tr.sentinel
    per_snapshot = (tr.buf.nbytes
                    + sum(leaf.nbytes for leaf in
                          jax.tree.leaves(tr.opt_state)))
    assert len(sent.ring) == 3                 # bounded, snapshot-per-step
    assert 0 < sent.ring.bytes() <= 3 * per_snapshot
    assert (reg.gauge("train_snapshot_ring_bytes").value
            == sent.ring.bytes())


# ---------------------------------------------------------------------------
# ring exhaustion -> elastic supervisor escalation


def test_ring_exhaustion_raises_sentinel_exhausted():
    ds = _tiny_ds()
    # unlimited nan faults: every step anomalous, the rollback streak
    # exceeds the budget and the sentinel escalates instead of looping
    faults.install(faults.FaultPlan.parse("nan-grad@train.grad,times=0"))
    tr = Trainer(_build_pipe(1), ds, ds, _cfg())
    with pytest.raises(SentinelExhausted, match="exceed"):
        tr.fit()
    assert tr.sentinel.n_rollbacks == tr.config.sentinel_ring


def test_escalation_recovers_through_elastic_supervisor(tmp_path):
    """A systematic fault (6 consecutive nan steps) exhausts the ring; the
    supervisor treats SentinelExhausted as RECOVERABLE, restores from the
    store and the next attempt (fault schedule spent, quarantine journal
    reloaded from the store dir) completes."""
    ds = _tiny_ds()
    store = CheckpointStore(str(tmp_path), keep=4)
    faults.install(faults.FaultPlan.parse("nan-grad@train.grad,times=6"))
    cfg = _cfg(checkpoint_dir=None)
    report = supervise(
        lambda n: make_elastic_trainer(_build_pipe, n, store, ds, ds, cfg),
        (1,), policy=RestartPolicy(max_restarts=2), sleep=lambda s: None)
    assert report["completed"] and report["restarts"] == 1
    a1, a2 = report["attempts"]
    assert a1["outcome"] == "fault" and a1["fault"] == "SentinelExhausted"
    # the supervisor's attempt report carries the sentinel's counters
    assert a1["sentinel"]["rollbacks"] >= 1
    assert a1["sentinel"]["anomalies"] > a1["sentinel"]["rollbacks"]
    assert a2["outcome"] == "completed"
    # the quarantine journal persisted in the store dir across attempts
    assert os.path.exists(os.path.join(str(tmp_path), "quarantine.jsonl"))


# ---------------------------------------------------------------------------
# graceful preemption: injected preempt fault + mid-epoch cursor resume


def test_preempt_fault_graceful_stop_and_bit_exact_resume(tmp_path):
    """preempt@train.sigterm=5: the in-flight step finishes, a SYNCHRONOUS
    checkpoint carrying the data cursor is written, fit returns cleanly —
    and the resumed run re-enters epoch 2 at batch 1, with the merged
    per-step losses equal to an uninterrupted run's, exactly."""
    ds = _tiny_ds()
    ref = Trainer(_build_pipe(1), ds, ds, _cfg())
    ref.fit()

    ck = str(tmp_path / "ck")
    mpath = str(tmp_path / "m.jsonl")
    faults.install(faults.FaultPlan.parse("preempt@train.sigterm=5"))
    p1 = Trainer(_build_pipe(1), ds, ds, _cfg(ck, metrics_json=mpath))
    p1.fit()
    faults.uninstall()
    # the interrupted epoch still emitted a metrics record (sentinel
    # counters re-assertable from artifacts even across a preemption)
    recs = [json.loads(line) for line in open(mpath)]
    assert recs[-1]["preempted"] is True and recs[-1]["step"] == 5
    assert recs[-1]["rollbacks"] == 0 and "anomaly_events" in recs[-1]
    assert p1.preempted and p1._step_count == 5
    meta = json.load(open(os.path.join(ck, "state.npz.meta.json")))
    assert meta["extra"]["epoch"] == 1 and meta["extra"]["next_batch"] == 1
    # the EWMA detector state rides the checkpoint, so the resumed run's
    # spike threshold matches the uninterrupted run's
    assert meta["extra"]["sentinel"]["healthy"] == 5
    assert meta["extra"]["sentinel"]["ewma"] is not None

    p2 = Trainer(_build_pipe(1), ds, ds, _cfg(ck))
    assert p2.start_epoch == 2 and p2._resume_batch_idx == 1
    assert p2.sentinel.detector_state() == meta["extra"]["sentinel"]
    p2.fit()
    assert not p2.preempted
    merged = dict(p1.sentinel.observed)
    merged.update(p2.sentinel.observed)
    assert merged == ref.sentinel.observed


def test_preempt_in_epoch_record_metrics(tmp_path):
    """The sentinel block rides the per-epoch metrics record (rollbacks
    re-assertable from metrics.jsonl — the CI drill's anti-vacuous gate)."""
    ds = _tiny_ds()
    path = str(tmp_path / "metrics.jsonl")
    faults.install(faults.FaultPlan.parse("nan-grad@train.grad=6"))
    tr = Trainer(_build_pipe(1), ds, ds, _cfg(metrics_json=path))
    tr.fit()
    faults.uninstall()
    records = [json.loads(line) for line in open(path)]
    assert records[-1]["rollbacks"] == 1
    assert records[-1]["anomalies"] == 1
    assert records[-1]["quarantined_batches"] == 1
    assert records[-1]["snapshot_ring_bytes"] > 0
    # the anomaly event landed on ITS epoch's record, with the timeline
    # fields the report CLI renders
    ev = [e for r in records for e in r.get("anomaly_events", [])]
    assert [e["step"] for e in ev] == [6]
    assert ev[0]["kind"] == "nan"
    assert tr.sentinel.drain_events() == []    # drained exactly once


# ---------------------------------------------------------------------------
# report CLI: the training-resilience block


def test_report_cli_renders_self_healing_block(tmp_path):
    from simple_distributed_machine_learning_tpu.telemetry import Telemetry
    from simple_distributed_machine_learning_tpu.telemetry import report
    ds = _tiny_ds()
    outdir = str(tmp_path / "tele")
    faults.install(faults.FaultPlan.parse("corrupt-batch@data.batch=5"))
    tr = Trainer(_build_pipe(1), ds, ds, _cfg(),
                 telemetry=Telemetry(outdir))
    tr.fit()
    faults.uninstall()
    collected = report.collect(outdir)
    assert collected["sentinel"]["rollbacks"] == 1
    assert collected["sentinel"]["quarantined_batches"] == 1
    assert [e["step"] for e in collected["sentinel"]["events"]] == [5]
    text = report.render(collected)
    assert "self-healing: 1 anomaly" in text
    assert "anomaly @step 5" in text
    # the counters also rode the Prometheus exposition, with HELP lines
    prom = open(os.path.join(outdir, "metrics.prom")).read()
    for name in ("train_anomalies_total", "train_rollbacks_total",
                 "train_quarantined_batches_total",
                 "train_snapshot_ring_bytes"):
        assert f"# HELP {name}" in prom, name


# ---------------------------------------------------------------------------
# CLI surface


def test_report_aggregates_counters_across_process_restarts(tmp_path):
    """Sentinel counters reset when the process restarts (preempt resume,
    supervisor restart) while the metrics.jsonl file persists — the report
    must SUM across generations, not read the newest record, or a resumed
    clean run would claim 0 anomalies above a non-empty timeline."""
    from simple_distributed_machine_learning_tpu.telemetry import report
    outdir = str(tmp_path)
    recs = [
        # generation 1: one absorbed anomaly, then a graceful preempt
        {"kind": "epoch", "epoch": 1, "anomalies": 1, "rollbacks": 1,
         "quarantined_batches": 1, "quarantine_persistent": True,
         "snapshot_ring_bytes": 100,
         "by_kind": {"nan": 1}, "sentinel_run": "aaaa0001",
         "anomaly_events": [{"step": 6, "kind": "nan", "epoch": 1,
                             "batch": 2, "value": None}]},
        # generation 2 (resumed process, counters RESET; the journal
        # reloaded from disk keeps quarantined cumulative). Its first
        # record already re-accumulated PAST generation 1's count — the
        # corner a pure counter-drop heuristic merges; the run id splits
        {"kind": "epoch", "epoch": 2, "anomalies": 2, "rollbacks": 2,
         "quarantined_batches": 3, "quarantine_persistent": True,
         "snapshot_ring_bytes": 120,
         "by_kind": {"spike": 2}, "sentinel_run": "bbbb0002",
         "anomaly_events": [{"step": 20, "kind": "spike", "epoch": 2,
                             "batch": 0, "value": 9.0}]},
        {"kind": "epoch", "epoch": 3, "anomalies": 2, "rollbacks": 2,
         "quarantined_batches": 3, "quarantine_persistent": True,
         "snapshot_ring_bytes": 120,
         "by_kind": {"spike": 2}, "sentinel_run": "bbbb0002",
         "anomaly_events": []},
    ]
    with open(os.path.join(outdir, "metrics.jsonl"), "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    sent = report.collect(outdir)["sentinel"]
    assert sent["anomalies"] == 3 and sent["rollbacks"] == 3
    assert sent["by_kind"] == {"nan": 1, "spike": 2}
    assert sent["quarantined_batches"] == 3
    assert len(sent["events"]) == 2
    text = report.render(report.collect(outdir))
    assert "self-healing: 3 anomalies" in text
    assert "[SELF-HEALED]" in text
    # id-less records (hand-built / foreign) fall back to drop detection
    with open(os.path.join(outdir, "metrics.jsonl"), "w") as f:
        for r in recs:
            r = dict(r)
            r.pop("sentinel_run")
            f.write(json.dumps(r) + "\n")
    sent = report.collect(outdir)["sentinel"]
    assert sent["anomalies"] == 2          # drop-rule merges 1 -> 2 -> 2


def test_cli_sentinel_flag_validation():
    from simple_distributed_machine_learning_tpu.cli import main
    with pytest.raises(SystemExit, match="--sentinel-window"):
        main(["--rank", "0", "--model", "mlp", "--sentinel",
              "--sentinel-window", "1"])
    with pytest.raises(SystemExit, match="--sentinel-snapshot-every"):
        main(["--rank", "0", "--model", "mlp", "--sentinel",
              "--sentinel-snapshot-every", "0"])
    # numeric kinds in a --chaos plan need the sentinel armed
    with pytest.raises(SystemExit, match="add --sentinel"):
        main(["--rank", "0", "--model", "mlp", "--chaos",
              "nan-grad@train.grad=5", "--checkpoint-dir", "/tmp/x"])


def test_cli_sentinel_chaos_drill_end_to_end(tmp_path, capsys):
    """The CI sentinel drill's in-process twin: nan-grad at step 5 under
    --sentinel --chaos -> absorbed in-memory (0 supervisor restarts), exit
    clean, quarantine journal written into the store dir."""
    from simple_distributed_machine_learning_tpu.cli import main
    main(["--rank", "0", "--world_size", "1", "--model", "mlp",
          "--mlp-dims", "784,16,10", "--stages", "1", "--epochs", "2",
          "--max-steps-per-epoch", "4", "--data-root", "/nonexistent",
          "--checkpoint-dir", str(tmp_path / "store"), "--sentinel",
          "--chaos", "nan-grad@train.grad=5"])
    out = capsys.readouterr().out
    assert "chaos: completed after 0 restart(s)" in out
    assert "sentinel absorbed 1 anomaly (1 rollback(s), 1 quarantined " \
           "batch(es))" in out
    q = [json.loads(line) for line in
         open(tmp_path / "store" / "quarantine.jsonl")]
    assert [r["step"] for r in q] == [5]


def test_cli_chaos_never_fired_plan_is_vacuous(tmp_path):
    """The min_anomalies-style gate: a chaos schedule that never fires
    fails the run instead of passing green."""
    from simple_distributed_machine_learning_tpu.cli import main
    with pytest.raises(SystemExit, match="never fired"):
        main(["--rank", "0", "--world_size", "1", "--model", "mlp",
              "--mlp-dims", "784,16,10", "--stages", "1", "--epochs", "1",
              "--max-steps-per-epoch", "2", "--data-root", "/nonexistent",
              "--checkpoint-dir", str(tmp_path / "store"),
              "--chaos", "host-kill@train.step=999"])


# ---------------------------------------------------------------------------
# SIGTERM subprocess drill (the real signal path)


@pytest.mark.slow
def test_sigterm_graceful_preemption_subprocess(tmp_path):
    """SIGTERM mid-training: the in-flight step finishes, a synchronous
    checkpoint with the mid-epoch cursor is written, the run exits 0 —
    and a rerun resumes from the cursor and completes."""
    ck = str(tmp_path / "ck")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    args = [sys.executable, "-m",
            "simple_distributed_machine_learning_tpu.cli", "--rank", "0",
            "--world_size", "1", "--model", "mlp",
            "--mlp-dims", "784,32,10", "--epochs", "2",
            "--data-root", "/nonexistent", "--sentinel",
            "--checkpoint-dir", ck]
    proc = subprocess.Popen(args, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env,
                            cwd=REPO)
    try:
        deadline = time.time() + 180
        while time.time() < deadline:
            line = proc.stdout.readline()
            if line.startswith("Train Epoch: 1 [6"):   # mid-epoch 1
                break
        else:
            raise AssertionError("training never got under way")
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=180)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, out
    assert "preempt: graceful stop on signal 15" in out
    assert "graceful shutdown complete" in out
    meta = json.load(open(os.path.join(ck, "state.npz.meta.json")))
    assert "next_batch" in meta["extra"]       # mid-epoch cursor persisted

    # the rerun resumes from the cursor and completes cleanly
    out2 = subprocess.run(args, capture_output=True, text=True, env=env,
                          cwd=REPO, timeout=600)
    assert out2.returncode == 0, out2.stdout + out2.stderr
    assert f"(batch {meta['extra']['next_batch']})" in out2.stdout
    meta2 = json.load(open(os.path.join(ck, "state.npz.meta.json")))
    assert meta2["extra"]["epoch"] == 2        # ran to completion
    assert "next_batch" not in meta2["extra"]


# ---------------------------------------------------------------------------
# bench rows


@pytest.mark.slow
def test_bench_sentinel_rows():
    sys.path.insert(0, REPO)
    import bench
    rows = bench._measure_sentinel(n_steps=24, fault_step=14)
    by = {r["config"]: r for r in rows}
    ov = by["train_sentinel_overhead"]
    assert ov["steps_per_sec_on"] > 0 and ov["steps_per_sec_off"] > 0
    rec = by["train_sentinel_recovery"]
    assert rec["recovered"] is True
    assert rec["faults_fired"] == 1 and rec["rollbacks"] == 1
