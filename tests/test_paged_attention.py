"""Fused Pallas paged-attention kernels + the int8-quantized KV pool.

Runs the real kernel code path in Pallas interpret mode on CPU (the same
kernel lowers through Mosaic on TPU), pinned against the gather-then-dense
attention math every serving program used before ISSUE 15:

- kernel-level parity: the flash-decode (K=1) and K-token verify variants
  vs the dense masked-softmax reference over the gathered span, including
  the fused-dequant int8 path against the SAME dequantized rows (tight
  tolerance: identical effective K/V, only accumulation order differs);
- engine-level bit-exactness: greedy token streams through
  ``attn_kernel="fused"`` equal the ``"dense"`` path's EXACTLY (f32, bf16,
  int8; plain and speculative ticks) — the ISSUE-15 acceptance anchor;
- quantized pool coverage: quantize→dequantize round-trip error bounds,
  ``kv_block_bytes`` scale-plane accounting, copy-on-write + prefix
  sharing refcounts over quantized blocks, TP=2 vs TP=1 token parity,
  and the fixed-KV-bytes >= 2x resident-request win vs bf16;
- the analyzer's HBM-bytes-per-tick model: the dense path carries the
  ``kv_attn_reread`` pass, the fused path is single-pass, quantized rows
  bill their scale bytes.
"""

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from tolerances import attn_tol

from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    QuantKV,
    _quantize_rows,
    make_gpt_stages,
    make_paged_block_copy,
)
from simple_distributed_machine_learning_tpu.ops.paged_attention import (
    paged_attention,
    paged_flash_decode,
)
from simple_distributed_machine_learning_tpu.serve import InferenceEngine
from simple_distributed_machine_learning_tpu.serve.slots import (
    PagedKVPool,
    kv_block_bytes,
    n_blocks_for_bytes,
)

CFG = GPTConfig(vocab=64, seq_len=32, d_model=32, n_heads=2, n_layers=2)


@pytest.fixture(scope="module")
def stages():
    return make_gpt_stages(jax.random.key(0), CFG, 1)[0]


def _dense_paged_reference(q, kc, vc, tables, qpos):
    """Gather-then-dense masked attention over the table span — exactly
    the serving programs' pre-kernel math (``models/gpt.py``)."""
    S, H, K, dh = q.shape
    NB = tables.shape[1]
    bs = kc.shape[-2]
    span = NB * bs
    outs = []
    for s in range(S):
        krow = np.moveaxis(np.asarray(kc, np.float32)[tables[s]], 0,
                           1).reshape(H, span, dh)
        vrow = np.moveaxis(np.asarray(vc, np.float32)[tables[s]], 0,
                           1).reshape(H, span, dh)
        sc = jnp.einsum("hqd,hkd->hqk", q[s].astype(jnp.float32),
                        krow) / math.sqrt(dh)
        live = np.arange(span)[None, None, :] <= qpos[s][None, :, None]
        sc = jnp.where(live, sc, -jnp.inf)
        outs.append(jnp.einsum("hqk,hkd->hqd", jax.nn.softmax(sc, -1),
                               vrow))
    return jnp.stack(outs)


def _toy_pool(key, S=3, H=2, dh=16, bs=4, NB=6, NBtot=12):
    kq, kk, kv = jax.random.split(key, 3)
    kc = jax.random.normal(kk, (NBtot, H, bs, dh))
    vc = jax.random.normal(kv, (NBtot, H, bs, dh))
    tables = np.zeros((S, NB), np.int32)
    tables[0, :4] = [2, 5, 7, 8]
    tables[1, :2] = [1, 3]
    tables[2, :1] = [9]
    pos = np.array([10, 4, 0], np.int32)
    return kq, kc, vc, tables, pos


def test_paged_flash_decode_matches_dense_gather():
    kq, kc, vc, tables, pos = _toy_pool(jax.random.key(0))
    q = jax.random.normal(kq, (3, 2, 1, 16))
    out = jax.jit(lambda *a: paged_flash_decode(*a, block_size=4))(
        q, kc, vc, jnp.asarray(tables), jnp.asarray(pos))
    ref = _dense_paged_reference(q, kc, vc, tables, pos[:, None])
    rtol, atol = attn_tol(jnp.float32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=rtol, atol=atol)


def test_paged_attention_verify_variant_matches_dense_gather():
    """The K-token variant: per-query masks at qpos = pos + j."""
    kq, kc, vc, tables, pos = _toy_pool(jax.random.key(1))
    K = 4
    q = jax.random.normal(kq, (3, 2, K, 16))
    qpos = np.minimum(pos[:, None] + np.arange(K)[None, :],
                      6 * 4 - 1).astype(np.int32)
    out = jax.jit(lambda *a: paged_attention(*a, block_size=4))(
        q, kc, vc, jnp.asarray(tables), jnp.asarray(qpos))
    ref = _dense_paged_reference(q, kc, vc, tables, qpos)
    rtol, atol = attn_tol(jnp.float32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=rtol, atol=atol)


def test_paged_attention_fused_dequant_matches_dequantized_rows():
    """int8 blocks + per-row scales through the kernel == dense attention
    over the EXPLICITLY dequantized rows — same effective K/V, so the
    comparison is tight (accumulation order only), proving dequantize is
    fused faithfully rather than approximated."""
    kq, kc, vc, tables, pos = _toy_pool(jax.random.key(2))
    K = 2
    q = jax.random.normal(kq, (3, 2, K, 16))
    qpos = np.minimum(pos[:, None] + np.arange(K)[None, :],
                      23).astype(np.int32)
    kd, ks = _quantize_rows(kc, jnp.int8)
    vd, vs = _quantize_rows(vc, jnp.int8)
    out = jax.jit(lambda *a: paged_attention(
        *a[:5], block_size=4, kscale=a[5], vscale=a[6]))(
        q, kd, vd, jnp.asarray(tables), jnp.asarray(qpos), ks, vs)
    deq_k = kd.astype(jnp.float32) * ks[..., None]
    deq_v = vd.astype(jnp.float32) * vs[..., None]
    ref = _dense_paged_reference(q, deq_k, deq_v, tables, qpos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # and the quantized result tracks the UNQUANTIZED one inside the
    # pinned int8 tolerance (the round-trip error budget)
    full = _dense_paged_reference(q, kc, vc, tables, qpos)
    rtol, atol = attn_tol(jnp.int8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(full),
                               rtol=rtol, atol=atol)


def test_quantize_roundtrip_error_bound():
    """|x - dequant(quant(x))| <= amax_row / (2 * qmax) elementwise — the
    per-row scale scheme's analytic bound (int8 qmax = 127)."""
    x = jax.random.normal(jax.random.key(3), (5, 4, 8, 32)) * 3.0
    qd, sc = _quantize_rows(x, jnp.int8)
    assert qd.dtype == jnp.int8 and sc.dtype == jnp.float32
    deq = qd.astype(jnp.float32) * sc[..., None]
    amax = np.max(np.abs(np.asarray(x)), axis=-1, keepdims=True)
    bound = amax / (2 * 127.0) + 1e-6
    assert np.all(np.abs(np.asarray(deq - x)) <= bound)
    # all-zero rows stay finite and decode to zero
    z = jnp.zeros((2, 4))
    zd, zs = _quantize_rows(z, jnp.int8)
    assert np.all(np.asarray(zd) == 0) and np.all(np.isfinite(zs))


def test_kv_block_bytes_accounts_scale_planes():
    L, H, bs, dh = 2, 2, 4, 16
    f32 = kv_block_bytes(L, H, bs, dh)
    bf16 = kv_block_bytes(L, H, bs, dh, "bfloat16")
    i8 = kv_block_bytes(L, H, bs, dh, "int8")
    assert f32 == 2 * L * H * bs * dh * 4
    assert bf16 == f32 // 2
    # int8 data + one f32 scale per (position, head) row, K and V
    assert i8 == 2 * L * H * bs * dh * 1 + 2 * L * H * bs * 4
    assert i8 < bf16 < f32
    # the pool's bytes_per_block uses the same formula (scales included)
    pool = PagedKVPool(L, 2, H, 16, dh, cache_dtype="int8", block_size=bs)
    assert pool.bytes_per_block == i8
    assert isinstance(pool.kc, QuantKV)
    assert pool.kc.nbytes == (pool.kc.data.nbytes + pool.kc.scale.nbytes)
    # fixed-byte sizing: the int8 budget funds strictly more blocks
    budget = 10 * bf16
    assert (n_blocks_for_bytes(budget, L, H, bs, dh, "int8")
            > n_blocks_for_bytes(budget, L, H, bs, dh, "bfloat16"))


def test_quantized_cache_is_paged_only():
    from simple_distributed_machine_learning_tpu.serve.slots import (
        KVCachePool,
    )

    with pytest.raises(ValueError, match="paged"):
        KVCachePool(2, 2, 2, 16, 16, cache_dtype="int8")


def test_engine_knob_validation(stages):
    with pytest.raises(ValueError, match="attn_kernel"):
        InferenceEngine(stages, CFG, attn_kernel="magic")
    with pytest.raises(ValueError, match="paged"):
        InferenceEngine(stages, CFG, kv_layout="dense",
                        attn_kernel="fused")
    with pytest.raises(ValueError, match="paged"):
        InferenceEngine(stages, CFG, kv_layout="dense",
                        cache_dtype="int8")


def _drain_tokens(stages, cfg, prompts, max_new=8, **kw):
    engine = InferenceEngine(stages, cfg, n_slots=3, block_size=4, **kw)
    handles = [engine.submit(p, max_new_tokens=max_new, seed=100 + i)
               for i, p in enumerate(prompts)]
    engine.drain()
    return engine, [list(h.tokens) for h in handles]


def _prompts(n=4, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, CFG.vocab, t).astype(np.int32)
            for t in (5, 9, 13, 7)[:n]]


@pytest.mark.parametrize("cache_dtype", [None, "bfloat16", "int8"])
def test_engine_greedy_fused_bit_exact_vs_dense_path(stages, cache_dtype):
    """THE acceptance anchor: greedy decode through attn_kernel='fused'
    emits the exact token stream of the gather-then-dense path — per
    storage dtype (f32/bf16 bit-exact vs their own dense path; the int8
    pool vs ITS dense path, quantization identical on both sides)."""
    prompts = _prompts()
    _, dense = _drain_tokens(stages, CFG, prompts, cache_dtype=cache_dtype)
    _, fused = _drain_tokens(stages, CFG, prompts, cache_dtype=cache_dtype,
                             attn_kernel="fused")
    assert dense == fused


def test_engine_speculative_fused_bit_exact(stages):
    """The K-token verify variant through the engine: fused speculative
    greedy streams equal the dense-path speculative ones AND the plain
    decode's (the existing spec-decode bit-exactness contract composes
    with the kernel)."""
    prompts = _prompts()
    kw = dict(draft_stages=stages, draft_cfg=CFG, spec_k=3)
    _, plain = _drain_tokens(stages, CFG, prompts)
    _, sp_dense = _drain_tokens(stages, CFG, prompts, **kw)
    _, sp_fused = _drain_tokens(stages, CFG, prompts,
                                attn_kernel="fused", **kw)
    assert sp_dense == sp_fused == plain
    # and over the quantized pool (fused vs dense, both int8)
    _, q_dense = _drain_tokens(stages, CFG, prompts, cache_dtype="int8",
                               **kw)
    _, q_fused = _drain_tokens(stages, CFG, prompts, cache_dtype="int8",
                               attn_kernel="fused", **kw)
    assert q_dense == q_fused


def test_quantized_pool_prefix_sharing_cow_refcounts(stages):
    """Copy-on-write + prefix sharing over int8 blocks: shared prompts
    reference the same physical blocks (prefix hits), divergence copies
    data AND scale planes (CoW counter), refcounts release cleanly, and
    sharing cannot change anyone's tokens vs an unshared run."""
    rng = np.random.default_rng(7)
    common = rng.integers(0, CFG.vocab, 9).astype(np.int32)
    prompts = [common,
               np.concatenate([common, [3, 5]]).astype(np.int32),
               np.concatenate([common, [11]]).astype(np.int32)]

    def serial_tokens(**kw):
        """One at a time through a fresh engine each — sharing impossible."""
        toks = []
        for i, p in enumerate(prompts):
            engine = InferenceEngine(stages, CFG, n_slots=3, block_size=4,
                                     **kw)
            h = engine.submit(p, max_new_tokens=6, seed=100 + i)
            engine.drain()
            toks.append(list(h.tokens))
        return toks

    engine = InferenceEngine(stages, CFG, n_slots=3, block_size=4,
                             cache_dtype="int8")
    # r0 boards and registers its prompt blocks; r1/r2 then share them
    # while r0 is STILL LIVE (ref >= 2), so their divergent writes into
    # the shared partial tail block must copy-on-write
    handles = [engine.submit(prompts[0], max_new_tokens=6, seed=100)]
    engine.step()               # r0's prefill completes + registry publish
    for i, p in enumerate(prompts[1:], start=1):
        handles.append(engine.submit(p, max_new_tokens=6, seed=100 + i))
    engine.drain()
    stats = engine.pool.stats()
    assert stats["prefix_hit_blocks_total"] > 0, "no prefix sharing fired"
    assert stats["cow_copies_total"] > 0, "no copy-on-write fired"
    # refcount discipline: nothing live after drain; cached blocks are
    # reclaimable, the rest free; the trash block is never referenced
    assert engine.pool.blocks_in_use == 0
    assert int(engine.pool.ref[PagedKVPool.TRASH]) == 0
    assert (stats["blocks_free"] + stats["blocks_cached"]
            == engine.pool.n_blocks)
    # sharing + CoW changed nothing about the streams
    assert [list(h.tokens) for h in handles] == serial_tokens(
        cache_dtype="int8")


def test_quantized_block_copy_moves_scale_planes():
    """The CoW device op must copy a QuantKV block's data AND its scale
    plane — rows without their scales decode to a different value."""
    L, H, bs, dh, NB = 2, 2, 4, 8, 3
    data = jnp.arange(L * (NB + 1) * H * bs * dh,
                      dtype=jnp.float32).reshape(L, NB + 1, H, bs, dh)
    qd, sc = _quantize_rows(data, jnp.int8)
    # the copy op DONATES its buffers: snapshot host copies first
    qd_np, sc_np = np.asarray(qd), np.asarray(sc)
    kc = QuantKV(qd, sc)
    vc = QuantKV(qd + 0, sc + 0.0)
    copy = make_paged_block_copy()
    kc2, vc2 = copy(kc, vc, jnp.int32(1), jnp.int32(3))
    np.testing.assert_array_equal(np.asarray(kc2.data[:, 1]), qd_np[:, 3])
    np.testing.assert_array_equal(np.asarray(kc2.scale[:, 1]), sc_np[:, 3])
    np.testing.assert_array_equal(np.asarray(vc2.scale[:, 2]), sc_np[:, 2])


def test_tp2_quantized_pool_token_parity(stages):
    """TP=2 over the head-sharded int8 pool (data + scale planes both
    split on the head axis) emits TP=1's exact tokens — fused kernel
    included (the kernel runs per shard inside shard_map)."""
    from simple_distributed_machine_learning_tpu.parallel.mesh import (
        make_mesh,
    )

    prompts = _prompts(3)
    _, base = _drain_tokens(stages, CFG, prompts, cache_dtype="int8")
    tp_cfg = dataclasses.replace(CFG, n_tensor_parallel=2)
    mesh = make_mesh(n_stages=1, n_data=1, n_model=2)
    _, tp_dense = _drain_tokens(stages, tp_cfg, prompts,
                                cache_dtype="int8", mesh=mesh)
    assert tp_dense == base
    _, tp_fused = _drain_tokens(stages, tp_cfg, prompts,
                                cache_dtype="int8", mesh=mesh,
                                attn_kernel="fused")
    assert tp_fused == base


def test_int8_pool_doubles_resident_requests_at_fixed_bytes(stages):
    """The ISSUE-15 capacity gate, engine-level: at the SAME KV byte
    budget (scale planes billed), an int8 pool sustains >= 2x the
    simultaneously resident requests of the bf16 pool under a burst."""
    L = sum(len(p["blocks"]) for p in (s.params for s in stages))
    dh = CFG.d_model // CFG.n_heads
    bs, max_new, plen = 4, 8, 13
    ml = plen + max_new
    bpr = -(-ml // bs)
    budget = (2 * bpr + 1) * kv_block_bytes(L, CFG.n_heads, bs, dh,
                                            "bfloat16")
    rng = np.random.default_rng(5)
    peaks = {}
    for cd in ("bfloat16", "int8"):
        nb = n_blocks_for_bytes(budget, L, CFG.n_heads, bs, dh, cd)
        engine = InferenceEngine(stages, CFG, n_slots=nb // bpr + 1,
                                 max_len=ml, block_size=bs, n_blocks=nb,
                                 cache_dtype=cd)
        for i in range(3 * (nb // bpr + 1)):
            engine.submit(rng.integers(0, CFG.vocab, plen).astype(np.int32),
                          max_new_tokens=max_new, seed=i)
        peak = 0
        while engine.busy:
            engine.step()
            peak = max(peak, engine.pool.n_active)
        peaks[cd] = peak
    assert peaks["int8"] >= 2 * peaks["bfloat16"], peaks


def test_hbm_model_matches_kernel_single_pass(stages):
    """The analyzer's per-tick model: dense path = gather + attn reread
    (two passes), fused = the gather pass alone; quantized rows bill
    data + scale bytes via the same kv_block_bytes rule the pool uses."""
    from simple_distributed_machine_learning_tpu.analysis.programs import (
        ServeSpec,
        hbm_tick_costs,
    )

    def costs(**kw):
        s = ServeSpec(CFG, n_slots=4, kv_layout="paged", block_size=4,
                      **kw)
        return {h.op: h.bytes_per_tick for h in hbm_tick_costs(s)}

    cd = costs()
    cf = costs(attn_kernel="fused")
    assert "decode.kv_attn_reread" in cd
    assert "decode.kv_attn_reread" not in cf
    assert cd["decode.kv_gather"] == cf["decode.kv_gather"]
    assert (cd["decode.kv_gather"] + cd["decode.kv_attn_reread"]
            == 2 * cf["decode.kv_gather"])
    # quantized traffic: per-position bytes == the pool's per-row bytes
    dh = CFG.d_model // CFG.n_heads
    cq = costs(cache_dtype="int8")
    per_pos = kv_block_bytes(1, CFG.n_heads, 1, dh, "int8")
    span = -(-CFG.seq_len // 4) * 4
    assert cq["decode.kv_gather"] == 4 * CFG.n_layers * span * per_pos
    # the speculative verify mirrors the decode rule
    draft_cfg = dataclasses.replace(CFG, n_layers=1)
    cv = costs(spec_k=3, draft_cfg=draft_cfg)
    cvf = costs(spec_k=3, draft_cfg=draft_cfg, attn_kernel="fused")
    assert "verify.kv_attn_reread" in cv
    assert "verify.kv_attn_reread" not in cvf


def test_engine_lint_covers_fused_quantized(stages):
    """InferenceEngine(lint=True) preflights the EXACT fused + int8
    programs (QuantKV abstract buffers, kernel path) without ERROR
    findings, and the drift gauge's prediction matches the pool."""
    engine = InferenceEngine(stages, CFG, n_slots=2, block_size=4,
                             cache_dtype="int8", attn_kernel="fused",
                             lint=True)
    h = engine.submit(np.arange(5, dtype=np.int32), max_new_tokens=4)
    engine.step()
    live, predicted = engine.kv_drift()
    assert live == predicted > 0
    engine.drain()
    assert h.state == "done"


@pytest.mark.skipif(not hasattr(jnp, "float8_e4m3fn"),
                    reason="no fp8 in this jnp build")
def test_fp8_cache_roundtrip_and_engine(stages):
    """fp8 (e4m3) where available: round-trip inside the pinned fp8
    tolerance and engine greedy parity fused-vs-dense."""
    x = jax.random.normal(jax.random.key(9), (4, 8, 16))
    qd, sc = _quantize_rows(x, jnp.float8_e4m3fn)
    deq = np.asarray(qd.astype(jnp.float32) * sc[..., None])
    rtol, atol = attn_tol(jnp.float8_e4m3fn)
    np.testing.assert_allclose(deq, np.asarray(x), rtol=rtol, atol=atol)
    prompts = _prompts(2)
    _, dense = _drain_tokens(stages, CFG, prompts,
                             cache_dtype=jnp.float8_e4m3fn)
    _, fused = _drain_tokens(stages, CFG, prompts,
                             cache_dtype=jnp.float8_e4m3fn,
                             attn_kernel="fused")
    assert dense == fused


# ---- ISSUE 16: the packed small-head-dim layout + kernel-derived HBM ----

@pytest.mark.parametrize("dh", [4, 8, 16])
def test_packed_layout_matches_natural(dh):
    """The 'packed' layout (K/V transposed so block positions take the
    lane slot — the ROADMAP #2 small-head-dim fix) is numerically
    identical to the natural layout: the zero-padded head rows contribute
    nothing to either dot."""
    key, kc, vc, tables, pos = _toy_pool(jax.random.key(3), dh=dh)
    S, H = tables.shape[0], kc.shape[1]
    q = jax.random.normal(key, (S, H, 2, dh))
    qpos = np.stack([np.maximum(pos - 1, 0), pos], axis=1).astype(np.int32)
    nat = paged_attention(q, kc, vc, tables, qpos, block_size=4,
                          _layout="natural")
    pak = paged_attention(q, kc, vc, tables, qpos, block_size=4,
                          _layout="packed")
    np.testing.assert_allclose(np.asarray(pak), np.asarray(nat),
                               rtol=1e-6, atol=1e-6)


def test_packed_layout_matches_natural_quantized():
    key, kc, vc, tables, pos = _toy_pool(jax.random.key(4), dh=4)
    kq, ks = _quantize_rows(kc, jnp.int8)
    vq, vs = _quantize_rows(vc, jnp.int8)
    S, H = tables.shape[0], kc.shape[1]
    q = jax.random.normal(key, (S, H, 1, 4))
    nat = paged_attention(q, kq, vq, tables, pos[:, None], block_size=4,
                          kscale=ks, vscale=vs, _layout="natural")
    pak = paged_attention(q, kq, vq, tables, pos[:, None], block_size=4,
                          kscale=ks, vscale=vs, _layout="packed")
    np.testing.assert_allclose(np.asarray(pak), np.asarray(nat),
                               rtol=1e-6, atol=1e-6)


def test_paged_attention_rejects_unknown_layout():
    key, kc, vc, tables, pos = _toy_pool(jax.random.key(5))
    q = jax.random.normal(key, (3, 2, 1, 16))
    with pytest.raises(ValueError, match="_layout"):
        paged_attention(q, kc, vc, tables, pos[:, None], block_size=4,
                        _layout="sideways")


@pytest.mark.parametrize("cache_dtype", [None, "int8"])
def test_kernel_hbm_rows_reconcile_with_tick_model(stages, cache_dtype):
    """ISSUE 16 acceptance: the kernel-DERIVED K/V stream bytes (block
    shapes x grid trips, from the traced pallas_calls' own BlockSpecs)
    agree EXACTLY with the tick model's ``decode.kv_gather`` row — which
    equals the dense twin's ``kv_attn_reread`` delta (the pass the fused
    kernel deletes)."""
    from simple_distributed_machine_learning_tpu.analysis.programs import (
        ServeSpec,
        hbm_tick_costs,
        lint_serve,
    )
    sspec = ServeSpec(CFG, n_slots=2, kv_layout="paged", block_size=4,
                      cache_dtype=cache_dtype, attn_kernel="fused",
                      prompt_lens=(4,))
    report = lint_serve(stages, sspec)
    assert report.ok(fail_on="warning"), report.format()
    derived = {}
    for h in report.hbm:
        if h.op == "kernel.kv_stream":
            derived[h.program] = derived.get(h.program, 0) + h.bytes_per_tick
    model = {(h.program, h.op): h.bytes_per_tick
             for h in report.hbm if not h.op.startswith("kernel.")}
    assert derived["paged_decode"] == model[("paged_decode",
                                             "decode.kv_gather")]
    # the dense twin pays the SAME bytes again as the attn reread: the
    # kernel-derived stream equals that deleted delta exactly
    dense = {h.op: h.bytes_per_tick
             for h in hbm_tick_costs(dataclasses.replace(
                 sspec, attn_kernel="dense"))}
    assert derived["paged_decode"] == dense["decode.kv_attn_reread"]


def test_kernel_hbm_mismatch_is_flagged():
    """Seeded drift between the tick model and the traced kernels must
    produce the kernel-hbm.mismatch ERROR (the reconciliation is a gate,
    not a report)."""
    from simple_distributed_machine_learning_tpu.analysis.programs import (
        ServeSpec,
        _reconcile_kernel_hbm,
        hbm_tick_costs,
    )
    from simple_distributed_machine_learning_tpu.analysis.report import (
        HBMCost,
    )
    sspec = ServeSpec(CFG, n_slots=2, kv_layout="paged", block_size=4,
                      attn_kernel="fused")
    model = hbm_tick_costs(sspec)
    want = next(h.bytes_per_tick for h in model
                if h.op == "decode.kv_gather")
    bad = [HBMCost("kernel.kv_stream", "paged_decode", want + 64)]
    findings = _reconcile_kernel_hbm(bad, model, sspec)
    assert any(f.rule == "kernel-hbm.mismatch" for f in findings)
    # and a fused spec whose programs traced NO kernel at all is flagged
    findings = _reconcile_kernel_hbm([], model, sspec)
    assert any(f.rule == "kernel-hbm.mismatch" for f in findings)
    # exact agreement is silent
    good = [HBMCost("kernel.kv_stream", "paged_decode", want)]
    assert not _reconcile_kernel_hbm(good, model, sspec)
