"""Dtype-aware comparison tolerances shared across the attention tests.

One rule for every test that compares attention/decode outputs whose K/V
round-tripped a storage dtype (flash kernel vs dense, bf16 caches vs f32,
quantized paged blocks vs wide): the tolerance is a property of the
STORAGE dtype, not of the individual test. Pinning it here ends the
per-test magic-number drift that left one bf16 comparison strict enough
to flake on backends whose accumulation order differs (the PR-15
known-env failure: bf16 beam decode flipping a near-tie ordering).
"""

import jax.numpy as jnp


def attn_tol(dtype) -> tuple[float, float]:
    """``(rtol, atol)`` for outputs computed through K/V stored as
    ``dtype``. f32 allows accumulation-order ulps only; bf16 allows its
    ~3-decimal-bit rounding through one attention round trip; quantized
    dtypes allow their per-row amax/qmax quantization step."""
    d = jnp.dtype(dtype)
    if d == jnp.dtype(jnp.float32):
        return (1e-5, 1e-5)
    if d == jnp.dtype(jnp.float16):
        return (2e-3, 2e-3)
    if d == jnp.dtype(jnp.bfloat16):
        return (5e-2, 5e-2)
    if d == jnp.dtype(jnp.int8):
        return (6e-2, 6e-2)
    if d.name.startswith("float8"):
        return (1.5e-1, 1.5e-1)
    raise ValueError(f"no pinned attention tolerance for dtype {d.name}")


def near_tie_token_mismatch_budget() -> float:
    """Fraction of tokens a sub-f32 cache may legitimately flip in an
    ARGMAX-over-near-ties decode (beam ordering, sampled top-k edges)
    before the comparison counts as a real divergence. Token streams with
    genuine math bugs diverge completely within a few positions; rounding
    flips stay sparse."""
    return 0.25
