"""Latency-hiding collective matmuls (parallel/overlap.py).

Parity of the ppermute-chunked ring schedules against the dense math and the
monolithic collectives they replace — forward AND backward (the custom_vjps
mirror the schedules) — on 2- and 4-shard meshes, plus the tensor-parallel
pair, the EP dispatch ring, and a GPT TP training-trajectory parity run with
``overlap='ring'``.

Everything is jitted: the ring schedules are built for one fused XLA program
(eager per-primitive dispatch of collective-permutes is not a supported
execution mode). Ring summation order differs from the monolithic all-reduce,
so comparisons are to float tolerance, not bit-exact (overlap.py's numerics
note).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from simple_distributed_machine_learning_tpu.parallel.compat import shard_map
from simple_distributed_machine_learning_tpu.parallel.overlap import (
    allgather_matmul,
    check_overlap,
    matmul_reducescatter,
    ring_all_gather,
    ring_psum,
    ring_reduce_scatter,
)

TOL = dict(rtol=2e-5, atol=2e-5)


def _mesh(mp):
    return Mesh(np.array(jax.devices()[:mp]), ("model",))


@pytest.mark.parametrize("mp", [2, 4])
def test_ring_all_gather_and_reduce_scatter(mp):
    mesh = _mesh(mp)
    x = jax.random.normal(jax.random.key(0), (8, 6))

    ag = jax.jit(shard_map(lambda s: ring_all_gather(s, "model"),
                           mesh=mesh, in_specs=P("model"), out_specs=P(None),
                           check_vma=False))
    np.testing.assert_allclose(np.asarray(ag(x)), np.asarray(x), **TOL)

    # per-device partials x * (i+1): the scattered sum is x * sum(1..mp)
    def rs(xf):
        i = lax.axis_index("model")
        return ring_reduce_scatter(xf * (i + 1.0), "model")

    f = jax.jit(shard_map(rs, mesh=mesh, in_specs=P(None),
                          out_specs=P("model"), check_vma=False))
    np.testing.assert_allclose(np.asarray(f(x)),
                               np.asarray(x) * sum(range(1, mp + 1)), **TOL)


@pytest.mark.parametrize("mp", [2, 4])
def test_ring_psum_matches_psum_fwd_and_grad(mp):
    mesh = _mesh(mp)
    x = jax.random.normal(jax.random.key(1), (6, 8))

    def loss(xf, use_ring):
        def body(v):
            part = v * (lax.axis_index("model") + 1.0)
            tot = (ring_psum(part, "model") if use_ring
                   else lax.psum(part, "model"))
            return jnp.sum(tot ** 2)
        return shard_map(body, mesh=mesh, in_specs=P(None), out_specs=P(),
                         check_vma=False)(xf)

    l_ring = jax.jit(lambda v: loss(v, True))(x)
    l_psum = jax.jit(lambda v: loss(v, False))(x)
    np.testing.assert_allclose(float(l_ring), float(l_psum), rtol=1e-6)
    g_ring = jax.jit(jax.grad(lambda v: loss(v, True)))(x)
    g_psum = jax.jit(jax.grad(lambda v: loss(v, False)))(x)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_psum), **TOL)


@pytest.mark.parametrize("mp", [2, 4])
def test_ring_psum_indivisible_last_axis_falls_back(mp):
    """A last axis that does not divide by the ring size silently takes the
    monolithic psum path — same value, no shape error."""
    mesh = _mesh(mp)
    x = jax.random.normal(jax.random.key(2), (4, 5))  # 5 % mp != 0
    f = jax.jit(shard_map(lambda v: ring_psum(v, "model"), mesh=mesh,
                          in_specs=P(None), out_specs=P(None, None),
                          check_vma=False))
    np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x) * mp, **TOL)


@pytest.mark.parametrize("mp", [2, 4])
def test_allgather_matmul_matches_dense(mp):
    """Column-parallel collective matmul: sharded rows x column-sharded
    weight == the dense product, values and both grads."""
    mesh = _mesh(mp)
    N, d, k = 8, 12, 8
    X = jax.random.normal(jax.random.key(0), (N, d))
    W = jax.random.normal(jax.random.key(1), (d, k))

    fwd = jax.jit(shard_map(
        lambda xs, ws: allgather_matmul(xs, ws, "model"),
        mesh=mesh, in_specs=(P("model"), P(None, "model")),
        out_specs=P(None, "model"), check_vma=False))
    np.testing.assert_allclose(np.asarray(fwd(X, W)), np.asarray(X @ W),
                               **TOL)

    def loss(Xf, Wf, use_ring):
        def body(xs, ws):
            y = (allgather_matmul(xs, ws, "model") if use_ring
                 else lax.all_gather(xs, "model", axis=0, tiled=True) @ ws)
            return lax.psum(jnp.sum(y ** 2), "model")
        return shard_map(body, mesh=mesh,
                         in_specs=(P("model"), P(None, "model")),
                         out_specs=P(), check_vma=False)(Xf, Wf)

    gx_r, gw_r = jax.jit(jax.grad(lambda a, b: loss(a, b, True),
                                  argnums=(0, 1)))(X, W)
    gx_m, gw_m = jax.jit(jax.grad(lambda a, b: loss(a, b, False),
                                  argnums=(0, 1)))(X, W)
    np.testing.assert_allclose(np.asarray(gx_r), np.asarray(gx_m), **TOL)
    np.testing.assert_allclose(np.asarray(gw_r), np.asarray(gw_m), **TOL)


@pytest.mark.parametrize("mp", [2, 4])
def test_matmul_reducescatter_matches_monolithic_psum(mp):
    """Row-parallel collective matmul: ring-accumulated partial products ==
    one blocking psum then slice, values and both grads."""
    mesh = _mesh(mp)
    N, k = 8, 8
    X = jax.random.normal(jax.random.key(3), (N, mp * 4))
    W = jax.random.normal(jax.random.key(4), (mp * 4, k))

    def y_of(xs, ws, use_ring):
        if use_ring:
            return matmul_reducescatter(xs, ws, "model")
        full = lax.psum(xs @ ws, "model")
        return lax.dynamic_slice_in_dim(
            full, lax.axis_index("model") * (N // mp), N // mp, 0)

    fwd = jax.jit(shard_map(
        lambda xs, ws: y_of(xs, ws, True), mesh=mesh,
        in_specs=(P(None, "model"), P("model")), out_specs=P("model"),
        check_vma=False))
    np.testing.assert_allclose(np.asarray(fwd(X, W)), np.asarray(X @ W),
                               **TOL)

    def loss(Xf, Wf, use_ring):
        def body(xs, ws):
            return lax.psum(jnp.sum(y_of(xs, ws, use_ring) ** 2), "model")
        return shard_map(body, mesh=mesh,
                         in_specs=(P(None, "model"), P("model")),
                         out_specs=P(), check_vma=False)(Xf, Wf)

    gx_r, gw_r = jax.jit(jax.grad(lambda a, b: loss(a, b, True),
                                  argnums=(0, 1)))(X, W)
    gx_m, gw_m = jax.jit(jax.grad(lambda a, b: loss(a, b, False),
                                  argnums=(0, 1)))(X, W)
    np.testing.assert_allclose(np.asarray(gx_r), np.asarray(gx_m), **TOL)
    np.testing.assert_allclose(np.asarray(gw_r), np.asarray(gw_m), **TOL)


@pytest.mark.parametrize("mp", [2, 4])
def test_tp_pair_ring_matches_none_and_dense(mp):
    """tp_pair_apply with overlap='ring' == overlap='none' == the dense
    pair, values and grads."""
    from simple_distributed_machine_learning_tpu.ops.layers import (
        linear,
        linear_init,
    )
    from simple_distributed_machine_learning_tpu.parallel.tensor import (
        stack_tp_shards,
        tp_pair_apply,
        tp_pair_init,
    )

    key = jax.random.key(0)
    d_in, d_h, d_out = 8, 16, 6
    x = jax.random.normal(jax.random.key(1), (4, d_in))
    mesh = _mesh(mp)
    stacked = stack_tp_shards(tp_pair_init(key, d_in, d_h, d_out, mp))

    def loss(p, xx, overlap):
        def body(pp, v):
            local = jax.tree.map(lambda l: l[0], pp)
            y = tp_pair_apply(local, v, axis="model", overlap=overlap)
            return lax.psum(jnp.sum(y ** 2), "model") / mp
        return shard_map(body, mesh=mesh, in_specs=(P("model"), P()),
                         out_specs=P(), check_vma=False)(p, xx)

    l_ring, g_ring = jax.jit(jax.value_and_grad(
        lambda p: loss(p, x, "ring")))(stacked)
    l_none, g_none = jax.jit(jax.value_and_grad(
        lambda p: loss(p, x, "none")))(stacked)
    np.testing.assert_allclose(float(l_ring), float(l_none), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g_ring), jax.tree.leaves(g_none)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), **TOL)

    # dense ground truth for the forward value
    k1, k2 = jax.random.split(key)
    w1, w2 = linear_init(k1, d_in, d_h), linear_init(k2, d_h, d_out)
    want = linear(w2, jax.nn.relu(linear(w1, x)))
    got = jax.jit(shard_map(
        lambda pp, v: tp_pair_apply(jax.tree.map(lambda l: l[0], pp), v,
                                    axis="model", overlap="ring"),
        mesh=mesh, in_specs=(P("model"), P()), out_specs=P(None, None),
        check_vma=False))(stacked, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), **TOL)


@pytest.mark.parametrize("ep", [2, 4])
def test_moe_ep_ring_matches_all_to_all(ep):
    """moe_apply_ep overlap='ring' (offset-ppermute dispatch, per-chunk FFN)
    == the 2x all_to_all schedule, loss and grads."""
    from simple_distributed_machine_learning_tpu.parallel.expert import (
        moe_apply_ep,
        moe_init,
    )

    mesh = Mesh(np.array(jax.devices()[:ep]), ("expert",))
    E, d, dh, T = 4, 8, 16, 12
    params = moe_init(jax.random.key(0), d, dh, E)
    x = jax.random.normal(jax.random.key(1), (ep * T, d))
    per = E // ep
    shards = [
        {"router": params["router"],
         "experts": jax.tree.map(lambda l, m=m: l[m * per:(m + 1) * per],
                                 params["experts"])}
        for m in range(ep)
    ]
    stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *shards)

    def loss(stk, xs, overlap):
        def body(p, xv):
            p = jax.tree.map(lambda l: l[0], p)
            y, aux = moe_apply_ep(p, xv, k=2, capacity=6, overlap=overlap)
            return lax.psum(jnp.sum(y ** 2), "expert") + aux
        return shard_map(body, mesh=mesh,
                         in_specs=(P("expert"), P("expert")), out_specs=P(),
                         check_vma=False)(stk, xs)

    l_ring, g_ring = jax.jit(jax.value_and_grad(
        lambda p: loss(p, x, "ring")))(stacked)
    l_none, g_none = jax.jit(jax.value_and_grad(
        lambda p: loss(p, x, "none")))(stacked)
    np.testing.assert_allclose(float(l_ring), float(l_none), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g_ring), jax.tree.leaves(g_none)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), **TOL)


# ---- GPT tensor parallelism end to end ---------------------------------


def _gpt_losses(ntp, overlap, n_steps, n_stages=1):
    """Train the tiny TP GPT through the real engine; return the losses.

    Ring runs use a 1-stage mesh: the whole point of the GPipe switch is
    that different stage devices execute different branches, and XLA:CPU's
    collective-permute rendezvous is global — branch-divergent ppermute
    rings deadlock there (on TPU the permutes are independent ICI DMAs).
    One stage keeps the switch single-branch while still driving the full
    shard_map engine.
    """
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )
    from simple_distributed_machine_learning_tpu.parallel.mesh import (
        make_mesh,
    )
    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        Pipeline,
    )
    from simple_distributed_machine_learning_tpu.train.optimizer import sgd
    from simple_distributed_machine_learning_tpu.train.step import (
        make_train_step,
    )

    cfg = GPTConfig(vocab=16, seq_len=8, d_model=16, n_heads=4, n_layers=2,
                    n_tensor_parallel=ntp, overlap=overlap)
    stages, wd, od = make_gpt_stages(jax.random.key(0), cfg, n_stages)
    mesh = make_mesh(n_stages=n_stages, n_data=1, n_model=ntp)
    pipe = Pipeline(stages, mesh, wd, od, n_microbatches=2, overlap=overlap)
    buf = pipe.init_params()
    opt = sgd(0.1, momentum=0.5)
    state = opt.init(buf)
    step = make_train_step(pipe, opt)
    x = jax.random.randint(jax.random.key(1), (4, 8), 0, 16).astype(
        jnp.float32)
    y = jax.random.randint(jax.random.key(2), (4, 8), 0, 16)
    losses = []
    for i in range(n_steps):
        buf, state, l = step(buf, state, x, y, jax.random.key(i))
        losses.append(float(l))
    return np.array(losses)


def test_gpt_tp_matches_dense_pipeline():
    """TP sharding alone (overlap='none') is loss-exact against the dense
    build through the 2-stage engine — the slices recompose the same math."""
    dense = _gpt_losses(1, "none", n_steps=5, n_stages=2)
    tp = _gpt_losses(2, "none", n_steps=5, n_stages=2)
    np.testing.assert_allclose(tp, dense, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("ntp", [2, 4])
def test_gpt_tp_ring_trajectory_matches_none(ntp):
    """The acceptance gate: overlap='ring' tracks overlap='none' within
    1e-5 over a 20-step GPT TP training run (4-device CPU mesh at ntp=4)."""
    l_none = _gpt_losses(ntp, "none", n_steps=20)
    l_ring = _gpt_losses(ntp, "ring", n_steps=20)
    np.testing.assert_allclose(l_ring, l_none, rtol=0, atol=1e-5)
    assert l_ring[-1] < l_ring[0]       # it actually trains


def test_overlap_validation():
    from simple_distributed_machine_learning_tpu.models.gpt import GPTConfig
    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        Pipeline,  # noqa: F401 - imported for the knob's home
    )

    with pytest.raises(ValueError, match="overlap"):
        check_overlap("diagonal")
    with pytest.raises(ValueError, match="overlap"):
        GPTConfig(overlap="diagonal")
    with pytest.raises(ValueError, match="n_heads"):
        GPTConfig(n_heads=4, n_tensor_parallel=3)
    with pytest.raises(ValueError, match="expert"):
        GPTConfig(n_experts=4, n_tensor_parallel=2)
    with pytest.raises(ValueError, match="attn_impl"):
        GPTConfig(attn_impl="flash", n_tensor_parallel=2)
