"""Request-scoped serve tracing, flight recorder, post-mortem bundles,
KV-drift gauges and the report CLI (ISSUE 12).

The acceptance pins:

- a crash-serve run produces per-request traces that SPAN the restart
  (submit -> crash -> re-admit -> completion under one rid, both
  incarnations visible) with no orphan end events;
- the virtual-clock scenario trace is byte-identical across two runs, and
  every exact-pinned scenario number is unchanged with tracing enabled
  (the recorder never reads a clock);
- the supervisor dumps a parseable post-mortem bundle on every restart,
  on DrainTimeout and on a shed burst, whose rows join the journal on the
  monotonic tick;
- the KV-drift gauge reads exactly 0 on clean paged AND dense runs (the
  PR-8 live-gauge == analyzer-prediction parity promoted to a runtime
  invariant), and old journals without the tick field stay recoverable.
"""

import hashlib
import json
import os

import jax
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    make_gpt_stages,
)
from simple_distributed_machine_learning_tpu.resilience import faults
from simple_distributed_machine_learning_tpu.resilience.scenarios import (
    VirtualClock,
    run_scenario,
)
from simple_distributed_machine_learning_tpu.serve import (
    DrainTimeout,
    FlightRecorder,
    InferenceEngine,
    ServeMetrics,
    ServeSupervisor,
    ServeTrace,
    engine_factory,
)
from simple_distributed_machine_learning_tpu.serve.flight import write_bundle
from simple_distributed_machine_learning_tpu.serve.journal import (
    RequestJournal,
    read_journal,
    recover_state,
)

CFG = GPTConfig(vocab=32, seq_len=48, d_model=32, n_heads=2, n_layers=2)
_STAGES = None


def _model():
    global _STAGES
    if _STAGES is None:
        _STAGES = make_gpt_stages(jax.random.key(0), CFG, 2)[0]
    return _STAGES


def _prompt(n, seed, first=None):
    p = np.array(jax.random.randint(jax.random.key(seed), (n,), 0,
                                    CFG.vocab), np.int32)
    if first is not None:
        p[0] = first            # distinct first tokens -> no prefix sharing
    return p


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.uninstall()
    yield
    faults.uninstall()


def _span_balance(events):
    """(orphan_ends, unclosed) over the async b/e events of a Chrome
    trace — the well-formedness invariant."""
    open_count: dict = {}
    orphans = []
    for e in events:
        key = (e.get("cat"), e.get("id"), e["name"])
        if e["ph"] == "b":
            open_count[key] = open_count.get(key, 0) + 1
        elif e["ph"] == "e":
            if open_count.get(key, 0) < 1:
                orphans.append(e)
            else:
                open_count[key] -= 1
    return orphans, {k: v for k, v in open_count.items() if v}


# ---------------------------------------------------------------------------
# trace well-formedness on a plain engine


def test_engine_trace_covers_request_lifecycle(tmp_path):
    stages = _model()
    trace = ServeTrace(outdir=str(tmp_path))
    eng = InferenceEngine(stages, CFG, n_slots=2, block_size=4,
                          prefill_chunk=3, trace=trace)
    h = eng.submit(_prompt(7, 1, first=0), max_new_tokens=4, seed=1)
    eng.submit(_prompt(5, 2, first=1), max_new_tokens=3, seed=2)
    eng.drain()
    trace.close()
    rows = [json.loads(line)
            for line in open(tmp_path / "request_timeline.jsonl")]
    evs_h = [r["ev"] for r in rows if r.get("rid") == h.rid]
    # the full ladder, in order: submit -> admit -> chunks -> first token
    # -> decode ticks -> done
    assert evs_h[0] == "submit" and evs_h[-1] == "done"
    assert "admit" in evs_h and "first_token" in evs_h
    assert evs_h.count("prefill_chunk") == 3          # ceil(7/3)
    assert evs_h.count("tick") == 3                   # tokens 2..4
    # timestamps non-decreasing within a request's timeline
    ts = [r["t"] for r in rows if r.get("rid") == h.rid]
    assert ts == sorted(ts)
    doc = json.load(open(tmp_path / "serve_trace.json"))
    orphans, unclosed = _span_balance(doc["traceEvents"])
    assert not orphans and not unclosed
    # chrome trace is pid-pinned (byte-identical across machines)
    assert all(e["pid"] == 0 for e in doc["traceEvents"])


def test_trace_preempt_resume_and_shed_events():
    from simple_distributed_machine_learning_tpu.serve import (
        PriorityScheduler,
    )
    stages = _model()
    trace = ServeTrace()
    eng = InferenceEngine(stages, CFG, n_slots=1, block_size=4,
                          scheduler=PriorityScheduler, trace=trace)
    low = eng.submit(_prompt(4, 1, first=0), max_new_tokens=10, seed=1,
                     cls="batch", priority=0)
    for _ in range(3):
        eng.step()
    eng.submit(_prompt(4, 2, first=1), max_new_tokens=3, seed=2,
               cls="interactive", priority=2)
    for _ in range(6):
        eng.step()
    eng.cancel(low.rid, "deadline")
    eng.drain()
    evs = [(r["ev"], r.get("rid")) for r in trace.rows]
    assert ("preempt", low.rid) in evs
    assert ("shed", low.rid) in evs
    orphans, unclosed = _span_balance(
        trace.to_chrome_trace()["traceEvents"])
    assert not orphans and not unclosed


def test_tracing_does_not_perturb_virtual_clock_metrics():
    """THE no-clock-reads pin: the same virtual-clock workload produces
    identical latency metrics with tracing on and off — a recorder that
    read the clock even once would shift every subsequent timestamp."""
    stages = _model()

    def run(trace):
        clock = VirtualClock()
        metrics = ServeMetrics(clock=clock)
        eng = InferenceEngine(stages, CFG, n_slots=2, block_size=4,
                              prefill_chunk=3, metrics=metrics,
                              clock=clock, trace=trace)
        for i in range(4):
            eng.submit(_prompt(5 + i, i, first=i), max_new_tokens=5,
                       seed=i)
        eng.drain()
        return metrics.summary()

    assert run(None) == run(ServeTrace())


# ---------------------------------------------------------------------------
# crash-serve: spans join across the restart (satellite 4)


def test_crash_serve_trace_spans_the_restart(tmp_path):
    """Spans for a recovered request cover submit -> crash -> re-admit ->
    completion across >= 1 restart, keyed by ONE rid; no orphan end
    events; and the exact-pinned scenario numbers hold with tracing ON."""
    stages = _model()
    trace = ServeTrace(outdir=str(tmp_path), suffix="-crash-serve")
    rep = run_scenario("crash-serve", stages, CFG, trace=trace)
    # tracing enabled must not move a single pinned number
    assert rep["slo_ok"] and rep["all_completed"] and rep["restarts"] == 1
    assert rep["slo"]["interactive"]["ttft_ms_p95"] == 23.16
    assert rep["trace_events"] == trace.n_events > 0
    rows = trace.rows
    crashed_rids = {r["rid"] for r in rows if r["ev"] == "crash"}
    assert crashed_rids, "the injected crash must show in the timeline"
    rid = sorted(crashed_rids)[0]
    evs = [r["ev"] for r in rows if r.get("rid") == rid]
    # the joined lifecycle under one trace id
    for needle in ("submit", "crash", "readmit", "done"):
        assert needle in evs, (rid, evs)
    assert evs.index("submit") < evs.index("crash") \
        < evs.index("readmit") < evs.index("done")
    # both engine incarnations visible on the one timeline
    incs = {r["inc"] for r in rows if r.get("rid") == rid}
    assert incs == {0, 1}
    orphans, unclosed = _span_balance(
        trace.to_chrome_trace()["traceEvents"])
    assert not orphans and not unclosed


def test_virtual_clock_trace_byte_identical_across_runs(tmp_path):
    stages = _model()
    digests = []
    for run_dir in ("a", "b"):
        d = tmp_path / run_dir
        run_scenario("crash-serve", stages, CFG, outdir=str(d), trace=True)
        digests.append(tuple(
            hashlib.sha256(
                open(d / name, "rb").read()).hexdigest()
            for name in ("serve_trace-crash-serve.json",
                         "request_timeline-crash-serve.jsonl")))
    assert digests[0] == digests[1]


def test_cold_restart_timeline_appends_under_same_rid(tmp_path):
    """Cold restart join: a NEW process's recorder (fresh=False) appends
    the recovered rid's events after the dead process's — one key, two
    engine incarnations' worth of history in one timeline file."""
    stages = _model()
    jpath = str(tmp_path / "journal.jsonl")
    trace1 = ServeTrace(outdir=str(tmp_path))
    sup = ServeSupervisor(engine_factory(stages, CFG, n_slots=2,
                                         block_size=4, prefill_chunk=3),
                          jpath, trace=trace1)
    h = sup.submit(_prompt(5, 1, first=0), max_new_tokens=6, seed=1)
    for _ in range(4):
        sup.step()
    mid_tokens = list(h.tokens)
    assert 0 < len(mid_tokens) < 6
    sup.close()         # process "dies" with the request in flight
    trace1.close()

    trace2 = ServeTrace(outdir=str(tmp_path), fresh=False)
    sup2 = ServeSupervisor(engine_factory(stages, CFG, n_slots=2,
                                          block_size=4, prefill_chunk=3),
                           jpath, trace=trace2)
    sup2.drain()
    sup2.close()
    trace2.close()
    rows = [json.loads(line)
            for line in open(tmp_path / "request_timeline.jsonl")]
    evs = [r["ev"] for r in rows if r.get("rid") == h.rid]
    assert evs[0] == "submit" and "readmit" in evs and evs[-1] == "done"
    # the recovered stream is the continuation, not a replay
    assert sup2.requests[h.rid].tokens[:len(mid_tokens)] == mid_tokens


# ---------------------------------------------------------------------------
# flight recorder + post-mortem bundles


def test_flight_recorder_ring_bounds():
    fr = FlightRecorder(capacity=3)
    for i in range(7):
        fr.record({"tick": i})
    assert fr.ticks_recorded == 7
    assert [r["tick"] for r in fr.rows()] == [4, 5, 6]
    with pytest.raises(ValueError, match="capacity"):
        FlightRecorder(capacity=0)


def test_restart_writes_postmortem_bundle_that_joins_journal(tmp_path):
    """One bundle per restart: parses, carries the dead incarnation's
    flight rows, request states and the journal tail — and bundle rows
    join journal records exactly on the monotonic tick."""
    stages = _model()
    faults.install(faults.FaultPlan.parse("engine-crash@serve.tick=3"))
    sup = ServeSupervisor(
        engine_factory(stages, CFG, n_slots=2, block_size=4,
                       prefill_chunk=3),
        str(tmp_path / "journal.jsonl"),
        postmortem_dir=str(tmp_path))
    for i in range(3):
        sup.submit(_prompt(5, i, first=i), max_new_tokens=6, seed=i)
    sup.drain()
    sup.close()
    assert sup.restarts == 1 and len(sup.postmortems) == 1
    bundle = json.load(open(sup.postmortems[0]))
    assert bundle["kind"] == "postmortem"
    assert bundle["trigger"] == "restart"
    assert "EngineCrash" in bundle["cause"]
    assert bundle["flight"], "the dead incarnation's flight rows"
    assert bundle["requests"] and bundle["journal_tail"]
    # the forensic join: flight ticks and journal ticks share one counter
    flight_ticks = {row["tick"] for row in bundle["flight"]}
    journal_ticks = {ev["tick"] for ev in bundle["journal_tail"]
                     if "tick" in ev}
    assert flight_ticks & journal_ticks
    assert bundle["tick"] >= max(flight_ticks)
    # every journal record written by the supervisor carries the tick
    events, _ = read_journal(str(tmp_path / "journal.jsonl"))
    assert events and all("tick" in ev for ev in events)
    ticks = [ev["tick"] for ev in events]
    assert ticks == sorted(ticks), "monotonic across the restart"


def test_drain_timeout_dumps_bundle_before_raising(tmp_path):
    stages = _model()
    sup = ServeSupervisor(
        engine_factory(stages, CFG, n_slots=1, block_size=4),
        str(tmp_path / "journal.jsonl"), postmortem_dir=str(tmp_path))
    sup.submit(_prompt(4, 1), max_new_tokens=12, seed=1)
    sup.submit(_prompt(4, 2), max_new_tokens=12, seed=2)
    with pytest.raises(DrainTimeout):
        sup.drain(max_ticks=2)
    assert len(sup.postmortems) == 1
    bundle = json.load(open(sup.postmortems[0]))
    assert bundle["trigger"] == "drain_timeout"
    live = [r for r in bundle["requests"]
            if r["state"] in ("queued", "active")]
    assert live, "the abandoned work is in the bundle"
    sup.close()


def test_shed_burst_dumps_bundle(tmp_path):
    """A tick that sheds >= shed_burst requests is a forensic event: the
    deadline mass-expiry here sheds every queued request at once."""
    stages = _model()
    clock = VirtualClock()
    sup = ServeSupervisor(
        engine_factory(stages, CFG, n_slots=1, block_size=4, clock=clock),
        str(tmp_path / "journal.jsonl"), clock=clock,
        postmortem_dir=str(tmp_path), shed_burst=3,
        default_ttft_deadline_s=0.004)
    for i in range(5):
        sup.submit(_prompt(4, i, first=i), max_new_tokens=4, seed=i)
    clock.sleep(1.0)            # every TTFT deadline expires
    sup.step()
    assert any("shed_burst" in p for p in sup.postmortems), sup.postmortems
    bundle = json.load(open(sup.postmortems[0]))
    assert bundle["trigger"] == "shed_burst"
    sup.close()


def test_write_bundle_atomic_and_complete(tmp_path):
    fr = FlightRecorder()
    fr.record({"tick": 1})
    path = write_bundle(str(tmp_path / "b.json"), trigger="restart",
                        cause="x", tick=1, flight=fr, requests={})
    b = json.load(open(path))
    assert b["flight"] == [{"tick": 1}] and b["requests"] == []
    assert not [p for p in os.listdir(tmp_path) if ".tmp." in p]


# ---------------------------------------------------------------------------
# journal tick satellite: old journals stay recoverable


def test_recover_state_tolerates_records_without_tick(tmp_path):
    """Regression pin for the journal format extension: a journal written
    BEFORE the tick field existed (hand-built here in the old grammar)
    recovers identically — cold restarts over old journals keep working."""
    path = str(tmp_path / "old.jsonl")
    old_records = [
        {"ev": "submit", "rid": 0, "prompt": [1, 2, 3], "max_new": 4,
         "temp": 0.0, "top_k": None, "top_p": None, "eos": None,
         "seed": 0, "cls": None, "prio": 0, "ttft_dl": None, "dl": None,
         "t": 1.0},
        {"ev": "tok", "rid": 0, "tok": 7, "kd": [1, 2], "dkd": None,
         "t": 2.0},
    ]
    with open(path, "w") as f:
        for rec in old_records:
            f.write(json.dumps(rec) + "\n")
    events, valid = read_journal(path)
    assert len(events) == 2 and valid == os.path.getsize(path)
    snaps = recover_state(events)
    assert snaps[0].tokens == [7] and snaps[0].state == "queued"
    # and the journal reopens for append over the old-format prefix
    j = RequestJournal(path, sync=False)
    j.log_done(rid=0, reason="length", t=3.0, tick=9)
    j.close()
    events2, _ = read_journal(path)
    assert events2[-1] == {"ev": "done", "rid": 0, "reason": "length",
                           "t": 3.0, "tick": 9}
    assert "tick" not in events2[0]


# ---------------------------------------------------------------------------
# KV drift: the PR-8 parity as a runtime invariant


def test_kv_drift_zero_every_tick_clean_paged_run():
    """THE drift acceptance pin (paged): with no prefix sharing, the live
    gauge equals the analyzer prediction at EVERY tick of the run."""
    stages = _model()
    metrics = ServeMetrics()
    eng = InferenceEngine(stages, CFG, n_slots=3, block_size=4,
                          prefill_chunk=3, metrics=metrics)
    for i in range(5):
        eng.submit(_prompt(5 + i, i, first=i), max_new_tokens=6, seed=i)
    while eng.busy:
        eng.step()
        live, predicted = eng.kv_drift()
        assert live == predicted, (live, predicted)
        assert metrics.kv_drift_bytes.value == 0
    s = metrics.summary()
    assert s["kv_drift_bytes"] == 0 and "kv_bytes_predicted" in s


def test_kv_drift_zero_dense_run():
    """The dense acceptance pin: the dense pool's full-allocation bytes
    equal the analyzer's dense prediction (geometry checked live)."""
    stages = _model()
    metrics = ServeMetrics()
    eng = InferenceEngine(stages, CFG, n_slots=2, kv_layout="dense",
                          metrics=metrics)
    eng.submit(_prompt(5, 1), max_new_tokens=4, seed=1)
    eng.drain()
    live, predicted = eng.kv_drift()
    assert live == predicted > 0
    assert metrics.kv_drift_bytes.value == 0
    assert metrics.summary()["kv_bytes_predicted"] == predicted


def test_kv_drift_negative_under_prefix_sharing_never_positive():
    """Shared blocks make the live gauge SMALLER than the no-sharing
    model — drift <= 0 always; a positive drift would be a block leak."""
    stages = _model()
    metrics = ServeMetrics()
    eng = InferenceEngine(stages, CFG, n_slots=2, block_size=4,
                          prefill_chunk=None, metrics=metrics)
    shared = _prompt(8, 99)
    eng.submit(shared.copy(), max_new_tokens=8, seed=0)
    # the first request must have REGISTERED its prompt blocks (prefill
    # done) and still be decoding when the duplicate binds — concurrent
    # sharing is what makes live < predicted
    eng.step()
    eng.step()
    saw_sharing = False
    eng.submit(shared.copy(), max_new_tokens=8, seed=1)
    while eng.busy:
        eng.step()
        live, predicted = eng.kv_drift()
        assert live <= predicted, (live, predicted)
        saw_sharing |= live < predicted
    assert saw_sharing, "identical prompts must actually share blocks"


# ---------------------------------------------------------------------------
# the report CLI


def test_report_cli_renders_and_exits_zero(tmp_path, capsys):
    from simple_distributed_machine_learning_tpu.telemetry import report

    stages = _model()
    d = str(tmp_path / "run")
    rep = run_scenario("crash-serve", stages, CFG, outdir=d, trace=True)
    assert rep["postmortem_bundles"] == 1
    rc = report.main(["--dir", d])
    out = capsys.readouterr().out
    assert rc == 0
    assert "scenario crash-serve [PASS]" in out
    assert "restart #1" in out and "postmortem" in out
    assert "kv drift" in out and "[OK]" in out
    assert "timeline" in out and "2 incarnation(s)" in out
    rc = report.main(["--dir", d, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert doc["serve"]["requests_completed"] == 16
    assert doc["postmortems"][0]["trigger"] == "restart"


def test_report_cli_exit_codes(tmp_path, capsys):
    from simple_distributed_machine_learning_tpu.telemetry import report

    assert report.main(["--dir", str(tmp_path / "missing")]) == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert report.main(["--dir", str(empty)]) == 2
    capsys.readouterr()


def test_report_json_schema_pinned(tmp_path, capsys):
    """The ``--json`` document's top-level keys are an interface other
    tooling parses — pinned EXACTLY (a new artifact must land here, and
    the ISSUE-19 ``slo_alerts``/``attribution`` blocks are always
    present, never conditionally spliced in)."""
    from simple_distributed_machine_learning_tpu.telemetry import report

    stages = _model()
    d = str(tmp_path / "run")
    run_scenario("overload-shed", stages, CFG, outdir=d, trace=True)
    assert report.main(["--dir", d, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert set(doc) == {
        "dir", "serve", "scenarios", "slo_alerts", "attribution",
        "epochs", "last_epoch", "sentinel", "journals", "timelines",
        "traces", "postmortems"}
    assert [(r["tick"], r["to"]) for r in doc["slo_alerts"]] == [
        (37, "pending"), (38, "firing"), (49, "resolved"),
        (50, "inactive")]
    att = doc["attribution"]["overload-shed"]
    assert att["requests"] == 11 and att["top_slow"][0]["rid"] == 2
    # the text renderer shows the same two blocks: alert transitions and
    # the top-K slow-request autopsy table
    assert report.main(["--dir", d]) == 0
    out = capsys.readouterr().out
    assert "alert slo_burn{class=interactive}: pending -> firing" in out
    assert "top slow requests (TTFT autopsy):" in out
