"""Checkpoint round-trip: bit-exact resume of params + optimizer state."""

import os

import jax
import numpy as np

from simple_distributed_machine_learning_tpu.models.mlp import make_mlp_stages
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.train.checkpoint import (
    restore_checkpoint,
    save_checkpoint,
)
from simple_distributed_machine_learning_tpu.train.optimizer import sgd
from simple_distributed_machine_learning_tpu.train.step import make_train_step


def test_checkpoint_roundtrip_and_resume(tmp_path):
    key = jax.random.key(0)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wd, od)
    opt = sgd(0.1, 0.5)
    buf = pipe.init_params()
    state = opt.init(buf)
    step = make_train_step(pipe, opt)

    x = jax.random.normal(key, (8, 12))
    y = jax.random.randint(key, (8,), 0, 10)
    for i in range(3):
        buf, state, _ = step(buf, state, x, y, jax.random.fold_in(key, i))

    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, buf, state, step=3, extra={"epoch": 1})
    assert os.path.exists(path) and os.path.exists(path + ".meta.json")

    # continue training the original for 2 more steps
    buf_a, state_a = buf, state
    for i in range(3, 5):
        buf_a, state_a, _ = step(buf_a, state_a, x, y, jax.random.fold_in(key, i))

    # restore and train the restored copy identically
    ck = restore_checkpoint(path, pipe=pipe, opt_treedef_like=opt.init(buf_a))
    assert ck["step"] == 3 and ck["extra"]["epoch"] == 1
    buf_b, state_b = ck["params"], ck["opt_state"]
    # sharding restored stage-wise
    assert "stage" in str(buf_b.sharding.spec)
    for i in range(3, 5):
        buf_b, state_b, _ = step(buf_b, state_b, x, y, jax.random.fold_in(key, i))

    np.testing.assert_array_equal(np.asarray(buf_a), np.asarray(buf_b))
    np.testing.assert_array_equal(np.asarray(state_a), np.asarray(state_b))


def test_restore_rejects_mismatched_buffer_shape(tmp_path):
    """An old-layout checkpoint must fail with a descriptive shape error
    BEFORE device_put can raise an opaque sharding/rank error (ADVICE r1)."""
    import pytest

    key = jax.random.key(1)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wd, od)
    buf = pipe.init_params()
    path = str(tmp_path / "old.npz")
    # simulate a checkpoint from the pre-[n_stages, n_model, P] layout
    save_checkpoint(path, np.asarray(jax.device_get(buf))[:, 0, :],
                    opt_state=[], step=1)
    with pytest.raises(ValueError, match="does not match the model"):
        restore_checkpoint(path, pipe=pipe)


def test_async_save_round_trips_bit_exact(tmp_path):
    """save_checkpoint_async: same file contents as the sync path, write
    overlapped on a background thread, errors surfaced via wait()."""
    import pytest

    from simple_distributed_machine_learning_tpu.train.checkpoint import (
        save_checkpoint_async,
    )

    key = jax.random.key(0)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wd, od)
    buf = pipe.init_params()
    opt = sgd(0.1, 0.5)
    state = opt.init(buf)

    path = str(tmp_path / "async.npz")
    h = save_checkpoint_async(path, buf, state, step=7, extra={"epoch": 2})
    h.wait()
    assert h.done and os.path.exists(path)
    ck = restore_checkpoint(path, pipe=pipe, opt_treedef_like=state)
    assert ck["step"] == 7 and ck["extra"]["epoch"] == 2
    np.testing.assert_array_equal(np.asarray(jax.device_get(buf)),
                                  np.asarray(jax.device_get(ck["params"])))

    # a failing write must raise from wait(), not vanish on the thread
    bad = save_checkpoint_async(str(tmp_path / "nodir" / ("x" * 300) / "y.npz"),
                                buf, state, step=0)
    with pytest.raises(BaseException):
        bad.wait()


def test_trainer_async_checkpoint_resumes(tmp_path):
    """Trainer(async_checkpoint=True): the per-epoch save lands on disk and
    a fresh Trainer auto-resumes from it."""
    from simple_distributed_machine_learning_tpu.data.mnist import Dataset
    from simple_distributed_machine_learning_tpu.train.trainer import (
        TrainConfig,
        Trainer,
    )

    rng = np.random.RandomState(0)
    ds = Dataset(rng.randn(120, 12).astype(np.float32),
                 rng.randint(0, 10, 120))
    key = jax.random.key(0)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wd, od)
    cfg = TrainConfig(epochs=2, batch_size=30, checkpoint_dir=str(tmp_path),
                      async_checkpoint=True, print_throughput=False)
    tr = Trainer(pipe, ds, ds, cfg)
    tr.fit()
    assert os.path.exists(str(tmp_path / "state.npz"))

    pipe2 = Pipeline(stages, mesh, wd, od)
    tr2 = Trainer(pipe2, ds, ds, cfg)
    assert tr2.start_epoch == 3
    np.testing.assert_array_equal(np.asarray(jax.device_get(tr.buf)),
                                  np.asarray(jax.device_get(tr2.buf)))


def test_resume_scalar_opt_state_on_multidevice_mesh():
    """Scalar optimizer-state leaves (a schedule's step counter, AdamW's
    step) must come back PLACEABLE after restore: committing them to the
    single device opt.init happened to use makes the first jitted step
    reject the mixed placement against the mesh-sharded buffer (caught by
    driving CLI resume; regression for train/checkpoint.py::_place)."""
    from simple_distributed_machine_learning_tpu.train import schedules
    from simple_distributed_machine_learning_tpu.train.checkpoint import (
        save_checkpoint,
    )
    from simple_distributed_machine_learning_tpu.train.optimizer import adamw

    key = jax.random.key(0)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    x = jax.random.normal(key, (8, 12))
    y = jax.random.randint(key, (8,), 0, 10)

    for opt in (sgd(schedules.cosine(0.1, 50), 0.5), adamw(1e-3)):
        pipe = Pipeline(stages, mesh, wd, od)
        buf = pipe.init_params()
        state = opt.init(buf)
        step = make_train_step(pipe, opt)
        buf, state, _ = step(buf, state, x, y, key)
        import tempfile
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "s.npz")
            save_checkpoint(path, buf, state, step=1)
            ck = restore_checkpoint(path, pipe=pipe,
                                    opt_treedef_like=opt.init(
                                        pipe.init_params()))
            step2 = make_train_step(pipe, opt)
            b2, s2, loss = step2(ck["params"], ck["opt_state"], x, y, key)
            assert np.isfinite(float(loss))


def test_repack_mlp_2_to_4_stage_trajectory_matches(tmp_path):
    """Cross-topology resume: train 2-stage, checkpoint, resume 4-stage via
    src_pipe repacking — params AND momentum land in the new layout, and the
    continued trajectory matches continuing at 2 stages (the engines are
    parity-tested across topologies, so identical state must give identical
    losses to float tolerance)."""
    key = jax.random.key(0)
    dims = [12, 16, 14, 16, 10]
    stages2, wd, od = make_mlp_stages(key, dims, 2)
    pipe2 = Pipeline(stages2, make_mesh(n_stages=2, n_data=1,
                                        devices=jax.devices()[:2]), wd, od)
    opt = sgd(0.1, 0.5)
    buf, state = pipe2.init_params(), None
    state = opt.init(buf)
    step2 = make_train_step(pipe2, opt)
    x = jax.random.normal(key, (8, 12))
    y = jax.random.randint(key, (8,), 0, 10)
    for i in range(3):
        buf, state, _ = step2(buf, state, x, y, jax.random.fold_in(key, i))
    path = str(tmp_path / "ck.npz")
    save_checkpoint(path, buf, state, step=3)

    # continue at 2 stages (ground truth)
    losses_a = []
    buf_a, state_a = buf, state
    for i in range(3, 6):
        buf_a, state_a, l = step2(buf_a, state_a, x, y,
                                  jax.random.fold_in(key, i))
        losses_a.append(float(l))

    # resume at 4 stages from the same checkpoint
    stages4, wd4, od4 = make_mlp_stages(key, dims, 4)
    pipe4 = Pipeline(stages4, make_mesh(n_stages=4, n_data=1,
                                        devices=jax.devices()[:4]), wd4, od4)
    ck = restore_checkpoint(path, pipe=pipe4,
                            opt_treedef_like=opt.init(pipe4.init_params()),
                            src_pipe=pipe2)
    buf_b, state_b = ck["params"], ck["opt_state"]
    step4 = make_train_step(pipe4, opt)
    losses_b = []
    for i in range(3, 6):
        buf_b, state_b, l = step4(buf_b, state_b, x, y,
                                  jax.random.fold_in(key, i))
        losses_b.append(float(l))
    np.testing.assert_allclose(losses_a, losses_b, rtol=2e-5, atol=2e-5)


def test_repack_gpt_blocks_embed_head(tmp_path):
    """The GPT convention: blocks re-split, embed sticks to the first stage,
    head to the last; the repacked 4-stage model computes the same function
    (same loss on the same batch)."""
    import jax.numpy as jnp

    from simple_distributed_machine_learning_tpu.data.text import (
        synthetic_tokens,
    )
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )
    from simple_distributed_machine_learning_tpu.train.checkpoint import (
        repack_checkpoint,
    )

    cfg = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=4)
    s2, wd, osh = make_gpt_stages(jax.random.key(0), cfg, 2)
    pipe2 = Pipeline(s2, make_mesh(n_stages=2, n_data=1,
                                   devices=jax.devices()[:2]), wd, osh)
    s4, wd4, osh4 = make_gpt_stages(jax.random.key(1), cfg, 4)
    pipe4 = Pipeline(s4, make_mesh(n_stages=4, n_data=1,
                                   devices=jax.devices()[:4]), wd4, osh4)
    opt = sgd(0.1, 0.5)
    buf2 = pipe2.init_params()
    p_in = str(tmp_path / "in.npz")
    p_out = str(tmp_path / "out.npz")
    save_checkpoint(p_in, buf2, opt.init(buf2), step=0)
    repack_checkpoint(p_in, p_out, pipe2, pipe4)
    ck = restore_checkpoint(p_out, pipe=pipe4,
                            opt_treedef_like=opt.init(pipe4.init_params()))

    data = synthetic_tokens(4, cfg.seq_len, cfg.vocab, seed=1)
    x = jnp.asarray(data.x, jnp.float32)
    y = jnp.asarray(data.y)
    key = jax.random.key(2)
    l2, lp2 = pipe2.loss_and_logits(buf2, x, y, key, deterministic=True)
    l4, lp4 = pipe4.loss_and_logits(ck["params"], x, y, key,
                                    deterministic=True)
    np.testing.assert_allclose(float(l2), float(l4), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(lp2), np.asarray(lp4), rtol=2e-4,
                               atol=2e-4)


def test_mid_write_crash_leaves_committed_checkpoint_intact(tmp_path):
    """Atomicity pin: a crash BETWEEN writing the checkpoint bytes and the
    atomic rename (injected ckpt-write-crash, which also truncates the
    in-flight temp like a real half-write) must leave the previously
    committed checkpoint bit-intact and restorable, with no temp litter."""
    from simple_distributed_machine_learning_tpu.resilience import faults

    key = jax.random.key(0)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wd, od)
    opt = sgd(0.1, 0.5)
    buf = pipe.init_params()
    state = opt.init(buf)
    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, buf, state, step=1, extra={"epoch": 1})
    before = open(path, "rb").read()

    faults.install(faults.FaultPlan.parse("ckpt-write-crash@ckpt.write"))
    try:
        import pytest
        with pytest.raises(faults.CheckpointWriteCrash):
            save_checkpoint(path, buf, state, step=2, extra={"epoch": 2})
    finally:
        faults.uninstall()
    assert open(path, "rb").read() == before
    assert not [f for f in os.listdir(str(tmp_path)) if ".tmp." in f]
    ck = restore_checkpoint(path, pipe=pipe, opt_treedef_like=state)
    assert ck["step"] == 1 and ck["extra"]["epoch"] == 1


def test_restore_rejects_truncated_file_with_clear_error(tmp_path):
    """A truncated/corrupt checkpoint must raise CheckpointCorruptError
    NAMING THE PATH — not a raw zipfile.BadZipFile or KeyError traceback."""
    import pytest

    from simple_distributed_machine_learning_tpu.train.checkpoint import (
        CheckpointCorruptError,
    )

    key = jax.random.key(0)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wd, od)
    buf = pipe.init_params()
    state = sgd(0.1, 0.5).init(buf)
    path = str(tmp_path / "trunc.npz")
    save_checkpoint(path, buf, state, step=3)

    # mid-write truncation (the torn file a real crash leaves behind)
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)
    with pytest.raises(CheckpointCorruptError, match="trunc.npz"):
        restore_checkpoint(path, pipe=pipe)

    # not-a-zip garbage
    bad = str(tmp_path / "garbage.npz")
    with open(bad, "wb") as f:
        f.write(b"not a zip at all")
    with pytest.raises(CheckpointCorruptError, match="garbage.npz"):
        restore_checkpoint(bad)

    # a valid npz that is not a training checkpoint (missing _meta_json)
    import numpy as _np
    notckpt = str(tmp_path / "notckpt.npz")
    _np.savez(notckpt, x=_np.zeros(3))
    with pytest.raises(CheckpointCorruptError, match="notckpt.npz"):
        restore_checkpoint(notckpt)


def test_repack_rejects_structural_renames():
    """LeNet's 1-stage fused tree is a structural rename of its 2-stage
    split, not a contiguous re-split — must be rejected loudly."""
    import pytest

    from simple_distributed_machine_learning_tpu.models.lenet import (
        make_lenet_stages,
    )
    from simple_distributed_machine_learning_tpu.train.checkpoint import (
        repack_packed_buffer,
    )

    s2, wd, od = make_lenet_stages(jax.random.key(0), 2)
    pipe2 = Pipeline(s2, make_mesh(n_stages=2, n_data=1,
                                   devices=jax.devices()[:2]), wd, od)
    s1, wd1, od1 = make_lenet_stages(jax.random.key(0), 1)
    pipe1 = Pipeline(s1, make_mesh(n_stages=1, n_data=1,
                                   devices=jax.devices()[:1]), wd1, od1)
    with pytest.raises(ValueError, match="cannot be re-packed"):
        repack_packed_buffer(pipe2._buf0, pipe2, pipe1)


def test_repack_gpt_fused_1_stage_to_pipeline():
    """1-stage (fused) -> 2-stage: the single tree's 'head' moves to the new
    last stage, 'embed' stays first; the scaled-out model computes the same
    function."""
    import jax.numpy as jnp

    from simple_distributed_machine_learning_tpu.data.text import (
        synthetic_tokens,
    )
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )
    from simple_distributed_machine_learning_tpu.train.checkpoint import (
        repack_packed_buffer,
    )

    cfg = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=2)
    s1, wd1, osh1 = make_gpt_stages(jax.random.key(0), cfg, 1)
    pipe1 = Pipeline(s1, make_mesh(n_stages=1, n_data=1,
                                   devices=jax.devices()[:1]), wd1, osh1)
    s2, wd2, osh2 = make_gpt_stages(jax.random.key(1), cfg, 2)
    pipe2 = Pipeline(s2, make_mesh(n_stages=2, n_data=1,
                                   devices=jax.devices()[:2]), wd2, osh2)
    buf2 = jax.device_put(
        repack_packed_buffer(pipe1._buf0, pipe1, pipe2),
        jax.sharding.NamedSharding(pipe2.mesh, pipe2.param_spec()))

    data = synthetic_tokens(4, cfg.seq_len, cfg.vocab, seed=3)
    x = jnp.asarray(data.x, jnp.float32)
    y = jnp.asarray(data.y)
    key = jax.random.key(4)
    l1, _ = pipe1.loss_and_logits(pipe1.init_params(), x, y, key,
                                  deterministic=True)
    l2, _ = pipe2.loss_and_logits(buf2, x, y, key, deterministic=True)
    np.testing.assert_allclose(float(l1), float(l2), rtol=2e-5, atol=2e-5)
