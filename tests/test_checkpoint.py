"""Checkpoint round-trip: bit-exact resume of params + optimizer state."""

import os

import jax
import numpy as np

from simple_distributed_machine_learning_tpu.models.mlp import make_mlp_stages
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.train.checkpoint import (
    restore_checkpoint,
    save_checkpoint,
)
from simple_distributed_machine_learning_tpu.train.optimizer import sgd
from simple_distributed_machine_learning_tpu.train.step import make_train_step


def test_checkpoint_roundtrip_and_resume(tmp_path):
    key = jax.random.key(0)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wd, od)
    opt = sgd(0.1, 0.5)
    buf = pipe.init_params()
    state = opt.init(buf)
    step = make_train_step(pipe, opt)

    x = jax.random.normal(key, (8, 12))
    y = jax.random.randint(key, (8,), 0, 10)
    for i in range(3):
        buf, state, _ = step(buf, state, x, y, jax.random.fold_in(key, i))

    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, buf, state, step=3, extra={"epoch": 1})
    assert os.path.exists(path) and os.path.exists(path + ".meta.json")

    # continue training the original for 2 more steps
    buf_a, state_a = buf, state
    for i in range(3, 5):
        buf_a, state_a, _ = step(buf_a, state_a, x, y, jax.random.fold_in(key, i))

    # restore and train the restored copy identically
    ck = restore_checkpoint(path, pipe=pipe, opt_treedef_like=opt.init(buf_a))
    assert ck["step"] == 3 and ck["extra"]["epoch"] == 1
    buf_b, state_b = ck["params"], ck["opt_state"]
    # sharding restored stage-wise
    assert "stage" in str(buf_b.sharding.spec)
    for i in range(3, 5):
        buf_b, state_b, _ = step(buf_b, state_b, x, y, jax.random.fold_in(key, i))

    np.testing.assert_array_equal(np.asarray(buf_a), np.asarray(buf_b))
    np.testing.assert_array_equal(np.asarray(state_a), np.asarray(state_b))


def test_restore_rejects_mismatched_buffer_shape(tmp_path):
    """An old-layout checkpoint must fail with a descriptive shape error
    BEFORE device_put can raise an opaque sharding/rank error (ADVICE r1)."""
    import pytest

    key = jax.random.key(1)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wd, od)
    buf = pipe.init_params()
    path = str(tmp_path / "old.npz")
    # simulate a checkpoint from the pre-[n_stages, n_model, P] layout
    save_checkpoint(path, np.asarray(jax.device_get(buf))[:, 0, :],
                    opt_state=[], step=1)
    with pytest.raises(ValueError, match="does not match the model"):
        restore_checkpoint(path, pipe=pipe)
