"""Checkpoint round-trip: bit-exact resume of params + optimizer state."""

import os

import jax
import numpy as np

from simple_distributed_machine_learning_tpu.models.mlp import make_mlp_stages
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.train.checkpoint import (
    restore_checkpoint,
    save_checkpoint,
)
from simple_distributed_machine_learning_tpu.train.optimizer import sgd
from simple_distributed_machine_learning_tpu.train.step import make_train_step


def test_checkpoint_roundtrip_and_resume(tmp_path):
    key = jax.random.key(0)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wd, od)
    opt = sgd(0.1, 0.5)
    buf = pipe.init_params()
    state = opt.init(buf)
    step = make_train_step(pipe, opt)

    x = jax.random.normal(key, (8, 12))
    y = jax.random.randint(key, (8,), 0, 10)
    for i in range(3):
        buf, state, _ = step(buf, state, x, y, jax.random.fold_in(key, i))

    path = str(tmp_path / "ckpt.npz")
    save_checkpoint(path, buf, state, step=3, extra={"epoch": 1})
    assert os.path.exists(path) and os.path.exists(path + ".meta.json")

    # continue training the original for 2 more steps
    buf_a, state_a = buf, state
    for i in range(3, 5):
        buf_a, state_a, _ = step(buf_a, state_a, x, y, jax.random.fold_in(key, i))

    # restore and train the restored copy identically
    ck = restore_checkpoint(path, pipe=pipe, opt_treedef_like=opt.init(buf_a))
    assert ck["step"] == 3 and ck["extra"]["epoch"] == 1
    buf_b, state_b = ck["params"], ck["opt_state"]
    # sharding restored stage-wise
    assert "stage" in str(buf_b.sharding.spec)
    for i in range(3, 5):
        buf_b, state_b, _ = step(buf_b, state_b, x, y, jax.random.fold_in(key, i))

    np.testing.assert_array_equal(np.asarray(buf_a), np.asarray(buf_b))
    np.testing.assert_array_equal(np.asarray(state_a), np.asarray(state_b))


def test_restore_rejects_mismatched_buffer_shape(tmp_path):
    """An old-layout checkpoint must fail with a descriptive shape error
    BEFORE device_put can raise an opaque sharding/rank error (ADVICE r1)."""
    import pytest

    key = jax.random.key(1)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wd, od)
    buf = pipe.init_params()
    path = str(tmp_path / "old.npz")
    # simulate a checkpoint from the pre-[n_stages, n_model, P] layout
    save_checkpoint(path, np.asarray(jax.device_get(buf))[:, 0, :],
                    opt_state=[], step=1)
    with pytest.raises(ValueError, match="does not match the model"):
        restore_checkpoint(path, pipe=pipe)


def test_async_save_round_trips_bit_exact(tmp_path):
    """save_checkpoint_async: same file contents as the sync path, write
    overlapped on a background thread, errors surfaced via wait()."""
    import pytest

    from simple_distributed_machine_learning_tpu.train.checkpoint import (
        save_checkpoint_async,
    )

    key = jax.random.key(0)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wd, od)
    buf = pipe.init_params()
    opt = sgd(0.1, 0.5)
    state = opt.init(buf)

    path = str(tmp_path / "async.npz")
    h = save_checkpoint_async(path, buf, state, step=7, extra={"epoch": 2})
    h.wait()
    assert h.done and os.path.exists(path)
    ck = restore_checkpoint(path, pipe=pipe, opt_treedef_like=state)
    assert ck["step"] == 7 and ck["extra"]["epoch"] == 2
    np.testing.assert_array_equal(np.asarray(jax.device_get(buf)),
                                  np.asarray(jax.device_get(ck["params"])))

    # a failing write must raise from wait(), not vanish on the thread
    bad = save_checkpoint_async(str(tmp_path / "nodir" / ("x" * 300) / "y.npz"),
                                buf, state, step=0)
    with pytest.raises(BaseException):
        bad.wait()


def test_trainer_async_checkpoint_resumes(tmp_path):
    """Trainer(async_checkpoint=True): the per-epoch save lands on disk and
    a fresh Trainer auto-resumes from it."""
    from simple_distributed_machine_learning_tpu.data.mnist import Dataset
    from simple_distributed_machine_learning_tpu.train.trainer import (
        TrainConfig,
        Trainer,
    )

    rng = np.random.RandomState(0)
    ds = Dataset(rng.randn(120, 12).astype(np.float32),
                 rng.randint(0, 10, 120))
    key = jax.random.key(0)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wd, od)
    cfg = TrainConfig(epochs=2, batch_size=30, checkpoint_dir=str(tmp_path),
                      async_checkpoint=True, print_throughput=False)
    tr = Trainer(pipe, ds, ds, cfg)
    tr.fit()
    assert os.path.exists(str(tmp_path / "state.npz"))

    pipe2 = Pipeline(stages, mesh, wd, od)
    tr2 = Trainer(pipe2, ds, ds, cfg)
    assert tr2.start_epoch == 3
    np.testing.assert_array_equal(np.asarray(jax.device_get(tr.buf)),
                                  np.asarray(jax.device_get(tr2.buf)))


def test_resume_scalar_opt_state_on_multidevice_mesh():
    """Scalar optimizer-state leaves (a schedule's step counter, AdamW's
    step) must come back PLACEABLE after restore: committing them to the
    single device opt.init happened to use makes the first jitted step
    reject the mixed placement against the mesh-sharded buffer (caught by
    driving CLI resume; regression for train/checkpoint.py::_place)."""
    from simple_distributed_machine_learning_tpu.train import schedules
    from simple_distributed_machine_learning_tpu.train.checkpoint import (
        save_checkpoint,
    )
    from simple_distributed_machine_learning_tpu.train.optimizer import adamw

    key = jax.random.key(0)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    x = jax.random.normal(key, (8, 12))
    y = jax.random.randint(key, (8,), 0, 10)

    for opt in (sgd(schedules.cosine(0.1, 50), 0.5), adamw(1e-3)):
        pipe = Pipeline(stages, mesh, wd, od)
        buf = pipe.init_params()
        state = opt.init(buf)
        step = make_train_step(pipe, opt)
        buf, state, _ = step(buf, state, x, y, key)
        import tempfile
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "s.npz")
            save_checkpoint(path, buf, state, step=1)
            ck = restore_checkpoint(path, pipe=pipe,
                                    opt_treedef_like=opt.init(
                                        pipe.init_params()))
            step2 = make_train_step(pipe, opt)
            b2, s2, loss = step2(ck["params"], ck["opt_state"], x, y, key)
            assert np.isfinite(float(loss))
