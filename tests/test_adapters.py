"""Multi-tenant LoRA serving (serve/adapters + models/lora): parity, store
invariants, recovery, isolation, routing.

The load-bearing property mirrors the serving suite's: per-tenant adapters
are a RESIDENCY optimization, not a math change — for every request naming
adapter ``t``, the engine's batched bank-row apply is bit-exact vs decoding
that request alone through a model whose weights were merged offline
(``lora.merge_adapter``), across greedy AND sampled streams, mixed tenants
sharing one tick, paged f32 and int8 caches, preemption, tick-boundary
hot-swap and a crash-restart.  Plus the AdapterStore invariants (row 0 is
the zero-delta base and never allocated, refcounted rows never evicted
while referenced, version bumps orphan stale rows and prefix namespaces),
the journal grammar (``adp`` rides submit records; pre-adapter journals
recover as base), adapter-aware fleet routing, the pinned
``hot-adapter-churn`` scenario, and the analyzer parity pin
(``predict_adapter_bytes`` == live store == metrics gauge, exactly).
"""

import dataclasses
import os

import jax
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.models import lora
from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    make_cached_decoder,
    make_gpt_stages,
)
from simple_distributed_machine_learning_tpu.resilience import faults
from simple_distributed_machine_learning_tpu.resilience.scenarios import (
    VirtualClock,
    run_scenario,
)
from simple_distributed_machine_learning_tpu.serve import (
    InferenceEngine,
    RequestJournal,
    ServeFleet,
    ServeMetrics,
    ServeSupervisor,
    engine_factory,
)
from simple_distributed_machine_learning_tpu.serve.adapters import (
    AdapterStore,
    adapter_namespace,
)
from simple_distributed_machine_learning_tpu.serve.journal import (
    read_journal,
    recover_state,
)

CFG = GPTConfig(vocab=32, seq_len=48, d_model=32, n_heads=2, n_layers=2)
_STAGES = None


def _model():
    global _STAGES
    if _STAGES is None:
        _STAGES = make_gpt_stages(jax.random.key(0), CFG, 2)[0]
    return _STAGES, [s.params for s in _STAGES]


def _solo(stages, params, prompt, n_new, seed, temperature=0.0, top_k=None,
          top_p=None):
    dec = make_cached_decoder(stages, CFG, len(prompt), n_new,
                              temperature=temperature, top_k=top_k,
                              top_p=top_p)
    out = dec(params, np.asarray(prompt, np.int32)[None],
              jax.random.key(seed))
    return np.asarray(out)[0, len(prompt):]


def _prompt(n, seed):
    return np.asarray(
        jax.random.randint(jax.random.key(seed), (n,), 0, CFG.vocab),
        np.int32)


def _adapter(seed, rank=2):
    """A NON-TRIVIAL adapter: ``init_lora_adapter`` zeroes B (a fresh
    adapter is the base model), so parity against merged weights would be
    vacuous — perturb B so the delta actually bends the logits."""
    w = dict(lora.init_lora_adapter(jax.random.key(seed), CFG, rank))
    kq, kv = jax.random.split(jax.random.key(seed + 9000))
    w["bq"] = 0.05 * jax.random.normal(kq, w["bq"].shape, w["bq"].dtype)
    w["bv"] = 0.05 * jax.random.normal(kv, w["bv"].shape, w["bv"].dtype)
    return w


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.uninstall()
    yield
    faults.uninstall()


# ---------------------------------------------------------------------------
# AdapterStore: registration, versioning, residency


def test_store_rejects_bad_names_and_shapes():
    store = AdapterStore(CFG, 2, 2)
    with pytest.raises(ValueError):
        store.register("", _adapter(1))
    with pytest.raises(ValueError):
        store.register("a\x00b", _adapter(1))
    with pytest.raises(ValueError):
        store.register("t1", lora.init_lora_adapter(
            jax.random.key(0), CFG, 4))        # rank mismatch
    bad = dict(_adapter(1))
    del bad["bv"]
    with pytest.raises(ValueError):
        store.register("t1", bad)
    assert store.names() == ()


def test_namespaces_version_qualified_and_base_empty():
    """The prefix-cache namespace carries the registration VERSION, so a
    hot-swap orphans the old version's cached blocks; base (None) is the
    pool's pre-adapter empty namespace, so base requests keep sharing
    prefixes with every non-adapter engine ever journaled."""
    store = AdapterStore(CFG, 2, 2)
    assert store.namespace_of(None) == b""
    store.register("t1", _adapter(1))
    ns0 = store.namespace_of("t1")
    assert ns0 == adapter_namespace("t1@0") != b""
    store.register("t1", _adapter(2))          # hot-swap: version bump
    assert store.namespace_of("t1") == adapter_namespace("t1@1") != ns0


def test_residency_refcount_eviction_and_release_guards():
    """n_slots+1 sizing: row 0 is the pinned zero-delta base; referenced
    rows are never evicted; a zero-ref resident row IS evicted when a
    third tenant needs the bank; release(0) is the base no-op and a
    double release raises."""
    store = AdapterStore(CFG, 2, 2)            # rows 1..2 usable
    for k in (1, 2, 3):
        store.register(f"t{k}", _adapter(k))
    r1 = store.retain("t1")
    r2 = store.retain("t2")
    assert {r1, r2} == {1, 2} and store.swaps_total == 2
    assert store.is_resident("t1") and store.row_of("t1") == r1
    store.release(r1)                          # t1 stays resident (warm)...
    assert store.is_resident("t1")
    r3 = store.retain("t3")                    # ...until t3 needs the row
    assert r3 == r1 and not store.is_resident("t1")
    assert store.swaps_total == 3
    assert store.retain("t3") == r3 and store.swaps_total == 3  # no re-upload
    store.release(0)                           # base rows carry no refs
    store.release(r3)
    store.release(r3)
    with pytest.raises(RuntimeError):
        store.release(r3)
    store.release(r2)


def test_hot_swap_keeps_referenced_row_until_released():
    """Re-registering a live tenant must not clobber the row an in-flight
    request is decoding against: the old version's row stays pinned, the
    next retain uploads the new version into a DIFFERENT row."""
    store = AdapterStore(CFG, 2, 2)
    store.register("t1", _adapter(1))
    old_row = store.retain("t1")
    store.register("t1", _adapter(2))          # swap while referenced
    assert not store.is_resident("t1")         # current version not uploaded
    new_row = store.retain("t1")
    assert new_row != old_row
    store.release(old_row)
    store.release(new_row)


def test_shared_host_survives_store_rebuild():
    """The crash-recovery contract: a rebuilt store constructed over the
    SAME host dict (supervisor's engine factory) serves every previously
    registered tenant with its version accounting intact."""
    host = {}
    s1 = AdapterStore(CFG, 2, 2, host=host)
    s1.register("t1", _adapter(1))
    s1.register("t1", _adapter(2))
    s2 = AdapterStore(CFG, 2, 2, host=host)    # the post-crash rebuild
    assert s2.is_registered("t1") and not s2.is_resident("t1")
    assert s2.retain("t1") > 0
    assert s2.stats()["store"] != s1.stats()["store"]


# ---------------------------------------------------------------------------
# THE parity anchor: engine streams bit-exact vs offline-merged weights


def test_mixed_tenant_streams_match_merged_dense_solo():
    """Base + two tenants interleaved through 2 slots (so ticks mix
    bank rows and admissions happen mid-flight), greedy AND sampled:
    every stream is bit-exact vs a solo decode through weights merged
    offline with ``lora.merge_adapter`` — the adapter path is a residency
    optimization, not a math change."""
    stages, params = _model()
    w1, w2 = _adapter(1), _adapter(2)
    eng = InferenceEngine(stages, CFG, n_slots=2,
                          adapters=AdapterStore(CFG, 2, 2))
    eng.register_adapter("t1", w1)
    eng.register_adapter("t2", w2)
    specs = [
        dict(prompt=_prompt(5, 1), max_new_tokens=7, seed=11),
        dict(prompt=_prompt(9, 2), max_new_tokens=5, seed=12, adapter="t1",
             temperature=0.8, top_k=5),
        dict(prompt=_prompt(3, 3), max_new_tokens=8, seed=13, adapter="t2"),
        dict(prompt=_prompt(7, 4), max_new_tokens=6, seed=14, adapter="t1"),
        dict(prompt=_prompt(4, 5), max_new_tokens=6, seed=15, adapter="t2",
             temperature=1.1, top_p=0.9),
    ]
    handles = [eng.submit(**specs[i]) for i in range(3)]
    for _ in range(3):
        eng.step()                             # mid-flight admissions
    handles += [eng.submit(**s) for s in specs[3:]]
    eng.drain()
    merged = {None: params,
              "t1": lora.merge_adapter(params, w1),
              "t2": lora.merge_adapter(params, w2)}
    # non-vacuous: the perturbed adapters actually change the weights
    assert any(not np.allclose(a, b) for a, b in
               zip(jax.tree.leaves(merged["t1"]), jax.tree.leaves(params)))
    for h, s in zip(handles, specs):
        np.testing.assert_array_equal(
            h.tokens, _solo(stages, merged[s.get("adapter")], s["prompt"],
                            s["max_new_tokens"], s["seed"],
                            temperature=s.get("temperature", 0.0),
                            top_k=s.get("top_k"), top_p=s.get("top_p")))


def test_adapter_parity_int8_cache():
    """Same anchor under the quantized KV cache: adapter engine vs an
    engine built from the merged weights, identical layout and cache
    dtype — engine-to-engine so quantization error cancels exactly."""
    stages, params = _model()
    w1 = _adapter(6)
    eng = InferenceEngine(stages, CFG, n_slots=2, cache_dtype="int8",
                          adapters=AdapterStore(CFG, 2, 2))
    eng.register_adapter("t1", w1)
    merged_stages = [dataclasses.replace(s, params=p) for s, p in
                     zip(stages, lora.merge_adapter(params, w1))]
    ref = InferenceEngine(merged_stages, CFG, n_slots=2, cache_dtype="int8")
    specs = [dict(prompt=_prompt(6, 21), max_new_tokens=6, seed=31),
             dict(prompt=_prompt(4, 22), max_new_tokens=5, seed=32,
                  temperature=0.9, top_k=4)]
    got = [eng.submit(**s, adapter="t1") for s in specs]
    want = [ref.submit(**s) for s in specs]
    eng.drain()
    ref.drain()
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g.tokens, w.tokens)


def test_adapter_parity_survives_preemption():
    """Preempt a tenant's request mid-decode: it re-boards (possibly into
    a different slot and bank row) and its full stream still matches the
    merged solo."""
    stages, params = _model()
    w1 = _adapter(7)
    eng = InferenceEngine(stages, CFG, n_slots=2,
                          adapters=AdapterStore(CFG, 2, 2))
    eng.register_adapter("t1", w1)
    r1 = eng.submit(_prompt(5, 31), max_new_tokens=8, seed=41, adapter="t1")
    r2 = eng.submit(_prompt(7, 32), max_new_tokens=6, seed=42)
    for _ in range(3):
        eng.step()
    eng.preempt(r1.rid)
    eng.drain()
    assert r1.n_preempted == 1
    np.testing.assert_array_equal(
        r1.tokens, _solo(stages, lora.merge_adapter(params, w1),
                         r1.prompt, 8, 41))
    np.testing.assert_array_equal(
        r2.tokens, _solo(stages, params, r2.prompt, 6, 42))


def test_hot_swap_takes_effect_next_admission_not_inflight():
    """Tick-boundary hot-swap semantics: a request decoding when its
    tenant is re-registered finishes on the OLD weights (its retained
    row); a request admitted after the swap decodes the NEW weights —
    both bit-exact vs their respective merged solos."""
    stages, params = _model()
    old_w, new_w = _adapter(8), _adapter(9)
    eng = InferenceEngine(stages, CFG, n_slots=2,
                          adapters=AdapterStore(CFG, 2, 2))
    eng.register_adapter("t1", old_w)
    r_old = eng.submit(_prompt(5, 33), max_new_tokens=8, seed=51,
                       adapter="t1")
    for _ in range(3):
        eng.step()
    eng.register_adapter("t1", new_w)          # swap under load
    r_new = eng.submit(_prompt(4, 34), max_new_tokens=6, seed=52,
                       adapter="t1")
    eng.drain()
    np.testing.assert_array_equal(
        r_old.tokens, _solo(stages, lora.merge_adapter(params, old_w),
                            r_old.prompt, 8, 51))
    np.testing.assert_array_equal(
        r_new.tokens, _solo(stages, lora.merge_adapter(params, new_w),
                            r_new.prompt, 6, 52))


# ---------------------------------------------------------------------------
# journal grammar + crash recovery


def test_journal_adp_roundtrip_and_pre_adapter_journals_read_as_base(
        tmp_path):
    """``adp`` rides submit records only when a tenant is named; a
    journal written BEFORE the adapter subsystem existed (no ``adp`` key
    anywhere) recovers every request onto the base model — the regression
    pin for old journals."""
    path = str(tmp_path / "j.jsonl")
    j = RequestJournal(path, sync=False)
    j.log_submit(rid=0, prompt=[1, 2, 3], max_new=4, temp=0.0, top_k=None,
                 top_p=None, eos=None, seed=7, cls=None, prio=0,
                 ttft_dl=None, dl=None, t=0.0)          # pre-adapter shape
    j.log_submit(rid=1, prompt=[4, 5], max_new=3, temp=0.0, top_k=None,
                 top_p=None, eos=None, seed=8, cls=None, prio=0,
                 ttft_dl=None, dl=None, t=0.0, adapter="t9")
    j.close()
    with open(path) as f:
        base_line, tenant_line = f.read().splitlines()
    assert "adp" not in base_line and '"adp":"t9"' in tenant_line
    events, valid = read_journal(path)
    state = recover_state(events[:valid])
    assert state[0].adapter is None
    assert state[1].adapter == "t9"


def test_crash_recovery_readmits_onto_correct_adapter(tmp_path):
    """An engine crash mid-flight with mixed tenants: the rebuilt engine
    (fresh AdapterStore over the supervisor's shared host dict) re-admits
    every journaled request onto ITS adapter, and all streams equal the
    uninterrupted run's — which equal the merged solos."""
    stages, params = _model()
    w1, w2 = _adapter(1), _adapter(2)
    specs = [
        dict(prompt=_prompt(5, 1), max_new_tokens=8, seed=11, adapter="t1"),
        dict(prompt=_prompt(9, 2), max_new_tokens=6, seed=12,
             temperature=0.8, top_k=5),
        dict(prompt=_prompt(3, 3), max_new_tokens=7, seed=13, adapter="t2"),
        dict(prompt=_prompt(7, 4), max_new_tokens=5, seed=14, adapter="t1",
             temperature=1.1, top_k=4),
    ]

    def run(name, chaos):
        if chaos:
            faults.install(faults.FaultPlan.parse(chaos))
        sup = ServeSupervisor(
            engine_factory(stages, CFG, n_slots=2, block_size=4,
                           prefill_chunk=3, adapter_rank=2),
            str(tmp_path / name))
        sup.register_adapter("t1", w1)
        sup.register_adapter("t2", w2)
        handles = [sup.submit(**s) for s in specs]
        sup.drain()
        sup.close()
        faults.uninstall()
        return sup, [list(h.tokens) for h in handles]

    _, base = run("base.jsonl", None)
    sup, crashed = run("crash.jsonl", "engine-crash@serve.tick=3")
    assert sup.restarts == 1
    assert crashed == base
    merged = {None: params, "t1": lora.merge_adapter(params, w1),
              "t2": lora.merge_adapter(params, w2)}
    for toks, s in zip(crashed, specs):
        np.testing.assert_array_equal(
            toks, _solo(stages, merged[s.get("adapter")], s["prompt"],
                        s["max_new_tokens"], s["seed"],
                        temperature=s.get("temperature", 0.0),
                        top_k=s.get("top_k")))


def test_unknown_adapter_rejected_before_journaling(tmp_path):
    """An unregistered tenant fails at the admission gate BEFORE the
    submit record is journaled — a crash-restart must not replay a
    request the engine can never serve."""
    stages, _ = _model()
    sup = ServeSupervisor(
        engine_factory(stages, CFG, n_slots=2, block_size=4,
                       prefill_chunk=3, adapter_rank=2),
        str(tmp_path / "rej.jsonl"))
    with pytest.raises(KeyError):
        sup.submit(_prompt(4, 1), max_new_tokens=3, seed=1, adapter="nope")
    sup.close()
    events, valid = read_journal(str(tmp_path / "rej.jsonl"))
    assert [e for e in events[:valid] if e.get("ev") == "submit"] == []


# ---------------------------------------------------------------------------
# prefix-cache isolation


def test_prefix_cache_isolated_per_tenant_and_orphaned_on_swap():
    """The SAME prompt served under t1 must not prefix-hit for t2 or for
    base (the K/V under a different delta is simply wrong), and a
    hot-swap of t1 orphans its old version's blocks."""
    stages, _ = _model()
    store = AdapterStore(CFG, 2, 2)
    eng = InferenceEngine(stages, CFG, n_slots=2, block_size=4,
                          prefill_chunk=None, adapters=store)
    eng.register_adapter("t1", _adapter(1))
    eng.register_adapter("t2", _adapter(2))
    p = _prompt(8, 71)
    eng.submit(p, max_new_tokens=2, seed=1, adapter="t1")
    eng.drain()
    ns1 = store.namespace_of("t1")
    assert eng.pool.shared_prefix_len(p, ns1) >= 4     # t1 re-use works
    assert eng.pool.shared_prefix_len(p, store.namespace_of("t2")) == 0
    assert eng.pool.shared_prefix_len(p, b"") == 0     # base isolated too
    eng.register_adapter("t1", _adapter(3))            # hot-swap
    assert eng.pool.shared_prefix_len(p, store.namespace_of("t1")) == 0
    assert eng.pool.shared_prefix_len(p, ns1) >= 4     # old ns now orphaned


# ---------------------------------------------------------------------------
# fleet routing + pinned scenario


def test_affinity_routes_to_adapter_resident_replica(tmp_path):
    """A fresh prompt (no prefix signal) for tenant t1 routes to the
    replica already holding t1's bank row, not the round-robin choice —
    and the adapter-affinity counter records the hit."""
    stages, _ = _model()
    metrics = ServeMetrics()
    fleet = ServeFleet(
        engine_factory(stages, CFG, n_slots=2, block_size=4,
                       prefill_chunk=3, adapter_rank=2,
                       metrics=metrics),
        os.path.join(str(tmp_path), "aff"), n_replicas=2,
        journal_sync=False, metrics=metrics, clock=VirtualClock(0.001))
    fleet.register_adapter("t1", _adapter(1))
    h0 = fleet.submit(_prompt(8, 81), max_new_tokens=2, seed=1,
                      adapter="t1")
    fleet.drain()                    # t1 now resident on h0's home only
    h1 = fleet.submit(_prompt(6, 82), max_new_tokens=2, seed=2,
                      adapter="t1")  # fresh prompt: no prefix overlap
    assert fleet._home[h1.rid] == fleet._home[h0.rid]
    fleet.drain()
    fleet.close()
    assert int(metrics.route_adapter_hits.value) >= 1
    assert int(metrics.adapter_swaps.value) == 1       # one upload, reused


def test_hot_adapter_churn_affinity_beats_round_robin_pinned():
    """The hot-adapter-churn scenario on both routing policies, exact
    pinned numbers: affinity keeps each tenant's bank row warm on its
    home replica (3 uploads — the min_adapter_swaps gate exactly, all
    forced by the tick-6 hot-swap) while round-robin re-uploads banks
    across the fleet (7) and never scores an adapter-affinity hit."""
    stages, _ = _model()
    aff = run_scenario("hot-adapter-churn", stages, CFG)
    rr = run_scenario("hot-adapter-churn", stages, CFG, route="round-robin")
    assert aff["completed"] == rr["completed"] == 18
    assert aff["slo_ok"] is True
    assert aff["adapters"]["rank"] == 2
    assert aff["adapters"]["tenants"] == ["tenant-a", "tenant-b"]
    assert aff["adapters"]["swaps"] == 3
    assert aff["adapters"]["adapter_affinity_hits"] == 15
    assert rr["adapters"]["swaps"] == 7
    assert rr["adapters"]["adapter_affinity_hits"] == 0
    assert aff["adapters"]["swaps"] < rr["adapters"]["swaps"]


# ---------------------------------------------------------------------------
# metrics + analyzer parity


def test_metrics_sum_swaps_across_stores():
    """A fleet's replicas share ONE ServeMetrics: the lifetime->delta
    swap accounting is keyed per store, so two stores' counters SUM
    instead of ratcheting to the max — and a repeated report of the same
    lifetime value adds nothing."""
    m = ServeMetrics()
    s1 = {"resident_bytes": 2048, "swaps_total": 2, "n_resident": 1,
          "n_rows": 3, "rank": 2, "store": 101}
    s2 = dict(s1, swaps_total=3, store=102)
    m.on_tick(0, 0, 2, adapter_stats=s1)
    m.on_tick(0, 0, 2, adapter_stats=s2)
    assert int(m.adapter_swaps.value) == 5
    m.on_tick(0, 0, 2, adapter_stats=s1)               # no new swaps
    assert int(m.adapter_swaps.value) == 5
    m.on_tick(0, 0, 2, adapter_stats=dict(s1, swaps_total=4))
    assert int(m.adapter_swaps.value) == 7


def test_analyzer_predicts_live_adapter_bytes_exactly():
    """The acceptance pin: ``predict_adapter_bytes`` over the live
    engine's spec equals the store's own accounting equals the exported
    gauge — one formula (lora.bank_bytes), zero drift — and the engine's
    exact programs lint clean with adapters on."""
    from simple_distributed_machine_learning_tpu.analysis.programs import (
        engine_spec,
        lint_engine,
        predict_adapter_bytes,
    )
    stages, _ = _model()
    metrics = ServeMetrics()
    store = AdapterStore(CFG, 2, 2)
    eng = InferenceEngine(stages, CFG, n_slots=2, adapters=store,
                          metrics=metrics)
    eng.register_adapter("t1", _adapter(1))
    eng.submit(_prompt(5, 91), max_new_tokens=3, seed=1, adapter="t1")
    eng.drain()
    predicted = predict_adapter_bytes(engine_spec(eng))
    assert predicted == store.resident_bytes > 0
    assert predicted == int(metrics.adapter_resident_bytes.value)
    assert predicted == lora.bank_bytes(3, CFG.n_layers, CFG.d_model, 2)
    assert lint_engine(eng).ok()
