"""Bounded model checking of the serve fleet protocol (ISSUE 18).

Pins the load-bearing claims:

- **Statespace corners** — hash-dedup soundness (a diamond's join state is
  explored once), depth-bound honesty (verdicts say "proved to depth N",
  never a bare "proved"; a state cap proves nothing), byte-identical
  reports across runs (sorted labels, no clock, no RNG).
- **The proof** — the clean 2-pool fleet model proves no-double-serve /
  no-lost-request / refcount-conservation / boarding-gate to depth >= 8
  with a crash and a handoff in budget, in well under the 60s bar.
- **Seeded defects find real counterexamples** — each defect knob
  (dropped tombstone, legacy tombstone-then-copy order, skipped shed
  refund, ungated boarding) yields a `protocol.*` ERROR whose exported
  FaultPlan parses, or is honestly marked model-only (whole-host crash).
- **Abstract-recovery fidelity** — ``abstract_recover`` folds REAL journal
  records (including tick-less / why-less OLD-grammar journals) to the
  same per-rid (state, n_tokens) picture ``recover_state`` rebuilds.
- **Journal-grammar lint** — every event kind a serve/ writer emits has a
  dispatching reader; a seeded writer emitting an unread kind is an ERROR.
- **The fix, drilled on the real fleet** — the pinned
  ``replica-kill@fleet.handoff`` drill (the adopt/seal race that lost
  requests under the old handoff order) completes exactly-once on the
  shipped fleet.
"""

import os
import subprocess
import sys
import time

import pytest

from simple_distributed_machine_learning_tpu.analysis.protocol import (
    CLEAN,
    DROPPED_TOMBSTONE,
    INVARIANTS,
    LEGACY_ORDER,
    SKIPPED_REFUND,
    UNGATED_BOARDING,
    abstract_recover,
    check_protocol,
    export_fault_plan,
    load_drill,
    render_drill,
)
from simple_distributed_machine_learning_tpu.analysis.statespace import (
    Violation,
    explore,
)
from simple_distributed_machine_learning_tpu.resilience import faults

DRILLS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "data", "protocol_drills")


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.uninstall()
    yield
    faults.uninstall()


# ---- 1. statespace corners ------------------------------------------------

def test_dedup_diamond_explores_join_once():
    """Two paths into one state: its successors run once, and the second
    arrival is a dedup hit, not a new state. States are values, so the
    join genuinely collides."""
    def trans(s):
        if s == "i":
            return [(("a",), "A"), (("b",), "B")]
        if s in ("A", "B"):
            return [(("join",), "T")]
        if s == "T":
            return [(("tail",), "Z")]
        return []
    res = explore("i", trans, {}, depth=10)
    assert res.states == 5                 # i, A, B, T, Z — not 6
    assert res.dedup_hits == 1             # the second edge into T
    assert res.transitions == 5
    assert res.complete


def test_ghost_state_differences_are_not_deduped():
    """Histories that differ in observable bookkeeping are different
    states: dedup must key the whole value, or a violated counter could
    hide behind a structurally-similar state."""
    def trans(s):
        path, count = s
        if path == "i":
            return [(("cheap",), ("T", count)), (("costly",), ("T", count + 1))]
        return []
    res = explore(("i", 0), trans, {}, depth=3)
    assert res.states == 3 and res.dedup_hits == 0


def test_depth_bound_honesty():
    """A cut frontier is reported: "proved to depth N ... deeper schedules
    unexplored". An exhausted space still carries its bound. Neither
    phrasing ever degenerates to a bare "proved"."""
    def unbounded(s):
        return [(("inc",), s + 1)]
    res = explore(0, unbounded, {}, depth=4)
    v = res.verdict(["inv"])
    assert "proved to depth 4" in v and "deeper schedules unexplored" in v
    assert not res.complete

    def finite(s):
        return [(("inc",), s + 1)] if s < 2 else []
    res2 = explore(0, finite, {}, depth=10)
    v2 = res2.verdict(["inv"])
    assert res2.complete
    assert "proved to depth 10" in v2 and "state space exhausted" in v2

    for verdict in (v, v2):
        assert "proved" not in verdict.replace("proved to depth", "")


def test_state_cap_is_never_a_proof():
    def unbounded(s):
        return [(("inc",), s + 1)]
    res = explore(0, unbounded, {}, depth=100, max_states=5)
    assert res.truncated and not res.complete
    v = res.verdict(["inv"])
    assert "inconclusive" in v and "nothing proved" in v
    assert "proved to depth" not in v


def test_bfs_counterexample_is_shortest():
    """BFS order guarantees the first witness of a violation is minimal —
    the counterexample a human debugs should be the 2-step one, not a
    12-step interleaving that happens to be found first."""
    def trans(s):
        return [(("inc",), s + 1), (("double",), s * 2)] if s < 40 else []
    res = explore(1, trans, {"small": lambda s: None if s < 4 else f"s={s}"},
                  depth=12)
    [v] = res.violations
    assert v.depth == 2 and v.trace == (("double",), ("double",))
    assert "violated at depth 2" in v.render()


def test_reports_are_byte_identical_across_runs():
    a = check_protocol(DROPPED_TOMBSTONE)
    b = check_protocol(DROPPED_TOMBSTONE)
    assert a.verdict == b.verdict
    assert a.format(costs=False) == b.format(costs=False)
    assert ([v.render() for v in a.exploration.violations]
            == [v.render() for v in b.exploration.violations])


# ---- 2. the proof ---------------------------------------------------------

def test_clean_model_proves_to_depth_8_fast():
    """The acceptance bar: the 2-pool fleet model (1 crash + 1 handoff in
    budget) proves every invariant to depth >= 8 on CPU in < 60s."""
    assert CLEAN.depth >= 8
    assert CLEAN.n_prefill >= 1 and CLEAN.n_decode >= 1
    assert CLEAN.crash_budget >= 1 and CLEAN.handoff_budget >= 1
    t0 = time.monotonic()
    report = check_protocol(CLEAN)
    assert time.monotonic() - t0 < 60
    assert report.findings == []
    assert report.ok(fail_on="warning")
    assert report.verdict.startswith(f"proved to depth {CLEAN.depth}")
    for inv in INVARIANTS:
        assert inv in report.verdict


# ---- 3. seeded defects --> exported counterexamples -----------------------

@pytest.mark.parametrize("cfg,invariant", [
    (DROPPED_TOMBSTONE, "double-serve"),
    (LEGACY_ORDER, "lost-request"),
    (SKIPPED_REFUND, "refcount"),
    (UNGATED_BOARDING, "boarding-gate"),
], ids=["dropped-tombstone", "legacy-order", "skipped-refund",
        "ungated-boarding"])
def test_defect_config_yields_counterexample(cfg, invariant):
    report = check_protocol(cfg)
    assert not report.ok(fail_on="error")
    assert f"protocol.{invariant}" in {f.rule for f in report.findings}
    v = next(v for v in report.exploration.violations
             if v.invariant == invariant)
    assert v.trace and v.depth == len(v.trace)
    plan, note = export_fault_plan(v)
    if plan is not None:
        # every exported schedule must be installable as-is
        parsed = faults.FaultPlan.parse(plan)
        assert parsed.specs
    else:
        assert "model-only" in note or "no schedulable" in note or \
            "no crash" in note


def test_exported_drill_file_matches_model():
    """The checked-in .chaos drill IS the model's export — regenerating it
    from the dropped-tombstone counterexample reproduces the committed
    schedule line byte-for-byte (drill_coverage counts this file, so it
    must never drift from what the checker would emit)."""
    report = check_protocol(DROPPED_TOMBSTONE)
    v = next(v for v in report.exploration.violations
             if v.invariant == "double-serve")
    plan, _ = export_fault_plan(v)
    committed = load_drill(
        os.path.join(DRILLS, "dropped_handoff_double_serve.chaos"))
    assert committed == plan


def test_render_load_drill_round_trip(tmp_path):
    report = check_protocol(DROPPED_TOMBSTONE)
    v = report.exploration.violations[0]
    text = render_drill(v, DROPPED_TOMBSTONE)
    p = tmp_path / "x.chaos"
    p.write_text(text)
    plan, _ = export_fault_plan(v)
    assert load_drill(str(p)) == plan
    assert v.invariant in text and "model config:" in text


def test_mid_handoff_crash_exports_handoff_site():
    """A crash label carrying the mid-handoff marker maps to the
    ``fleet.handoff`` injection site (the adopt/seal race), and the result
    parses against the real faults grammar."""
    v = Violation(invariant="double-serve", message="m",
                  trace=(("handoff_begin", 0, 0), ("crash", 0, "mid-handoff")),
                  depth=2)
    plan, _ = export_fault_plan(v)
    assert plan == "replica-kill@fleet.handoff,rank=0"
    [spec] = faults.FaultPlan.parse(plan).specs
    assert (spec.kind, spec.site, spec.rank) == \
        ("replica-kill", "fleet.handoff", 0)


def test_host_crash_counterexamples_are_model_only():
    v = Violation(invariant="lost-request", message="m",
                  trace=(("submit_journal", 0), ("crash_host",)), depth=2)
    plan, note = export_fault_plan(v)
    assert plan is None
    assert "whole-host crash" in note        # the note explains WHY


# ---- 4. abstract recovery vs the real fold (old-grammar regression) ------

def _submit(rid, max_new):
    return {"ev": "submit", "rid": rid, "prompt": [1, 2], "max_new": max_new,
            "temp": 0.0, "top_k": None, "top_p": None, "eos": None,
            "seed": 0, "cls": None, "prio": 0, "ttft_dl": None, "dl": None,
            "t": 0.0}


def _tok(rid, tok):
    # deliberately tick-less and time-less: the OLD journal grammar
    return {"ev": "tok", "rid": rid, "tok": tok, "kd": [0, 0, 0, 0]}


def _snap(rid, state, toks, max_new):
    # deliberately why-less: a pre-disaggregation snap record
    ev = _submit(rid, max_new)
    ev.update({"ev": "snap", "state": state, "reason": None,
               "toks": toks, "kd": None, "ftt": None, "dt": None})
    return ev


OLD_GRAMMAR_JOURNAL = [
    _submit(0, 4), _tok(0, 9), _tok(0, 9),
    {"ev": "handoff", "rid": 0},                       # tombstoned: gone
    _submit(1, 3), _tok(1, 5),                         # in flight, 1/3
    _snap(2, "queued", [5, 6], 2),                     # not-acked: promotes
    _submit(3, 4), _tok(3, 7),
    {"ev": "done", "rid": 3, "reason": "eos"},         # acknowledged done
    {"ev": "handoff", "rid": 4},
    _snap(4, "queued", [8], 4),                        # adopted BACK: lives
    _submit(5, 4),
    {"ev": "shed", "rid": 5, "reason": "overload"},
    {"ev": "restart", "n": 1},                         # observability-only
]


def test_abstract_recover_matches_recover_state_on_old_journals():
    """The model's fold and the real fold agree rid-for-rid on a journal
    written in the OLD grammar (no tick fields, no snap ``why``) — the
    regression that pins the abstract model to what recovery actually
    does, including the tombstone drop, the snap resurrection after a
    handoff-back, and the journaled-but-not-acked DONE promotion."""
    from simple_distributed_machine_learning_tpu.serve.journal import (
        recover_state,
    )
    real = recover_state(OLD_GRAMMAR_JOURNAL)
    model = abstract_recover(OLD_GRAMMAR_JOURNAL)
    assert set(real) == set(model) == {1, 2, 3, 4, 5}   # 0 stays tombstoned
    to_model = {"queued": "q", "active": "a", "done": "d", "shed": "s"}
    for rid, r in real.items():
        st, ntok = model[rid]
        assert to_model[r.state] == st, f"rid {rid}"
        assert len(r.tokens) == ntok, f"rid {rid}"
    assert model[2] == ("d", 2)          # the not-acked promotion, both sides
    assert model[4][0] == "q"            # resurrected after its tombstone


# ---- 5. journal-grammar lint ---------------------------------------------

def test_journal_grammar_clean_on_repo():
    from simple_distributed_machine_learning_tpu.analysis.hostlint import (
        lint_journal_grammar,
    )
    assert lint_journal_grammar() == []


def test_journal_grammar_flags_unread_event_kind(tmp_path):
    """A writer emitting a kind no reader dispatches on is an ERROR; adding
    a reader branch for the kind clears it. Keyed on the literal "ev"
    field, so unrelated dict lookups never count as dispatch."""
    from simple_distributed_machine_learning_tpu.analysis.hostlint import (
        lint_journal_grammar,
    )
    w = tmp_path / "writer.py"
    w.write_text('def log_promote(j, rid):\n'
                 '    j.append({"ev": "promote", "rid": rid})\n')
    r = tmp_path / "reader.py"
    r.write_text('def recover(evs):\n'
                 '    for ev in evs:\n'
                 '        kind = ev["ev"]\n'
                 '        if kind == "submit":\n'
                 '            pass\n'
                 '        elif kind in ("tok", "done"):\n'
                 '            pass\n')
    findings = lint_journal_grammar([str(w)], [str(r)], repo=str(tmp_path))
    from simple_distributed_machine_learning_tpu.analysis.report import (
        Severity,
    )
    assert [f.rule for f in findings] == ["journal-grammar.unread-event"]
    assert findings[0].severity is Severity.ERROR
    assert "'promote'" in findings[0].message

    r2 = tmp_path / "reader2.py"
    r2.write_text('def report(evs):\n'
                  '    return [e for e in evs if e.get("ev") == "promote"]\n')
    assert lint_journal_grammar([str(w)], [str(r), str(r2)],
                                repo=str(tmp_path)) == []


def test_serve_protocol_cli_runs_without_jax():
    """--serve-protocol is a pure-stdlib gate: it must run (and prove) with
    jax purged and blocked, exactly like --hostlint — the CI lint job sets
    no backend."""
    prog = (
        "import sys\n"
        "for m in [k for k in sys.modules"
        " if k == 'jax' or k.startswith(('jax.', 'jaxlib'))]:\n"
        "    del sys.modules[m]\n"
        "class B:\n"
        "    def find_spec(self, name, path=None, target=None):\n"
        "        if name == 'jax' or name.startswith(('jax.', 'jaxlib')):\n"
        "            raise ImportError('blocked: ' + name)\n"
        "sys.meta_path.insert(0, B())\n"
        "try:\n"
        "    import jax\n"
        "except ImportError:\n"
        "    pass\n"
        "else:\n"
        "    print('BLOCKER INERT'); sys.exit(3)\n"
        "from simple_distributed_machine_learning_tpu.analysis.__main__ "
        "import main\n"
        "sys.exit(main(['--serve-protocol', '--depth', '6']))\n"
    )
    proc = subprocess.run([sys.executable, "-c", prog],
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))),
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "proved to depth 6" in proc.stdout


# ---- 6. the fix, drilled on the real fleet -------------------------------

def test_drill_coverage_learns_exported_chaos_drills():
    """tests/data/protocol_drills/*.chaos is a coverage source, and the
    new replica-kill@fleet.handoff pair (the adopt/seal race) is fired by
    the committed drill — no gaps fleet-wide."""
    assert faults.drill_coverage() == []


def test_handoff_kill_drill_exactly_once_on_fixed_fleet(tmp_path):
    """Satellite-1 pin: kill the handoff SOURCE between the destination's
    adopt and the source's tombstone seal (the interleaving the old
    tombstone-then-copy order turned into a lost request, and a missing
    live-elsewhere guard turns into a double-serve). The shipped fleet
    must stream the request exactly once."""
    import jax
    import numpy as np

    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )
    from simple_distributed_machine_learning_tpu.serve import (
        ServeFleet,
        engine_factory,
    )
    from simple_distributed_machine_learning_tpu.serve.request import DONE

    plan_text = load_drill(os.path.join(DRILLS, "handoff_kill.chaos"))
    assert plan_text == "replica-kill@fleet.handoff,rank=0"

    cfg = GPTConfig(vocab=32, seq_len=48, d_model=32, n_heads=2, n_layers=2)
    stages = make_gpt_stages(jax.random.key(0), cfg, 2)[0]
    prompt = np.asarray(
        jax.random.randint(jax.random.key(3), (4,), 0, cfg.vocab), np.int32)
    fleet = ServeFleet(
        engine_factory(stages, cfg, n_slots=2, block_size=4,
                       prefill_chunk=3),
        os.path.join(str(tmp_path), "j"), n_replicas=3,
        prefill_replicas=1, journal_sync=False)
    got = []
    h = fleet.submit(prompt, max_new_tokens=4, seed=3,
                     on_token=lambda req, tok: got.append(tok))
    faults.install(faults.FaultPlan.parse(plan_text))
    for _ in range(80):
        fleet.step()
        if h.state == DONE:
            break
    faults.uninstall()
    for _ in range(10):                      # settle: nothing replays after
        fleet.step()
    fleet.close()
    assert h.state == DONE
    assert got == list(h.tokens) and len(got) == 4   # exactly once
    assert fleet.handoffs >= 1 and fleet.replica_losses == 1
