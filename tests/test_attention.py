"""Attention: causal MHA correctness + ring attention == full attention."""

import jax
import jax.numpy as jnp
import numpy as np

from simple_distributed_machine_learning_tpu.parallel.compat import (
    shard_map,
)
import torch

from simple_distributed_machine_learning_tpu.ops.attention import (
    causal_attention,
    mha_init,
    ring_attention,
)


def test_causal_attention_matches_torch_sdpa():
    key = jax.random.key(0)
    b, t, d, h = 2, 8, 16, 4
    params = mha_init(key, d, h)
    x = jax.random.normal(jax.random.key(1), (b, t, d))
    got = causal_attention(params, x, h)

    # torch ground truth with the same weights
    xt = torch.from_numpy(np.asarray(x))
    q = (xt @ torch.from_numpy(np.asarray(params["wq"]))).reshape(b, t, h, d // h).transpose(1, 2)
    k = (xt @ torch.from_numpy(np.asarray(params["wk"]))).reshape(b, t, h, d // h).transpose(1, 2)
    v = (xt @ torch.from_numpy(np.asarray(params["wv"]))).reshape(b, t, h, d // h).transpose(1, 2)
    out = torch.nn.functional.scaled_dot_product_attention(q, k, v, is_causal=True)
    want = (out.transpose(1, 2).reshape(b, t, d)
            @ torch.from_numpy(np.asarray(params["wo"]))).numpy()
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


def test_causality():
    """Future tokens must not influence earlier outputs."""
    key = jax.random.key(2)
    params = mha_init(key, 16, 2)
    x = jax.random.normal(jax.random.key(3), (1, 8, 16))
    y1 = causal_attention(params, x, 2)
    x2 = x.at[:, -1].set(99.0)  # perturb only the last token
    y2 = causal_attention(params, x2, 2)
    np.testing.assert_allclose(np.asarray(y1[:, :-1]), np.asarray(y2[:, :-1]),
                               rtol=1e-5, atol=1e-5)
    assert not np.allclose(np.asarray(y1[:, -1]), np.asarray(y2[:, -1]))


def test_ring_attention_matches_full():
    from jax.sharding import Mesh, PartitionSpec as P

    key = jax.random.key(4)
    b, t, d, h = 2, 32, 16, 4
    n_seq = 4
    params = mha_init(key, d, h)
    x = jax.random.normal(jax.random.key(5), (b, t, d))

    mesh = Mesh(np.array(jax.devices()[:n_seq]), ("seq",))
    ring = jax.jit(shard_map(
        lambda p, xx: ring_attention(p, xx, h, "seq"),
        mesh=mesh, in_specs=(P(), P(None, "seq", None)),
        out_specs=P(None, "seq", None)))
    got = ring(params, x)
    want = causal_attention(params, x, h)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_grads_match_full():
    from jax.sharding import Mesh, PartitionSpec as P

    key = jax.random.key(6)
    b, t, d, h = 1, 16, 8, 2
    params = mha_init(key, d, h)
    x = jax.random.normal(jax.random.key(7), (b, t, d))
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))

    def ring_loss(p, xx):
        f = shard_map(lambda pp, v: ring_attention(pp, v, 2, "seq"),
                          mesh=mesh, in_specs=(P(), P(None, "seq", None)),
                          out_specs=P(None, "seq", None))
        return jnp.sum(f(p, xx) ** 2)

    def full_loss(p, xx):
        return jnp.sum(causal_attention(p, xx, 2) ** 2)

    g_ring = jax.grad(ring_loss)(params, x)
    g_full = jax.grad(full_loss)(params, x)
    for name in ("wq", "wk", "wv", "wo"):
        np.testing.assert_allclose(np.asarray(g_ring[name]),
                                   np.asarray(g_full[name]),
                                   rtol=5e-5, atol=5e-5)
