"""In-process watchdog unit tests (utils/failure.py).

The OS-process integration path (actually SIGKILLing a rank) lives in
tests/test_multiprocess.py::test_dead_peer_aborts_rank0; these cover the
protocol edges cheaply: goodbye-vs-crash disambiguation in both directions
and staleness detection, with an injected fail handler instead of os._exit.
"""

from __future__ import annotations

import socket
import time

from simple_distributed_machine_learning_tpu.utils.failure import (
    HeartbeatWatchdog,
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def _pair(port, **kw):
    fails0, fails1 = [], []
    w0 = HeartbeatWatchdog(0, 2, "localhost", port, fail_handler=fails0.append,
                           **kw).start()
    w1 = HeartbeatWatchdog(1, 2, "localhost", port, fail_handler=fails1.append,
                           **kw).start()
    return w0, w1, fails0, fails1


def test_clean_shutdown_no_spurious_failure():
    """Either side stopping cleanly (goodbye byte) must not trip the other —
    including rank 0 exiting FIRST while rank 1 keeps heartbeating."""
    w0, w1, fails0, fails1 = _pair(_free_port(), interval=0.1, timeout=5.0)
    assert _wait(lambda: w1._client is not None)
    w0.stop()                      # master leaves first
    time.sleep(0.5)                # several heartbeat intervals
    w1.stop()
    assert fails0 == [] and fails1 == []


def test_peer_socket_death_detected():
    """A peer whose socket dies without goodbye is reported on rank 0."""
    w0, w1, fails0, _ = _pair(_free_port(), interval=0.1, timeout=5.0)
    assert _wait(lambda: w1._client is not None)
    w1._client.close()             # simulate a killed process (no goodbye)
    assert _wait(lambda: len(fails0) > 0)
    assert "vanished" in fails0[0]
    w0.stop()


def test_master_death_detected():
    """Rank 0's socket dying without goodbye is reported on the peer."""
    w0, w1, fails0, fails1 = _pair(_free_port(), interval=0.1, timeout=5.0)
    assert _wait(lambda: len(w0._conns) == 1)
    for c in w0._conns:            # kill the server side without goodbye
        c.close()
    try:
        w0._server.close()
    except OSError:
        pass
    assert _wait(lambda: len(fails1) > 0)
    assert "rank 0" in fails1[0]
    w1.stop()


def test_stale_peer_detected():
    """A connected-but-frozen peer (open socket, no heartbeats) trips the
    staleness monitor within ~timeout."""
    port = _free_port()
    fails0 = []
    w0 = HeartbeatWatchdog(0, 2, "localhost", port, interval=0.1, timeout=0.8,
                           fail_handler=fails0.append).start()
    # a raw socket that connects and then goes silent — no watchdog client
    frozen = socket.create_connection(("localhost", port))
    assert _wait(lambda: len(fails0) > 0, timeout=10.0)
    assert "heartbeat" in fails0[0] or "stopped" in fails0[0]
    frozen.close()
    w0.stop()
