"""In-process watchdog unit tests (utils/failure.py).

The OS-process integration path (actually SIGKILLing a rank) lives in
tests/test_multiprocess.py::test_dead_peer_aborts_rank0; these cover the
protocol edges cheaply: goodbye-vs-crash disambiguation in both directions
(including through the spawned monitor subprocess's quit-byte protocol),
staleness detection — natural and via an injected frozen-peer fault
(resilience/faults.py) — the heartbeat port-collision bind fallback, and
the monitor's parent-state logic (surviving a parent re-exec, killing a
SIGSTOPped parent), with an injected fail handler instead of os._exit.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import threading
import time

from simple_distributed_machine_learning_tpu.resilience import faults
from simple_distributed_machine_learning_tpu.utils.failure import (
    EXIT_PEER_LOST,
    HeartbeatWatchdog,
    spawn_watchdog,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def _pair(port, **kw):
    fails0, fails1 = [], []
    w0 = HeartbeatWatchdog(0, 2, "localhost", port, fail_handler=fails0.append,
                           **kw).start()
    w1 = HeartbeatWatchdog(1, 2, "localhost", port, fail_handler=fails1.append,
                           **kw).start()
    return w0, w1, fails0, fails1


def test_clean_shutdown_no_spurious_failure():
    """Either side stopping cleanly (goodbye byte) must not trip the other —
    including rank 0 exiting FIRST while rank 1 keeps heartbeating."""
    w0, w1, fails0, fails1 = _pair(_free_port(), interval=0.1, timeout=5.0)
    assert _wait(lambda: w1._client is not None)
    w0.stop()                      # master leaves first
    time.sleep(0.5)                # several heartbeat intervals
    w1.stop()
    assert fails0 == [] and fails1 == []


def test_peer_socket_death_detected():
    """A peer whose socket dies without goodbye is reported on rank 0."""
    w0, w1, fails0, _ = _pair(_free_port(), interval=0.1, timeout=5.0)
    assert _wait(lambda: w1._client is not None)
    w1._client.close()             # simulate a killed process (no goodbye)
    assert _wait(lambda: len(fails0) > 0)
    assert "vanished" in fails0[0]
    w0.stop()


def test_master_death_detected():
    """Rank 0's socket dying without goodbye is reported on the peer."""
    w0, w1, fails0, fails1 = _pair(_free_port(), interval=0.1, timeout=5.0)
    assert _wait(lambda: len(w0._conns) == 1)
    for c in w0._conns:            # kill the server side without goodbye
        c.close()
    try:
        w0._server.close()
    except OSError:
        pass
    assert _wait(lambda: len(fails1) > 0)
    assert "rank 0" in fails1[0]
    w1.stop()


def test_stale_peer_detected():
    """A connected-but-frozen peer (open socket, no heartbeats) trips the
    staleness monitor within ~timeout."""
    port = _free_port()
    fails0 = []
    w0 = HeartbeatWatchdog(0, 2, "localhost", port, interval=0.1, timeout=0.8,
                           fail_handler=fails0.append).start()
    assert _wait(lambda: w0._server is not None)
    # a raw socket that connects and then goes silent — no watchdog client
    frozen = socket.create_connection(("localhost", port))
    assert _wait(lambda: len(fails0) > 0, timeout=10.0)
    assert "heartbeat" in fails0[0] or "stopped" in fails0[0]
    frozen.close()
    w0.stop()


def test_injected_frozen_peer_fault_trips_staleness():
    """The deterministic frozen-peer drill (resilience/faults.py): rank 1's
    client fires the scheduled fault, keeps its socket open but never
    heartbeats — rank 0's staleness monitor must call it frozen. This is
    the detection half of the frozen-peer recovery path (the supervisor
    handles the restart half; tests/test_resilience.py)."""
    faults.install(faults.FaultPlan.parse(
        "frozen-peer@watchdog.heartbeat,rank=1"))
    try:
        w0, w1, fails0, fails1 = _pair(_free_port(), interval=0.1,
                                       timeout=0.8)
        assert _wait(lambda: len(fails0) > 0, timeout=10.0)
        assert "stopped heartbeating" in fails0[0]
        assert fails1 == []
        w0.stop()
        w1.stop()
    finally:
        faults.uninstall()


def test_heartbeat_port_collision_retries_until_free():
    """The port-collision fallback: rank 0 finds its heartbeat port held by
    another process, retries binding, and the run proceeds normally once
    the holder exits — no unhandled OSError, no spurious abort."""
    port = _free_port()
    # bind WITHOUT listen: w0's bind collides, but clients are refused
    # (not silently accepted by the impostor) and retry on their own
    holder = socket.socket()
    holder.bind(("localhost", port))
    threading.Timer(0.5, holder.close).start()
    w0, w1, fails0, fails1 = _pair(port, interval=0.1, timeout=8.0)
    assert _wait(lambda: w1._client is not None and w0._server is not None)
    w0.stop()
    time.sleep(0.3)
    w1.stop()
    assert fails0 == [] and fails1 == []


def test_heartbeat_port_collision_timeout_fails_loudly():
    """A port held past the timeout fails through _fail with an actionable
    message instead of an OSError lost on a daemon thread."""
    port = _free_port()
    holder = socket.socket()
    holder.bind(("localhost", port))
    fails0: list[str] = []
    w0 = HeartbeatWatchdog(0, 2, "localhost", port, interval=0.1,
                           timeout=0.7, fail_handler=fails0.append).start()
    assert _wait(lambda: len(fails0) > 0, timeout=10.0)
    assert "could not bind heartbeat port" in fails0[0]
    w0.stop()
    holder.close()


# ---------------------------------------------------------------------------
# spawned-monitor subprocess: goodbye-vs-crash + parent-state edge cases


def test_monitor_goodbye_vs_crash_disambiguation():
    """The spawn_watchdog quit-byte protocol end to end: a monitor stopped
    with the goodbye protocol must NOT trip rank 0, while an aborted
    monitor (no goodbye — crash semantics) MUST read as a vanished peer."""
    # clean: handle.stop() sends 'q' first
    port = _free_port()
    fails0: list[str] = []
    w0 = HeartbeatWatchdog(0, 2, "localhost", port, interval=0.2,
                           timeout=15.0, fail_handler=fails0.append).start()
    h = spawn_watchdog(1, 2, "localhost", port, interval=0.2, timeout=15.0)
    assert _wait(lambda: len(w0._conns) == 1, timeout=20.0)
    h.stop()
    time.sleep(0.5)
    assert fails0 == []
    w0.stop()

    # crash: handle.abort() kills without goodbye
    port = _free_port()
    fails0 = []
    w0 = HeartbeatWatchdog(0, 2, "localhost", port, interval=0.2,
                           timeout=15.0, fail_handler=fails0.append).start()
    h = spawn_watchdog(1, 2, "localhost", port, interval=0.2, timeout=15.0)
    assert _wait(lambda: len(w0._conns) == 1, timeout=20.0)
    h.abort()
    assert _wait(lambda: len(fails0) > 0, timeout=20.0)
    assert "vanished" in fails0[0]
    w0.stop()


def _spawn_monitor(parent_pid: int, timeout: float) -> subprocess.Popen:
    """A world-size-1 monitor: no heartbeat protocol, pure parent babysitter
    — exactly the parent-state loop under test."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("PYTHONPATH", None)
    return subprocess.Popen(
        [sys.executable, "-m",
         "simple_distributed_machine_learning_tpu.utils.failure",
         "--rank", "0", "--world-size", "1", "--addr", "localhost",
         "--port", "1", "--interval", "0.1", "--timeout", str(timeout),
         "--parent-pid", str(parent_pid)],
        stdin=subprocess.PIPE, env=env, cwd=REPO)


def test_monitor_survives_parent_reexec():
    """A trainer that re-execs itself (the elastic-restart shape: same pid,
    fresh program) must NOT be killed by its monitor — the pid stays alive
    and running, so the monitor keeps protecting it and exits quietly when
    the parent finally finishes."""
    parent = subprocess.Popen(
        [sys.executable, "-c",
         "import os, sys, time; time.sleep(0.4); "
         "os.execv(sys.executable, [sys.executable, '-c', "
         "'import time; time.sleep(1.2)'])"])
    mon = _spawn_monitor(parent.pid, timeout=0.6)
    # parent re-execs at 0.4s and lives until ~1.6s; a monitor that
    # misread the exec as death/stop would have killed it by 1.2s
    time.sleep(1.2)
    assert parent.poll() is None, "monitor killed a live re-exec'd parent"
    assert mon.poll() is None
    assert parent.wait(timeout=15) == 0      # exits on its own
    assert mon.wait(timeout=15) == 0         # parent gone -> quiet exit
    mon.stdin.close()


def test_monitor_kills_stopped_parent():
    """A SIGSTOPped trainer (frozen from the outside world's view) is
    SIGKILLed once it overstays the timeout, and the monitor exits with
    EXIT_PEER_LOST — the frozen-trainer half of the watchdog design."""
    parent = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(60)"])
    mon = _spawn_monitor(parent.pid, timeout=0.5)
    time.sleep(0.3)                       # let the monitor start watching
    os.kill(parent.pid, signal.SIGSTOP)
    try:
        assert mon.wait(timeout=20) == EXIT_PEER_LOST
        # the parent was SIGKILLed (negative return code = signal)
        assert parent.wait(timeout=10) == -signal.SIGKILL
    finally:
        try:
            os.kill(parent.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        parent.wait()
        mon.stdin.close()
