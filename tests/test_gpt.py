"""Tiny-GPT pipeline (BASELINE config 5): parity, grads, learning."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.data.text import synthetic_tokens
from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    make_gpt_stages,
)
from simple_distributed_machine_learning_tpu.ops.losses import nll_loss
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import (
    Pipeline,
    fused_reference,
)
from simple_distributed_machine_learning_tpu.parallel.staging import (
    pack_stage_params,
)
from simple_distributed_machine_learning_tpu.train.optimizer import sgd
from simple_distributed_machine_learning_tpu.train.step import make_train_step

CFG = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=2)


def _problem(batch):
    key = jax.random.key(0)
    stages, wire_dim, out_shape = make_gpt_stages(key, CFG, 2)
    data = synthetic_tokens(batch, CFG.seq_len, CFG.vocab, seed=1)
    x = jnp.asarray(data.x, jnp.float32)
    y = jnp.asarray(data.y)
    return stages, wire_dim, out_shape, x, y


def test_gpt_pipeline_matches_fused():
    stages, wire_dim, out_shape, x, y = _problem(8)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wire_dim, out_shape, n_microbatches=2)
    buf = pipe.init_params()
    key = jax.random.key(0)

    loss, logp = pipe.loss_and_logits(buf, x, y, key, deterministic=True)
    fused = fused_reference(stages)
    want_logp = fused([s.params for s in stages], x, key, True)
    want_loss = nll_loss(want_logp, y, "mean")  # mean over batch and tokens
    np.testing.assert_allclose(float(loss), float(want_loss), rtol=2e-5,
                               atol=2e-5)
    np.testing.assert_allclose(np.asarray(logp), np.asarray(want_logp),
                               rtol=2e-5, atol=2e-5)


def test_gpt_pipeline_grads_match_fused():
    stages, wire_dim, out_shape, x, y = _problem(4)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wire_dim, out_shape, n_microbatches=1)
    buf = pipe.init_params()
    key = jax.random.key(0)

    grads = jax.grad(lambda b: pipe.loss_and_logits(b, x, y, key, True)[0])(buf)

    fused = fused_reference(stages)

    def fused_loss(ps):
        return nll_loss(fused(ps, x, key, True), y, "mean")

    fg = jax.grad(fused_loss)([s.params for s in stages])
    want, _ = pack_stage_params(fg)
    # grads buffer is [n_stages, n_model=1, n_expert=1, P]; fused pack is
    # [n_stages, P]
    np.testing.assert_allclose(np.asarray(grads)[:, 0, 0], np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_gpt_learns_markov_structure():
    stages, wire_dim, out_shape, x, y = _problem(32)
    mesh = make_mesh(n_stages=2, n_data=2)
    pipe = Pipeline(stages, mesh, wire_dim, out_shape, n_microbatches=2)
    buf = pipe.init_params()
    opt = sgd(0.5, momentum=0.9)
    state = opt.init(buf)
    step = make_train_step(pipe, opt)
    key = jax.random.key(0)
    first = None
    for i in range(30):
        buf, state, loss = step(buf, state, x, y,
                                jax.random.fold_in(key, i))
        if first is None:
            first = float(loss)
    # uniform = ln(32) ~ 3.47; markov structure must be learnable well below
    assert float(loss) < first - 0.5, (first, float(loss))


def test_moe_gpt_pipeline_trains():
    """MoE-GPT (dense top-2 routed experts per block) through the 2-stage
    pipeline: parity with fused, and loss decreases under SGD."""
    cfg = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=2,
                    n_experts=4, moe_top_k=2)
    key = jax.random.key(0)
    stages, wire_dim, out_shape = make_gpt_stages(key, cfg, 2)
    data = synthetic_tokens(16, cfg.seq_len, cfg.vocab, seed=1)
    x = jnp.asarray(data.x, jnp.float32)
    y = jnp.asarray(data.y)

    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wire_dim, out_shape, n_microbatches=2)
    buf = pipe.init_params()

    loss, _ = pipe.loss_and_logits(buf, x, y, key, deterministic=True)
    # the engine's objective = NLL + the Switch aux loss the stages return
    h = x
    aux = 0.0
    for s, st in enumerate(stages):
        k = jax.random.fold_in(key, s)
        out = st.apply(st.params, h.reshape((h.shape[0],) + st.in_shape),
                       k, True)
        # per-sequence routing makes the full-batch aux equal the engine's
        # microbatch-averaged aux (mean over all sequences either way)
        h, a = out
        aux += float(a)
    want = nll_loss(h, y, "mean")
    assert aux > 0.0   # balancing pressure is real, not dropped (ADVICE r1)
    np.testing.assert_allclose(float(loss), float(want) + aux,
                               rtol=2e-5, atol=2e-5)

    opt = sgd(0.3, momentum=0.5)
    opt_state = opt.init(buf)
    step = make_train_step(pipe, opt)
    l0 = None
    for i in range(15):
        buf, opt_state, l = step(buf, opt_state, x, y, jax.random.key(i))
        l0 = float(l) if l0 is None else l0
    assert float(l) < l0


def test_generate_greedy_matches_stepwise_argmax():
    """One-scan greedy decode == manually rolling argmax one token at a
    time (pins causal masking of the not-yet-written buffer tail and the
    read-at-i-1 indexing)."""
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        generate,
        make_gpt_stages,
    )
    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        fused_reference,
    )

    cfg = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=2)
    stages, _, _ = make_gpt_stages(jax.random.key(0), cfg, n_stages=1)
    prompt = jax.random.randint(jax.random.key(1), (2, 6), 0, cfg.vocab)

    out = generate(stages, prompt, n_new=5)
    assert out.shape == (2, 11)
    np.testing.assert_array_equal(np.asarray(out[:, :6]), np.asarray(prompt))

    # manual roll: full-length zero-padded buffer, one argmax at a time
    fused = fused_reference(stages)
    params = [s.params for s in stages]
    buf = np.zeros((2, cfg.seq_len), np.int32)
    buf[:, :6] = np.asarray(prompt)
    for i in range(6, 11):
        logp = fused(params, jnp.asarray(buf, jnp.float32),
                     jax.random.key(0), True)
        buf[:, i] = np.asarray(jnp.argmax(logp[:, i - 1], axis=-1))
    np.testing.assert_array_equal(np.asarray(out), buf[:, :11])


def test_generate_sampling_shapes_and_validation():
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        generate,
        make_gpt_stages,
    )

    cfg = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=2)
    stages, _, _ = make_gpt_stages(jax.random.key(0), cfg, n_stages=1)
    prompt = jax.random.randint(jax.random.key(1), (2, 4), 0, cfg.vocab)
    out = generate(stages, prompt, n_new=4, key=jax.random.key(2),
                   temperature=1.0)
    assert out.shape == (2, 8)
    assert int(out.max()) < cfg.vocab and int(out.min()) >= 0

    with pytest.raises(ValueError, match="exceeds the model's sequence"):
        generate(stages, prompt, n_new=13)
    with pytest.raises(ValueError, match="needs a PRNG key"):
        generate(stages, prompt, n_new=2, temperature=0.5)


def test_cached_decoder_matches_recompute():
    """KV-cache greedy decode produces the exact token sequence of the
    full-prefix-recompute decoder: same math, cache rows replace the O(T^2)
    re-forward. Covers multi-stage param re-joining (embed on stage 0, head
    on the last) and a prompt_len=1 prefill."""
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_cached_decoder,
        make_decoder,
        make_gpt_stages,
    )

    cfg = GPTConfig(vocab=32, seq_len=24, d_model=32, n_heads=2, n_layers=2)
    for n_stages, t0, n_new in [(1, 6, 10), (2, 6, 10), (2, 1, 8)]:
        stages, _, _ = make_gpt_stages(jax.random.key(0), cfg, n_stages)
        params = [s.params for s in stages]
        prompt = jax.random.randint(jax.random.key(1), (2, t0), 0, cfg.vocab)
        want = make_decoder(stages, t0, n_new)(
            params, prompt, jax.random.key(0))
        got = make_cached_decoder(stages, cfg, t0, n_new)(
            params, prompt, jax.random.key(0))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_bf16_cache_decoders_match_f32():
    """cache_dtype=bf16 halves decode memory; greedy tokens must match the
    f32-cache decoders on this (deterministic) model — bf16 K/V error is
    orders of magnitude below the argmax logit gaps here. Covers the cached
    and beam decoders (the pp decoder shares the same block helpers)."""
    from simple_distributed_machine_learning_tpu.models.beam import (
        make_beam_decoder,
    )
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_cached_decoder,
        make_gpt_stages,
    )

    cfg = GPTConfig(vocab=32, seq_len=24, d_model=32, n_heads=2, n_layers=2)
    stages, _, _ = make_gpt_stages(jax.random.key(0), cfg, 2)
    params = [s.params for s in stages]
    prompt = jax.random.randint(jax.random.key(1), (2, 6), 0, cfg.vocab)

    want = make_cached_decoder(stages, cfg, 6, 10)(
        params, prompt, jax.random.key(0))
    got = make_cached_decoder(stages, cfg, 6, 10, cache_dtype=jnp.bfloat16)(
        params, prompt, jax.random.key(0))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    bw, bs = make_beam_decoder(stages, cfg, 6, 8, beam_size=3)(
        params, prompt, jax.random.key(0))
    gw, gs = make_beam_decoder(stages, cfg, 6, 8, beam_size=3,
                               cache_dtype=jnp.bfloat16)(
        params, prompt, jax.random.key(0))
    # beam search ARGSORTS cumulative scores, and a bf16 cache legitimately
    # flips near-tie orderings (the accumulation-order-sensitive corner
    # that made exact token equality a known-env failure on this CPU
    # backend): the dtype-aware contract is sparse token flips at most,
    # with the scores themselves inside the pinned bf16 tolerance
    from tolerances import attn_tol, near_tie_token_mismatch_budget

    mismatch = float(np.mean(np.asarray(gw) != np.asarray(bw)))
    assert mismatch <= near_tie_token_mismatch_budget(), (
        f"bf16 beam tokens diverged beyond near-tie flips: "
        f"{mismatch:.0%} mismatched")
    rtol, atol = attn_tol(jnp.bfloat16)
    np.testing.assert_allclose(np.asarray(gs), np.asarray(bs),
                               rtol=rtol, atol=atol)


def test_cached_decoder_validation():
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_cached_decoder,
        make_gpt_stages,
    )

    cfg = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=2)
    stages, _, _ = make_gpt_stages(jax.random.key(0), cfg, n_stages=1)
    with pytest.raises(ValueError, match="exceeds the model's sequence"):
        make_cached_decoder(stages, cfg, 8, 9)
    with pytest.raises(ValueError, match="n_new >= 1"):
        make_cached_decoder(stages, cfg, 8, 0)

    wrong = GPTConfig(vocab=32, seq_len=64, d_model=32, n_heads=2, n_layers=2)
    with pytest.raises(ValueError, match="does not match the stages'"):
        make_cached_decoder(stages, wrong, 8, 4)

    moe = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=2,
                    n_experts=4)
    moe_stages, _, _ = make_gpt_stages(jax.random.key(0), moe, n_stages=1)
    with pytest.raises(ValueError, match="dense-MLP blocks only"):
        make_cached_decoder(moe_stages, moe, 4, 4)


def test_cached_decoder_sampling_matches_recompute():
    """temperature > 0: both decoders split the PRNG key once per generated
    token in the same order, so sampled tokens are IDENTICAL too — pins the
    key-stream contract, not just the greedy path."""
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_cached_decoder,
        make_decoder,
        make_gpt_stages,
    )

    cfg = GPTConfig(vocab=32, seq_len=24, d_model=32, n_heads=2, n_layers=2)
    stages, _, _ = make_gpt_stages(jax.random.key(0), cfg, 2)
    params = [s.params for s in stages]
    prompt = jax.random.randint(jax.random.key(1), (2, 5), 0, cfg.vocab)
    want = make_decoder(stages, 5, 9, temperature=1.0)(
        params, prompt, jax.random.key(7))
    got = make_cached_decoder(stages, cfg, 5, 9, temperature=1.0)(
        params, prompt, jax.random.key(7))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_top_k_top_p_sampling():
    """Top-k / nucleus filtering: cross-decoder parity, support restriction
    (every sampled token lies in the allowed set), and validation."""
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_cached_decoder,
        make_decoder,
        make_gpt_stages,
    )
    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        fused_reference,
    )

    cfg = GPTConfig(vocab=32, seq_len=24, d_model=32, n_heads=2, n_layers=2)
    stages, _, _ = make_gpt_stages(jax.random.key(0), cfg, 1)
    params = [s.params for s in stages]
    prompt = jax.random.randint(jax.random.key(1), (2, 4), 0, cfg.vocab)

    # cross-decoder parity: same key stream -> identical filtered samples
    for kw in [dict(top_k=3), dict(top_p=0.5), dict(top_k=5, top_p=0.9)]:
        want = make_decoder(stages, 4, 8, temperature=0.8, **kw)(
            params, prompt, jax.random.key(9))
        got = make_cached_decoder(stages, cfg, 4, 8, temperature=0.8, **kw)(
            params, prompt, jax.random.key(9))
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    # support restriction: with top_k=3, every generated token must be among
    # that step's 3 highest-probability tokens (check step 1 over many seeds)
    fused = fused_reference(stages)
    logp = fused(params, jnp.pad(prompt, ((0, 0), (0, 20))).astype(
        jnp.float32), jax.random.key(0), True)
    allowed = np.asarray(jax.lax.top_k(logp[:, 3], 3)[1])      # [2, 3]
    dec = make_cached_decoder(stages, cfg, 4, 1, temperature=1.0, top_k=3)
    for seed in range(20):
        out = np.asarray(dec(params, prompt, jax.random.key(seed)))
        for b in range(2):
            assert out[b, 4] in allowed[b], (seed, out[b, 4], allowed[b])

    # top_k=1 at any temperature is greedy
    greedy = make_cached_decoder(stages, cfg, 4, 8)(
        params, prompt, jax.random.key(0))
    k1 = make_cached_decoder(stages, cfg, 4, 8, temperature=2.0, top_k=1)(
        params, prompt, jax.random.key(3))
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(greedy))

    with pytest.raises(ValueError, match="temperature > 0"):
        make_cached_decoder(stages, cfg, 4, 4, top_k=3)
    with pytest.raises(ValueError, match="top_p"):
        make_cached_decoder(stages, cfg, 4, 4, temperature=1.0, top_p=1.5)
    with pytest.raises(ValueError, match="top_k"):
        make_decoder(stages, 4, 4, temperature=1.0, top_k=0)


def test_decoder_from_pipeline_uses_live_buffer():
    """Decode straight from the training Pipeline's packed buffer: training
    for a few steps CHANGES the decoded continuation (the decoder reads the
    live weights, not a stale copy), and the output matches unpacking the
    buffer manually."""
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        decoder_from_pipeline,
        make_cached_decoder,
        make_gpt_stages,
    )

    cfg = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=2)
    stages, wd, osh = make_gpt_stages(jax.random.key(0), cfg, 2)
    mesh = make_mesh(n_stages=2, n_data=1, devices=jax.devices()[:2])
    pipe = Pipeline(stages, mesh, wd, osh, n_microbatches=1)
    buf = pipe.init_params()
    prompt = jax.random.randint(jax.random.key(1), (2, 4), 0, cfg.vocab)
    dec = decoder_from_pipeline(pipe, cfg, 4, 8)

    out0 = np.asarray(dec(buf, prompt, jax.random.key(0)))
    want = make_cached_decoder(stages, cfg, 4, 8)(
        pipe.unpack(buf), prompt, jax.random.key(0))
    np.testing.assert_array_equal(out0, np.asarray(want))

    data = synthetic_tokens(8, cfg.seq_len, cfg.vocab, seed=2)
    opt = sgd(0.5, momentum=0.9)
    state = opt.init(buf)
    step = make_train_step(pipe, opt)
    for i in range(10):
        buf, state, _ = step(buf, state,
                             jnp.asarray(data.x, jnp.float32),
                             jnp.asarray(data.y), jax.random.key(i))
    out1 = np.asarray(dec(buf, prompt, jax.random.key(0)))
    assert not np.array_equal(out0, out1), "decode ignored training updates"


def test_generate_cfg_uses_cached_path():
    """generate(..., cfg=) routes through the KV-cache decoder and returns
    the exact recompute-path tokens."""
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        generate,
        make_gpt_stages,
    )

    cfg = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=2)
    stages, _, _ = make_gpt_stages(jax.random.key(0), cfg, n_stages=1)
    prompt = jax.random.randint(jax.random.key(1), (2, 6), 0, cfg.vocab)
    want = generate(stages, prompt, n_new=5)
    got = generate(stages, prompt, n_new=5, cfg=cfg)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    got_s = generate(stages, prompt, n_new=5, cfg=cfg, key=jax.random.key(2),
                     temperature=0.9, top_k=4)
    want_s = generate(stages, prompt, n_new=5, key=jax.random.key(2),
                      temperature=0.9, top_k=4)
    np.testing.assert_array_equal(np.asarray(got_s), np.asarray(want_s))
