"""Expert parallelism: EP MoE (all_to_all over an expert mesh axis) must
compute exactly what the dense MoE computes on each token shard, values and
grads; capacity semantics drop overflow tokens to zero output."""

import jax
import jax.numpy as jnp
import numpy as np

from simple_distributed_machine_learning_tpu.parallel.compat import (
    shard_map,
)
from jax.sharding import Mesh, PartitionSpec as P

from simple_distributed_machine_learning_tpu.parallel.expert import (
    moe_apply,
    moe_apply_ep,
    moe_init,
)

D_MODEL, D_HIDDEN, N_EXPERTS, N_SHARDS, T_LOCAL = 16, 32, 8, 4, 12


def _ep_fn(mesh, k, capacity):
    espec = jax.tree.map(lambda _: P("expert"),
                         {"in": {"w": 0, "b": 0}, "out": {"w": 0, "b": 0}})
    pspec = {"router": P(), "experts": espec}

    def per_device(p, xx):
        return moe_apply_ep(p, xx, k=k, capacity=capacity)

    return jax.jit(shard_map(
        per_device, mesh=mesh,
        in_specs=(pspec, P("expert")), out_specs=(P("expert"), P()),
        ))


def test_ep_matches_dense_per_shard():
    key = jax.random.key(0)
    params = moe_init(key, D_MODEL, D_HIDDEN, N_EXPERTS)
    x = jax.random.normal(jax.random.key(1), (N_SHARDS * T_LOCAL, D_MODEL))
    k, cap = 2, T_LOCAL * 2  # ample capacity: nothing drops

    mesh = Mesh(np.array(jax.devices()[:N_SHARDS]), ("expert",))
    y_ep, aux_ep = _ep_fn(mesh, k, cap)(params, x)

    # ground truth: the dense path on each token shard (routing is per-shard
    # in EP, so capacity positions are assigned within each shard)
    chunks, auxes = [], []
    for i in range(N_SHARDS):
        y, aux = moe_apply(params, x[i * T_LOCAL:(i + 1) * T_LOCAL], k=k,
                           capacity=cap)
        chunks.append(y)
        auxes.append(aux)
    np.testing.assert_allclose(np.asarray(y_ep),
                               np.concatenate([np.asarray(c) for c in chunks]),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux_ep), float(np.mean(auxes)),
                               rtol=1e-5)


def test_ep_grads_match_dense():
    key = jax.random.key(2)
    params = moe_init(key, D_MODEL, D_HIDDEN, N_EXPERTS)
    x = jax.random.normal(jax.random.key(3), (N_SHARDS * T_LOCAL, D_MODEL))
    k, cap = 1, T_LOCAL  # top-1, still no drops
    mesh = Mesh(np.array(jax.devices()[:N_SHARDS]), ("expert",))
    ep = _ep_fn(mesh, k, cap)

    def loss_ep(params, x):
        y, _ = ep(params, x)
        return jnp.mean(y ** 2)

    def loss_dense(params, x):
        ys = [moe_apply(params, x[i * T_LOCAL:(i + 1) * T_LOCAL], k=k,
                        capacity=cap)[0] for i in range(N_SHARDS)]
        return jnp.mean(jnp.concatenate(ys) ** 2)

    g_ep = jax.grad(loss_ep, argnums=(0, 1))(params, x)
    g_d = jax.grad(loss_dense, argnums=(0, 1))(params, x)
    for a, b in zip(jax.tree.leaves(g_ep), jax.tree.leaves(g_d)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_capacity_overflow_drops_tokens():
    """With every token forced onto expert 0 and capacity 1, exactly one token
    per shard survives; dropped tokens produce zero output (residual path)."""
    key = jax.random.key(4)
    params = moe_init(key, D_MODEL, D_HIDDEN, N_EXPERTS)
    # bias routing hard toward expert 0
    router = np.zeros((D_MODEL, N_EXPERTS), np.float32)
    router[:, 0] = 10.0
    params = dict(params, router=jnp.asarray(router))
    x = jnp.abs(jax.random.normal(jax.random.key(5), (6, D_MODEL))) + 0.1

    y, _ = moe_apply(params, x, k=1, capacity=1)
    norms = np.linalg.norm(np.asarray(y), axis=-1)
    assert norms[0] > 0            # first token wins the single slot
    np.testing.assert_allclose(norms[1:], 0.0, atol=1e-6)


def test_dense_moe_trains():
    """A dense-MoE regression head actually learns (loss decreases)."""
    key = jax.random.key(6)
    params = moe_init(key, D_MODEL, D_HIDDEN, 4)
    w_true = 0.3 * jax.random.normal(jax.random.key(7), (D_MODEL, D_MODEL))
    x = jax.random.normal(jax.random.key(8), (64, D_MODEL))
    y_true = x @ w_true

    @jax.jit
    def step(params, lr=0.5):
        def loss_fn(p):
            y, aux = moe_apply(p, x, k=2)
            return jnp.mean((x + y - y_true) ** 2) + 0.01 * aux
        l, g = jax.value_and_grad(loss_fn)(params)
        return jax.tree.map(lambda p, g: p - lr * g, params, g), l

    params, l0 = step(params)
    for _ in range(100):
        params, l = step(params)
    assert float(l) < 0.3 * float(l0)
