"""Trainer console parity and end-to-end learning on the synthetic dataset."""

import re

import jax
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.data.mnist import Dataset, synthetic_mnist
from simple_distributed_machine_learning_tpu.models.lenet import make_lenet_stages
from simple_distributed_machine_learning_tpu.models.mlp import make_mlp_stages
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.train.trainer import TrainConfig, Trainer

# the reference's exact print formats (simple_distributed.py:114-117,:130-132)
TRAIN_RE = re.compile(
    r"^Train Epoch: (\d+) \[(\d+)/(\d+) \((\d+)%\)\]\tLoss: (\d+\.\d{6})$")
TEST_RE = re.compile(
    r"^Test set: Average loss: (\d+\.\d{4}), Accuracy: (\d+)/(\d+) \((\d+)%\)$")


def test_console_format_matches_reference(capsys):
    train, test = synthetic_mnist(n_train=240, n_test=100, seed=3)
    key = jax.random.key(0)
    stages, wire_dim, out_dim = make_lenet_stages(key, 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wire_dim, out_dim, n_microbatches=2)
    cfg = TrainConfig(epochs=2, batch_size=60, log_interval=2,
                      print_throughput=False)
    Trainer(pipe, train, test, cfg).fit()

    out = capsys.readouterr().out
    lines = [l for l in out.split("\n") if l]
    train_lines = [l for l in lines if l.startswith("Train Epoch")]
    test_lines = [l for l in lines if l.startswith("Test set")]
    assert train_lines and test_lines
    for l in train_lines:
        assert TRAIN_RE.match(l), f"bad train line: {l!r}"
    for l in test_lines:
        assert TEST_RE.match(l), f"bad test line: {l!r}"
    # 2 epochs * ceil(4 batches / log_interval 2) = 4 train logs, 2 test logs
    assert len(train_lines) == 4 and len(test_lines) == 2
    # first log of an epoch is batch 0 of 240 samples
    m = TRAIN_RE.match(train_lines[0])
    assert m.group(2) == "0" and m.group(3) == "240"


def test_learns_synthetic_digits():
    train, test = synthetic_mnist(n_train=240, n_test=100, seed=3)
    train = Dataset(train.x.reshape(len(train.x), -1), train.y)
    test = Dataset(test.x.reshape(len(test.x), -1), test.y)
    stages, wire_dim, out_dim = make_mlp_stages(jax.random.key(0), [784, 64, 10], 2)
    pipe = Pipeline(stages, make_mesh(n_stages=2, n_data=1), wire_dim, out_dim,
                    n_microbatches=2)
    cfg = TrainConfig(epochs=5, batch_size=60, print_throughput=False)
    trainer = Trainer(pipe, train, test, cfg)
    trainer.fit()
    avg_loss, correct = trainer.evaluate()
    assert correct / 100 > 0.5          # 10% is chance level
    assert avg_loss < 2.0


def test_trainer_checkpoint_resume(tmp_path):
    """fit() checkpoints every epoch; a new Trainer on the same dir resumes at
    the next epoch with identical params and continues to the target epoch."""
    train, test = synthetic_mnist(n_train=120, n_test=60, seed=4)
    key = jax.random.key(0)

    def build(epochs):
        stages, wire_dim, out_dim = make_mlp_stages(
            key, [784, 32, 10], 2)
        ds_tr = Dataset(train.x.reshape(len(train.x), -1), train.y)
        ds_te = Dataset(test.x.reshape(len(test.x), -1), test.y)
        mesh = make_mesh(n_stages=2, n_data=1)
        pipe = Pipeline(stages, mesh, wire_dim, out_dim)
        cfg = TrainConfig(epochs=epochs, batch_size=60, print_throughput=False,
                          checkpoint_dir=str(tmp_path))
        return Trainer(pipe, ds_tr, ds_te, cfg)

    t1 = build(epochs=2)
    t1.fit()
    steps_after_2 = t1._step_count

    t2 = build(epochs=3)            # same dir: resumes after epoch 2
    assert t2.start_epoch == 3
    assert t2._step_count == steps_after_2
    np.testing.assert_array_equal(np.asarray(jax.device_get(t2.buf)),
                                  np.asarray(jax.device_get(t1.buf)))
    t2.fit()                        # runs exactly epoch 3
    assert t2._step_count > steps_after_2

    t3 = build(epochs=3)
    assert t3.start_epoch == 4      # nothing left to do


def test_metrics_json_records_per_epoch(tmp_path):
    """metrics_json appends one well-formed JSON line per epoch with the
    documented keys — the machine-readable counterpart of the console
    surface (SURVEY §5.5)."""
    import json

    train, test = synthetic_mnist(n_train=120, n_test=60, seed=5)
    stages, wire_dim, out_dim = make_mlp_stages(jax.random.key(0),
                                                [784, 32, 10], 2)
    ds_tr = Dataset(train.x.reshape(len(train.x), -1), train.y)
    ds_te = Dataset(test.x.reshape(len(test.x), -1), test.y)
    pipe = Pipeline(stages, make_mesh(n_stages=2, n_data=1),
                    wire_dim, out_dim)
    path = tmp_path / "metrics.jsonl"
    cfg = TrainConfig(epochs=3, batch_size=60, print_throughput=False,
                      metrics_json=str(path))
    Trainer(pipe, ds_tr, ds_te, cfg).fit()

    records = [json.loads(l) for l in path.read_text().splitlines()]
    assert [r["epoch"] for r in records] == [1, 2, 3]
    for r in records:
        assert set(r) == {"schema", "time", "epoch", "step", "train_loss",
                          "samples_per_sec", "eval_loss", "accuracy",
                          "correct", "n_eval"}
        # versioned since the telemetry registry took over the write path;
        # every pre-existing documented key is still present above
        assert r["schema"] == 2
        assert r["n_eval"] == 60
        assert 0 <= r["correct"] <= 60
        # accuracy is the documented headline key; the raw counts it is
        # computed from stay alongside it
        assert abs(r["accuracy"] - r["correct"] / r["n_eval"]) < 1e-6
        assert r["samples_per_sec"] >= 0.0
    # steps accumulate across epochs (2 batches/epoch here)
    assert records[-1]["step"] == 6
