"""Ragged final train batch: padded + masked, every sample trains."""

import jax
import numpy as np

from simple_distributed_machine_learning_tpu.data.mnist import Dataset
from simple_distributed_machine_learning_tpu.models.mlp import make_mlp_stages
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.train.trainer import TrainConfig, Trainer


def test_trainer_trains_on_short_final_batch(capsys):
    rng = np.random.default_rng(0)
    # 100 samples, batch 60 -> batches of 60 and 40 (ragged)
    train = Dataset(rng.normal(size=(100, 12)).astype(np.float32),
                    (np.arange(100) % 10).astype(np.int32))
    test = Dataset(train.x[:20], train.y[:20])
    stages, wd, od = make_mlp_stages(jax.random.key(0), [12, 32, 10], 2)
    pipe = Pipeline(stages, make_mesh(n_stages=2, n_data=1), wd, od)
    cfg = TrainConfig(epochs=1, batch_size=60, log_interval=1,
                      print_throughput=False)
    tr = Trainer(pipe, train, test, cfg)
    tr.train_epoch(1)
    out = capsys.readouterr().out
    # both batches ran (2 train log lines at log_interval=1)
    assert out.count("Train Epoch: 1") == 2
    # 2 optimizer steps happened
    assert tr._step_count == 2


def test_trainer_smaller_than_batch_dataset_still_trains():
    rng = np.random.default_rng(1)
    train = Dataset(rng.normal(size=(30, 12)).astype(np.float32),
                    (np.arange(30) % 10).astype(np.int32))
    stages, wd, od = make_mlp_stages(jax.random.key(0), [12, 32, 10], 2)
    pipe = Pipeline(stages, make_mesh(n_stages=2, n_data=1), wd, od)
    cfg = TrainConfig(epochs=1, batch_size=60, print_throughput=False)
    tr = Trainer(pipe, train, Dataset(train.x, train.y), cfg)
    before = np.asarray(tr.buf).copy()
    tr.train_epoch(1)
    assert tr._step_count == 1
    assert not np.allclose(before, np.asarray(tr.buf))  # params moved
