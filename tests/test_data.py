"""Data pipeline: reference sizing/ordering semantics + hermetic fallback."""

import numpy as np

from simple_distributed_machine_learning_tpu.data.mnist import (
    batches,
    load_mnist,
    synthetic_mnist,
)


def test_reference_sizing_and_determinism():
    # Reference: both splits cut to 1/10 -> 6000 train / 1000 test
    # (simple_distributed.py:91-92); deterministic order (:94-95).
    train, test = load_mnist(root="/nonexistent-data-dir")
    assert train.x.shape == (6000, 28, 28, 1) and train.y.shape == (6000,)
    assert test.x.shape == (1000, 28, 28, 1)
    assert train.x.dtype == np.float32 and 0.0 <= train.x.min() <= train.x.max() <= 1.0
    train2, _ = load_mnist(root="/nonexistent-data-dir")
    np.testing.assert_array_equal(train.x, train2.x)
    np.testing.assert_array_equal(train.y, train2.y)


def test_synthetic_is_learnable_structure():
    train, _ = synthetic_mnist(n_train=200, n_test=10)
    # class-conditional means must differ (else nothing to learn)
    m0 = train.x[train.y == 0].mean(0)
    m1 = train.x[train.y == 1].mean(0)
    assert np.abs(m0 - m1).mean() > 0.05


def test_batches_fixed_order_and_ragged_padding():
    train, test = load_mnist(root="/nonexistent-data-dir")
    bs = list(batches(test, 60, pad_last=True))
    # reference test split: 1000 = 16*60 + 40
    assert len(bs) == 17
    assert all(b.x.shape == (60, 28, 28, 1) for b in bs)
    assert bs[-1].n_valid == 40
    np.testing.assert_array_equal(bs[-1].x[40:], 0.0)
    # fixed order: first batch is the first 60 rows
    np.testing.assert_array_equal(bs[0].x, test.x[:60])

    # train split divides exactly; pad_last=False drops nothing
    tb = list(batches(train, 60, pad_last=False))
    assert len(tb) == 100 and all(b.n_valid == 60 for b in tb)


def test_shuffled_batches_permute_deterministically():
    """shuffle_seed: same multiset of samples, new deterministic order."""
    import numpy as np

    from simple_distributed_machine_learning_tpu.data.mnist import (
        Dataset,
        batches,
        prefetch_batches,
    )

    x = np.arange(100, dtype=np.float32).reshape(100, 1)
    ds = Dataset(x, np.arange(100))
    plain = np.concatenate([b.x[:b.n_valid, 0] for b in batches(ds, 30)])
    s1 = np.concatenate([b.x[:b.n_valid, 0]
                         for b in batches(ds, 30, shuffle_seed=7)])
    s1b = np.concatenate([b.x[:b.n_valid, 0]
                          for b in batches(ds, 30, shuffle_seed=7)])
    s2 = np.concatenate([b.x[:b.n_valid, 0]
                         for b in batches(ds, 30, shuffle_seed=8)])
    assert not np.array_equal(plain, s1)
    np.testing.assert_array_equal(s1, s1b)          # reproducible
    assert not np.array_equal(s1, s2)               # seed-sensitive
    np.testing.assert_array_equal(np.sort(s1), plain)  # same samples
    # prefetch path shuffles identically (labels stay paired with rows)
    pf = [b for b in prefetch_batches(ds, 30, shuffle_seed=7)]
    np.testing.assert_array_equal(
        np.concatenate([b.x[:b.n_valid, 0] for b in pf]), s1)
    for b in pf:
        np.testing.assert_array_equal(b.x[:b.n_valid, 0],
                                      b.y[:b.n_valid].astype(np.float32))


def test_per_host_sharding_single_process():
    """Single process addresses the whole mesh: host_rows is the full range
    and make_global_batch reassembles exactly (the multi-process behavior —
    each host materializing 1/dp — is asserted cross-process in
    tests/test_multiprocess.py::test_four_process_dp_pp)."""
    import jax
    import numpy as np

    from simple_distributed_machine_learning_tpu.data.sharding import (
        host_rows,
        make_global_batch,
    )
    from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(n_stages=2, n_data=2)
    assert host_rows(mesh, 60) == (0, 60)
    x = np.arange(60 * 3, dtype=np.float32).reshape(60, 3)
    g = make_global_batch(mesh, x, 60)
    assert isinstance(g, jax.Array) and g.shape == (60, 3)
    np.testing.assert_array_equal(np.asarray(g), x)


def test_byte_corpus_shapes_and_targets(tmp_path):
    import numpy as np

    from simple_distributed_machine_learning_tpu.data.text import byte_corpus

    p = tmp_path / "corpus.bin"
    p.write_bytes(bytes(range(256)) * 10)        # 2560 bytes
    tr, te = byte_corpus(str(p), seq_len=32)
    assert tr.x.shape[1] == te.x.shape[1] == 32
    # next-byte contract: y[t] == x[t+1] within a window
    np.testing.assert_array_equal(tr.y[:, :-1], tr.x[:, 1:])
    np.testing.assert_array_equal(te.y[:, :-1], te.x[:, 1:])
    # the test split is contiguous, offset ONE byte past the train tail: the
    # last train target (raw[n_train*T]) must never appear in the test text
    raw = np.frombuffer(p.read_bytes(), np.uint8)
    n_train = tr.x.shape[0]
    boundary = n_train * 32
    assert int(tr.y[-1, -1]) == int(raw[boundary])
    np.testing.assert_array_equal(te.x[0], raw[boundary + 1:boundary + 33])
    assert int(tr.x.max()) < 256 and int(tr.x.min()) >= 0

    import pytest
    small = tmp_path / "tiny.bin"
    small.write_bytes(b"xy")
    with pytest.raises(ValueError, match="needs at least"):
        byte_corpus(str(small), seq_len=32)
    # exactly 2T+1 bytes: enough for two windows but not for the held-out
    # skip — must still refuse rather than silently leak
    edge = tmp_path / "edge.bin"
    edge.write_bytes(bytes(65))
    with pytest.raises(ValueError, match="needs at least"):
        byte_corpus(str(edge), seq_len=32)


def test_byte_corpus_max_seqs_caps_both_splits(tmp_path):
    import pytest

    from simple_distributed_machine_learning_tpu.data.text import byte_corpus

    p = tmp_path / "big.bin"
    p.write_bytes(bytes(range(256)) * 100)       # 25600 bytes
    tr, te = byte_corpus(str(p), seq_len=32, max_seqs=4)
    assert tr.x.shape[0] == 3 and te.x.shape[0] == 1
    with pytest.raises(ValueError, match="max_seqs"):
        byte_corpus(str(p), seq_len=32, max_seqs=1)
