"""Continuous-batching serving (serve/): parity, invariants, traffic.

The load-bearing property: continuous batching is a SCHEDULING optimization,
not a math change — for a fixed seed, every request's tokens are bit-exact
vs decoding it alone through ``models.make_cached_decoder``, across mixed
prompt lengths, mid-flight admissions, EOS early exits, and every sampling
mode — and since the paged pool landed, ALSO across block-table storage,
chunked prefill boundaries, shared prefixes and copy-on-write divergence
(the default engine is paged, so every parity test above exercises it; the
dense layout keeps its own parity pin). Plus the scheduler invariants (no
double occupancy/allocation, admission blocks on block exhaustion and
resumes, every request completes, freed slots reuse next tick, queues drain
above capacity), the serving metrics incl. the block-pool gauges, the
simulator with its shared system prefix, the checkpoint→serve path, and the
bench claims: continuous beats sequential, paged sustains more concurrency
at fixed KV bytes, chunked prefill cuts the long-prompt stall tick.
"""

import dataclasses
import json
import os

import jax
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    make_cached_decoder,
    make_gpt_stages,
    make_slot_decode_step,
    make_slot_prefill,
)
from simple_distributed_machine_learning_tpu.serve import (
    InferenceEngine,
    ServeMetrics,
    SimConfig,
    simulate,
)
from simple_distributed_machine_learning_tpu.serve.request import (
    ACTIVE,
    DONE,
    Request,
    validate_request,
)
from simple_distributed_machine_learning_tpu.serve.slots import (
    KVCachePool,
    PagedKVPool,
)

CFG = GPTConfig(vocab=32, seq_len=48, d_model=32, n_heads=2, n_layers=2)
_STAGES = None


def _model():
    global _STAGES
    if _STAGES is None:
        _STAGES = make_gpt_stages(jax.random.key(0), CFG, 2)[0]
    return _STAGES, [s.params for s in _STAGES]


def _solo(stages, params, prompt, n_new, seed, temperature=0.0, top_k=None,
          top_p=None):
    """The reference tokens: this request decoded ALONE through the
    one-shot KV-cache decoder with the same seed and sampling params."""
    dec = make_cached_decoder(stages, CFG, len(prompt), n_new,
                              temperature=temperature, top_k=top_k,
                              top_p=top_p)
    out = dec(params, np.asarray(prompt, np.int32)[None],
              jax.random.key(seed))
    return np.asarray(out)[0, len(prompt):]


def _prompt(n, seed):
    return np.asarray(
        jax.random.randint(jax.random.key(seed), (n,), 0, CFG.vocab),
        np.int32)


# ---------------------------------------------------------------------------
# parity: bit-exact vs solo decode


def test_single_request_matches_solo_decode():
    stages, params = _model()
    eng = InferenceEngine(stages, CFG, n_slots=3)
    r = eng.submit(_prompt(5, 1), max_new_tokens=6, seed=11)
    eng.drain()
    assert r.state == DONE and r.finish_reason == "length"
    np.testing.assert_array_equal(
        r.tokens, _solo(stages, params, r.prompt, 6, 11))


def test_mixed_prompt_lengths_and_sampling_parity():
    """5 requests, 2 slots (so queueing + mid-flight boarding happens),
    mixed prompt lengths and sampling modes — each request's tokens are
    bit-exact vs its solo decode."""
    stages, params = _model()
    eng = InferenceEngine(stages, CFG, n_slots=2)
    specs = [
        dict(prompt=_prompt(3, 2), max_new_tokens=7, seed=20),
        dict(prompt=_prompt(9, 3), max_new_tokens=5, seed=21,
             temperature=0.8, top_k=5),
        dict(prompt=_prompt(5, 4), max_new_tokens=8, seed=22,
             temperature=0.9, top_p=0.9),
        dict(prompt=_prompt(7, 5), max_new_tokens=4, seed=23),
        dict(prompt=_prompt(4, 6), max_new_tokens=6, seed=24,
             temperature=1.1, top_k=7, top_p=0.8),
    ]
    handles = [eng.submit(**s) for s in specs]
    eng.drain()
    for h, s in zip(handles, specs):
        want = _solo(stages, params, s["prompt"], s["max_new_tokens"],
                     s["seed"], temperature=s.get("temperature", 0.0),
                     top_k=s.get("top_k"), top_p=s.get("top_p"))
        np.testing.assert_array_equal(np.asarray(h.tokens), want,
                                      err_msg=f"request {h.rid}")


def test_mid_flight_admission_parity():
    """A request admitted while another is mid-decode gets the same tokens
    as its solo decode — co-residents cannot change anyone's output."""
    stages, params = _model()
    eng = InferenceEngine(stages, CFG, n_slots=2)
    r1 = eng.submit(_prompt(6, 7), max_new_tokens=10, seed=30)
    for _ in range(4):                       # r1 alone for 4 ticks
        eng.step()
    assert 0 < len(r1.tokens) < 10
    r2 = eng.submit(_prompt(4, 8), max_new_tokens=6, seed=31,
                    temperature=0.7, top_k=4)
    eng.drain()
    np.testing.assert_array_equal(
        r1.tokens, _solo(stages, params, r1.prompt, 10, 30))
    np.testing.assert_array_equal(
        r2.tokens, _solo(stages, params, r2.prompt, 6, 31,
                         temperature=0.7, top_k=4))


def test_eos_early_exit_parity_and_slot_free():
    """EOS retires the request with a PREFIX of its solo decode (up to and
    including the first EOS) and frees the slot immediately."""
    stages, params = _model()
    solo = _solo(stages, params, _prompt(5, 9), 8, 40)
    eos = int(solo[2])                       # an eos the solo decode emits
    cut = int(np.where(solo == eos)[0][0]) + 1   # ...its FIRST occurrence
    eng = InferenceEngine(stages, CFG, n_slots=1)
    r = eng.submit(_prompt(5, 9), max_new_tokens=8, seed=40, eos_id=eos)
    eng.drain()
    assert r.finish_reason == "eos"
    assert len(r.tokens) == cut < 8
    np.testing.assert_array_equal(r.tokens, solo[:cut])
    assert eng.pool.n_free == 1


# ---------------------------------------------------------------------------
# scheduler invariants


def test_queue_drains_above_capacity_no_double_occupancy():
    """9 requests through 2 slots: occupancy never exceeds capacity, a
    slot never hosts two requests (pool guards raise), every request
    completes, and a freed slot is reused on the next tick."""
    stages, params = _model()
    eng = InferenceEngine(stages, CFG, n_slots=2)
    handles = [eng.submit(_prompt(3 + i % 3, 10 + i),
                          max_new_tokens=3 + i % 4, seed=50 + i)
               for i in range(9)]
    max_active = 0
    while eng.busy:
        queued_before = eng.scheduler.queue_depth
        eng.step()
        assert eng.pool.n_active <= 2
        max_active = max(max_active, eng.pool.n_active)
        # FCFS: the queue never grows mid-run (no re-queueing); slots can
        # all retire within one decode tick, so n_active == 0 with work
        # still queued is legal — the next tick's admission boards it
        assert eng.scheduler.queue_depth <= queued_before
        occ = [eng.pool.occupant(s) for s in eng.pool.active_slots()]
        assert len(occ) == len(set(occ))     # no slot double-occupied
    assert all(h.state == DONE for h in handles)
    assert eng.scheduler.queue_depth == 0
    assert max_active == 2                   # the batch actually filled
    # each completed with its requested token budget, and parity held
    for i, h in enumerate(handles):
        assert len(h.tokens) == 3 + i % 4
        np.testing.assert_array_equal(
            h.tokens, _solo(stages, params, h.prompt, len(h.tokens),
                            50 + i))


def test_freed_slot_reusable_next_tick():
    stages, _ = _model()
    eng = InferenceEngine(stages, CFG, n_slots=1)
    r1 = eng.submit(_prompt(4, 30), max_new_tokens=1, seed=60)
    r2 = eng.submit(_prompt(6, 31), max_new_tokens=5, seed=61)
    eng.step()                    # tick 1: r1 prefills, finishes, frees
    assert r1.state == DONE and eng.pool.n_free == 1
    assert r2.state == "queued"
    eng.step()                    # tick 2: r2 boards the freed slot
    assert r2.state == "active" and r2.slot is not None
    assert len(r2.tokens) == 2    # prefill token + one decode tick
    eng.drain()
    assert r2.state == DONE and len(r2.tokens) == 5


def test_pool_guards():
    pool = KVCachePool(2, 2, 2, 8, 4)
    a = pool.acquire(0)
    b = pool.acquire(1)
    assert {a, b} == {0, 1}
    with pytest.raises(RuntimeError, match="full pool"):
        pool.acquire(2)
    pool.release(a)
    with pytest.raises(RuntimeError, match="already-free"):
        pool.release(a)
    assert pool.acquire(3) == a   # freed slot comes back


def test_request_validation():
    stages, _ = _model()
    eng = InferenceEngine(stages, CFG, n_slots=1, max_len=16)
    with pytest.raises(ValueError, match="exceeds the pool"):
        eng.submit(_prompt(10, 0), max_new_tokens=7)
    with pytest.raises(ValueError, match="non-empty"):
        eng.submit(np.zeros(0, np.int32), max_new_tokens=2)
    with pytest.raises(ValueError, match="temperature > 0"):
        eng.submit(_prompt(4, 0), max_new_tokens=2, top_k=3)
    with pytest.raises(ValueError, match="top_k"):
        eng.submit(_prompt(4, 0), max_new_tokens=2, temperature=1.0,
                   top_k=999)
    with pytest.raises(ValueError, match="top_p"):
        eng.submit(_prompt(4, 0), max_new_tokens=2, temperature=1.0,
                   top_p=1.5)
    with pytest.raises(ValueError, match="max_len"):
        make_slot_prefill(stages, CFG, CFG.seq_len + 1)
    with pytest.raises(ValueError, match="max_len"):
        make_slot_decode_step(stages, CFG, 1)
    # engine-independent request plumbing
    validate_request(np.zeros(3, np.int32), 2, 0.0, None, None, 32, 16)
    r = Request(rid=0, prompt=np.zeros(3, np.int32), max_new_tokens=4)
    assert r.finished_by(7) is None


def test_drain_timeout_reports_unfinished():
    """The drain cap is a loud, structured signal: hitting ``max_ticks``
    with work still in flight raises DrainTimeout naming the abandoned
    request handles (queued AND active), never a silently shorter return
    value. Requests stay live — a later full drain finishes them."""
    from simple_distributed_machine_learning_tpu.serve import DrainTimeout

    stages, params = _model()
    eng = InferenceEngine(stages, CFG, n_slots=1)
    r1 = eng.submit(_prompt(4, 13), max_new_tokens=8, seed=71)
    r2 = eng.submit(_prompt(5, 14), max_new_tokens=4, seed=72)
    with pytest.raises(DrainTimeout) as ei:
        eng.drain(max_ticks=2)
    unfinished = ei.value.unfinished
    assert {r.rid for r in unfinished} == {r1.rid, r2.rid}
    assert str(r1.rid) in str(ei.value) and "2 ticks" in str(ei.value)
    # nothing was abandoned for real: draining on finishes both, bit-exact
    eng.drain()
    np.testing.assert_array_equal(
        r1.tokens, _solo(stages, params, r1.prompt, 8, 71))
    np.testing.assert_array_equal(
        r2.tokens, _solo(stages, params, r2.prompt, 4, 72))


def test_streaming_callback_order():
    stages, params = _model()
    eng = InferenceEngine(stages, CFG, n_slots=1)
    seen = []
    r = eng.submit(_prompt(4, 12), max_new_tokens=5, seed=70,
                   on_token=lambda req, t: seen.append((req.rid, t)))
    eng.drain()
    assert seen == [(r.rid, t) for t in r.tokens]
    assert len(seen) == 5


# ---------------------------------------------------------------------------
# paged pool: chunked prefill, prefix sharing, copy-on-write, exhaustion


@pytest.mark.slow
def test_dense_layout_parity():
    """The dense layout stays available (the bench baseline) and stays
    bit-exact — the default engine is now paged, so pin dense explicitly."""
    stages, params = _model()
    eng = InferenceEngine(stages, CFG, n_slots=2, kv_layout="dense")
    r1 = eng.submit(_prompt(5, 101), max_new_tokens=6, seed=111)
    r2 = eng.submit(_prompt(8, 102), max_new_tokens=5, seed=112,
                    temperature=0.8, top_k=5)
    eng.drain()
    np.testing.assert_array_equal(
        r1.tokens, _solo(stages, params, r1.prompt, 6, 111))
    np.testing.assert_array_equal(
        r2.tokens, _solo(stages, params, r2.prompt, 5, 112,
                         temperature=0.8, top_k=5))
    with pytest.raises(ValueError, match="paged-pool knobs"):
        InferenceEngine(stages, CFG, kv_layout="dense", prefill_chunk=4)
    with pytest.raises(ValueError, match="kv_layout"):
        InferenceEngine(stages, CFG, kv_layout="rowful")


def test_chunked_prefill_bitexact_across_chunk_sizes():
    """Chunk boundaries are invisible in the tokens: chunk sizes 1,
    block_size and the whole prompt (None) all reproduce the solo decode
    bit for bit, greedy and sampled."""
    stages, params = _model()
    p = _prompt(13, 120)
    # the prompt_len (whole-prompt) chunk is prefill_chunk=None — the
    # default every OTHER paged test in this file already exercises — so
    # this test pins the extremes: 1-token chunks (greedy) and block_size
    # chunks (sampled, so a key-stream crosses chunk boundaries too)
    cases = [(1, 0.0, None), (4, 0.9, 5)]
    for chunk, temperature, top_k in cases:
        want = _solo(stages, params, p, 6, 77, temperature=temperature,
                     top_k=top_k)
        eng = InferenceEngine(stages, CFG, n_slots=2, block_size=4,
                              prefill_chunk=chunk)
        r = eng.submit(p, max_new_tokens=6, seed=77,
                       temperature=temperature, top_k=top_k)
        eng.drain()
        np.testing.assert_array_equal(
            r.tokens, want, err_msg=f"chunk={chunk} t={temperature}")


def test_prefix_sharing_cow_sibling_unchanged():
    """B's prompt extends A's full prompt while A is mid-decode: B boards
    referencing A's blocks (prefix hit), B's first divergent write COPIES
    the shared tail block first, and BOTH requests still match their solo
    decodes — the sibling's tokens are untouched by the share."""
    stages, params = _model()
    pa = _prompt(13, 130)                        # bs=4: 3 full + tail fill 1
    pb = np.concatenate([pa, _prompt(4, 131)])   # strict extension
    eng = InferenceEngine(stages, CFG, n_slots=2, block_size=4)
    ra = eng.submit(pa, max_new_tokens=8, seed=140)
    for _ in range(3):                           # A prefilled + decoding
        eng.step()
    assert 0 < len(ra.tokens) < 8
    rb = eng.submit(pb, max_new_tokens=6, seed=141, temperature=0.8,
                    top_k=4)
    eng.drain()
    st = eng.pool.stats()
    assert st["prefix_hit_blocks_total"] >= 4, st   # 3 full + partial tail
    assert st["cow_copies_total"] >= 1, st
    np.testing.assert_array_equal(
        ra.tokens, _solo(stages, params, pa, 8, 140))
    np.testing.assert_array_equal(
        rb.tokens, _solo(stages, params, pb, 6, 141, temperature=0.8,
                         top_k=4))


@pytest.mark.slow
def test_identical_prompt_reuses_cached_blocks():
    """A retired request's prompt blocks stay cached (reclaimable): an
    identical later prompt shares every full block and recomputes only the
    capped tail — same tokens, fewer fresh blocks."""
    stages, params = _model()
    p = _prompt(12, 150)                         # bs=4: exactly 3 full blocks
    eng = InferenceEngine(stages, CFG, n_slots=2, block_size=4)
    r1 = eng.submit(p, max_new_tokens=4, seed=160)
    eng.drain()
    hits0 = eng.pool.stats()["prefix_hit_blocks_total"]
    r2 = eng.submit(p, max_new_tokens=4, seed=160)
    eng.drain()
    st = eng.pool.stats()
    # the cap (share at most prompt_len - 1) keeps the last position's
    # forward pass real, so only the first 2 full blocks can be shared
    assert st["prefix_hit_blocks_total"] - hits0 == 2, st
    assert r1.tokens == r2.tokens
    np.testing.assert_array_equal(
        r1.tokens, _solo(stages, params, p, 4, 160))


def test_admission_blocks_on_pool_exhaustion_and_resumes():
    """4 slots but only enough blocks for ~1 fat request: admission must
    hold requests in the queue while blocks are short (even with slots
    free), board them as retirements free blocks, and every request still
    matches its solo decode."""
    stages, params = _model()
    eng = InferenceEngine(stages, CFG, n_slots=4, block_size=4, n_blocks=12)
    hs = [eng.submit(_prompt(20, 170 + i), max_new_tokens=8, seed=180 + i)
          for i in range(4)]
    blocked = False
    max_active = 0
    while eng.busy:
        eng.step()
        max_active = max(max_active, eng.pool.n_active)
        if eng.scheduler.queue_depth and eng.pool.n_free:
            blocked = True          # slot free but blocks short -> queued
    assert blocked, "admission never blocked on block exhaustion"
    assert max_active < 4            # 27 rows/request: 12 blocks can't fit 4
    for i, h in enumerate(hs):
        assert h.state == DONE
        np.testing.assert_array_equal(
            h.tokens, _solo(stages, params, h.prompt, 8, 180 + i),
            err_msg=f"request {i}")


def test_can_admit_counts_reclaimable_shared_blocks_once():
    """Regression: a request whose shared prefix blocks sit in the
    reclaimable LRU must not have them counted BOTH as free-of-charge
    (budget discount) and as allocatable headroom (blocks_available) —
    binding revives them out of the LRU, so the old double count let
    can_admit approve a request begin_seq couldn't fund (RuntimeError out
    of engine.step() mid-serve, exactly under memory pressure + a warm
    prefix cache)."""
    pool = PagedKVPool(1, 3, 1, 20, 2, block_size=4, n_blocks=6)

    class _Req:
        def __init__(self, prompt, max_new):
            self.prompt = np.asarray(prompt, np.int32)
            self.max_new_tokens = max_new
            self.slot = None
            self.prefill_pos = None

    # A: 5-token prompt, 8 rows -> 2 blocks; registers its prefix, retires
    a = _Req(np.arange(5), 4)
    a.slot = pool.acquire(0)
    pool.bind_seq(a)
    for p in range(8):
        pool.ensure_writable(a.slot, p)
    pool.register_prefix(a.slot, a.prompt)
    pool.end_seq(a.slot)
    pool.release(a.slot)
    assert pool.blocks_cached == 2 and len(pool._free_blocks) == 4
    # C: a distinct 3-block request holds a live reservation
    c = _Req(np.full(9, 31), 4)          # 12 rows -> 3 blocks
    c.slot = pool.acquire(1)
    pool.bind_seq(c)
    assert pool.blocks_available == 3
    # B shares A's full first block (which is reclaimable, ref 0): the
    # share revives it out of the LRU, so availability for B's budget is
    # really 2 — if B's budget is 3, admission must be refused, not
    # approved-then-crashed
    b = _Req(np.concatenate([np.arange(5), np.full(7, 17)]), 5)  # 16 rows
    # budget: blocks_for(16)=4 minus 1 shared full = 3 > 2 effective
    assert not pool.can_admit(b)
    # after C frees, B fits and binds cleanly — sharing A's full first
    # block AND its registered partial tail (prefix length 5)
    pool.end_seq(c.slot)
    pool.release(c.slot)
    assert pool.can_admit(b)
    b.slot = pool.acquire(2)
    assert pool.bind_seq(b) == 5


def test_paged_pool_invariants():
    """Direct block-pool discipline: no double slot occupancy (inherited),
    no allocation without budget, no double free, reservation returned at
    end_seq, cached blocks evicted LRU only under pressure."""
    pool = PagedKVPool(2, 2, 2, 16, 4, block_size=4, n_blocks=6)
    assert pool.blocks_per_seq == 4 and pool.blocks_available == 6

    class _Req:                      # what can_admit/bind_seq consume
        def __init__(self, prompt, max_new):
            self.prompt = np.asarray(prompt, np.int32)
            self.max_new_tokens = max_new
            self.slot = None
            self.prefill_pos = None

    r = _Req(np.arange(9), 8)        # 16 rows -> 4 blocks
    assert pool.can_admit(r)
    r.slot = pool.acquire(0)
    assert pool.bind_seq(r) == 0     # nothing registered yet: no sharing
    assert pool.blocks_available == 2
    with pytest.raises(RuntimeError, match="live block table or reserv"):
        pool.begin_seq(r.slot, r.prompt, 2)
    # a second fat request fits a slot but not the block budget
    r2 = _Req(np.arange(9), 8)
    assert not pool.can_admit(r2)
    # writes allocate on demand, contiguously
    first = pool.ensure_writable(r.slot, 0)
    assert first is None and len(pool.tables[r.slot]) == 1
    with pytest.raises(RuntimeError, match="contiguously"):
        pool.ensure_writable(r.slot, 9)
    for p in range(1, 9):            # the rest of the prompt's rows
        assert pool.ensure_writable(r.slot, p) is None
    assert len(pool.tables[r.slot]) == 3
    pool.register_prefix(r.slot, r.prompt)
    used = list(pool.tables[r.slot])
    pool.end_seq(r.slot)
    pool.release(r.slot)
    assert pool.blocks_available == 6        # reservation returned
    assert pool.blocks_cached == len(used)   # registered blocks reclaimable
    with pytest.raises(RuntimeError, match="double free"):
        pool._unref_block(used[0])
    # pressure evicts the cached blocks instead of failing
    r3 = _Req(np.full(9, 99), 8)             # 16 rows -> 4 blocks, no overlap
    assert pool.can_admit(r3)
    r3.slot = pool.acquire(3)
    pool.bind_seq(r3)
    for p in range(16):
        pool.ensure_writable(r3.slot, p)
    assert pool.evictions_total >= 1 and pool.blocks_cached < len(used)
    with pytest.raises(ValueError, match="n_blocks"):
        PagedKVPool(2, 2, 2, 16, 4, block_size=4, n_blocks=3)


# ---------------------------------------------------------------------------
# metrics + simulator


def test_serve_metrics_populated(tmp_path):
    stages, _ = _model()
    metrics = ServeMetrics(outdir=str(tmp_path))
    eng = InferenceEngine(stages, CFG, n_slots=2, metrics=metrics)
    for i in range(3):
        eng.submit(_prompt(4, 40 + i), max_new_tokens=4, seed=80 + i)
    eng.drain()
    s = metrics.summary()
    assert s["requests_submitted"] == s["requests_completed"] == 3
    assert s["tokens_generated"] == 12
    assert s["ttft_ms_p50"] > 0 and s["tpot_ms_p50"] is not None
    assert 0 < s["slot_occupancy_mean"] <= 1
    assert metrics.ttft_ms.count == 3        # one TTFT per request
    assert metrics.tpot_ms.count == 9        # tokens after the first
    rec = metrics.emit(extra={"n_slots": 2})
    assert rec["kind"] == "serve" and rec["schema"] == 2
    got = json.loads(open(os.path.join(tmp_path, "metrics.jsonl"))
                     .read().splitlines()[-1])
    assert got["tokens_generated"] == 12
    prom = open(os.path.join(tmp_path, "metrics.prom")).read()
    assert "serve_tokens_generated_total 12" in prom
    assert 'serve_ttft_ms{quantile="0.5"}' in prom


@pytest.mark.slow
def test_shared_prefix_simulator_deterministic_and_shared(tmp_path):
    """``shared_prefix_len``: every simulated prompt carries one common
    seeded prefix; the paged engine serves it from shared blocks (prefix
    hits observed), the block metrics land in JSONL + Prometheus, and the
    tokens stay deterministic and bit-exact vs solo decodes."""
    stages, params = _model()
    sim = SimConfig(n_requests=6, rate=200.0, seed=5, prompt_lens=(4, 7),
                    max_new_tokens=5, shared_prefix_len=9)

    def run(outdir=None):
        eng = InferenceEngine(stages, CFG, n_slots=2, block_size=4,
                              prefill_chunk=3,
                              metrics=ServeMetrics(outdir=outdir))
        report = simulate(eng, sim)
        return (eng, report,
                [eng.requests[rid].tokens for rid in sorted(eng.requests)])

    eng, rep1, toks1 = run(outdir=str(tmp_path))
    _, rep2, toks2 = run()
    assert rep1["all_completed"] and rep2["all_completed"]
    assert toks1 == toks2
    st = eng.pool.stats()
    assert st["prefix_hit_blocks_total"] > 0, st
    # parity: the shared-prefix workload still matches per-request solo
    from simple_distributed_machine_learning_tpu.serve.simulator import (
        build_workload,
    )
    _, specs = build_workload(sim, CFG.vocab)
    for i, sp in enumerate(specs):
        assert int(sp["prompt"].shape[0]) in (13, 16)   # prefix + bucket
        want = _solo(stages, params, sp["prompt"], sp["max_new_tokens"],
                     sp["seed"], temperature=sp["temperature"],
                     top_k=sp["top_k"])
        np.testing.assert_array_equal(toks1[i], want, err_msg=f"req {i}")
    # block metrics made it into the summary, the record and the exposition
    s = eng.metrics.summary()
    for k in ("blocks_total", "blocks_in_use", "kv_bytes_resident",
              "prefix_hit_blocks", "cow_copies", "prefill_chunk_ms_p50"):
        assert k in s, k
    assert s["blocks_total"] > 0 and s["prefix_hit_blocks"] > 0
    assert s["prefill_chunk_ms_p50"] is not None   # chunk histogram fed
    rec = eng.metrics.emit()
    assert rec["prefix_hit_blocks"] == s["prefix_hit_blocks"]
    prom = open(os.path.join(tmp_path, "metrics.prom")).read()
    for name in ("serve_blocks_in_use", "serve_kv_bytes_resident",
                 "serve_prefix_hit_blocks_total",
                 'serve_prefill_chunk_ms{quantile="0.5"}'):
        assert name in prom, name
    with pytest.raises(ValueError, match="shared_prefix_len"):
        SimConfig(shared_prefix_len=-1)


def test_simulator_completes_and_is_deterministic():
    """Open-loop Poisson trace: all requests complete, and per-request
    tokens are identical across runs (scheduling cannot change outputs,
    so wall-clock admission jitter is invisible in the tokens)."""
    stages, _ = _model()
    sim = SimConfig(n_requests=6, rate=200.0, seed=5, prompt_lens=(4, 7),
                    max_new_tokens=5)

    def run():
        eng = InferenceEngine(stages, CFG, n_slots=2,
                              metrics=ServeMetrics())
        report = simulate(eng, sim)
        json.dumps(report)           # the report is pure JSON
        return report, [eng.requests[rid].tokens
                        for rid in sorted(eng.requests)]

    rep1, toks1 = run()
    rep2, toks2 = run()
    assert rep1["all_completed"] and rep2["all_completed"]
    assert toks1 == toks2
    assert rep1["tokens_generated"] == 6 * 5
    assert all(r["ttft_s"] is not None for r in rep1["requests"])
    # duration form: rate x duration expected arrivals
    assert SimConfig.from_duration(8.0, 2.0).n_requests == 16
    assert SimConfig.from_duration(1.0, 0.1).n_requests == 1
    with pytest.raises(ValueError, match="duration_s"):
        SimConfig.from_duration(8.0, 0.0)


# ---------------------------------------------------------------------------
# checkpoint -> serve, and the bench claim


def test_checkpoint_to_serve_cli(tmp_path, capsys):
    """Train a few steps, save, then --serve-sim --checkpoint-dir restores
    and serves from the trained params without retraining."""
    from simple_distributed_machine_learning_tpu.cli import main

    ckpt = str(tmp_path / "ck")
    tele = str(tmp_path / "tele")
    main(["--rank", "0", "--world_size", "1", "--model", "gpt",
          "--stages", "2", "--epochs", "1", "--dryrun", "2",
          "--batch-size", "8", "--microbatches", "2",
          "--checkpoint-dir", ckpt])
    capsys.readouterr()
    main(["--rank", "0", "--world_size", "1", "--model", "gpt",
          "--stages", "2", "--serve-sim", "4", "--serve-rate", "100",
          "--serve-slots", "2", "--serve-max-new", "4",
          "--checkpoint-dir", ckpt, "--telemetry-dir", tele])
    out = capsys.readouterr().out
    assert "| serve: restored params from" in out
    assert "Train Epoch" not in out           # no retraining
    assert "| serve: 4/4 requests completed" in out
    recs = [json.loads(ln) for ln in
            open(os.path.join(tele, "metrics.jsonl")).read().splitlines()]
    assert recs[-1]["kind"] == "serve" and recs[-1]["completed"] == 4


def test_serve_sim_fresh_init_cli(capsys):
    from simple_distributed_machine_learning_tpu.cli import main

    main(["--rank", "0", "--world_size", "1", "--model", "gpt",
          "--serve-sim", "3", "--serve-rate", "100", "--serve-slots", "2",
          "--serve-max-new", "3"])
    out = capsys.readouterr().out
    assert "| serve: fresh-initialized params" in out
    assert "| serve: 3/3 requests completed" in out


@pytest.mark.slow
def test_serve_sim_paged_flags_cli(capsys):
    """The paged serving flags end-to-end: small blocks, chunked prefill
    and a shared prefix through --serve-sim; the block-stats line reports
    prefix-share hits (> 0 — every prompt shares the system prefix)."""
    from simple_distributed_machine_learning_tpu.cli import main

    main(["--rank", "0", "--world_size", "1", "--model", "gpt",
          "--serve-sim", "4", "--serve-rate", "100", "--serve-slots", "2",
          "--serve-max-new", "3", "--serve-block-size", "4",
          "--serve-prefill-chunk", "3", "--serve-shared-prefix", "9"])
    out = capsys.readouterr().out
    assert "| serve: 4/4 requests completed" in out
    assert "prefix-share hits" in out
    hits = int(out.split(" prefix-share hits")[0].split(",")[-1].strip())
    assert hits > 0, out


def test_serve_cli_flag_validation():
    from simple_distributed_machine_learning_tpu.cli import main

    base = ["--rank", "0", "--world_size", "1", "--model", "gpt",
            "--serve-sim", "2"]
    with pytest.raises(SystemExit, match="serve-block-size"):
        main(base + ["--serve-block-size", "0"])
    with pytest.raises(SystemExit, match="serve-prefill-chunk"):
        main(base + ["--serve-prefill-chunk", "-1"])
    with pytest.raises(SystemExit, match="serve-shared-prefix"):
        main(base + ["--serve-shared-prefix", "-2"])
    with pytest.raises(SystemExit, match="leaves no room"):
        main(base + ["--serve-shared-prefix", "60"])
    with pytest.raises(SystemExit, match="serve-tp"):
        main(base + ["--serve-tp", "0"])
    with pytest.raises(SystemExit, match="divide"):
        main(base + ["--serve-tp", "3"])
    with pytest.raises(SystemExit, match="serve-spec-k"):
        main(base + ["--serve-spec-k", "1"])


def test_serve_sim_rejects_sharded_builds():
    from simple_distributed_machine_learning_tpu.cli import main

    with pytest.raises(SystemExit, match="dense single-device"):
        main(["--rank", "0", "--model", "gpt", "--serve-sim", "2",
              "--experts", "4"])
    with pytest.raises(SystemExit, match="only supported with"):
        main(["--rank", "0", "--model", "mlp", "--serve-sim", "2"])


def test_bench_continuous_beats_sequential():
    """The acceptance anchor: batched continuous decoding sustains higher
    aggregate tokens/sec than sequential one-request-at-a-time decode at
    the same model size, with TTFT/TPOT quantiles reported."""
    import bench
    from bench import measure_serving

    artifact = os.path.join(bench.REPO, "benchmarks", "serving.json")
    existed = os.path.exists(artifact)
    # rate far above service capacity so the continuous batch actually
    # fills (at low offered load both engines are arrival-bound and tie);
    # compare=False: the paged-vs-dense comparison has its own test
    rows = measure_serving(rates=(2000.0,), n_requests=12, slots=4,
                           max_new=12, cfg=CFG, prompt_lens=(4, 8),
                           compare=False)
    seq = next(r for r in rows if r["config"] == "gpt_serve_sequential")
    cont = next(r for r in rows if r["config"] == "gpt_serve")
    assert seq["completed"] == cont["completed"] == 12
    assert cont["tokens_per_sec"] > seq["tokens_per_sec"], (cont, seq)
    for r in (seq, cont):
        for k in ("ttft_ms_p50", "ttft_ms_p95", "tpot_ms_p50",
                  "tpot_ms_p95"):
            assert r[k] is not None and r[k] > 0, (k, r)
    # CPU smoke shapes never write the TPU sweep's artifact
    assert os.path.exists(artifact) == existed


@pytest.mark.slow
def test_bench_paged_sustains_more_concurrency_at_fixed_memory():
    """The tentpole's memory claim, measured: at (near-)equal KV-cache
    bytes the paged pool boards strictly more concurrent requests than the
    dense slot pool — a dense row reserves max_len positions, a paged
    sequence only its actual blocks. Structural, not timing-dependent: the
    burst arrives all at once and concurrency is capped by memory."""
    import jax as _jax

    from bench import _measure_paged_vs_dense
    from simple_distributed_machine_learning_tpu.models.gpt import (
        make_gpt_stages as _mk,
    )

    stages = _mk(_jax.random.key(0), CFG, n_stages=1)[0]
    # fixed_mem only: the longprompt stall rows are timing-based and get
    # their own slow-marked test on a prefill-dominated shape
    rows = _measure_paged_vs_dense(stages, CFG, slots=4, n_requests=12,
                                   max_new=8, prompt_lens=(4, 8),
                                   block_size=8, parts=("fixed_mem",))
    dense = next(r for r in rows
                 if r["config"] == "gpt_serve_dense_fixed_mem")
    paged = next(r for r in rows
                 if r["config"] == "gpt_serve_paged_fixed_mem")
    assert dense["completed"] == dense["n_requests"]
    assert paged["completed"] == paged["n_requests"]
    # same usable block capacity (paged adds only the 1-block trash page)
    assert paged["kv_bytes"] <= dense["kv_bytes"] * 1.2
    assert paged["max_concurrent"] > dense["max_concurrent"], (paged, dense)


@pytest.mark.slow
def test_bench_chunked_prefill_cuts_stall_tick_latency():
    """The tentpole's latency claim, measured on a prefill-dominated shape
    (long prompt ~= seq budget): with chunked prefill the worst decode-tick
    latency under a long-prompt arrival is lower than the monolithic
    baseline's. Timing-based, so: a shape where the effect is ~2x, and
    best-of-3 to ride out scheduler noise."""
    import jax as _jax

    from bench import _measure_paged_vs_dense
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig as _Cfg,
        make_gpt_stages as _mk,
    )

    cfg = _Cfg(vocab=64, seq_len=192, d_model=64, n_heads=4, n_layers=2)
    stages = _mk(_jax.random.key(0), cfg, n_stages=1)[0]
    last = None
    for _ in range(3):
        rows = _measure_paged_vs_dense(stages, cfg, slots=4, n_requests=8,
                                       max_new=8, prompt_lens=(4, 8),
                                       block_size=16,
                                       parts=("longprompt",))
        mono = next(r for r in rows
                    if r["config"] == "gpt_serve_dense_longprompt")
        chunked = next(
            r for r in rows
            if r["config"] == "gpt_serve_paged_chunked_longprompt")
        last = (chunked, mono)
        if (chunked["tick_ms_max"] < mono["tick_ms_max"]
                and chunked["tick_ms_p95"] < mono["tick_ms_p95"]):
            return
    raise AssertionError(f"chunked prefill never beat monolithic: {last}")


# ---------------------------------------------------------------------------
# speculative decoding (draft/verify) + tensor-parallel serving (ISSUE 9)
#
# The PR-5 anchor extends: a GREEDY request served speculatively emits
# bit-exactly its solo make_cached_decoder tokens — the verify rows are the
# same math the plain decode tick computes, and greedy acceptance emits the
# target's own argmaxes. TP=2 must reproduce TP=1 token-for-token (the
# all-reduce + pmean row-closing makes every shard sample identical rows).


DRAFT_CFG = dataclasses.replace(CFG, n_layers=1)
_DRAFT_STAGES = None


def _draft_model():
    global _DRAFT_STAGES
    if _DRAFT_STAGES is None:
        _DRAFT_STAGES = make_gpt_stages(jax.random.key(9), DRAFT_CFG, 1)[0]
    return _DRAFT_STAGES


def _spec_engine(layout="paged", slots=3, spec_k=4, draft_stages=None,
                 draft_cfg=None, **kw):
    stages, _ = _model()
    if layout == "paged":
        kw.setdefault("block_size", 8)
    return InferenceEngine(
        stages, CFG, n_slots=slots, kv_layout=layout,
        draft_stages=(_draft_model() if draft_stages is None
                      else draft_stages),
        draft_cfg=draft_cfg or DRAFT_CFG, spec_k=spec_k, **kw)


def test_spec_greedy_bitexact_mixed_and_midflight():
    """Greedy speculative decode, paged layout: mixed prompt lengths with
    queueing plus a mid-flight admission — every request's tokens equal
    its solo decode exactly (the acceptance rule's bit-exactness pin)."""
    stages, params = _model()
    eng = _spec_engine(slots=2)
    specs = [
        dict(prompt=_prompt(3, 60), max_new_tokens=9, seed=70),
        dict(prompt=_prompt(9, 61), max_new_tokens=5, seed=71),
        dict(prompt=_prompt(5, 62), max_new_tokens=8, seed=72),
    ]
    handles = [eng.submit(**s) for s in specs]
    for _ in range(3):                  # first requests mid-stream
        eng.step()
    late = dict(prompt=_prompt(6, 63), max_new_tokens=7, seed=73)
    handles.append(eng.submit(**late))
    specs.append(late)
    eng.drain()
    for h, s in zip(handles, specs):
        np.testing.assert_array_equal(
            h.tokens, _solo(stages, params, s["prompt"],
                            s["max_new_tokens"], s["seed"]))


def test_spec_eos_early_exit_parity():
    """EOS mid-verify: the emitted tokens stop at (and include) the first
    EOS even when the tick accepted a longer prefix — the retired slot's
    already-written tail K/V is unreachable (trailing-write/trash-page
    discipline), so co-residents stay bit-exact."""
    stages, params = _model()
    solo = _solo(stages, params, _prompt(5, 64), 8, 74)
    eos = int(solo[2])
    cut = int(np.where(solo == eos)[0][0]) + 1
    eng = _spec_engine(slots=2)
    r = eng.submit(_prompt(5, 64), max_new_tokens=8, seed=74, eos_id=eos)
    r2 = eng.submit(_prompt(4, 65), max_new_tokens=6, seed=75)
    eng.drain()
    assert r.finish_reason == "eos"
    assert len(r.tokens) == cut < 8
    np.testing.assert_array_equal(r.tokens, solo[:cut])
    np.testing.assert_array_equal(
        r2.tokens, _solo(stages, params, r2.prompt, 6, 75))


@pytest.mark.slow
def test_spec_dense_layout_parity():
    """The dense slot pool serves the same speculative streams."""
    stages, params = _model()
    eng = _spec_engine(layout="dense", slots=2)
    handles = [eng.submit(_prompt(n, 80 + n), max_new_tokens=7, seed=80 + n)
               for n in (3, 7, 5)]
    eng.drain()
    for h in handles:
        np.testing.assert_array_equal(
            h.tokens, _solo(stages, params, h.prompt, 7, h.seed))


@pytest.mark.slow
def test_spec_preemption_parity():
    """PR-7 preemption composes with speculative decoding: a victim
    requeues mid-stream, re-prefills (target AND draft caches rebuilt) and
    continues bit-exact vs its solo decode."""
    stages, params = _model()
    eng = _spec_engine(slots=2, prefill_chunk=8)
    r1 = eng.submit(_prompt(4, 90), max_new_tokens=10, seed=90)
    r2 = eng.submit(_prompt(6, 91), max_new_tokens=8, seed=91)
    for _ in range(3):
        eng.step()
    assert 0 < len(r1.tokens) < 10
    eng.preempt(r1.rid)
    assert r1.n_preempted == 1
    eng.drain()
    np.testing.assert_array_equal(
        r1.tokens, _solo(stages, params, r1.prompt, 10, 90))
    np.testing.assert_array_equal(
        r2.tokens, _solo(stages, params, r2.prompt, 8, 91))


def test_spec_accept_all_rate_and_tokens_per_tick():
    """draft == target: every greedy proposal verifies — accept_rate pins
    at 1.0, a full-budget tick emits spec_k tokens, and the spec counters
    + shape gauges land in the metrics record."""
    stages, params = _model()
    metrics = ServeMetrics()
    eng = _spec_engine(slots=2, spec_k=4, draft_stages=stages,
                       draft_cfg=CFG, metrics=metrics)
    r = eng.submit(_prompt(5, 95), max_new_tokens=8, seed=95)
    eng.step()                               # admit + prefill + first tick
    ticks = 1
    while r.state != DONE:
        eng.step()
        ticks += 1
    np.testing.assert_array_equal(
        r.tokens, _solo(stages, params, r.prompt, 8, 95))
    # 8 tokens at 4/tick: the first tick prefills AND verifies (paged
    # whole-prompt chunk), so the whole request takes exactly 2 ticks
    assert ticks == 2, ticks
    s = metrics.summary()
    assert s["spec_accept_rate"] == 1.0
    assert s["spec_proposed_tokens"] == s["spec_accepted_tokens"] > 0
    assert s["spec_rejected_tokens"] == 0
    assert s["tp"] == 1 and s["spec_k"] == 4


@pytest.mark.slow
def test_spec_sampled_deterministic_per_seed():
    """Sampled speculative streams are deterministic per seed (the
    residual-rejection draws come from the request's own key streams) and
    a greedy co-resident still matches its solo decode exactly."""
    stages, params = _model()

    def run():
        eng = _spec_engine(slots=2, spec_k=3)
        h1 = eng.submit(_prompt(5, 96), max_new_tokens=7, seed=96,
                        temperature=0.9, top_k=6)
        h2 = eng.submit(_prompt(4, 97), max_new_tokens=6, seed=97)
        eng.drain()
        return list(h1.tokens), list(h2.tokens)

    a1, a2 = run()
    b1, b2 = run()
    assert a1 == b1
    np.testing.assert_array_equal(
        a2, _solo(stages, params, _prompt(4, 97), 6, 97))
    assert a2 == b2


def _tp_engine(layout, tp, spec=False, **kw):
    from simple_distributed_machine_learning_tpu.parallel.mesh import (
        make_mesh,
    )
    stages, _ = _model()
    cfg = dataclasses.replace(CFG, n_tensor_parallel=tp)
    mesh = make_mesh(n_stages=1, n_data=1, n_model=tp) if tp > 1 else None
    if layout == "paged":
        kw.setdefault("block_size", 8)
    if spec:
        kw.update(draft_stages=_draft_model(), draft_cfg=DRAFT_CFG,
                  spec_k=4)
    return InferenceEngine(stages, cfg, n_slots=2, kv_layout=layout,
                           mesh=mesh, **kw)


def test_tp2_matches_tp1_dense():
    """TP=2 serving on a 2-CPU-device model mesh reproduces the TP=1
    stream token-for-token (dense layout): head-sharded QKV/O + the
    collective-matmul MLP + the pmean row-closing are the same math."""
    stages, params = _model()
    eng = _tp_engine("dense", 2)
    assert eng.pool.tp == 2
    handles = [eng.submit(_prompt(n, 100 + n), max_new_tokens=6,
                          seed=100 + n) for n in (4, 7)]
    eng.drain()
    for h in handles:
        np.testing.assert_array_equal(
            h.tokens, _solo(stages, params, h.prompt, 6, h.seed))


@pytest.mark.slow
def test_tp2_matches_tp1_paged_and_gauge_per_shard():
    """Paged TP=2 parity, plus the byte accounting: the pool's
    serve_kv_bytes_resident gauge reports PER-SHARD bytes and equals the
    analyzer's per-shard prediction exactly."""
    from simple_distributed_machine_learning_tpu.analysis.programs import (
        ServeSpec,
        predict_kv_bytes_resident,
    )
    stages, params = _model()
    eng = _tp_engine("paged", 2)
    handles = [eng.submit(_prompt(n, 110 + n), max_new_tokens=6,
                          seed=110 + n) for n in (4, 7)]
    for _ in range(4):
        eng.step()
    rows = []
    for h in handles:
        if h.state != ACTIVE:
            continue
        rows.append(h.prefill_pos if h.prefill_pos is not None
                    else int(h.prompt.shape[0]) + len(h.tokens) - 1)
    sspec = ServeSpec(dataclasses.replace(CFG, n_tensor_parallel=2),
                      n_slots=2, kv_layout="paged", block_size=8)
    assert (predict_kv_bytes_resident(sspec, [r for r in rows if r > 0])
            == eng.pool.stats()["kv_bytes_resident"] > 0)
    eng.drain()
    for h in handles:
        np.testing.assert_array_equal(
            h.tokens, _solo(stages, params, h.prompt, 6, h.seed))


@pytest.mark.slow
def test_tp2_with_speculation_matches_solo():
    """Both tentpole axes at once: a TP=2 target verifying a replicated
    draft's proposals still reproduces the solo stream exactly."""
    stages, params = _model()
    eng = _tp_engine("paged", 2, spec=True)
    handles = [eng.submit(_prompt(n, 120 + n), max_new_tokens=6,
                          seed=120 + n) for n in (3, 6)]
    eng.drain()
    for h in handles:
        np.testing.assert_array_equal(
            h.tokens, _solo(stages, params, h.prompt, 6, h.seed))


def test_spec_and_tp_engine_validation():
    """Constructor contracts: the half-configured speculative/TP states
    all refuse loudly (no compiles happen on these paths)."""
    stages, _ = _model()
    with pytest.raises(ValueError, match="spec_k >= 2"):
        InferenceEngine(stages, CFG, n_slots=2,
                        draft_stages=_draft_model(), draft_cfg=DRAFT_CFG,
                        spec_k=1)
    with pytest.raises(ValueError, match="BOTH draft_stages"):
        InferenceEngine(stages, CFG, n_slots=2, draft_stages=stages,
                        spec_k=4)
    with pytest.raises(ValueError, match="without draft_stages"):
        InferenceEngine(stages, CFG, n_slots=2, spec_k=4)
    with pytest.raises(ValueError, match="vocab"):
        bad = dataclasses.replace(DRAFT_CFG, vocab=CFG.vocab + 1)
        InferenceEngine(stages, CFG, n_slots=2,
                        draft_stages=_draft_model(), draft_cfg=bad,
                        spec_k=4)
    with pytest.raises(ValueError, match="mesh"):
        InferenceEngine(stages,
                        dataclasses.replace(CFG, n_tensor_parallel=2),
                        n_slots=2)
    from simple_distributed_machine_learning_tpu.models.gpt import (
        make_slot_propose,
    )
    with pytest.raises(ValueError, match="single-device"):
        make_slot_propose(stages,
                          dataclasses.replace(CFG, n_tensor_parallel=2),
                          16, 4)


def test_bench_spec_beats_plain_2x():
    """The acceptance gate: with draft == target (accept-all) the
    speculative engine serves >= 2x the plain engine's aggregate
    tokens-per-tick on the identical workload — deterministic tick
    counts, not wall clock, so a loaded CI box cannot flake it."""
    from bench import _measure_spec_vs_plain
    stages, _ = _model()
    [row] = _measure_spec_vs_plain(stages, CFG, slots=3, n_requests=8,
                                   max_new=16, prompt_lens=(4, 8),
                                   block_size=8)
    assert row["accept_rate"] == 1.0
    assert row["speedup_vs_plain"] >= 2.0, row
    assert row["ticks_spec"] < row["ticks_plain"]
    for k in ("wall_tokens_per_sec_spec", "wall_tokens_per_sec_plain"):
        assert row[k] > 0
