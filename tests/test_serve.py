"""Continuous-batching serving (serve/): parity, invariants, traffic.

The load-bearing property: continuous batching is a SCHEDULING optimization,
not a math change — for a fixed seed, every request's tokens are bit-exact
vs decoding it alone through ``models.make_cached_decoder``, across mixed
prompt lengths, mid-flight admissions, EOS early exits, and every sampling
mode. Plus the scheduler invariants (no double occupancy, every request
completes, freed slots reuse next tick, queues drain above capacity), the
serving metrics, the simulator, the checkpoint→serve path, and the
bench sweep's continuous-beats-sequential claim.
"""

import json
import os

import jax
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    make_cached_decoder,
    make_gpt_stages,
    make_slot_decode_step,
    make_slot_prefill,
)
from simple_distributed_machine_learning_tpu.serve import (
    InferenceEngine,
    ServeMetrics,
    SimConfig,
    simulate,
)
from simple_distributed_machine_learning_tpu.serve.request import (
    DONE,
    Request,
    validate_request,
)
from simple_distributed_machine_learning_tpu.serve.slots import KVCachePool

CFG = GPTConfig(vocab=32, seq_len=48, d_model=32, n_heads=2, n_layers=2)
_STAGES = None


def _model():
    global _STAGES
    if _STAGES is None:
        _STAGES = make_gpt_stages(jax.random.key(0), CFG, 2)[0]
    return _STAGES, [s.params for s in _STAGES]


def _solo(stages, params, prompt, n_new, seed, temperature=0.0, top_k=None,
          top_p=None):
    """The reference tokens: this request decoded ALONE through the
    one-shot KV-cache decoder with the same seed and sampling params."""
    dec = make_cached_decoder(stages, CFG, len(prompt), n_new,
                              temperature=temperature, top_k=top_k,
                              top_p=top_p)
    out = dec(params, np.asarray(prompt, np.int32)[None],
              jax.random.key(seed))
    return np.asarray(out)[0, len(prompt):]


def _prompt(n, seed):
    return np.asarray(
        jax.random.randint(jax.random.key(seed), (n,), 0, CFG.vocab),
        np.int32)


# ---------------------------------------------------------------------------
# parity: bit-exact vs solo decode


def test_single_request_matches_solo_decode():
    stages, params = _model()
    eng = InferenceEngine(stages, CFG, n_slots=3)
    r = eng.submit(_prompt(5, 1), max_new_tokens=6, seed=11)
    eng.drain()
    assert r.state == DONE and r.finish_reason == "length"
    np.testing.assert_array_equal(
        r.tokens, _solo(stages, params, r.prompt, 6, 11))


def test_mixed_prompt_lengths_and_sampling_parity():
    """5 requests, 2 slots (so queueing + mid-flight boarding happens),
    mixed prompt lengths and sampling modes — each request's tokens are
    bit-exact vs its solo decode."""
    stages, params = _model()
    eng = InferenceEngine(stages, CFG, n_slots=2)
    specs = [
        dict(prompt=_prompt(3, 2), max_new_tokens=7, seed=20),
        dict(prompt=_prompt(9, 3), max_new_tokens=5, seed=21,
             temperature=0.8, top_k=5),
        dict(prompt=_prompt(5, 4), max_new_tokens=8, seed=22,
             temperature=0.9, top_p=0.9),
        dict(prompt=_prompt(7, 5), max_new_tokens=4, seed=23),
        dict(prompt=_prompt(4, 6), max_new_tokens=6, seed=24,
             temperature=1.1, top_k=7, top_p=0.8),
    ]
    handles = [eng.submit(**s) for s in specs]
    eng.drain()
    for h, s in zip(handles, specs):
        want = _solo(stages, params, s["prompt"], s["max_new_tokens"],
                     s["seed"], temperature=s.get("temperature", 0.0),
                     top_k=s.get("top_k"), top_p=s.get("top_p"))
        np.testing.assert_array_equal(np.asarray(h.tokens), want,
                                      err_msg=f"request {h.rid}")


def test_mid_flight_admission_parity():
    """A request admitted while another is mid-decode gets the same tokens
    as its solo decode — co-residents cannot change anyone's output."""
    stages, params = _model()
    eng = InferenceEngine(stages, CFG, n_slots=2)
    r1 = eng.submit(_prompt(6, 7), max_new_tokens=10, seed=30)
    for _ in range(4):                       # r1 alone for 4 ticks
        eng.step()
    assert 0 < len(r1.tokens) < 10
    r2 = eng.submit(_prompt(4, 8), max_new_tokens=6, seed=31,
                    temperature=0.7, top_k=4)
    eng.drain()
    np.testing.assert_array_equal(
        r1.tokens, _solo(stages, params, r1.prompt, 10, 30))
    np.testing.assert_array_equal(
        r2.tokens, _solo(stages, params, r2.prompt, 6, 31,
                         temperature=0.7, top_k=4))


def test_eos_early_exit_parity_and_slot_free():
    """EOS retires the request with a PREFIX of its solo decode (up to and
    including the first EOS) and frees the slot immediately."""
    stages, params = _model()
    solo = _solo(stages, params, _prompt(5, 9), 8, 40)
    eos = int(solo[2])                       # an eos the solo decode emits
    cut = int(np.where(solo == eos)[0][0]) + 1   # ...its FIRST occurrence
    eng = InferenceEngine(stages, CFG, n_slots=1)
    r = eng.submit(_prompt(5, 9), max_new_tokens=8, seed=40, eos_id=eos)
    eng.drain()
    assert r.finish_reason == "eos"
    assert len(r.tokens) == cut < 8
    np.testing.assert_array_equal(r.tokens, solo[:cut])
    assert eng.pool.n_free == 1


# ---------------------------------------------------------------------------
# scheduler invariants


def test_queue_drains_above_capacity_no_double_occupancy():
    """9 requests through 2 slots: occupancy never exceeds capacity, a
    slot never hosts two requests (pool guards raise), every request
    completes, and a freed slot is reused on the next tick."""
    stages, params = _model()
    eng = InferenceEngine(stages, CFG, n_slots=2)
    handles = [eng.submit(_prompt(3 + i % 3, 10 + i),
                          max_new_tokens=3 + i % 4, seed=50 + i)
               for i in range(9)]
    max_active = 0
    while eng.busy:
        queued_before = eng.scheduler.queue_depth
        eng.step()
        assert eng.pool.n_active <= 2
        max_active = max(max_active, eng.pool.n_active)
        # FCFS: the queue never grows mid-run (no re-queueing); slots can
        # all retire within one decode tick, so n_active == 0 with work
        # still queued is legal — the next tick's admission boards it
        assert eng.scheduler.queue_depth <= queued_before
        occ = [eng.pool.occupant(s) for s in eng.pool.active_slots()]
        assert len(occ) == len(set(occ))     # no slot double-occupied
    assert all(h.state == DONE for h in handles)
    assert eng.scheduler.queue_depth == 0
    assert max_active == 2                   # the batch actually filled
    # each completed with its requested token budget, and parity held
    for i, h in enumerate(handles):
        assert len(h.tokens) == 3 + i % 4
        np.testing.assert_array_equal(
            h.tokens, _solo(stages, params, h.prompt, len(h.tokens),
                            50 + i))


def test_freed_slot_reusable_next_tick():
    stages, _ = _model()
    eng = InferenceEngine(stages, CFG, n_slots=1)
    r1 = eng.submit(_prompt(4, 30), max_new_tokens=1, seed=60)
    r2 = eng.submit(_prompt(6, 31), max_new_tokens=5, seed=61)
    eng.step()                    # tick 1: r1 prefills, finishes, frees
    assert r1.state == DONE and eng.pool.n_free == 1
    assert r2.state == "queued"
    eng.step()                    # tick 2: r2 boards the freed slot
    assert r2.state == "active" and r2.slot is not None
    assert len(r2.tokens) == 2    # prefill token + one decode tick
    eng.drain()
    assert r2.state == DONE and len(r2.tokens) == 5


def test_pool_guards():
    pool = KVCachePool(2, 2, 2, 8, 4)
    a = pool.acquire(0)
    b = pool.acquire(1)
    assert {a, b} == {0, 1}
    with pytest.raises(RuntimeError, match="full pool"):
        pool.acquire(2)
    pool.release(a)
    with pytest.raises(RuntimeError, match="already-free"):
        pool.release(a)
    assert pool.acquire(3) == a   # freed slot comes back


def test_request_validation():
    stages, _ = _model()
    eng = InferenceEngine(stages, CFG, n_slots=1, max_len=16)
    with pytest.raises(ValueError, match="exceeds the pool"):
        eng.submit(_prompt(10, 0), max_new_tokens=7)
    with pytest.raises(ValueError, match="non-empty"):
        eng.submit(np.zeros(0, np.int32), max_new_tokens=2)
    with pytest.raises(ValueError, match="temperature > 0"):
        eng.submit(_prompt(4, 0), max_new_tokens=2, top_k=3)
    with pytest.raises(ValueError, match="top_k"):
        eng.submit(_prompt(4, 0), max_new_tokens=2, temperature=1.0,
                   top_k=999)
    with pytest.raises(ValueError, match="top_p"):
        eng.submit(_prompt(4, 0), max_new_tokens=2, temperature=1.0,
                   top_p=1.5)
    with pytest.raises(ValueError, match="max_len"):
        make_slot_prefill(stages, CFG, CFG.seq_len + 1)
    with pytest.raises(ValueError, match="max_len"):
        make_slot_decode_step(stages, CFG, 1)
    # engine-independent request plumbing
    validate_request(np.zeros(3, np.int32), 2, 0.0, None, None, 32, 16)
    r = Request(rid=0, prompt=np.zeros(3, np.int32), max_new_tokens=4)
    assert r.finished_by(7) is None


def test_streaming_callback_order():
    stages, params = _model()
    eng = InferenceEngine(stages, CFG, n_slots=1)
    seen = []
    r = eng.submit(_prompt(4, 12), max_new_tokens=5, seed=70,
                   on_token=lambda req, t: seen.append((req.rid, t)))
    eng.drain()
    assert seen == [(r.rid, t) for t in r.tokens]
    assert len(seen) == 5


# ---------------------------------------------------------------------------
# metrics + simulator


def test_serve_metrics_populated(tmp_path):
    stages, _ = _model()
    metrics = ServeMetrics(outdir=str(tmp_path))
    eng = InferenceEngine(stages, CFG, n_slots=2, metrics=metrics)
    for i in range(3):
        eng.submit(_prompt(4, 40 + i), max_new_tokens=4, seed=80 + i)
    eng.drain()
    s = metrics.summary()
    assert s["requests_submitted"] == s["requests_completed"] == 3
    assert s["tokens_generated"] == 12
    assert s["ttft_ms_p50"] > 0 and s["tpot_ms_p50"] is not None
    assert 0 < s["slot_occupancy_mean"] <= 1
    assert metrics.ttft_ms.count == 3        # one TTFT per request
    assert metrics.tpot_ms.count == 9        # tokens after the first
    rec = metrics.emit(extra={"n_slots": 2})
    assert rec["kind"] == "serve" and rec["schema"] == 2
    got = json.loads(open(os.path.join(tmp_path, "metrics.jsonl"))
                     .read().splitlines()[-1])
    assert got["tokens_generated"] == 12
    prom = open(os.path.join(tmp_path, "metrics.prom")).read()
    assert "serve_tokens_generated_total 12" in prom
    assert 'serve_ttft_ms{quantile="0.5"}' in prom


def test_simulator_completes_and_is_deterministic():
    """Open-loop Poisson trace: all requests complete, and per-request
    tokens are identical across runs (scheduling cannot change outputs,
    so wall-clock admission jitter is invisible in the tokens)."""
    stages, _ = _model()
    sim = SimConfig(n_requests=6, rate=200.0, seed=5, prompt_lens=(4, 7),
                    max_new_tokens=5)

    def run():
        eng = InferenceEngine(stages, CFG, n_slots=2,
                              metrics=ServeMetrics())
        report = simulate(eng, sim)
        json.dumps(report)           # the report is pure JSON
        return report, [eng.requests[rid].tokens
                        for rid in sorted(eng.requests)]

    rep1, toks1 = run()
    rep2, toks2 = run()
    assert rep1["all_completed"] and rep2["all_completed"]
    assert toks1 == toks2
    assert rep1["tokens_generated"] == 6 * 5
    assert all(r["ttft_s"] is not None for r in rep1["requests"])
    # duration form: rate x duration expected arrivals
    assert SimConfig.from_duration(8.0, 2.0).n_requests == 16
    assert SimConfig.from_duration(1.0, 0.1).n_requests == 1
    with pytest.raises(ValueError, match="duration_s"):
        SimConfig.from_duration(8.0, 0.0)


# ---------------------------------------------------------------------------
# checkpoint -> serve, and the bench claim


def test_checkpoint_to_serve_cli(tmp_path, capsys):
    """Train a few steps, save, then --serve-sim --checkpoint-dir restores
    and serves from the trained params without retraining."""
    from simple_distributed_machine_learning_tpu.cli import main

    ckpt = str(tmp_path / "ck")
    tele = str(tmp_path / "tele")
    main(["--rank", "0", "--world_size", "1", "--model", "gpt",
          "--stages", "2", "--epochs", "1", "--dryrun", "2",
          "--batch-size", "8", "--microbatches", "2",
          "--checkpoint-dir", ckpt])
    capsys.readouterr()
    main(["--rank", "0", "--world_size", "1", "--model", "gpt",
          "--stages", "2", "--serve-sim", "4", "--serve-rate", "100",
          "--serve-slots", "2", "--serve-max-new", "4",
          "--checkpoint-dir", ckpt, "--telemetry-dir", tele])
    out = capsys.readouterr().out
    assert "| serve: restored params from" in out
    assert "Train Epoch" not in out           # no retraining
    assert "| serve: 4/4 requests completed" in out
    recs = [json.loads(ln) for ln in
            open(os.path.join(tele, "metrics.jsonl")).read().splitlines()]
    assert recs[-1]["kind"] == "serve" and recs[-1]["completed"] == 4


def test_serve_sim_fresh_init_cli(capsys):
    from simple_distributed_machine_learning_tpu.cli import main

    main(["--rank", "0", "--world_size", "1", "--model", "gpt",
          "--serve-sim", "3", "--serve-rate", "100", "--serve-slots", "2",
          "--serve-max-new", "3"])
    out = capsys.readouterr().out
    assert "| serve: fresh-initialized params" in out
    assert "| serve: 3/3 requests completed" in out


def test_serve_sim_rejects_sharded_builds():
    from simple_distributed_machine_learning_tpu.cli import main

    with pytest.raises(SystemExit, match="dense single-device"):
        main(["--rank", "0", "--model", "gpt", "--serve-sim", "2",
              "--experts", "4"])
    with pytest.raises(SystemExit, match="only supported with"):
        main(["--rank", "0", "--model", "mlp", "--serve-sim", "2"])


def test_bench_continuous_beats_sequential():
    """The acceptance anchor: batched continuous decoding sustains higher
    aggregate tokens/sec than sequential one-request-at-a-time decode at
    the same model size, with TTFT/TPOT quantiles reported."""
    import bench
    from bench import measure_serving

    artifact = os.path.join(bench.REPO, "benchmarks", "serving.json")
    existed = os.path.exists(artifact)
    # rate far above service capacity so the continuous batch actually
    # fills (at low offered load both engines are arrival-bound and tie)
    rows = measure_serving(rates=(2000.0,), n_requests=12, slots=4,
                           max_new=12, cfg=CFG, prompt_lens=(4, 8))
    seq = next(r for r in rows if r["config"] == "gpt_serve_sequential")
    cont = next(r for r in rows if r["config"] == "gpt_serve")
    assert seq["completed"] == cont["completed"] == 12
    assert cont["tokens_per_sec"] > seq["tokens_per_sec"], (cont, seq)
    for r in (seq, cont):
        for k in ("ttft_ms_p50", "ttft_ms_p95", "tpot_ms_p50",
                  "tpot_ms_p95"):
            assert r[k] is not None and r[k] > 0, (k, r)
    # CPU smoke shapes never write the TPU sweep's artifact
    assert os.path.exists(artifact) == existed
