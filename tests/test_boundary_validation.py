"""Build-time boundary validation on the parallel paths (VERDICT r3 item 5).

The wire codec zero-pads/truncates, so a mis-sized stage would otherwise
train silently on fabricated zeros. Plain-path validation has been covered
since round 1 (tests/test_pipeline.py); these tests pin the TP/EP/seq paths,
which now trace the stage apply under shard_map + eval_shape instead of
being skipped.
"""

import dataclasses

import jax
import pytest

from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    make_gpt_stages,
)
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.parallel.tensor import (
    make_mlp_tp_stages,
)


def test_tp_missized_stage_raises_at_build():
    stages, wd, od = make_mlp_tp_stages(jax.random.key(0),
                                        [8, 16, 12, 16, 10], 2, 2)
    stages = list(stages)
    stages[1] = dataclasses.replace(stages[1], in_shape=(13,))
    mesh = make_mesh(n_stages=2, n_data=1, n_model=2)
    with pytest.raises(ValueError, match="stage 0 outputs 12 features"):
        Pipeline(stages, mesh, wd, od)


def test_tp_wellformed_stage_builds():
    stages, wd, od = make_mlp_tp_stages(jax.random.key(0),
                                        [8, 16, 12, 16, 10], 2, 2)
    mesh = make_mesh(n_stages=2, n_data=1, n_model=2)
    Pipeline(stages, mesh, wd, od)   # must not raise


def test_ep_missized_stage_raises_at_build():
    cfg = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=2,
                    n_experts=4, moe_top_k=2, n_expert_parallel=2)
    stages, wd, od = make_gpt_stages(jax.random.key(0), cfg, 2)
    stages = list(stages)
    stages[1] = dataclasses.replace(stages[1],
                                    in_shape=(cfg.seq_len, cfg.d_model + 1))
    mesh = make_mesh(n_stages=2, n_data=1, n_expert=2)
    with pytest.raises(ValueError, match="features"):
        Pipeline(stages, mesh, wd, od)


def test_seq_missized_stage_raises_at_build():
    cfg = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=4, n_layers=2,
                    attn_impl="ring", n_seq=2)
    stages, wd, od = make_gpt_stages(jax.random.key(0), cfg, 2)
    t_loc = cfg.seq_len // 2
    stages = list(stages)
    stages[1] = dataclasses.replace(stages[1],
                                    in_shape=(t_loc, cfg.d_model + 1))
    mesh = make_mesh(n_stages=2, n_data=1, n_seq=2)
    with pytest.raises(ValueError, match="features"):
        Pipeline(stages, mesh, wd, od)


def test_seq_last_stage_width_mismatch_raises_at_build():
    """A seq-parallel pipeline whose declared out_shape disagrees with the
    last stage's per-shard output width is caught at build."""
    cfg = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=4, n_layers=2,
                    attn_impl="ring", n_seq=2)
    stages, wd, _ = make_gpt_stages(jax.random.key(0), cfg, 2)
    mesh = make_mesh(n_stages=2, n_data=1, n_seq=2)
    with pytest.raises(ValueError, match="out_shape"):
        Pipeline(stages, mesh, wd, (cfg.seq_len, cfg.vocab + 1))
