"""LR schedules + gradient clipping: math, torch parity, CLI wiring.

The reference trains at one constant lr (simple_distributed.py:20,:103);
these are framework extensions, pinned against torch's lr_scheduler /
clip_grad_norm_ semantics so a torch user gets identical trajectories.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.train import schedules
from simple_distributed_machine_learning_tpu.train.optimizer import (
    adamw,
    clip_by_global_norm,
    sgd,
)


def test_cosine_matches_closed_form():
    sched = schedules.cosine(0.5, 100, final_frac=0.1)
    for t in [0, 1, 37, 99, 100, 250]:
        frac = min(t / 100, 1.0)
        want = 0.5 * (0.1 + 0.9 * 0.5 * (1 + math.cos(math.pi * frac)))
        assert float(sched(jnp.int32(t))) == pytest.approx(want, rel=1e-6)


def test_warmup_then_cosine():
    sched = schedules.warmup_cosine(1.0, 10, 110)
    # linear ramp: k-th update at (k+1)/warmup
    assert float(sched(jnp.int32(0))) == pytest.approx(0.1)
    assert float(sched(jnp.int32(9))) == pytest.approx(1.0)
    # then cosine over the remaining 100 steps
    assert float(sched(jnp.int32(10))) == pytest.approx(1.0)
    assert float(sched(jnp.int32(60))) == pytest.approx(0.5, rel=1e-6)
    assert float(sched(jnp.int32(110))) == pytest.approx(0.0, abs=1e-7)


def test_step_decay():
    sched = schedules.step_decay(0.1, 30, gamma=0.5)
    assert float(sched(jnp.int32(29))) == pytest.approx(0.1)
    assert float(sched(jnp.int32(30))) == pytest.approx(0.05)
    assert float(sched(jnp.int32(90))) == pytest.approx(0.0125)


def _run_ours(opt, params, grads_seq):
    state = opt.init(params)
    out = []
    for g in grads_seq:
        params, state = opt.update(g, state, params)
        out.append(jax.tree.map(np.asarray, params))
    return out


def test_scheduled_constant_equals_plain_sgd():
    key = jax.random.key(0)
    params = {"w": jax.random.normal(key, (4, 3)), "b": jnp.ones((3,))}
    grads_seq = [jax.tree.map(lambda a: a * (i + 1) * 0.01, params)
                 for i in range(5)]
    plain = _run_ours(sgd(0.1, 0.5), params, grads_seq)
    sched = _run_ours(sgd(schedules.constant(0.1), 0.5), params, grads_seq)
    for p, s in zip(plain, sched):
        np.testing.assert_allclose(p["w"], s["w"], rtol=1e-6)


def test_sgd_cosine_matches_torch_lambdalr():
    """torch SGD(momentum) + LambdaLR(cosine), identical grads both sides:
    per-step parameter trajectories must match."""
    import torch

    steps, total = 12, 12
    rng = np.random.RandomState(0)
    w0 = rng.randn(5, 4).astype(np.float32)
    grads = [rng.randn(5, 4).astype(np.float32) for _ in range(steps)]

    def lam(k):  # torch multiplies base_lr by lam(epoch)
        return 0.5 * (1 + math.cos(math.pi * min(k / total, 1.0)))

    tw = torch.nn.Parameter(torch.tensor(w0))
    topt = torch.optim.SGD([tw], lr=0.2, momentum=0.5)
    tsched = torch.optim.lr_scheduler.LambdaLR(topt, lam)
    torch_traj = []
    for g in grads:
        tw.grad = torch.tensor(g)
        topt.step()
        tsched.step()
        torch_traj.append(tw.detach().numpy().copy())

    ours = _run_ours(sgd(schedules.cosine(0.2, total), 0.5),
                     jnp.asarray(w0), [jnp.asarray(g) for g in grads])
    for t_w, o_w in zip(torch_traj, ours):
        np.testing.assert_allclose(t_w, o_w, rtol=1e-5, atol=1e-6)


def test_adamw_schedule_scales_first_step():
    params = jnp.ones((3,))
    g = jnp.full((3,), 0.5)
    # schedule(0) = 0 -> first update must be a no-op (decay scaled too)
    opt = adamw(schedules.step_decay(0.0, 10), weight_decay=0.1)
    p1, _ = opt.update(g, opt.init(params), params)
    np.testing.assert_allclose(np.asarray(p1), np.ones((3,)), rtol=1e-7)


def test_clip_matches_torch_clip_grad_norm():
    import torch

    rng = np.random.RandomState(1)
    w0 = rng.randn(6, 2).astype(np.float32)
    grads = [rng.randn(6, 2).astype(np.float32) * s for s in (5.0, 0.01, 2.0)]

    tw = torch.nn.Parameter(torch.tensor(w0))
    topt = torch.optim.SGD([tw], lr=0.1, momentum=0.5)
    torch_traj = []
    for g in grads:
        tw.grad = torch.tensor(g)
        torch.nn.utils.clip_grad_norm_([tw], max_norm=1.0)
        topt.step()
        torch_traj.append(tw.detach().numpy().copy())

    opt = clip_by_global_norm(sgd(0.1, 0.5), 1.0)
    ours = _run_ours(opt, jnp.asarray(w0), [jnp.asarray(g) for g in grads])
    for t_w, o_w in zip(torch_traj, ours):
        np.testing.assert_allclose(t_w, o_w, rtol=1e-5, atol=1e-6)


def test_clip_norm_weights_discount_replicas():
    """With 1/replication weights, a doubled (replicated) gradient clips to
    the same scale as the single copy."""
    g = jnp.full((4,), 3.0)                  # norm 6
    stacked = jnp.stack([g, g])              # replicated twice: raw norm 6*sqrt2
    w = jnp.full((2, 1), 0.5)                # replication_weights analogue

    applied = {}

    def capture_update(grads, state, params):
        applied["g"] = grads
        return params, state

    from simple_distributed_machine_learning_tpu.train.optimizer import (
        Optimizer,
    )
    inner = Optimizer(lambda p: (), capture_update)
    clip_by_global_norm(inner, 1.0, norm_weights=w).update(
        stacked, (), stacked)
    # weighted norm = 6 -> scale 1/6 (unweighted would give 1/(6*sqrt2))
    np.testing.assert_allclose(np.asarray(applied["g"][0]),
                               np.asarray(g) / 6.0, rtol=1e-4)


def test_pipeline_replication_weights():
    from simple_distributed_machine_learning_tpu.models.mlp import (
        make_mlp_stages,
    )
    from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        Pipeline,
    )
    from simple_distributed_machine_learning_tpu.parallel.tensor import (
        make_mlp_tp_stages,
    )

    # plain stages on a tp=2 mesh are stored twice -> weight 1/2
    stages, wire, out = make_mlp_tp_stages(jax.random.key(0),
                                           [8, 16, 16, 16, 4], 2, 2)
    mesh = make_mesh(n_stages=2, n_model=2)
    pipe = Pipeline(stages, mesh, wire, out)
    w = pipe.replication_weights()
    assert w.shape == (2, 2, 1, 1)
    # TP stages carry real shards: each param counts once
    np.testing.assert_allclose(w, 1.0)

    stages2, wire2, out2 = make_mlp_stages(jax.random.key(0), [8, 6, 4], 2)
    pipe2 = Pipeline(stages2, mesh, wire2, out2)
    np.testing.assert_allclose(pipe2.replication_weights(), 0.5)


def test_scheduled_sgd_through_pipeline_train_step():
    """End to end: a scheduled+clipped optimizer drives the compiled pipeline
    step; loss decreases and the step counter advances."""
    from simple_distributed_machine_learning_tpu.models.mlp import (
        make_mlp_stages,
    )
    from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        Pipeline,
    )
    from simple_distributed_machine_learning_tpu.train.step import (
        make_train_step,
    )

    stages, wire, out = make_mlp_stages(jax.random.key(0), [16, 12, 4], 2)
    mesh = make_mesh(n_stages=2)
    pipe = Pipeline(stages, mesh, wire, out)
    opt = clip_by_global_norm(
        sgd(schedules.warmup_cosine(0.1, 3, 20), 0.5), 1.0,
        pipe.replication_weights())
    buf = pipe.init_params()
    state = opt.init(buf)
    step = make_train_step(pipe, opt)
    x = jax.random.normal(jax.random.key(1), (8, 16))
    y = jax.random.randint(jax.random.key(2), (8,), 0, 4)
    losses = []
    for i in range(10):
        buf, state, loss = step(buf, state, x, y, jax.random.key(3))
        losses.append(float(loss))
    count, _ = state
    assert int(count) == 10
    assert losses[-1] < losses[0]


def test_cli_schedule_and_clip(capsys):
    from simple_distributed_machine_learning_tpu.cli import main

    main(["--rank", "0", "--world_size", "1", "--model", "mlp",
          "--mlp-dims", "784,32,10", "--stages", "2", "--epochs", "1",
          "--data-root", "/nonexistent", "--lr-schedule", "warmup-cosine",
          "--warmup-steps", "5", "--clip-norm", "1.0"])
    out = capsys.readouterr().out
    assert "Test set: Average loss:" in out


def test_1f1b_quick_parity_smoke():
    """Quick-tier coverage of the 1F1B engine (the full sweep lives in the
    slow-tier tests/test_onefb.py): loss AND grads of the hand-scheduled
    backward match GPipe on a 2-stage, 2-microbatch pipeline."""
    import numpy as np

    from simple_distributed_machine_learning_tpu.models.mlp import (
        make_mlp_stages,
    )
    from simple_distributed_machine_learning_tpu.parallel.mesh import (
        make_mesh,
    )
    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        Pipeline,
    )

    dims = [12, 16, 10]
    stages, wire, out = make_mlp_stages(jax.random.key(0), dims, 2)
    mesh = make_mesh(n_stages=2, n_data=1, devices=jax.devices()[:2])
    gp = Pipeline(stages, mesh, wire, out, n_microbatches=2)
    fb = Pipeline(stages, mesh, wire, out, n_microbatches=2,
                  schedule="1f1b")
    x = jax.random.normal(jax.random.key(1), (8, 12))
    y = jax.random.randint(jax.random.key(2), (8,), 0, 10)
    buf = gp.init_params()
    key = jax.random.key(7)
    lg, gg = gp.loss_and_grads(buf, x, y, key, deterministic=True)
    lf, gf = fb.loss_and_grads(buf, x, y, key, deterministic=True)
    np.testing.assert_allclose(float(lg), float(lf), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(gg), np.asarray(gf), rtol=2e-4,
                               atol=2e-4)
