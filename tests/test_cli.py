"""CLI surface: reference-compatible flags end to end."""

import pytest

from simple_distributed_machine_learning_tpu.cli import build_parser, main


def test_parser_has_reference_flags_and_defaults():
    # flags and defaults per /root/reference/simple_distributed.py:144-156
    p = build_parser()
    args = p.parse_args(["--rank", "0"])
    assert args.rank == 0
    assert args.interface == "eth0"
    assert args.master_addr == "localhost"
    assert args.master_port == "29500"


def test_rank_required_for_multiprocess():
    with pytest.raises(AssertionError, match="Must provide rank"):
        main(["--world_size", "2"])


def test_cli_end_to_end_single_process(capsys):
    # tiny full run through the CLI: 1 epoch of the MLP on synthetic data
    main(["--rank", "0", "--world_size", "1", "--model", "mlp",
          "--mlp-dims", "784,32,10", "--stages", "2", "--epochs", "1",
          "--data-root", "/nonexistent", "--microbatches", "2"])
    out = capsys.readouterr().out
    assert "Train Epoch: 1 [0/6000 (0%)]" in out
    assert "Test set: Average loss:" in out


def test_cli_sp_requires_gpt():
    with pytest.raises(SystemExit, match="--sp is only supported"):
        main(["--rank", "0", "--model", "mlp", "--sp", "2"])


def test_cli_profile_writes_trace(tmp_path):
    """--profile captures an XProf trace of the whole run (SURVEY §5.1)."""
    import os

    trace_dir = str(tmp_path / "trace")
    main(["--rank", "0", "--world_size", "1", "--model", "mlp",
          "--mlp-dims", "784,32,10", "--stages", "2", "--epochs", "1",
          "--data-root", "/nonexistent", "--profile", trace_dir])
    found = [os.path.join(r, f) for r, _, fs in os.walk(trace_dir)
             for f in fs]
    assert found, "profiler produced no trace files"


def test_cli_dryrun_telemetry_end_to_end(tmp_path):
    """--dryrun N + --telemetry-dir: the cheap observability smoke CI runs —
    N train batches, eval, and a parseable metrics/trace/prom artifact set."""
    import json
    import os

    tele = str(tmp_path / "tele")
    main(["--rank", "0", "--world_size", "1", "--model", "mlp",
          "--mlp-dims", "784,32,10", "--stages", "2", "--epochs", "5",
          "--dryrun", "2", "--microbatches", "2",
          "--data-root", "/nonexistent", "--telemetry-dir", tele])
    recs = [json.loads(ln) for ln in
            open(os.path.join(tele, "metrics.jsonl")).read().splitlines()]
    assert len(recs) == 1                   # --dryrun forces a single epoch
    r = recs[0]
    assert r["schema"] == 2 and r["steps"] == 1     # 2 batches - compile
    assert r["step_time_ms_p50"] > 0 and r["step_time_ms_p95"] > 0
    assert r["examples_per_sec"] > 0
    assert r["live_array_bytes"] > 0
    assert r["ici_bytes_per_step"] > 0      # 2-stage pipeline: ppermute hops
    trace = json.load(open(os.path.join(tele, "trace.json")))
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"feed", "step", "eval"} <= names
    assert os.path.exists(os.path.join(tele, "metrics.prom"))


def test_cli_dryrun_rejects_negative():
    with pytest.raises(SystemExit, match="--dryrun"):
        main(["--rank", "0", "--model", "mlp", "--dryrun", "-1"])


def test_cli_adamw_zero1(capsys):
    """--optimizer adamw --zero1 end to end through the CLI."""
    main(["--rank", "0", "--world_size", "1", "--model", "mlp",
          "--mlp-dims", "784,32,10", "--stages", "2", "--epochs", "1",
          "--lr", "0.001", "--optimizer", "adamw", "--zero1",
          "--data-root", "/nonexistent", "--microbatches", "2"])
    out = capsys.readouterr().out
    assert "Test set: Average loss:" in out


def test_cli_gpt_text_corpus_end_to_end(tmp_path, capsys):
    """--text-corpus: the GPT trains on real bytes from a local file end to
    end through the CLI (the reference's real-data-first sourcing,
    simple_distributed.py:87-95, mapped to a zero-egress LM path)."""
    p = tmp_path / "corpus.txt"
    # highly regular text: a byte-LM's loss visibly drops within one epoch
    p.write_bytes(b"the quick brown fox jumps over the lazy dog. " * 600)
    main(["--rank", "0", "--world_size", "1", "--model", "gpt",
          "--text-corpus", str(p), "--stages", "2", "--epochs", "1",
          "--batch-size", "20", "--microbatches", "2", "--lr", "0.05"])
    out = capsys.readouterr().out
    assert "Train Epoch: 1" in out
    assert "Test set: Average loss:" in out
    import re
    losses = [float(m) for m in re.findall(r"Loss: ([0-9.]+)", out)]
    assert losses[-1] < losses[0] * 0.7, losses


def test_cli_generate_prints_sample(tmp_path, capsys):
    """--generate after --text-corpus training prints decoded text through
    the KV-cache decoder bound to the live param buffer."""
    p = tmp_path / "corpus.txt"
    p.write_bytes(b"abcabcabcabc " * 400)
    main(["--rank", "0", "--world_size", "1", "--model", "gpt",
          "--text-corpus", str(p), "--stages", "2", "--epochs", "1",
          "--batch-size", "12", "--microbatches", "2", "--lr", "0.1",
          "--generate", "24"])
    out = capsys.readouterr().out
    assert "| sample (" in out
    # the sample line carries a 16-byte prompt + 24 generated characters
    import ast
    line = [l for l in out.splitlines() if l.startswith(("'", '"'))][-1]
    assert len(ast.literal_eval(line)) == 40


def test_cli_generate_telemetry_records_decode(tmp_path, capsys):
    """--generate with --telemetry-dir routes decode timing through the
    telemetry StepTimer/registry: a kind=decode record with decode latency
    and tokens/sec lands in metrics.jsonl (and the decode series rides the
    Prometheus exposition) instead of being print-only."""
    import json
    import os

    p = tmp_path / "corpus.txt"
    p.write_bytes(b"xyzxyzxyz " * 400)
    tele = str(tmp_path / "tele")
    main(["--rank", "0", "--world_size", "1", "--model", "gpt",
          "--text-corpus", str(p), "--stages", "2", "--epochs", "1",
          "--dryrun", "2", "--batch-size", "12", "--microbatches", "2",
          "--generate", "16", "--telemetry-dir", tele])
    out = capsys.readouterr().out
    assert "| sample (" in out                       # print surface intact
    recs = [json.loads(ln) for ln in
            open(os.path.join(tele, "metrics.jsonl")).read().splitlines()]
    dec = [r for r in recs if r.get("kind") == "decode"]
    assert len(dec) == 1
    d = dec[0]
    assert d["schema"] == 2 and d["n_new"] == 16
    assert d["compile_time_s"] > 0                   # first decode window
    assert d["step_time_ms_p50"] > 0                 # steady decode window
    assert d["tokens_per_sec"] > 0
    assert "decode_time_ms" in open(
        os.path.join(tele, "metrics.prom")).read()


def test_cli_generate_requires_gpt():
    with pytest.raises(SystemExit, match="--generate is only supported"):
        main(["--rank", "0", "--model", "mlp", "--generate", "8"])


def test_cli_eval_only_from_checkpoint(tmp_path, capsys):
    """--eval-only restores the checkpoint and evaluates without training:
    accuracy matches the end of the training run, and no train lines print."""
    import re

    from simple_distributed_machine_learning_tpu.cli import main

    ckpt = str(tmp_path / "ck")
    main(["--rank", "0", "--world_size", "1", "--model", "mlp",
          "--stages", "2", "--epochs", "2", "--microbatches", "2",
          "--checkpoint-dir", ckpt])
    trained = capsys.readouterr().out
    acc_trained = re.findall(r"Accuracy: (\d+)/", trained)[-1]

    main(["--rank", "0", "--world_size", "1", "--model", "mlp",
          "--stages", "2", "--epochs", "2", "--microbatches", "2",
          "--checkpoint-dir", ckpt, "--eval-only"])
    out = capsys.readouterr().out
    assert "Train Epoch" not in out
    assert re.findall(r"Accuracy: (\d+)/", out)[-1] == acc_trained


def test_cli_scenario_slo_gate(tmp_path, capsys):
    """--scenario: the SLO-gated serving scenario exits 0 with per-class
    attainment printed and the gateable records in --telemetry-dir; the
    virtual clock makes the numbers machine-independent."""
    import json
    import os

    main(["--rank", "0", "--scenario", "burst-interactive",
          "--telemetry-dir", str(tmp_path)])
    out = capsys.readouterr().out
    assert "scenario burst-interactive (priority): 28/28 completed" in out
    assert "SLO ATTAINED" in out
    recs = [json.loads(line)
            for line in open(os.path.join(str(tmp_path), "metrics.jsonl"))]
    scen = [r for r in recs if r.get("kind") == "scenario"][-1]
    assert scen["slo_ok"] is True
    assert scen["slo"]["interactive"]["ttft_attainment"] >= 0.9


def test_cli_scenario_list_and_unknown(capsys):
    main(["--rank", "0", "--scenario", "list"])
    out = capsys.readouterr().out
    assert "burst-interactive" in out and "multi-tenant" in out
    with pytest.raises(SystemExit, match="unknown --scenario"):
        main(["--rank", "0", "--scenario", "nope"])


def test_cli_chaos_elastic_restart_end_to_end(tmp_path, capsys):
    """--chaos: host-kill mid-epoch-2 -> the supervisor restores the
    epoch-1 checkpoint from the store, repacks 2 stages -> 1, resumes to
    completion and exits 0 (the CI chaos job's shape)."""
    main(["--rank", "0", "--world_size", "1", "--model", "mlp",
          "--mlp-dims", "784,32,10", "--stages", "2", "--epochs", "3",
          "--max-steps-per-epoch", "4", "--data-root", "/nonexistent",
          "--checkpoint-dir", str(tmp_path / "store"),
          "--chaos", "host-kill@train.step=6", "--chaos-stages", "2,1"])
    out = capsys.readouterr().out
    assert "restored ckpt-00000004.npz (step 4, written at 2 stages, " \
           "repacked onto 1); resuming at epoch 2" in out
    assert ("chaos: completed after 1 restart(s); attempts: "
            "2st/fault(HostLost) -> 1st/completed") in out
    import os
    files = os.listdir(str(tmp_path / "store"))
    assert "MANIFEST.jsonl" in files
    assert any(f.startswith("ckpt-") and f.endswith(".npz") for f in files)


def test_cli_chaos_validation():
    with pytest.raises(SystemExit, match="--checkpoint-dir"):
        main(["--rank", "0", "--model", "mlp", "--chaos",
              "host-kill@train.step=1"])
    with pytest.raises(SystemExit, match="mlp or gpt"):
        main(["--rank", "0", "--model", "lenet", "--chaos",
              "host-kill@train.step=1", "--checkpoint-dir", "/tmp/x"])
    with pytest.raises(SystemExit, match="bad --chaos spec"):
        main(["--rank", "0", "--model", "mlp", "--chaos", "explode@here",
              "--checkpoint-dir", "/tmp/x"])
    with pytest.raises(SystemExit, match="--chaos-stages"):
        main(["--rank", "0", "--model", "mlp", "--chaos",
              "host-kill@train.step=1", "--checkpoint-dir", "/tmp/x",
              "--chaos-stages", "two,one"])
    with pytest.raises(SystemExit, match="--max-steps-per-epoch"):
        main(["--rank", "0", "--model", "mlp", "--max-steps-per-epoch", "0"])
