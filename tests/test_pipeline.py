"""Pipeline correctness: the #1 test battery (SURVEY §7 "hard parts" (a)).

Every test compares the N-device pipeline (shard_map + ppermute + lax.switch
+ GPipe scan) against the single-device fused composition of the same stages
— forward values, gradients, and whole SGD training trajectories must match
to float tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.models.mlp import make_mlp_stages
from simple_distributed_machine_learning_tpu.ops.losses import nll_loss
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import (
    Pipeline,
    fused_reference,
)
from simple_distributed_machine_learning_tpu.parallel.staging import (
    pack_stage_params,
)
from simple_distributed_machine_learning_tpu.train.optimizer import sgd
from simple_distributed_machine_learning_tpu.train.step import make_train_step

RTOL = 2e-5
ATOL = 2e-5


def _fused_loss(stages, stage_params, x, targets):
    fused = fused_reference(stages)
    logp = fused(stage_params, x, jax.random.key(0), deterministic=True)
    return nll_loss(logp, targets, "mean")


def _make_problem(key, dims, n_stages, batch):
    km, kx, kt = jax.random.split(key, 3)
    stages, wire_dim, out_dim = make_mlp_stages(km, dims, n_stages)
    x = jax.random.normal(kx, (batch, dims[0]))
    targets = jax.random.randint(kt, (batch,), 0, dims[-1])
    return stages, wire_dim, out_dim, x, targets


@pytest.mark.parametrize("n_stages,n_data,n_micro", [
    (2, 1, 1),   # the reference's own topology: 2 stages, sequential schedule
    (2, 1, 4),   # 2-stage GPipe
    (4, 1, 1),   # BASELINE config 3: 4-stage, microbatch=1
    (4, 2, 4),   # pipeline + data parallel + GPipe combined
    (1, 1, 2),   # degenerate single-stage (fused) pipeline
])
@pytest.mark.slow            # heavy parity sweep: per-round gate
def test_pipeline_matches_fused_loss_and_grad(n_stages, n_data, n_micro):
    key = jax.random.key(42)
    dims = [12, 16, 16, 16, 10] if n_stages == 4 else [12, 16, 10]
    batch = 8 * n_micro
    stages, wire_dim, out_dim, x, targets = _make_problem(
        key, dims, max(n_stages, 1), batch)

    mesh = make_mesh(n_stages=n_stages, n_data=n_data)
    pipe = Pipeline(stages, mesh, wire_dim, out_dim, n_microbatches=n_micro)
    buf = pipe.init_params()

    loss, logp = pipe.loss_and_logits(buf, x, targets, jax.random.key(0),
                                      deterministic=True)
    want_loss = _fused_loss(stages, [s.params for s in stages], x, targets)
    np.testing.assert_allclose(float(loss), float(want_loss),
                               rtol=RTOL, atol=ATOL)

    # log-probs on the wire match the fused forward
    fused = fused_reference(stages)
    want_logp = fused([s.params for s in stages], x, jax.random.key(0), True)
    np.testing.assert_allclose(np.asarray(logp), np.asarray(want_logp),
                               rtol=RTOL, atol=ATOL)

    # gradients through ppermute/scan/switch match fused autodiff
    grads = jax.grad(lambda b: pipe.loss_and_logits(
        b, x, targets, jax.random.key(0), deterministic=True)[0])(buf)
    fused_grads = jax.grad(
        lambda ps: _fused_loss(stages, ps, x, targets)
    )([s.params for s in stages])
    want_buf, _ = pack_stage_params(fused_grads)
    # grads buffer is [n_stages, n_model=1, n_expert=1, P]; fused pack is
    # [n_stages, P]
    np.testing.assert_allclose(np.asarray(grads)[:, 0, 0],
                               np.asarray(want_buf), rtol=5e-5, atol=5e-5)


@pytest.mark.parametrize("n_micro", [1, 4])
@pytest.mark.slow
def test_loss_only_engine_matches_full(n_micro):
    """Pipeline.loss (the training path: no logits accumulator in the scan
    carry) must produce the identical value AND gradient as
    loss_and_logits()[0] — same RNG stream, same reductions."""
    key = jax.random.key(7)
    stages, wire_dim, out_dim, x, targets = _make_problem(
        key, [12, 16, 10], 2, 8 * n_micro)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wire_dim, out_dim, n_microbatches=n_micro)
    buf = pipe.init_params()
    k = jax.random.key(1)

    l_full, g_full = jax.value_and_grad(
        lambda b: pipe.loss_and_logits(b, x, targets, k, False)[0])(buf)
    l_only, g_only = jax.value_and_grad(
        lambda b: pipe.loss(b, x, targets, k, False))(buf)
    np.testing.assert_allclose(float(l_only), float(l_full),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(g_only), np.asarray(g_full),
                               rtol=1e-6, atol=1e-6)


def test_training_trajectory_matches_fused():
    """5 SGD(momentum) steps on the 2-stage pipeline == fused single-device."""
    key = jax.random.key(7)
    stages, wire_dim, out_dim, x, targets = _make_problem(key, [12, 16, 10], 2, 8)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wire_dim, out_dim, n_microbatches=1)
    buf = pipe.init_params()
    opt = sgd(0.1, momentum=0.5)

    # pipeline side (deterministic: rebuild train step without dropout noise)
    import functools

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def pipe_step(b, m, x, t):
        loss, grads = jax.value_and_grad(lambda bb: pipe.loss_and_logits(
            bb, x, t, jax.random.key(0), deterministic=True)[0])(b)
        b2, m2 = opt.update(grads, m, b)
        return b2, m2, loss

    # fused side
    fused_params = [s.params for s in stages]
    fused_state = opt.init(fused_params)
    mom = opt.init(buf)
    pipe_losses, fused_losses = [], []
    for _ in range(5):
        buf, mom, loss = pipe_step(buf, mom, x, targets)
        pipe_losses.append(float(loss))
        fl, fg = jax.value_and_grad(
            lambda ps: _fused_loss(stages, ps, x, targets))(fused_params)
        fused_params, fused_state = opt.update(fg, fused_state, fused_params)
        fused_losses.append(float(fl))
    np.testing.assert_allclose(pipe_losses, fused_losses, rtol=1e-4, atol=1e-4)
    # losses should be strictly decreasing on this toy problem
    assert pipe_losses[-1] < pipe_losses[0]


@pytest.mark.slow
def test_data_parallel_matches_single_data_rank():
    """Same global batch, dp=4 vs dp=1: identical loss and grads."""
    key = jax.random.key(9)
    stages, wire_dim, out_dim, x, targets = _make_problem(key, [12, 16, 10], 2, 16)

    results = []
    for n_data in (1, 4):
        mesh = make_mesh(n_stages=2, n_data=n_data)
        pipe = Pipeline(stages, mesh, wire_dim, out_dim, n_microbatches=2)
        buf = pipe.init_params()
        loss = pipe.loss_and_logits(buf, x, targets, jax.random.key(0),
                                    deterministic=True)[0]
        grads = jax.grad(lambda b: pipe.loss_and_logits(
            b, x, targets, jax.random.key(0), deterministic=True)[0])(buf)
        results.append((float(loss), np.asarray(grads)))
    np.testing.assert_allclose(results[0][0], results[1][0], rtol=RTOL)
    np.testing.assert_allclose(results[0][1], results[1][1],
                               rtol=5e-5, atol=5e-5)


def test_weighted_loss_masks_padding():
    """Zero-weighted padded rows must not dilute the loss: weighted loss over
    a padded batch == unweighted loss over just the valid prefix."""
    key = jax.random.key(13)
    stages, wire_dim, out_dim, x, targets = _make_problem(key, [12, 16, 10], 2, 16)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wire_dim, out_dim, n_microbatches=2)
    buf = pipe.init_params()

    n_valid = 10
    x_pad = x.at[n_valid:].set(0.0)
    w = (jnp.arange(16) < n_valid).astype(jnp.float32)
    loss_w = pipe.loss_and_logits(buf, x_pad, targets, key, True, weights=w)[0]

    # unweighted over the valid prefix (use a divisible sub-batch)
    pipe1 = Pipeline(stages, mesh, wire_dim, out_dim, n_microbatches=1)
    loss_ref = pipe1.loss_and_logits(buf, x[:n_valid], targets[:n_valid],
                                     key, True)[0]
    np.testing.assert_allclose(float(loss_w), float(loss_ref),
                               rtol=RTOL, atol=RTOL)


def test_dropout_trains_and_eval_is_deterministic():
    key = jax.random.key(11)
    stages, wire_dim, out_dim, x, targets = _make_problem(key, [12, 16, 10], 2, 8)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wire_dim, out_dim, n_microbatches=2)
    buf = pipe.init_params()
    l1 = pipe.loss_and_logits(buf, x, targets, jax.random.key(1), True)[0]
    l2 = pipe.loss_and_logits(buf, x, targets, jax.random.key(2), True)[0]
    np.testing.assert_allclose(float(l1), float(l2))  # eval ignores the key


@pytest.mark.slow
def test_gpipe_replicated_plain_stages_on_sharded_mesh():
    """Plain (unsharded) stages on a model=2 mesh: the switch transpose
    used to reject this with 'mismatched varying manual axes' — the
    zero-valued full-vma anchor in each branch pins every branch's input
    cotangent type. Gradients must match the fused model on every slot."""
    from simple_distributed_machine_learning_tpu.ops.losses import nll_loss
    from simple_distributed_machine_learning_tpu.parallel.staging import (
        unpack_stage_params,
    )

    stages, wd, od = make_mlp_stages(jax.random.key(0), [8, 16, 4], 2)
    mesh = make_mesh(n_stages=2, n_model=2, n_data=1)
    pipe = Pipeline(stages, mesh, wd, od, n_microbatches=2)
    x = jax.random.normal(jax.random.key(1), (8, 8))
    y = jax.random.randint(jax.random.key(2), (8,), 0, 4)
    buf = pipe.init_params()
    k = jax.random.key(7)
    fused = fused_reference(stages)

    def floss(b):
        ps = [unpack_stage_params(b[s, 0, 0], pipe.metas[s])
              for s in range(2)]
        return nll_loss(fused(ps, x, k, True), y, "mean")

    lF, gF = jax.value_and_grad(floss)(buf)
    lg, gg = pipe.loss_and_grads(buf, x, y, k, deterministic=True)
    np.testing.assert_allclose(float(lg), float(lF), rtol=1e-6)
    gF, gg = np.asarray(gF), np.asarray(gg)
    for s in range(2):
        for m in range(2):
            np.testing.assert_allclose(gg[s, m, 0], gF[s, 0, 0],
                                       rtol=1e-5, atol=1e-7)


@pytest.mark.slow
def test_gpipe_mixed_dense_and_moe_stages_on_expert_mesh():
    """A dense GPT stage and an EP-MoE GPT stage in ONE pipeline on an
    expert=2 mesh — another switch-transpose vma mismatch fixed by the
    branch anchor (the closed-over param row is a cond operand too).
    Smoke: loss/grads compute and are finite."""
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )

    cfg_d = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=2,
                      n_layers=2, n_experts=0)
    cfg_m = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=2,
                      n_layers=2, n_experts=2, moe_top_k=2,
                      n_expert_parallel=2)
    sd, wdd, _ = make_gpt_stages(jax.random.key(0), cfg_d, 2)
    sm, wdm, od = make_gpt_stages(jax.random.key(0), cfg_m, 2)
    mesh = make_mesh(n_stages=2, n_data=1, n_expert=2)
    pipe = Pipeline([sd[0], sm[1]], mesh, max(wdd, wdm), od,
                    n_microbatches=2)
    x = jax.random.randint(jax.random.key(1), (8, 16), 0,
                           32).astype(jax.numpy.float32)
    y = jax.random.randint(jax.random.key(2), (8, 16), 0, 32)
    buf = pipe.init_params()
    loss, grads = pipe.loss_and_grads(buf, x, y, jax.random.key(7),
                                      deterministic=True)
    assert np.isfinite(float(loss))
    assert np.isfinite(np.asarray(grads)).all()
