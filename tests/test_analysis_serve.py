"""Serve-path preflight (analysis/programs.py + bounds.py + hostlint.py).

Four contracts pin the whole-program gate:

1. the serving-program registry lints CLEAN on both KV layouts — and not
   vacuously: the interval pass must PROVE every PROMISE_IN_BOUNDS gather
   (zero ``unproven-promise`` findings), and the trace recursion must reach
   every program (zero ``trace.failed``);
2. contract violations the host-side pool guards against are flagged when
   declared possible — block-table entries past the pool, position counters
   past ``max_len`` — each as a ``scatter-bounds`` ERROR;
3. the retrace policy and ``_DECODE_BUILD_CACHE`` memo discipline are
   machine-checked (jaxpr-invisible, so checked at the builder/AST level);
4. the HBM model's resident-bytes prediction equals the live pool's
   ``serve_kv_bytes_resident`` gauge on multiple occupancy/block shapes.

Everything except the HBM cross-check is trace-only.
"""

import functools
import os

import jax
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.analysis import analyze, spec
from simple_distributed_machine_learning_tpu.analysis.bounds import (
    Interval,
    _cmp_iv,
    _floordiv_iv,
    _mod_iv,
)
from simple_distributed_machine_learning_tpu.analysis.programs import (
    ServeSpec,
    build_registry,
    check_builder_memo,
    hbm_tick_costs,
    lint_engine,
    lint_serve,
    predict_kv_bytes_resident,
)
from simple_distributed_machine_learning_tpu.analysis.trace import (
    all_primitives,
    trace_to_jaxpr,
)
from simple_distributed_machine_learning_tpu.models.gpt import (
    DECODE_BUILDERS,
    GPTConfig,
    make_gpt_stages,
    make_paged_decode_step,
)

CFG = GPTConfig(vocab=32, seq_len=24, d_model=16, n_heads=2, n_layers=2)
BUCKETS = (4, 6, 9)


@pytest.fixture(scope="module")
def stages():
    return make_gpt_stages(jax.random.key(0), CFG, 1)[0]


def _specs():
    return [
        ServeSpec(CFG, n_slots=3, max_len=16, kv_layout="paged",
                  block_size=4, prefill_chunk=3, prompt_lens=BUCKETS),
        ServeSpec(CFG, n_slots=3, max_len=16, kv_layout="paged",
                  block_size=8, prefill_chunk=None, prompt_lens=BUCKETS),
        ServeSpec(CFG, n_slots=3, max_len=16, kv_layout="dense",
                  prompt_lens=BUCKETS),
    ]


# ---- 1. the registry lints clean on both layouts -------------------------

@pytest.mark.parametrize("i", range(3))
def test_registry_clean_both_layouts(stages, i):
    report = lint_serve(stages, _specs()[i])
    assert report.ok(fail_on="warning"), report.format()
    # the clean pass is a PROOF, not silence: the paged gathers run in
    # PROMISE_IN_BOUNDS mode, so an unproven interval would have warned
    rules = {f.rule for f in report.findings}
    assert "scatter-bounds.unproven-promise" not in rules
    assert "trace.failed" not in rules


def test_registry_covers_every_decode_builder(stages):
    # the paged + dense registries together enumerate every memoized
    # decode builder (plus the composite ticks)
    names = set()
    for s in _specs():
        programs, _ = build_registry(stages, s)
        names.update(p.name for p in programs)
    assert {"cached_decoder", "slot_prefill", "slot_decode",
            "paged_prefill_chunk", "paged_decode", "paged_block_copy",
            "dense_tick", "paged_tick"} <= names


def test_trace_recursion_reaches_serve_primitives(stages):
    """The trace.py audit, pinned: the generic sub-jaxpr recursion reaches
    the index-bearing primitives the serve programs actually emit —
    including the scatter/gather/dynamic_update_slice INSIDE the cached
    decoder's scan — and no program fails to trace."""
    prims = set()
    for s in _specs():
        programs, _ = build_registry(stages, s)
        for prog in programs:
            plain = jax.tree.map(
                lambda a: a.sds if hasattr(a, "sds") else a, prog.args,
                is_leaf=lambda a: hasattr(a, "sds"))
            prims |= all_primitives(trace_to_jaxpr(prog.fn, *plain))
    assert {"scatter", "gather", "dynamic_update_slice", "dynamic_slice",
            "scan", "pjit", "argmax", "concatenate", "iota"} <= prims


def test_hbm_table_present_and_ranked(stages):
    report = lint_serve(stages, _specs()[0])
    assert report.hbm, "HBM cost table empty"
    ops = {h.op for h in report.hbm}
    assert {"decode.kv_gather", "decode.kv_scatter",
            "prefill.kv_scatter", "cow.block_copy"} <= ops
    gather = next(h for h in report.hbm if h.op == "decode.kv_gather")
    scatter = next(h for h in report.hbm if h.op == "decode.kv_scatter")
    # the per-tick gather (full table span, every slot) dominates the
    # one-position scatter — the ratio IS the span
    assert gather.bytes_per_tick == scatter.bytes_per_tick * 16
    assert "HBM bytes per serve tick" in report.format()


def test_hbm_prefill_chunk_matches_registry_resolution():
    """The HBM table's prefill row must describe the chunk the registry
    actually built — ONE resolution rule (ServeSpec.resolved_chunk) for
    both, including the no-chunk/no-buckets default every
    ``InferenceEngine(lint=True)`` deployment hits."""
    for s in (_specs()[0], _specs()[1],
              ServeSpec(CFG, n_slots=2, max_len=16, block_size=4)):
        row = next(h for h in hbm_tick_costs(s)
                   if h.op == "prefill.kv_scatter")
        assert f"{s.resolved_chunk}-token" in row.note, (row.note, s)
    # the default deployment lints an 8-token chunk, not a 1-token one
    assert ServeSpec(CFG, n_slots=2, max_len=16,
                     block_size=4).resolved_chunk == 8


# ---- 2. contract violations are flagged ----------------------------------

def _paged_decode_args(stages, tables_hi, pos_hi, S=2, ml=16, bs=4):
    nb = -(-ml // bs) * S
    params = [jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), s.params)
        for s in stages]
    kc = jax.ShapeDtypeStruct(
        (CFG.n_layers, nb + 1, CFG.n_heads, bs,
         CFG.d_model // CFG.n_heads), np.float32)
    return (params, kc, kc,
            spec((S,), np.int32, 0, CFG.vocab - 1),
            spec((S,), np.int32, 0, pos_hi),
            spec((S, -(-ml // bs)), np.int32, 0, tables_hi),
            jax.ShapeDtypeStruct((S, 2), np.uint32),
            jax.ShapeDtypeStruct((S,), np.float32),
            spec((S,), np.int32, 0, CFG.vocab),
            jax.ShapeDtypeStruct((S,), np.float32)), nb


def test_oob_table_and_position_flagged(stages):
    step = make_paged_decode_step(stages, CFG, 16, 4)
    args, nb = _paged_decode_args(stages, tables_hi=None, pos_hi=15)
    args = list(args)
    good_tables = spec((2, 4), np.int32, 0, nb)
    args[5] = good_tables
    assert analyze(step, *args).ok(fail_on="warning")
    # table entries one past the pool: the K/V scatter lands in (or the
    # PROMISE gather reads) someone else's block
    args[5] = spec((2, 4), np.int32, 0, nb + 1)
    report = analyze(step, *args)
    oob = [f for f in report.findings
           if f.rule == "scatter-bounds.out-of-range"]
    assert oob and not report.ok(), report.format()
    # position one past max_len: the pos-table gather and block math break
    args[5] = good_tables
    args[4] = spec((2,), np.int32, 0, 16)
    report = analyze(step, *args)
    assert not report.ok(), report.format()


def test_unbounded_inputs_warn_on_promise_gathers(stages):
    # no declared contract at all: the PROMISE_IN_BOUNDS block gathers
    # cannot be proven — the analyzer must say so rather than stay silent
    step = make_paged_decode_step(stages, CFG, 16, 4)
    args, _ = _paged_decode_args(stages, tables_hi=None, pos_hi=15)
    args = list(args)
    args[5] = jax.ShapeDtypeStruct((2, 4), np.int32)   # tables: no contract
    args[4] = spec((2,), np.int32, 0, 15)
    report = analyze(step, *args)
    assert any(f.rule == "scatter-bounds.unproven-promise"
               for f in report.findings), report.format()
    assert report.ok()      # WARNING: unproven, not proven-broken


def test_double_donation_flagged():
    # one buffer aliased into two parameters of a call that donates one of
    # them: the non-donated alias reads pages the donation may reuse
    import functools

    @functools.partial(jax.jit, donate_argnums=(0,))
    def inner(a, b):
        return a + b

    def tick(x):
        return inner(x, x)

    report = analyze(tick, jax.ShapeDtypeStruct((4,), np.float32))
    assert any(f.rule == "donation.double-donation"
               for f in report.findings), report.format()
    # distinct buffers: clean
    clean = analyze(lambda x, y: inner(x, y),
                    jax.ShapeDtypeStruct((4,), np.float32),
                    jax.ShapeDtypeStruct((4,), np.float32))
    assert not any(f.rule == "donation.double-donation"
                   for f in clean.findings), clean.format()


def test_while_cond_gathers_not_vacuously_clean(stages):
    # an index-bearing PROMISE read in a while-loop PREDICATE is a program
    # too: the bounds pass must walk cond_jaxpr, not just the body
    def f(table, idx):
        def cond(c):
            i, _ = c
            return table.at[i].get(mode="promise_in_bounds") > 0
        def body(c):
            i, s = c
            return i + 1, s + 1
        return jax.lax.while_loop(cond, body, (idx, 0))

    t = jax.ShapeDtypeStruct((8,), np.int32)
    unproven = analyze(f, t, jax.ShapeDtypeStruct((), np.int32))
    assert any(f_.rule == "scatter-bounds.unproven-promise"
               for f_ in unproven.findings), unproven.format()


def test_no_contracts_at_all_still_runs_bounds(stages):
    # zero analysis.spec args anywhere: the bounds pass must still walk
    # the program (rules.py runs check_bounds unconditionally) — a
    # PROMISE_IN_BOUNDS gather in a spec-free analyze() call is the
    # vacuously-clean hole, not a clean proof
    step = make_paged_decode_step(stages, CFG, 16, 4)
    args, _ = _paged_decode_args(stages, tables_hi=None, pos_hi=15)
    plain = [jax.ShapeDtypeStruct(a.sds.shape, a.sds.dtype)
             if hasattr(a, "sds") else a for a in args]
    report = analyze(step, *plain)
    assert any(f.rule == "scatter-bounds.unproven-promise"
               for f in report.findings), report.format()
    assert report.ok(), report.format()


# ---- 3. retrace policy + memo discipline ---------------------------------

def test_real_builders_are_memoized(stages):
    # the speculative builders take the draft build (same tiny model here)
    extra = {
        "make_slot_propose": lambda m: m(stages, CFG, 16, 4),
        "make_slot_verify_step": lambda m: m(stages, CFG, 16, 4),
        "make_paged_verify_step": lambda m: m(stages, CFG, 16, 4, 4),
        "make_slot_spec_tick": lambda m: m(stages, CFG, stages, CFG, 16, 4),
        "make_paged_spec_tick": lambda m: m(stages, CFG, stages, CFG, 16,
                                            4, 4),
    }
    for name, make in DECODE_BUILDERS.items():
        if name in extra:
            build = functools.partial(extra[name], make)
        elif name == "make_cached_decoder":
            def build():
                return make(stages, CFG, 4, 4)
        elif name in ("make_paged_block_copy", "make_adapter_bank_update"):
            build = make
        elif "paged" in name:
            def build():
                return make(stages, CFG, 16, 4)
        else:
            def build():
                return make(stages, CFG, 16)
        assert check_builder_memo(name, build) == [], name


def test_unbounded_retrace_flagged_bounded_clean(stages):
    unbounded = ServeSpec(CFG, n_slots=2, max_len=16, kv_layout="dense")
    report = lint_serve(stages, unbounded)
    assert any(f.rule == "retrace-explosion.unbounded-trace-key"
               for f in report.findings), report.format()
    assert report.ok()                      # WARNING-level: gates don't trip
    bounded = ServeSpec(CFG, n_slots=2, max_len=16, kv_layout="dense",
                        prompt_lens=BUCKETS)
    assert lint_serve(stages, bounded).ok(fail_on="warning")
    # paged: a prefill_chunk bounds the SERVING shapes even with no
    # buckets — the only remaining warning is the cached (solo-parity)
    # decoder, whose per-(prompt, n_new) retrace is caller-owned
    chunked = ServeSpec(CFG, n_slots=2, max_len=16, kv_layout="paged",
                        block_size=4, prefill_chunk=4)
    report = lint_serve(stages, chunked)
    assert report.ok()
    unbounded_rules = [f for f in report.findings
                       if f.rule == "retrace-explosion.unbounded-trace-key"]
    assert [f.where for f in unbounded_rules] == ["make_cached_decoder"]


def test_hostlint_clean_and_pinned_to_gpt():
    from simple_distributed_machine_learning_tpu.analysis.hostlint import (
        DECODE_BUILDER_NAMES,
        lint_repo,
    )
    assert set(DECODE_BUILDER_NAMES) == set(DECODE_BUILDERS)
    report = lint_repo()
    assert report.ok(fail_on="warning"), report.format()


def test_hostlint_flags_bypass_and_unmemoized(tmp_path):
    from simple_distributed_machine_learning_tpu.analysis.hostlint import (
        _lint_call_sites,
        lint_builder_definitions,
    )
    bad = tmp_path / "bad_site.py"
    bad.write_text(
        "import jax\n"
        "from simple_distributed_machine_learning_tpu.models.gpt import (\n"
        "    _build_cached_decoder, _DECODE_BUILD_CACHE)\n"
        "dec = _build_cached_decoder(8, 4, 4, 2, 8, None, 0.0, None, None)\n"
        "_DECODE_BUILD_CACHE.clear()\n"
        "step = jax.jit(lambda x: x)\n")
    rules = {f.rule for f in _lint_call_sites(str(bad), allow_jit=False)}
    assert {"hostlint.builder-bypass", "hostlint.cache-poke",
            "hostlint.raw-jit-in-serve"} <= rules
    # every other spelling of a raw jit must be caught too — aliased
    # module, from-import, renamed from-import, pjit
    for src in ("from jax import jit\nstep = jit(lambda x: x)\n",
                "from jax import jit as q\nstep = q(lambda x: x)\n",
                "import jax as j\nstep = j.jit(lambda x: x)\n",
                "from jax.experimental.pjit import pjit\n"
                "step = pjit(lambda x: x)\n"):
        aliased = tmp_path / "aliased_site.py"
        aliased.write_text(src)
        got = {f.rule for f in _lint_call_sites(str(aliased),
                                                allow_jit=False)}
        assert "hostlint.raw-jit-in-serve" in got, src
    # a gpt.py whose builder dropped the memo
    fake_gpt = tmp_path / "gpt.py"
    fake_gpt.write_text(
        "def make_cached_decoder(stages, cfg):\n"
        "    import jax\n"
        "    return jax.jit(lambda p: p)\n")
    findings = lint_builder_definitions(str(fake_gpt))
    assert any(f.rule == "hostlint.unmemoized-builder" for f in findings)


def test_hostlint_cli_exit_codes():
    from simple_distributed_machine_learning_tpu.analysis.__main__ import (
        main,
    )
    assert main(["--hostlint"]) == 0


def test_hostlint_runs_without_jax():
    """The AST lint's reason to exist is running when jax is broken or
    absent (the CI hostlint step sets no backend): importing and running
    it must not pull jax through the package __init__ chain. Simulated by
    purging jax from sys.modules and blocking any re-import."""
    import subprocess
    import sys

    prog = (
        "import sys\n"
        "for m in [k for k in sys.modules"
        " if k == 'jax' or k.startswith(('jax.', 'jaxlib'))]:\n"
        "    del sys.modules[m]\n"
        "class B:\n"  # find_spec: the one meta-path hook every
        "    def find_spec(self, name, path=None, target=None):\n"
        "        if name == 'jax' or name.startswith(('jax.', 'jaxlib')):\n"
        "            raise ImportError('blocked: ' + name)\n"
        "sys.meta_path.insert(0, B())\n"
        "try:\n"           # the blocker must itself work on this python,
        "    import jax\n"  # or the test is vacuous
        "except ImportError:\n"
        "    pass\n"
        "else:\n"
        "    print('BLOCKER INERT'); sys.exit(3)\n"
        "from simple_distributed_machine_learning_tpu.analysis.__main__ "
        "import main\n"
        "sys.exit(main(['--hostlint']))\n"
    )
    proc = subprocess.run([sys.executable, "-c", prog],
                          cwd=os.path.dirname(os.path.dirname(
                              os.path.abspath(__file__))),
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)


# ---- 4. HBM model vs the live pool's gauge -------------------------------

@pytest.mark.parametrize("block_size,n_reqs,prompts", [
    (4, 2, (5, 9)),
    (8, 3, (4, 6, 9)),
    (4, 3, (3, 3, 11)),
])
def test_predicted_resident_bytes_match_gauge(stages, block_size, n_reqs,
                                              prompts):
    from simple_distributed_machine_learning_tpu.serve import (
        InferenceEngine,
    )
    rng = np.random.default_rng(3)
    ml = 20
    engine = InferenceEngine(stages, CFG, n_slots=n_reqs, max_len=ml,
                             block_size=block_size)
    handles = []
    for i, plen in enumerate(prompts):
        # distinct first tokens: no prefix sharing, so the no-sharing
        # model is exact
        prompt = rng.integers(0, CFG.vocab, plen).astype(np.int32)
        prompt[0] = i
        handles.append(engine.submit(prompt, max_new_tokens=6, seed=i))
    sspec = ServeSpec(CFG, n_slots=n_reqs, max_len=ml, kv_layout="paged",
                      block_size=block_size)
    for _ in range(n_reqs + 2):      # prefills (one per tick) + decodes
        engine.step()
        rows = []
        for h in handles:
            if h.state != "active":
                continue
            if h.prefill_pos is not None:        # mid-prefill
                rows.append(h.prefill_pos)
            else:
                rows.append(int(h.prompt.shape[0]) + len(h.tokens) - 1)
        predicted = predict_kv_bytes_resident(sspec,
                                              [r for r in rows if r > 0])
        assert predicted == engine.pool.stats()["kv_bytes_resident"], (
            block_size, rows, engine.pool.stats())
    assert engine.pool.stats()["kv_bytes_resident"] > 0
    # the static per-tick model agrees with the pool's block geometry
    gather = next(h for h in hbm_tick_costs(sspec)
                  if h.op == "decode.kv_gather")
    span = -(-ml // block_size) * block_size
    assert gather.bytes_per_tick == (
        n_reqs * engine.pool.bytes_per_block * span // block_size)


# ---- sharded + speculative registry (ISSUE 9) ----------------------------

def _draft():
    import dataclasses
    dcfg = dataclasses.replace(CFG, n_layers=1)
    return make_gpt_stages(jax.random.key(1), dcfg, 1)[0], dcfg


def test_registry_clean_speculative_both_layouts(stages):
    """The draft propose scan, the batched verify and the FUSED composite
    tick join the registry and lint clean — the proof, not silence, rule
    of contract 1 extends to every speculative program."""
    draft_stages, dcfg = _draft()
    for s in (ServeSpec(CFG, n_slots=3, max_len=16, kv_layout="paged",
                        block_size=4, prefill_chunk=3, prompt_lens=BUCKETS,
                        spec_k=4, draft_cfg=dcfg),
              ServeSpec(CFG, n_slots=3, max_len=16, kv_layout="dense",
                        prompt_lens=BUCKETS, spec_k=4, draft_cfg=dcfg)):
        report = lint_serve(stages, s, draft_stages=draft_stages)
        assert report.ok(fail_on="warning"), report.format()
        rules = {f.rule for f in report.findings}
        assert "trace.failed" not in rules
        assert "scatter-bounds.unproven-promise" not in rules
        programs, _ = build_registry(stages, s, draft_stages=draft_stages)
        names = {p.name for p in programs}
        want = ({"paged_propose", "paged_verify", "paged_spec_tick"}
                if s.kv_layout == "paged"
                else {"slot_propose", "slot_verify", "dense_spec_tick"})
        assert want <= names, names


def test_lint_serve_requires_the_draft_build():
    _, dcfg = _draft()
    s = ServeSpec(CFG, n_slots=2, max_len=16, kv_layout="dense",
                  prompt_lens=BUCKETS, spec_k=4, draft_cfg=dcfg)
    with pytest.raises(ValueError, match="draft_stages"):
        lint_serve(None, s)


def test_registry_clean_tp2(stages):
    """TP-sharded serving programs on a live 2-device model mesh: the
    mesh-axis and scatter-bounds rules walk the sharded block gathers of
    the exact shard_map twins the TP engine runs — clean on both layouts,
    and TP without the mesh is refused."""
    import dataclasses

    from simple_distributed_machine_learning_tpu.parallel.mesh import (
        make_mesh,
    )
    cfg2 = dataclasses.replace(CFG, n_tensor_parallel=2)
    mesh = make_mesh(n_stages=1, n_data=1, n_model=2)
    for s in (ServeSpec(cfg2, n_slots=3, max_len=16, kv_layout="paged",
                        block_size=4, prefill_chunk=3,
                        prompt_lens=BUCKETS),
              ServeSpec(cfg2, n_slots=3, max_len=16, kv_layout="dense",
                        prompt_lens=BUCKETS)):
        report = lint_serve(stages, s, mesh=mesh)
        assert report.ok(fail_on="warning"), report.format()
        assert "trace.failed" not in {f.rule for f in report.findings}
    with pytest.raises(ValueError, match="mesh"):
        lint_serve(stages, ServeSpec(cfg2, n_slots=3, max_len=16,
                                     kv_layout="dense",
                                     prompt_lens=BUCKETS))


def test_hbm_per_shard_bytes(stages):
    """Under TP the HBM model reports PER-SHARD bytes: every K/V stream
    row halves at tp=2, the resident-bytes prediction halves, and the
    prediction still equals a live tp-declared pool's gauge exactly."""
    import dataclasses

    from simple_distributed_machine_learning_tpu.serve.slots import (
        PagedKVPool,
    )
    cfg2 = dataclasses.replace(CFG, n_tensor_parallel=2)
    s1 = ServeSpec(CFG, n_slots=3, max_len=16, kv_layout="paged",
                   block_size=4, prefill_chunk=3)
    s2 = dataclasses.replace(s1, cfg=cfg2)
    c1 = {h.op: h.bytes_per_tick for h in hbm_tick_costs(s1)}
    c2 = {h.op: h.bytes_per_tick for h in hbm_tick_costs(s2)}
    assert set(c1) == set(c2)
    for op in c1:
        assert c2[op] * 2 == c1[op], op
    rows = [5, 9]
    assert (predict_kv_bytes_resident(s2, rows) * 2
            == predict_kv_bytes_resident(s1, rows))
    # the pool's own per-shard accounting is the same rule, so the gauge
    # parity of contract 4 carries over shard-for-shard (a LIVE tp=2
    # engine's gauge is cross-checked in tests/test_serve.py)
    kw = dict(n_layers=CFG.n_layers, n_slots=3, n_heads=CFG.n_heads,
              max_len=16, head_dim=CFG.d_model // CFG.n_heads,
              block_size=4)
    assert (PagedKVPool(**kw, tp=2).bytes_per_block * 2
            == PagedKVPool(**kw).bytes_per_block)
    with pytest.raises(ValueError, match="divide"):
        PagedKVPool(**kw, tp=3)


# ---- engine + CLI wiring -------------------------------------------------

def test_engine_lint_true_constructs_and_gates(stages, monkeypatch):
    from simple_distributed_machine_learning_tpu.serve import (
        InferenceEngine,
    )
    eng = InferenceEngine(stages, CFG, n_slots=2, max_len=16, block_size=4,
                          prefill_chunk=3, lint=True)
    assert lint_engine(eng, prompt_lens=BUCKETS).ok()
    monkeypatch.setenv("SDML_LINT_INJECT", "unit")
    with pytest.raises(RuntimeError, match="preflight found ERROR"):
        InferenceEngine(stages, CFG, n_slots=2, max_len=16, block_size=4,
                        prefill_chunk=3, lint=True)


def test_serve_cli_gate_exit_codes(monkeypatch):
    from simple_distributed_machine_learning_tpu.analysis.__main__ import (
        main,
    )
    assert main(["--serve"]) == 0
    monkeypatch.setenv("SDML_LINT_INJECT", "unit")
    assert main(["--serve"]) == 1


# ---- bounds arithmetic unit checks ---------------------------------------

def test_interval_arithmetic_corners():
    assert _floordiv_iv(Interval(-5, 11), Interval(4, 4)) == Interval(-2, 2)
    assert _mod_iv(Interval(-5, 11), Interval(4, 4)) == Interval(0, 3)
    assert _cmp_iv("lt", Interval(0, 3), Interval(4, 9)) == Interval(1, 1)
    assert _cmp_iv("lt", Interval(4, 9), Interval(0, 4)) == Interval(0, 0)
    assert _cmp_iv("lt", Interval(0, 5), Interval(3, 4)) == Interval(0, 1)
    assert _cmp_iv("ge", Interval(0, 5), Interval(0, 0)) == Interval(1, 1)


def test_narrowing_cast_drops_the_proof():
    # int32 -> int8 WRAPS at runtime for values past 127: the declared
    # interval must not survive the cast and falsely certify a PROMISE
    # gather — a fitting cast keeps the proof
    def f(x, i):
        j = jax.lax.convert_element_type(i, np.int8)
        return x.at[j].get(mode="promise_in_bounds")

    x = jax.ShapeDtypeStruct((100,), np.float32)
    wrapping = analyze(f, x, spec((), np.int32, 0, 200))
    assert any(f_.rule == "scatter-bounds.unproven-promise"
               for f_ in wrapping.findings), wrapping.format()
    fitting = analyze(f, x, spec((), np.int32, 0, 90))
    assert fitting.ok(fail_on="warning"), fitting.format()


def test_bounds_prove_simple_program():
    def f(table, idx):
        return table[idx // 4]

    t = spec((3,), np.int32, 0, 2)
    good = analyze(f, t, spec((3,), np.int32, 0, 11))
    assert good.ok(fail_on="warning"), good.format()
    bad = analyze(f, t, spec((3,), np.int32, 0, 12))
    assert any(f_.rule == "scatter-bounds.out-of-range"
               for f_ in bad.findings), bad.format()


def test_half_declared_contract_degrades_to_unproven():
    """A one-sided spec (only ``lo`` or only ``hi``) proves nothing about
    the unbounded side, so it must get the same not-proven treatment as no
    contract at all — a WARNING at worst, never a gating ERROR. A finite
    bound that puts the WHOLE interval outside the operand is still a
    provable violation."""
    def f(x, i):
        return x[i]

    x = jax.ShapeDtypeStruct((4, 8), np.float32)
    half = analyze(f, x, spec((3,), np.int32, lo=0))
    assert half.ok(), half.format()
    assert any(f_.rule == "scatter-bounds.unproven-promise"
               for f_ in half.findings), half.format()
    # lo=10 into a 4-row operand: every possible value is out of range,
    # provable even though hi is unbounded
    beyond = analyze(f, x, spec((3,), np.int32, lo=10))
    assert any(f_.rule == "scatter-bounds.out-of-range"
               for f_ in beyond.findings), beyond.format()


def test_scatter_variant_primitives_checked():
    """``.at[].min()``/``.at[].max()`` lower to scatter-min/scatter-max
    (hyphenated primitive names) — they must hit the same bounds check as
    plain scatter, not fall through to the generic unknown handler."""
    x = jax.ShapeDtypeStruct((4,), np.float32)
    for op in ("min", "max"):
        def f(x, i, _op=op):
            return getattr(x.at[i], _op)(3.0)

        bad = analyze(f, x, spec((), np.int32, 0, 9))
        assert any(f_.rule == "scatter-bounds.out-of-range"
                   for f_ in bad.findings), (op, bad.format())
        good = analyze(f, x, spec((), np.int32, 0, 3))
        assert good.ok(fail_on="warning"), (op, good.format())


# ---- the serve supervisor's degraded-fallback layout ----------------------

def test_degraded_spec_matches_engine_factory_rule(stages):
    """``degraded_spec`` and ``serve/supervisor.py::engine_factory`` must
    apply the SAME fallback transform (spec off, tp 1, dense rows) — the
    registry's degraded entry is only a proof if it describes the engine a
    chaos-stressed supervisor actually rebuilds."""
    import dataclasses as _dc

    from simple_distributed_machine_learning_tpu.analysis.programs import (
        degraded_spec,
    )
    from simple_distributed_machine_learning_tpu.serve.supervisor import (
        engine_factory,
    )

    full = ServeSpec(CFG, n_slots=3, max_len=16, kv_layout="paged",
                     block_size=4, prefill_chunk=3, prompt_lens=BUCKETS,
                     spec_k=4, draft_cfg=_dc.replace(CFG, n_layers=1))
    d = degraded_spec(full)
    assert d.kv_layout == "dense" and d.spec_k == 0 and d.tp == 1
    assert d.n_slots == full.n_slots and d.ml == full.ml
    draft_cfg = _dc.replace(CFG, n_layers=1)
    draft_stages = make_gpt_stages(jax.random.key(1), draft_cfg, 1)[0]
    eng = engine_factory(stages, CFG, n_slots=3, max_len=16, block_size=4,
                         prefill_chunk=3, draft_stages=draft_stages,
                         draft_cfg=draft_cfg, spec_k=4)(True)
    assert eng.kv_layout == "dense" and not eng.speculative
    assert eng.tp == 1 and eng.pool.n_slots == 3
    # and the degraded ENGINE's own lint (the exact programs it built)
    # is clean: zero trace.failed, zero unproven-promise
    report = lint_engine(eng, prompt_lens=BUCKETS)
    assert report.ok(fail_on="warning"), report.format()
    rules = {f.rule for f in report.findings}
    assert "trace.failed" not in rules
    assert "scatter-bounds.unproven-promise" not in rules


def test_default_registry_includes_clean_degraded_entry():
    """The CI ``--serve`` sweep carries an explicitly named degraded-
    fallback report, and it is clean — the fallback that only exists on
    the worst day is proven on every PR."""
    from simple_distributed_machine_learning_tpu.analysis.programs import (
        default_registry_reports,
    )

    reports = default_registry_reports()
    degraded = [r for r in reports if "degraded" in r.name]
    assert len(degraded) == 1
    r = degraded[0]
    assert r.ok(fail_on="warning"), r.format()
    rules = {f.rule for f in r.findings}
    assert "trace.failed" not in rules
    assert "scatter-bounds.unproven-promise" not in rules


# ---- ISSUE 16: inside the kernel box ------------------------------------
#
# The pallas_call rule family (analysis/kernels.py). The serve registry
# already proves the REAL kernels clean above; here the corners of the
# index-map interval arithmetic, the scalar-prefetch contract seeding and
# the hostlint/fault-drill satellites get their own pins.


def _lint_pallas(kernel, grid, in_specs, out_specs, out_shape, *args,
                 **contracts):
    from jax.experimental import pallas as pl

    def prog(*a):
        return pl.pallas_call(kernel, grid=grid, in_specs=in_specs,
                              out_specs=out_specs, out_shape=out_shape,
                              interpret=True, **contracts.pop("pl_kw", {}))(*a)

    return analyze(prog, *args, name="kernel_corner")


def test_kernel_floordiv_and_rem_index_maps_prove_clean():
    """i//2 and i%3 over grid axes: the interval corners PR 8 pinned on
    gather indices must also carry proofs THROUGH BlockSpec index maps —
    both derived maps stay inside a (3, 6) block grid for grid=(6,)."""
    from jax.experimental import pallas as pl

    def kern(x_ref, y_ref, o_ref):
        o_ref[...] = x_ref[...] + y_ref[...]

    x = jax.ShapeDtypeStruct((3, 8), np.float32)   # blocks (1,8), rows i//2
    y = jax.ShapeDtypeStruct((3, 8), np.float32)   # rows i%3
    report = _lint_pallas(
        kern, (6,),
        [pl.BlockSpec((1, 8), lambda i: (i // 2, 0)),
         pl.BlockSpec((1, 8), lambda i: (i % 3, 0))],
        pl.BlockSpec((1, 8), lambda i: (i % 3, 0)),
        jax.ShapeDtypeStruct((3, 8), np.float32),
        x, y)
    bad = [f for f in report.findings if f.family.startswith("kernel-")]
    assert not bad, report.format()


def test_kernel_oob_floordiv_index_map_is_proved_escaping():
    """grid=(8,) with rows i//2 over a 3-row operand REACHES row 3: a
    finite counterexample, so kernel-oob (ERROR), not merely unproven."""
    from jax.experimental import pallas as pl

    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    report = _lint_pallas(
        kern, (8,),
        [pl.BlockSpec((1, 8), lambda i: (i // 2, 0))],
        pl.BlockSpec((1, 8), lambda i: (i % 8, 0)),
        jax.ShapeDtypeStruct((8, 8), np.float32),
        jax.ShapeDtypeStruct((3, 8), np.float32))
    assert any(f.rule == "kernel-oob.index-map" for f in report.findings), (
        report.format())
    assert not report.ok()


def test_kernel_scalar_prefetch_contract_seeds_the_proof():
    """A PrefetchScalarGridSpec block-table deref is only provable when the
    caller DECLARES the table's range (analysis.spec): with the contract
    the map proves clean, without it the same kernel is kernel-unproven —
    never silently ok."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kern(tbl_ref, x_ref, o_ref):
        o_ref[...] = x_ref[...]

    def prog(tbl, x):
        gspec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=(4,),
            in_specs=[pl.BlockSpec((1, 8), lambda i, tbl: (tbl[i], 0))],
            out_specs=pl.BlockSpec((1, 8), lambda i, tbl: (i, 0)))
        return pl.pallas_call(
            kern, grid_spec=gspec, interpret=True,
            out_shape=jax.ShapeDtypeStruct((4, 8), np.float32))(tbl, x)

    x = jax.ShapeDtypeStruct((5, 8), np.float32)
    proven = analyze(prog, spec((4,), np.int32, 0, 4), x)
    assert not [f for f in proven.findings
                if f.family.startswith("kernel-")], proven.format()
    unproven = analyze(prog, jax.ShapeDtypeStruct((4,), np.int32), x)
    assert any(f.rule == "kernel-unproven.index-map"
               for f in unproven.findings), unproven.format()
    # a contract that ADMITS escape is an ERROR, not just unproven
    escaping = analyze(prog, spec((4,), np.int32, 0, 9), x)
    assert any(f.rule == "kernel-oob.index-map"
               for f in escaping.findings), escaping.format()


def test_kernel_narrowing_cast_drops_the_proof():
    """An i32->i8 cast inside the index map forgets the interval when the
    grid axis provably overflows int8 (wrap semantics): the proof must
    degrade to kernel-unproven, never claim clean. A grid that FITS the
    narrow dtype keeps its proof — same contract PR 8 pinned on gather
    indices, now through BlockSpec index maps."""
    from jax.experimental import pallas as pl

    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...]

    def build(grid_n):
        return _lint_pallas(
            kern, (grid_n,),
            [pl.BlockSpec((1, 8),
                          lambda i: (i.astype(np.int8).astype(np.int32),
                                     0))],
            pl.BlockSpec((1, 8), lambda i: (i, 0)),
            jax.ShapeDtypeStruct((grid_n, 8), np.float32),
            jax.ShapeDtypeStruct((grid_n, 8), np.float32))

    # [0, 299] wraps in int8 -> interval lost -> unproven
    report = build(300)
    assert any(f.rule == "kernel-unproven.index-map"
               for f in report.findings), report.format()
    # [0, 3] is representable -> interval survives -> proof holds
    assert not [f for f in build(4).findings
                if f.family.startswith("kernel-")]


def test_serve_kernel_cli_gate():
    from simple_distributed_machine_learning_tpu.analysis.__main__ import (
        main,
    )
    assert main(["--serve-kernel"]) == 0
    os.environ["SDML_LINT_INJECT"] = "drill"
    try:
        assert main(["--serve-kernel"]) == 1
    finally:
        del os.environ["SDML_LINT_INJECT"]


# ---- satellite: wall-clock/random hostlint rule -------------------------


def test_hostlint_flags_wallclock_and_random_in_serve(tmp_path):
    from simple_distributed_machine_learning_tpu.analysis.hostlint import (
        _lint_call_sites,
    )
    bad = tmp_path / "clocky.py"
    bad.write_text(
        "import time\n"
        "import random\n"
        "from datetime import datetime\n"
        "t0 = time.monotonic()\n"
        "jitter = random.random()\n"
        "stamp = datetime.now()\n")
    rules = [f for f in _lint_call_sites(str(bad), allow_jit=False)
             if f.rule == "hostlint.wall-clock-in-serve"]
    assert len(rules) == 3, [f.message for f in rules]
    # the sanctioned idiom — injectable default args REFERENCING the clock
    # (no call) plus calls through the injected parameter — stays clean
    good = tmp_path / "injected.py"
    good.write_text(
        "import time\n"
        "def tick(clock=time.monotonic):\n"
        "    return clock()\n")
    assert not [f for f in _lint_call_sites(str(good), allow_jit=False)
                if f.rule == "hostlint.wall-clock-in-serve"]


# ---- satellite: fault-drill coverage lint -------------------------------


def test_fault_drill_coverage_clean_and_detects_gaps(tmp_path):
    from simple_distributed_machine_learning_tpu.resilience.faults import (
        KINDS,
        SITES,
        drill_coverage,
    )
    # the repo itself: every kind and site fired somewhere in tests/ or CI
    assert drill_coverage() == []
    # a synthetic tree that only ever drills one pair
    tree = tmp_path / "repo"
    (tree / "tests").mkdir(parents=True)
    (tree / "tests" / "test_x.py").write_text(
        'SCENARIO = "slow-tick@serve.tick"\n')
    gaps = drill_coverage(root=str(tree))
    assert any("kind" in g and "host-kill" in g for g in gaps)
    assert any("site" in g and "train.step" in g for g in gaps)
    assert any("nan-grad@train.grad" in g for g in gaps)  # pinned pair
    # injected kinds/sites localize the check (pure-unit path)
    gaps = drill_coverage(root=str(tree), kinds=("slow-tick",),
                          sites=("serve.tick",), pairs=())
    assert gaps == []
    assert "slow-tick" in KINDS and "serve.tick" in SITES


# ---- satellite: metric-catalog coverage lint (ISSUE 19) -----------------


def test_metric_catalog_rule_repo_clean():
    """Every metric constant registered in serve/metrics.py,
    telemetry/slo.py and telemetry/attribution.py resolves to HELP text —
    the repo's own catalog has no undocumented instrument."""
    from simple_distributed_machine_learning_tpu.analysis.hostlint import (
        lint_metric_catalog,
    )
    assert lint_metric_catalog() == []


def test_metric_catalog_rule_flags_undocumented(tmp_path):
    """The seeded defect: a registering module with a metric name the
    catalog has never heard of must ERROR (path injection mirrors the
    journal-grammar lint's writer/reader seeding)."""
    from simple_distributed_machine_learning_tpu.analysis.hostlint import (
        Severity,
        lint_metric_catalog,
    )
    bad = tmp_path / "metrics_like.py"
    bad.write_text(
        'DOCUMENTED = "serve_blocks_in_use"\n'
        'UNDOCUMENTED = "serve_bogus_flux_capacitor_total"\n'
        'NOT_A_METRIC = "some random string"\n')
    findings = lint_metric_catalog(metric_files=[str(bad)])
    assert [f.rule for f in findings] == ["metric-catalog.undocumented"]
    assert findings[0].severity is Severity.ERROR
    assert "serve_bogus_flux_capacitor_total" in findings[0].message


def test_metric_catalog_covers_slo_and_attribution_instruments():
    """The new ISSUE-19 instruments resolve through the catalog (their
    HELP bullets live in their own modules' docstrings)."""
    from simple_distributed_machine_learning_tpu.telemetry.catalog import (
        metric_help,
    )
    helps = metric_help()
    for name in ("serve_slo_burn_rate", "serve_alerts_firing",
                 "serve_ttft_component_ms",
                 "serve_route_alert_demotions_total"):
        assert name in helps, name


def test_hostlint_wall_clock_rule_covers_slo_pipeline(tmp_path):
    """The zero-wall-clock-reads pin, hostlint-enforced: the clock rule
    now runs over telemetry/{slo,alerts,attribution}.py exactly as over
    serve/ (check_clock decouples it from the jit gate), and a seeded
    clock read in an SLO-pipeline-like module is flagged."""
    from simple_distributed_machine_learning_tpu.analysis.hostlint import (
        _lint_call_sites,
    )
    bad = tmp_path / "slo_like.py"
    bad.write_text(
        "import time\n"
        "def evaluate(tick):\n"
        "    return time.monotonic()\n")
    # telemetry modules lint with the clock rule ON but raw-jit OFF
    flagged = [f.rule for f in _lint_call_sites(str(bad), allow_jit=True,
                                                check_clock=True)]
    assert flagged == ["hostlint.wall-clock-in-serve"]
    assert not _lint_call_sites(str(bad), allow_jit=True)


def test_hostlint_cli_inject_drill(monkeypatch, capsys):
    """SDML_LINT_INJECT trips the --hostlint gate: the negative test
    proving the CI lint job's preflight actually fails on an ERROR."""
    from simple_distributed_machine_learning_tpu.analysis.__main__ import (
        main,
    )
    monkeypatch.setenv("SDML_LINT_INJECT", "drill")
    assert main(["--hostlint"]) == 1
    out = capsys.readouterr().out
    assert "injected.drill" in out and "FLAGGED" in out
    monkeypatch.delenv("SDML_LINT_INJECT")
    assert main(["--hostlint"]) == 0
    capsys.readouterr()
