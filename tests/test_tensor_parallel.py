"""Tensor parallelism: TP linear pair == dense computation, values and grads."""

import jax
import jax.numpy as jnp
import numpy as np

from simple_distributed_machine_learning_tpu.parallel.compat import (
    shard_map,
)
from jax.sharding import Mesh, PartitionSpec as P

from simple_distributed_machine_learning_tpu.ops.layers import linear, linear_init
from simple_distributed_machine_learning_tpu.parallel.tensor import (
    stack_tp_shards,
    tp_pair_apply,
    tp_pair_init,
)


def _dense_pair(key, d_in, d_h, d_out, x):
    k1, k2 = jax.random.split(key)
    w1 = linear_init(k1, d_in, d_h)
    w2 = linear_init(k2, d_h, d_out)
    return linear(w2, jax.nn.relu(linear(w1, x)))


def test_tp_pair_matches_dense():
    key = jax.random.key(0)
    d_in, d_h, d_out, mp = 8, 32, 6, 4
    x = jax.random.normal(jax.random.key(1), (5, d_in))

    shards = tp_pair_init(key, d_in, d_h, d_out, mp)
    stacked = stack_tp_shards(shards)
    mesh = Mesh(np.array(jax.devices()[:mp]), ("model",))

    def per_device(p, xx):
        local = jax.tree.map(lambda l: l[0], p)  # strip sharded leading axis
        return tp_pair_apply(local, xx, axis="model")

    f = jax.jit(shard_map(
        per_device, mesh=mesh,
        in_specs=(P("model"), P()), out_specs=P()))
    got = f(stacked, x)
    want = _dense_pair(key, d_in, d_h, d_out, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_tp_pair_grads_match_dense():
    key = jax.random.key(2)
    d_in, d_h, d_out, mp = 8, 16, 4, 2
    x = jax.random.normal(jax.random.key(3), (3, d_in))
    mesh = Mesh(np.array(jax.devices()[:mp]), ("model",))
    shards = tp_pair_init(key, d_in, d_h, d_out, mp)
    stacked = stack_tp_shards(shards)

    def tp_loss(p, xx):
        f = shard_map(
            lambda pp, v: tp_pair_apply(jax.tree.map(lambda l: l[0], pp), v,
                                        axis="model"),
            mesh=mesh, in_specs=(P("model"), P()), out_specs=P(),
            )
        return jnp.sum(f(p, xx) ** 2)

    g_tp = jax.grad(tp_loss)(stacked, x)

    # dense ground truth, gradients re-sharded for comparison
    k1, k2 = jax.random.split(key)
    w1 = linear_init(k1, d_in, d_h)
    w2 = linear_init(k2, d_h, d_out)

    def dense_loss(ws, xx):
        return jnp.sum(linear(ws[1], jax.nn.relu(linear(ws[0], xx))) ** 2)

    g_d = jax.grad(dense_loss)([w1, w2], x)
    h = d_h // mp
    for i in range(mp):
        np.testing.assert_allclose(
            np.asarray(g_tp["w1"]["w"][i]), np.asarray(g_d[0]["w"][:, i*h:(i+1)*h]),
            rtol=5e-5, atol=5e-5)
        np.testing.assert_allclose(
            np.asarray(g_tp["w2"]["w"][i]), np.asarray(g_d[1]["w"][i*h:(i+1)*h]),
            rtol=5e-5, atol=5e-5)
