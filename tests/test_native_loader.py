"""Native C++ loader: IDX codec parity with the NumPy path + prefetcher."""

import gzip
import struct

import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.data import native_loader
from simple_distributed_machine_learning_tpu.data.mnist import _read_idx

pytestmark = pytest.mark.skipif(not native_loader.available(),
                                reason="native toolchain unavailable")


def _write_idx_images(path, arr_u8):
    with open(path, "wb") as f:
        f.write(struct.pack(">I", 0x00000800 | arr_u8.ndim))
        for d in arr_u8.shape:
            f.write(struct.pack(">I", d))
        f.write(arr_u8.tobytes())


def test_idx_codec_matches_numpy(tmp_path):
    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, size=(5, 28, 28), dtype=np.uint8)
    p = str(tmp_path / "imgs-idx3-ubyte")
    _write_idx_images(p, imgs)
    native = native_loader.idx_read_native(p)
    want = _read_idx(p).astype(np.float32) / 255.0
    np.testing.assert_allclose(native, want, rtol=1e-6)

    labels = rng.integers(0, 10, size=(5,), dtype=np.uint8)
    p2 = str(tmp_path / "labels-idx1-ubyte")
    _write_idx_images(p2, labels)
    native_l = native_loader.idx_read_native(p2)
    np.testing.assert_array_equal(native_l, labels.astype(np.float32))


def test_prefetcher_yields_same_batches_as_numpy_path():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(25, 4, 3)).astype(np.float32)
    y = rng.integers(0, 10, size=(25,)).astype(np.int32)
    pf = native_loader.NativePrefetcher(x, y, batch=10)
    got = list(pf)
    pf.close()
    assert len(got) == 3
    np.testing.assert_allclose(got[0][0], x[:10])
    np.testing.assert_array_equal(got[0][1], y[:10])
    assert got[0][2] == 10
    # ragged tail: 5 valid rows, zero-padded to 10
    np.testing.assert_allclose(got[2][0][:5], x[20:])
    assert got[2][2] == 5
    np.testing.assert_allclose(got[2][0][5:], 0.0)


def test_prefetcher_custom_order():
    x = np.arange(12, dtype=np.float32).reshape(6, 2)
    y = np.arange(6, dtype=np.int32)
    order = np.array([5, 4, 3, 2, 1, 0])
    pf = native_loader.NativePrefetcher(x, y, batch=3, order=order)
    got = list(pf)
    pf.close()
    np.testing.assert_array_equal(got[0][1], [5, 4, 3])
    np.testing.assert_allclose(got[0][0], x[[5, 4, 3]])


def test_prefetch_batches_matches_python_iterator():
    """The Trainer's prefetch path yields exactly what batches() yields."""
    import numpy as np

    from simple_distributed_machine_learning_tpu.data.mnist import (
        Dataset,
        batches,
        prefetch_batches,
    )

    rng = np.random.default_rng(0)
    ds = Dataset(rng.normal(size=(25, 4, 4, 1)).astype(np.float32),
                 rng.integers(0, 10, size=25).astype(np.int32))
    got = list(prefetch_batches(ds, 10))
    want = list(batches(ds, 10, pad_last=True))
    assert len(got) == len(want)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g.x, w.x)
        np.testing.assert_array_equal(g.y, w.y)
        assert g.n_valid == w.n_valid
